"""The content-addressed object store + run manifests + `sofa archive`.

Ingest walks the logdir's sha256 digest ledger (durability.py — computed
on the spot when the logdir predates it), streams each artifact into
``objects/<aa>/<sha256>`` exactly once, and lands a per-run manifest in
``runs/<run_id>.json`` plus one fsync'd catalog line.  Every byte-level
dedup falls out of the pipeline's existing determinism: tiles are
gzip'd with ``mtime=0``, frames are written by a deterministic columnar
writer, so two runs over unchanged inputs share every object and the
second ingest costs one catalog entry.

Crash safety mirrors the logdir pipeline: objects and run docs land via
``durability.atomic_write`` (deterministic ``.tmp`` names, so a replay
overwrites a crash's leftovers), the catalog line is the commit point,
and the ingest is journaled in the LOGDIR's run journal (`sofa resume`
replays an uncommitted ``archive`` stage).  ``archive_fsck`` verifies
the store: every object re-hashes to its name, every run doc's
references exist, uncataloged run docs (crash between run-doc write and
catalog append) are re-adopted by ``--repair``.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from sofa_tpu.archive import (
    ARCHIVE_MARKER_NAME,
    ARCHIVE_SCHEMA,
    ARCHIVE_VERSION,
    OBJECTS_DIR_NAME,
    QUARANTINE_DIR_NAME,
    RUNS_DIR_NAME,
    catalog,
)
from sofa_tpu.printing import (
    print_error,
    print_progress,
    print_title,
    print_warning,
)

RUN_SCHEMA = "sofa_tpu/archive_run"
RUN_VERSION = 1

_HASH_CHUNK = 1 << 20

# fsck verdict vocabulary for the store, in rendering order.  ``corrupt``
# (object bytes no longer hash to its name), ``missing`` (a run doc
# references an absent object), ``orphaned`` (``*.tmp`` leftovers of an
# interrupted write), ``uncataloged`` (a run doc the catalog never
# committed — recoverable: --repair re-appends its ingest line),
# ``index`` (a columnar-index chunk whose bytes stopped matching its
# index-signed sha — pure derived state: --repair drops + rebuilds it).
# ``unreferenced`` objects (no surviving run points at them) are reported
# but are NOT damage: they are what `sofa archive gc` exists to sweep.
ARCHIVE_FSCK_VERDICTS = ("corrupt", "missing", "orphaned", "uncataloged",
                         "index")


class ArchiveStore:
    """One archive root.  ``create=True`` initializes the marker/dirs."""

    def __init__(self, root: str, create: bool = False):
        self.root = root
        self.marker_path = os.path.join(root, ARCHIVE_MARKER_NAME)
        if create and not os.path.isfile(self.marker_path):
            self._init_root()

    def _init_root(self) -> None:
        os.makedirs(os.path.join(self.root, OBJECTS_DIR_NAME), exist_ok=True)
        os.makedirs(os.path.join(self.root, RUNS_DIR_NAME), exist_ok=True)
        import threading

        # writer-unique stage + first-writer-wins rename: pool workers
        # (and their handler threads) creating the same tenant root
        # concurrently must not tear each other's marker — every loser's
        # marker said the same thing anyway
        stage = (f"{self.marker_path}.{os.getpid()}"
                 f".{threading.get_ident()}.tmp")
        with open(stage, "w") as f:  # sofa-lint: disable=SL009 — writer-unique stage renamed below; atomic_write's fixed .tmp name is exactly the cross-process race being avoided
            json.dump({"schema": ARCHIVE_SCHEMA, "version": ARCHIVE_VERSION,
                       "created_unix": round(time.time(), 3)}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            if os.path.isfile(self.marker_path):
                os.unlink(stage)
            else:
                os.replace(stage, self.marker_path)
        except OSError:
            pass

    @property
    def exists(self) -> bool:
        return os.path.isfile(self.marker_path)

    # -- objects -----------------------------------------------------------
    def object_path(self, sha: str) -> str:
        return os.path.join(self.root, OBJECTS_DIR_NAME, sha[:2], sha)

    def has_object(self, sha: str) -> bool:
        return os.path.isfile(self.object_path(sha))

    def put_file(self, src: str,
                 expected_sha: Optional[str] = None) -> Tuple[str, int]:
        """Store ``src``'s bytes; returns (sha256, bytes_added).

        Dedup fast path: when the caller's digest-ledger sha is trusted
        and the object already exists, nothing is read at all.  Otherwise
        the bytes are hashed while staging into a deterministic ``.tmp``
        beside the object (a crashed ingest's leftover is simply
        overwritten by the replay), then renamed in."""
        if expected_sha and self.has_object(expected_sha):
            return expected_sha, 0
        h = hashlib.sha256()
        stage = self.object_path(expected_sha or "xx/staging") + ".tmp"
        os.makedirs(os.path.dirname(stage), exist_ok=True)
        size = 0
        with open(src, "rb") as fin, open(stage, "wb") as fout:  # sofa-lint: disable=SL009 — staged under a deterministic .tmp name and renamed below; atomic_write cannot target a path unknown until the stream is hashed
            while True:
                chunk = fin.read(_HASH_CHUNK)
                if not chunk:
                    break
                h.update(chunk)
                fout.write(chunk)
                size += len(chunk)
            fout.flush()
            os.fsync(fout.fileno())
        sha = h.hexdigest()
        dest = self.object_path(sha)
        if os.path.isfile(dest):
            os.unlink(stage)
            return sha, 0
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        os.replace(stage, dest)
        return sha, size

    def put_bytes(self, blob: bytes) -> Tuple[str, int]:
        """Store an in-memory blob; returns (sha256, bytes_added).

        Staged under a pid-unique ``.tmp`` (fsck still classifies it as
        an orphan, never damage): two pool workers receiving the SAME
        object concurrently (tier mode) each stage privately and the
        renames converge on identical bytes — no fixed-name collision."""
        sha = hashlib.sha256(blob).hexdigest()
        dest = self.object_path(sha)
        if os.path.isfile(dest):
            return sha, 0
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        stage = f"{dest}.{os.getpid()}.tmp"
        with open(stage, "wb") as f:  # sofa-lint: disable=SL009 — pid-unique stage renamed below; atomic_write's fixed .tmp name would collide across pool workers storing the same object
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(stage, dest)
        return sha, len(blob)

    def read_object(self, sha: str) -> Optional[bytes]:
        try:
            with open(self.object_path(sha), "rb") as f:
                return f.read()
        except OSError:
            return None

    # -- run docs ----------------------------------------------------------
    def run_doc_path(self, run_id: str) -> str:
        return os.path.join(self.root, RUNS_DIR_NAME, f"{run_id}.json")

    def load_run(self, run_id: str) -> Optional[dict]:
        try:
            with open(self.run_doc_path(run_id)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    def run_ids(self) -> List[str]:
        try:
            names = os.listdir(os.path.join(self.root, RUNS_DIR_NAME))
        except OSError:
            return []
        return sorted(n[:-5] for n in names
                      if n.endswith(".json") and len(n) == 69)

    def resolve_run_id(self, prefix: str) -> Optional[str]:
        """Full run id from a unique prefix (>= 6 chars), else None."""
        if len(prefix) < 6:
            return None
        hits = [r for r in self.run_ids() if r.startswith(prefix)]
        return hits[0] if len(hits) == 1 else None

    def extract(self, run_id: str, dest: str) -> int:
        """Materialize an archived run's files under ``dest`` (tooling /
        tests); returns the file count."""
        doc = self.load_run(run_id)
        if doc is None:
            raise FileNotFoundError(f"no archived run {run_id}")
        n = 0
        for rel, ent in sorted((doc.get("files") or {}).items()):
            blob = self.read_object(ent.get("sha256", ""))
            if blob is None:
                print_warning(f"archive: object for {rel} is missing — "
                              "skipped in extract (run `sofa fsck` on the "
                              "archive root)")
                continue
            path = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            from sofa_tpu.durability import atomic_write

            with atomic_write(path, "wb") as f:
                f.write(blob)
            n += 1
        return n


def run_content_id(files: Dict[str, dict]) -> str:
    """The run id: sha256 over the sorted (rel, sha256) content map — a
    content address, so an unchanged logdir re-ingests to the same id."""
    h = hashlib.sha256()
    for rel in sorted(files):
        h.update(f"{rel}\0{files[rel]['sha256']}\n".encode())
    return h.hexdigest()


def _read_features_csv(path: str) -> Dict[str, float]:
    """features.csv (name,value) -> dict; latest value wins, like
    Features.get.  Missing/unparsable file -> {}."""
    import csv

    out: Dict[str, float] = {}
    try:
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                try:
                    out[str(row["name"])] = float(row["value"])
                except (KeyError, ValueError, TypeError):
                    continue
    except OSError:
        return {}
    return out


def ingest_run(cfg, root: str, label: str = "",
               tel=None) -> dict:
    """Ingest ``cfg.logdir`` into the archive at ``root``.

    Returns the catalog summary ``{"run", "files", "new_objects",
    "bytes_added", "wall_s"}``.  Journaled in the logdir's run journal
    (stage ``archive``) so `sofa resume` replays a killed ingest."""
    from sofa_tpu import durability

    logdir = cfg.logdir
    t0 = time.perf_counter()
    store = ArchiveStore(root, create=True)
    journal = durability.Journal(logdir)
    journal.begin("archive", key=durability.logdir_raw_key(logdir),
                  archive_root=os.path.abspath(root))

    from sofa_tpu.telemetry import maybe_span

    with maybe_span("archive_scan", cat="stage"):
        ledger = durability.load_digests(logdir)
        if ledger is None:
            ledger = durability.compute_digests(logdir)
        targets: Dict[str, dict] = dict(ledger.get("files") or {})

    files: Dict[str, dict] = {}
    new_objects = 0
    bytes_added = 0
    with maybe_span("archive_objects", cat="stage"):
        for rel, ent in sorted(targets.items()):
            path = os.path.join(logdir, rel)
            expected = None
            try:
                st = os.stat(path)
            except OSError:
                continue  # vanished since the ledger: nothing to archive
            if st.st_size == ent.get("bytes") \
                    and st.st_mtime_ns == ent.get("mtime_ns"):
                expected = ent.get("sha256")
            try:
                sha, added = store.put_file(path, expected)
            except OSError as e:
                print_warning(f"archive: cannot store {rel}: {e} — "
                              "skipped (the run doc will not reference it)")
                continue
            files[rel] = {"sha256": sha, "bytes": int(st.st_size),
                          "kind": ent.get("kind")
                          or durability._file_kind(rel)}
            if added:
                new_objects += 1
                bytes_added += added
        # The run manifest is the health record of the run — archive it
        # too (the digest ledger skips it by design), but NORMALIZED: the
        # archive/regress verbs' own sections and the per-write timestamp
        # are stripped, so the act of archiving can never change the next
        # ingest's content (re-ingest must stay a pure catalog append).
        blob = _normalized_manifest(logdir)
        if blob is not None:
            from sofa_tpu.telemetry import MANIFEST_NAME

            sha, added = store.put_bytes(blob)
            files[MANIFEST_NAME] = {"sha256": sha, "bytes": len(blob),
                                    "kind": "derived"}
            if added:
                new_objects += 1
                bytes_added += added

    run_id = run_content_id(files)
    features = _read_features_csv(os.path.join(logdir, "features.csv"))
    doc = {
        "schema": RUN_SCHEMA, "version": RUN_VERSION,
        "run": run_id, "t": round(time.time(), 3),
        "logdir": os.path.abspath(logdir),
        "hostname": _hostname(),
        "label": label or "",
        "files": files,
        "features": features,
    }
    with maybe_span("archive_commit", cat="stage"):
        prev = store.load_run(run_id)
        if prev is None or prev.get("files") != files:
            with durability.atomic_write(store.run_doc_path(run_id),
                                         fsync=True) as f:
                json.dump(doc, f, indent=1, sort_keys=True)
        # The catalog line is the ingest's commit point: fsck adopts a
        # run doc whose append never landed.
        catalog.append_event(root, "ingest", run=run_id,
                             logdir=os.path.abspath(logdir),
                             files=len(files), new_objects=new_objects,
                             bytes_added=bytes_added,
                             **({"label": label} if label else {}))
    # Ingest commit point = index refresh point (archive/index.py): the
    # suffix-only parse folds exactly this ingest's catalog line in.  It
    # runs INSIDE the journaled archive stage, so a kill mid-refresh
    # leaves the stage uncommitted and `sofa resume` replays ingest +
    # refresh to the identical bytes (the commit doc carries no clock).
    from sofa_tpu import pool
    from sofa_tpu.archive import index as aindex

    with maybe_span("archive_index", cat="stage"):
        idx = aindex.refresh_after_ingest(root, jobs=pool.cfg_jobs(cfg))
    journal.commit("archive", key=durability.logdir_raw_key(logdir),
                   run=run_id)
    summary = {"run": run_id, "files": len(files),
               "new_objects": new_objects, "bytes_added": bytes_added,
               "wall_s": round(time.perf_counter() - t0, 3)}
    if idx is not None:
        summary["index"] = {"runs": idx.get("runs"),
                            "events": idx.get("events"),
                            **(idx.get("_stats") or {})}
    if tel is not None:
        tel.set_meta(archive={**summary, "root": os.path.abspath(root)})
    print_progress(
        f"archive: run {run_id[:12]} — {len(files)} file(s), "
        f"{new_objects} new object(s), {bytes_added / 2**20:.2f} MiB added "
        f"-> {root}")
    return summary


# Verbs whose manifest sections describe ARCHIVING/SHIPPING the run
# rather than the run itself: stripped by normalization so that
# archiving, re-archiving, or the agent stamping meta.agent/meta.serve
# can never change the next ingest's content address ("serve",
# "metrics", and "slo" appear only as meta keys — the ack's
# observability fold carries a per-push trace id and wall time — but
# the strip loops cover both namespaces).
_SELF_VERBS = ("archive", "regress", "agent", "serve", "tier",
               "metrics", "slo")


def _normalized_manifest(logdir: str) -> Optional[bytes]:
    """run_manifest.json reduced to canonical bytes that are a pure
    function of the RUN: the archive/regress self-sections, the per-write
    timestamp, and the last-writer-wins ``env``/``config`` snapshots
    (pid, the writing verb's own flags) are stripped — so archiving a
    run, or re-archiving it, can never change what the next ingest sees.
    The health ledger itself (collectors, sources, pipeline runs, stages)
    is what the archive preserves."""
    from sofa_tpu.telemetry import load_manifest

    doc = load_manifest(logdir)
    if doc is None:
        return None
    for volatile in ("generated_unix", "env", "config"):
        doc.pop(volatile, None)
    runs = doc.get("runs")
    if isinstance(runs, dict):
        for verb in _SELF_VERBS:
            runs.pop(verb, None)
    meta = doc.get("meta")
    if isinstance(meta, dict):
        for key in _SELF_VERBS:
            meta.pop(key, None)
    if isinstance(doc.get("stages"), list):
        doc["stages"] = [s for s in doc["stages"]
                         if s.get("verb") not in _SELF_VERBS]
    # A container the strip emptied must normalize like one that never
    # existed — "agent stamped meta.agent, then nothing" and "no agent
    # ever ran" are the same run content.
    for key in ("meta", "runs", "collectors", "sources", "stages"):
        if key in doc and not doc[key]:
            doc.pop(key)
    return json.dumps(doc, indent=1, sort_keys=True).encode()


def _hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:
        return ""


# ---------------------------------------------------------------------------
# gc.
# ---------------------------------------------------------------------------

def gc(root: str, keep: int = 0, keep_days: float = 0.0) -> dict:
    """Drop ingest runs beyond the retention policy and sweep objects no
    surviving run references.  The ONLY deletion path for archived data.

    ``keep``: newest N ingest runs survive (0 = no count limit);
    ``keep_days``: runs ingested within the last D days survive (0 = no
    age limit).  A run survives if EITHER rule keeps it.

    The whole sweep holds the root's ``derived_write_guard`` sentinel:
    the fleet service (archive/service.py) answers uploads 503 +
    Retry-After while it is up, so a push can never race gc deleting the
    objects it just deduped against."""
    from sofa_tpu.trace import derived_write_guard

    with derived_write_guard(root):
        return _gc_locked(root, keep=keep, keep_days=keep_days)


def _gc_locked(root: str, keep: int, keep_days: float) -> dict:
    store = ArchiveStore(root)
    entries = catalog.read_catalog(root)
    runs = catalog.ingest_entries(entries)
    cutoff = (time.time() - keep_days * 86400.0) if keep_days > 0 else None
    dropped: List[str] = []
    kept: List[dict] = []
    for i, e in enumerate(runs):
        newest_n = keep > 0 and i >= len(runs) - keep
        fresh = cutoff is not None and e.get("t", 0) >= cutoff
        if newest_n or fresh or (keep <= 0 and cutoff is None):
            kept.append(e)
        else:
            dropped.append(e["run"])
    for run_id in dropped:
        try:
            os.unlink(store.run_doc_path(run_id))
        except OSError as e:
            print_warning(f"archive gc: cannot drop run doc "
                          f"{run_id[:12]}: {e}")
    # Sweep objects referenced by no surviving run doc (including docs
    # that were never cataloged — fsck's adoption path owns those, gc
    # must not pull bytes out from under them).
    referenced = set()
    for run_id in store.run_ids():
        doc = store.load_run(run_id) or {}
        for ent in (doc.get("files") or {}).values():
            referenced.add(ent.get("sha256"))
    swept = 0
    freed = 0
    obj_root = os.path.join(root, OBJECTS_DIR_NAME)
    for dirpath, _dirs, names in os.walk(obj_root):
        for name in names:
            if name.endswith(".tmp") or name in referenced:
                continue
            path = os.path.join(dirpath, name)
            try:
                freed += os.path.getsize(path)
                os.unlink(path)
                swept += 1
            except OSError as e:
                print_warning(f"archive gc: cannot sweep object "
                              f"{name[:12]}: {e}")
    # Compact the catalog: ingest lines of surviving runs + every
    # non-ingest event (the bench trajectory is history, not retention).
    keep_ids = {e["run"] for e in kept}
    compacted = [e for e in entries
                 if e.get("ev") != "ingest" or e.get("run") in keep_ids]
    catalog.rewrite(root, compacted)
    summary = {"dropped_runs": len(dropped), "swept_objects": swept,
               "freed_bytes": freed}
    catalog.append_event(root, "gc", **summary)
    # The rewrite bumped the catalog generation, deterministically
    # invalidating the columnar index — rebuild it at this commit point
    # so the next query is index-fed instead of paying a full scan.
    from sofa_tpu.archive import index as aindex

    aindex.refresh_after_ingest(root)
    print_progress(
        f"archive gc: dropped {len(dropped)} run(s), swept {swept} "
        f"object(s), freed {freed / 2**20:.2f} MiB")
    return summary


# ---------------------------------------------------------------------------
# fsck.
# ---------------------------------------------------------------------------

def archive_fsck(root: str, repair: bool = False) -> Optional[dict]:
    """Verify store integrity; returns the report dict or None when
    ``root`` is not an archive.  Verdicts: ARCHIVE_FSCK_VERDICTS (damage)
    plus informational ``unreferenced`` (gc's job, not damage)."""
    store = ArchiveStore(root)
    if not store.exists:
        return None
    report: Dict[str, list] = {v: [] for v in ARCHIVE_FSCK_VERDICTS}
    report["unreferenced"] = []
    entries = catalog.read_catalog(root)
    cataloged = {e.get("run") for e in entries if e.get("ev") == "ingest"}
    referenced: Dict[str, str] = {}
    for run_id in store.run_ids():
        doc = store.load_run(run_id)
        if doc is None:
            report["corrupt"].append(f"runs/{run_id}.json")
            continue
        if run_id not in cataloged:
            report["uncataloged"].append(run_id)
        for rel, ent in sorted((doc.get("files") or {}).items()):
            sha = ent.get("sha256", "")
            referenced.setdefault(sha, f"{run_id[:12]}:{rel}")
            if not store.has_object(sha):
                report["missing"].append(f"{run_id[:12]}:{rel}")
    checked = 0
    obj_root = os.path.join(root, OBJECTS_DIR_NAME)
    for dirpath, _dirs, names in os.walk(obj_root):
        for name in sorted(names):
            path = os.path.join(dirpath, name)
            if name.endswith(".tmp"):
                report["orphaned"].append(
                    os.path.relpath(path, root).replace(os.sep, "/"))
                continue
            checked += 1
            if _sha256_file(path) != name:
                report["corrupt"].append(
                    os.path.relpath(path, root).replace(os.sep, "/"))
            elif name not in referenced:
                report["unreferenced"].append(name)
    for dirpath, dirs, names in os.walk(root):
        if os.path.basename(dirpath) == OBJECTS_DIR_NAME:
            dirs[:] = []  # object tmps already classified above
            continue
        for name in names:
            if name.endswith(".tmp"):
                report["orphaned"].append(os.path.relpath(
                    os.path.join(dirpath, name), root).replace(os.sep, "/"))
    # The columnar catalog index (archive/index.py) is digest-less pure
    # derived state — integrity is its per-chunk index-signed shas, and
    # THIS is where that claim is enforced (the frames.verify_frame_store
    # discipline applied to the archive).
    from sofa_tpu.archive import index as aindex

    report["index"] = aindex.verify(root)
    report["checked"] = checked
    if repair:
        _archive_repair(store, report)
    return report


def _sha256_file(path: str) -> Optional[str]:
    h = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            while True:
                chunk = f.read(_HASH_CHUNK)
                if not chunk:
                    break
                h.update(chunk)
    except OSError:
        return None
    return h.hexdigest()


def _archive_repair(store: ArchiveStore, report: Dict[str, list]) -> None:
    """Adopt uncataloged runs, restore corrupt objects from their source
    logdir when it still holds matching bytes (quarantine otherwise),
    and sweep tmp orphans.  Mutates ``report`` toward post-repair truth."""
    root = store.root
    for run_id in list(report.get("uncataloged") or []):
        doc = store.load_run(run_id) or {}
        catalog.append_event(root, "ingest", run=run_id,
                             logdir=doc.get("logdir", ""),
                             files=len(doc.get("files") or {}),
                             new_objects=0, bytes_added=0, recovered=True)
        report["uncataloged"].remove(run_id)
        print_progress(f"archive fsck: re-adopted uncataloged run "
                       f"{run_id[:12]} into the catalog")
    # sha -> (source logdir, rel) from the run docs, for re-copy repair.
    sources: Dict[str, Tuple[str, str]] = {}
    for run_id in store.run_ids():
        doc = store.load_run(run_id) or {}
        for rel, ent in (doc.get("files") or {}).items():
            sources.setdefault(ent.get("sha256", ""),
                               (doc.get("logdir", ""), rel))
    for relpath in list(report.get("corrupt") or []):
        sha = os.path.basename(relpath)
        src = sources.get(sha)
        restored = False
        if src and src[0]:
            cand = os.path.join(src[0], src[1])
            if os.path.isfile(cand) and _sha256_file(cand) == sha:
                try:
                    os.unlink(store.object_path(sha))
                except OSError:
                    pass
                try:
                    store.put_file(cand, None)
                    restored = True
                except OSError as e:
                    print_warning(f"archive fsck: re-copy of {sha[:12]} "
                                  f"from {cand} failed: {e}")
        if restored:
            report["corrupt"].remove(relpath)
            print_progress(f"archive fsck: restored object {sha[:12]} "
                           f"from {src[0]}")
            continue
        qdir = os.path.join(root, QUARANTINE_DIR_NAME)
        try:
            os.makedirs(qdir, exist_ok=True)
            os.replace(os.path.join(root, relpath),
                       os.path.join(qdir, sha))
            report["corrupt"].remove(relpath)
            report.setdefault("missing", []).append(
                f"{(sources.get(sha) or ('?', '?'))[1]} (quarantined "
                f"{sha[:12]})")
            print_warning(f"archive fsck: object {sha[:12]} is rotted and "
                          "its source is gone — quarantined (runs "
                          "referencing it now report missing)")
        except OSError as e:
            print_warning(f"archive fsck: cannot quarantine {sha[:12]}: "
                          f"{e}")
    for rel in list(report.get("orphaned") or []):
        try:
            os.unlink(os.path.join(root, rel))
            report["orphaned"].remove(rel)
        except OSError as e:
            print_warning(f"archive fsck: cannot sweep {rel}: {e}")
    if report.get("index"):
        # pure derived state: drop the damaged index wholesale and
        # rebuild from the catalog + run docs (reusing a chunk whose
        # signed sha still matched would keep rotted bytes alive — the
        # frame-store repair rule)
        from sofa_tpu.archive import index as aindex

        aindex.drop(root)
        rebuilt = aindex.refresh_after_ingest(root)
        still = aindex.verify(root)
        if rebuilt is not None and not still:
            report["index"] = []
            print_progress("archive fsck: dropped the damaged columnar "
                           "index and rebuilt it from the catalog")
        else:
            report["index"] = still or report["index"]


# ---------------------------------------------------------------------------
# Tile diff — the multi-run board view's fast path.
# ---------------------------------------------------------------------------

def tile_diff(doc_a: dict, doc_b: dict) -> dict:
    """Per-series tile comparison of two archived runs BY CONTENT HASH —
    identical tiles compare equal without either payload being read
    (the pyramid is content-keyed and gzip'd deterministically, so
    unchanged data means byte-identical objects).  Returns::

        {"series": {name: {"unchanged": n, "changed": n,
                           "only_a": n, "only_b": n}},
         "totals": {...same counters summed...}}
    """
    def tiles_of(doc: dict) -> Dict[str, str]:
        out = {}
        for rel, ent in (doc.get("files") or {}).items():
            if rel.startswith("_tiles/") and rel.endswith(".json.gz"):
                out[rel] = ent.get("sha256", "")
        return out

    a, b = tiles_of(doc_a), tiles_of(doc_b)
    series: Dict[str, Dict[str, int]] = {}

    def bucket(rel: str) -> Dict[str, int]:
        parts = rel.split("/")
        name = parts[1] if len(parts) > 2 else "?"
        return series.setdefault(name, {"unchanged": 0, "changed": 0,
                                        "only_a": 0, "only_b": 0})

    for rel in sorted(set(a) | set(b)):
        s = bucket(rel)
        if rel not in b:
            s["only_a"] += 1
        elif rel not in a:
            s["only_b"] += 1
        elif a[rel] == b[rel]:
            s["unchanged"] += 1
        else:
            s["changed"] += 1
    totals = {"unchanged": 0, "changed": 0, "only_a": 0, "only_b": 0}
    for s in series.values():
        for k in totals:
            totals[k] += s[k]
    return {"series": series, "totals": totals}


# ---------------------------------------------------------------------------
# `sofa archive` verb.
# ---------------------------------------------------------------------------

def _fmt_mib(n) -> str:
    return f"{(n or 0) / 2**20:.2f}MiB"


def _parse_since(spec: str) -> Optional[float]:
    """``--since`` → unix-time cutoff: a plain number is an absolute
    timestamp; ``<N>d``/``<N>h``/``<N>m`` are relative to now.  None (and
    a warning) on an unparsable spec — a bad filter must not silently
    show everything as if it matched."""
    spec = (spec or "").strip()
    if not spec:
        return None
    unit = {"d": 86400.0, "h": 3600.0, "m": 60.0}.get(spec[-1].lower())
    try:
        if unit is not None:
            return time.time() - float(spec[:-1]) * unit
        return float(spec)
    except ValueError:
        print_warning(f"archive ls: cannot parse --since {spec!r} "
                      "(want a unix timestamp, or e.g. 7d / 12h / 30m) "
                      "— the filter is ignored")
        return None


def _ls_runs(root: str, cfg=None):
    """(filtered runs, total runs, bench count, source) for `ls` — the
    index-fed fast path when a CURRENT index exists (SOFA_ARCHIVE_INDEX=0
    opts out), else the linear scan; BOTH apply the one filter contract
    (index.filter_runs — the tail read applies the same predicates
    vectorized) and feed the one renderer, so the output is
    byte-identical either way (proven by test_archive_index.py)."""
    from sofa_tpu.archive import index as aindex

    host = getattr(cfg, "archive_host", "") or None
    label = getattr(cfg, "archive_label", "") or None
    since = _parse_since(getattr(cfg, "archive_since", "") or "")
    limit = int(getattr(cfg, "archive_limit", 0) or 0) or None

    if limit:
        # newest-N: O(result) — only the tail chunks that hold the
        # answer are read, the totals come from the commit manifest
        tail = aindex.run_entries_tail(root, limit, host=host,
                                       label=label, since=since)
        if tail is not None:
            runs, total, bench_count = tail
            return runs, total, bench_count, "index"
    runs_all = aindex.run_entries(root)
    bench_count = None
    if runs_all is not None:
        bench_count = int((aindex.load_commit(root) or {})
                          .get("bench_events") or 0)
    host_of = None
    source = "index"
    if runs_all is None:
        entries = catalog.read_catalog(root)
        runs_all = catalog.ingest_entries(entries)
        bench_count = len(catalog.bench_entries(entries))
        source = "scan"
        store = ArchiveStore(root)

        def host_of(run_id):
            # the O(fleet)-doc-opens cost the index deletes: only paid
            # when --host filters on the scan path
            return str((store.load_run(run_id) or {})
                       .get("hostname") or "")

    runs = aindex.filter_runs(runs_all, host=host, label=label,
                              since=since, limit=limit, host_of=host_of)
    return runs, len(runs_all), bench_count, source


def render_ls(root: str, runs: "List[dict] | None" = None,
              total_runs: "int | None" = None,
              bench_count: "int | None" = None) -> List[str]:
    if runs is None:
        entries = catalog.read_catalog(root)
        runs = catalog.ingest_entries(entries)
        bench_count = len(catalog.bench_entries(entries))
        total_runs = len(runs)
    shown = (f"{len(runs)} run(s)" if len(runs) == total_runs
             else f"{len(runs)} of {total_runs} run(s)")
    lines = [f"archive: {root} — {shown}, "
             f"{bench_count} bench event(s)"]
    rows = [["RUN", "WHEN", "FILES", "ADDED", "LOGDIR"]]
    for e in runs:
        when = time.strftime("%Y-%m-%d %H:%M",
                             time.localtime(e.get("t", 0)))
        rows.append([e["run"][:12], when, str(e.get("files", "?")),
                     _fmt_mib(e.get("bytes_added")),
                     str(e.get("logdir", ""))[-48:]])
    from sofa_tpu.telemetry import _table

    lines += _table(rows)
    return lines


def render_show(store: ArchiveStore, doc: dict) -> List[str]:
    files = doc.get("files") or {}
    by_kind: Dict[str, List[int]] = {}
    for ent in files.values():
        k = by_kind.setdefault(ent.get("kind", "?"), [0, 0])
        k[0] += 1
        k[1] += ent.get("bytes", 0)
    lines = [f"run {doc.get('run', '?')}",
             f"  ingested {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(doc.get('t', 0)))}"
             f" from {doc.get('logdir', '?')}"
             + (f" [{doc['label']}]" if doc.get("label") else "")]
    for kind, (n, b) in sorted(by_kind.items()):
        lines.append(f"  {kind}: {n} file(s), {_fmt_mib(b)}")
    feats = doc.get("features") or {}
    if feats:
        lines.append(f"  features ({len(feats)}):")
        for name in sorted(feats)[:20]:
            lines.append(f"    {name:<36} {feats[name]:>12.6g}")
        if len(feats) > 20:
            lines.append(f"    ... {len(feats) - 20} more")
    n_tiles = sum(1 for rel in files if rel.startswith("_tiles/"))
    if n_tiles:
        lines.append(f"  tiles: {n_tiles} pyramid file(s) "
                     "(content-addressed; board diffs them by hash)")
    return lines


def sofa_archive(cfg, action: str, arg: str = "",
                 repair: bool = False) -> int:
    """``sofa archive <logdir> | ls | show <run> | gc [--keep N]
    [--keep_days D] | fsck [--repair]`` — the trace-database verb."""
    from sofa_tpu import telemetry
    from sofa_tpu.archive import resolve_root

    root = resolve_root(cfg)
    if action in ("", None):
        print_error("archive needs an action: `sofa archive <logdir>` "
                    "to ingest, or ls / show <run> / gc")
        return 2
    if action == "ls":
        store = ArchiveStore(root)
        if not store.exists:
            print_error(f"no archive at {root} — `sofa archive <logdir>` "
                        "creates one")
            return 2
        runs, total, bench_count, _source = _ls_runs(root, cfg)
        print("\n".join(render_ls(root, runs, total_runs=total,
                                  bench_count=bench_count)))
        return 0
    if action == "show":
        store = ArchiveStore(root)
        run_id = store.resolve_run_id(arg) if arg else None
        if run_id is None:
            print_error(f"archive show: no unique run matches {arg!r} "
                        "(need a >= 6-char unique id prefix; see "
                        "`sofa archive ls`)")
            return 2
        doc = store.load_run(run_id)
        if doc is None:
            print_error(f"archive show: run doc for {run_id[:12]} is "
                        "unreadable — run `sofa fsck` on the archive root")
            return 2
        print_title(f"archived run {run_id[:12]}")
        print("\n".join(render_show(store, doc)))
        return 0
    if action == "fsck":
        # `sofa archive fsck [--repair]` — store-integrity alias of
        # `sofa fsck <archive_root>` (agents and CI scripts read better
        # naming the store explicitly; same exit contract 0/1/2).
        from sofa_tpu.durability import _archive_fsck_verb

        if not ArchiveStore(root).exists:
            print_error(f"no archive at {root}")
            return 2
        return _archive_fsck_verb(root, repair)
    if action == "gc":
        keep = int(getattr(cfg, "archive_keep", 0) or 0)
        keep_days = float(getattr(cfg, "archive_keep_days", 0.0) or 0.0)
        if keep <= 0 and keep_days <= 0:
            print_error("archive gc needs a retention policy: --keep N "
                        "and/or --keep_days D (refusing to guess)")
            return 2
        if not ArchiveStore(root).exists:
            print_error(f"no archive at {root}")
            return 2
        gc(root, keep=keep, keep_days=keep_days)
        return 0
    # default: the action is a logdir to ingest
    if not os.path.isdir(action):
        print_error(f"archive: {action!r} is not a logdir or a known "
                    "action (ls / show / gc)")
        return 2
    import copy

    c = copy.deepcopy(cfg)
    c.logdir = action
    c.__post_init__()
    tel = telemetry.begin("archive")
    try:
        ingest_run(c, root, label=getattr(cfg, "archive_label", "") or "",
                   tel=tel)
        tel.write(c.logdir, rc=0, cfg=c)
        return 0
    finally:
        telemetry.end(tel)
