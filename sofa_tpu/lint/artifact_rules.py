"""SL014–SL018 — artifact-lifecycle flow analysis.

PRs 1–9 grew five parallel hand-maintained answers to "what is a derived
artifact": the registry in ``trace.py`` (DERIVED_FILES/DIRS/SUFFIXES),
the digest skip-list beside it, ``sofa clean``'s sweep, fsck's
classification, and ``tools/manifest_check.py``'s validators — plus board
pages that fetch endpoints by string literal.  Nothing verified these
agree; every new artifact had to be threaded through all of them by hand,
and the next omission is a silent fsck blind spot or a file `sofa clean`
never removes.

This module extracts the whole artifact flow graph statically — writers
(filename literals flowing into ``durability.atomic_write`` /
``atomic_replace`` / ``fsync_append`` / the frame-CSV writers), readers
(logdir ``open``/``read_csv`` sites), the trace.py registries, the meta.*
keys the manifest carries, schema-id/version literals, and the ``fetch()``
endpoints in ``board/*.html`` — and enforces closure:

SL014  artifact written but unregistered in DERIVED_FILES/DIRS (and not
       covered by a derived suffix): it leaks past `sofa clean`
SL015  digest skip-list closure: a skip entry naming nothing registered
       (typo'd blind spot), a skip dir outside DERIVED_DIRS, or an
       artifact rewritten by a verb that never refreshes digests yet is
       not skip-listed (fsck would flag every re-run as corrupt)
SL016  manifest ``meta.*`` key written but never validated by
       manifest_check — or validated but never written (both directions
       of schema drift)
SL017  board fetch endpoint with no producer or server route (error);
       registered machine-readable artifact with no reader anywhere
       (dead artifact, warn)
SL018  schema-id/version literal agreement between writers, the
       manifest_check validator, and docs/OBSERVABILITY.md's schema
       registry table

The graph is also the data model behind the ``sofa artifacts`` inventory
verb (sofa_tpu/artifacts.py).  Extraction is purely syntactic, like the
rest of sofa-lint: the checked code is never imported.  manifest_check,
the board pages, and the docs table live OUTSIDE the linted package;
they are discovered relative to the registry's trace.py (``../tools/``,
``board/``, ``../docs/``) — absent companions disable exactly the rules
that need them, so fixture trees opt in per rule.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from sofa_tpu.lint.core import (
    FileContext,
    Finding,
    Rule,
    SEV_ERROR,
    SEV_WARN,
)

# Path-taking writer helpers: dotted-origin tail -> index of the path arg.
_WRITER_FNS = {
    "atomic_write": 0,
    "atomic_replace": 0,
    "fsync_append": 0,
    "write_csv": 1,
    "write_frame": 1,
    "write_report_js_doc": 1,
}
# DataFrame writer methods whose first argument is the target path.
_WRITER_METHODS = frozenset({"to_csv", "to_parquet"})

_READER_FNS = frozenset({"open", "io.open", "gzip.open"})
_READER_METHODS = frozenset({"read_csv", "read_parquet", "read_json",
                             "read_frame", "DictReader", "loadtxt"})

_SCHEMA_ID_RE = re.compile(r"^sofa_tpu/[a-z_]+$")
_FILENAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.-]*\.[A-Za-z0-9.]+$")
# Data-ish literals in board pages: fetch()/fetchCSV() args, script srcs,
# and the [id, "file.csv"] table idiom.
_BOARD_REF_RE = re.compile(
    r'["\']([A-Za-z0-9_][A-Za-z0-9_./-]*'
    r'\.(?:csv|json|jsonl|js|txt|json\.gz))["\']'
    r'|(?:fetch|fetchCSV)\(\s*["\']([^"\']+)["\']')
_DOCS_ROW_RE = re.compile(
    r"^\|\s*`?(sofa_tpu/[a-z_]+)`?\s*\|\s*(\d+)\s*\|")
# Suffixes SL017's dead-artifact check covers: machine-readable formats a
# reader should exist for.  Human reports (.txt) are end artifacts.
_MACHINE_SUFFIXES = (".js", ".json", ".jsonl", ".csv")


@dataclass(frozen=True)
class Writer:
    """One path-literal-carrying write site."""

    relpath: str
    line: int
    name: str            # the artifact filename literal
    fragments: tuple     # every path-fragment literal seen in the call


@dataclass(frozen=True)
class MetaKey:
    key: str
    relpath: str
    line: int


@dataclass(frozen=True)
class SchemaDecl:
    schema_id: str
    version: "int | None"
    relpath: str
    line: int


@dataclass
class ArtifactGraph:
    """The cross-file artifact flow facts SL014–SL018 (and the ``sofa
    artifacts`` verb) consult.  ``ok`` is False when the linted file set
    carries no registry-bearing trace.py — every artifact rule is then
    inert, which is what lets single-file lints and synthetic fixtures
    run the classic rules without artifact noise."""

    ok: bool = False
    registry_relpath: str = ""
    registry_lines: Dict[tuple, int] = field(default_factory=dict)
    raw_files: frozenset = frozenset()
    derived_files: frozenset = frozenset()
    derived_dirs: frozenset = frozenset()
    derived_suffixes: tuple = ()
    skip_files: frozenset = frozenset()
    skip_dirs: frozenset = frozenset()
    writers: tuple = ()
    reader_names: frozenset = frozenset()
    board_present: bool = False
    board_files: frozenset = frozenset()
    board_fetches: tuple = ()          # (relpath, line, endpoint)
    routes: frozenset = frozenset()    # route heads viz.py serves
    meta_writes: tuple = ()            # MetaKey
    meta_validated: "tuple | None" = None   # MetaKey; None = no validator
    schema_writers: tuple = ()         # SchemaDecl
    schema_validators: tuple = ()      # SchemaDecl (manifest_check)
    manifest_check_refs: frozenset = frozenset()
    docs_versions: "Dict[str, tuple] | None" = None  # id -> (ver, rel, line)
    docs_relpath: str = ""
    pass_artifacts: frozenset = frozenset()
    frame_names: frozenset = frozenset()
    loose_writer_names: frozenset = frozenset()
    digestless_verb_files: frozenset = frozenset()

    # -- coverage helpers (shared with `sofa artifacts`) -------------------
    def clean_coverage(self, name: str, fragments: Tuple[str, ...] = ()) \
            -> "str | None":
        """How `sofa clean` accounts for this artifact, or None if it
        would leak.  The same decision procedure record.sofa_clean runs
        at sweep time, evaluated statically."""
        if name in self.raw_files:
            return "raw"
        if name in self.derived_files:
            return "registered"
        if name.endswith(tuple(self.derived_suffixes)):
            return "suffix"
        for frag in fragments:
            for part in frag.replace(os.sep, "/").split("/"):
                if part in self.derived_dirs:
                    return f"dir:{part}"
                if part in self.skip_dirs:
                    return f"dir:{part}"
        return None

    def digest_coverage(self, name: str,
                        fragments: Tuple[str, ...] = ()) -> str:
        if name in self.skip_files:
            return "skip-list"
        for frag in fragments:
            for part in frag.replace(os.sep, "/").split("/"):
                if part in self.skip_dirs:
                    return f"skip-dir:{part}"
        return "digested"

    def endpoint_producers(self) -> frozenset:
        return frozenset(
            set(self.derived_files) | set(self.raw_files)
            | {w.name for w in self.writers} | set(self.pass_artifacts)
            | {f"{n}.csv" for n in self.frame_names}
            | {f"{n}.parquet" for n in self.frame_names}
            | set(self.loose_writer_names) | set(self.board_files))

    def reader_set(self) -> frozenset:
        board = {os.path.basename(ep) for _f, _l, ep in self.board_fetches}
        return frozenset(set(self.reader_names) | board
                         | set(self.manifest_check_refs))


# ---------------------------------------------------------------------------
# Per-file extraction.
# ---------------------------------------------------------------------------

class _ModuleFacts:
    """Everything one parse of one .py file contributes to the graph."""

    def __init__(self, path: str, relpath: str):
        self.relpath = relpath
        self.writers: List[Writer] = []
        self.reader_names: set = set()
        self.meta_writes: List[MetaKey] = []
        self.schema_decls: List[SchemaDecl] = []
        self.str_consts: Dict[str, str] = {}
        self.int_consts: Dict[str, int] = {}
        self.has_verb = False
        self.has_dynamic_writer = False
        self.refreshes_digests = False
        self.verb_funcs: set = set()
        self.frame_names: set = set()
        self.route_heads: set = set()
        self.filename_literals: set = set()
        try:
            with open(path, "rb") as f:
                self.tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError, ValueError):
            self.tree = None
            return
        self._imports()
        self._module_consts()
        self._scopes()

    def _imports(self):
        self.import_alias: Dict[str, str] = {}
        self.from_import: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_import[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def _module_consts(self):
        for node in self.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            v = node.value
            if isinstance(v, ast.Constant):
                if isinstance(v.value, str):
                    self.str_consts[tgt.id] = v.value
                elif isinstance(v.value, int) and \
                        not isinstance(v.value, bool):
                    self.int_consts[tgt.id] = v.value
            elif isinstance(v, (ast.Tuple, ast.List)) and \
                    tgt.id.endswith("_FRAMES"):
                # e.g. preprocess._XPLANE_FRAMES — frame-name vocabulary
                self.frame_names.update(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))

    def _scopes(self):
        """function-scope single-target assigns: name -> value expression
        (resolves ``hints_dir = cfg.path("x")`` and ``path =
        os.path.join(logdir, JOURNAL_NAME)`` when the name later rides a
        writer's or reader's path argument)."""
        self.scope_assigns: Dict[tuple, ast.expr] = {}
        self.func_of: Dict[int, str] = {}

        def walk(node, func):
            for child in ast.iter_child_nodes(node):
                nf = func
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nf = f"{func}.{child.name}" if func else child.name
                if isinstance(child, ast.Assign) and \
                        len(child.targets) == 1 and \
                        isinstance(child.targets[0], ast.Name):
                    key = (func, child.targets[0].id)
                    self.scope_assigns.setdefault(key, child.value)
                self.func_of[id(child)] = nf if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)) else func
                walk(child, nf)

        walk(self.tree, "")

    # -- resolution --------------------------------------------------------
    def resolve_call_name(self, node: ast.Call) -> str:
        fn = node.func
        if isinstance(fn, ast.Name):
            return self.from_import.get(fn.id,
                                        self.import_alias.get(fn.id, fn.id))
        if isinstance(fn, ast.Attribute):
            parts = [fn.attr]
            cur = fn.value
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                parts.append(self.import_alias.get(
                    cur.id, self.from_import.get(cur.id, cur.id)))
            return ".".join(reversed(parts))
        return ""

    def path_fragments(self, expr, func: str,
                       cross: Dict[tuple, str],
                       _depth: int = 0, _seen=None) -> List[str]:
        """Every string literal reachable from a path expression: direct
        constants, names resolved through enclosing-scope assignments
        (recursively, so ``a = join(b, CONST)`` chains resolve), module
        constants, and cross-module from-imports."""
        out: List[str] = []
        seen = _seen if _seen is not None else set()
        if _depth > 4:
            return out
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.append(sub.value)
            elif isinstance(sub, ast.Name):
                name = sub.id
                if name in seen:
                    continue
                seen.add(name)
                if name in self.str_consts:
                    out.append(self.str_consts[name])
                    continue
                scope, hit = func, None
                while hit is None:
                    hit = self.scope_assigns.get((scope, name))
                    if not scope:
                        break
                    scope = scope.rpartition(".")[0]
                if hit is not None:
                    out.extend(self.path_fragments(
                        hit, func, cross, _depth + 1, seen))
                elif name in self.from_import:
                    origin = self.from_import[name]
                    mod, _, attr = origin.rpartition(".")
                    val = cross.get((mod.rpartition(".")[-1], attr))
                    if val is not None:
                        out.append(val)
        return out

    # -- the walk ----------------------------------------------------------
    def harvest(self, cross: Dict[tuple, str]):
        if self.tree is None:
            return
        in_ingest_tasks = False
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("sofa_") and \
                    self.func_of.get(id(node), "") == node.name:
                self.has_verb = True
                self.verb_funcs.add(node.name)
            if not isinstance(node, ast.Call):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        _FILENAME_RE.match(node.value):
                    self.filename_literals.add(node.value)
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        re.match(r"^/[a-z_]+/$", node.value):
                    self.route_heads.add(node.value.strip("/"))
                continue
            func = self.func_of.get(id(node), "")
            resolved = self.resolve_call_name(node)
            tail = resolved.rsplit(".", 1)[-1]
            if tail == "write_digests":
                self.refreshes_digests = True
            # preprocess's ingest task table: T("source", ..., frames=...)
            if tail == "T" and "_ingest_tasks" in func and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                self.frame_names.add(node.args[0].value)
                for kw in node.keywords:
                    if kw.arg == "frames":
                        self.frame_names.update(
                            s.value for s in ast.walk(kw.value)
                            if isinstance(s, ast.Constant)
                            and isinstance(s.value, str))
                in_ingest_tasks = True
            # meta.* writers
            if tail == "set_meta" and isinstance(node.func, ast.Attribute):
                for kw in node.keywords:
                    if kw.arg:
                        self.meta_writes.append(
                            MetaKey(kw.arg, self.relpath, node.lineno))
            if tail == "_patch_manifest":
                for kw in node.keywords:
                    if kw.arg == "meta" and isinstance(kw.value, ast.Dict):
                        for k in kw.value.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                self.meta_writes.append(MetaKey(
                                    k.value, self.relpath, k.lineno))
            # writers
            arg_idx = _WRITER_FNS.get(tail)
            is_method_writer = (isinstance(node.func, ast.Attribute)
                                and node.func.attr in _WRITER_METHODS)
            if arg_idx is not None or is_method_writer:
                idx = 0 if is_method_writer else arg_idx
                if len(node.args) > idx:
                    frags = self.path_fragments(node.args[idx], func, cross)
                    names = [os.path.basename(f) for f in frags
                             if _FILENAME_RE.match(os.path.basename(f))]
                    if names:
                        self.writers.append(Writer(
                            self.relpath, node.lineno, names[-1],
                            tuple(frags)))
                    else:
                        # a write whose path arrives via a parameter (the
                        # diff movers-table helper): the module's own
                        # filename literals become producers-by-
                        # association for the endpoint check only
                        self.has_dynamic_writer = True
            # readers
            is_reader = resolved in _READER_FNS or tail in _READER_METHODS
            if resolved in _READER_FNS:
                mode = None
                if len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                       ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and any(m in mode for m in "wax"):
                    is_reader = False
            if is_reader and node.args:
                for f in self.path_fragments(node.args[0], func, cross):
                    base = os.path.basename(f)
                    if _FILENAME_RE.match(base):
                        self.reader_names.add(base)
        if in_ingest_tasks:
            self.frame_names.discard("")

    def schema_literals(self):
        for name, value in self.str_consts.items():
            if not _SCHEMA_ID_RE.match(value):
                continue
            version = None
            if name.endswith("_SCHEMA"):
                version = self.int_consts.get(name[:-7] + "_VERSION")
            line = 0
            for node in self.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.targets[0], ast.Name) and \
                        node.targets[0].id == name:
                    line = node.lineno
            self.schema_decls.append(
                SchemaDecl(value, version, self.relpath, line))


# ---------------------------------------------------------------------------
# Registry + companion extraction.
# ---------------------------------------------------------------------------

def _registry_from_trace(path: str):
    """The five registry tables out of trace.py's AST, with per-entry
    line numbers for finding anchors.  Returns None when the file does
    not declare DERIVED_FILES (not a registry-bearing trace.py)."""
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    tables: Dict[str, List[tuple]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = node.value
        elts = None
        if isinstance(v, (ast.List, ast.Tuple, ast.Set)):
            elts = v.elts
        elif isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                and v.func.id == "frozenset" and v.args and \
                isinstance(v.args[0], (ast.Set, ast.List, ast.Tuple)):
            elts = v.args[0].elts
        if elts is None:
            continue
        tables[tgt.id] = [(e.value, e.lineno) for e in elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
    if "DERIVED_FILES" not in tables:
        return None
    return tables


def _board_facts(board_dir: str, base: str):
    files, fetches = set(), []
    for name in sorted(os.listdir(board_dir)):
        if not name.endswith((".html", ".js", ".css")):
            continue
        files.add(name)
        path = os.path.join(board_dir, name)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(path), base)
        rel = rel.replace(os.sep, "/") if not rel.startswith("..") \
            else os.path.abspath(path)
        for i, line in enumerate(src.splitlines(), 1):
            for m in _BOARD_REF_RE.finditer(line):
                ep = m.group(1) or m.group(2)
                if ep:
                    fetches.append((rel, i, ep))
    # de-dup per (file, endpoint) keeping the first line
    seen, uniq = set(), []
    for rel, line, ep in fetches:
        if (rel, ep) not in seen:
            seen.add((rel, ep))
            uniq.append((rel, line, ep))
    return frozenset(files), tuple(uniq)


def _docs_versions(path: str, base: str):
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            src = f.read()
    except OSError:
        return None, ""
    rel = os.path.relpath(os.path.abspath(path), base)
    rel = rel.replace(os.sep, "/") if not rel.startswith("..") \
        else os.path.abspath(path)
    rows: Dict[str, tuple] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _DOCS_ROW_RE.match(line.strip())
        if m:
            rows[m.group(1)] = (int(m.group(2)), rel, i)
    return rows, rel


def build_artifact_graph(files, base: str,
                         passes=()) -> ArtifactGraph:
    """Assemble the graph from the linted file set.  ``files`` must
    contain a registry-bearing trace.py for the graph to activate; the
    validator / board / docs companions are discovered relative to it."""
    base = os.path.abspath(base)
    trace_path = None
    tables = None
    for f in files:
        if os.path.basename(f) == "trace.py":
            tables = _registry_from_trace(f)
            if tables is not None:
                trace_path = os.path.abspath(f)
                break
    if trace_path is None:
        return ArtifactGraph(ok=False)

    def rel(p):
        ab = os.path.abspath(p)
        return (os.path.relpath(ab, base).replace(os.sep, "/")
                if ab.startswith(base + os.sep) else ab)

    registry_lines: Dict[tuple, int] = {}
    for table, prefix in (("RAW_FILES", "raw"), ("DERIVED_FILES", "derived"),
                          ("DERIVED_DIRS", "dir"),
                          ("DIGEST_SKIP_FILES", "skip"),
                          ("DIGEST_SKIP_DIRS", "skipdir")):
        for name, line in tables.get(table, []):
            registry_lines[(prefix, name)] = line

    pkg_dir = os.path.dirname(trace_path)
    repo = os.path.dirname(pkg_dir)

    # per-file facts + the cross-module constant table
    facts: List[_ModuleFacts] = []
    mc_path = os.path.join(repo, "tools", "manifest_check.py")
    py_files = [f for f in files if f.endswith(".py")]
    if os.path.isfile(mc_path):
        py_files.append(mc_path)
    seen = set()
    for f in py_files:
        ab = os.path.abspath(f)
        if ab in seen:
            continue
        seen.add(ab)
        facts.append(_ModuleFacts(f, rel(f)))
    cross: Dict[tuple, str] = {}
    for mf in facts:
        stem = os.path.splitext(os.path.basename(mf.relpath))[0]
        for name, value in mf.str_consts.items():
            cross.setdefault((stem, name), value)
    for mf in facts:
        if mf.tree is not None:
            mf.harvest(cross)
            mf.schema_literals()

    mc_rel = rel(mc_path) if os.path.isfile(mc_path) else ""
    mc_facts = next((mf for mf in facts if mf.relpath == mc_rel), None)

    # Verb entry points = sofa_* functions the CLI dispatcher actually
    # from-imports (a sofa_* helper another module wraps — the aisi pass
    # — is not a verb).  Lint's own cli.py is not the dispatcher.
    dispatched: set = set()
    for mf in facts:
        if os.path.basename(mf.relpath) == "cli.py" and \
                "/lint/" not in f"/{mf.relpath}":
            for origin in mf.from_import.values():
                tail = origin.rsplit(".", 1)[-1]
                if tail.startswith("sofa_") or tail == "cluster_record":
                    dispatched.add(tail)

    writers: List[Writer] = []
    reader_names: set = set()
    meta_writes: List[MetaKey] = []
    schema_writers: List[SchemaDecl] = []
    frame_names: set = set()
    route_heads: set = set()
    loose_names: set = set()
    digestless: set = set()
    for mf in facts:
        if mf is mc_facts:
            continue
        writers.extend(mf.writers)
        reader_names |= mf.reader_names
        meta_writes.extend(mf.meta_writes)
        schema_writers.extend(mf.schema_decls)
        frame_names |= mf.frame_names
        route_heads |= mf.route_heads
        if mf.has_dynamic_writer:
            loose_names |= mf.filename_literals
        if (mf.verb_funcs & dispatched) and not mf.refreshes_digests:
            digestless.add(mf.relpath)

    meta_validated = None
    schema_validators: tuple = ()
    mc_refs: frozenset = frozenset()
    if mc_facts is not None and mc_facts.tree is not None:
        keys: List[MetaKey] = []
        for node in ast.walk(mc_facts.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                receiver_consts = {
                    s.value for s in ast.walk(node.func.value)
                    if isinstance(s, ast.Constant)
                    and isinstance(s.value, str)}
                if "meta" in receiver_consts:
                    keys.append(MetaKey(node.args[0].value, mc_rel,
                                        node.lineno))
        meta_validated = tuple(sorted(keys, key=lambda k: k.key))
        schema_validators = tuple(mc_facts.schema_decls)
        mc_refs = frozenset(mc_facts.filename_literals)

    board_dir = os.path.join(pkg_dir, "board")
    board_present = os.path.isdir(board_dir)
    board_files: frozenset = frozenset()
    board_fetches: tuple = ()
    if board_present:
        board_files, board_fetches = _board_facts(board_dir, base)

    docs_path = os.path.join(repo, "docs", "OBSERVABILITY.md")
    docs_versions, docs_rel = (None, "")
    if os.path.isfile(docs_path):
        docs_versions, docs_rel = _docs_versions(docs_path, base)

    pass_artifacts = frozenset(
        a for d in passes for a in getattr(d, "provides_artifacts", ()))

    return ArtifactGraph(
        ok=True,
        registry_relpath=rel(trace_path),
        registry_lines=registry_lines,
        raw_files=frozenset(n for n, _l in tables.get("RAW_FILES", [])),
        derived_files=frozenset(
            n for n, _l in tables.get("DERIVED_FILES", [])),
        derived_dirs=frozenset(n for n, _l in tables.get("DERIVED_DIRS", [])),
        derived_suffixes=tuple(
            n for n, _l in tables.get("DERIVED_SUFFIXES", [])),
        skip_files=frozenset(
            n for n, _l in tables.get("DIGEST_SKIP_FILES", [])),
        skip_dirs=frozenset(
            n for n, _l in tables.get("DIGEST_SKIP_DIRS", [])),
        writers=tuple(sorted(writers, key=lambda w: (w.relpath, w.line, w.name))),
        reader_names=frozenset(reader_names),
        board_present=board_present,
        board_files=board_files,
        board_fetches=board_fetches,
        routes=frozenset(route_heads),
        meta_writes=tuple(sorted(meta_writes, key=lambda k: (k.relpath, k.line, k.key))),
        meta_validated=meta_validated,
        schema_writers=tuple(sorted(schema_writers, key=lambda s: (s.relpath, s.line, s.schema_id))),
        schema_validators=schema_validators,
        manifest_check_refs=mc_refs,
        docs_versions=docs_versions,
        docs_relpath=docs_rel,
        pass_artifacts=pass_artifacts,
        frame_names=frozenset(frame_names),
        loose_writer_names=frozenset(loose_names),
        digestless_verb_files=frozenset(digestless),
    )


# ---------------------------------------------------------------------------
# The rules.
# ---------------------------------------------------------------------------

def _graph(ctx: FileContext) -> Optional[ArtifactGraph]:
    g = getattr(ctx.project, "artifacts", None)
    return g if isinstance(g, ArtifactGraph) and g.ok else None


class _ArtifactRule(Rule):
    """Base: finish()-only rules over the shared flow graph.  Cross-file
    findings (board pages, manifest_check, the docs table) are emitted
    while visiting the registry's trace.py so each appears exactly once;
    writer-anchored findings are emitted from the writer's own file (and
    are inline-suppressible there)."""

    node_types: tuple = ()


class UnregisteredArtifactWrite(_ArtifactRule):
    """SL014 — an artifact written into the logdir that neither the
    DERIVED_FILES/DERIVED_DIRS registry, a derived suffix, nor RAW_FILES
    accounts for: `sofa clean` leaks it and `record._clean_stale` lets
    it bleed into the next run's trace."""

    rule_id = "SL014"
    severity = SEV_ERROR
    # the archive store writes into its own root (gc is its only deletion
    # path, archive_fsck its ledger) — logdir lifecycle does not apply
    exempt_files = ("archive/",)

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        for w in g.writers:
            if w.relpath != ctx.relpath:
                continue
            if g.clean_coverage(w.name, w.fragments) is None:
                yield Finding(
                    w.relpath, w.line, self.rule_id,
                    f"artifact {w.name!r} is written here but registered "
                    "nowhere — not in trace.DERIVED_FILES, no "
                    "DERIVED_SUFFIXES match, not under a DERIVED_DIRS "
                    "directory: `sofa clean` leaks it",
                    self.severity)


class DigestSkipClosure(_ArtifactRule):
    """SL015 — the digest skip-list agrees with the registry in both
    directions, so `sofa fsck` has no blind spots: every skip entry
    names a registered artifact (a rename leaves a typo'd entry that
    silently uncovers the renamed file), every skip dir is a registered
    scratch dir, and an artifact a non-digest-refreshing verb rewrites
    must be on the skip-list (else every re-run reads as corrupt)."""

    rule_id = "SL015"
    severity = SEV_ERROR
    exempt_files = ("archive/",)

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        if ctx.relpath == g.registry_relpath:
            known = g.derived_files | g.raw_files
            for name in sorted(g.skip_files - known):
                yield Finding(
                    g.registry_relpath,
                    g.registry_lines.get(("skip", name), 0), self.rule_id,
                    f"digest skip-list entry {name!r} names no registered "
                    "artifact (RAW_FILES/DERIVED_FILES) — a rename left "
                    "the real file silently digest-covered or the entry "
                    "is dead", self.severity)
            allowed_dirs = g.derived_dirs | {"_inject", "__pycache__"}
            for name in sorted(g.skip_dirs - allowed_dirs):
                yield Finding(
                    g.registry_relpath,
                    g.registry_lines.get(("skipdir", name), 0),
                    self.rule_id,
                    f"digest skip dir {name!r} is not in DERIVED_DIRS — "
                    "`sofa clean` does not know it, so its contents leak",
                    self.severity)
        if ctx.relpath in g.digestless_verb_files:
            for w in g.writers:
                if w.relpath != ctx.relpath:
                    continue
                if g.digest_coverage(w.name, w.fragments) == "digested":
                    yield Finding(
                        w.relpath, w.line, self.rule_id,
                        f"artifact {w.name!r} is written by a verb module "
                        "that never refreshes the digest ledger "
                        "(durability.write_digests) — the next `sofa "
                        "fsck` reads the rewrite as corruption; add it "
                        "to trace.DIGEST_SKIP_FILES or refresh digests",
                        self.severity)


class ManifestMetaClosure(_ArtifactRule):
    """SL016 — every manifest ``meta.*`` section written by the pipeline
    is validated by tools/manifest_check.py, and every key the validator
    checks is still written by someone.  Both directions are schema
    drift: an unvalidated key rots silently; a validated-but-unwritten
    key means the producer was renamed or dropped and CI checks air."""

    rule_id = "SL016"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None or g.meta_validated is None:
            return
        validated = {k.key for k in g.meta_validated}
        written = {k.key for k in g.meta_writes}
        seen_here = set()
        for mw in g.meta_writes:
            if mw.relpath != ctx.relpath or mw.key in seen_here:
                continue
            seen_here.add(mw.key)
            if mw.key not in validated:
                yield Finding(
                    mw.relpath, mw.line, self.rule_id,
                    f"manifest key meta.{mw.key} is written here but "
                    "tools/manifest_check.py never validates it — the "
                    "section can rot without CI noticing; add a "
                    "validator clause", self.severity)
        if ctx.relpath == g.registry_relpath:
            for mk in g.meta_validated:
                if mk.key not in written:
                    yield Finding(
                        mk.relpath, mk.line, self.rule_id,
                        f"manifest_check validates meta.{mk.key} but no "
                        "pipeline code writes that key — the producer "
                        "was renamed or dropped; fix the validator or "
                        "restore the writer", self.severity)


class BoardEndpointFlow(_ArtifactRule):
    """SL017 — board pages and the data they fetch stay connected:
    every literal ``fetch()`` endpoint needs a producer (a registered
    artifact, an extracted writer, a declared pass artifact, a frame
    CSV) or a server route (viz.py's /tiles/, /archive/); and every
    registered machine-readable artifact needs at least one reader
    somewhere (board or pipeline) — a writer nobody reads is a dead
    artifact (warn-tier: it may be an external-tool contract)."""

    rule_id = "SL017"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None or not g.board_present or \
                ctx.relpath != g.registry_relpath:
            return
        producers = g.endpoint_producers()
        for bfile, line, ep in g.board_fetches:
            clean = ep.lstrip("./")
            head, _, _rest = clean.partition("/")
            if "/" in clean and (head in g.routes
                                 or head in g.derived_dirs
                                 or head.lstrip("_") in g.routes):
                continue
            if os.path.basename(clean) in producers:
                continue
            yield Finding(
                bfile, line, self.rule_id,
                f"board endpoint {ep!r} has no producer in the tree (no "
                "registered artifact, writer, pass artifact, frame CSV, "
                "or viz route) — the page fetches a 404",
                self.severity)
        readers = g.reader_set()
        for name in sorted(g.derived_files):
            if not name.endswith(_MACHINE_SUFFIXES):
                continue
            if name not in readers:
                yield Finding(
                    g.registry_relpath,
                    g.registry_lines.get(("derived", name), 0),
                    self.rule_id,
                    f"registered artifact {name!r} has a writer but no "
                    "reader anywhere (board fetch, pipeline open, "
                    "manifest_check) — dead artifact?", SEV_WARN)


class SchemaVersionAgreement(_ArtifactRule):
    """SL018 — every ``sofa_tpu/*`` schema-id literal tells one story:
    all writers of an id agree on its version, the manifest_check
    validator pins the same version, and docs/OBSERVABILITY.md's schema
    registry table carries a matching row.  A version bumped in one
    place but not the others is exactly the drift the versioning policy
    exists to prevent."""

    rule_id = "SL018"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        by_id: Dict[str, List[SchemaDecl]] = {}
        for sd in g.schema_writers:
            by_id.setdefault(sd.schema_id, []).append(sd)
        validators = {sd.schema_id: sd for sd in g.schema_validators}
        for sd in g.schema_writers:
            if sd.relpath != ctx.relpath:
                continue
            peers = by_id[sd.schema_id]
            versions = {p.version for p in peers if p.version is not None}
            if len(versions) > 1 and sd.version is not None and \
                    sd is min((p for p in peers if p.version is not None),
                              key=lambda p: (p.relpath, p.line)):
                yield Finding(
                    sd.relpath, sd.line, self.rule_id,
                    f"schema {sd.schema_id!r} is written with conflicting "
                    f"versions {sorted(versions)} across "
                    f"{sorted({p.relpath for p in peers})}",
                    self.severity)
            val = validators.get(sd.schema_id)
            if val is not None and sd.version is not None:
                if val.version is None:
                    yield Finding(
                        sd.relpath, sd.line, self.rule_id,
                        f"schema {sd.schema_id!r} v{sd.version}: "
                        "tools/manifest_check.py declares the id but pins "
                        "no *_VERSION constant — version drift passes "
                        "validation", self.severity)
                elif val.version != sd.version:
                    yield Finding(
                        sd.relpath, sd.line, self.rule_id,
                        f"schema {sd.schema_id!r}: writer says "
                        f"v{sd.version}, manifest_check pins "
                        f"v{val.version}", self.severity)
            if g.docs_versions is not None and sd.version is not None:
                row = g.docs_versions.get(sd.schema_id)
                if row is None:
                    yield Finding(
                        sd.relpath, sd.line, self.rule_id,
                        f"schema {sd.schema_id!r} v{sd.version} has no "
                        "row in docs/OBSERVABILITY.md's schema registry "
                        "table", self.severity)
                elif row[0] != sd.version:
                    yield Finding(
                        sd.relpath, sd.line, self.rule_id,
                        f"schema {sd.schema_id!r}: writer says "
                        f"v{sd.version}, docs/OBSERVABILITY.md's table "
                        f"says v{row[0]} — regenerate the table",
                        self.severity)
        if ctx.relpath == g.registry_relpath:
            writer_ids = set(by_id)
            for sd in g.schema_validators:
                if sd.schema_id not in writer_ids:
                    yield Finding(
                        sd.relpath, sd.line, self.rule_id,
                        f"manifest_check validates schema "
                        f"{sd.schema_id!r} that no writer in the tree "
                        "emits — stale validator", self.severity)
            if g.docs_versions is not None:
                for sid, (_ver, drel, dline) in sorted(
                        g.docs_versions.items()):
                    if sid not in writer_ids:
                        yield Finding(
                            drel, dline, self.rule_id,
                            f"docs/OBSERVABILITY.md lists schema {sid!r} "
                            "that no writer in the tree emits — stale "
                            "table row", self.severity)


ARTIFACT_RULES = (
    UnregisteredArtifactWrite,
    DigestSkipClosure,
    ManifestMetaClosure,
    BoardEndpointFlow,
    SchemaVersionAgreement,
)
