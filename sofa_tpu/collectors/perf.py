"""CPU sampling via `perf record`.

Wraps the profiled command as `perf record -o logdir/perf.data -F rate
[-e events] -- <cmd>` (reference: sofa_record.py:339-354).  When perf is
missing or gated by kernel sysctls the collector degrades to a
/usr/bin/time -v wrapper (reference fallback, sofa_record.py:401-405) and the
CPU timeline is reconstructed from procmon's per-core counters instead.

The reference hard-exits when kptr_restrict/perf_event_paranoid are too
strict (sofa_record.py:188-199); we degrade with the exact sysctl command in
the warning instead — profiling should never refuse to run.
"""

from __future__ import annotations

import os
from typing import List, Optional

from sofa_tpu.collectors.base import Collector


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _count_events(spec: str) -> int:
    """TOP-LEVEL events in a perf -e list: commas inside raw PMU
    descriptors (cpu/event=0x3c,umask=0x1/) or {group} syntax separate
    parameters, not events."""
    n, depth, in_pmu = 1, 0, False
    for ch in spec:
        if ch == "/":
            in_pmu = not in_pmu
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth = max(depth - 1, 0)
        elif ch == "," and depth == 0 and not in_pmu:
            n += 1
    return n


class PerfCollector(Collector):
    name = "perf"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.mode = "off"  # off | perf | time

    def probe(self) -> Optional[str]:
        # A degraded perf is still a usable collector (time -v fallback), so
        # fallback paths warn here and return None; only "no fallback either"
        # reports unavailable.
        from sofa_tpu import telemetry
        from sofa_tpu.printing import print_warning

        self.mode = "perf"
        degraded = None
        if self.cfg.no_perf_events:
            self.mode = "time"
        elif self.which("perf") is None:
            self.mode = "time"
            degraded = "perf not installed; /usr/bin/time -v fallback"
            print_warning("perf: not installed — falling back to /usr/bin/time -v")
        else:
            paranoid = _read_int("/proc/sys/kernel/perf_event_paranoid")
            if paranoid is not None and paranoid > 1 and os.geteuid() != 0:
                self.mode = "time"
                degraded = (f"perf_event_paranoid={paranoid}; "
                            "/usr/bin/time -v fallback")
                print_warning(
                    f"perf: perf_event_paranoid={paranoid}; run "
                    "`sudo sysctl -w kernel.perf_event_paranoid=-1` to enable "
                    "perf sampling — falling back to /usr/bin/time -v"
                )
        if degraded:
            # An involuntary fallback is a fidelity loss the manifest must
            # carry (--no-perf-events is a choice, not a degradation).
            telemetry.collector_event(self.name, "degraded", reason=degraded)
        if self.mode == "time" and not os.path.isfile("/usr/bin/time"):
            return "neither perf nor /usr/bin/time available"
        return None

    def _record_argv(self) -> List[str]:
        cfg = self.cfg
        argv = [
            "perf", "record",
            "-o", cfg.path("perf.data"),
            "-F", str(cfg.cpu_sample_rate),
        ]
        if cfg.perf_call_graph == "fp":
            argv += ["--call-graph", "fp"]
        elif cfg.perf_call_graph == "dwarf":
            argv += ["--call-graph", "dwarf,16384"]
        if cfg.perf_events:
            argv += ["-e", cfg.perf_events]
        return argv

    def command_prefix(self) -> List[str]:
        cfg = self.cfg
        if self.mode == "perf":
            return self._record_argv() + ["--"]
        if self.mode == "time" and os.path.isfile("/usr/bin/time"):
            return ["/usr/bin/time", "-v", "-o", cfg.path("time.txt")]
        return []

    def attach_argv(self, pid: int) -> List[str]:
        """`perf record -p <pid>` for attach mode; [] when perf unavailable."""
        if self.mode != "perf":
            return []
        return self._record_argv() + ["-p", str(pid)]

    def scoped_argv(self, cgroup: str) -> List[str]:
        """Container-scoped sampling: system-wide filtered to the
        container's cgroup (`-a -G`, like the reference's
        --cgroup=docker/<cid>, sofa_record.py:380-399).  Pid-attach
        fallback is attach_argv."""
        if self.mode != "perf":
            return []
        # perf pairs cgroups with events positionally: one -G entry per
        # -e event, or only the first event gets scoped.
        n_events = (_count_events(self.cfg.perf_events)
                    if self.cfg.perf_events else 1)
        return self._record_argv() + [
            "-a", "-G", ",".join([cgroup] * n_events)]

    def outputs(self) -> List[str]:
        cfg = self.cfg
        return [cfg.path("perf.data"), cfg.path("perf.script"),
                cfg.path("time.txt"), cfg.path("kallsyms")]

    def harvest(self) -> None:
        # Copy kernel symbols for offline `perf script` runs, like the
        # reference snapshots /proc/kallsyms (sofa_record.py:231-233).
        if self.mode != "perf":
            return
        try:
            with open("/proc/kallsyms") as src, open(self.cfg.path("kallsyms"), "w") as dst:
                dst.write(src.read())
        except OSError:
            pass
