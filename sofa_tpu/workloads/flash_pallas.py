"""Pallas TPU flash attention — the fused local-attention kernel.

The transformer workload's per-chip attention (plain_causal_attention and
each ring-attention hop) materializes the [B,H,Tq,Tk] score matrix in HBM;
this kernel keeps the online-softmax recurrence in VMEM so scores never
leave the chip: one grid program per (batch*head, q-block), a fori_loop over
k-blocks up to the causal frontier, f32 accumulators, MXU matmuls via
jnp.dot(preferred_element_type=f32).

Layout notes (see /opt/skills/guides/pallas_guide.md): last dim = head_dim
rides the 128-lane axis; K/V stay fully VMEM-resident per (batch, head) —
T=8192, D=128 in bf16 is 2 MB each, comfortably under the ~16 MB VMEM
budget; q blocks default to 128 rows (one MXU tile of sublanes in f32).

Falls back to the interpreter off-TPU so numerics are testable anywhere
(tests/test_workloads.py compares against the reference lax implementation).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  causal: bool, scale: float):
    # q_ref: [1, block_q, D]; k_ref, v_ref: [1, T, D]; o_ref: [1, block_q, D]
    iq = pl.program_id(1)
    t_total = k_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    if causal:
        # Only k-blocks at or before the causal frontier contribute.
        n_blocks = (iq * block_q + block_q + block_k - 1) // block_k
    else:
        n_blocks = t_total // block_k

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        m_blk = jnp.max(s, axis=-1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                           # [bq, bk]
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, n_blocks, body, (acc, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(
    q, k, v,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Fused attention over [B, T, H, D] tensors (H == kv heads; expand GQA
    before calling, as the transformer workload already does)."""
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} must divide block sizes "
                         f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = d ** -0.5

    # [B, T, H, D] -> [B*H, T, D]: contiguous (T, D) planes per grid row.
    def to_planes(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qp, kp, vp = to_planes(q), to_planes(k), to_planes(v)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq: (bh, iq, 0)),
            pl.BlockSpec((1, t, d), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, iq: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
