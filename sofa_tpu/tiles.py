"""Level-of-detail timeline tiles — the board's deep-zoom data path.

``report.js`` carries a globally downsampled overview of every series (the
level-0 picture: ~``--viz_downsample_to`` points no matter how large the
trace), which makes first paint O(pixels) but means zooming IN shows *less*
detail, not more.  This module builds the complement: a per-series
multi-resolution tile pyramid under ``<logdir>/_tiles/`` that the board
fetches viewport-driven on zoom, so deep zoom regains full event fidelity
while the wire payload stays bounded per request.

Layout (all files pre-gzipped columnar JSON)::

    <logdir>/_tiles/<series>/<level>/<n>.json.gz   one tile
    <logdir>/_tiles/<series>/tile_index.json       per-series content key

Pyramid math: a series' time domain [x0, x1] splits into ``2**L`` equal
windows at level ``L``; tile ``n`` at level ``L`` covers exactly tiles
``2n`` and ``2n+1`` at level ``L+1`` (refinement invariant).  Levels deepen
until every leaf tile holds at most ``TILE_RAW_MAX`` raw events (capped by
``--tile_levels``); leaf tiles are ALWAYS exact — the acceptance contract
is that a deepest-zoom request returns the raw events for its window with
no downsampling loss.  Non-leaf tiles over the budget are decimated to a
min/max envelope: ``TILE_BUCKETS`` equal sub-windows each keep their
lowest- and highest-y point (so the drawn outline of the decimated tile is
pixel-identical to the raw data's outline at that zoom), plus the
``TILE_STRAGGLERS`` longest-duration events in the tile (the same
straggler-preservation argument as trace.downsample), plus a per-bucket
``density`` histogram.

Tiles are content-keyed cached like the ingest cache: the per-series key
signs the series' data arrays and the pyramid parameters, so a re-run over
unchanged frames skips the build entirely, and any data change rebuilds
only the series that changed.  Builds fan out across the shared ``--jobs``
thread pool (sofa_tpu/pool.py) — json+gzip release the GIL.

Empty windows get no file (sparse pyramid); the board treats a 404 as an
empty tile.  Series small enough that the report.js overview is already
exact (len <= --viz_downsample_to) get no pyramid at all.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
import re
import shutil
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

TILES_DIR_NAME = "_tiles"
TILE_INDEX_NAME = "tile_index.json"
TILES_VERSION = 1

# A leaf tile holds at most this many raw events (auto level depth stops
# here); sized so a worst-case exact tile gzips well under the 64 KiB
# per-request budget.
TILE_RAW_MAX = 4096
# Decimation buckets per non-leaf tile: each bucket keeps its min/max-y
# point, so a tile never ships more than ~2*TILE_BUCKETS + TILE_STRAGGLERS
# points regardless of raw density.
TILE_BUCKETS = 256
TILE_STRAGGLERS = 64
# Auto mode depth cap: 12 levels of exact leaves cover ~8M-point series
# (TILE_RAW_MAX * 2**11); --tile_levels overrides.
MAX_LEVELS = 12

_SAFE_NAME = re.compile(r"[^A-Za-z0-9_.-]")


def series_dir_name(name: str) -> str:
    """Filesystem-safe directory for a series name (filter keywords are
    user input and may hold separators); collisions get a hash suffix."""
    safe = _SAFE_NAME.sub("_", name).lstrip(".") or "series"
    if safe != name:
        safe += "-" + hashlib.sha1(name.encode()).hexdigest()[:8]
    return safe


def _scrub(values, digits: int) -> np.ndarray:
    """Vectorized NaN/Inf -> 0.0 (bare NaN tokens are invalid JSON for the
    board's parser) + rounding — replaces the per-value _num round-trip."""
    a = np.asarray(values, dtype=float)
    a = np.where(np.isfinite(a), a, 0.0)
    return np.round(a, digits)


def _tile_params(levels_cap: int) -> dict:
    return {
        "version": TILES_VERSION,
        "raw_max": TILE_RAW_MAX,
        "buckets": TILE_BUCKETS,
        "stragglers": TILE_STRAGGLERS,
        "levels_cap": int(levels_cap),
    }


def _series_key(df: pd.DataFrame, ycol: str, params: dict) -> str:
    """Content key: signs the series' RAW data columns + pyramid
    parameters.  Raw (unsorted, unscrubbed) on purpose: the pyramid is a
    deterministic function of the raw columns, and hashing them directly
    keeps the warm path free of the sort/scrub work it exists to skip.
    pd.util.hash_pandas_object is deterministic across processes (fixed
    default hash key), so --jobs 1 and --jobs 4 agree."""
    h = hashlib.sha1()
    h.update(repr(sorted(params.items())).encode())
    for col in ("timestamp", ycol, "duration"):
        h.update(np.ascontiguousarray(
            df[col].to_numpy(dtype=float)).tobytes())
    h.update(pd.util.hash_pandas_object(df["name"], index=False)
             .to_numpy().tobytes())
    return h.hexdigest()


def _levels_for(xs: np.ndarray, cap: int, x0: "float | None" = None,
                width: "float | None" = None) -> int:
    """Smallest depth whose leaf tiles all hold <= TILE_RAW_MAX events
    (xs sorted ascending), bounded by ``cap``.  ``x0``/``width`` pin the
    pyramid domain (the live build's fixed horizon); default is the data
    extent."""
    n = len(xs)
    if x0 is None:
        x0 = float(xs[0])
    if width is None:
        width = (float(xs[-1]) - x0) or 1e-9
    level = 0
    while level < cap - 1:
        nt = 1 << level
        edges = x0 + width * np.arange(1, nt) / nt
        splits = np.searchsorted(xs, edges, side="left")
        counts = np.diff(np.concatenate([[0], splits, [n]]))
        if counts.max() <= TILE_RAW_MAX:
            break
        level += 1
    return level + 1


def _write_tile(path: str, doc: dict) -> int:
    """Gzip a tile deterministically (mtime=0 so --jobs 1 / --jobs 4 and
    repeated builds are byte-identical); returns compressed size.
    Level 1: the pyramid is rebuilt on every data change but each tile
    is fetched rarely, so build speed wins over the last few percent of
    ratio (the <15%-of-wall budget) — the integer encoding already did
    the compression's work."""
    blob = gzip.compress(
        json.dumps(doc, separators=(",", ":")).encode(), 1, mtime=0)
    from sofa_tpu.durability import atomic_write

    with atomic_write(path, "wb") as f:
        f.write(blob)
    return len(blob)


def _first_match_per_run(values, target_per_run, run_starts, run_of):
    """First index in each contiguous run whose value equals the run's
    target (the index recovery half of a vectorized per-run argmin)."""
    eq = np.flatnonzero(values == target_per_run[run_of])
    _uniq, first = np.unique(run_of[eq], return_index=True)
    return eq[first]


def _level_envelope(xs, ys, x0: float, width: float, nt: int):
    """Per-bucket min/max-y point indices for one whole level at once.

    ``xs`` is sorted and the level's global bucket grid (``nt`` tiles x
    TILE_BUCKETS buckets, equal x-width) is monotone in x — points are
    already grouped into contiguous per-bucket runs, so the per-bucket
    extrema come from ``reduceat`` in O(n) with no sort at all (a lexsort
    here was ~30% of the whole pyramid build).  Returns (bucket id per
    occupied run, min index, max index, bucket id per point) with runs
    ordered by bucket id.
    """
    nb = nt * TILE_BUCKETS
    gb = ((xs - x0) / width * nb).astype(np.int64)
    np.clip(gb, 0, nb - 1, out=gb)
    starts = np.flatnonzero(
        np.concatenate([[True], gb[1:] != gb[:-1]]))
    run_of = np.repeat(np.arange(len(starts)),
                       np.diff(np.concatenate([starts, [len(gb)]])))
    min_val = np.minimum.reduceat(ys, starts)
    max_val = np.maximum.reduceat(ys, starts)
    min_idx = _first_match_per_run(ys, min_val, starts, run_of)
    max_idx = _first_match_per_run(ys, max_val, starts, run_of)
    return gb[starts], min_idx, max_idx, gb


# Fixed-point scales for the integer tile encoding: x at 0.1 µs, y at
# 1e-6 (the overview's rounding), d at 1 ns.  Integers encode ~3x faster
# than shortest-repr floats through the C json encoder AND the x stream
# delta-encodes into small ints that gzip tightly — this is what keeps the
# pyramid build inside its share of the analyze budget.
X_SCALE, Y_SCALE, D_SCALE = 1e-7, 1e-6, 1e-9


def _build_pyramid(sdir: str, xs, ys, ds, names: pd.Series,
                   levels: int, x0: "float | None" = None,
                   width: "float | None" = None,
                   dirty_from: "float | None" = None,
                   stats: "dict | None" = None) -> dict:
    """Write every tile of one series under ``sdir``; returns stats.

    ``x0``/``width`` pin the domain (live builds use a fixed power-of-two
    horizon so the tile grid never shifts under appends); ``dirty_from``
    is the incremental floor — occupied tiles whose window ends at or
    before it are KEPT on disk untouched instead of rewritten (the
    append-mostly contract), counted into ``stats['kept']`` vs
    ``stats['wrote']``."""
    n = len(xs)
    if x0 is None:
        x0 = float(xs[0])
    if width is None:
        width = (float(xs[-1]) - x0) or 1e-9
    # names intern ONCE per series: tiles (and report.js) ship a local
    # string table + small int codes — symbol/HLO-op names repeat heavily,
    # so this is most of the payload win over per-point strings
    codes, uniques = pd.factorize(names, use_na_sentinel=False)
    uniques = [str(u) for u in uniques]
    xi = np.round(xs / X_SCALE).astype(np.int64)
    yi = np.round(ys / Y_SCALE).astype(np.int64)
    di = np.round(ds / D_SCALE).astype(np.int64)
    n_tiles = 0
    n_bytes = 0
    kept = 0
    total_wrote = 0
    per_level: List[int] = []
    for level in range(levels):
        nt = 1 << level
        edges = x0 + width * np.arange(1, nt) / nt
        splits = np.searchsorted(xs, edges, side="left")
        bounds = np.concatenate([[0], splits, [n]])
        counts = np.diff(bounds)
        ldir = os.path.join(sdir, str(level))
        os.makedirs(ldir, exist_ok=True)
        leaf = level == levels - 1
        env = None
        if not leaf and counts.max() > TILE_RAW_MAX:
            env = _level_envelope(xs, ys, x0, width, nt)
        wrote = 0
        occupied = 0
        for i in range(nt):
            a, b = int(bounds[i]), int(bounds[i + 1])
            if a == b:
                continue  # sparse pyramid: empty windows get no file
            occupied += 1
            tx0 = x0 + width * i / nt
            tw = width / nt
            if dirty_from is not None and tx0 + tw <= dirty_from:
                # clean tile: every event in its window was already
                # committed by an earlier epoch — keep the file as is
                kept += 1
                try:
                    n_bytes += os.path.getsize(
                        os.path.join(ldir, f"{i}.json.gz"))
                except OSError:
                    pass
                continue
            exact = leaf or (b - a) <= TILE_RAW_MAX
            doc = {
                "level": level, "n": i,
                "x0": round(tx0, 9), "x1": round(tx0 + tw, 9),
                "count": b - a, "exact": bool(exact),
            }
            if exact:
                keep = np.arange(a, b)
            else:
                run_b, run_min, run_max, gb = env
                lo, hi = i * TILE_BUCKETS, (i + 1) * TILE_BUCKETS
                r0, r1 = np.searchsorted(run_b, [lo, hi])
                seg_d = ds[a:b]
                k = min(TILE_STRAGGLERS, b - a)
                top = a + np.argpartition(seg_d, len(seg_d) - k)[-k:]
                keep = np.unique(np.concatenate(
                    [run_min[r0:r1], run_max[r0:r1], top]))
                doc["buckets"] = TILE_BUCKETS
                doc["density"] = np.bincount(
                    gb[a:b] - lo, minlength=TILE_BUCKETS).tolist()
            # envelope over ALL raw points in the window, not just kept
            doc["ymin"] = float(ys[a:b].min())
            doc["ymax"] = float(ys[a:b].max())
            xk = xi[keep]
            doc["sx"], doc["sy"], doc["sd"] = X_SCALE, Y_SCALE, D_SCALE
            doc["xd"] = np.diff(xk, prepend=0).tolist()  # delta-encoded
            doc["yv"] = yi[keep].tolist()
            doc["dv"] = di[keep].tolist()
            local, inv = np.unique(codes[keep], return_inverse=True)
            doc["names"] = [uniques[int(j)] for j in local]
            doc["ni"] = inv.tolist()
            n_bytes += _write_tile(
                os.path.join(ldir, f"{i}.json.gz"), doc)
            wrote += 1
        per_level.append(occupied)
        n_tiles += occupied
        total_wrote += wrote
    if stats is not None:
        stats["wrote"] = total_wrote
        stats["kept"] = kept
    return {"levels": levels, "x0": round(x0, 9),
            "x1": round(x0 + width, 9),
            "count": int(n), "tiles": per_level,
            "tile_count": n_tiles, "bytes": n_bytes}


def tile_points(doc: dict) -> dict:
    """Decode one tile back to value space: {"x", "y", "d" (np arrays),
    "name" (list)} — the Python mirror of the board's pointsFromTile."""
    xk = np.cumsum(np.asarray(doc["xd"], dtype=np.int64))
    table = doc.get("names") or []
    return {
        "x": xk * doc["sx"],
        "y": np.asarray(doc["yv"], dtype=np.int64) * doc["sy"],
        "d": np.asarray(doc["dv"], dtype=np.int64) * doc["sd"],
        "name": [table[i] for i in doc.get("ni") or []],
    }


def _series_arrays(s) -> tuple:
    """(xs, ys, ds, names) sorted by timestamp, NaN-scrubbed — the exact
    value space the board renders (tiles and overview must agree)."""
    df = s.data
    ycol = s.y_axis if s.y_axis in df.columns else "event"
    xs = _scrub(df["timestamp"].to_numpy(), 7)
    ys = _scrub(df[ycol].to_numpy(), 6)
    ds = _scrub(df["duration"].to_numpy(), 9)
    order = np.argsort(xs, kind="stable")
    names = df["name"].astype(str)
    return (xs[order], ys[order], ds[order],
            names.iloc[order].reset_index(drop=True))


def build_tiles(cfg, series, jobs: "int | None" = None,
                tel=None, prune: bool = True) -> Dict[str, object]:
    """Build (or reuse) the tile pyramid for every series that needs one.

    Returns the tiles manifest embedded in report.js meta: the board reads
    it to know which series have pyramids, their domain, and depth.
    Content-keyed: a series whose data and parameters are unchanged since
    the last build is skipped wholesale (warm re-runs are ~free).
    ``prune=False`` when ``series`` is a partial view (narrow exporter
    frames) — pruning then would delete healthy sibling pyramids.
    """
    from sofa_tpu import pool
    from sofa_tpu.printing import print_progress, print_warning

    jobs = jobs if jobs else pool.cfg_jobs(cfg)
    levels_flag = int(getattr(cfg, "tile_levels", 0) or 0)
    cap = levels_flag if levels_flag > 0 else MAX_LEVELS
    params = _tile_params(cap)
    root = cfg.path(TILES_DIR_NAME)
    # the overview is already exact for small series — no pyramid needed
    overview_max = int(getattr(cfg, "viz_downsample_to", 10000))
    work = [s for s in series if len(s.data) > overview_max]

    def build_one(s) -> "tuple | None":
        try:
            ycol = s.y_axis if s.y_axis in s.data.columns else "event"
            key = _series_key(s.data, ycol, params)
            dname = series_dir_name(s.name)
            sdir = os.path.join(root, dname)
            index_path = os.path.join(sdir, TILE_INDEX_NAME)
            try:
                with open(index_path) as f:
                    index = json.load(f)
            except (OSError, ValueError):
                index = None
            if isinstance(index, dict) and index.get("key") == key:
                entry = dict(index.get("entry") or {})
                entry["path"] = dname
                return s.name, entry, True
            # rebuild from scratch: stale levels must not shadow new ones
            if os.path.isdir(sdir):
                shutil.rmtree(sdir, ignore_errors=True)
            os.makedirs(sdir, exist_ok=True)
            xs, ys, ds, names = _series_arrays(s)
            levels = _levels_for(xs, cap)
            entry = _build_pyramid(sdir, xs, ys, ds, names, levels)
            # the index is written LAST (and fsync'd — it is the pyramid's
            # commit point) so a half-built pyramid never passes the key
            # check on the next run
            from sofa_tpu.durability import atomic_write

            with atomic_write(index_path, fsync=True) as f:
                json.dump({"key": key, "params": params, "entry": entry}, f)
            entry = dict(entry)
            entry["path"] = dname
            return s.name, entry, False
        except Exception as e:  # noqa: BLE001 — per-series degradation
            print_warning(f"tiles: cannot build pyramid for {s.name}: {e}")
            return None

    built = [r for r in pool.thread_map(build_one, work, jobs)
             if r is not None]
    manifest: Dict[str, object] = {
        "dir": TILES_DIR_NAME,
        "version": TILES_VERSION,
        "raw_max": TILE_RAW_MAX,
        "series": {name: entry for name, entry, _cached in built},
    }
    # prune pyramids of series that no longer exist (renamed filters, ...)
    if prune:
        keep_dirs = {series_dir_name(name) for name, _e, _c in built}
        if os.path.isdir(root):
            for entry in os.listdir(root):
                if entry not in keep_dirs and \
                        os.path.isdir(os.path.join(root, entry)):
                    shutil.rmtree(os.path.join(root, entry),
                                  ignore_errors=True)
    n_cached = sum(1 for _n, _e, cached in built if cached)
    total_tiles = sum(e.get("tile_count", 0) for _n, e, _c in built)
    total_bytes = sum(e.get("bytes", 0) for _n, e, _c in built)
    if tel is not None:
        tel.set_meta(tiles={
            "series": len(built), "cached": n_cached,
            "tile_count": int(total_tiles), "bytes": int(total_bytes),
            "levels_cap": cap,
        })
    if built:
        print_progress(
            f"tiles: {len(built)} series pyramids ({total_tiles} tiles, "
            f"{total_bytes / 2**20:.1f} MiB, {n_cached} cached) -> {root}")
    return manifest


def ensure_tiles(cfg, frames=None, series=None, tel=None,
                 prune: bool = True) -> "dict | None":
    """Build/refresh the pyramid for a logdir that already has a report.js
    (standalone ``sofa analyze`` / ``sofa export`` over an older
    preprocess) and patch the manifest into report.js meta.  Warm no-op
    when the content keys all match.  Returns the manifest, or None when
    tiles are disabled or there is nothing to do."""
    from sofa_tpu.printing import print_warning

    if not getattr(cfg, "enable_tiles", True):
        return None
    report = cfg.path("report.js")
    if not os.path.isfile(report):
        return None  # no board data contract to deepen
    if series is None:
        if frames is None:
            return None
        from sofa_tpu.frames import materialize
        from sofa_tpu.preprocess import VIZ_COLUMNS, build_series

        # Chunk-built tiles: lazy columnar frames materialize only the
        # viz column slice — the pyramid is a function of (x, y, d,
        # name) + the series filters, so the full-width frame never
        # exists in RAM on this path (docs/FRAMES.md).  Eager frames
        # pass through untouched.
        frames = {name: materialize(v, list(VIZ_COLUMNS))
                  for name, v in frames.items()}
        series = build_series(cfg, frames)
    manifest = build_tiles(cfg, series, tel=tel, prune=prune)
    try:
        patch_report_meta(report, manifest, merge=not prune)
    except (OSError, ValueError) as e:
        print_warning(f"tiles: cannot patch report.js manifest: {e}")
    return manifest


def patch_report_meta(report_path: str, manifest: dict,
                      merge: bool = False) -> None:
    """Rewrite report.js meta.tiles in place (atomic via the shared
    report.js writer) without touching the series payload.  ``merge=True``
    folds the new per-series entries into an existing manifest instead of
    replacing it (partial rebuilds must not drop sibling pyramids)."""
    from sofa_tpu.trace import write_report_js_doc

    with open(report_path) as f:
        text = f.read()
    doc = json.loads(text[len("sofa_traces = "):].rstrip(";\n"))
    meta = doc.setdefault("meta", {})
    if merge and isinstance(meta.get("tiles"), dict):
        prev = dict(meta["tiles"])
        prev_series = dict(prev.get("series") or {})
        prev_series.update(manifest.get("series") or {})
        manifest = dict(manifest)
        manifest["series"] = prev_series
    if meta.get("tiles") == manifest:
        return  # warm path: nothing changed, don't churn mtimes/ETags
    meta["tiles"] = manifest
    write_report_js_doc(doc, report_path)


def read_tile(logdir: str, series_path: str, level: int,
              n: int) -> Optional[dict]:
    """Load one tile (tests + tooling; the board fetches over HTTP)."""
    path = os.path.join(logdir, TILES_DIR_NAME, series_path,
                        str(level), f"{n}.json.gz")
    try:
        with gzip.open(path, "rt") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Live incremental builds (`sofa live`, sofa_tpu/live.py).
#
# The batch build above is content-keyed at SERIES granularity: any data
# change rebuilds the whole pyramid.  A live epoch appends a few thousand
# events to multi-hundred-thousand-event series, so the live build pins
# the tile grid to a fixed power-of-two horizon anchored at the series'
# first event — appends land in the grid's right-hand windows, leaves are
# append-mostly, and only the tiles whose window intersects the dirty
# suffix rebuild.  The per-series live index (same tile_index.json file,
# a ``live`` section instead of the batch ``key``) records the domain,
# depth, committed row count, and a sha over the committed prefix: a
# mid-series change (a rescan source rewriting history) fails the prefix
# check and falls back to a full rebuild — never a silently wrong tile.
# A later batch build (`sofa live --drain`, or plain preprocess) sees no
# ``key`` and rebuilds from scratch, converging byte-identically to a
# never-interrupted batch run.
# ---------------------------------------------------------------------------

#: The live horizon is the smallest power-of-two multiple of this many
#: seconds that covers PAD x the observed span — appends rarely outgrow
#: it, and outgrowing it just re-anchors (one full rebuild, amortized
#: O(log n) over a run's life).
LIVE_HORIZON_BASE_S = 1.0
LIVE_HORIZON_PAD = 2.0


def _live_horizon(span: float) -> float:
    width = LIVE_HORIZON_BASE_S
    target = max(span, 1e-3) * LIVE_HORIZON_PAD
    while width < target:
        width *= 2.0
    return width


def _prefix_sha(xs, ys, ds, names: pd.Series, rows: int) -> str:
    """sha1 over the first ``rows`` sorted events — the committed-prefix
    identity the incremental path trusts before keeping old tiles."""
    h = hashlib.sha1()
    for a in (xs, ys, ds):
        h.update(np.ascontiguousarray(a[:rows]).tobytes())
    h.update(pd.util.hash_pandas_object(names.iloc[:rows], index=False)
             .to_numpy().tobytes())
    return h.hexdigest()


def build_tiles_live(cfg, series, jobs: "int | None" = None,
                     tel=None) -> "tuple[dict, dict]":
    """Incremental pyramid refresh for a live epoch.

    Returns ``(manifest, stats)`` — the same meta.tiles manifest shape as
    :func:`build_tiles` plus a stats dict proving the dirty-tile-only
    contract: ``rebuilt`` (tiles written this epoch), ``kept`` (occupied
    tiles left untouched), ``unchanged_series`` (skipped wholesale) and
    ``full_rebuilds`` (re-anchor / prefix-mismatch / depth growth)."""
    from sofa_tpu import pool
    from sofa_tpu.durability import atomic_write
    from sofa_tpu.printing import print_warning

    jobs = jobs if jobs else pool.cfg_jobs(cfg)
    levels_flag = int(getattr(cfg, "tile_levels", 0) or 0)
    cap = levels_flag if levels_flag > 0 else MAX_LEVELS
    params = _tile_params(cap)
    root = cfg.path(TILES_DIR_NAME)
    overview_max = int(getattr(cfg, "viz_downsample_to", 10000))
    work = [s for s in series if len(s.data) > overview_max]

    def build_one(s) -> "tuple | None":
        try:
            dname = series_dir_name(s.name)
            sdir = os.path.join(root, dname)
            index_path = os.path.join(sdir, TILE_INDEX_NAME)
            try:
                with open(index_path) as f:
                    index = json.load(f)
            except (OSError, ValueError):
                index = None
            live = (index or {}).get("live") \
                if isinstance(index, dict) else None
            xs, ys, ds, names = _series_arrays(s)
            n = len(xs)
            mode = "full"
            dx0, dwidth, levels = float(xs[0]), None, None
            if isinstance(live, dict) and live.get("params") == params:
                dx0 = float(live["x0"])
                dwidth = float(live["width"])
                levels = int(live["levels"])
                rows = int(live.get("rows", 0))
                if 0 < rows <= n and float(xs[0]) >= dx0 \
                        and float(xs[-1]) < dx0 + dwidth \
                        and _prefix_sha(xs, ys, ds, names, rows) \
                        == live.get("prefix_sha"):
                    if rows == n:
                        mode = "unchanged"
                    elif _levels_for(xs, cap, dx0, dwidth) <= levels:
                        mode = "append"
                        dirty_from = float(xs[rows])
                    # deeper pyramid needed: fall through to a full
                    # rebuild at the new depth (counts as re-anchor)
            if mode == "unchanged":
                entry = dict((index.get("entry") or {}))
                entry["path"] = dname
                return s.name, entry, {"kept": entry.get("tile_count", 0),
                                       "wrote": 0, "unchanged": True}
            if mode == "full":
                dx0 = float(xs[0])
                dwidth = _live_horizon(float(xs[-1]) - dx0)
                levels = _levels_for(xs, cap, dx0, dwidth)
                dirty_from = None
                if os.path.isdir(sdir):
                    shutil.rmtree(sdir, ignore_errors=True)
            os.makedirs(sdir, exist_ok=True)
            stats: dict = {}
            entry = _build_pyramid(sdir, xs, ys, ds, names, levels,
                                   x0=dx0, width=dwidth,
                                   dirty_from=dirty_from, stats=stats)
            live_doc = {
                "x0": dx0, "width": dwidth, "levels": levels,
                "rows": n,
                "prefix_sha": _prefix_sha(xs, ys, ds, names, n),
                "params": params,
            }
            # The index is the pyramid's commit point, exactly like the
            # batch build: fsync'd, written LAST.  No batch ``key`` on
            # purpose — a later batch build must rebuild from scratch.
            with atomic_write(index_path, fsync=True) as f:
                json.dump({"live": live_doc, "entry": entry}, f)
            entry = dict(entry)
            entry["path"] = dname
            stats["full"] = mode == "full"
            return s.name, entry, stats
        except Exception as e:  # noqa: BLE001 — per-series degradation
            print_warning(f"tiles: cannot live-build pyramid for "
                          f"{s.name}: {e}")
            return None

    built = [r for r in pool.thread_map(build_one, work, jobs)
             if r is not None]
    manifest: Dict[str, object] = {
        "dir": TILES_DIR_NAME,
        "version": TILES_VERSION,
        "raw_max": TILE_RAW_MAX,
        "series": {name: entry for name, entry, _st in built},
    }
    stats = {
        "series": len(built),
        "rebuilt": sum(st.get("wrote", 0) for _n, _e, st in built),
        "kept": sum(st.get("kept", 0) for _n, _e, st in built),
        "unchanged_series": sum(1 for _n, _e, st in built
                                if st.get("unchanged")),
        "full_rebuilds": sum(1 for _n, _e, st in built if st.get("full")),
    }
    if tel is not None:
        tel.set_meta(tiles={
            "series": len(built),
            "cached": stats["unchanged_series"],
            "tile_count": int(sum(e.get("tile_count", 0)
                                  for _n, e, _s in built)),
            "bytes": int(sum(e.get("bytes", 0) for _n, e, _s in built)),
            "levels_cap": cap,
        })
    return manifest, stats
