"""Scaled fleet tier tests: `sofa serve --workers N` / `--replica-of`
(sofa_tpu/archive/tier.py, docs/FLEET.md "Scaling the tier").

The contracts under test, each deterministic and network-free beyond
loopback: consistent-hash ring stability under worker add/remove, the
write-ahead ingest queue's SIGKILL-replay byte-identity (a drain killed
mid-apply and re-run converges to the store an uninterrupted drain
produces), commit acks independent of index-refresh wall time (the
PR-15 inline-refresh bottleneck, fixed behind the WAL drainer),
incremental replica pulls with a mtime-proven no-op, primary-vs-replica
query byte identity at the same commit sha, the SO_REUSEPORT->dispatcher
fallback, the `worker_die`/`replica_stale` fault grammar, and the
`/v1/tier` topology document `sofa status --fleet` renders.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request

import pytest

from sofa_tpu import durability, faults, telemetry
from sofa_tpu.agent import sofa_agent
from sofa_tpu.archive import catalog as acat
from sofa_tpu.archive import index as aindex
from sofa_tpu.archive import tier
from sofa_tpu.archive.service import (
    TENANTS_DIR_NAME,
    _serve_pool,
    _serve_replica,
    service_url,
    sofa_serve,
)
from sofa_tpu.archive.store import archive_fsck
from sofa_tpu.config import SofaConfig

TOKEN = "tier-test-token"


def _mklog(root, name="run1", files=None):
    """A minimal finished logdir: manifest + digest ledger + payload."""
    logdir = os.path.join(str(root), name) + "/"
    os.makedirs(logdir, exist_ok=True)
    payload = files or {"sofa_time.txt": "123.0\n",
                        "features.csv": "name,value\nelapsed_time,1.5\n"}
    for fname, content in payload.items():
        with open(logdir + fname, "w") as f:
            f.write(content)
    tel = telemetry.begin("analyze")
    tel.write(logdir, rc=0)
    telemetry.end(tel)
    durability.write_digests(logdir)
    return logdir


def _agent_cfg(tmp_path, url, **kw):
    kw.setdefault("serve_token", TOKEN)
    kw.setdefault("agent_service", url)
    kw.setdefault("agent_spool", str(tmp_path / "spool"))
    kw.setdefault("agent_settle_s", 0.0)
    kw.setdefault("agent_retries", 4)
    kw.setdefault("agent_backoff_s", 0.01)
    kw.setdefault("agent_backoff_cap_s", 0.05)
    return SofaConfig(logdir=str(tmp_path / "unused"), **kw)


def _wait_for(pred, timeout_s=15.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
    pytest.fail(f"timed out after {timeout_s}s waiting for {what}")


def _fsck_clean(root):
    report = archive_fsck(root)
    assert report is not None, f"no archive at {root}"
    bad = {k: v for k, v in report.items()
           if isinstance(v, list) and v and k != "unreferenced"}
    assert not bad, f"store damage: {bad}"


def _tree_bytes(root, skip=("_journal.jsonl",)):
    """path -> content for every file under root, journal excluded (the
    killed drain legitimately carries an extra uncommitted begin)."""
    out = {}
    for dirpath, _dirs, names in os.walk(root):
        for n in sorted(names):
            if n in skip:
                continue
            p = os.path.join(dirpath, n)
            with open(p, "rb") as f:
                out[os.path.relpath(p, root)] = f.read()
    return out


@pytest.fixture
def primary(tmp_path, monkeypatch):
    """An in-process single-worker PRIMARY (WAL drainer + refresher) on
    an ephemeral loopback port, with a fast refresh cadence."""
    monkeypatch.setattr(tier, "REFRESH_MIN_INTERVAL_S", 0.05)
    cfg = SofaConfig(logdir=str(tmp_path / "unused_srv"),
                     serve_token=TOKEN, serve_port=0)
    httpd = sofa_serve(cfg, root=str(tmp_path / "store"),
                       serve_forever=False)
    assert httpd is not None
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def _get(url, headers=None):
    req = urllib.request.Request(
        url, headers={"Authorization": f"Bearer {TOKEN}",
                      **(headers or {})})
    with urllib.request.urlopen(req, timeout=10.0) as r:
        return r.status, dict(r.headers), r.read()


# ---------------------------------------------------------------------------
# The consistent-hash ring.
# ---------------------------------------------------------------------------

def test_ring_stable_under_worker_add():
    tenants = [f"team-{i:03d}" for i in range(200)]
    before = {t: tier.ring_owner(t, 4) for t in tenants}
    after = {t: tier.ring_owner(t, 5) for t in tenants}
    moved = [t for t in tenants if before[t] != after[t]]
    # only arcs the new worker's vnodes cover move — and they move TO it
    assert moved, "a new worker must steal some tenants"
    assert all(after[t] == 4 for t in moved)
    # ~1/5 expected; anything near a full reshuffle is a broken ring
    assert len(moved) < len(tenants) // 2


def test_ring_stable_under_worker_remove():
    tenants = [f"team-{i:03d}" for i in range(200)]
    before = {t: tier.ring_owner(t, 4) for t in tenants}
    after = {t: tier.ring_owner(t, (0, 1, 3)) for t in tenants}
    for t in tenants:
        if before[t] == 2:
            assert after[t] in (0, 1, 3)
        else:  # everyone else keeps their owner
            assert after[t] == before[t]


def test_ring_owner_deterministic_across_calls():
    assert tier.ring_owner("default", 4) == tier.ring_owner("default", 4)
    assert tier.ring_owner("default", (0, 1, 2, 3)) == \
        tier.ring_owner("default", 4)


# ---------------------------------------------------------------------------
# The write-ahead ingest queue.
# ---------------------------------------------------------------------------

def _wal_records(n, t0=1700000000.0):
    return [{"run": f"{i:02d}" + "ab" * 31, "t": round(t0 + i, 3),
             "logdir": f"/jobs/{i}/", "hostname": "host-a", "label": "",
             "tenant": "default", "files": {},
             "features": {"elapsed_time": 1.0 + i}}
            for i in range(n)]


def test_wal_depth_and_pending_runs(tmp_path):
    troot = str(tmp_path / "default")
    app = tier.WalAppender(troot, worker=0)
    recs = _wal_records(3)
    for rec in recs:
        app.append(rec)
    assert tier.wal_depth(troot) == 3
    assert tier.wal_pending_runs(troot) == {r["run"] for r in recs}
    stats = tier.drain_tenant(troot, refresh=False)
    assert stats["applied"] == 3
    assert tier.wal_depth(troot) == 0
    runs = acat.ingest_entries(acat.read_catalog(troot))
    assert [e["run"] for e in runs] == [r["run"] for r in recs]
    # caught-up drain is a no-op
    again = tier.drain_tenant(troot, refresh=False)
    assert again == {"applied": 0, "replayed": 0, "refreshed": False}


def test_sigkill_mid_drain_replays_byte_identical(tmp_path):
    """A drain hard-killed between the run-doc write and the catalog
    append (the widest replay window, SOFA_WAL_EXIT_AFTER) and then
    re-run converges to the byte-identical store an uninterrupted drain
    of the same WAL produces — and both fsck clean."""
    from sofa_tpu.archive.store import ArchiveStore

    root_a = str(tmp_path / "a" / "default")
    # the archive marker carries a creation timestamp — stamp it BEFORE
    # the copy so both roots share one (replay identity is about the
    # WAL-derived bytes, not the store's birth certificate)
    ArchiveStore(root_a, create=True)
    app = tier.WalAppender(root_a, worker=0)
    for rec in _wal_records(3):
        app.append(rec)
    root_b = str(tmp_path / "b" / "default")
    shutil.copytree(root_a, root_b)

    code = ("import sys\nfrom sofa_tpu.archive import tier\n"
            "tier.drain_tenant(sys.argv[1], refresh=False)\n")
    env = {**os.environ, "SOFA_WAL_EXIT_AFTER": "1",
           "JAX_PLATFORMS": "cpu"}
    env.pop("SOFA_FAULTS", None)
    proc = subprocess.run([sys.executable, "-c", code, root_a], env=env,
                          capture_output=True, timeout=120)
    assert proc.returncode == 88, proc.stderr.decode()
    # the kill landed the first run doc but not its catalog line
    assert tier.wal_depth(root_a) == 3
    assert not os.path.isfile(os.path.join(root_a, "catalog.jsonl")) or \
        not acat.ingest_entries(acat.read_catalog(root_a))

    stats_a = tier.drain_tenant(root_a, refresh=False)   # the replay
    stats_b = tier.drain_tenant(root_b, refresh=False)   # uninterrupted
    assert stats_a["applied"] + stats_a["replayed"] == 3
    assert stats_b == {"applied": 3, "replayed": 0, "refreshed": False}
    assert _tree_bytes(root_a) == _tree_bytes(root_b)
    _fsck_clean(root_a)
    _fsck_clean(root_b)
    if aindex.available():
        # refresh lands the same index commit sha on both
        assert tier.refresh_tenant(root_a)
        assert tier.refresh_tenant(root_b)
        sha_a = (aindex.load_commit(root_a) or {}).get("commit_sha")
        sha_b = (aindex.load_commit(root_b) or {}).get("commit_sha")
        assert sha_a and sha_a == sha_b


def test_push_ack_not_gated_on_index_refresh(primary, tmp_path,
                                             monkeypatch):
    """The PR-15 regression: commit acks must NOT queue behind
    ``refresh_after_ingest`` wall time (which grows with index size).
    With the server's refresh pinned at 1 s, the push must still ack
    fast — and the refresh must still happen, asynchronously."""
    from sofa_tpu.archive.client import ServiceClient, push_run
    from sofa_tpu.archive.store import ArchiveStore, ingest_run

    # spool the run BEFORE patching: the local spool ingest refreshes
    # its own index too, and its wall time is not what's under test
    logdir = _mklog(tmp_path / "watch")
    spool_root = str(tmp_path / "spoolstore")
    summary = ingest_run(SofaConfig(logdir=logdir), spool_root)

    refreshed = threading.Event()
    real = aindex.refresh_after_ingest

    def slow_refresh(root, *a, **kw):
        time.sleep(1.0)
        out = real(root, *a, **kw)
        refreshed.set()
        return out

    monkeypatch.setattr(aindex, "refresh_after_ingest", slow_refresh)
    client = ServiceClient(service_url(primary), TOKEN, timeout_s=10,
                           retries=2, backoff_s=0.01)
    t0 = time.monotonic()
    res = push_run(ArchiveStore(spool_root), summary["run"], client)
    elapsed = time.monotonic() - t0
    assert res["status"] in ("pushed", "committed")
    assert elapsed < 0.9, (
        f"push ack took {elapsed:.2f}s — it waited on the 1s index "
        "refresh, the inline-refresh bottleneck is back")
    troot = primary.tenant_root("default")
    assert len(acat.ingest_entries(acat.read_catalog(troot))) == 1
    if aindex.available():
        _wait_for(refreshed.is_set, what="async index refresh")


# ---------------------------------------------------------------------------
# Read replicas.
# ---------------------------------------------------------------------------

def _primary_commit_sha(primary, tenant="default"):
    troot = primary.tenant_root(tenant)
    return (aindex.load_commit(troot) or {}).get("commit_sha") or ""


@pytest.mark.skipif(not aindex.available(),
                    reason="columnar index needs pyarrow")
def test_replica_pull_incremental_and_noop(primary, tmp_path):
    watch = tmp_path / "watch"
    _mklog(watch, "run1")
    cfg = _agent_cfg(tmp_path, service_url(primary))
    assert sofa_agent(cfg, watch=str(watch), once=True) == 0
    sha1 = _wait_for(lambda: _primary_commit_sha(primary),
                     what="primary index commit")

    replica_root = str(tmp_path / "replica")
    puller = tier.ReplicaPuller(replica_root, service_url(primary), TOKEN)
    res = puller.pull_once()
    assert not res["errors"]
    assert res["fetched_chunks"] > 0
    rtroot = os.path.join(replica_root, TENANTS_DIR_NAME, "default")
    assert (aindex.load_commit(rtroot) or {}).get("commit_sha") == sha1

    # the no-op pull, proven by mtimes: same commit sha upstream means
    # NOTHING under the replica's _index/ is rewritten
    def _mtimes():
        out = {}
        for dirpath, _dirs, names in os.walk(rtroot):
            for n in names:
                p = os.path.join(dirpath, n)
                out[p] = os.stat(p).st_mtime_ns
        return out

    before = _mtimes()
    res2 = puller.pull_tenant("default")
    assert res2["unchanged"] and res2["fetched_chunks"] == 0
    assert _mtimes() == before

    # a second run moves the commit; the pull transfers only new chunks
    _mklog(watch, "run2")
    assert sofa_agent(cfg, watch=str(watch), once=True) == 0
    sha2 = _wait_for(
        lambda: (_primary_commit_sha(primary) != sha1
                 and _primary_commit_sha(primary)),
        what="primary index commit to advance")
    res3 = puller.pull_tenant("default")
    assert not res3.get("error") and res3["fetched_chunks"] >= 1
    assert (aindex.load_commit(rtroot) or {}).get("commit_sha") == sha2


@pytest.mark.skipif(not aindex.available(),
                    reason="columnar index needs pyarrow")
def test_replica_query_byte_identical_and_stale_header(primary, tmp_path):
    watch = tmp_path / "watch"
    _mklog(watch, "run1")
    cfg = _agent_cfg(tmp_path, service_url(primary))
    assert sofa_agent(cfg, watch=str(watch), once=True) == 0
    sha1 = _wait_for(lambda: _primary_commit_sha(primary),
                     what="primary index commit")

    replica_root = str(tmp_path / "replica")
    os.environ["SOFA_REPLICA_POLL_S"] = "3600"  # tests drive pull_once
    try:
        httpd_r = _serve_replica(replica_root, TOKEN,
                                 service_url(primary), "127.0.0.1", 0, 8,
                                 serve_forever=False)
        assert httpd_r is not None
        t = threading.Thread(target=httpd_r.serve_forever, daemon=True)
        t.start()
        try:
            url_p = service_url(primary)
            url_r = service_url(httpd_r)
            q = "/v1/default/query?kind=runs"
            status_p, hdr_p, body_p = _get(url_p + q)
            status_r, hdr_r, body_r = _get(url_r + q)
            assert status_p == status_r == 200
            # same commit sha -> byte-identical answer, ETag == the sha
            assert body_p == body_r
            assert hdr_p["ETag"] == hdr_r["ETag"] == f'"idx-{sha1}"'
            assert hdr_r["X-Sofa-Replica"] == "1"
            assert hdr_r["X-Sofa-Replica-Commit"] == sha1
            assert "X-Sofa-Replica-Stale" not in hdr_r

            # advance the primary, THEN pin the replica (replica_stale;
            # installed after the push — `sofa agent` re-installs the
            # plan from ITS config, clearing a pre-set one): the replica
            # answers from its old commit and SAYS SO
            _mklog(watch, "run2")
            assert sofa_agent(cfg, watch=str(watch), once=True) == 0
            sha2 = _wait_for(
                lambda: (_primary_commit_sha(primary) != sha1
                         and _primary_commit_sha(primary)),
                what="primary index commit to advance")
            faults._PLAN = faults.parse("service:replica_stale")
            try:
                res = httpd_r.replica.pull_tenant("default")
                assert res["stale"] is True
                _status, hdr_s, body_s = _get(url_r + q)
                assert hdr_s["X-Sofa-Replica-Commit"] == sha1
                assert hdr_s["X-Sofa-Replica-Stale"] == "1"
                assert hdr_s["X-Sofa-Replica-Behind"] == sha2
                assert body_s == body_r  # still the old commit's bytes
            finally:
                faults.clear()
            # plan cleared: the next pull catches up and the flag drops
            res = httpd_r.replica.pull_tenant("default")
            assert not res.get("error") and not res["stale"]
            _status, hdr_c, _body = _get(url_r + q)
            assert hdr_c["X-Sofa-Replica-Commit"] == \
                _primary_commit_sha(primary)
            assert "X-Sofa-Replica-Stale" not in hdr_c
        finally:
            httpd_r.shutdown()
            httpd_r.server_close()
            t.join(timeout=5)
    finally:
        os.environ.pop("SOFA_REPLICA_POLL_S", None)


# ---------------------------------------------------------------------------
# The worker pool.
# ---------------------------------------------------------------------------

def test_reuseport_fallback_knob(monkeypatch):
    monkeypatch.setenv("SOFA_TIER_NO_REUSEPORT", "1")
    assert tier.reuseport_available() is False


def test_pool_dispatcher_fallback_serves(tmp_path, monkeypatch):
    """Without SO_REUSEPORT the pool fronts the workers with the
    dispatcher on ONE public port: pushes land, /v1/tier answers with
    the sharded topology."""
    monkeypatch.setenv("SOFA_TIER_NO_REUSEPORT", "1")
    handle = _serve_pool(str(tmp_path / "store"), TOKEN, "127.0.0.1", 0,
                         0.0, 8, 2, serve_forever=False)
    assert handle is not None
    try:
        assert handle.reuse is False and handle.dispatcher is not None
        watch = tmp_path / "watch"
        _mklog(watch)
        cfg = _agent_cfg(tmp_path, handle.url, agent_retries=8)
        assert sofa_agent(cfg, watch=str(watch), once=True) == 0
        _status, _hdr, body = _get(handle.url + "/v1/tier")
        doc = json.loads(body)
        assert doc["schema"] == tier.TIER_SCHEMA
        assert doc["version"] == tier.TIER_VERSION
        assert doc["workers"] == 2 and doc["reuseport"] is False
        rows = {r["tenant"]: r for r in doc["tenants"]}
        assert rows["default"]["worker"] == tier.ring_owner("default", 2)
        # the ack was read-your-writes: the WAL is already applied
        assert rows["default"]["wal_depth"] == 0
    finally:
        handle.stop()


# ---------------------------------------------------------------------------
# Fault grammar + topology rendering.
# ---------------------------------------------------------------------------

def test_tier_fault_kinds_parse_and_consume():
    plan = faults.parse("service:worker_die@2,service:replica_stale")
    assert plan.tier_replica_stale() is True
    # a respawned worker (generation > 0) must never re-fire
    assert plan.tier_worker_die(2, generation=1) is False
    assert plan.tier_worker_die(1, generation=0) is False
    assert plan.tier_worker_die(2, generation=0) is True
    assert plan.tier_worker_die(2, generation=0) is False  # consumed
    # tier kinds are the SERVER side's to absorb — the transport client
    # must skip them entirely
    assert plan.service_fault("service", "put", "k") is None
    with pytest.raises(ValueError):
        faults.parse("service:worker_die@zero")
    with pytest.raises(ValueError):
        faults.parse("service:replica_stale@start")


def test_tier_disk_full_and_conn_reset_grammar():
    """Satellite: the two new fault kinds parse, consume, and stay on
    their own side of the client/server split (docs/ROBUSTNESS.md)."""
    plan = faults.parse("service:disk_full@2")
    # disk_full is the SERVER side's (TIER_KINDS) — the transport
    # client must skip it entirely
    assert plan.service_fault("service", "put", "k") is None
    assert plan.tier_disk_full() is False      # 1st consulted write
    assert plan.tier_disk_full() is True       # 2nd: ENOSPC fires once
    assert plan.tier_disk_full() is False      # consumed — retry lands
    # the ordinal defaults to the first write
    plan = faults.parse("service:disk_full")
    assert plan.tier_disk_full() is True
    with pytest.raises(ValueError):
        faults.parse("service:disk_full@zero")
    with pytest.raises(ValueError):
        faults.parse("service:disk_full@0.5")
    # conn_reset is a client-side NET kind: once per request key
    plan = faults.parse("service:conn_reset")
    assert plan.tier_disk_full() is False
    spec = plan.service_fault("service", "put", "a")
    assert spec is not None and spec.kind == "conn_reset"
    assert plan.service_fault("service", "put", "a") is None
    assert plan.service_fault("service", "put", "b") is not None


# ---------------------------------------------------------------------------
# Admission control: the X-Sofa-Deadline contract.
# ---------------------------------------------------------------------------

def _post(url, doc, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Authorization": f"Bearer {TOKEN}", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10.0) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


_HAVE_DOC = {"files": {"a.txt": {"sha256": "ab" * 32}}}


def test_deadline_expired_on_arrival_is_504(primary):
    """A request whose X-Sofa-Deadline already passed is refused with a
    typed 504 and NO Retry-After — the client gave up; doing the work
    would answer nobody (docs/FLEET.md)."""
    url = service_url(primary) + "/v1/default/have"
    code, headers, doc = _post(url, _HAVE_DOC, headers={
        "X-Sofa-Deadline": f"{time.time() - 5.0:.3f}"})
    assert code == 504
    assert doc["error"] == "deadline_expired"
    assert "Retry-After" not in headers
    assert primary.stats.get("504_deadline_expired", 0) >= 1


def test_deadline_missing_header_serves_normally(primary):
    code, _headers, doc = _post(service_url(primary) +
                                "/v1/default/have", _HAVE_DOC)
    assert code == 200 and doc["missing"] == ["ab" * 32]


@pytest.mark.parametrize("raw", [
    # a clock-skewed agent 30 days in the future must not buy itself an
    # infinite deadline: beyond the skew cap the header is IGNORED (the
    # request serves), never obeyed
    lambda: f"{time.time() + 30 * 86400:.3f}",
    lambda: "not-a-deadline",                  # unparsable: ignored
])
def test_deadline_skew_and_garbage_are_ignored(primary, raw):
    code, _headers, doc = _post(
        service_url(primary) + "/v1/default/have", _HAVE_DOC,
        headers={"X-Sofa-Deadline": raw()})
    assert code == 200 and doc["missing"] == ["ab" * 32]


def test_deadline_within_cap_is_honored_not_refused(primary):
    """A sane near-future deadline serves: only EXPIRED refuses."""
    code, _headers, _doc = _post(
        service_url(primary) + "/v1/default/have", _HAVE_DOC,
        headers={"X-Sofa-Deadline": f"{time.time() + 30.0:.3f}"})
    assert code == 200


# ---------------------------------------------------------------------------
# Graceful lifecycle: SIGTERM drains the WAL and exits 0.
# ---------------------------------------------------------------------------

def test_sigterm_worker_drains_wal_and_exits_zero(tmp_path):
    """The graceful-lifecycle contract (docs/FLEET.md): a SIGTERM'd
    pool worker stops accepting, drains every owned tenant's WAL to
    EMPTY, and exits 0 — the acked pushes seeded into the WAL are
    committed state on disk after the exit, never lost."""
    import multiprocessing

    root = str(tmp_path / "store")
    troot = os.path.join(root, TENANTS_DIR_NAME, "default")
    app = tier.WalAppender(troot, worker=0)
    recs = _wal_records(3)
    for rec in recs:
        app.append(rec)
    assert tier.wal_depth(troot) == 3

    ctx = multiprocessing.get_context("fork")
    ready = ctx.Queue()
    spec = {"root": root, "token": TOKEN, "bind": "127.0.0.1",
            "port": 0, "reuse": False, "quota_mb": 0.0,
            "max_inflight": 8, "workers": 1, "slo": ""}
    proc = ctx.Process(target=tier._worker_main,
                       args=(spec, 0, 0, ready), daemon=True)
    proc.start()
    msg = ready.get(timeout=30)
    assert "error" not in msg, msg
    # the worker is serving — health answers before the TERM
    _wait_for(lambda: _get(
        f"http://127.0.0.1:{msg['port']}/v1/health")[0] == 200,
        what="worker health")
    os.kill(proc.pid, signal.SIGTERM)
    proc.join(timeout=30)
    assert proc.exitcode == 0, f"worker exited {proc.exitcode}"
    assert tier.wal_depth(troot) == 0
    runs = acat.ingest_entries(acat.read_catalog(troot))
    assert [e["run"] for e in runs] == [r["run"] for r in recs]
    _fsck_clean(troot)


def test_fleet_status_renders_tier(primary, tmp_path, capsys):
    watch = tmp_path / "watch"
    _mklog(watch)
    cfg = _agent_cfg(tmp_path, service_url(primary))
    assert sofa_agent(cfg, watch=str(watch), once=True) == 0
    status_cfg = types.SimpleNamespace(status_fleet=service_url(primary),
                                       serve_token=TOKEN)
    assert tier.sofa_fleet_status(status_cfg) == 0
    out = capsys.readouterr().out
    assert "fleet tier at" in out and "role primary" in out
    assert "default" in out
    # a dead endpoint is a routed error, not a traceback
    bad = types.SimpleNamespace(status_fleet="http://127.0.0.1:1",
                                serve_token=TOKEN)
    assert tier.sofa_fleet_status(bad) == 1
