"""A 20-step tiny-transformer training loop with per-step annotations.

The iteration-detection target: each step is wrapped in the step_annotation
marker the AISI pass anchors on (sofa_tpu/ml/aisi.py), so
``sofa stat "python examples/train_tiny.py" --enable_aisi`` yields an
iterations.csv with step times and fw/bw splits.
"""

import jax

from sofa_tpu.workloads.common import step_annotation
from sofa_tpu.workloads.transformer import TransformerConfig, build


def main(steps: int = 20):
    cfg = TransformerConfig.tiny(seq=128)
    params, opt_state, step, tokens = build(cfg, mesh=None, batch=8, seq=128)
    params, opt_state, loss = step(params, opt_state, tokens)  # compile
    jax.block_until_ready(loss)
    for i in range(steps):
        with step_annotation(i):
            params, opt_state, loss = step(params, opt_state, tokens)
    print(f"final loss {float(loss):.4f} after {steps} steps")


if __name__ == "__main__":
    main()
