#!/usr/bin/env python3
"""sofa-lint entry point — AST invariant checker for sofa_tpu's contracts.

    python tools/sofa_lint.py sofa_tpu/ [--json] [--update-baseline]

Exit codes: 0 clean, 1 new findings, 2 internal error.  Equivalent to the
``sofa lint`` verb; see docs/STATIC_ANALYSIS.md for the rule catalog and
the lint_baseline.json workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sofa_tpu.lint.cli import run_lint  # noqa: E402

if __name__ == "__main__":
    sys.exit(run_lint())
