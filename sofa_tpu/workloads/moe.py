"""Switch-style Mixture-of-Experts decoder with expert parallelism.

The all-to-all traffic generator: the reference profiler classified NCCL
collectives by kernel-name grep (/root/reference/bin/sofa_analyze.py:363-368)
and never saw expert-parallel dispatch at all; this workload generates the
real thing — two `lax.all_to_all` exchanges per MoE layer over the "expert"
mesh axis (CopyKind.ALL_TO_ALL in the trace taxonomy, sofa_tpu/trace.py) —
so the comm profile, ICI matrix, and per-iteration attribution all have a
first-class EP workload to observe.

TPU-first shape discipline: top-1 (Switch) routing with a *static* capacity
per expert — dispatch/combine are dense one-hot einsums, so XLA sees fixed
shapes and keeps everything on the MXU; tokens over capacity are dropped
(standard Switch behavior, the aux loss pushes the router toward balance).
Experts shard one-or-more-per-chip over the ``expert`` axis; tokens ride
(data × expert) as a flat data dimension outside the MoE block.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sofa_tpu.workloads.compat import shard_map
from sofa_tpu.workloads.ring_attention import plain_causal_attention
from sofa_tpu.workloads.transformer import _rmsnorm


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab: int = 8192
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    n_experts: int = 8
    capacity_factor: float = 1.25
    max_seq: int = 512
    dtype: Any = jnp.bfloat16
    router_aux_weight: float = 0.01

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def tiny(n_experts: int = 4) -> "MoEConfig":
        return MoEConfig(vocab=256, d_model=32, n_layers=2, n_heads=2,
                         d_ff=64, n_experts=n_experts, max_seq=64)


def init_params(cfg: MoEConfig, key) -> Dict[str, Any]:
    k = iter(jax.random.split(key, 12))
    d, f, e, l = cfg.d_model, cfg.d_ff, cfg.n_experts, cfg.n_layers

    def norm(key, *shape):
        fan_in = shape[-2] if len(shape) > 1 else shape[-1]
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        "embed": norm(next(k), cfg.vocab, d),
        "layers": {
            "attn_norm": jnp.ones((l, d), jnp.float32),
            "wqkv": norm(next(k), l, d, 3 * d),
            "wo": norm(next(k), l, d, d),
            "moe_norm": jnp.ones((l, d), jnp.float32),
            # Router stays float32: tiny, and logit noise moves tokens.
            "router": jax.random.normal(next(k), (l, d, e),
                                        jnp.float32) * (d ** -0.5),
            "w_up": norm(next(k), l, e, d, f),
            "w_down": norm(next(k), l, e, f, d),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": norm(next(k), d, cfg.vocab),
    }


def param_specs(cfg: MoEConfig) -> Dict[str, Any]:
    """Experts shard over "expert"; everything else is replicated."""
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(None, None),
            "wqkv": P(None, None, None),
            "wo": P(None, None, None),
            "moe_norm": P(None, None),
            "router": P(None, None, None),
            "w_up": P(None, "expert", None, None),
            "w_down": P(None, "expert", None, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, None),
    }


def _dispatch_tensors(logits, n_experts: int, capacity: int):
    """Top-1 routing -> (dispatch [N,E,C] one-hot, combine [N,E,C],
    gate [N] f32, aux).

    Position of each token inside its expert's buffer is its rank among
    same-expert tokens (cumsum); ranks >= capacity are dropped.
    combine == dispatch * gate[n] — callers wanting MXU-friendly precision
    use the factorized form: the {0,1} dispatch is exact in bf16, so the
    return gather runs in storage dtype and the gate (a softmax
    probability, NOT exactly representable in bf16) applies afterwards as
    an f32 per-token scale.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [N, E]
    expert = jnp.argmax(probs, axis=-1)                           # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [N, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot              # [N, E]
    keep = (pos < capacity).astype(jnp.float32) * onehot
    pos_oh = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                            dtype=jnp.float32)                     # [N, C]
    dispatch = keep[:, :, None] * pos_oh[:, None, :]               # [N, E, C]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * sum_e(fraction_dispatched_e * mean_prob_e).
    frac = onehot.mean(axis=0)
    aux = n_experts * jnp.sum(frac * probs.mean(axis=0))
    return dispatch, combine, gate, aux


def _expert_ffn(xs, w_up, w_down, dtype, upcast: bool = False):
    """Per-expert gelu MLP over dispatched slots.

    xs: [..., E, C, D] in ``dtype`` (bf16 on TPU — the MXU path); matmuls
    accumulate in f32, activations return to ``dtype``.  With
    ``upcast=True`` (execution platform is not TPU — the caller checks the
    *mesh's* devices, not the process default backend) the dots run in
    f32: XLA:CPU's dot thunk rejects bf16 batched contractions (numerics
    are covered by the f32 equivalence tests either way).
    """
    if upcast and dtype == jnp.bfloat16:
        dtype = jnp.float32
        xs = xs.astype(dtype)
    h = jnp.einsum("...ecd,edf->...ecf", xs, w_up.astype(dtype),
                   preferred_element_type=jnp.float32)
    h = jax.nn.gelu(h).astype(dtype)
    return jnp.einsum("...ecf,efd->...ecd", h, w_down.astype(dtype),
                      preferred_element_type=jnp.float32)


def _gather_dtype(cfg: MoEConfig, upcast: bool):
    """Dtype for the dispatch/combine contractions.

    The dispatch one-hot is exactly representable in bf16, so on TPU the
    token-gather matmul runs the MXU in native bf16 mode with f32
    accumulation — these contractions are ~N^2-scale flops at pod batch
    sizes, the same 4x f32-mode penalty the flash kernel fixed.  CPU
    (upcast) keeps f32: XLA:CPU rejects bf16 batched dots.
    """
    return jnp.float32 if upcast else cfg.dtype


def moe_ffn_dense(x, router_w, w_up, w_down, cfg: MoEConfig,
                  upcast: bool = False):
    """Single-device reference: every expert runs on every token's slot.

    x: [N, D].  Ground truth for the expert-parallel path in tests; also
    the fallback when no mesh is given.
    """
    n = x.shape[0]
    capacity = _capacity(n, cfg)
    gdt = _gather_dtype(cfg, upcast)
    logits = x.astype(jnp.float32) @ router_w                      # [N, E]
    dispatch, _, gate, aux = _dispatch_tensors(logits, cfg.n_experts,
                                               capacity)
    xs = jnp.einsum("nec,nd->ecd", dispatch.astype(gdt), x.astype(gdt),
                    preferred_element_type=jnp.float32
                    ).astype(cfg.dtype)                            # [E, C, D]
    # Round-trip through cfg.dtype exactly like the expert-parallel path
    # does at its return all-to-all, so the two paths stay bit-identical.
    ys = _expert_ffn(xs, w_up, w_down, cfg.dtype,
                     upcast=upcast).astype(cfg.dtype)
    # factorized combine: exact {0,1} gather in storage dtype, then the
    # f32 gate scale — full gate precision at bf16 gather speed
    out = jnp.einsum("nec,ecd->nd", dispatch.astype(gdt), ys.astype(gdt),
                     preferred_element_type=jnp.float32) * gate[:, None]
    return out.astype(x.dtype), aux


def moe_ffn_expert_parallel(x, router_w, w_up, w_down, cfg: MoEConfig,
                            axis_name: str, upcast: bool = False):
    """Expert-parallel MoE block; runs inside shard_map over ``axis_name``.

    x: [N_local, D] — this shard's tokens.  w_up/w_down: [E_local, D, F] —
    this shard's experts.  Two all-to-alls: tokens out to their experts,
    results back.  Expert id e lives on shard e // E_local.
    """
    shards = lax.psum(1, axis_name)
    e_local = w_up.shape[0]
    n_local, d = x.shape
    capacity = _capacity(n_local, cfg)
    gdt = _gather_dtype(cfg, upcast)
    logits = x.astype(jnp.float32) @ router_w
    dispatch, _, gate, aux = _dispatch_tensors(logits, cfg.n_experts,
                                               capacity)
    # Routing math stays f32 (one-hot sums); the gather contraction runs
    # in storage dtype (see _gather_dtype) and the dispatched slots ride
    # the wire and the MXU in cfg.dtype — the ICI byte counts a profiled
    # run observes are the real bf16 deployment numbers.
    xs = jnp.einsum("nec,nd->ecd", dispatch.astype(gdt), x.astype(gdt),
                    preferred_element_type=jnp.float32).astype(cfg.dtype)
    # [E, C, D] -> [S, E_local, C, D]; all_to_all swaps the shard dim for
    # the token-source dim, landing every token on its expert's chip.
    xs = xs.reshape(shards, e_local, capacity, d)
    xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)                   # [S(src), E_local, C, D]
    ys = _expert_ffn(xs, w_up, w_down, cfg.dtype,
                     upcast=upcast).astype(cfg.dtype)
    ys = lax.all_to_all(ys, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)                   # [S, E_local, C, D]
    ys = ys.reshape(cfg.n_experts, capacity, d)
    # factorized combine (see moe_ffn_dense): exact bf16 gather, f32 gate
    out = jnp.einsum("nec,ecd->nd", dispatch.astype(gdt), ys.astype(gdt),
                     preferred_element_type=jnp.float32) * gate[:, None]
    # Per-device aux averaged across shards — the actual Switch/GShard
    # formulation (each device balances its own batch).  This is a
    # different statistic from the dense path's global-batch aux, so the
    # two paths agree on logits but not (exactly) on aux.
    aux = lax.pmean(aux, axis_name)
    return out.astype(x.dtype), aux


def _capacity(n_tokens: int, cfg: MoEConfig) -> int:
    return max(1, int(np.ceil(n_tokens / cfg.n_experts
                              * cfg.capacity_factor)))


def forward(params, tokens, cfg: MoEConfig,
            mesh: Optional[Mesh] = None):
    """Logits [B, T, vocab] + router aux loss.  With a mesh, the MoE block
    runs expert-parallel over its "expert" axis; attention and the dense
    parts treat (data, expert) as one flat batch dimension."""
    b, t = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    use_ep = mesh is not None and mesh.shape.get("expert", 1) > 1
    if use_ep and cfg.n_experts % mesh.shape["expert"]:
        raise ValueError(f"n_experts {cfg.n_experts} must divide over the "
                         f"expert axis ({mesh.shape['expert']})")
    # bf16 fallback keys on the platform the computation actually runs on:
    # the mesh's devices when given (tests build CPU meshes even on TPU
    # hosts), else the process default backend.
    if mesh is not None:
        platform = next(iter(mesh.devices.flat)).platform
    else:
        platform = jax.default_backend()
    upcast = platform != "tpu"

    def moe_block(h2, router_w, w_up, w_down):
        flat = h2.reshape(b * t, cfg.d_model)
        if use_ep:
            spec_x = P(("data", "expert"), None)
            spec_w = P("expert", None, None)

            def fn(xs, up, down):
                out, aux = moe_ffn_expert_parallel(xs, router_w, up, down,
                                                   cfg, "expert",
                                                   upcast=upcast)
                # moe_ffn_* pmeans aux over the expert axis; tokens also
                # shard over "data", so finish the mean there for a fully
                # replicated scalar.
                return out, lax.pmean(aux, "data")

            out, aux = shard_map(
                fn, mesh=mesh,
                in_specs=(spec_x, spec_w, spec_w),
                out_specs=(spec_x, P()))(flat, w_up, w_down)
        else:
            out, aux = moe_ffn_dense(flat, router_w, w_up, w_down, cfg,
                                     upcast=upcast)
        return out.reshape(b, t, cfg.d_model), aux

    def layer(carry, lp):
        x, aux_sum = carry
        h = _rmsnorm(x, lp["attn_norm"])
        qkv = (h @ lp["wqkv"]).reshape(b, t, 3, cfg.n_heads, cfg.d_head)
        o = plain_causal_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        x = x + o.reshape(b, t, -1) @ lp["wo"]
        h2 = _rmsnorm(x, lp["moe_norm"])
        y, aux = moe_block(h2, lp["router"], lp["w_up"], lp["w_down"])
        return (x + y, aux_sum + aux), None

    (x, aux_sum), _ = lax.scan(layer, (x, jnp.float32(0.0)),
                               params["layers"])
    x = _rmsnorm(x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, aux_sum / cfg.n_layers


def loss_fn(params, tokens, cfg: MoEConfig, mesh: Optional[Mesh] = None):
    logits, aux = forward(params, tokens, cfg, mesh)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold) + cfg.router_aux_weight * aux


def build(cfg: MoEConfig, mesh: Optional[Mesh], batch: int, seq: int,
          seed: int = 0):
    """Params + optimizer + jitted step + a data batch, placed on the mesh."""
    import optax

    key = jax.random.PRNGKey(seed)
    params = init_params(cfg, key)
    if mesh is not None:
        specs = param_specs(cfg)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab)
    if mesh is not None:
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P(("data", "expert"), None)))
    return params, opt_state, step, tokens


def main(argv=None):
    from sofa_tpu.workloads.common import (make_mesh, parse_workload_args,
                                           steps_per_sec)

    args = parse_workload_args(argv, {
        "batch": 8, "seq": 256, "steps": 10, "d_model": 256, "n_layers": 2,
        "n_heads": 4, "d_ff": 512, "n_experts": 8, "vocab": 8192,
        "data": 0, "expert": 0,
    })
    cfg = MoEConfig(vocab=args.vocab, d_model=args.d_model,
                    n_layers=args.n_layers, n_heads=args.n_heads,
                    d_ff=args.d_ff, n_experts=args.n_experts,
                    max_seq=args.seq)
    n = len(jax.devices())
    mesh = None
    if n > 1:
        sizes = None
        if args.data or args.expert:
            sizes = (args.data or -1, args.expert or -1)
        mesh = make_mesh(("data", "expert"), sizes)
        ep = mesh.shape["expert"]
        if cfg.n_experts % ep:
            bumped = ep * -(-cfg.n_experts // ep)
            print(f"moe: rounding n_experts {cfg.n_experts} -> {bumped} "
                  f"(multiple of expert axis {ep})")
            cfg = dataclasses.replace(cfg, n_experts=bumped)
    params, opt_state, step, tokens = build(cfg, mesh, args.batch, args.seq)

    def one(state):
        p, o, _ = state
        return step(p, o, tokens)

    sps, state = steps_per_sec(one, (params, opt_state, 0.0), args.steps)
    mesh_desc = dict(mesh.shape) if mesh else {"single": 1}
    print(f"moe: {sps:.3f} steps/s  {sps * args.batch * args.seq:,.0f} "
          f"tokens/s  loss={float(state[2]):.3f}  mesh={mesh_desc}")


if __name__ == "__main__":
    main()
