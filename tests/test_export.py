"""Static chart export (`sofa export`) — reference parity for
network_report.pdf / blktrace scatter (sofa_analyze.py:531-638), rendered
from the unified-schema frames without serving HTTP."""

import os

from sofa_tpu.config import SofaConfig
from sofa_tpu.record import sofa_record


def test_export_static_renders_pdf(logdir):
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.export_static import export_static
    from sofa_tpu.preprocess import sofa_preprocess

    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=50)
    sofa_record("sleep 1.2", cfg)  # long enough for >=2 netstat samples
    sofa_preprocess(cfg)
    sofa_analyze(cfg)
    written = export_static(cfg)
    assert cfg.path("sofa_report.pdf") in written
    assert cfg.path("overview.png") in written
    assert os.path.getsize(cfg.path("sofa_report.pdf")) > 2000
    assert os.path.getsize(cfg.path("overview.png")) > 2000
    # PDF really is multi-page (overview + host-network at minimum)
    import re

    raw = open(cfg.path("sofa_report.pdf"), "rb").read()
    assert raw.startswith(b"%PDF")
    counts = [int(m) for m in re.findall(rb"/Count (\d+)", raw)]
    assert counts and max(counts) >= 2, counts

    # `sofa clean` treats the exports as derived artifacts
    from sofa_tpu.record import sofa_clean

    with open(cfg.path("pystacks.folded"), "w") as f:
        f.write("a;b 1\n")
    sofa_clean(cfg)
    assert not os.path.exists(cfg.path("sofa_report.pdf"))
    assert not os.path.exists(cfg.path("overview.png"))
    assert not os.path.exists(cfg.path("pystacks.folded"))


def test_export_perfetto(tmp_path):
    """Trace-Event-Format export: ops/steps/host spans land on the right
    process/thread tracks with analysis args; counters become 'C' events;
    the CLI --perfetto flag drives it end to end."""
    import gzip
    import json
    import subprocess
    import sys

    import pytest

    from sofa_tpu.export_perfetto import export_perfetto
    from sofa_tpu.trace import make_frame, write_csv

    d = str(tmp_path / "plog") + "/"
    os.makedirs(d)
    write_csv(make_frame([
        {"timestamp": 0.001, "duration": 0.0005, "deviceId": 0,
         "category": 0, "name": "fusion.1", "device_kind": "tpu",
         "flops": 1e9, "hlo_category": "fusion", "phase": "fw"},
        {"timestamp": 0.002, "duration": 0.0002, "deviceId": 0,
         "category": 2, "name": "copy-start.2", "device_kind": "tpu",
         "copyKind": 1},
    ]), d + "tputrace.csv")
    write_csv(make_frame([
        {"timestamp": 0.0, "duration": 0.003, "deviceId": 0,
         "name": "step 0", "device_kind": "tpu"},
    ]), d + "tpusteps.csv")
    write_csv(make_frame([
        {"timestamp": 0.0, "duration": 0.001, "deviceId": -1, "tid": 7,
         "name": "TfOp", "module": "python", "device_kind": "host"},
    ]), d + "hosttrace.csv")
    write_csv(make_frame([
        {"timestamp": 0.01, "event": 55.0, "deviceId": 0,
         "name": "tc_util", "device_kind": "tpu"},
    ]), d + "tpuutil.csv")
    write_csv(make_frame([
        {"timestamp": 0.005, "event": 0.0, "deviceId": -1,
         "name": "alive", "device_kind": "tpu"},      # heartbeat: excluded
        {"timestamp": 0.005, "event": 2.5, "deviceId": 1,
         "name": "hbm_used_gb", "device_kind": "tpu"},
    ]), d + "tpumon.csv")

    from sofa_tpu.config import SofaConfig as _C

    path = export_perfetto(_C(logdir=d))
    doc = json.load(gzip.open(path, "rt"))
    evs = doc["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert {"tpu_op", "step", "host"} <= {e["cat"] for e in spans}
    op = next(e for e in spans if e["name"] == "fusion.1")
    assert op["pid"] == 0 and op["tid"] == 0
    assert op["dur"] == pytest.approx(500.0)
    assert op["args"]["flops"] == 1e9 and op["args"]["phase"] == "fw"
    dma = next(e for e in spans if e["name"] == "copy-start.2")
    assert dma["tid"] == 1                       # async DMA lane
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters and counters[0]["args"]["tc_util"] == 55.0
    hbm = [e for e in counters if e["name"] == "hbm_used_gb"]
    assert hbm and hbm[0]["pid"] == 1 and hbm[0]["args"]["hbm_used_gb"] == 2.5
    assert not any(e["name"] == "alive" for e in counters)  # heartbeat out
    procs = [e for e in evs if e["ph"] == "M"
             and e["name"] == "process_name"]
    # tpu1 exists only via the tpumon counter — device naming must cover it
    assert {"tpu0", "tpu1", "host"} <= {e["args"]["name"] for e in procs}

    # CLI flag: no chartable host samplers here, but perfetto succeeds
    r = subprocess.run([sys.executable, "-m", "sofa_tpu", "export",
                        "--logdir", d, "--perfetto"],
                       capture_output=True, text=True,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-500:]
    assert "perfetto export" in r.stdout + r.stderr


def test_export_perfetto_native_writer_equivalence(tmp_path, capsys,
                                                   monkeypatch):
    """The native writer (native/perfetto_write.cc) and the Python path
    emit the same events (ts/dur within the writer's ns resolution), and a
    corrupt interchange file fails the tool without killing the export."""
    import gzip
    import json
    import math
    import subprocess

    import numpy as np

    from sofa_tpu.collectors.native_build import ensure_built
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.export_perfetto import export_perfetto
    from sofa_tpu.trace import make_frame, write_csv

    tool = ensure_built("perfetto_write")
    if tool is None:
        import pytest

        pytest.skip("no C++ compiler for the native writer")

    n = 120_000  # past the native-path threshold
    rng = np.random.default_rng(7)
    d = str(tmp_path / "nlog") + "/"
    os.makedirs(d)
    write_csv(make_frame({
        "timestamp": np.cumsum(rng.exponential(1e-5, n)),
        "duration": rng.exponential(5e-6, n),
        "deviceId": rng.integers(0, 4, n),
        "category": rng.integers(0, 3, n) % 2,
        "name": np.array([f"fusion.{i % 37}" for i in range(n)]),
        "hlo_category": "fusion",
        "flops": np.array([float(1e9 + (i % 37)) for i in range(n)]),
        "device_kind": "tpu",
    }), d + "tputrace.csv")
    cfg = SofaConfig(logdir=d)

    monkeypatch.delenv("SOFA_NATIVE_PERFETTO", raising=False)
    native = export_perfetto(cfg, out_name="native.json.gz")
    # A silent fallback would make the comparison below vacuous (Python vs
    # Python): require the native path to have actually run.
    assert "(native writer" in capsys.readouterr().out
    monkeypatch.setenv("SOFA_NATIVE_PERFETTO", "0")
    python = export_perfetto(cfg, out_name="python.json.gz")
    assert "(native writer" not in capsys.readouterr().out
    ea = json.load(gzip.open(native, "rt"))["traceEvents"]
    eb = json.load(gzip.open(python, "rt"))["traceEvents"]
    # + per-device meta: process_name + 4 thread_name rows x 4 devices
    assert len(ea) == len(eb) == n + 20
    for x, y in zip(ea, eb):
        assert (x.get("name"), x.get("pid"), x.get("tid"), x.get("args")) \
            == (y.get("name"), y.get("pid"), y.get("tid"), y.get("args"))
        for k in ("ts", "dur"):
            assert math.isclose(x.get(k, 0.0), y.get(k, 0.0),
                                abs_tol=0.0005001)  # %.3f µs = ns grain

    # Malformed interchange input: nonzero exit, no output published.
    bad = str(tmp_path / "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"\x00" * 64)
    r = subprocess.run([tool, bad, str(tmp_path / "bad.json.gz")],
                       capture_output=True)
    assert r.returncode != 0


def test_host_threads_matches_row_loop():
    """The columnar thread-metadata pass stays byte-identical to the
    drop_duplicates().iterrows() loop it replaced."""
    from sofa_tpu.export_perfetto import _host_threads
    from sofa_tpu.trace import make_frame

    sel = make_frame([
        {"timestamp": 0.1, "tid": 11, "module": "jit_step"},
        {"timestamp": 0.2, "tid": 11, "module": "other"},   # dup tid
        {"timestamp": 0.3, "tid": 12, "module": ""},        # empty -> tid N
        {"timestamp": 0.4, "tid": -5, "module": "neg"},     # mask applies
        {"timestamp": 0.5, "tid": 2**31 + 7, "module": "wrap"},
    ])

    def row_loop(sel):
        threads = {}
        for _, row in sel.drop_duplicates("tid").iterrows():
            threads[int(row["tid"]) & 0x7FFFFFFF] = (
                str(row.get("module")) or f"tid {row['tid']}")
        return threads

    assert _host_threads(sel) == row_loop(sel)


def test_export_perfetto_clamps_nonfinite_times(tmp_path):
    """inf/NaN/huge-finite timestamps must never reach either writer's
    float formatting: nan_to_num BEFORE the 1e6 scale would re-overflow to
    inf and both writers would emit the invalid JSON token `inf`."""
    import gzip
    import json
    import math

    from sofa_tpu.config import SofaConfig
    from sofa_tpu.export_perfetto import export_perfetto
    from sofa_tpu.trace import make_frame, write_csv

    d = str(tmp_path / "clog") + "/"
    os.makedirs(d)
    write_csv(make_frame([
        {"timestamp": float("inf"), "duration": 1e-3, "deviceId": 0,
         "category": 0, "name": "inf_ts", "device_kind": "tpu"},
        {"timestamp": 0.1, "duration": float("nan"), "deviceId": 0,
         "category": 0, "name": "nan_dur", "device_kind": "tpu"},
        {"timestamp": 1e200, "duration": -5.0, "deviceId": 0,
         "category": 0, "name": "huge_ts_neg_dur", "device_kind": "tpu"},
    ]), d + "tputrace.csv")
    path = export_perfetto(SofaConfig(logdir=d))
    evs = json.load(gzip.open(path, "rt"))["traceEvents"]  # valid JSON
    by = {e["name"]: e for e in evs if e.get("ph") == "X"}
    assert math.isfinite(by["inf_ts"]["ts"]) and by["inf_ts"]["ts"] <= 1e15
    assert by["nan_dur"]["dur"] == 0.0
    assert by["huge_ts_neg_dur"]["ts"] <= 1e15
    assert by["huge_ts_neg_dur"]["dur"] == 0.0  # negative clips to 0


def test_export_perfetto_multihost_host_processes(tmp_path):
    """Per-host host timelines stay separate Perfetto processes: host rows
    carry their host's ordinal base in deviceId (host 1 -> 256), and thread
    ids from different machines must never share a track."""
    import gzip
    import json

    from sofa_tpu.config import SofaConfig
    from sofa_tpu.export_perfetto import export_perfetto
    from sofa_tpu.trace import make_frame, write_csv

    d = str(tmp_path / "plog") + "/"
    os.makedirs(d)
    write_csv(make_frame([
        {"timestamp": 0.0, "duration": 0.001, "deviceId": 0, "tid": 7,
         "name": "TfOp", "module": "python", "device_kind": "host"},
        {"timestamp": 0.0, "duration": 0.002, "deviceId": 256, "tid": 7,
         "name": "TfOp", "module": "python", "device_kind": "host"},
    ]), d + "hosttrace.csv")
    write_csv(make_frame([
        {"timestamp": 0.0, "duration": 0.001, "deviceId": 0, "tid": 3,
         "name": "send", "module": "Megascale Trace",
         "device_kind": "custom"},
        {"timestamp": 0.0, "duration": 0.001, "deviceId": 0, "tid": 3,
         "name": "recv", "module": "Other Plane", "device_kind": "custom"},
    ]), d + "customtrace.csv")
    doc = json.load(gzip.open(export_perfetto(SofaConfig(logdir=d)), "rt"))
    evs = doc["traceEvents"]
    host_pids = {e["pid"] for e in evs
                 if e["ph"] == "X" and e["cat"] == "host"}
    assert len(host_pids) == 2
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"host0", "host1"} <= names
    # two CUSTOM planes on one host get distinct processes too
    custom_pids = {e["pid"] for e in evs
                   if e["ph"] == "X" and e["cat"] == "custom_plane"}
    assert len(custom_pids) == 2
    assert {"Megascale Trace", "Other Plane"} <= names


def test_export_cluster_merged_perfetto(tmp_path):
    """--cluster_hosts merges per-host logdirs onto the cluster clock for
    the exporters: host B's series shift by its clock offset and its chips
    rebase to ordinal 256+, so one trace.json.gz spans the pod."""
    import gzip
    import json

    import pytest

    from sofa_tpu.analyze import load_cluster_frames
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.export_perfetto import export_perfetto
    from sofa_tpu.trace import make_frame, write_csv

    base = str(tmp_path / "clog")
    for host, tb in (("ha", 1000.0), ("hb", 1002.5)):
        d = base + f"-{host}/"
        os.makedirs(d)
        with open(d + "sofa_time.txt", "w") as f:
            f.write(f"{tb}\n")
        write_csv(make_frame([
            {"timestamp": 1.0, "duration": 0.5, "deviceId": 0,
             "category": 0, "name": f"fusion.{host}",
             "device_kind": "tpu"},
        ]), d + "tputrace.csv")
    cfg = SofaConfig(logdir=base + "/", cluster_hosts=["ha", "hb"])
    frames = load_cluster_frames(cfg, only=["tputrace"])
    ops = frames["tputrace"].sort_values("deviceId")
    assert ops["deviceId"].tolist() == [0, 256]
    # host b's clock is 2.5s ahead of the cluster zero
    assert ops["timestamp"].tolist() == pytest.approx([1.0, 3.5])

    path = export_perfetto(cfg, frames)
    doc = json.load(gzip.open(path, "rt"))
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert pids == {0, 256}


def test_cluster_merge_preserves_real_pids(tmp_path):
    """Frames that carry the REAL sampled process pid (cputrace, strace,
    blktrace...) must survive a cluster merge intact — only host-sampler
    frames (mpstat/netbandwidth/...) get pid repurposed as the host ordinal;
    host identity for everything rides the stamped `host` column (r3 advisor
    finding, analyze.py load_cluster_frames)."""
    from sofa_tpu.analyze import load_cluster_frames
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.trace import make_frame, write_csv

    base = str(tmp_path / "clog")
    for host, tb in (("ha", 1000.0), ("hb", 1000.0)):
        d = base + f"-{host}/"
        os.makedirs(d)
        with open(d + "sofa_time.txt", "w") as f:
            f.write(f"{tb}\n")
        write_csv(make_frame([
            {"timestamp": 1.0, "duration": 0.01, "deviceId": 2,
             "category": 0, "name": "main", "pid": 4242},
        ]), d + "cputrace.csv")
        write_csv(make_frame([
            {"timestamp": 1.0, "duration": 1.0, "deviceId": 0,
             "category": 0, "name": "rxkB/s", "event": 5.0, "pid": -1},
        ]), d + "netbandwidth.csv")
    cfg = SofaConfig(logdir=base + "/", cluster_hosts=["ha", "hb"])
    frames = load_cluster_frames(cfg, only=["cputrace", "netbandwidth"])
    cpu = frames["cputrace"].sort_values("host")
    assert cpu["pid"].tolist() == [4242, 4242]  # NOT overwritten
    assert cpu["host"].tolist() == [0, 1]
    net = frames["netbandwidth"].sort_values("host")
    assert net["pid"].tolist() == [0, 1]  # sampler: host ordinal in pid
    assert net["host"].tolist() == [0, 1]


def test_export_empty_logdir_degrades(tmp_path):
    from sofa_tpu.export_static import export_static

    d = str(tmp_path / "empty") + "/"
    os.makedirs(d)
    written = export_static(SofaConfig(logdir=d))
    assert written == []
    assert not os.path.exists(os.path.join(d, "sofa_report.pdf"))
