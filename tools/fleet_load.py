#!/usr/bin/env python
"""Fleet load harness: the scaled tier's missing proof (docs/FLEET.md).

Drives N concurrent synthetic agents (each performing the real
have -> put -> commit protocol with unique content-addressed runs) plus
query pollers against a fleet service tier, and reports what the single
process could never show honestly:

  fleet_push_p50_ms / fleet_push_p99_ms     end-to-end push latency
  fleet_query_p50_ms / fleet_query_p99_ms   /v1/query latency under load
  fleet_saturation_rps                      completed pushes per second

The workload is DETERMINISTIC (payloads keyed by (tenant, agent, i)), so
two tiers fed the same parameters must commit the same run-id sets and
answer /v1/query with the same rows — ``--compare 1,4`` runs the
workload against a 1-worker and a 4-worker tier, asserts that
equivalence, and reports the saturation ratio (the acceptance bar:
>= 3x for 4 workers on mixed push+query load).

Modes::

    python tools/fleet_load.py --url http://host:8044 --token T
    python tools/fleet_load.py --smoke            # self-hosted, seconds
    python tools/fleet_load.py --compare 1,4      # the scaling proof

``--smoke`` is the bench.py evidence hook: tiny fleet, a few seconds,
JSON on the last stdout line (``bench.py`` archives the metrics on
success and dead-tunnel paths alike).
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

DEFAULT_TOKEN = "fleet-load-token"


def _pct(sorted_ms: List[float], pct: float) -> float:
    if not sorted_ms:
        return 0.0
    idx = min(int(len(sorted_ms) * pct / 100.0), len(sorted_ms) - 1)
    return sorted_ms[idx]


class _Conn:
    """One keep-alive connection to the tier (per worker thread)."""

    def __init__(self, url: str, token: str, timeout_s: float = 30.0):
        parsed = urllib.parse.urlparse(url)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 80
        self.token = token
        self.timeout_s = timeout_s
        self._conn: "http.client.HTTPConnection | None" = None

    def request(self, method: str, path: str,
                body: "bytes | None" = None,
                extra: "Dict[str, str] | None" = None) -> Tuple[int, dict]:
        headers = {"Authorization": f"Bearer {self.token}"}
        if extra:
            headers.update(extra)
        if body is not None:
            headers["Content-Type"] = "application/json" \
                if method == "POST" else "application/octet-stream"
        # Closed-loop load: a dropped push would silently shrink the
        # committed run set and break cross-tier equivalence, so wait
        # out backpressure (503/429) patiently — the saturation number
        # comes from wall time, not from giving up.  The budget is
        # time-based: under deep saturation one request can eat many
        # 503 rounds, and a fixed attempt count quietly becomes a
        # latency ceiling that drops the slowest pushes.
        deadline = time.monotonic() + 120.0
        attempt = 0
        while time.monotonic() < deadline:
            attempt += 1
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
                try:
                    self._conn.connect()
                    # small-message request/response traffic: Nagle +
                    # delayed ACK would add ~40 ms per round trip
                    self._conn.sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    self._conn.close()
                    self._conn = None
                    time.sleep(min(0.05 * (attempt + 1), 0.5))
                    continue
            try:
                self._conn.request(method, path, body=body or b"",
                                   headers=headers)
                resp = self._conn.getresponse()
                data = resp.read()
            except OSError:
                # worker died / conn dropped: reconnect and retry — the
                # tier's failover contract is that a sibling answers
                self._conn.close()
                self._conn = None
                time.sleep(min(0.05 * (attempt + 1), 0.5))
                continue
            if resp.status in (503, 429, 507):
                # short fixed backoff: a long sleep leaves server write
                # slots idle and measures the sleep, not the tier.  507
                # is the fires-once disk_full refusal — the backed-off
                # retry proving recovery is the chaos_tier.py contract.
                time.sleep(0.05)
                continue
            try:
                return resp.status, json.loads(data) if data else {}
            except ValueError:
                return resp.status, {}
        return 599, {}

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


def _synthetic_run(tenant: str, agent: int, i: int,
                   payload_bytes: int) -> Dict[str, bytes]:
    """A deterministic tiny run: same (tenant, agent, i) -> same bytes ->
    same content-addressed run id on EVERY tier it is pushed to."""
    blob = (f"{tenant}/{agent}/{i}:".encode()
            * (payload_bytes // len(f"{tenant}/{agent}/{i}:") + 1)
            )[:payload_bytes]
    return {"run_manifest.json": json.dumps(
                {"synthetic": True, "agent": agent, "i": i},
                sort_keys=True).encode(),
            "payload.bin": blob}


def _push_run(conn: _Conn, tenant: str, files_bytes: Dict[str, bytes],
              trace: str = "") -> Tuple[bool, float]:
    """One full agent push (have -> missing puts -> commit); returns
    (committed, wall ms).  ``trace`` rides every request as X-Sofa-Trace
    — the cross-process push-tracing protocol (docs/FLEET.md) — so the
    tier's spans join the harness's push under one id."""
    files = {rel: {"sha256": hashlib.sha256(data).hexdigest(),
                   "bytes": len(data)}
             for rel, data in files_bytes.items()}
    by_sha = {files[rel]["sha256"]: data
              for rel, data in files_bytes.items()}
    extra = {"X-Sofa-Trace": trace} if trace else None
    t0 = time.perf_counter()
    status, doc = conn.request("POST", f"/v1/{tenant}/have",
                               json.dumps({"files": files}).encode(),
                               extra=extra)
    if status != 200:
        return False, (time.perf_counter() - t0) * 1000.0
    for sha in doc.get("missing") or []:
        status, _ = conn.request("PUT", f"/v1/{tenant}/object/{sha}",
                                 by_sha[sha], extra=extra)
        if status != 200:
            return False, (time.perf_counter() - t0) * 1000.0
    status, ack = conn.request(
        "POST", f"/v1/{tenant}/commit",
        json.dumps({"files": files, "logdir": f"synthetic/{tenant}",
                    "hostname": "fleet-load"}).encode(), extra=extra)
    ms = (time.perf_counter() - t0) * 1000.0
    return status == 200 and bool(ack.get("committed")), ms


def run_fleet_load(url: str, token: str, *, agents: int = 8,
                   pushes: int = 8, pollers: int = 2, tenants: int = 4,
                   payload_bytes: int = 2048,
                   push_interval_s: float = 0.0) -> dict:
    """Drive the workload; returns the metrics document.  Deterministic
    run set: ``agents * pushes`` runs spread over ``tenants`` tenant
    namespaces.

    ``push_interval_s > 0`` switches the agents from closed-loop
    (back-to-back) to OPEN-LOOP pacing: agent ``a``'s push ``i`` is due
    at ``harness_start + i * push_interval_s`` on the shared absolute
    clock, and a thread that falls behind fires immediately without
    re-anchoring.  Per-iteration sleeps would let a slow tier quietly
    lower the offered load (each stall pushes every later request back),
    which inflates the saturation number exactly when the tier is
    struggling — the regime chaos_tier.py exists to measure."""
    push_ms: List[float] = []
    query_ms: List[float] = []
    errors: List[str] = []
    traces: List[dict] = []
    lock = threading.Lock()
    done = threading.Event()
    # The shared schedule origin: set ONCE just before the threads
    # start, never re-read per iteration — the absolute harness start.
    t_start = time.monotonic()

    def agent_main(a: int) -> None:
        tenant = f"lt{a % tenants}"
        for i in range(pushes):
            if push_interval_s > 0.0:
                due = t_start + i * push_interval_s
                lag = due - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
            # fresh connection per push, like the real short-lived
            # `sofa agent` invocations — and it re-rolls the
            # SO_REUSEPORT hash, so demand rebalances across workers
            # between pushes instead of pinning to one for the run
            conn = _Conn(url, token)
            # deterministic per-push trace id (the workload is keyed
            # the same way): the fleet-trace test greps for exactly it
            trace = hashlib.sha256(
                f"trace:{tenant}/{a}/{i}".encode()).hexdigest()[:16]
            try:
                ok, ms = _push_run(
                    conn, tenant, _synthetic_run(tenant, a, i,
                                                 payload_bytes),
                    trace=trace)
            finally:
                conn.close()
            with lock:
                if ok:
                    push_ms.append(ms)
                    traces.append({"trace": trace, "tenant": tenant,
                                   "agent": a, "i": i})
                else:
                    errors.append(f"agent {a} push {i} failed")

    def poller_main(p: int) -> None:
        conn = _Conn(url, token)
        tenant = f"lt{p % tenants}"
        # Open-loop pacing from the absolute harness start: query k is
        # due at t_start + k * 0.05.  A sleep-after-each-query loop
        # would add each slow query's latency to every later deadline,
        # silently lowering the offered poll rate exactly when the tier
        # is slow — the case the p99 exists to expose.
        k = 0
        try:
            while not done.is_set():
                due = t_start + k * 0.05
                k += 1
                lag = due - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                if done.is_set():
                    break
                t0 = time.perf_counter()
                status, _ = conn.request(
                    "GET", f"/v1/{tenant}/query?kind=runs&limit=50")
                ms = (time.perf_counter() - t0) * 1000.0
                with lock:
                    if status == 200:
                        query_ms.append(ms)
                    else:
                        errors.append(f"poller {p} query -> {status}")
        finally:
            conn.close()

    threads = [threading.Thread(target=agent_main, args=(a,), daemon=True)
               for a in range(agents)]
    pthreads = [threading.Thread(target=poller_main, args=(p,),
                                 daemon=True) for p in range(pollers)]
    t0 = time.perf_counter()
    for t in threads + pthreads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    done.set()
    for t in pthreads:
        t.join(timeout=5.0)
    push_ms.sort()
    query_ms.sort()
    metrics = {
        "fleet_push_p50_ms": round(_pct(push_ms, 50), 3),
        "fleet_push_p99_ms": round(_pct(push_ms, 99), 3),
        "fleet_query_p50_ms": round(_pct(query_ms, 50), 3),
        "fleet_query_p99_ms": round(_pct(query_ms, 99), 3),
        "fleet_saturation_rps": round(len(push_ms) / wall_s, 3)
        if wall_s > 0 else 0.0,
    }
    return {"metrics": metrics, "pushes": len(push_ms),
            "queries": len(query_ms), "wall_s": round(wall_s, 3),
            "errors": errors[:20], "error_count": len(errors),
            "traces": traces,
            "tenants": [f"lt{i}" for i in range(tenants)]}


def wait_drained(url: str, token: str, timeout_s: float = 60.0) -> bool:
    """Block until every tenant's WAL depth reads 0 on /v1/tier."""
    conn = _Conn(url, token)
    deadline = time.monotonic() + timeout_s
    try:
        while time.monotonic() < deadline:
            status, doc = conn.request("GET", "/v1/tier")
            if status == 200 and all(
                    t.get("wal_depth") == 0
                    for t in doc.get("tenants") or []):
                return True
            time.sleep(0.2)
        return False
    finally:
        conn.close()


def fetch_metrics(url: str, token: str) -> Optional[dict]:
    """One authenticated ``GET /v1/metrics`` — the worker's observability
    document (None on any failure: a metrics-off tier still loads)."""
    conn = _Conn(url, token)
    try:
        status, doc = conn.request("GET", "/v1/metrics")
    finally:
        conn.close()
    return doc if status == 200 and isinstance(doc, dict) else None


def committed_runs(url: str, token: str,
                   tenants: List[str]) -> Dict[str, List[str]]:
    """Per tenant, the sorted committed run ids as /v1/query answers
    them — the cross-tier equivalence witness."""
    conn = _Conn(url, token)
    out: Dict[str, List[str]] = {}
    try:
        for tenant in tenants:
            rows: List[str] = []
            offset = 0
            while True:
                status, doc = conn.request(
                    "GET", f"/v1/{tenant}/query?kind=runs&limit=500"
                           f"&offset={offset}")
                if status != 200:
                    break
                batch = [r.get("run") for r in doc.get("rows") or []]
                rows.extend(r for r in batch if r)
                offset += len(batch)
                if not batch or offset >= int(doc.get("total") or 0):
                    break
            out[tenant] = sorted(rows)
    finally:
        conn.close()
    return out


def _start_tier(root: str, token: str, workers: int,
                inflight: int = 64, io_ms: float = 0.0):
    """Self-host a tier on an OS-assigned loopback port; returns
    (url, stop_callable).  ALWAYS the forked pool path — a --workers 1
    tier must be one worker process, not an in-process thread, or the
    cross-count comparison measures two different architectures.
    ``io_ms`` is the emulated storage latency (SOFA_TIER_IO_MS) slept
    per write while its admission slot is held: on a dev box the page
    cache makes writes CPU-cheap, which hides the storage-bound regime
    the worker pool exists to scale."""
    from sofa_tpu.archive import service

    old_io = os.environ.get("SOFA_TIER_IO_MS")
    os.environ["SOFA_TIER_IO_MS"] = str(io_ms)
    try:
        handle = service._serve_pool(
            root, token, "127.0.0.1", 0, 0.0, inflight, workers,
            serve_forever=False)
    finally:
        if old_io is None:
            os.environ.pop("SOFA_TIER_IO_MS", None)
        else:
            os.environ["SOFA_TIER_IO_MS"] = old_io
    if handle is None:
        raise RuntimeError("tier failed to start")
    return handle.url, handle.stop


def _one_tier(workers: int, token: str, load_kw: dict,
              inflight: int = 64, io_ms: float = 0.0) -> dict:
    """Workload against a fresh self-hosted tier; returns the result doc
    plus the drained per-tenant run sets."""
    with tempfile.TemporaryDirectory(prefix="fleet_load_") as root:
        url, stop = _start_tier(root, token, workers,
                                inflight=inflight, io_ms=io_ms)
        try:
            res = run_fleet_load(url, token, **load_kw)
            res["drained"] = wait_drained(url, token)
            res["runs"] = committed_runs(url, token, res["tenants"])
            res["workers"] = workers
            mdoc = fetch_metrics(url, token)
            if mdoc is not None:
                snap = mdoc.get("snapshot") or {}
                res["tier_metrics"] = {
                    "scrape_seq": mdoc.get("scrape_seq"),
                    "scrape_wall_ms": snap.get("scrape_wall_ms"),
                    "push_p99_ms": snap.get("push_p99_ms"),
                    "wal_depth": snap.get("wal_depth"),
                }
        finally:
            stop()
    return res


def main(argv: "List[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="drive an existing tier at this URL")
    ap.add_argument("--token", default=os.environ.get(
        "SOFA_SERVE_TOKEN", DEFAULT_TOKEN))
    ap.add_argument("--agents", type=int, default=32)
    ap.add_argument("--pushes", type=int, default=8,
                    help="runs pushed per agent (closed loop)")
    ap.add_argument("--pollers", type=int, default=4)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--payload_bytes", type=int, default=2048)
    ap.add_argument("--push_interval_s", type=float, default=0.0,
                    help="open-loop pacing: agent push i is due at "
                         "harness_start + i * interval on the shared "
                         "absolute clock (0 = closed loop)")
    ap.add_argument("--workers", type=int, default=2,
                    help="self-hosted tier size (no --url)")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale fleet for bench evidence")
    ap.add_argument("--compare", metavar="N,M",
                    help="run the workload against each worker count, "
                         "assert equivalent results, report the ratio")
    ap.add_argument("--io_ms", type=float, default=None,
                    help="emulated storage latency per write "
                         "(SOFA_TIER_IO_MS); default 150 under "
                         "--compare, else 0")
    ap.add_argument("--inflight", type=int, default=None,
                    help="per-worker write-slot budget; default 4 "
                         "under --compare, else 64")
    ap.add_argument("--no_metrics", action="store_true",
                    help="self-hosted tiers run with the observability "
                         "plane off (SOFA_TIER_METRICS=0) — the bench's "
                         "metrics-overhead baseline")
    args = ap.parse_args(argv)
    if args.no_metrics:
        os.environ["SOFA_TIER_METRICS"] = "0"
    # --compare measures admission capacity (slots / storage latency),
    # which is what the worker pool multiplies.  With io_ms=0 on a
    # page-cached dev box the bottleneck is one core of Python HTTP
    # parsing, which no process count can scale.
    if args.io_ms is None:
        args.io_ms = 150.0 if args.compare else 0.0
    if args.inflight is None:
        args.inflight = 4 if args.compare else 64
    if args.smoke:
        args.agents, args.pushes = min(args.agents, 6), min(args.pushes, 4)
        args.pollers, args.tenants = min(args.pollers, 2), 2
    load_kw = dict(agents=args.agents, pushes=args.pushes,
                   pollers=args.pollers, tenants=args.tenants,
                   payload_bytes=args.payload_bytes,
                   push_interval_s=args.push_interval_s)

    if args.compare:
        counts = sorted({max(int(c), 1)
                         for c in args.compare.split(",") if c.strip()})
        results = {}
        for workers in counts:
            print(f"fleet_load: driving {args.agents} agents x "
                  f"{args.pushes} pushes against --workers {workers}",
                  file=sys.stderr)
            results[workers] = _one_tier(workers, args.token, load_kw,
                                         inflight=args.inflight,
                                         io_ms=args.io_ms)
        base = results[counts[0]]
        equivalent = all(r["runs"] == base["runs"]
                         and r["error_count"] == 0
                         for r in results.values())
        ratio = (results[counts[-1]]["metrics"]["fleet_saturation_rps"]
                 / max(base["metrics"]["fleet_saturation_rps"], 1e-9))
        doc = {"compare": {w: r["metrics"]
                           for w, r in results.items()},
               "io_ms": args.io_ms, "inflight": args.inflight,
               "equivalent": equivalent,
               "saturation_ratio": round(ratio, 2),
               "runs_per_tenant": {t: len(v)
                                   for t, v in base["runs"].items()}}
        for w in counts:
            m = results[w]["metrics"]
            print(f"  --workers {w}: {m['fleet_saturation_rps']} rps, "
                  f"push p99 {m['fleet_push_p99_ms']} ms, "
                  f"query p99 {m['fleet_query_p99_ms']} ms",
                  file=sys.stderr)
        print(f"  saturation ratio ({counts[-1]}w/{counts[0]}w): "
              f"{doc['saturation_ratio']}x; results equivalent: "
              f"{equivalent}", file=sys.stderr)
        print(json.dumps(doc))
        return 0 if equivalent else 1

    if args.url:
        res = run_fleet_load(args.url, args.token, **load_kw)
    else:
        res = _one_tier(args.workers, args.token, load_kw,
                        inflight=args.inflight, io_ms=args.io_ms)
    m = res["metrics"]
    print(f"fleet_load: {res['pushes']} pushes, {res['queries']} "
          f"queries in {res['wall_s']}s — {m['fleet_saturation_rps']} "
          f"rps, push p50/p99 {m['fleet_push_p50_ms']}/"
          f"{m['fleet_push_p99_ms']} ms, query p50/p99 "
          f"{m['fleet_query_p50_ms']}/{m['fleet_query_p99_ms']} ms, "
          f"{res['error_count']} error(s)", file=sys.stderr)
    print(json.dumps(res))
    return 0 if res["error_count"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
