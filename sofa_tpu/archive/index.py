"""Incremental columnar index over the archive — ``<root>/_index/``.

Every fleet-wide *read* used to be a linear scan: ``archive ls`` re-parsed
the whole ``catalog.jsonl``, a rolling `sofa regress` baseline did it
again, and any cross-run feature question opened one ``runs/<id>.json``
per run — O(fleet) per query.  This module is the O(fleet)→O(result)
move: the catalog and the runs' feature vectors land as chunked Arrow
column stores (the ``_frames/`` machinery of sofa_tpu/frames.py pointed
at the archive), maintained *tail-aware* like the `sofa live` offset
ledger, so queries become column scans with predicate pushdown instead
of N file opens.

Layout::

    _index/index_commit.json   THE commit point (schema
                               ``sofa_tpu/archive_index`` v1, fsync'd,
                               written LAST): the committed catalog byte
                               offset, head signature + rewrite
                               generation, event/run totals, and a
                               commit sha over every chunk content hash
                               (the /v1/query ETag)
    _index/catalog/            every catalog event as columns
                               (run, verb, label, host, timestamp,
                               bytes, files, logdir) — file order kept
    _index/runs/               the DEDUPED ingest sequence (newest event
                               per run id, ``ingest_entries`` order) +
                               each run's feature count: `ls` and the
                               rolling-baseline window are tail-chunk
                               reads over this family
    _index/features/           runs × features, long form
                               (run, name, value, timestamp) — extracted
                               from run docs at index time, including the
                               per-device ``tpu*_sol_distance`` values
                               the fleet board ranks

Each family is a normal chunk store — per-chunk content shas, fixed row
boundaries, its own schema-versioned fsync'd-last ``frame_index.json``
(validated by tools/manifest_check.py) — so an append rewrites only the
tail chunk and `sofa archive fsck` re-hashes committed chunks.

Contracts:

* **Suffix-only refresh** — the commit records the catalog byte offset
  it covers, backed off to the last whole record (`sofa live`'s torn-
  tail discipline); a refresh parses exactly the appended suffix, and a
  refresh over an unchanged catalog parses 0 bytes and touches 0 files.
* **Deterministic invalidation** — a gc compaction is detected three
  ways (size shrink, head-signature change over the committed prefix,
  and the ``catalog.gen`` rewrite generation `catalog.rewrite` bumps)
  and triggers a full rebuild, never a silently stale answer.
* **Pure derived state** — everything here is re-derivable from
  ``catalog.jsonl`` + the run docs: :func:`drop` + :func:`refresh` is
  always safe, and `sofa archive fsck --repair` does exactly that when
  a chunk rots.
* **Crash safety** — chunk stores commit family-by-family (their own
  fsync'd-last indexes) and ``index_commit.json`` lands last: a SIGKILL
  mid-refresh leaves the previous commit readable, readers that find
  commit and catalog out of agreement fall back to the linear scan, and
  the next refresh (or the `sofa resume` replay of the journaled ingest
  that triggered it) converges to the never-interrupted bytes — the
  commit doc carries no wall clock on purpose.
* **Readers never write** — :func:`query` and friends serve a *current*
  index or fall back to the scan path; refresh runs at ingest/serve
  commit points on the shared ``--jobs`` pool.  ``SOFA_ARCHIVE_INDEX=0``
  forces every consumer onto the scan path.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import os
import shutil
from typing import Callable, Dict, List, Optional

from sofa_tpu.archive import catalog
from sofa_tpu.concurrency import Guard
from sofa_tpu.printing import print_warning

INDEX_DIR_NAME = "_index"
INDEX_COMMIT_NAME = "index_commit.json"
INDEX_SCHEMA = "sofa_tpu/archive_index"
# Bumps on any BREAKING layout/meaning change (the run-manifest policy,
# docs/OBSERVABILITY.md); additive keys do not.
INDEX_VERSION = 1

CATALOG_FAMILY = "catalog"
RUNS_FAMILY = "runs"
FEATURES_FAMILY = "features"
FAMILIES = (CATALOG_FAMILY, RUNS_FAMILY, FEATURES_FAMILY)

#: Column families, schema-pinned like trace.COLUMNS pins the frame
#: store (string columns and float64 columns; absent strings are "",
#: absent numerics NaN).
CATALOG_COLUMNS = ["run", "verb", "label", "host", "logdir",
                   "timestamp", "bytes", "files"]
RUNS_COLUMNS = ["run", "label", "host", "logdir",
                "timestamp", "bytes", "files", "n_features"]
FEATURE_COLUMNS = ["run", "name", "value", "timestamp"]
_STR_COLS = {"run", "verb", "label", "host", "logdir", "name"}

#: Rows per index chunk — sized so a 50k-run catalog stays in a handful
#: of chunks while a newest-N tail read touches exactly one.
INDEX_CHUNK_ROWS = 1 << 14


def _chaos_tick() -> None:
    """``SOFA_INDEX_EXIT_AFTER=<n>`` hard-exits at the start of the n-th
    chunk-store write of this process — the deterministic SIGKILL stand-
    in the kill-mid-index-refresh chaos cell (tools/chaos_matrix.py)
    drives to prove resume/rebuild convergence."""
    try:
        n = int(os.environ.get("SOFA_INDEX_EXIT_AFTER", "0"))
    except ValueError:
        n = 0
    if not n:
        return
    count = int(os.environ.get("_SOFA_INDEX_WRITES", "0")) + 1
    os.environ["_SOFA_INDEX_WRITES"] = str(count)
    if count >= n:
        os._exit(87)


def index_dir(root: str) -> str:
    return os.path.join(root, INDEX_DIR_NAME)


def family_dir(root: str, family: str) -> str:
    return os.path.join(root, INDEX_DIR_NAME, family)


def commit_path(root: str) -> str:
    return os.path.join(root, INDEX_DIR_NAME, INDEX_COMMIT_NAME)


def available() -> bool:
    """Whether the index can operate here (pyarrow present) — without it
    every consumer stays on the linear-scan path, stated once."""
    from sofa_tpu import frames

    return frames.columnar_available()


def enabled() -> bool:
    """The consumer-side gate: pyarrow present and not opted out via
    ``SOFA_ARCHIVE_INDEX=0`` (the scan-mode escape hatch tests and
    operators use)."""
    return os.environ.get("SOFA_ARCHIVE_INDEX", "1") != "0" \
        and available()


#: Roots whose committed index is authoritative BY FIAT — read replicas
#: (archive/tier.py) serve pulled immutable commits with no local
#: catalog to check against, so ``is_current`` trusts the commit as-is.
#: Process-local; a replica pins each tenant root after its first pull.
_PINNED_ROOTS: set = set()
_PINNED_GUARD = Guard("archive_index.pins", protects=("_PINNED_ROOTS",))


def pin_root(root: str) -> None:
    with _PINNED_GUARD:
        _PINNED_ROOTS.add(os.path.abspath(root))


def unpin_root(root: str) -> None:
    with _PINNED_GUARD:
        _PINNED_ROOTS.discard(os.path.abspath(root))


def load_commit(root: str) -> Optional[dict]:
    """The committed index manifest, or None when there is no readable
    v1 commit (readers then fall back to the linear scan)."""
    try:
        with open(commit_path(root)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != INDEX_SCHEMA \
            or doc.get("version") != INDEX_VERSION:
        return None
    return doc


def is_current(root: str, commit: "dict | None" = None) -> bool:
    """Whether the committed index covers the catalog AS IT IS NOW — the
    read-path gate: queries serve a current index and scan otherwise
    (readers never refresh; ingest/serve commit points do).

    Current means: same rewrite generation, same head signature over the
    committed prefix, and no un-indexed *whole* record appended (a torn
    final line — the mid-append crash — is not yet data)."""
    commit = commit if commit is not None else load_commit(root)
    if commit is None:
        return False
    if os.path.abspath(root) in _PINNED_ROOTS:
        # a replica root: the pulled commit IS the truth — there is no
        # local catalog for it to be current against
        return True
    offset = int(commit.get("catalog_offset") or 0)
    try:
        size = os.path.getsize(catalog.catalog_path(root))
    except OSError:
        size = 0
    if size < offset:
        return False  # the catalog shrank: not the same ledger
    if catalog.generation(root) != commit.get("catalog_gen"):
        return False  # gc compaction bumped the rewrite generation
    if catalog.head_sig(root, offset) != commit.get("catalog_head_sha"):
        return False  # same name, different bytes at the head
    if size == offset:
        return True
    tail = _read_range(catalog.catalog_path(root), offset, size)
    from sofa_tpu.live import whole_records

    return not whole_records(tail or b"")


def _read_range(path: str, start: int, end: int) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            f.seek(start)
            return f.read(max(end - start, 0))
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Building the column families.
# ---------------------------------------------------------------------------

def _parse_events(buf: bytes) -> List[dict]:
    """The suffix parser: JSON events from a whole-records byte range
    (unparsable lines skipped, the catalog reader's rule).  A seam on
    purpose — the suffix-only-refresh test monkeypatches it to raise on
    any byte the commit already covers."""
    out: List[dict] = []
    for line in buf.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            continue
        if isinstance(e, dict):
            out.append(e)
    return out


def _conform_family(df, columns: List[str]):
    """Pin a family frame to its canonical schema and dtypes (strings as
    object/str with "" for absent, numerics as float64 with NaN) — the
    per-chunk content hashes must be a pure function of the DATA, not of
    whichever pandas inference path built the frame."""
    import numpy as np
    import pandas as pd

    out = pd.DataFrame(index=df.index if len(df) else None)
    for c in columns:
        col = df[c] if c in df.columns else None
        if c in _STR_COLS:
            if col is None:
                vals = [""] * len(df)
            else:
                vals = ["" if v is None or (isinstance(v, float)
                                            and v != v) else str(v)
                        for v in col.tolist()]
            out[c] = pd.Series(vals, index=out.index, dtype=object)
        else:
            if col is None:
                out[c] = pd.Series(np.full(len(df), np.nan),
                                   index=out.index, dtype="float64")
            else:
                out[c] = pd.to_numeric(col, errors="coerce").astype(
                    "float64")
    return out


def _event_rows(events: List[dict],
                host_of: Callable[[str], str]) -> "object":
    """Catalog events -> family rows (one per event, file order kept —
    the order ``ingest_entries`` dedup semantics depend on)."""
    import pandas as pd

    rows = []
    for e in events:
        verb = str(e.get("ev") or "?")
        run = e.get("run") if isinstance(e.get("run"), str) else ""
        rows.append({
            "run": run,
            "verb": verb,
            "label": str(e.get("label") or e.get("metric") or ""),
            "host": host_of(run) if verb == "ingest" and run else "",
            "logdir": str(e.get("logdir") or ""),
            "timestamp": e.get("t"),
            "bytes": (e.get("bytes_added") if verb == "ingest"
                      else e.get("freed_bytes") if verb == "gc"
                      else e.get("value")),
            "files": e.get("files"),
        })
    return _conform_family(pd.DataFrame(rows, columns=CATALOG_COLUMNS),
                           CATALOG_COLUMNS)


def _feature_rows(events: List[dict],
                  docs: Dict[str, "dict | None"]) -> "object":
    """New ingest events -> feature-family rows: the run doc's inlined
    feature vector flattened to (run, name, value, t) long form.  Runs
    whose doc is unreadable contribute nothing — exactly the rolling-
    baseline scan's skip rule."""
    import pandas as pd

    rows = []
    for e in events:
        if e.get("ev") != "ingest" or not isinstance(e.get("run"), str):
            continue
        doc = docs.get(e["run"])
        feats = (doc or {}).get("features") or {}
        for name, value in feats.items():
            if isinstance(value, (int, float)) \
                    and not isinstance(value, bool):
                rows.append({"run": e["run"], "name": str(name),
                             "value": float(value),
                             "timestamp": e.get("t")})
    return _conform_family(pd.DataFrame(rows, columns=FEATURE_COLUMNS),
                           FEATURE_COLUMNS)


def _runs_rows(ev_all, ft_all) -> "object":
    """The deduped run family from the full event family: newest ingest
    event per run id, ``catalog.ingest_entries`` order EXACTLY (each
    run's first-appearance position breaks timestamp ties — the dict-
    insertion rule of the old per-row loop), plus each run's feature
    count so the rolling-baseline window selection never touches the
    features family.

    Whole-column pandas/NumPy ops throughout: the per-row
    ``to_dict("records")`` round trip this replaces dominated full
    index rebuilds at catalog scale.  An absent timestamp sorts as 0
    (the loop's ``or 0`` rule, now total: NaN keys previously fell
    through ``or`` into undefined float comparisons)."""
    import numpy as np
    import pandas as pd

    ing = ev_all[(ev_all["verb"] == "ingest") & (ev_all["run"] != "")]
    ing = ing.reset_index(drop=True)
    if len(ing):
        # keep-last dedup carries the newest event's values; the sort
        # key pairs (timestamp NaN->0, per-run first-appearance
        # position) — first-position is unique, so the order is total
        first_pos = pd.Series(ing.index, index=ing["run"]) \
            .groupby(level=0, sort=False).first()
        dedup = ing[~ing.duplicated("run", keep="last")]
        order = np.lexsort((
            dedup["run"].map(first_pos).to_numpy(dtype=np.int64),
            np.nan_to_num(dedup["timestamp"].to_numpy(dtype=float),
                          nan=0.0)))
        dedup = dedup.iloc[order]
    else:
        dedup = ing
    out = dedup[["run", "label", "host", "logdir",
                 "timestamp", "bytes", "files"]].copy()
    if len(ft_all):
        dd = ft_all[~ft_all.duplicated(["run", "name"], keep="last")]
        out["n_features"] = out["run"].map(
            dd["run"].value_counts()).fillna(0.0).astype(float)
    else:
        out["n_features"] = 0.0
    return _conform_family(out.reset_index(drop=True), RUNS_COLUMNS)


def _family_frame(root: str, family: str, columns: List[str]):
    """The committed family as a DataFrame (empty, schema-true, when the
    store is missing) — the incremental refresh's load half: committed
    rows LOAD from Arrow chunks, they are never re-parsed from JSON."""
    import pandas as pd

    from sofa_tpu import frames

    handle = frames.open_chunk_store(family_dir(root, family))
    if handle is None:
        return _conform_family(pd.DataFrame(columns=columns), columns)
    return _conform_family(handle.read(), columns)


def _commit_sha(family_docs: Dict[str, dict]) -> str:
    h = hashlib.sha1()
    for family in sorted(family_docs):
        doc = family_docs[family]
        h.update(f"{family}:{doc.get('rows', 0)}\n".encode())
        for c in doc.get("chunks") or []:
            h.update(f"{c.get('sha')}\n".encode())
    return h.hexdigest()


def refresh(root: str, jobs: int = 0) -> Optional[dict]:
    """Refresh (or build) the index; returns the commit doc with a
    transient ``_stats`` key, or None when pyarrow is unavailable (the
    scan path rules, stated by the caller).

    Incremental by construction: a committed, still-valid prefix is
    never re-parsed — only the appended whole-record suffix is — and the
    chunk stores' content keying means an append rewrites only each
    family's tail chunk.  An unchanged catalog returns WITHOUT touching
    any file (0 bytes parsed, untouched mtimes).  Run docs for newly
    ingested runs load on the shared ``--jobs`` pool."""
    from sofa_tpu import frames, pool

    if not available():
        return None
    state_gen = catalog.generation(root)
    commit = load_commit(root)
    cpath = catalog.catalog_path(root)
    try:
        size = os.path.getsize(cpath)
    except OSError:
        size = 0
    full = commit is None
    offset = 0 if full else int(commit.get("catalog_offset") or 0)
    if not full:
        if size < offset \
                or commit.get("catalog_gen") != state_gen \
                or catalog.head_sig(root, offset) \
                != commit.get("catalog_head_sha"):
            # rotation discipline: a compacted/rewritten catalog triggers
            # a full rebuild — never a silently stale suffix parse
            full = True
            offset = 0
    if not full:
        # the commit is the ONLY truth about what the families hold: a
        # refresh killed between a family write and the commit leaves
        # that family AHEAD of the commit, and treating its rows as the
        # committed baseline would double-append the suffix — any
        # disagreement rebuilds from byte 0 (self-healing without fsck)
        for family in FAMILIES:
            fdoc = frames._load_index(os.path.join(
                family_dir(root, family), frames.FRAME_INDEX_NAME))
            want = ((commit.get("families") or {}).get(family)
                    or {}).get("rows")
            if fdoc is None or fdoc.get("rows") != want:
                full = True
                offset = 0
                break

    from sofa_tpu.live import whole_records

    buf = _read_range(cpath, offset, size) if size > offset else b""
    consumed = whole_records(buf or b"")
    if not full and not consumed and commit is not None:
        # warm no-op: nothing new committed to the catalog (at most a
        # torn tail) — parse 0 bytes, rewrite 0 chunks, touch 0 mtimes
        return {**commit, "_stats": {"full": False, "parsed_bytes": 0,
                                     "new_events": 0, "chunks_wrote": 0}}
    new_events = _parse_events(consumed)
    new_offset = offset + len(consumed)

    # run docs for the new ingest events, loaded on the shared pool
    from sofa_tpu.archive.store import ArchiveStore

    store = ArchiveStore(root)
    new_runs = sorted({e["run"] for e in new_events
                       if e.get("ev") == "ingest"
                       and isinstance(e.get("run"), str)})
    n_jobs = pool.resolve_jobs(jobs)
    docs: Dict[str, "dict | None"] = dict(zip(new_runs, pool.thread_map(
        store.load_run, new_runs, n_jobs))) if new_runs else {}

    import pandas as pd

    ev_new = _event_rows(new_events,
                         lambda r: str((docs.get(r) or {})
                                       .get("hostname") or ""))
    ft_new = _feature_rows(new_events, docs)
    if full:
        ev_all, ft_all = ev_new, ft_new
    else:
        # committed rows LOAD from Arrow (already schema-conformed by
        # their write); only the suffix rows were built above — the
        # refresh stays O(suffix parse + column load), no re-conform
        def _grown(old, new):
            if not len(new):
                return old
            if not len(old):
                return new
            return pd.concat([old, new], ignore_index=True)

        ev_all = _grown(_family_frame(root, CATALOG_FAMILY,
                                      CATALOG_COLUMNS), ev_new)
        ft_all = _grown(_family_frame(root, FEATURES_FAMILY,
                                      FEATURE_COLUMNS), ft_new)
    runs_all = _runs_rows(ev_all, ft_all)

    family_docs: Dict[str, dict] = {}
    wrote = 0
    for family, df, cols in ((CATALOG_FAMILY, ev_all, CATALOG_COLUMNS),
                             (RUNS_FAMILY, runs_all, RUNS_COLUMNS),
                             (FEATURES_FAMILY, ft_all, FEATURE_COLUMNS)):
        _chaos_tick()
        doc = frames.write_chunk_store(df, family_dir(root, family),
                                       family, columns=cols,
                                       chunk_rows=INDEX_CHUNK_ROWS)
        wrote += int((doc.get("_stats") or {}).get("wrote", 0))
        family_docs[family] = doc

    n_ingest = int(((ev_all["verb"] == "ingest")
                    & (ev_all["run"] != "")).sum())
    out = {
        "schema": INDEX_SCHEMA, "version": INDEX_VERSION,
        "catalog_offset": int(new_offset),
        "catalog_gen": int(state_gen),
        "catalog_head_sha": catalog.head_sig(root, new_offset),
        "events": int(len(ev_all)),
        "ingest_events": n_ingest,
        "bench_events": int((ev_all["verb"] == "bench").sum()),
        "runs": int(len(runs_all)),
        "features_rows": int(len(ft_all)),
        "commit_sha": _commit_sha(family_docs),
        "families": {
            family: {"rows": int(doc.get("rows") or 0),
                     "chunks": len(doc.get("chunks") or [])}
            for family, doc in family_docs.items()},
    }
    # No wall clock on purpose: the commit is a pure function of the
    # catalog + run docs, so a killed-and-resumed refresh converges
    # byte-identical to a never-interrupted one.
    from sofa_tpu.durability import atomic_write

    with atomic_write(commit_path(root), fsync=True) as f:
        json.dump(out, f, indent=1, sort_keys=True)
    out["_stats"] = {"full": bool(full), "parsed_bytes": len(consumed),
                     "new_events": len(new_events), "chunks_wrote": wrote}
    return out


def refresh_after_ingest(root: str, jobs: int = 0) -> Optional[dict]:
    """The ingest/serve commit-point hook: refresh, degrading to a
    warning on ANY failure — the index is derived state and must never
    be able to fail the write path that feeds it."""
    try:
        return refresh(root, jobs=jobs)
    except Exception as e:  # noqa: BLE001 — derived state: degrade, never fail the ingest
        print_warning(f"archive index: refresh failed ({e}) — queries "
                      "fall back to the linear scan until the next "
                      "refresh; `sofa archive fsck --repair` rebuilds")
        return None


def drop(root: str) -> None:
    """Remove the index wholesale (fsck --repair's first half; the
    rebuild is a plain :func:`refresh`)."""
    shutil.rmtree(index_dir(root), ignore_errors=True)


def verify(root: str) -> List[str]:
    """Integrity check: re-hash every committed chunk of every family
    against their index-signed shas (frames.verify_chunk_store), and
    flag a commit manifest whose families disagree with the chunk
    stores.  Returns root-relative damage paths; [] when healthy or when
    there is simply no index."""
    from sofa_tpu import frames

    commit = load_commit(root)
    if commit is None:
        if os.path.isdir(index_dir(root)):
            return [f"{INDEX_DIR_NAME}/{INDEX_COMMIT_NAME}"]
        return []
    bad: List[str] = []
    for family in FAMILIES:
        bad.extend(frames.verify_chunk_store(
            family_dir(root, family), f"{INDEX_DIR_NAME}/{family}"))
        want = (commit.get("families") or {}).get(family) or {}
        index_doc = frames._load_index(os.path.join(
            family_dir(root, family), frames.FRAME_INDEX_NAME))
        have_rows = (index_doc or {}).get("rows")
        if index_doc is None or (want and want.get("rows") != have_rows):
            bad.append(f"{INDEX_DIR_NAME}/{family}/"
                       f"{frames.FRAME_INDEX_NAME}")
    return sorted(set(bad))


# ---------------------------------------------------------------------------
# Queries.
# ---------------------------------------------------------------------------

def _open_family(root: str, family: str, commit: "dict | None" = None):
    """(handle, commit) when the index is CURRENT, else (None, None)."""
    commit = commit if commit is not None else load_commit(root)
    if not enabled() or not is_current(root, commit):
        return None, None
    from sofa_tpu import frames

    handle = frames.open_chunk_store(family_dir(root, family))
    return (handle, commit) if handle is not None else (None, None)


def _run_record(rec: dict) -> dict:
    """One runs-family row -> the ``ingest_entries`` event shape (plus
    ``host``), NaN numerics mapped back to absent keys so the shared
    renderer prints byte-identically to the scan path."""
    e = {"ev": "ingest", "run": rec["run"],
         "t": float(rec["timestamp"]),
         "logdir": rec["logdir"], "host": rec["host"]}
    if rec["files"] == rec["files"]:          # not NaN
        e["files"] = int(rec["files"])
    if rec["bytes"] == rec["bytes"]:
        e["bytes_added"] = int(rec["bytes"])
    if rec["label"]:
        e["label"] = rec["label"]
    return e


def run_entries(root: str) -> Optional[List[dict]]:
    """The catalog's full deduped ingest sequence — ``ingest_entries``
    shape and ordering, fed from the pre-deduped runs family (None when
    the index is absent or stale; callers fall back to the scan).  Each
    entry additionally carries ``host`` (from the run doc at index
    time), so a host filter needs no doc opens."""
    handle, _commit = _open_family(root, RUNS_FAMILY)
    if handle is None:
        return None
    return [_run_record(rec) for rec in handle.read().to_dict("records")]


def run_entries_tail(root: str, limit: int,
                     host: "str | None" = None,
                     label: "str | None" = None,
                     since: "float | None" = None
                     ) -> "Optional[tuple]":
    """The newest ``limit`` filtered runs, oldest-first, touching only
    the tail chunks of the runs family that actually contain them —
    O(result), THE `ls --limit` fast path.  Returns (entries,
    total_runs, bench_events) or None when no current index."""
    handle, commit = _open_family(root, RUNS_FAMILY)
    if handle is None:
        return None
    import pandas as pd

    chunks = handle.index.get("chunks") or []
    parts: List[object] = []
    count = 0
    for i in range(len(chunks) - 1, -1, -1):
        df = handle.read_chunk(i)
        mask = pd.Series(True, index=df.index)
        if since is not None:
            mask &= df["timestamp"] >= since
        if label:
            mask &= df["label"] == label
        if host:
            mask &= df["host"] == host
        sub = df[mask]
        parts.insert(0, sub)
        count += len(sub)
        if limit and count >= limit:
            break
    rows = (pd.concat(parts, ignore_index=True) if parts
            else pd.DataFrame(columns=RUNS_COLUMNS))
    if limit:
        rows = rows.iloc[max(len(rows) - limit, 0):]
    entries = [_run_record(rec) for rec in rows.to_dict("records")]
    return entries, int(commit.get("runs") or 0), \
        int(commit.get("bench_events") or 0)


def filter_runs(runs: List[dict], host: "str | None" = None,
                label: "str | None" = None,
                since: "float | None" = None,
                limit: "int | None" = None,
                host_of: "Callable[[str], str] | None" = None
                ) -> List[dict]:
    """The one filter pipeline the scan path (and the full-index path)
    runs — identical inputs MUST yield identical `ls` output, and
    ``run_entries_tail`` applies these exact predicates vectorized.
    ``runs`` is ingest_entries-shaped, oldest first; ``limit`` keeps the
    NEWEST N (order preserved); ``host_of`` lazily resolves a run's host
    when the entries do not carry one (the scan path — this is the
    N-doc-opens cost the index exists to delete)."""
    out = []
    for e in runs:
        if since is not None and float(e.get("t", 0) or 0) < since:
            continue
        if label and (e.get("label") or "") != label:
            continue
        if host:
            h = e["host"] if "host" in e else (
                host_of(e["run"]) if host_of else "")
            if h != host:
                continue
        out.append(e)
    if limit is not None and limit > 0:
        out = out[-limit:]
    return out


def rolling_samples(root: str, rolling: int,
                    exclude_run: "str | None" = None
                    ) -> "Optional[Dict[str, List[float]]]":
    """Index-fed twin of ``baseline.rolling_samples``: per-feature sample
    lists from the newest ``rolling`` indexed runs (oldest first, the run
    under test excluded) — same selection rules, zero run-doc opens and
    O(window) chunk reads.  None when the index is absent/stale (the
    caller scans).

    Window selection walks the runs family backward (``n_features > 0``
    is the has-features rule); the feature rows then come from the
    features family's TAIL chunks — the newest feature-bearing runs'
    rows are by construction the closest to the tail, so the backward
    read stops as soon as every selected run is covered."""
    handle, commit = _open_family(root, RUNS_FAMILY)
    if handle is None:
        return None
    chunks = handle.index.get("chunks") or []
    selected: List[str] = []                 # newest first
    for i in range(len(chunks) - 1, -1, -1):
        # two projected columns per tail chunk: the window selection
        # never touches the rest of the family, let alone a run doc
        df = handle.read_chunk(i, columns=["run", "n_features"])
        sub = df[(df["n_features"] > 0) & (df["run"] != exclude_run)] \
            if exclude_run else df[df["n_features"] > 0]
        take = rolling - len(selected)
        selected.extend(reversed(sub["run"].tolist()[-take:]
                                 if take < len(sub)
                                 else sub["run"].tolist()))
        if len(selected) >= rolling:
            break
    if not selected:
        return {}
    from sofa_tpu import frames

    fhandle = frames.open_chunk_store(family_dir(root, FEATURES_FAMILY))
    if fhandle is None:
        return None
    import pandas as pd

    # phase 1: find the minimal tail-chunk range covering the window by
    # reading only the run column; phase 2: materialize exactly those
    # chunks and slice the window's rows out
    needed = set(selected)
    fchunks = fhandle.index.get("chunks") or []
    seen: set = set()
    lo = len(fchunks)
    for i in range(len(fchunks) - 1, -1, -1):
        lo = i
        seen.update(fhandle.read_chunk(i, columns=["run"])
                    ["run"].unique())
        if needed <= seen:
            break
    parts = [fhandle.read_chunk(i) for i in range(lo, len(fchunks))]
    buf = (pd.concat(parts, ignore_index=True) if parts
           else pd.DataFrame(columns=FEATURE_COLUMNS))
    if len(buf):
        buf = buf[buf["run"].isin(needed)]
        # a re-ingested run's newest rows are nearest the tail: within
        # the buffer keep-last is exactly the newest-event-wins rule
        buf = buf[~buf.duplicated(["run", "name"], keep="last")]
    # whole-column regroup (the per-row records loop this replaces was
    # the O(window * features) hot spot): a stable sort by each row's
    # window rank orders the buffer newest run first while keeping the
    # family's row order within a run, so per-name value lists reversed
    # read oldest first — exactly the nested selected/by_run loops
    rank = {run_id: i for i, run_id in enumerate(selected)}
    out: Dict[str, List[float]] = {}
    if len(buf):
        buf = buf.iloc[buf["run"].map(rank).argsort(kind="stable")]
        for name, grp in buf.groupby("name", sort=False)["value"]:
            out[name] = grp.tolist()[::-1]   # oldest first, for readers
    return out


def _runs_meta(root: str, commit: dict,
               run_ids: set) -> Dict[str, dict]:
    """Provenance rows (t, host, label, logdir) for a SET of runs —
    O(result): one projected run-column read locates the rows, then only
    the chunks that hold them materialize."""
    handle, _c = _open_family(root, RUNS_FAMILY, commit)
    if handle is None or not run_ids:
        return {}
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    # hash-join membership (pc.is_in), NOT np.isin: the run column is
    # strings, and numpy's object-dtype isin degrades to an O(rows*ids)
    # scan that dominated every fleet-pass fold at catalog scale
    vset = pa.array(sorted(run_ids))
    mask = pc.is_in(handle.read_table(columns=["run"])["run"],
                    value_set=vset)
    step = int(handle.index.get("chunk_rows") or INDEX_CHUNK_ROWS)
    hits = np.nonzero(mask.to_numpy(zero_copy_only=False))[0]
    meta: Dict[str, dict] = {}
    for ci in sorted({int(p) // step for p in hits}):
        # filter in Arrow, THEN materialize — to_pandas on the matched
        # rows only, not the whole chunk (to_pandas keeps the family's
        # null->NaN convention, so the row dicts are unchanged)
        tbl = handle.read_chunk_table(ci)
        sub = tbl.filter(pc.is_in(tbl["run"], value_set=vset))
        for rec in sub.to_pandas().to_dict("records"):
            meta[rec["run"]] = rec
    return meta


def _offender_page(root: str, pattern: str, offset: int,
                   limit: int) -> "Optional[tuple]":
    """(total, page rows) of the worst-offender ranking, index-fed —
    ordered by (-value, run, name) like the scan twin.  The whole scan
    runs as Arrow compute kernels; python objects materialize only for
    the boundary tie group and the final page."""
    import numpy as np

    handle, commit = _open_family(root, FEATURES_FAMILY)
    if handle is None:
        return None
    tbl = handle.read_table(columns=["run", "name", "value"])
    if tbl.num_rows:
        import pyarrow as pa
        import pyarrow.compute as pc

        # fnmatch the UNIQUE names (dozens), then one is_in kernel over
        # the rows — no per-row python
        names = pc.unique(tbl["name"]).to_pylist()
        keep = [n for n in names if fnmatch.fnmatchcase(n, pattern)]
        tbl = tbl.filter(pc.is_in(tbl["name"],
                                  value_set=pa.array(keep or [""])))
    if tbl.num_rows and commit.get("ingest_events") != commit.get("runs"):
        # only a catalog with re-ingested runs can carry duplicate
        # (run, name) rows — the rare path pays the pandas dedup
        df = tbl.to_pandas()
        tbl = None
        df = df[~df.duplicated(["run", "name"], keep="last")]
        vals = df["value"].to_numpy()
    else:
        df = None
        vals = (tbl["value"].to_numpy() if tbl.num_rows
                else np.empty(0))
    total = int(len(vals))
    if not total:
        return 0, []
    want = min(offset + limit, total) if limit else total
    if want and want < total:
        kth = np.partition(vals, total - want)[total - want]
        mask = vals >= kth
        cand = (df[mask] if df is not None
                else tbl.filter(mask).to_pandas())
    else:
        cand = df if df is not None else tbl.to_pandas()
    ranked = sorted(cand.to_dict("records"),
                    key=lambda r: (-r["value"], r["run"], r["name"]))
    page = ranked[offset:offset + limit] if limit else ranked[offset:]
    # join the run's provenance for the PAGE rows only — O(result)
    meta = _runs_meta(root, commit, {r["run"] for r in page})
    rows = [{"run": r["run"], "name": r["name"],
             "value": float(r["value"]),
             "t": float((meta.get(r["run"]) or {}).get("timestamp")
                        or 0.0),
             "host": (meta.get(r["run"]) or {}).get("host", ""),
             "label": (meta.get(r["run"]) or {}).get("label", ""),
             "logdir": (meta.get(r["run"]) or {}).get("logdir", "")}
            for r in page]
    return total, rows


def offenders(root: str, pattern: str = "tpu*_sol_distance",
              limit: int = 20) -> Optional[List[dict]]:
    """The fleet board's worst-offender ranking, index-fed: (run,
    feature) rows ranked by value descending — sol distance is "how far
    from the speed of light", higher is worse.  None when no current
    index (callers fall back to :func:`offenders_scan`)."""
    page = _offender_page(root, pattern, 0, limit)
    return None if page is None else page[1]


def offenders_scan(store, pattern: str = "tpu*_sol_distance",
                   limit: int = 20) -> List[dict]:
    """The linear-scan twin of :func:`offenders` — one run-doc open per
    run, O(fleet).  The fallback when no index exists, and the baseline
    tools/catalog_bench.py times the index against."""
    runs = catalog.ingest_entries(catalog.read_catalog(store.root))
    rows = []
    for e in runs:
        doc = store.load_run(e.get("run"))
        if doc is None:
            continue
        for name, value in (doc.get("features") or {}).items():
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            if not fnmatch.fnmatchcase(str(name), pattern):
                continue
            rows.append({"run": e["run"], "name": str(name),
                         "value": float(value),
                         "t": float(e.get("t", 0) or 0),
                         "host": str(doc.get("hostname") or ""),
                         "label": str(e.get("label") or ""),
                         "logdir": str(e.get("logdir") or "")})
    rows.sort(key=lambda r: (-r["value"], r["run"], r["name"]))
    return rows[:max(int(limit), 0)] if limit else rows


# ---------------------------------------------------------------------------
# The service/query surface (`/v1/<tenant>/query`, docs/FLEET.md).
# ---------------------------------------------------------------------------

#: Pagination bounds for the served query endpoint.
QUERY_DEFAULT_LIMIT = 50
QUERY_MAX_LIMIT = 500


def query(root: str, kind: str = "runs", host: "str | None" = None,
          label: "str | None" = None, since: "float | None" = None,
          feature: "str | None" = None, limit: int = QUERY_DEFAULT_LIMIT,
          offset: int = 0) -> dict:
    """The fleet query API: filter/sort/limit/since over runs and
    features, index-fed with a linear-scan fallback (``source`` states
    which answered).  Returns::

        {"kind", "total", "offset", "limit", "rows", "source",
         "commit_sha"}       # commit_sha None on the scan path

    ``kind="runs"``: newest-first deduped ingest runs, filtered by
    host/label/since.  ``kind="features"``: per-(run, feature) rows
    matched by the fnmatch ``feature`` pattern, worst value first (the
    board's offender ranking).  Pagination slices AFTER filtering, so
    ``total`` is the filtered population."""
    limit = max(1, min(int(limit or QUERY_DEFAULT_LIMIT),
                       QUERY_MAX_LIMIT))
    offset = max(int(offset or 0), 0)
    commit = load_commit(root)
    fresh = enabled() and is_current(root, commit)
    commit_sha = (commit or {}).get("commit_sha") if fresh else None

    if kind == "features":
        pattern = feature or "*"
        paged = None
        if fresh and not (host or label or since is not None):
            paged = _offender_page(root, pattern, offset, limit)
        if paged is not None:
            total, rows = paged
            return {"kind": kind, "total": total, "offset": offset,
                    "limit": limit, "rows": rows, "source": "index",
                    "commit_sha": commit_sha}
        # filtered (or index-less) ranking: the full row set is needed
        # for an honest total anyway
        rows = offenders(root, pattern=pattern, limit=0) if fresh \
            else None
        source = "index"
        if rows is None:
            from sofa_tpu.archive.store import ArchiveStore

            rows = offenders_scan(ArchiveStore(root), pattern=pattern,
                                  limit=0)
            source = "scan"
            commit_sha = None
        if host:
            rows = [r for r in rows if r.get("host") == host]
        if label:
            rows = [r for r in rows if r.get("label") == label]
        if since is not None:
            rows = [r for r in rows if r.get("t", 0) >= since]
        return {"kind": kind, "total": len(rows), "offset": offset,
                "limit": limit, "rows": rows[offset:offset + limit],
                "source": source, "commit_sha": commit_sha}

    runs = run_entries(root) if fresh else None
    source = "index"
    host_of = None
    if runs is None:
        from sofa_tpu.archive.store import ArchiveStore

        store = ArchiveStore(root)
        runs = catalog.ingest_entries(catalog.read_catalog(root))
        source = "scan"
        commit_sha = None

        def host_of(run_id):  # noqa: E306 — the scan path's doc lookup
            return str((store.load_run(run_id) or {})
                       .get("hostname") or "")

    rows = filter_runs(runs, host=host, label=label, since=since,
                       host_of=host_of)
    rows = list(reversed(rows))              # newest first for the API
    return {"kind": "runs", "total": len(rows), "offset": offset,
            "limit": limit, "rows": rows[offset:offset + limit],
            "source": source, "commit_sha": commit_sha}
