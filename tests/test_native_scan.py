"""Native columnar XPlane scan (native/xplane_scan.cc + ingest/native_scan).

The native path must be invisible except for speed: every test here pins
the pure-Python ingest as ground truth and asserts the native-assembled
frames are identical — on the REAL v5e fixture, on multi-host ingest, and
on the per-event-stats fallback that synthetic traces exercise.
"""

import os
import shutil
import time

import numpy as np
import pandas as pd
import pytest

from conftest import MARKER_UNIX_NS, add_event, add_stat
from sofa_tpu.ingest import native_scan
from sofa_tpu.ingest import xplane as xplane_mod
from sofa_tpu.ingest.xplane import ingest_xprof_dir, load_xspace

TPU_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                           "tpu_device.xplane.pb")


@pytest.fixture
def scanner():
    exe = native_scan.ensure_scanner()
    if exe is None:
        pytest.skip("no C++ toolchain for the native scanner")
    return exe


def test_scanner_matches_proto(scanner):
    """Raw scan arrays equal the proto-parsed event fields on the real
    capture — field-number/wire-format drift would show here first."""
    planes = native_scan.scan_file(TPU_FIXTURE, xplane_mod._DERIVED_STAT_KEYS)
    assert planes is not None
    xs = load_xspace(TPU_FIXTURE)
    assert [p.name for p in planes] == [p.name for p in xs.planes]
    checked_events = 0
    for sp, plane in zip(planes, xs.planes):
        assert [ln.name for ln in sp.lines] == [ln.name for ln in plane.lines]
        for sl, line in zip(sp.lines, plane.lines):
            assert sl.line_id == line.id
            assert sl.timestamp_ns == line.timestamp_ns
            assert len(sl.metadata_ids) == len(line.events)
            for i, ev in enumerate(line.events):
                assert sl.metadata_ids[i] == ev.metadata_id
                assert sl.offsets_ps[i] == ev.offset_ps
                assert sl.durations_ps[i] == ev.duration_ps
                checked_events += 1
    assert checked_events > 0


def _ingest_both_ways(xprof_dir, monkeypatch):
    native_calls = {"chunks": 0}
    real = xplane_mod._native_op_chunk

    def counting(*a, **k):
        out = real(*a, **k)
        if out is not None:
            native_calls["chunks"] += 1
        return out

    monkeypatch.setattr(xplane_mod, "_native_op_chunk", counting)
    tb = time.time() - 5
    monkeypatch.setenv("SOFA_NATIVE_SCAN", "1")
    frames_native = ingest_xprof_dir(xprof_dir, tb)
    monkeypatch.setenv("SOFA_NATIVE_SCAN", "0")
    frames_py = ingest_xprof_dir(xprof_dir, tb)
    return frames_native, frames_py, native_calls["chunks"]


def _assert_frames_equal(frames_native, frames_py):
    for key in ("tputrace", "tpumodules", "tpusteps", "hosttrace",
                "customtrace", "tpuutil"):
        pd.testing.assert_frame_equal(
            frames_native[key], frames_py[key], check_dtype=False,
            check_exact=False, rtol=1e-12, atol=1e-15, obj=key)
    assert frames_native["_meta"] == frames_py["_meta"]


def test_ingest_equivalence_real_fixture(tmp_path, monkeypatch, scanner):
    prof = tmp_path / "xprof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    shutil.copy(TPU_FIXTURE, prof / "host.xplane.pb")
    frames_native, frames_py, chunks = _ingest_both_ways(
        str(tmp_path / "xprof"), monkeypatch)
    assert chunks > 0, "native fast path never ran on the real capture"
    assert not frames_native["tputrace"].empty
    _assert_frames_equal(frames_native, frames_py)


def test_ingest_equivalence_host_plane_fixture(tmp_path, monkeypatch,
                                               scanner):
    """The host-plane fast path (marker filtering, thread lanes) against
    the real CPU capture with step annotations."""
    prof = tmp_path / "xprof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    shutil.copy(TPU_FIXTURE.replace("tpu_device", "cpu_host"),
                prof / "host.xplane.pb")
    frames_native, frames_py, _ = _ingest_both_ways(
        str(tmp_path / "xprof"), monkeypatch)
    assert not frames_native["hosttrace"].empty
    names = set(frames_native["hosttrace"]["name"])
    assert "sofa_step_0" in names
    assert not any("sofa_timebase_marker" in n for n in names)
    _assert_frames_equal(frames_native, frames_py)


def test_event_level_stats_fall_back_identically(tmp_path, monkeypatch,
                                                 scanner):
    """Synthetic traces put derived stats on the EVENT (not its metadata);
    the native scanner flags those lines and the Python loop must produce
    the frame — with per-event values honored, not the metadata cache."""
    from sofa_tpu.ingest import xplane_pb2

    xs = xplane_pb2.XSpace()
    host = xs.planes.add()
    host.name = "/host:CPU"
    hline = host.lines.add()
    hline.id = 1
    hline.name = "python"
    add_event(host, hline, f"sofa_timebase_marker:{MARKER_UNIX_NS}",
              1_000_000, 1000)
    dev = xs.planes.add()
    dev.name = "/device:TPU:0"
    oline = dev.lines.add()
    oline.name = "XLA Ops"
    # same metadata id, different per-event flops -> the metadata cache
    # alone would get event 2 wrong
    add_event(dev, oline, "%dot.1 = ...", 2_000_000, 1000,
              stats=[("flops", 111.0)])
    add_event(dev, oline, "%dot.1 = ...", 2_100_000, 1000,
              stats=[("flops", 222.0)])
    prof = tmp_path / "xprof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    (prof / "host.xplane.pb").write_bytes(xs.SerializeToString())

    frames_native, frames_py, _ = _ingest_both_ways(
        str(tmp_path / "xprof"), monkeypatch)
    _assert_frames_equal(frames_native, frames_py)
    ops = frames_native["tputrace"].sort_values("timestamp")
    assert ops["flops"].tolist() == [111.0, 222.0]


def test_scanner_fuzz_random_spaces(tmp_path, scanner):
    """Randomized XSpaces (many planes/lines, event stats, num_occurrences
    oneof, negative ids, empty names) — the wire scanner must agree with
    the proto parse on every event field, every time."""
    import random

    from sofa_tpu.ingest import xplane_pb2

    rng = random.Random(1234)
    for trial in range(6):
        xs = xplane_pb2.XSpace()
        for p in range(rng.randint(1, 4)):
            plane = xs.planes.add()
            plane.name = rng.choice(
                ["/device:TPU:0", "/host:CPU", "", "/device:CUSTOM:X",
                 "plane-é"])
            for s in range(rng.randint(0, 3)):
                sid = s + 1
                plane.stat_metadata[sid].id = sid
                plane.stat_metadata[sid].name = rng.choice(
                    ["flops", "bytes_accessed", "run_id", "x"])
            for li in range(rng.randint(0, 3)):
                line = plane.lines.add()
                line.id = rng.randint(-2, 2 ** 40)
                line.name = rng.choice(["XLA Ops", "Steps", "", "weird"])
                line.timestamp_ns = rng.randint(-5, 2 ** 50)
                for e in range(rng.randint(0, 30)):
                    ev = line.events.add()
                    ev.metadata_id = rng.randint(0, 2 ** 30)
                    if rng.random() < 0.5:
                        ev.offset_ps = rng.randint(0, 2 ** 55)
                    else:
                        ev.num_occurrences = rng.randint(0, 100)
                    ev.duration_ps = rng.randint(0, 2 ** 45)
                    for _ in range(rng.randint(0, 2)):
                        st = ev.stats.add()
                        st.metadata_id = rng.randint(0, 4)
                        st.int64_value = rng.randint(0, 100)
        path = tmp_path / f"fuzz{trial}.xplane.pb"
        path.write_bytes(xs.SerializeToString())
        planes = native_scan.scan_file(
            str(path), xplane_mod._DERIVED_STAT_KEYS)
        assert planes is not None, f"trial {trial} failed to scan"
        assert [p.name for p in planes] == [p.name for p in xs.planes]
        for sp, plane in zip(planes, xs.planes):
            derived = {mid for mid, m in plane.stat_metadata.items()
                       if m.name in xplane_mod._DERIVED_STAT_KEYS}
            for sl, line in zip(sp.lines, plane.lines):
                assert sl.line_id == line.id
                assert sl.timestamp_ns == line.timestamp_ns
                assert len(sl.metadata_ids) == len(line.events)
                for i, ev in enumerate(line.events):
                    assert sl.metadata_ids[i] == ev.metadata_id
                    assert sl.offsets_ps[i] == ev.offset_ps
                    assert sl.durations_ps[i] == ev.duration_ps
                    want_flag = bool(ev.stats) and any(
                        s.metadata_id in derived for s in ev.stats)
                    assert bool(sl.flags[i] & 1) == want_flag, (trial, i)


def test_scan_disabled_is_none(monkeypatch):
    monkeypatch.setenv("SOFA_NATIVE_SCAN", "0")
    assert native_scan.scan_file(TPU_FIXTURE, ("flops",)) is None


def test_corrupt_input_degrades(tmp_path, scanner):
    bad = tmp_path / "bad.xplane.pb"
    bad.write_bytes(b"\xff\xfe definitely not a proto" * 10)
    out = native_scan.scan_file(str(bad), ("flops",))
    assert out is None or out == []
