"""Colored console logging for sofa_tpu.

Equivalent surface to the reference's sofa_print helpers
(/root/reference/bin/sofa_print.py:18-49) — title / error / warning / info /
hint / progress banners with ANSI colors, gated on a module-level verbosity —
but implemented as a tiny logger object so library users can silence it.

Environment knobs:

  SOFA_LOG_LEVEL       debug | info | warn | error — minimum severity that
                       reaches the console (default info; debug also shows
                       print_info lines without --verbose).  Suppression is
                       display-only: warnings/errors still count into the
                       run manifest's noise counters (sofa_tpu/telemetry.py).
  SOFA_LOG_TIMESTAMPS  truthy -> prefix every line with a wall-clock
                       HH:MM:SS.mmm timestamp (fleet log correlation).
"""

from __future__ import annotations

import os
import sys
import time

_COLORS = {
    "red": "\033[1;31m",
    "green": "\033[1;32m",
    "yellow": "\033[1;33m",
    "blue": "\033[1;34m",
    "magenta": "\033[1;35m",
    "cyan": "\033[1;36m",
    "white": "\033[1;37m",
    "end": "\033[0m",
}

# Module state: whether to emit at all, and whether stdout is a tty (no color
# when piped, so test harnesses can grep plain strings).
enabled = True
verbose = False

DEBUG, INFO, WARN, ERROR = 10, 20, 30, 40
_LEVELS = {"debug": DEBUG, "info": INFO, "warn": WARN, "warning": WARN,
           "error": ERROR}


class SofaUserError(FileNotFoundError):
    """A usage error with a curated message (missing logdir, ...).

    The CLI prints these as one [ERROR] line without a traceback; any OTHER
    exception keeps its stack so bug reports stay diagnosable.  Subclasses
    FileNotFoundError so library callers' existing except clauses hold."""


def _threshold() -> int:
    """Read per call: tests and long-lived sessions may flip the env var."""
    return _LEVELS.get(
        os.environ.get("SOFA_LOG_LEVEL", "").strip().lower(), INFO)


def _timestamp() -> str:
    if os.environ.get("SOFA_LOG_TIMESTAMPS", "").lower() in ("", "0", "false"):
        return ""
    now = time.time()
    return time.strftime("%H:%M:%S", time.localtime(now)) \
        + f".{int(now * 1000) % 1000:03d} "


def _use_color(stream) -> bool:
    if os.environ.get("NO_COLOR"):
        return False
    return stream.isatty()


def _note_telemetry(level: str, msg: str) -> None:
    """Count every warning/error into the active run's manifest counters —
    BEFORE any display gating, so SOFA_LOG_LEVEL=error still records how
    noisy the run was.  Lazy import: telemetry imports this module."""
    try:
        from sofa_tpu import telemetry

        telemetry.console_event(level, msg)
    except Exception:  # noqa: BLE001 — logging must never raise
        pass


def _emit(tag: str, color: str, msg: str, stream=None,
          level: int = INFO) -> None:
    if not enabled or level < _threshold():
        return
    stream = stream or sys.stdout
    ts = _timestamp()
    if _use_color(stream):
        print(f"{ts}{_COLORS[color]}{tag}{_COLORS['end']} {msg}", file=stream)
    else:
        print(f"{ts}{tag} {msg}", file=stream)
    stream.flush()


def print_title(msg: str) -> None:
    if not enabled or INFO < _threshold():
        return
    bar = "=" * max(8, len(msg))
    if _use_color(sys.stdout):
        print(f"\n{_COLORS['cyan']}{bar}\n{msg}\n{bar}{_COLORS['end']}")
    else:
        print(f"\n{bar}\n{msg}\n{bar}")
    sys.stdout.flush()


def print_error(msg: str) -> None:
    # Errors and warnings go to stderr: stdout may be piped data
    # (features tables, report output) and must stay parseable.
    _note_telemetry("error", msg)
    _emit("[ERROR]", "red", msg, stream=sys.stderr, level=ERROR)


def print_warning(msg: str) -> None:
    _note_telemetry("warning", msg)
    _emit("[WARNING]", "yellow", msg, stream=sys.stderr, level=WARN)


def print_info(msg: str) -> None:
    if verbose or _threshold() <= DEBUG:
        _emit("[INFO]", "white", msg, level=INFO)


def print_hint(msg: str) -> None:
    _emit("[HINT]", "green", msg, level=INFO)


def print_progress(msg: str) -> None:
    _emit("[PROGRESS]", "blue", msg, level=INFO)


def print_main_progress(msg: str) -> None:
    _emit("[STAGE]", "magenta", msg, level=INFO)
