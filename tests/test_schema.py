import json

import pandas as pd
import pytest

from sofa_tpu import trace
from sofa_tpu.trace import (
    BASE_COLUMNS,
    COLUMNS,
    CopyKind,
    SofaSeries,
    classify_hlo_kind,
    downsample,
    empty_frame,
    make_frame,
    packed_ip,
    read_csv,
    series_to_report_js,
    unpack_ip,
    write_csv,
)


def test_base_schema_is_reference_compatible():
    # The 13 columns, in order (reference sofa_config.py:49-62).
    assert BASE_COLUMNS == [
        "timestamp", "event", "duration", "deviceId", "copyKind", "payload",
        "bandwidth", "pkt_src", "pkt_dst", "pid", "tid", "name", "category",
    ]


def test_make_frame_defaults_and_order():
    df = make_frame([{"timestamp": 1.5, "name": "matmul"}])
    assert list(df.columns) == COLUMNS
    assert df.loc[0, "deviceId"] == -1
    assert df.loc[0, "copyKind"] == -1
    assert df.loc[0, "name"] == "matmul"


def test_make_frame_rejects_unknown_columns():
    with pytest.raises(ValueError):
        make_frame([{"timestamp": 1.0, "bogus": 2}])


def test_csv_round_trip(tmp_path):
    df = make_frame(
        [
            {"timestamp": 0.1, "name": "a", "copyKind": int(CopyKind.ALL_REDUCE)},
            {"timestamp": 0.2, "name": "b", "payload": 4096, "bandwidth": 1e9},
        ]
    )
    p = tmp_path / "t.csv"
    write_csv(df, str(p))
    df2 = read_csv(str(p))
    assert list(df2.columns) == COLUMNS
    pd.testing.assert_frame_equal(
        df.reset_index(drop=True), df2.reset_index(drop=True), check_dtype=False
    )


def test_read_csv_fills_missing_extension_columns(tmp_path):
    # A base-13-only CSV (e.g. produced by the reference) must load cleanly.
    p = tmp_path / "old.csv"
    pd.DataFrame({c: [0] if c != "name" else ["x"] for c in BASE_COLUMNS}).to_csv(
        p, index=False
    )
    df = read_csv(str(p))
    assert df.loc[0, "device_kind"] == ""
    assert df.loc[0, "flops"] == 0.0


def test_make_frame_fills_per_row_gaps():
    """A row omitting a key that ANOTHER row provides must get the schema
    default, not NaN — NaN silently falls out of `category == 0` filters."""
    df = make_frame([
        {"timestamp": 0.0, "name": "a"},
        {"timestamp": 1.0, "name": "b", "category": 2},
    ])
    assert df["category"].tolist() == [0, 2]
    assert not df.isna().any().any()


def test_downsample():
    df = make_frame([{"timestamp": i * 0.01, "name": str(i)} for i in range(1000)])
    out = downsample(df, 100)
    assert len(out) <= 100
    assert out.iloc[0]["name"] == "0"
    assert downsample(df, 0) is df
    assert downsample(df, 2000) is df


def test_downsample_keeps_stragglers():
    """Reduction must be duration-weighted, not pure stride: a rare long op
    that falls between strides is exactly the event the user zooms to first
    on a pod-scale timeline (r3 verdict #6). 1M rows -> 10k budget, the
    single 100ms straggler and the runner-up must both survive, and the
    budget must hold."""
    import numpy as np

    n = 1_000_000
    rows = pd.DataFrame({
        "timestamp": np.arange(n) * 1e-6,
        "duration": np.full(n, 1e-7),
        "name": "op",
    })
    rows.loc[123_457, "duration"] = 0.1      # straggler OFF the stride grid
    rows.loc[777_001, "duration"] = 0.05
    out = downsample(rows, 10_000)
    assert len(out) <= 10_000
    assert 0.1 in out["duration"].values
    assert 0.05 in out["duration"].values
    # still time-ordered (iloc selection preserves original order)
    assert (np.diff(out["timestamp"].to_numpy()) > 0).all()


def test_classify_hlo_kind():
    assert classify_hlo_kind("all-reduce.1") == CopyKind.ALL_REDUCE
    assert classify_hlo_kind("all-reduce-start") == CopyKind.ALL_REDUCE
    assert classify_hlo_kind("fusion.3", "convolution") == CopyKind.KERNEL
    assert classify_hlo_kind("infeed.0") == CopyKind.H2D
    assert classify_hlo_kind("outfeed.0") == CopyKind.D2H
    assert classify_hlo_kind("collective-permute.2") == CopyKind.COLLECTIVE_PERMUTE
    assert classify_hlo_kind("copy.5") == CopyKind.D2D
    assert classify_hlo_kind("all_gather", "") == CopyKind.ALL_GATHER


def test_report_js_contract(tmp_path):
    s = SofaSeries(
        name="tpu_ops",
        title="TPU ops",
        color="purple",
        data=make_frame([{"timestamp": 1.0, "event": 2.0, "name": "fusion.1"}]),
    )
    p = tmp_path / "report.js"
    series_to_report_js([s], str(p), extra={"elapsed": 3.0})
    text = p.read_text()
    assert text.startswith("sofa_traces = ")
    doc = json.loads(text[len("sofa_traces = "):].rstrip(";\n"))
    assert doc["series"][0]["name"] == "tpu_ops"
    # columnar data contract: parallel arrays + interned name table
    data = doc["series"][0]["data"]
    assert data["x"] == [1.0]
    assert data["y"] == [2.0]
    assert data["names"][data["ni"][0]] == "fusion.1"
    assert doc["meta"]["elapsed"] == 3.0


def test_to_points_matches_columnar():
    """to_points stays the row-oriented view of the columnar payload."""
    s = SofaSeries(
        name="ops", title="ops", color="purple",
        data=make_frame([
            {"timestamp": 1.0, "event": 2.0, "name": "a", "duration": 0.5},
            {"timestamp": 2.0, "event": float("nan"), "name": "b"},
        ]),
    )
    pts = s.to_points()
    assert pts == [
        {"x": 1.0, "y": 2.0, "name": "a", "d": 0.5},
        {"x": 2.0, "y": 0.0, "name": "b", "d": 0.0},  # NaN scrubbed to 0
    ]


def test_packed_ip_round_trip():
    # Bit-compatible with the reference packing (sofa_preprocess.py:182-186).
    assert packed_ip("10.1.2.3") == 10 * 1000**3 + 1 * 1000**2 + 2 * 1000 + 3
    assert unpack_ip(packed_ip("192.168.0.254")) == "192.168.0.254"
    assert packed_ip("not.an.ip") == -1


def test_empty_frame_columns():
    assert list(empty_frame().columns) == trace.COLUMNS


def test_csv_round_trip_property(tmp_path):
    """Hypothesis: any schema frame survives write_csv -> read_csv (the
    arrow writer + the arrow-first reader added for pod-scale speed must
    agree with the schema for arbitrary content, incl. quotes/commas/
    newlines in names, extreme floats, and NaN-free defaults)."""
    import pytest

    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    names = st.text(
        st.characters(codec="utf-8",
                      exclude_characters="\x00\r",
                      exclude_categories=("Cs",)),
        min_size=0, max_size=24)
    finite = st.floats(allow_nan=False, allow_infinity=False, width=64)

    @settings(max_examples=40, deadline=None)
    @given(st.lists(
        st.fixed_dictionaries({
            "timestamp": finite,
            "duration": finite,
            "name": names,
            "module": names,
            "deviceId": st.integers(-1, 2**31 - 1),
            "payload": st.integers(0, 2**53),
            "event": finite,
        }),
        min_size=1, max_size=12))
    def run(rows):
        df = make_frame(rows)
        p = tmp_path / "prop.csv"
        write_csv(df, str(p))
        df2 = read_csv(str(p))
        assert list(df2.columns) == COLUMNS
        pd.testing.assert_frame_equal(
            df.reset_index(drop=True), df2.reset_index(drop=True),
            check_dtype=False)

    run()


def test_csv_round_trip_numeric_looking_names(tmp_path):
    """Digit-only names beside empty ones must survive reload verbatim —
    value inference once made the column float and '5' came back '5.0'."""
    df = make_frame([{"timestamp": 0.1, "name": "5"},
                     {"timestamp": 0.2, "name": ""},
                     {"timestamp": 0.3, "name": "007"}])
    p = tmp_path / "n.csv"
    write_csv(df, str(p))
    df2 = read_csv(str(p))
    assert list(df2["name"]) == ["5", "", "007"]
