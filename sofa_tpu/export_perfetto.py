"""Perfetto / Chrome-trace export of the unified timeline.

``sofa export --perfetto`` writes ``trace.json.gz`` in the Trace Event
Format, openable in ui.perfetto.dev or chrome://tracing — so a sofa
capture can ride the ecosystem's standard trace viewer in addition to the
built-in board.  The reference has no equivalent (its only interchange
formats are CSVs); this is TPU-first interop: every frame of the unified
schema maps onto Perfetto's process/thread/track model:

  process = device (tpu<N> / host / custom plane), named via metadata
  thread  = lane within the device (sync ops, async DMA, Steps, modules,
            host threads by tid)
  X events = spans (ops, steps, host events) with args carrying the
            schema's analysis columns (flops, bytes, phase, op_path, ...)
  C events = counter tracks from tpuutil (tc/mxu util %, HBM GB/s),
    tpumon (live HBM used/occupancy per device) and
            host net/cpu series

Timestamps are emitted in microseconds relative to the capture so traces
stay compact.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu.printing import print_progress, print_warning

# Stable synthetic pids per source "process" — Perfetto groups tracks by pid.
_HOST_PID = 1_000_000
_CUSTOM_PID = 1_100_000

PERFETTO_FRAMES = ["tputrace", "tpusteps", "tpumodules", "hosttrace",
                   "customtrace", "tpuutil", "tpumon", "mpstat",
                   "netbandwidth"]


# Row iteration uses itertuples for the SMALL frames; the pod-scale op
# frame gets a columnar path below (itertuples walks arrow-backed string
# cells one by one — ~12M __iter__ calls on a 1.6M-row trace — and
# per-event json.dumps dominated the export; column-wise bulk conversion +
# cached per-unique-args serialization cut the 1.6M-event export ~4x).

def _op_args(row) -> Dict[str, object]:
    args: Dict[str, object] = {}
    for key in ("hlo_category", "module", "phase", "op_path", "source"):
        v = getattr(row, key, "")
        if v:
            args[key] = str(v)
    for key in ("flops", "bytes_accessed", "payload"):
        v = getattr(row, key, 0)
        if v:
            args[key] = float(v)
    g = getattr(row, "groups", "")
    if g:
        args["replica_groups"] = str(g)
    return args


def _device_events(ops: pd.DataFrame, events: "List[dict | str]") -> None:
    import numpy as np

    n = len(ops)
    # .tolist() yields PYTHON scalars — np.float64's repr is not valid JSON
    ts = (np.nan_to_num(ops["timestamp"].to_numpy(dtype=float)) * 1e6).tolist()
    dur = (np.maximum(
        np.nan_to_num(ops["duration"].to_numpy(dtype=float)), 0.0)
        * 1e6).tolist()
    pid = ops["deviceId"].to_numpy(dtype=int).tolist()
    cat = ops["category"].to_numpy(dtype=int)
    lane = np.where(cat == 0, 0, np.where(cat == 2, 1, 2)).tolist()

    # Args are metadata-derived, so the (name, args) pair takes only a few
    # hundred distinct values in a pod-scale trace.  An EXACT vectorized
    # signature (groupby.ngroup over the arg columns, C speed, no hash
    # collisions) means only the FIRST row of each signature is ever
    # converted to Python objects; the per-row loop is one list index plus
    # one f-string.
    sig_cols = [k for k in ("name", "hlo_category", "module", "phase",
                            "op_path", "source", "flops", "bytes_accessed",
                            "payload", "groups") if k in ops.columns]
    sig_arr = ops.groupby(sig_cols, sort=False, dropna=False).ngroup() \
        .to_numpy()
    sig = sig_arr.tolist()
    uniq, firsts = np.unique(sig_arr, return_index=True)

    dumps = json.dumps
    prefix: List[str] = [""] * len(uniq)
    for s, row in zip(uniq.tolist(),
                      ops.iloc[firsts].itertuples(index=False)):
        prefix[s] = (
            f'{{"name":{dumps(str(row.name))},"ph":"X","cat":"tpu_op",'
            f'"args":{dumps(_op_args(row), separators=(",", ":"))},')
    for i in range(n):
        # pre-serialized Trace-Event line (floats via repr: valid JSON for
        # the finite python floats .tolist()/nan_to_num guarantee)
        events.append(
            f'{prefix[sig[i]]}"ts":{ts[i]!r},"dur":{dur[i]!r},'
            f'"pid":{pid[i]},"tid":{lane[i]}}}')


def _steps_events(steps: pd.DataFrame, events: List[dict]) -> None:
    for row in steps.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "step",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": int(row.deviceId), "tid": 3,
        })


def _module_events(mods: pd.DataFrame, events: List[dict]) -> None:
    for row in mods.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "xla_module",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": int(row.deviceId), "tid": 4,
        })


def _host_events(host: pd.DataFrame, events: List[dict]) -> None:
    # deviceId on host rows is the host's ordinal base (host_index*256), so
    # each host of a pod gets its own Perfetto process — thread ids from
    # different machines must never interleave on one track.
    for row in host.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "host",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": _HOST_PID + max(int(row.deviceId), 0),
            "tid": int(row.tid) & 0x7FFFFFFF,
            "args": ({"thread": row.module}
                     if getattr(row, "module", "") else {}),
        })


def _custom_events(custom: pd.DataFrame, events: List[dict],
                   plane_pids: Dict[tuple, int]) -> None:
    # One pid per (host, plane label): a runtime can emit several CUSTOM
    # planes per host and they share deviceId (the host's ordinal base).
    for row in custom.itertuples(index=False):
        key = (int(row.deviceId), getattr(row, "module", ""))
        pid = plane_pids.setdefault(key, _CUSTOM_PID + len(plane_pids))
        events.append({
            "name": row.name, "ph": "X", "cat": "custom_plane",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": pid,
            "tid": int(row.tid) & 0x7FFFFFFF,
            "args": {"plane": key[1]},
        })


def _counter_events(util: pd.DataFrame, events: List[dict]) -> None:
    for row in util.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "C", "cat": "util",
            "ts": row.timestamp * 1e6,
            "pid": int(row.deviceId),
            "args": {row.name: float(row.event)},
        })


def _host_counter_events(df: pd.DataFrame, names: List[str],
                         label: str, events: List[dict]) -> None:
    """Per-timestamp mean of a host sampler series as a Perfetto counter —
    per HOST, so a cluster export never averages one saturated machine
    against its idle neighbors.  Host identity is the `pid` column
    (stamped by load_cluster_frames; -1 = single-host capture); deviceId
    in sampler frames is the CPU-core/lane index and is deliberately
    averaged over."""
    if df.empty:
        return
    for hpid, host_rows in df.groupby("pid"):
        pid = _HOST_PID + max(int(hpid), 0) * 256
        for name in names:
            rows = host_rows[host_rows["name"] == name]
            if rows.empty:
                continue
            agg = rows.groupby("timestamp")["event"].mean()
            for ts, v in agg.items():
                events.append({
                    "name": f"{label}{name}", "ph": "C", "cat": "host_util",
                    "ts": ts * 1e6, "pid": pid,
                    "args": {f"{label}{name}": float(v)},
                })


def _meta(events: List[dict], pid: int, name: str,
          threads: Optional[Dict[int, str]] = None) -> None:
    events.append({"name": "process_name", "ph": "M", "pid": pid,
                   "args": {"name": name}})
    for tid, tname in (threads or {}).items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})


def export_perfetto(cfg, frames: Optional[Dict[str, pd.DataFrame]] = None,
                    out_name: str = "trace.json.gz") -> Optional[str]:
    """Write the Trace-Event-Format export; returns the path or None."""
    if frames is None:
        from sofa_tpu.analyze import load_frames

        frames = load_frames(cfg, only=PERFETTO_FRAMES)

    def get(name: str) -> pd.DataFrame:
        df = frames.get(name)
        return df if df is not None else pd.DataFrame()

    # device events are PRE-SERIALIZED json strings (see _device_events);
    # everything else stays a dict until the writer
    events: "List[dict | str]" = []
    ops = get("tputrace")
    if not ops.empty:
        _device_events(ops, events)
    steps = get("tpusteps")
    if not steps.empty:
        _steps_events(steps, events)
    mods = get("tpumodules")
    if not mods.empty:
        _module_events(mods, events)
    host = get("hosttrace")
    if not host.empty:
        _host_events(host, events)
    custom = get("customtrace")
    plane_pids: Dict[tuple, int] = {}
    if not custom.empty:
        _custom_events(custom, events, plane_pids)
    util = get("tpuutil")
    if not util.empty:
        _counter_events(util, events)
    # Live HBM occupancy rides the same per-device counter convention as
    # the trace-derived rates; heartbeat rows (deviceId -1) are liveness
    # bookkeeping, not a device counter.
    mon = get("tpumon")
    if not mon.empty:
        mon = mon[(mon["name"] != "alive") & (mon["deviceId"] >= 0)]
    if not mon.empty:
        _counter_events(mon, events)
    _host_counter_events(get("mpstat"), ["usr", "sys", "iow"],
                         "cpu_", events)
    net = get("netbandwidth")
    if not net.empty:
        _host_counter_events(net, sorted(set(net["name"])), "", events)
    if not events:
        print_warning("perfetto export: no trace frames — run "
                      "`sofa report` first")
        return None

    device_ids = set()
    for df in (ops, steps, mods, util, mon):
        if not df.empty:
            device_ids.update(int(d) for d in df["deviceId"].unique())
    for pid in sorted(device_ids):
        _meta(events, pid, f"tpu{pid}",
              {0: "XLA Ops (sync)", 1: "Async DMA", 3: "Steps",
               4: "XLA Modules"})
    if not host.empty:
        for base, sel in host.groupby("deviceId"):
            threads = {}
            for _, row in sel.drop_duplicates("tid").iterrows():
                threads[int(row["tid"]) & 0x7FFFFFFF] = (
                    str(row.get("module")) or f"tid {row['tid']}")
            base = max(int(base), 0)
            name = "host" if host["deviceId"].nunique() == 1 \
                else f"host{base // 256}"
            _meta(events, _HOST_PID + base, name, threads)
    for (dev, label), pid in plane_pids.items():
        _meta(events, pid, str(label or "custom plane"))

    os.makedirs(cfg.logdir, exist_ok=True)  # cluster export may precede it
    path = cfg.path(out_name)
    # Streamed write, gzip level 5, compact separators: a pod-scale trace
    # is millions of events and the default (level-9 gzip over one giant
    # json.dump string) took most of the export's wall time.
    dumps = json.dumps
    with gzip.open(path, "wt", encoding="utf-8", compresslevel=5) as f:
        f.write('{"traceEvents":[')
        # device events arrive pre-serialized (see _device_events); batch
        # ~64k per write — per-event f.write calls were ~15% of the export
        batch: List[str] = []
        wrote_any = False

        def flush():
            nonlocal wrote_any
            if not batch:
                return
            if wrote_any:
                f.write(",")
            f.write(",".join(batch))
            wrote_any = True
            batch.clear()

        for e in events:
            batch.append(e if isinstance(e, str)
                         else dumps(e, separators=(",", ":")))
            if len(batch) >= 65536:
                flush()
        flush()
        f.write('],"displayTimeUnit":"ms","otherData":')
        f.write(dumps({"producer": "sofa_tpu", "logdir": cfg.logdir}))
        f.write("}")
    print_progress(f"perfetto export: {len(events)} events -> {path} "
                   "(open in ui.perfetto.dev)")
    return path
