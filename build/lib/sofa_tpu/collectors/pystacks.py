"""In-process Python stack sampler — the pyflame analogue.

The reference shells out to pyflame (/root/reference/bin/sofa_record.py:326-333),
a tool that is long dead upstream.  Instead we sample ``sys._current_frames()``
from a daemon thread inside the profiled interpreter (delivered by the same
sitecustomize injection as the XPlane collector), which needs no ptrace
capability and works in containers.

Output format (pystacks.txt), one line per thread per tick:

    <unix_ts> <tid> <outermost;...;innermost>

where each frame is ``module.qualname``.  Parsed by
sofa_tpu/ingest/pystacks_parse.py.
"""

from __future__ import annotations

import os

# Self-contained module text written into the injection directory; it must
# not import sofa_tpu (see xprof.py for why).
_SAMPLER = '''
"""sofa_tpu in-process Python stack sampler (auto-generated)."""
import sys
import threading
import time


def _format_stack(frame):
    parts = []
    depth = 0
    while frame is not None and depth < 128:
        code = frame.f_code
        mod = frame.f_globals.get("__name__", "?")
        parts.append("%s.%s" % (mod, code.co_name))
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


def _loop(rate_hz, out_path, self_tid):
    interval = 1.0 / max(rate_hz, 1e-3)
    with open(out_path, "a", buffering=1) as out:
        while True:
            ts = time.time()
            try:
                frames = sys._current_frames()
            except Exception:
                return
            for tid, frame in frames.items():
                if tid == self_tid:
                    continue
                try:
                    out.write("%.6f %d %s\\n" % (ts, tid, _format_stack(frame)))
                except Exception:
                    return
            time.sleep(interval)


def start_sampler(rate_hz, out_path):
    # The sampler must skip its own thread; its ident is only known once the
    # thread runs, so capture it inside the target.
    def _run():
        _loop(rate_hz, out_path, threading.get_ident())

    t = threading.Thread(target=_run, daemon=True, name="sofa_tpu_pystacks")
    t.start()
    return t
'''


def write_sampler_module(inject_dir: str) -> None:
    with open(os.path.join(inject_dir, "sofa_tpu_pystacks.py"), "w") as f:
        f.write(_SAMPLER)
