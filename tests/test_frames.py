"""Chunked columnar frame store (sofa_tpu/frames.py + the trace.py shims).

Covers the tentpole contracts: chunk roundtrip/dtype stability vs
``_conform``, projection == full-load equivalence across every
registered pass, incremental append == batch-write byte identity,
content-keyed chunk reuse, time-range pushdown, the csv/parquet/columnar
format shims and stale-store shadowing, missing-pyarrow fallback to CSV,
csv-vs-columnar output byte-identity at --jobs 1 and 4, the
clean/fsck/resume interplay, and the frame_index schema contract.
"""

from __future__ import annotations

import json
import os
import shutil

import pandas as pd
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sofa_tpu import frames as framestore  # noqa: E402
from sofa_tpu.config import SofaConfig  # noqa: E402
from sofa_tpu.trace import (  # noqa: E402
    COLUMNS,
    _conform,
    make_frame,
    read_frame,
    resolve_trace_format,
    write_frame,
)

TB = 1_700_000_000.0


def _mc():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(_ROOT, "tools", "manifest_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _frame(n: int, t0: float = 0.0) -> pd.DataFrame:
    return make_frame([
        {"timestamp": t0 + i * 0.001, "event": float(i % 7),
         "duration": 1e-4, "deviceId": i % 4, "name": f"op_{i % 13}",
         "payload": i, "hlo_category": "fusion" if i % 3 else "",
         "phase": "fw" if i % 2 else "bw"}
        for i in range(n)])


def seed_raw_logdir(path) -> str:
    """A logdir with raw collector files for the tailable parsers —
    enough for a real preprocess+analyze e2e with no hardware."""
    log = os.path.join(str(path), "log") + "/"
    os.makedirs(log, exist_ok=True)
    with open(log + "sofa_time.txt", "w") as f:
        f.write(f"{TB}\n")
    with open(log + "misc.txt", "w") as f:
        f.write("elapsed_time 2.5\ncores 8\npid 1\nrc 0\n")
    rows = []
    for t in range(400):
        ts_ns = int((TB + t * 0.001) * 1e9)
        rows.append(f"{ts_ns} -1 0 0 0\n")
        for dev in range(2):
            rows.append(f"{ts_ns} {dev} {2500000000 + t * 1000} "
                        "8000000000 0\n")
    with open(log + "tpumon.txt", "w") as f:
        f.write("".join(rows))
    with open(log + "pystacks.txt", "w") as f:
        f.write("".join(
            f"{TB + i * 0.001:.6f} {1 + i % 4} main;train;step_{i % 50};k\n"
            for i in range(500)))
    return log


# --- chunk store unit contracts ---------------------------------------------

def test_chunk_roundtrip_dtype_stability(tmp_path):
    """write -> open -> read is value- AND dtype-identical to the
    in-memory frame, i.e. the exact dtypes _conform pins — the columnar
    store can never flip a column the way CSV re-inference can."""
    d = str(tmp_path) + "/"
    df = _frame(1000)
    framestore.write_frame_chunks(df, d, "t", chunk_rows=256)
    handle = framestore.open_frame(d, "t")
    got = handle.read()
    pd.testing.assert_frame_equal(got, df)
    conformed = _conform(df.copy())
    assert list(got.dtypes) == list(conformed.dtypes)
    assert list(got.columns) == COLUMNS


def test_empty_frame_store_roundtrip(tmp_path):
    d = str(tmp_path) + "/"
    from sofa_tpu.trace import empty_frame

    framestore.write_frame_chunks(empty_frame(), d, "t")
    handle = framestore.open_frame(d, "t")
    assert handle.rows == 0
    got = handle.read()
    assert got.empty and list(got.columns) == COLUMNS


def test_projection_preserves_order_and_maps_nothing_else(tmp_path):
    d = str(tmp_path) + "/"
    framestore.write_frame_chunks(_frame(500), d, "t", chunk_rows=128)
    handle = framestore.open_frame(d, "t")
    got = handle.read(columns=["name", "timestamp", "no_such_column"])
    assert list(got.columns) == ["name", "timestamp"]
    assert len(got) == 500


def test_time_range_pushdown_skips_chunks(tmp_path):
    d = str(tmp_path) + "/"
    framestore.write_frame_chunks(_frame(4096), d, "t", chunk_rows=512)
    handle = framestore.open_frame(d, "t")
    assert len(handle.index["chunks"]) == 8
    got = handle.read(columns=["name"], time_range=(0.1, 0.2))
    # rows 100..200 inclusive live in chunk 0 ([0, 0.511]) only
    assert len(got) == 101
    assert handle.chunks_read == 1
    # a range filter that needs timestamp internally must not leak it
    assert list(got.columns) == ["name"]
    full = handle.read(time_range=(0.0, 100.0))
    assert len(full) == 4096


def test_rewrite_reuses_every_chunk(tmp_path):
    d = str(tmp_path) + "/"
    doc1 = framestore.write_frame_chunks(_frame(1000), d, "t",
                                         chunk_rows=256)
    sdir = framestore.frame_dir(d, "t")
    mtimes = {f: os.path.getmtime(os.path.join(sdir, f))
              for f in os.listdir(sdir) if f.endswith(".arrow")}
    doc2 = framestore.write_frame_chunks(_frame(1000), d, "t",
                                         chunk_rows=256)
    assert doc2["_stats"]["wrote"] == 0
    assert doc2["_stats"]["reused"] == len(doc1["chunks"]) == 4
    for f, mt in mtimes.items():
        assert os.path.getmtime(os.path.join(sdir, f)) == mt, \
            f"chunk {f} was rewritten on a warm run"


def test_incremental_append_equals_batch_byte_identity(tmp_path):
    """The live-epoch contract: appends rewrite only the tail chunk, and
    the chunk files + index converge byte-identical to one batch write."""
    d1 = str(tmp_path / "inc") + "/"
    d2 = str(tmp_path / "batch") + "/"
    full = _frame(1000)
    framestore.write_frame_chunks(full.iloc[:300], d1, "t", chunk_rows=256)
    doc_a = framestore.write_frame_chunks(full.iloc[:700], d1, "t",
                                          chunk_rows=256)
    # chunk 0 (rows 0..255) was committed by the first write and reused
    assert doc_a["_stats"]["reused"] == 1
    doc_i = framestore.write_frame_chunks(full, d1, "t", chunk_rows=256)
    assert doc_i["_stats"]["reused"] == 2  # chunks 0 and 1 untouched
    doc_b = framestore.write_frame_chunks(full, d2, "t", chunk_rows=256)
    assert {k: v for k, v in doc_i.items() if k != "_stats"} \
        == {k: v for k, v in doc_b.items() if k != "_stats"}
    for c in doc_b["chunks"]:
        with open(os.path.join(framestore.frame_dir(d1, "t"),
                               c["file"]), "rb") as f:
            a = f.read()
        with open(os.path.join(framestore.frame_dir(d2, "t"),
                               c["file"]), "rb") as f:
            b = f.read()
        assert a == b, f"chunk {c['file']} diverged from the batch write"


def test_shrink_drops_stale_tail_chunks(tmp_path):
    d = str(tmp_path) + "/"
    framestore.write_frame_chunks(_frame(1000), d, "t", chunk_rows=256)
    framestore.write_frame_chunks(_frame(300), d, "t", chunk_rows=256)
    handle = framestore.open_frame(d, "t")
    assert handle.rows == 300
    files = sorted(f for f in os.listdir(framestore.frame_dir(d, "t"))
                   if f.endswith(".arrow"))
    assert files == ["000000.arrow", "000001.arrow"]
    assert len(handle.read()) == 300


def test_shrink_crash_before_index_commit_keeps_prev_generation(
        tmp_path, monkeypatch):
    """Stale tail chunks are unlinked only AFTER the index commit: a kill
    between the chunk writes and the index write must leave the previous
    committed generation fully readable (its files still on disk)."""
    import sofa_tpu.durability as durability

    d = str(tmp_path) + "/"
    full = _frame(1024)
    framestore.write_frame_chunks(full, d, "t", chunk_rows=256)

    def boom(*a, **k):
        raise OSError("simulated kill before the index commit")

    monkeypatch.setattr(durability, "atomic_write", boom)
    with pytest.raises(OSError):
        # a pure shrink to a chunk-aligned prefix: chunks 0-1 reused,
        # 2-3 stale — the only writes left are the unlinks + the index
        framestore.write_frame_chunks(full.iloc[:512], d, "t",
                                      chunk_rows=256)
    monkeypatch.undo()
    handle = framestore.open_frame(d, "t")
    assert handle.rows == 1024
    pd.testing.assert_frame_equal(handle.read(), full)


def test_reader_truncates_uncommitted_tail_rows(tmp_path):
    """The index is the commit point for ROWS too: a tail chunk file
    grown past its committed entry (an in-flight live append, or a kill
    between the tail-chunk replace and the index write) must not leak
    uncommitted rows — index.rows always agrees with what read returns."""
    import pyarrow as pa
    import pyarrow.feather as feather

    d = str(tmp_path) + "/"
    full = _frame(500)
    framestore.write_frame_chunks(full.iloc[:300], d, "t", chunk_rows=256)
    tail = os.path.join(framestore.frame_dir(d, "t"), "000001.arrow")
    feather.write_feather(
        pa.Table.from_pandas(full.iloc[256:], preserve_index=False),
        tail, compression="uncompressed")
    handle = framestore.open_frame(d, "t")
    assert handle.rows == 300
    got = handle.read()
    assert len(got) == 300
    pd.testing.assert_frame_equal(
        got, full.iloc[:300].reset_index(drop=True))


def test_all_nan_timestamp_chunk_signs_null_bounds(tmp_path):
    """All-NaN timestamps sign null (not the non-JSON NaN token) bounds,
    and an unsigned range is conservatively INCLUDED in time_range reads
    — the row-level filter stays the authority."""
    import numpy as np

    d = str(tmp_path) + "/"
    df = _frame(100)
    df["timestamp"] = np.nan
    doc = framestore.write_frame_chunks(df, d, "t", chunk_rows=64)
    with open(os.path.join(framestore.frame_dir(d, "t"),
                           framestore.FRAME_INDEX_NAME)) as f:
        raw = f.read()
    json.loads(raw, parse_constant=lambda tok: pytest.fail(
        f"non-standard JSON token {tok} in frame_index.json"))
    assert all(c["t_min"] is None and c["t_max"] is None
               for c in doc["chunks"])
    mc = _mc()
    assert mc.validate_frame_index(
        {k: v for k, v in doc.items() if k != "_stats"}) == []
    handle = framestore.open_frame(d, "t")
    got = handle.read(time_range=(0.0, 1.0))
    assert handle.chunks_read == 2  # unsigned chunks were not skipped
    assert len(got) == 0            # ...but NaN rows fail the row filter
    assert len(handle.read()) == 100


def test_verify_frame_store_and_fsck_repair(tmp_path):
    """_frames is digest-skipped, so fsck re-hashes every committed
    chunk against its index-signed sha instead; silent rot is a corrupt
    verdict and --repair drops the store wholesale (the content-keyed
    rewrite must never reuse damaged bytes)."""
    import pyarrow as pa
    import pyarrow.feather as feather

    from sofa_tpu.durability import sofa_fsck
    from sofa_tpu.preprocess import sofa_preprocess

    log = seed_raw_logdir(tmp_path)
    cfg = SofaConfig(logdir=log)
    sofa_preprocess(cfg)
    for name in framestore.frame_store_names(log):
        assert framestore.verify_frame_store(log, name) == []
    assert sofa_fsck(cfg) == 0
    name = "tpumon"  # a store that actually carries chunks
    handle = framestore.open_frame(log, name)
    c = handle.index["chunks"][0]
    rot = handle.read().iloc[:c["rows"]].copy()
    rot["payload"] = rot["payload"] + 1  # same shape, different bytes
    feather.write_feather(
        pa.Table.from_pandas(rot, preserve_index=False),
        os.path.join(framestore.frame_dir(log, name), c["file"]),
        compression="uncompressed")
    rel = f"{framestore.FRAMES_DIR_NAME}/{name}/{c['file']}"
    assert framestore.verify_frame_store(log, name) == [rel]
    assert sofa_fsck(cfg) == 1
    assert sofa_fsck(cfg, repair=True) == 0
    assert framestore.verify_frame_store(log, name) == []


def test_open_frame_absent_and_foreign_version(tmp_path):
    d = str(tmp_path) + "/"
    assert framestore.open_frame(d, "ghost") is None
    sdir = framestore.frame_dir(d, "t")
    os.makedirs(sdir)
    with open(os.path.join(sdir, framestore.FRAME_INDEX_NAME), "w") as f:
        json.dump({"schema": framestore.FRAME_INDEX_SCHEMA,
                   "version": 99, "chunks": []}, f)
    assert framestore.open_frame(d, "t") is None  # never guess a format


# --- trace.py shims ---------------------------------------------------------

def test_write_frame_format_switch_never_shadows(tmp_path):
    d = str(tmp_path) + "/"
    base = d + "t"
    df = _frame(200)
    write_frame(df, base, "columnar")
    assert framestore.open_frame(d, "t") is not None
    # a columnar store shadows a stale full CSV from an older run
    with open(base + ".csv", "w") as f:
        f.write("timestamp\n1\n")
    got = read_frame(base)
    assert len(got) == 200
    # switching to csv drops the store so the csv is authoritative again
    write_frame(df.iloc[:50], base, "csv")
    assert framestore.open_frame(d, "t") is None
    assert len(read_frame(base)) == 50
    # parquet mode likewise clears the store and wins over csv
    write_frame(df, base, "columnar")
    write_frame(df.iloc[:70], base, "parquet")
    assert framestore.open_frame(d, "t") is None
    assert len(read_frame(base)) == 70


def test_read_frame_projection_hint(tmp_path):
    d = str(tmp_path) + "/"
    write_frame(_frame(100), d + "t", "columnar")
    got = read_frame(d + "t", columns=["timestamp", "name"])
    assert list(got.columns) == ["timestamp", "name"]
    # CSV shim: reads full, projects after
    write_frame(_frame(100), d + "u", "csv")
    got = read_frame(d + "u", columns=["timestamp", "name"])
    assert list(got.columns) == ["timestamp", "name"]


def test_resolve_trace_format_env_and_fallback(tmp_path, monkeypatch):
    cfg = SofaConfig(logdir=str(tmp_path))
    assert resolve_trace_format(cfg) == "columnar"
    monkeypatch.setenv("SOFA_TRACE_FORMAT", "csv")
    assert resolve_trace_format(cfg) == "csv"
    monkeypatch.delenv("SOFA_TRACE_FORMAT")
    cfg.trace_format = "parquet"
    assert resolve_trace_format(cfg) == "parquet"
    cfg.trace_format = "bogus"
    assert resolve_trace_format(cfg) == "columnar"
    # missing pyarrow: columnar degrades to the CSV path, stated
    cfg.trace_format = ""
    monkeypatch.setattr(framestore, "columnar_available", lambda: False)
    assert resolve_trace_format(cfg) == "csv"


def test_missing_pyarrow_preprocess_falls_back_to_full_csv(tmp_path,
                                                           monkeypatch):
    from sofa_tpu.preprocess import sofa_preprocess

    log = seed_raw_logdir(tmp_path)
    monkeypatch.setattr(framestore, "columnar_available", lambda: False)
    cfg = SofaConfig(logdir=log, viz_downsample_to=5)
    frames = sofa_preprocess(cfg)
    assert not os.path.isdir(cfg.path(framestore.FRAMES_DIR_NAME))
    # the CSV is FULL fidelity on the fallback path, not a viz copy
    assert len(read_frame(cfg.path("tpumon"))) == len(frames["tpumon"]) > 5


# --- preprocess/analyze e2e -------------------------------------------------

def test_preprocess_columnar_default_and_warm_reuse(tmp_path):
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.telemetry import load_manifest

    log = seed_raw_logdir(tmp_path)
    cfg = SofaConfig(logdir=log, viz_downsample_to=50)
    frames = sofa_preprocess(cfg)
    handle = framestore.open_frame(log, "tpumon")
    assert handle is not None
    pd.testing.assert_frame_equal(handle.read(), frames["tpumon"])
    # the board's viz CSV sits beside the store, downsampled
    viz = pd.read_csv(cfg.path("tpumon.csv"))
    assert len(viz) <= 50 < handle.rows
    meta = ((load_manifest(log) or {}).get("meta") or {}).get("frames")
    assert meta and meta["format"] == "columnar"
    assert _mc().validate_manifest(load_manifest(log)) == []
    # warm rerun: the ingest cache serves frames, the store reuses chunks
    sofa_preprocess(cfg)
    meta2 = ((load_manifest(log) or {}).get("meta") or {}).get("frames")
    assert meta2["reused"] == meta2["chunks"] > 0
    assert _mc()._check_frame_indexes(log) == []


def test_csv_and_columnar_outputs_byte_identical(tmp_path):
    """The interchange-format swap is proven by equivalence: features.csv
    and report.js are byte-identical between --trace_format csv and
    columnar, at --jobs 1 and --jobs 4."""
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_clean

    log = seed_raw_logdir(tmp_path)
    want = {}
    for jobs in (1, 4):
        for fmt in ("csv", "columnar"):
            cfg = SofaConfig(logdir=log, trace_format=fmt, jobs=jobs,
                             viz_downsample_to=100)
            sofa_analyze(cfg, frames=sofa_preprocess(cfg))
            for rel in ("features.csv", "report.js"):
                with open(cfg.path(rel), "rb") as f:
                    data = f.read()
                if rel in want:
                    assert data == want[rel], \
                        f"{rel} diverged (fmt={fmt}, jobs={jobs})"
                else:
                    want[rel] = data
            sofa_clean(cfg)


def test_jobs_determinism_of_chunk_bytes(tmp_path):
    from sofa_tpu.preprocess import sofa_preprocess

    logs = {}
    for jobs in (1, 4):
        log = seed_raw_logdir(tmp_path / f"j{jobs}")
        sofa_preprocess(SofaConfig(logdir=log, jobs=jobs))
        logs[jobs] = log
    for name in framestore.frame_store_names(logs[1]):
        sdir1 = framestore.frame_dir(logs[1], name)
        sdir4 = framestore.frame_dir(logs[4], name)
        files1 = sorted(os.listdir(sdir1))
        assert files1 == sorted(os.listdir(sdir4)), name
        for f in files1:
            with open(os.path.join(sdir1, f), "rb") as fh:
                a = fh.read()
            with open(os.path.join(sdir4, f), "rb") as fh:
                b = fh.read()
            assert a == b, f"{name}/{f} differs between --jobs 1 and 4"


def test_registry_projection_equals_full_load_per_pass(tmp_path):
    """For every registered pass: features computed from the lazy
    projection-pushdown handles equal features computed from eager
    full-width frames — the declared reads_columns contracts are honest
    under real materialization, not just under SL010's static check."""
    from sofa_tpu.analysis import registry
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.analyze import load_frames, open_frames
    from sofa_tpu.preprocess import sofa_preprocess

    log = seed_raw_logdir(tmp_path)
    cfg = SofaConfig(logdir=log)
    sofa_preprocess(cfg)
    registry.load_builtin_passes()
    eager = load_frames(cfg)
    lazy = open_frames(cfg)
    handles = [v for v in lazy.values()
               if isinstance(v, framestore.FrameHandle)]
    assert handles, "no frame opened lazily from the columnar store"

    f_eager, f_lazy = Features(), Features()
    rep_e, _ = registry.run_passes(eager, cfg, f_eager, jobs=1)
    rep_l, _ = registry.run_passes(lazy, cfg, f_lazy, jobs=1)
    assert [s for s, e in rep_e["passes"].items()
            if e.get("status") == "failed"] == []
    assert rep_e["passes"].keys() == rep_l["passes"].keys()
    for name, ent in rep_l["passes"].items():
        assert ent.get("status") != "failed", (name, ent.get("error"))
    pd.testing.assert_frame_equal(f_lazy.to_frame(), f_eager.to_frame())
    # and the projection actually engaged: some handle served a read
    assert any(h.chunks_read > 0 for h in handles)


def test_undeclared_frame_read_fails_loudly_not_silently(tmp_path):
    """A pass touching a frame outside its declared reads_frames gets
    the lazy handle, not silently empty data: the violation surfaces as
    that pass's failed status while analyze continues."""
    from sofa_tpu.analysis import registry
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.analyze import open_frames
    from sofa_tpu.preprocess import sofa_preprocess

    log = seed_raw_logdir(tmp_path)
    cfg = SofaConfig(logdir=log)
    sofa_preprocess(cfg)
    with registry.scoped():
        registry.clear()

        def dishonest(frames, cfg_, features):
            return float(frames["tpumon"]["event"].sum())  # undeclared!

        registry.register_pass(dishonest, name="chaos_dishonest",
                               reads_frames=("pystacks",),
                               reads_columns=("timestamp",))
        report, _ = registry.run_passes(open_frames(cfg), cfg,
                                        Features(), jobs=1)
    ent = report["passes"]["chaos_dishonest"]
    assert ent["status"] == "failed"


# --- clean / fsck / resume interplay ----------------------------------------

def test_clean_fsck_resume_interplay(tmp_path):
    from sofa_tpu.durability import JOURNAL_NAME, sofa_fsck, sofa_resume
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_clean

    log = seed_raw_logdir(tmp_path)
    cfg = SofaConfig(logdir=log)
    sofa_preprocess(cfg)
    assert sofa_fsck(cfg) == 0  # _frames is digest-skip: no fsck noise
    with open(cfg.path("report.js"), "rb") as f:
        want = f.read()
    # crash one instruction before the commit: resume replays and
    # converges (warm caches + chunk reuse make it cheap)
    with open(cfg.path(JOURNAL_NAME)) as f:
        lines = [ln for ln in f.read().splitlines()
                 if '"commit"' not in ln or '"preprocess"' not in ln]
    with open(cfg.path(JOURNAL_NAME), "w") as f:
        f.write("\n".join(lines) + "\n")
    assert sofa_resume(cfg) == 0
    with open(cfg.path("report.js"), "rb") as f:
        assert f.read() == want
    assert sofa_fsck(cfg) == 0
    sofa_clean(cfg)
    assert not os.path.isdir(cfg.path(framestore.FRAMES_DIR_NAME))
    assert not os.path.isfile(cfg.path("tpumon.csv"))
    assert os.path.isfile(cfg.path("tpumon.txt"))  # raw stays


# --- live interplay ---------------------------------------------------------

def test_live_epoch_writes_chunk_store_and_drain_converges(tmp_path):
    from sofa_tpu.live import sofa_live
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_clean

    log = seed_raw_logdir(tmp_path)
    with open(log + "tpumon.txt", "rb") as f:
        raw = f.read().splitlines(keepends=True)
    ctrl = SofaConfig(logdir=log)
    sofa_preprocess(ctrl)
    batch = framestore.open_frame(log, "tpumon").read()
    sofa_clean(ctrl)

    with open(log + "tpumon.txt", "wb") as f:
        f.write(b"".join(raw[:len(raw) // 2]))
    cfg = SofaConfig(logdir=log, live_interval_s=0.0, live_stall_s=0.0)
    assert sofa_live(cfg, epochs=1) == 0
    h1 = framestore.open_frame(log, "tpumon")
    assert h1 is not None and 0 < h1.rows < len(batch)
    with open(log + "tpumon.txt", "ab") as f:
        f.write(b"".join(raw[len(raw) // 2:]))
    assert sofa_live(cfg, epochs=1) == 0
    h2 = framestore.open_frame(log, "tpumon")
    pd.testing.assert_frame_equal(h2.read(), batch)


def test_live_columnar_degrade_keeps_full_fidelity_csv(tmp_path,
                                                       monkeypatch):
    """When the per-frame columnar write degrades to CSV, the live
    writer must NOT overwrite that full-fidelity CSV with the
    downsampled viz copy — the degraded CSV is the frame's only
    artifact (preprocess._write_one's early return, mirrored)."""
    from sofa_tpu.live import _write_frame_atomic

    d = str(tmp_path) + "/"
    df = _frame(500)

    def refuse(*a, **k):
        raise RuntimeError("simulated arrow conversion failure")

    monkeypatch.setattr(framestore, "write_frame_chunks", refuse)
    cfg = SofaConfig(logdir=d, viz_downsample_to=10)
    _write_frame_atomic(df, d + "t", cfg, fmt="columnar")
    assert framestore.open_frame(d, "t") is None
    got = read_frame(d + "t")
    assert len(got) == 500  # full fidelity, not the 10-row viz copy


# --- frame_index schema contract --------------------------------------------

def test_frame_index_schema_validates(tmp_path):
    mc = _mc()
    d = str(tmp_path) + "/"
    doc = framestore.write_frame_chunks(_frame(700), d, "t",
                                        chunk_rows=256)
    clean = {k: v for k, v in doc.items() if k != "_stats"}
    assert mc.validate_frame_index(clean) == []
    assert mc._check_frame_indexes(d) == []
    for mutate, frag in (
            (lambda x: x.update(schema="wrong"), "schema"),
            (lambda x: x.update(version=2), "version"),
            (lambda x: x.update(rows=1), "disagrees"),
            (lambda x: x["chunks"][0].update(rows=5), "chunk_rows"),
            (lambda x: x.pop("chunks"), "chunks"),
    ):
        bad = json.loads(json.dumps(clean))
        mutate(bad)
        probs = mc.validate_frame_index(bad)
        assert probs and any(frag in p for p in probs), (frag, probs)


def test_sofa_passes_renders_column_footprint(tmp_path, capsys):
    from sofa_tpu.analysis.registry import sofa_passes

    cfg = SofaConfig(logdir=str(tmp_path))
    assert sofa_passes(cfg) == 0
    out = capsys.readouterr().out
    assert "column footprint:" in out
    assert f"/{len(COLUMNS)}" in out


_RSS_GEN = r"""
import sys
import numpy as np
import pandas as pd
sys.path.insert(0, sys.argv[3])
from sofa_tpu import frames as framestore
from sofa_tpu.trace import make_frame, write_csv

d, n = sys.argv[1], int(sys.argv[2])
names = np.array([f"fused_computation_{i}.clone" for i in range(512)])
paths = np.array([f"jit(train)/transpose(jvp(main))/dot_{i}" for i in range(256)])
idx = np.arange(n)
df = make_frame({
    "timestamp": idx * 1e-6,
    "event": (idx % 701).astype(float),
    "duration": np.full(n, 1e-6),
    "deviceId": idx % 8,
    "payload": idx % 4096,
    "name": pd.Series(names[idx % 512]),
    "op_path": pd.Series(paths[idx % 256]),
    "hlo_category": pd.Series(np.array(["fusion", "convolution",
                                        "all-reduce", ""])[idx % 4]),
    "flops": (idx % 1000) * 1e6,
    "bytes_accessed": (idx % 1000) * 1e3,
})
framestore.write_frame_chunks(df, d, "tputrace")
write_csv(df, d + "tputrace.csv.full")
"""

_RSS_COLUMNAR = r"""
import resource, sys
sys.path.insert(0, sys.argv[2])
from sofa_tpu.analysis import registry
from sofa_tpu.analysis.features import Features
from sofa_tpu.analyze import open_frames
from sofa_tpu.config import SofaConfig

cfg = SofaConfig(logdir=sys.argv[1])
registry.load_builtin_passes()
frames = open_frames(cfg)
assert frames["tputrace"].rows == int(sys.argv[3])
select = {"tpu_profile", "op_tree_profile", "comm_profile",
          "roofline_profile", "sol_roofline"}
report, _ = registry.run_passes(frames, cfg, Features(), jobs=1,
                                select=select)
failed = [n for n, e in report["passes"].items()
          if e.get("status") == "failed"]
assert not failed, failed
print(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024)
"""

_RSS_CSV = r"""
import resource, sys
sys.path.insert(0, sys.argv[2])
from sofa_tpu.trace import read_csv

df = read_csv(sys.argv[1])
assert len(df) == int(sys.argv[3])
print(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024)
"""


@pytest.mark.slow
def test_ten_million_event_analyze_bounded_rss(tmp_path):
    """The out-of-core acceptance proof: a synthetic 10^7-event tputrace
    runs the heavy tputrace passes under a bounded peak RSS through the
    projection-pushdown path (each pass sees only its declared columns'
    mapped slices), while a full-frame CSV materialization of the same
    trace exceeds the bound."""
    import subprocess
    import sys as _sys

    n = 10_000_000
    # Measured on this container: projected analyze peaks ~3.3 GB (the
    # 11-column tpu_profile slice + groupby transients), full-frame CSV
    # materialization alone ~6.4 GB — the bound sits between with >25 %
    # margin each side.
    bound_mb = 4500
    d = str(tmp_path / "big") + "/"
    os.makedirs(d)
    with open(d + "sofa_time.txt", "w") as f:
        f.write(f"{TB}\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([_sys.executable, "-c", _RSS_GEN, d, str(n), _ROOT],
                   check=True, timeout=900, env=env)
    r = subprocess.run([_sys.executable, "-c", _RSS_COLUMNAR, d, _ROOT,
                        str(n)], capture_output=True, text=True,
                       timeout=900, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    col_rss = int(r.stdout.strip().splitlines()[-1])
    r = subprocess.run([_sys.executable, "-c", _RSS_CSV,
                        d + "tputrace.csv.full", _ROOT, str(n)],
                       capture_output=True, text=True, timeout=900,
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    csv_rss = int(r.stdout.strip().splitlines()[-1])
    assert col_rss < bound_mb, \
        f"projected analyze peaked at {col_rss} MB (bound {bound_mb})"
    assert csv_rss > bound_mb, \
        f"CSV materialization peaked at only {csv_rss} MB — the bound " \
        "no longer separates the paths; tighten it"


def test_materialize_helper(tmp_path):
    d = str(tmp_path) + "/"
    framestore.write_frame_chunks(_frame(50), d, "t")
    handle = framestore.open_frame(d, "t")
    got = framestore.materialize(handle, ["name"])
    assert list(got.columns) == ["name"]
    df = _frame(5)
    assert framestore.materialize(df, ["name"]) is df  # eager untouched
