"""The ``sofa`` command line.

Eight subcommands with the same verbs and composition rules as the reference
CLI (/root/reference/bin/sofa:328-376):

  record "cmd"      collect raw traces into logdir
  preprocess        raw files -> unified-schema CSVs + report.js
  analyze           CSVs -> features, hints, reports
  viz               serve the board GUI over logdir
  report            [preprocess] + analyze [+ --with-gui viz]
  stat "cmd"        record + preprocess + analyze
  diff              preprocess base/match logdirs + swarm diff
  export            static sofa_report.pdf/overview.png for headless sharing
  top               live terminal dashboard over a running recording
  status            render logdir/run_manifest.json (the pipeline's own
                    health ledger, sofa_tpu/telemetry.py) as a table;
                    exits nonzero on failed collectors
  lint              AST invariant checker for sofa_tpu's own contracts
                    (sofa_tpu/lint/, docs/STATIC_ANALYSIS.md); exits 1 on
                    findings not grandfathered in lint_baseline.json;
                    --rule SLxxx[,SLyyy] filters, --explain SLxxx prints
                    the rule's catalog row, --jobs fans out per-file
  artifacts         artifact-lifecycle inventory (sofa_tpu/artifacts.py):
                    every artifact -> writers/readers/clean/digest/fsck/
                    manifest_check coverage from the statically-extracted
                    flow graph SL014-SL018 enforce; optional logdir audit;
                    --json emits schema sofa_tpu/artifact_inventory
                    (exit 2 on closure violations)
  protocol          client<->server protocol inventory (sofa_tpu/
                    protocol.py): every fleet-tier route -> statuses ->
                    typed error bodies -> Retry-After discipline ->
                    client dispatch, plus the fault-kind grammar and the
                    SOFA_* env-knob registry, from the statically-
                    extracted graph SL024-SL028 enforce; --json emits
                    schema sofa_tpu/protocol_inventory (exit 2 on
                    closure violations)
  passes            render the analysis-pass registry (sofa_tpu/analysis/
                    registry.py): the resolved dependency DAG, each pass's
                    declared contract, and — when logdir holds a manifest —
                    the last run's per-pass timings/statuses; exits 2 on an
                    unschedulable graph
  whatif            hardware-free what-if replay over a recorded logdir
                    (sofa_tpu/whatif/): re-time the step timeline under
                    --apply scenarios and report predicted step time with
                    calibrated error bars; exits 1 when the zero-scenario
                    identity gate fails (uncalibrated)
  resume            replay the crash journal's uncommitted suffix after a
                    killed verb (sofa_tpu/durability.py): committed work
                    is served from the content-keyed caches, the rest
                    re-runs
  fsck              verify the logdir's sha256 integrity ledger; --repair
                    invalidates poisoned cache/tile entries and re-derives
                    (exit 0 healthy / 1 damage / 2 no ledger)
  serve             fleet archive service (sofa_tpu/archive/service.py):
                    token-authenticated idempotent chunked-upload ingest
                    over a multi-tenant archive root, with quotas and
                    503/429 backpressure; `sofa agent` pushes into it
  agent             per-host fleet daemon (sofa_tpu/agent.py): watch a
                    directory for finished runs, spool them into a
                    durable local archive, and forward to a `sofa serve`
                    endpoint with bounded timeouts + jittered backoff;
                    --once runs a single scan+drain pass
  live              crash-tolerant streaming profiling (sofa_tpu/live.py):
                    epoch loop tailing every raw source from a per-source
                    byte offset in the fsync'd _live_offsets.json ledger —
                    torn tails back off to the last whole record, committed
                    chunks never re-parse, only dirty tiles rebuild, and
                    registry passes re-run incrementally on the dirty
                    window; --drain converges byte-identical to a batch
                    preprocess+analyze (docs/LIVE.md)
  clean             remove derived files, keep raw collector output
  setup             host-enablement doctor (sysctls, tool caps) — replaces
                    the reference's empower.py / enable_strace_perf_pcm.py

Flags are declared once and materialized onto a SofaConfig dataclass
(sofa_tpu/config.py) rather than the reference's field-by-field copy
(bin/sofa:159-326).
"""

from __future__ import annotations

import argparse
import os
import sys

from sofa_tpu import __version__
from sofa_tpu.config import Filter, SofaConfig
from sofa_tpu.plugins import load_plugins
from sofa_tpu import printing
from sofa_tpu.printing import SofaUserError
from sofa_tpu.printing import print_error, print_main_progress


def build_parser() -> argparse.ArgumentParser:
    # Every optional flag defaults to argparse.SUPPRESS: an attribute exists on
    # the parsed namespace ONLY if the user actually typed the flag.  Config
    # resolution is then a clean two-layer overlay — SofaConfig defaults (or
    # the TOML file) below, explicitly-typed CLI flags on top — with no
    # "flag set to its default value" ambiguity.
    S = argparse.SUPPRESS
    p = argparse.ArgumentParser(
        prog="sofa",
        argument_default=S,
        description="sofa_tpu: TPU-native cross-layer profiler "
        "(record / preprocess / analyze / viz).",
    )
    p.add_argument("--version", action="version", version=f"sofa_tpu {__version__}")
    p.add_argument("command", choices=[
        "record", "preprocess", "analyze", "report", "stat", "diff", "viz",
        "export", "top", "status", "lint", "passes", "clean", "setup",
        "resume", "fsck", "archive", "regress", "whatif", "artifacts",
        "protocol", "serve", "agent", "live", "fleet",
    ])
    p.add_argument("usr_command", nargs="?", default="",
                   help="command to profile (record/stat); logdir "
                        "(status/resume/fsck/passes/whatif/artifacts/live); "
                        "path to lint (lint); logdir or ls/show/gc/fsck "
                        "(archive); run (regress); archive root (serve); "
                        "watch directory (agent); analyze (fleet)")
    p.add_argument("extra", nargs="?", default="",
                   help="second positional: the run id for `archive show`, "
                        "the baseline run for `regress`, the archive root "
                        "for `archive backup`/`archive restore` and "
                        "`fleet analyze`")
    p.add_argument("extra2", nargs="?", default="",
                   help="third positional: the destination for `archive "
                        "backup`, the restore target for `archive restore`")

    g = p.add_argument_group("pipeline")
    g.add_argument("--logdir")
    g.add_argument("--config", default=None, help="TOML config file; explicit CLI flags override it")
    g.add_argument("--verbose", action="store_true")
    g.add_argument("--skip_preprocess", action="store_true")
    g.add_argument("--jobs", type=int,
                   help="worker count for the pipeline pools (ingest, "
                        "frame IO, per-host cluster analysis); 0 = auto "
                        "from cpu count")
    g.add_argument("--no_ingest_cache", action="store_true",
                   help="bypass the content-keyed ingest cache "
                        "(always reparse raw collector files)")
    g.add_argument("--with-gui", dest="with_gui", action="store_true", default=False,
                   help="serve the board after `report`")
    g.add_argument("--perfetto", action="store_true", default=False,
                   help="`export` also writes trace.json.gz "
                        "(Trace Event Format, opens in ui.perfetto.dev)")
    g.add_argument("--folded", action="store_true", default=False,
                   help="`export` also writes *.folded collapsed stacks "
                        "(speedscope.app / flamegraph.pl)")
    g.add_argument("--interval", type=float, default=2.0,
                   help="`top` refresh period in seconds")
    g.add_argument("--once", action="store_true", default=False,
                   help="`top` renders one frame and exits; `agent` runs "
                        "one scan+drain pass and exits (0 = everything "
                        "delivered, 1 = spooled but undelivered)")

    g = p.add_argument_group("record: host")
    g.add_argument("--perf_events")
    g.add_argument("--no-perf-events", dest="no_perf_events", action="store_true")
    g.add_argument("--cpu_sample_rate", type=int)
    g.add_argument("--perf_call_graph", choices=["off", "fp", "dwarf"])
    g.add_argument("--sys_mon_rate", type=int)
    g.add_argument("--enable_strace", action="store_true")
    g.add_argument("--strace_min_time", type=float)
    g.add_argument("--enable_py_stacks", action="store_true")
    g.add_argument("--enable_tcpdump", action="store_true")
    g.add_argument("--netstat_interface")
    g.add_argument("--blkdev")
    g.add_argument("--pid", type=int, help="attach to a running pid instead of launching")

    g = p.add_argument_group("record: tpu")
    g.add_argument("--disable_xprof", action="store_true")
    g.add_argument("--xprof_host_tracer_level", type=int)
    g.add_argument("--xprof_python_tracer", action="store_true")
    g.add_argument("--xprof_delay_s", type=float)
    g.add_argument("--xprof_duration_s", type=float)
    g.add_argument("--tpu_mon_rate", type=int)
    g.add_argument("--disable_tpu_mon", action="store_true")
    g.add_argument("--disable_memprof", action="store_true",
                   help="skip the peak-HBM allocation-site snapshot")
    g.add_argument("--epilogue_deadline_s", type=float,
                   help="seconds past the child's atexit trace-stop "
                        "breadcrumb before record presumes it wedged and "
                        "kills its process group (default: derived from "
                        "the in-child stop timeouts)")

    g = p.add_argument_group("record: fault tolerance")
    g.add_argument("--inject_faults",
                   help="fault-injection spec, e.g. 'procmon:die@2s,"
                        "tcpdump:wedge@stop,pcap:corrupt' (SOFA_FAULTS env "
                        "equivalent; see docs/ROBUSTNESS.md)")
    g.add_argument("--collector_restarts", type=int,
                   help="restart budget for a collector that dies mid-run "
                        "(default 1; 0 disables restarts)")
    g.add_argument("--collector_stop_timeout_s", type=float,
                   help="per-collector stop deadline in seconds — a wedged "
                        "flush degrades that series instead of hanging "
                        "record (default 15; 0 = unbounded)")
    g.add_argument("--collector_harvest_timeout_s", type=float,
                   help="per-collector harvest deadline in seconds "
                        "(default 120; 0 = unbounded)")
    g.add_argument("--disk_budget", type=float, dest="disk_budget_mb",
                   help="total raw-output disk budget in MB across all "
                        "collectors: the supervisor rotates oldest output "
                        "files (or truncates the worst offender, manifest "
                        "status truncated_by_budget) instead of letting an "
                        "unbounded collector fill the disk (0 = unlimited)")
    g.add_argument("--collector_disk_budget", type=float,
                   dest="collector_disk_budget_mb",
                   help="per-collector raw-output disk budget in MB "
                        "(0 = unlimited)")

    g = p.add_argument_group("preprocess")
    g.add_argument("--cpu_time_offset_ms", type=int)
    g.add_argument("--tpu_time_offset_ms", type=float,
                   help="shift device/XPlane timestamps by this many ms when "
                        "automatic marker/timebase alignment is wrong")
    g.add_argument("--viz_downsample_to", type=int)
    g.add_argument("--tile_levels", type=int,
                   help="cap the LOD tile-pyramid depth (0 = auto: deepen "
                        "until every leaf tile is exact)")
    g.add_argument("--no_tiles", action="store_true",
                   help="skip the timeline tile pyramid (board serves the "
                        "downsampled overview only; deep zoom loses "
                        "event fidelity)")
    g.add_argument("--trace_format", choices=["csv", "parquet", "columnar"],
                   help="frame interchange format (default columnar: the "
                        "chunked memory-mapped _frames/ store, "
                        "docs/FRAMES.md; SOFA_TRACE_FORMAT env equivalent; "
                        "csv retained for foreign-logdir compat)")
    g.add_argument("--network_filters", help="comma-joined ip filters")
    g.add_argument("--cpu_filters", help="comma-joined keyword:color specs")
    g.add_argument("--tpu_filters", help="comma-joined keyword:color specs")

    g = p.add_argument_group("analyze")
    g.add_argument("--num_iterations", type=int)
    g.add_argument("--num_swarms", type=int)
    g.add_argument("--enable_aisi", action="store_true")
    g.add_argument("--enable_hsg", action="store_true")
    g.add_argument("--enable_swarms", action="store_true")
    g.add_argument("--is_idle_threshold", type=float)
    g.add_argument("--profile_region", help='manual ROI "begin:end" seconds')
    g.add_argument("--spotlight", action="store_true", help="auto-ROI from TPU utilization")
    g.add_argument("--hint_server", help="gRPC advice service host:port")
    g.add_argument("--iterations_from",
                   choices=["auto", "steps", "marker", "module", "op"])

    g = p.add_argument_group("diff")
    g.add_argument("--base_logdir")
    g.add_argument("--match_logdir")

    g = p.add_argument_group("live")
    g.add_argument("--live_interval_s", type=float,
                   help="live: seconds between streaming epochs "
                        "(default 2)")
    g.add_argument("--live_epochs", type=int,
                   help="live: run exactly N epochs then exit "
                        "(0 = until interrupted)")
    g.add_argument("--live_stall_s", type=float,
                   help="live: a source that stops growing for this long "
                        "while siblings stream degrades to `stalled` "
                        "(default 30; 0 = never)")
    g.add_argument("--drain", action="store_true", default=False,
                   help="live: after the epoch loop ends (or immediately "
                        "with --live_epochs 0), run a full batch "
                        "preprocess+analyze so every artifact converges "
                        "byte-identical to a never-interrupted batch run")

    g = p.add_argument_group("fsck")
    g.add_argument("--repair", action="store_true", default=False,
                   help="fsck: invalidate the poisoned cache/tile entries, "
                        "sweep orphans, and re-derive damaged artifacts "
                        "(on an archive root: re-adopt uncataloged runs, "
                        "restore/quarantine rotted objects)")

    g = p.add_argument_group("archive / regress")
    g.add_argument("--archive_root",
                   help="multi-run trace archive root (SOFA_ARCHIVE_ROOT "
                        "env equivalent; default ./sofa_archive)")
    g.add_argument("--label", dest="archive_label",
                   help="archive: free-form tag stored with the ingested "
                        "run")
    g.add_argument("--keep", type=int, dest="archive_keep",
                   help="archive gc: keep the newest N runs")
    g.add_argument("--keep_days", type=float, dest="archive_keep_days",
                   help="archive gc: keep runs ingested within D days")
    g.add_argument("--limit", type=int, dest="archive_limit",
                   help="archive ls: show only the newest N runs "
                        "(index-fed when the columnar catalog index is "
                        "current — docs/ARCHIVE.md)")
    g.add_argument("--since", dest="archive_since",
                   help="archive ls: only runs ingested since (a unix "
                        "timestamp, or relative like 7d / 12h / 30m)")
    g.add_argument("--host", dest="archive_host",
                   help="archive ls: only runs ingested from this host")
    g.add_argument("--rolling", type=int, dest="regress_rolling",
                   help="regress: compare against a rolling baseline over "
                        "the newest N archived runs instead of a second "
                        "run argument")
    g.add_argument("--pct", type=float, dest="regress_pct",
                   help="regress --rolling: baseline percentile "
                        "(default 50 = median)")
    g.add_argument("--regress_threshold", type=float,
                   help="relative %% move a regressed/improved verdict "
                        "requires (default 10)")

    g = p.add_argument_group("fleet (serve / agent)")
    g.add_argument("--serve_bind", help="serve: bind address (default "
                                        "127.0.0.1; 0.0.0.0 opens it)")
    g.add_argument("--serve_port", type=int,
                   help="serve: base port (default 8044; 0 = OS-assigned)")
    g.add_argument("--token", dest="serve_token",
                   help="shared bearer token for serve AND agent "
                        "(SOFA_SERVE_TOKEN env equivalent; serve refuses "
                        "to start without one)")
    g.add_argument("--quota_mb", type=float, dest="serve_quota_mb",
                   help="serve: per-tenant object-store quota in MB "
                        "(0 = unlimited; breaches answer 429 and agents "
                        "fall back to their spool)")
    g.add_argument("--max_inflight", type=int, dest="serve_max_inflight",
                   help="serve: concurrent write requests before 503 + "
                        "Retry-After backpressure (default 8)")
    g.add_argument("--workers", type=int, dest="serve_workers",
                   help="serve: pool worker processes sharing the port "
                        "via SO_REUSEPORT (dispatcher fallback); tenants "
                        "consistent-hash-sharded across them (default 1)")
    g.add_argument("--replica-of", "--replica_of", dest="serve_replica_of",
                   metavar="URL",
                   help="serve: run as a read-only query replica of this "
                        "primary — pulls immutable index commits, serves "
                        "/v1/query with honest staleness headers")
    g.add_argument("--slo", dest="serve_slo", metavar="SPEC",
                   help="serve: declared service-level objectives, e.g. "
                        "'push_p99_ms<50,wal_depth<1000,replica_behind<3' "
                        "— evaluated per scrape window into a typed "
                        "slo_verdict; breaches hit the catalog and "
                        "`sofa status --fleet` exits nonzero "
                        "(docs/FLEET.md)")
    g.add_argument("--fleet", dest="status_fleet", metavar="URL",
                   help="status: render the live tier topology from this "
                        "service's /v1/tier endpoint instead of a logdir "
                        "(comma-join URLs for failover)")
    g.add_argument("--rolling-restart", "--rolling_restart",
                   dest="serve_rolling_restart", action="store_true",
                   default=False,
                   help="serve: signal the running supervisor for this root "
                        "to restart its workers one at a time (ring handoff, "
                        "zero acked-push loss) and exit")
    g.add_argument("--tenant", dest="fleet_tenant",
                   help="agent: tenant namespace to push into "
                        "(default 'default')")
    g.add_argument("--service", dest="agent_service",
                   help="agent: fleet service URL, e.g. "
                        "http://collector:8044 (SOFA_AGENT_SERVICE env; "
                        "empty = spool-only mode; comma-join URLs for "
                        "client-side failover with /v1/health probes)")
    g.add_argument("--spool", dest="agent_spool",
                   help="agent: durable spool root (SOFA_AGENT_SPOOL env; "
                        "default ./sofa_spool)")
    g.add_argument("--poll_s", type=float, dest="agent_poll_s",
                   help="agent: watch-scan period in seconds (default 5)")
    g.add_argument("--settle_s", type=float, dest="agent_settle_s",
                   help="agent: a logdir must be quiet this long to count "
                        "as finished (default 0.5)")
    g.add_argument("--push_timeout_s", type=float, dest="agent_timeout_s",
                   help="agent: per-request transport deadline (default 10)")
    g.add_argument("--push_retries", type=int, dest="agent_retries",
                   help="agent: per-operation retry budget (default 4)")
    g.add_argument("--push_backoff_s", type=float, dest="agent_backoff_s",
                   help="agent: retry backoff base, jittered (default 0.5)")
    g.add_argument("--push_backoff_cap_s", type=float,
                   dest="agent_backoff_cap_s",
                   help="agent: retry backoff cap (default 30)")

    g = p.add_argument_group("viz")
    g.add_argument("--viz_port", type=int)
    g.add_argument("--viz_bind", help='bind address (default 127.0.0.1; '
                                      'use 0.0.0.0 to serve remotely)')

    g = p.add_argument_group("cluster")
    g.add_argument("--cluster_hosts", help="comma-joined host list for multi-host runs")

    g = p.add_argument_group("setup")
    # ONE --apply flag, two verbs: `sofa setup --apply` (bare: run the fix
    # commands) and `sofa whatif <logdir> --apply <scenarios>` (valued:
    # comma-joined scenario specs, docs/WHATIF.md — unknown scenarios
    # degrade, never abort).
    g.add_argument("--apply", nargs="?", const=True, default=False,
                   metavar="SCENARIOS",
                   help="setup: run the fix commands instead of printing "
                        "them; whatif: comma-joined scenarios to replay, "
                        "e.g. 'overlap:all-reduce,scale:fusion=sol,link:2'")
    g.add_argument("--empower", action="append", dest="empower", default=None,
                   help="setup: utility to grant profiling capabilities "
                        "(e.g. --empower tcpdump); repeatable")
    g.add_argument("--no-device-probe", dest="no_device_probe",
                   action="store_true",
                   help="setup: skip the bounded device-backend health "
                        "probe (host-only checks)")

    g = p.add_argument_group("lint")
    g.add_argument("--rule", dest="lint_rule", metavar="SLxxx[,SLyyy]",
                   help="lint: only report these rule id(s)")
    g.add_argument("--explain", dest="lint_explain", metavar="SLxxx",
                   help="lint: print the rule's catalog row and exit")

    p.add_argument("--json", action="store_true", dest="as_json",
                   default=False,
                   help="artifacts/protocol: machine-readable inventory "
                        "on stdout (schema sofa_tpu/artifact_inventory "
                        "or sofa_tpu/protocol_inventory, validated by "
                        "tools/manifest_check.py)")
    p.add_argument("--plugin", action="append", dest="plugins",
                   help="module[:func] called with the config at startup")
    return p


def config_from_args(args: argparse.Namespace) -> SofaConfig:
    cfg = SofaConfig.from_toml(args.config) if args.config else SofaConfig()
    passed = vars(args)

    def was_set(name: str) -> bool:
        return name in passed

    # Flags that map 1:1 onto SofaConfig fields.
    for name in (
        "logdir", "verbose", "skip_preprocess", "jobs",
        "perf_events", "no_perf_events", "cpu_sample_rate", "perf_call_graph",
        "sys_mon_rate",
        "enable_strace", "strace_min_time", "enable_py_stacks", "enable_tcpdump",
        "netstat_interface", "blkdev", "pid",
        "xprof_host_tracer_level", "xprof_python_tracer", "xprof_delay_s",
        "xprof_duration_s", "tpu_mon_rate", "epilogue_deadline_s",
        "inject_faults", "collector_restarts", "collector_stop_timeout_s",
        "collector_harvest_timeout_s", "disk_budget_mb",
        "collector_disk_budget_mb",
        "cpu_time_offset_ms", "tpu_time_offset_ms", "viz_downsample_to",
        "tile_levels", "trace_format",
        "num_iterations", "num_swarms", "enable_aisi", "enable_hsg",
        "enable_swarms", "is_idle_threshold", "profile_region", "spotlight",
        "hint_server", "iterations_from",
        "base_logdir", "match_logdir", "viz_port", "viz_bind", "plugins",
        "archive_root", "archive_label", "archive_keep", "archive_keep_days",
        "archive_limit", "archive_since", "archive_host",
        "regress_rolling", "regress_pct", "regress_threshold",
        "live_interval_s", "live_epochs", "live_stall_s",
        "serve_bind", "serve_port", "serve_token", "serve_quota_mb",
        "serve_max_inflight", "serve_workers", "serve_replica_of",
        "serve_slo", "serve_rolling_restart",
        "status_fleet", "fleet_tenant", "agent_service",
        "agent_spool", "agent_poll_s", "agent_settle_s", "agent_timeout_s",
        "agent_retries", "agent_backoff_s", "agent_backoff_cap_s",
    ):
        if was_set(name):
            setattr(cfg, name, passed[name])
    if isinstance(passed.get("apply"), str):
        cfg.whatif_apply = passed["apply"]
    if was_set("no_ingest_cache"):
        cfg.ingest_cache = not passed["no_ingest_cache"]
    if was_set("no_tiles"):
        cfg.enable_tiles = not passed["no_tiles"]
    if was_set("disable_xprof"):
        cfg.enable_xprof = not passed["disable_xprof"]
    if was_set("disable_tpu_mon"):
        cfg.enable_tpu_mon = not passed["disable_tpu_mon"]
    if was_set("disable_memprof"):
        cfg.enable_mem_prof = not passed["disable_memprof"]
    if was_set("network_filters"):
        cfg.network_filters = [s for s in passed["network_filters"].split(",") if s]
    if was_set("cpu_filters"):
        cfg.cpu_filters = [Filter.parse(s) for s in passed["cpu_filters"].split(",") if s]
    if was_set("tpu_filters"):
        cfg.tpu_filters = [Filter.parse(s) for s in passed["tpu_filters"].split(",") if s]
    if was_set("cluster_hosts"):
        cfg.cluster_hosts = [s for s in passed["cluster_hosts"].split(",") if s]
    if args.usr_command:
        cfg.command = args.usr_command
    cfg.__post_init__()
    return cfg


def main(argv=None) -> int:
    rc = _run(argv)
    # Flush INSIDE the pipe guard: output smaller than the block buffer
    # would otherwise first hit a dead pipe in the interpreter's exit
    # flush, where no handler can catch it (exit status 120 + "Exception
    # ignored" noise).  The work already finished — rc stands.
    try:
        sys.stdout.flush()
    except BrokenPipeError:
        _stdout_to_devnull()
    return rc


def _stdout_to_devnull() -> None:
    """Neutralize further writes so the exit flush can't re-raise EPIPE."""
    try:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    except OSError:
        pass


def _run(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        cfg = config_from_args(args)
    except (ValueError, OSError) as e:
        print_error(f"bad configuration: {e}")
        return 2
    printing.verbose = cfg.verbose
    load_plugins(cfg)

    cmd = args.command
    try:
        if cmd == "record":
            if not cfg.command and cfg.pid is None:
                print_error('record needs a command: sofa record "python train.py"')
                return 2
            from sofa_tpu.record import cluster_record, sofa_record
            print_main_progress("SOFA record")
            if cfg.cluster_hosts:
                return cluster_record(cfg.command, cfg)
            return sofa_record(cfg.command, cfg)
        if cmd == "preprocess":
            from sofa_tpu.preprocess import sofa_preprocess
            print_main_progress("SOFA preprocess")
            sofa_preprocess(cfg)
            return 0
        if cmd == "analyze":
            from sofa_tpu.analyze import sofa_analyze
            print_main_progress("SOFA analyze")
            sofa_analyze(cfg)
            return 0
        if cmd == "report":
            from sofa_tpu.analyze import sofa_analyze, cluster_analyze
            from sofa_tpu.preprocess import sofa_preprocess
            print_main_progress("SOFA report")
            if cfg.cluster_hosts:
                from sofa_tpu.analyze import cluster_host_cfgs
                preloaded = {}
                for _i, host, host_cfg in cluster_host_cfgs(cfg):
                    if not cfg.skip_preprocess and \
                            os.path.isdir(host_cfg.logdir):
                        preloaded[host] = sofa_preprocess(host_cfg)
                cluster_analyze(cfg, preloaded=preloaded or None)
            else:
                # hand the preprocessed frames straight to analyze — at pod
                # scale re-reading the CSVs written one line earlier costs
                # ~25% of the whole report wall-time
                frames = (sofa_preprocess(cfg)
                          if not cfg.skip_preprocess else None)
                sofa_analyze(cfg, frames=frames)
                frames = None  # don't pin pod-scale frames under the GUI
            if args.with_gui:
                from sofa_tpu.viz import sofa_viz
                sofa_viz(cfg)
            return 0
        if cmd == "export":
            from sofa_tpu.export_static import STATIC_FRAMES, export_static
            print_main_progress("SOFA export")
            wanted = set(STATIC_FRAMES)
            if args.perfetto:
                from sofa_tpu.export_perfetto import (
                    PERFETTO_FRAMES, export_perfetto)
                wanted |= set(PERFETTO_FRAMES)
            if args.folded:
                from sofa_tpu.export_folded import (
                    FOLDED_FRAMES, export_folded)
                wanted |= set(FOLDED_FRAMES)
            if args.perfetto or args.folded or cfg.cluster_hosts:
                # One deserialization pass for every exporter — tputrace is
                # the pod-scale frame; reading it twice is real money.
                # --cluster_hosts merges every host's frames onto the
                # cluster clock first, so one trace/PDF spans the pod.
                from sofa_tpu.analyze import load_cluster_frames, load_frames
                frames = (load_cluster_frames(cfg, only=sorted(wanted))
                          if cfg.cluster_hosts
                          else load_frames(cfg, only=sorted(wanted)))
                # Exit contract: an EXPLICITLY flagged artifact failing is
                # an error; the implicit static charts contribute success
                # but (e.g. matplotlib not installed) must not fail a run
                # whose requested artifacts all landed.  Folded stacks stay
                # soft — legitimately absent when no stack sampler ran.
                wrote_any = bool(export_static(cfg, frames))
                failed_explicit = False
                if args.perfetto:
                    p_ok = bool(export_perfetto(cfg, frames))
                    wrote_any |= p_ok
                    failed_explicit |= not p_ok
                if args.folded:
                    wrote_any |= bool(export_folded(cfg, frames))
                return 0 if wrote_any and not failed_explicit else 1
            return 0 if export_static(cfg) else 1
        if cmd == "top":
            from sofa_tpu.top import sofa_top
            return sofa_top(cfg, interval=args.interval, once=args.once)
        if cmd == "stat":
            if not cfg.command:
                print_error('stat needs a command: sofa stat "python train.py"')
                return 2
            from sofa_tpu.analyze import sofa_analyze
            from sofa_tpu.preprocess import sofa_preprocess
            from sofa_tpu.record import sofa_record
            print_main_progress("SOFA stat = record + preprocess + analyze")
            rc = sofa_record(cfg.command, cfg)
            # A failed workload still leaves traces worth analyzing; report
            # anyway but surface the child's rc as our exit status.
            sofa_analyze(cfg, frames=sofa_preprocess(cfg))
            return rc
        if cmd == "diff":
            if not (cfg.base_logdir and cfg.match_logdir):
                print_error("diff needs --base_logdir and --match_logdir")
                return 2
            from sofa_tpu.ml.diff import sofa_diff
            print_main_progress("SOFA diff")
            return sofa_diff(cfg)
        if cmd == "viz":
            from sofa_tpu.viz import sofa_viz
            print_main_progress("SOFA viz")
            sofa_viz(cfg)
            return 0
        if cmd == "status" and getattr(cfg, "status_fleet", ""):
            # the tier topology lives on the service, not in a logdir —
            # no manifest load, no logdir resolution
            from sofa_tpu.archive.tier import sofa_fleet_status
            print_main_progress("SOFA status")
            return sofa_fleet_status(cfg)
        if cmd in ("status", "resume", "fsck", "passes", "whatif", "live"):
            if args.usr_command and "logdir" not in vars(args):
                # `sofa status sofalog/` reads more naturally than
                # --logdir for a logdir-only verb; an explicit flag wins.
                cfg.logdir = args.usr_command
                cfg.__post_init__()
            if cmd == "live":
                from sofa_tpu.live import sofa_live
                print_main_progress("SOFA live")
                return sofa_live(cfg, drain=args.drain)
            if cmd == "status":
                from sofa_tpu.telemetry import sofa_status
                return sofa_status(cfg)
            if cmd == "passes":
                from sofa_tpu.analysis.registry import sofa_passes
                return sofa_passes(cfg)
            if cmd == "whatif":
                from sofa_tpu.whatif import sofa_whatif
                print_main_progress("SOFA whatif")
                return sofa_whatif(cfg)
            if cmd == "resume":
                from sofa_tpu.durability import sofa_resume
                print_main_progress("SOFA resume")
                return sofa_resume(cfg)
            from sofa_tpu.durability import sofa_fsck
            print_main_progress("SOFA fsck")
            return sofa_fsck(cfg, repair=args.repair)
        if cmd == "archive":
            from sofa_tpu.archive.store import sofa_archive
            print_main_progress("SOFA archive")
            return sofa_archive(cfg, args.usr_command, args.extra,
                                args.extra2, repair=args.repair)
        if cmd == "serve":
            from sofa_tpu.archive.service import sofa_serve
            print_main_progress("SOFA serve")
            return sofa_serve(cfg, root=args.usr_command or None)
        if cmd == "fleet":
            from sofa_tpu.analysis.fleet import sofa_fleet
            print_main_progress("SOFA fleet")
            return sofa_fleet(cfg, args.usr_command, args.extra)
        if cmd == "agent":
            from sofa_tpu.agent import sofa_agent
            print_main_progress("SOFA agent")
            return sofa_agent(cfg, watch=args.usr_command or None,
                              once=args.once)
        if cmd == "regress":
            from sofa_tpu.archive.verdict import sofa_regress
            print_main_progress("SOFA regress")
            return sofa_regress(cfg, args.usr_command, args.extra)
        if cmd == "lint":
            from sofa_tpu.lint.cli import run_lint
            # lint is config-free: the positional argument is a path, and
            # the nested parser owns the exit-code contract (0/1/2).
            argv = [args.usr_command] if args.usr_command else []
            if getattr(args, "lint_rule", None):
                argv += ["--rule", args.lint_rule]
            if getattr(args, "lint_explain", None):
                argv += ["--explain", args.lint_explain]
            if "jobs" in vars(args):
                argv += ["--jobs", str(vars(args)["jobs"])]
            return run_lint(argv)
        if cmd == "artifacts":
            from sofa_tpu.artifacts import sofa_artifacts
            # config-free like lint: the positional is an optional logdir
            # to audit against the extracted graph.
            return sofa_artifacts(logdir=args.usr_command or None,
                                  as_json=args.as_json)
        if cmd == "protocol":
            from sofa_tpu.protocol import sofa_protocol
            # config-free like artifacts: the inventory is a property of
            # the shipped tree, not of any logdir.
            return sofa_protocol(as_json=args.as_json)
        if cmd == "clean":
            from sofa_tpu.record import sofa_clean
            sofa_clean(cfg)
            return 0
        if cmd == "setup":
            from sofa_tpu.setup_env import sofa_setup
            print_main_progress("SOFA setup")
            return sofa_setup(utilities=args.empower, apply=bool(args.apply),
                              probe_device=not getattr(
                                  args, "no_device_probe", False))
    except KeyboardInterrupt:
        print_error("interrupted")
        return 130
    except SofaUserError as e:
        # Curated guard raises only (missing logdir, ...): one clean line.
        # A plain FileNotFoundError from deeper code keeps its traceback —
        # that's a bug report, not a usage error.
        print_error(str(e))
        return 1
    except BrokenPipeError:
        # `sofa <anything> | head` closing our stdout mid-print is normal
        # pipeline behavior — but for subcommands whose product is files
        # on disk, the break also aborted the remaining work, so only the
        # streaming commands may report success.
        _stdout_to_devnull()
        return 0 if cmd in ("top", "viz") else 1
    print_error(f"unknown command {cmd!r}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
