"""``sofa live`` — crash-tolerant streaming profiling with resumable ingest.

Every other verb is batch: nothing is visible until record finishes and
analyze writes report.js.  This verb turns the pipeline into an epoch
loop over a GROWING logdir — each tick tails every raw collector file
from a per-source byte offset, folds only the new whole records in, and
refreshes the board's artifacts, so the timeline and ``[sol]``/
``[whatif]`` hints update while the job runs ("Enhancing Performance
Insight at Scale", PAPERS.md: always-on streaming diagnostics).

Robustness is the spine, not a feature (docs/LIVE.md failure matrix):

* **Offset ledger** — ``<logdir>/_live_offsets.json`` (schema
  ``sofa_tpu/live_offsets`` v1) is the epoch's commit point: per-source
  committed byte offsets, chunk table, head signature, and stall clocks,
  written fsync'd tmp+rename LAST in the epoch.  A SIGKILL at any instant
  leaves either the old ledger (the epoch replays, byte-identically) or
  the new one (the epoch committed) — never a half-state.
* **Torn tails** — the tailer consumes new bytes only up to the last
  whole record (``\\n`` boundary, the ``_journal.jsonl`` torn-tail
  discipline applied to collector outputs); a partially flushed final
  record waits for the next tick.  Garbage is never parsed.
* **Chunk-granular cache** — each committed ``[start, end)`` byte range
  parses exactly once (ingest/cache.ChunkStore); later epochs and crash
  replays LOAD the stored frame.  The ``chunks_parsed``/``chunks_loaded``
  counters in ``meta.live`` are the no-reparse proof.
* **Rotation** — a shrunken file or changed head signature (and the
  injected ``<source>:rotate`` fault) resets the source to byte 0 and
  drops its chunks; the other sources keep streaming.
* **Stalled sources** — a source that stops growing past
  ``--live_stall_s`` while siblings stream degrades to ``stalled`` in
  ``meta.live`` (supervisor.GrowthWatermark — the watchdog's
  output-stall discipline); ``manifest_check --require-healthy`` treats
  it as unhealthy.
* **Convergence** — ``sofa live --drain`` (or a plain batch
  ``sofa preprocess`` + ``analyze``) over the final logdir produces
  output byte-identical to a never-interrupted batch run: live tile
  indexes carry no batch content key, so the drain rebuilds them from
  scratch through the exact batch path.

Derived writes inside an epoch are all atomic (tmp+rename), so the viz
server serves the last committed generation mid-epoch instead of 503ing
for the whole run — the ``derived_write_guard`` sentinel is for batch
verbs whose CSVs stream non-atomically.

Incrementality is contract-driven: registry passes re-run only when
their declared ``reads_frames`` (or a feature they read, transitively)
touches a frame that changed this epoch (analysis/registry.
select_for_dirty); tile pyramids rebuild only the tiles whose window
intersects the dirty suffix (tiles.build_tiles_live).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu import faults, pool
from sofa_tpu.config import SofaConfig
from sofa_tpu.printing import print_progress, print_warning

OFFSETS_NAME = "_live_offsets.json"
OFFSETS_SCHEMA = "sofa_tpu/live_offsets"
OFFSETS_VERSION = 1

#: Bytes of the file head signed per source: a different head under the
#: same path is a rotated file, not an append.
_HEAD_SIG_BYTES = 256

#: Committed chunks per source before they compact into one (a pure
#: load+store merge — no reparse), bounding the per-epoch concat fan-in
#: the way journal compaction bounds replay length.
CHUNK_COMPACT_COUNT = 64

#: Per-source live statuses surfaced in ``meta.live.sources``.
LIVE_SOURCE_STATUSES = ("streaming", "idle", "stalled", "rotated",
                        "torn", "absent")


def _tail_parsers(cfg: SofaConfig):
    """The tailable-source table: (source, raw file, chunk parser).

    Only parsers whose output is a pure per-record function of the input
    text qualify — parse(whole file) must equal concat(parse(chunk_i))
    at record boundaries, which is what makes the chunk cache sound.
    Delta/stateful parsers (mpstat's jiffy differencing, vmstat's tick
    counter, blktrace's D→C pairing, perf's MHz interpolation, pcap,
    xplane) stay on the whole-source content-keyed rescan path instead —
    their files either are tiny samplers or rewrite history anyway."""
    from sofa_tpu.ingest import procfs, strace_parse
    from sofa_tpu.ingest.tpumon_parse import parse_tpumon

    def p_strace(text, tb):
        return strace_parse.parse_strace(text, time_base=tb,
                                         min_time=cfg.strace_min_time)

    def p_pystacks(text, tb):
        return strace_parse.parse_pystacks(text, time_base=tb)

    def p_tpumon(text, tb):
        return parse_tpumon(text, tb)

    def p_cpuinfo(text, tb):
        return procfs.parse_cpuinfo(text, time_base=tb)

    return [
        ("strace", "strace.txt", p_strace),
        ("pystacks", "pystacks.txt", p_pystacks),
        ("tpumon", "tpumon.txt", p_tpumon),
        ("cpuinfo", "cpuinfo.txt", p_cpuinfo),
    ]


#: Source names the chunk tailer owns (everything else reaches frames
#: through preprocess._run_ingest's content-keyed rescan path).
TAILABLE_SOURCES = ("strace", "pystacks", "tpumon", "cpuinfo")


# ---------------------------------------------------------------------------
# The offset ledger.
# ---------------------------------------------------------------------------

class OffsetLedger:
    """The fsync'd per-source byte/record offset ledger — THE commit
    point of a live epoch.  Everything in it is re-derivable from the
    raw files; losing it costs a reparse, never data."""

    def __init__(self, logdir: str):
        self.path = os.path.join(logdir, OFFSETS_NAME)
        self.doc: dict = {
            "schema": OFFSETS_SCHEMA, "version": OFFSETS_VERSION,
            "epoch": 0, "updated_unix": 0.0, "time_base": None,
            "watermark_s": None, "sources": {}, "growth": {},
            "features_rows": 0,
        }

    @classmethod
    def load(cls, logdir: str) -> "OffsetLedger":
        ledger = cls(logdir)
        try:
            with open(ledger.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return ledger
        if not isinstance(doc, dict) or doc.get("schema") != OFFSETS_SCHEMA \
                or doc.get("version") != OFFSETS_VERSION:
            print_warning(f"live: {OFFSETS_NAME} is not a v{OFFSETS_VERSION}"
                          " offset ledger — starting from byte 0")
            return ledger
        ledger.doc.update(doc)
        return ledger

    def source(self, name: str) -> dict:
        return self.doc["sources"].setdefault(
            name, {"offset": 0, "chunks": [], "head_sha": None,
                   "events": 0})

    def reset_source(self, name: str) -> dict:
        self.doc["sources"][name] = {"offset": 0, "chunks": [],
                                     "head_sha": None, "events": 0}
        return self.doc["sources"][name]

    def commit(self) -> None:
        from sofa_tpu.durability import atomic_write

        self.doc["updated_unix"] = round(time.time(), 3)
        try:
            with atomic_write(self.path, fsync=True) as f:
                json.dump(self.doc, f, indent=1, sort_keys=True)
        except OSError as e:
            print_warning(f"live: cannot write {self.path}: {e} — the "
                          "next epoch re-tails this one's bytes")


# ---------------------------------------------------------------------------
# The tailer.
# ---------------------------------------------------------------------------

def _head_sig(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            return hashlib.sha1(f.read(_HEAD_SIG_BYTES)).hexdigest()
    except OSError:
        return None


def _read_range(path: str, start: int, end: int) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            f.seek(start)
            return f.read(max(end - start, 0))
    except OSError:
        return None


def whole_records(buf: bytes) -> bytes:
    """Torn-tail backoff: the prefix of ``buf`` ending at the last
    newline — a partially flushed final record is never parsed (the
    ``_journal.jsonl`` discipline applied to collector outputs)."""
    idx = buf.rfind(b"\n")
    return buf[:idx + 1] if idx >= 0 else b""


class _TailOutcome:
    """One source's epoch result: its assembled frame + the meta.live
    row + whether anything changed."""

    def __init__(self, source: str):
        self.source = source
        self.frame: Optional[pd.DataFrame] = None
        self.dirty = False
        self.info: dict = {"status": "idle", "offset": 0, "lag_bytes": 0,
                           "chunks": 0, "chunks_parsed": 0,
                           "chunks_loaded": 0, "events": 0}


def _tail_source(cfg: SofaConfig, ledger: OffsetLedger, chunks,
                 source: str, raw: str, parser, time_base: float,
                 epoch: int, watermark) -> _TailOutcome:
    """One epoch's tail of one source: detect rotation, back off the torn
    tail, parse exactly the new whole records, and assemble the source's
    cumulative frame from committed chunk frames (loads, not parses)."""
    from sofa_tpu.trace import _conform, empty_frame

    out = _TailOutcome(source)
    path = cfg.path(raw)
    entry = ledger.source(source)
    spec = faults.maybe_stream_fault(source, epoch)
    try:
        size = os.path.getsize(path)
    except OSError:
        size = -1
    if size < 0 and not entry["chunks"]:
        out.info["status"] = "absent"
        out.frame = empty_frame()
        return out

    rotated = False
    if size >= 0:
        head = _head_sig(path)
        if spec is not None and spec.kind == "rotate":
            rotated = True
        elif size < entry["offset"]:
            rotated = True  # the file shrank: this is not the same stream
        elif entry["head_sha"] and head and entry["head_sha"] != head \
                and entry["offset"] > 0:
            rotated = True  # same name, different bytes at the head
        if rotated:
            print_warning(f"live: {raw} rotated — re-ingesting {source} "
                          "from byte 0 (committed chunks dropped)")
            chunks.drop(source)
            entry = ledger.reset_source(source)
            entry["head_sha"] = head
            out.info["status"] = "rotated"
            out.dirty = True
        elif entry["head_sha"] is None and head is not None:
            entry["head_sha"] = head

    stalled_fault = spec is not None and spec.kind == "stall"
    start = int(entry["offset"])
    end = size if size >= 0 else start
    if stalled_fault:
        end = start  # the source freezes this epoch, deterministically
    elif spec is not None and spec.kind == "tail_truncate":
        end = start + (end - start) // 2
    new_rows = 0
    if end > start:
        buf = _read_range(path, start, end)
        if buf:
            if spec is not None and spec.kind == "tail_torn":
                buf = buf[:-min(7, len(buf))]  # cut mid-record
            consumed = whole_records(buf)
            if consumed:
                t0 = time.perf_counter()
                try:
                    df = parser(consumed.decode("utf-8",
                                                errors="replace"),
                                time_base)
                except Exception as e:  # noqa: BLE001 — per-source degradation, like batch ingest
                    print_warning(f"live: {source} chunk parse failed "
                                  f"({e}) — the chunk stays unconsumed")
                    df = None
                if df is not None:
                    cend = start + len(consumed)
                    chunks.store(source, start, cend, df)
                    entry["chunks"].append([start, cend, int(len(df))])
                    entry["offset"] = cend
                    entry["events"] = int(entry.get("events", 0)
                                          + len(df))
                    new_rows = len(df)
                    out.dirty = True
                    out.info["chunks_parsed"] += 1
                    out.info["parse_wall_s"] = round(
                        time.perf_counter() - t0, 6)
            elif buf:
                out.info["status"] = "torn"

    # assemble the cumulative frame: committed chunks LOAD, never parse
    parts: List[pd.DataFrame] = []
    for s, e, _rows in entry["chunks"]:
        df = chunks.load(source, s, e)
        if df is None:
            # unreadable/missing chunk: re-derive exactly that byte range
            rbuf = _read_range(path, s, e)
            if rbuf is None:
                continue  # rotated away mid-assembly: drop the range
            try:
                df = parser(rbuf.decode("utf-8", errors="replace"),
                            time_base)
            except Exception as e2:  # noqa: BLE001 — per-source degradation
                print_warning(f"live: {source} chunk re-derive failed "
                              f"({e2})")
                continue
            chunks.store(source, s, e, df)
            out.info["chunks_parsed"] += 1
        else:
            out.info["chunks_loaded"] += 1
        if len(df):
            parts.append(df)
    # the freshly parsed chunk was stored AND reloaded above through the
    # same table — no special-casing, and the replay path is the hot path
    if len(entry["chunks"]) > CHUNK_COMPACT_COUNT and parts:
        # compact: one merged chunk replaces the table (pure load+store,
        # no reparse — the journal-compaction discipline)
        merged = pd.concat(parts, ignore_index=True)
        s0 = int(entry["chunks"][0][0])
        e1 = int(entry["chunks"][-1][1])
        if chunks.store(source, s0, e1, merged):
            for s, e, _r in entry["chunks"]:
                if not (s == s0 and e == e1):
                    chunks.discard(source, s, e)
            entry["chunks"] = [[s0, e1, int(len(merged))]]
    frame = (pd.concat(parts, ignore_index=True) if parts
             else empty_frame())
    out.frame = _conform(frame)
    out.info["events"] = int(len(out.frame))
    out.info["offset"] = int(entry["offset"])
    out.info["chunks"] = len(entry["chunks"])
    out.info["lag_bytes"] = int(max(size - entry["offset"], 0)) \
        if size >= 0 else 0
    if out.info["status"] in ("idle",):
        if new_rows:
            out.info["status"] = "streaming"
            watermark.update(source, max(size, 0), time.time())
        else:
            # an injected stall freezes the size the clock sees, so the
            # stall window elapses deterministically even if the file
            # keeps growing underneath
            wm_size = int(entry["offset"]) if stalled_fault \
                else max(size, 0)
            grown = watermark.update(source, wm_size, time.time())
            out.info["status"] = ("stalled" if grown == "stalled"
                                  else "idle")
    return out


# ---------------------------------------------------------------------------
# The epoch.
# ---------------------------------------------------------------------------

def _inject_previous_features(cfg: SofaConfig, features, selected) -> int:
    """Seed ``features`` with the previous epoch's rows for every enabled
    pass OUTSIDE the incremental window (its inputs are unchanged, so its
    features are still true).  Rows whose name matches a SELECTED pass's
    provides pattern are left out — the re-run recomputes them."""
    from fnmatch import fnmatchcase

    from sofa_tpu.analysis import registry

    path = cfg.path("features.csv")
    if not os.path.isfile(path):
        return 0
    try:
        prev = pd.read_csv(path)
    except Exception as e:  # noqa: BLE001 — a torn table seeds nothing
        print_warning(f"live: cannot read previous features.csv ({e})")
        return 0
    specs = [s for s in registry.registered() if s.enabled(cfg)]
    kept_pats = [p for s in specs if s.name not in selected
                 for p in s.provides_features]
    fresh_pats = [p for s in specs if s.name in selected
                  for p in s.provides_features]
    n = 0
    for name, value in zip(prev.get("name", []), prev.get("value", [])):
        name = str(name)
        if any(fnmatchcase(name, p) for p in fresh_pats):
            continue
        if any(fnmatchcase(name, p) for p in kept_pats):
            try:
                features.add(name, float(value))
                n += 1
            except (TypeError, ValueError):
                continue
    return n


def _write_frame_atomic(df: pd.DataFrame, base_path: str,
                        cfg: "SofaConfig | None" = None,
                        fmt: str = "csv") -> None:
    """Atomic frame write for a live epoch — every artifact must stay
    readable mid-epoch (the board serves the last committed generation
    instead of 503ing, so no derived_write_guard on this path).

    ``columnar`` (the default format) APPENDS: the chunk store's
    content-keyed fixed boundaries mean an epoch's tail growth rewrites
    only the final partial chunk plus the new tail — committed column
    chunks are never rewritten (the tile append-mostly discipline
    applied to the frames themselves, docs/FRAMES.md) — and the
    downsampled board CSV refreshes beside it, exactly like a batch
    columnar preprocess.  CSV mode keeps the legacy whole-file
    rewrite."""
    from sofa_tpu.durability import atomic_replace
    from sofa_tpu.trace import downsample, write_csv, write_frame

    if fmt == "columnar":
        if write_frame(df, base_path, "columnar").endswith(".csv"):
            # the columnar write degraded per-frame to a FULL-fidelity
            # CSV at base_path+".csv" — overwriting it with the
            # downsampled viz copy would silently make lossy data the
            # frame's only artifact (preprocess._write_one's early
            # return, mirrored)
            return
        viz_max = int(getattr(cfg, "viz_downsample_to", 10000))
        with atomic_replace(base_path + ".csv") as tmp:
            write_csv(downsample(df, viz_max), tmp)
        return
    with atomic_replace(base_path + ".csv") as tmp:
        write_csv(df, tmp)
    # stale higher-priority stores from an earlier columnar/parquet run
    # must not shadow the fresh csv
    from sofa_tpu import frames as framestore

    logdir, name = os.path.split(base_path)
    framestore.delete_frame_store(logdir or ".", name)
    try:
        os.unlink(base_path + ".parquet")
    except OSError:
        pass


def _run_epoch(cfg: SofaConfig, ledger: OffsetLedger) -> dict:
    """One live tick.  Returns the ``meta.live`` document it recorded."""
    from sofa_tpu import durability, telemetry
    from sofa_tpu.analysis import advice, registry
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.analyze import stage_board
    from sofa_tpu.durability import atomic_write
    from sofa_tpu.ingest.cache import (CACHE_DIR_NAME, IngestCache,
                                       raw_files_present)
    from sofa_tpu.preprocess import (_XPLANE_FRAMES, _ingest_tasks,
                                     _run_ingest, assemble_frames,
                                     build_series, read_misc,
                                     read_time_base)
    from sofa_tpu.supervisor import GrowthWatermark
    from sofa_tpu.trace import reap_stale_sentinel

    reap_stale_sentinel(cfg.logdir)
    epoch = int(ledger.doc["epoch"]) + 1
    first = ledger.doc["epoch"] == 0
    tel = telemetry.begin("live")
    journal = durability.Journal(cfg.logdir)
    journal.begin("live", key=durability.logdir_raw_key(cfg.logdir),
                  epoch=epoch)
    try:
        time_base = read_time_base(cfg)
        cfg.time_base = time_base
        if ledger.doc.get("time_base") is not None \
                and ledger.doc["time_base"] != time_base:
            print_warning("live: sofa_time.txt changed — committed chunks "
                          "were parsed against the old time base; "
                          "re-ingesting from byte 0")
            chunks0 = IngestCache(cfg.path(CACHE_DIR_NAME),
                                  enabled=cfg.ingest_cache).chunks()
            for name in list(ledger.doc["sources"]):
                chunks0.drop(name)
                ledger.reset_source(name)
        ledger.doc["time_base"] = time_base
        jobs = pool.cfg_jobs(cfg)
        tel.set_meta(pool={"jobs": jobs, "cpu_count": os.cpu_count() or 1})
        offset = cfg.cpu_time_offset_ms / 1e3
        tpu_off = cfg.tpu_time_offset_ms / 1e3
        cache = IngestCache(cfg.path(CACHE_DIR_NAME),
                            enabled=cfg.ingest_cache)
        chunks = cache.chunks()
        watermark = GrowthWatermark.from_doc(cfg.live_stall_s,
                                             ledger.doc.get("growth"))

        # --- tail the chunkable sources -------------------------------
        dirty_frames: set = set()
        live_sources: Dict[str, dict] = {}
        tail_frames: Dict[str, pd.DataFrame] = {}
        with tel.span("tail", cat="stage"):
            for source, raw, parser in _tail_parsers(cfg):
                o = _tail_source(cfg, ledger, chunks, source, raw,
                                 parser, time_base, epoch, watermark)
                df = o.frame
                if offset and not df.empty:
                    df = df.copy()
                    df["timestamp"] = df["timestamp"] + offset
                tail_frames[source] = df
                live_sources[source] = o.info
                if o.dirty:
                    dirty_frames.add(source)
                tel.source_event(
                    source,
                    status=("parsed" if o.info["chunks_parsed"]
                            else ("cached" if o.info["events"]
                                  else "empty")),
                    cache=("miss" if o.info["chunks_parsed"] else
                           ("hit" if o.info["chunks_loaded"]
                            else "bypass" if not cache.enabled
                            else "hit")),
                    wall_s=o.info.get("parse_wall_s", 0.0),
                    events=o.info["events"])
        # `stalled` means wedged while SIBLINGS stream — when every tail
        # is quiet the job is simply done/idle, not degraded
        if not any(i["status"] == "streaming"
                   for i in live_sources.values()):
            for i in live_sources.values():
                if i["status"] == "stalled":
                    i["status"] = "idle"
        ledger.doc["growth"] = watermark.to_doc()

        # --- rescan the stateful remainder through the batch cache ----
        rescan = [t.name for t in _ingest_tasks(cfg, time_base, jobs)
                  if t.name not in TAILABLE_SOURCES]
        with tel.span("ingest", cat="stage"):
            tasks, results, cache = _run_ingest(cfg, time_base, jobs,
                                                tel, only=set(rescan))
        frames, tpu_meta = assemble_frames(tasks, results, offset,
                                           tpu_off)
        from sofa_tpu.ingest.cache import make_key

        for t in tasks:
            keyed = raw_files_present(make_key(t.name, t.raw_paths,
                                               t.params))
            if t.name not in cache.hits and (keyed or not cache.enabled):
                dirty_frames.update(t.frame_names)
        frames.update(tail_frames)
        if first:
            dirty_frames = set(frames)

        # --- refresh derived artifacts (all writes atomic) ------------
        meta_live: dict = {
            "active": True, "epoch": epoch,
            "updated_unix": round(time.time(), 3),
            "interval_s": cfg.live_interval_s,
            "sources": live_sources,
        }
        marks = [float(df["timestamp"].max())
                 for name, df in tail_frames.items() if len(df)]
        meta_live["watermark_s"] = round(min(marks), 6) if marks else None
        ledger.doc["watermark_s"] = meta_live["watermark_s"]
        if dirty_frames:
            from sofa_tpu.trace import resolve_trace_format

            fmt = resolve_trace_format(cfg)
            with tel.span("write_frames", cat="stage"):
                to_write = sorted(n for n in dirty_frames
                                  if n in frames and n != "cpuinfo")
                pool.thread_map(
                    lambda n: _write_frame_atomic(frames[n], cfg.path(n),
                                                  cfg=cfg, fmt=fmt),
                    to_write, jobs)
            series = build_series(cfg, frames)
            tiles_manifest = None
            tile_stats = {}
            if cfg.enable_tiles:
                from sofa_tpu import tiles

                with tel.span("tiles", cat="stage"):
                    try:
                        tiles_manifest, tile_stats = tiles.build_tiles_live(
                            cfg, series, jobs=jobs, tel=tel)
                    except Exception as e:  # noqa: BLE001 — tiles are an enhancement, never fatal
                        print_warning(f"live: tile refresh failed ({e}); "
                                      "the board serves the overview only")
            meta_live["tiles"] = {
                "rebuilt": int(tile_stats.get("rebuilt", 0)),
                "kept": int(tile_stats.get("kept", 0)),
                "full_rebuilds": int(tile_stats.get("full_rebuilds", 0)),
            }

            # incremental analysis on the dirty window
            registry.load_builtin_passes()
            features = Features()
            misc = read_misc(cfg)
            features.add("elapsed_time",
                         float(misc.get("elapsed_time", 0) or 0))
            select = None
            if not first:
                select = registry.select_for_dirty(cfg, dirty_frames)
                _inject_previous_features(cfg, features, select)
            with tel.span("passes", cat="stage"):
                pass_report, extra_series = registry.run_passes(
                    frames, cfg, features, tel=tel, select=select)
            tel.set_meta(passes=pass_report)
            statuses = [e.get("status")
                        for e in pass_report["passes"].values()]
            meta_live["passes"] = {
                "ran": statuses.count("ok") + statuses.count("failed"),
                "skipped_clean": sum(
                    1 for e in pass_report["passes"].values()
                    if "unchanged" in str(e.get("skip_reason", ""))),
            }
            with atomic_write(cfg.path("features.csv")) as f:
                features.to_frame().to_csv(f, index=False)

            with tel.span("report_js", cat="stage"):
                meta = {
                    "elapsed_time": float(misc.get("elapsed_time", 0)
                                          or 0),
                    "time_base": time_base,
                    "tpu_meta": tpu_meta,
                    "logdir": cfg.logdir,
                    "live": {"epoch": epoch, "active": True},
                }
                if tiles_manifest is not None:
                    meta["tiles"] = tiles_manifest
                from sofa_tpu.trace import series_to_report_js

                series_to_report_js(series + list(extra_series),
                                    cfg.path("report.js"),
                                    cfg.viz_downsample_to, meta)
            if tpu_meta:
                with atomic_write(cfg.path("tpu_meta.json")) as f:
                    json.dump(tpu_meta, f, indent=1)
            with tel.span("hints", cat="stage"):
                advice.hint_report(features, cfg)
            if first:
                stage_board(cfg)
        else:
            meta_live["tiles"] = {"rebuilt": 0, "kept": 0,
                                  "full_rebuilds": 0}
            meta_live["passes"] = {"ran": 0, "skipped_clean": 0}

        meta_live["chunks_parsed"] = sum(
            s.get("chunks_parsed", 0) for s in live_sources.values())
        meta_live["chunks_loaded"] = sum(
            s.get("chunks_loaded", 0) for s in live_sources.values())
        tel.set_meta(live=meta_live, ingest_cache=cache.stats())
        ledger.doc["epoch"] = epoch
        ledger.commit()
        tel.write(cfg.logdir, rc=0, cfg=cfg)
        if dirty_frames:
            with tel.span("digests", cat="stage"):
                durability.write_digests(cfg.logdir)
        journal.commit("live", key=durability.logdir_raw_key(cfg.logdir),
                       epoch=epoch)
        n_streaming = sum(1 for s in live_sources.values()
                          if s["status"] == "streaming")
        print_progress(
            f"live epoch {epoch}: {n_streaming} source(s) streaming, "
            f"{meta_live['chunks_parsed']} chunk(s) parsed, "
            f"{meta_live['chunks_loaded']} loaded, tiles "
            f"{meta_live['tiles']['rebuilt']} rebuilt / "
            f"{meta_live['tiles']['kept']} kept, passes "
            f"{meta_live['passes']['ran']} ran / "
            f"{meta_live['passes']['skipped_clean']} clean")
        return meta_live
    finally:
        telemetry.end(tel)


# ---------------------------------------------------------------------------
# The verb.
# ---------------------------------------------------------------------------

def _drain(cfg: SofaConfig) -> int:
    """Converge the logdir to the exact batch output: a full
    ``preprocess`` + ``analyze`` (live tile indexes carry no batch key,
    so every pyramid rebuilds through the batch path), then mark
    ``meta.live`` inactive."""
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.durability import _patch_manifest
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.telemetry import load_manifest

    print_progress("live: draining — full batch preprocess+analyze for "
                   "byte-identical convergence")
    frames = sofa_preprocess(cfg)
    sofa_analyze(cfg, frames=frames)
    doc = load_manifest(cfg.logdir) or {}
    live_meta = dict(((doc.get("meta") or {}).get("live")) or {})
    if live_meta:
        # mark the stream drained; a logdir with no live section (e.g.
        # cleaned back to raw before the drain) has nothing to mark
        live_meta["active"] = False
        live_meta["drained"] = True
        _patch_manifest(cfg.logdir, meta={"live": live_meta})
    return 0


def sofa_live(cfg: SofaConfig, epochs: "int | None" = None,
              drain: bool = False) -> int:
    """``sofa live <logdir> [--live_epochs N] [--drain]`` — the epoch
    loop.  Exit 0 on a clean run/drain, 1 when the final epoch left a
    stalled source (degraded, stated), 2 on a missing logdir (raised as
    a usage error)."""
    from sofa_tpu.printing import SofaUserError

    if not os.path.isdir(cfg.logdir):
        raise SofaUserError(
            f"logdir {cfg.logdir} does not exist — point `sofa live` at "
            "a recording (or a directory collectors are writing into)")
    n = cfg.live_epochs if epochs is None else int(epochs)
    if drain and n == 0:
        # `sofa live <logdir> --drain` with no epoch budget is the
        # after-the-job convergence verb: no loop, straight to batch.
        return _drain(cfg)
    faults.install_from(cfg)
    last: dict = {}
    try:
        ledger = OffsetLedger.load(cfg.logdir)
        i = 0
        while n == 0 or i < n:
            i += 1
            last = _run_epoch(cfg, ledger)
            if n == 0 or i < n:
                time.sleep(max(cfg.live_interval_s, 0.0))
    except KeyboardInterrupt:
        print_progress("live: interrupted — the offset ledger holds the "
                       "committed state; `sofa live` resumes from it")
    finally:
        faults.clear()
    if drain:
        return _drain(cfg)
    stalled = sorted(name for name, s in (last.get("sources") or {}).items()
                     if s.get("status") == "stalled")
    if stalled:
        print_warning("live: stalled source(s) at exit: "
                      + ", ".join(stalled)
                      + " — their series end early; the other sources "
                      "kept streaming")
        return 1
    return 0
