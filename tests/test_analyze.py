import os

import pandas as pd
import pytest

from sofa_tpu.analysis import advice, comm, concurrency, tpu
from sofa_tpu.analysis.features import Features
from sofa_tpu.config import SofaConfig
from sofa_tpu.trace import CopyKind, make_frame


@pytest.fixture
def cfg(logdir):
    return SofaConfig(logdir=logdir)


def tpu_frame():
    rows = []
    t = 0.0
    for i in range(10):
        rows.append({"timestamp": t, "duration": 0.008, "deviceId": 0,
                     "copyKind": int(CopyKind.KERNEL), "name": f"fusion.{i}",
                     "hlo_category": "convolution", "flops": 1e9,
                     "bytes_accessed": 1e6, "device_kind": "tpu"})
        t += 0.008
        rows.append({"timestamp": t, "duration": 0.002, "deviceId": 0,
                     "copyKind": int(CopyKind.ALL_REDUCE), "name": "all-reduce.1",
                     "hlo_category": "all-reduce", "payload": int(4e6),
                     "bytes_accessed": 4e6, "device_kind": "tpu"})
        t += 0.002
    return make_frame(rows)


def test_tpu_profile_and_comm(cfg):
    frames = {"tputrace": tpu_frame(), "tpumodules": make_frame(
        [{"timestamp": 0.0, "duration": 0.1, "deviceId": 0, "name": "jit_step"}])}
    f = Features()
    tpu.tpu_profile(frames, cfg, f)
    comm.comm_profile(frames, cfg, f)
    assert f.get("tpu_devices") == 1
    assert f.get("tpu0_kernel_time") == pytest.approx(0.08)
    assert f.get("tpu0_collective_time") == pytest.approx(0.02)
    assert f.get("comm_ratio") == pytest.approx(0.2)
    assert f.get("comm_all_reduce_bytes") == pytest.approx(4e7)
    assert os.path.isfile(cfg.path("tpu_top_ops.csv"))
    assert os.path.isfile(cfg.path("comm.csv"))
    assert f.get("hlo_time_convolution") == pytest.approx(0.08)


def test_ici_matrix_ring_model():
    coll = make_frame([
        {"timestamp": 0.0, "duration": 1e-3, "copyKind": int(CopyKind.ALL_REDUCE),
         "payload": 8_000_000, "name": "all-reduce.0"},
    ])
    topo = {"devices": [{"id": i, "coords": [i, 0, 0]} for i in range(4)]}
    mat = comm.ici_traffic_matrix(coll, topo)
    assert mat is not None
    # all-reduce of 8 MB over 4 chips: each of the 4 ring edges carries
    # 2*P*(n-1)/n = 12 MB.
    assert mat.to_numpy().max() == pytest.approx(12e6)
    assert mat.to_numpy().sum() == pytest.approx(48e6)
    assert comm.ici_traffic_matrix(coll, None) is None


def test_spotlight_roi(cfg):
    rows = []
    for i in range(40):
        util = 90.0 if 10 <= i < 30 else 1.0
        rows.append({"timestamp": 0.1 * i, "duration": 0.1, "event": util,
                     "deviceId": 0, "name": "tc_util", "device_kind": "tpu"})
    frames = {"tpuutil": make_frame(rows)}
    cfg.spotlight = True
    f = Features()
    tpu.spotlight_roi(frames, cfg, f)
    assert 0 < cfg.roi_begin < cfg.roi_end
    assert cfg.roi_begin == pytest.approx(1.0, abs=0.35)
    assert cfg.roi_end == pytest.approx(3.0, abs=0.25)


def test_profile_region_manual(cfg):
    cfg.profile_region = "1.5:2.5"
    f = Features()
    tpu.spotlight_roi({}, cfg, f)
    assert cfg.roi_begin == 1.5 and cfg.roi_end == 2.5


def test_concurrency_breakdown(cfg):
    mp_rows = []
    for i in range(20):
        for metric, val in (("usr", 80.0 if i < 10 else 5.0),
                            ("sys", 5.0), ("iow", 1.0 if i < 10 else 60.0),
                            ("idl", 14.0)):
            mp_rows.append({"timestamp": 0.1 * i, "duration": 0.1, "event": val,
                            "deviceId": -1, "name": metric})
    frames = {"mpstat": make_frame(mp_rows)}
    f = Features()
    concurrency.concurrency_breakdown(frames, cfg, f)
    assert f.get("elapsed_usr_ratio") == pytest.approx(0.5, abs=0.15)
    assert f.get("elapsed_iow_ratio") == pytest.approx(0.5, abs=0.15)
    assert os.path.isfile(cfg.path("performance.csv"))
    perf = pd.read_csv(cfg.path("performance.csv"))
    assert {"class", "usr", "tpu_util"} <= set(perf.columns)


def test_mesh_advice(cfg):
    import json

    topo = {"devices": [{"id": i, "coords": [i % 2, i // 2, 0],
                         "core_on_chip": 0} for i in range(8)],
            "device_count": 8}
    with open(cfg.path("tpu_topo.json"), "w") as fjson:
        json.dump(topo, fjson)
    f = Features()
    advice.mesh_advice({}, cfg, f)
    text = open(cfg.path("sofa_hints/mesh_advice.txt")).read()
    assert "device_count = 8" in text
    assert "(2, 4)" in text or "(4, 2)" in text  # most-square mesh wins
    assert "ici_ring_order" in text


def test_hint_rules():
    f = Features()
    f.add("comm_ratio", 0.4)
    f.add("tpu_ops", 100)
    f.add("mxu_util_mean", 5.0)
    f.add("elapsed_iow_ratio", 0.5)
    hints = advice.generate_hints(f, SofaConfig())
    text = " ".join(hints)
    assert "communication-bound" in text
    assert "MXU utilization is low" in text
    assert "I/O-wait" in text


def test_analyze_end_to_end(logdir, capsys):
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_record

    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=50)
    sofa_record("sleep 0.3", cfg)
    sofa_preprocess(cfg)
    features = sofa_analyze(cfg)
    out = capsys.readouterr().out
    assert "Complete!!" in out            # the e2e sentinel (reference test/test.py:75)
    assert "Final Performance Features" in out
    assert features.get("elapsed_time") >= 0.3
    assert features.get("num_cores") >= 1
    assert os.path.isfile(cfg.path("features.csv"))
    assert os.path.isfile(cfg.path("index.html"))  # board staged


def test_cluster_analyze(tmp_path):
    from sofa_tpu.analyze import cluster_analyze
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_record

    base = str(tmp_path / "clog")
    hosts = ["host1", "host2"]
    for h in hosts:
        cfg = SofaConfig(logdir=f"{base}-{h}/", enable_xprof=False, sys_mon_rate=50)
        sofa_record("sleep 0.2", cfg)
        sofa_preprocess(cfg)
    cfg = SofaConfig(logdir=base + "/", cluster_hosts=hosts)
    results = cluster_analyze(cfg)
    assert set(results) == set(hosts)
    summary = pd.read_csv(cfg.path("cluster_summary.csv"))
    assert list(summary["host"]) == hosts
    assert (summary["elapsed_time"] >= 0.2).all()
