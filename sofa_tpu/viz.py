"""`sofa viz` — serve the board GUI over the logdir.

The reference is a single-threaded file server (sofa_viz.py:18); this one
is a production data server for the board's O(pixels) contract:

  * ``ThreadingHTTPServer`` — tile bursts on zoom are many small parallel
    requests; one slow pod-scale CSV download must not head-of-line block
    them.
  * ETag/If-None-Match on every file + ``Cache-Control`` — derived
    artifacts change between runs, so revalidation is cheap 304s instead
    of re-downloads.
  * Accept-Encoding negotiation for the pre-gzipped LOD tiles
    (``_tiles/<series>/<level>/<n>.json.gz``, sofa_tpu/tiles.py): gzip
    bytes go straight to the wire when the client accepts gzip (every
    browser does) and are decompressed server-side otherwise.  ``/tiles/…``
    is a stable route alias for the on-disk ``_tiles/`` pyramid.
  * 503 + Retry-After while a pipeline verb is mid-write on the logdir
    (trace.derived_write_guard's sentinel): a board refresh racing
    `sofa preprocess` gets an honest retry signal, never torn JSON.
    `sofa live` epochs never raise that sentinel — every live write is
    tmp+rename atomic, so mid-epoch reads serve the last committed
    generation instead of 503ing for the whole run (docs/LIVE.md), and
    the board polls ``meta.live`` to grow the timeline as epochs land.

The ``/archive/`` route here is the READ half of the fleet archive; its
write-capable sibling is `sofa serve` (sofa_tpu/archive/service.py),
which reuses this server's shape — ThreadingHTTPServer subclass with
guard-declared shared stats, the same mid-write 503 pattern — for the
authenticated multi-tenant ingest endpoint `sofa agent` pushes into.
"""

from __future__ import annotations

import errno
import functools
import gzip
import http.server
import io
import os
import posixpath
import socket

from sofa_tpu.concurrency import Guard
from sofa_tpu.printing import print_error, print_progress

# Requests answered 503 while the write-guard sentinel is up: the board's
# data artifacts (report.js, frame CSVs, tiles, manifests).  Board chrome
# (HTML/board JS/CSS) keeps serving — only data can be torn mid-write.
_DATA_SUFFIXES = (".csv", ".parquet", ".json", ".json.gz")


class _BoardServer(http.server.ThreadingHTTPServer):
    """The board's server.  Subclassing carries the socket/thread policy
    as CLASS attributes instead of mutating ThreadingHTTPServer globally —
    the old module-level assignment changed every other HTTP server in the
    process (the SL019 shared-state class of bug).  Handler threads share
    one request ledger under a declared guard; `sofa viz` prints it at
    shutdown so a fleet operator can see 503 churn at a glance."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._stats_guard = Guard("viz.server_stats", protects=("stats",))
        self.stats: dict = {}

    def count_response(self, key: str) -> None:
        with self._stats_guard:
            self.stats[key] = self.stats.get(key, 0) + 1

    def stats_line(self) -> "str | None":
        with self._stats_guard:
            stats = dict(self.stats)
        if not stats:
            return None
        return ", ".join(f"{v} {k}" for k, v in sorted(stats.items()))


def _display_host(bind: str) -> str:
    """URL host worth printing for a bind address.  Wildcard binds print
    an address a *remote* user can reach; a failing gethostname (broken
    resolv/containers) degrades to localhost instead of crashing before
    the server ever serves, and IPv6 literals get their URL brackets."""
    if bind in ("127.0.0.1", "::1"):
        return "localhost"
    if bind in ("", "0.0.0.0", "::"):
        try:
            return socket.gethostname() or "localhost"
        except OSError:
            return "localhost"
    if ":" in bind:
        return f"[{bind}]"
    return bind


class _BoardHandler(http.server.SimpleHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive across a zoom's tile burst
    server_version = "sofa_tpu"

    def __init__(self, *args, archive_root=None, **kwargs):
        # The multi-run archive lives OUTSIDE the logdir; the /archive/
        # route maps onto it so the board's multi-run diff page can fetch
        # the catalog, run manifests, and content-addressed objects.
        self.archive_root = archive_root
        super().__init__(*args, **kwargs)

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _translate_archive(self, path: str) -> "str | None":
        """Map /archive/<rel> under the archive root; None on traversal
        attempts (every ``..`` component is rejected outright)."""
        import urllib.parse

        rel = urllib.parse.unquote(
            path.split("?", 1)[0].split("#", 1)[0])[len("/archive/"):]
        parts = []
        for p in rel.split("/"):
            if not p or p == ".":
                continue
            if p == "..":
                return None
            parts.append(p)
        return os.path.join(os.path.abspath(self.archive_root), *parts)

    def translate_path(self, path):  # noqa: A003
        # /tiles/... is the public route for the on-disk _tiles/ pyramid
        # (the underscore path also works — a dumb static host has no
        # rewrite, so the board fetches the literal layout).
        clean = path.split("?", 1)[0].split("#", 1)[0]
        if clean.startswith("/tiles/"):
            path = "/_tiles/" + path[len("/tiles/"):]
        elif clean.startswith("/archive/") and self.archive_root:
            return self._translate_archive(path) or \
                super().translate_path("/archive-denied")
        return super().translate_path(path)

    # -- helpers -----------------------------------------------------------
    def _is_data(self, fs_path: str) -> bool:
        rel = fs_path.replace(os.sep, "/")
        return (rel.endswith(_DATA_SUFFIXES)
                or posixpath.basename(rel) == "report.js"
                or "/_tiles/" in rel)

    def _count(self, key: str) -> None:
        counter = getattr(self.server, "count_response", None)
        if counter is not None:  # plain test harnesses use a bare server
            counter(key)

    def _unavailable(self):
        self._count("503_mid_write")
        self.send_response(503)
        self.send_header("Retry-After", "1")
        self.send_header("Content-Length", "0")
        self.end_headers()
        return None

    def _not_modified(self, etag: str):
        self._count("304_revalidated")
        self.send_response(304)
        self.send_header("ETag", etag)
        self.end_headers()
        return None

    # -- the one serving path (GET and HEAD both run through send_head) ----
    def send_head(self):
        from sofa_tpu.trace import derived_writing

        path = self.translate_path(self.path)
        if os.path.isdir(path):
            return super().send_head()  # index.html redirect / listing
        in_archive = bool(self.archive_root) and \
            path.startswith(os.path.abspath(self.archive_root) + os.sep)
        # Archive artifacts land atomically (tmp+rename) and objects are
        # immutable by construction — the logdir's mid-write 503 guard
        # does not apply to them.
        if not in_archive and self._is_data(path) \
                and derived_writing(self.directory):
            # CSVs stream and tiles land file-by-file: while a writer
            # holds the guard, data responses would race torn bytes.
            return self._unavailable()
        actual, precompressed = path, False
        if os.path.isfile(path):
            precompressed = path.endswith(".json.gz")
        elif os.path.isfile(path + ".gz"):
            # tiles fetched without the suffix negotiate transparently
            actual, precompressed = path + ".gz", True
        else:
            return super().send_head()  # canonical 404
        try:
            st = os.stat(actual)
        except OSError:
            return super().send_head()
        etag = f'"{st.st_mtime_ns:x}-{st.st_size:x}"'
        if self.headers.get("If-None-Match") == etag:
            return self._not_modified(etag)
        accepts_gzip = "gzip" in (self.headers.get("Accept-Encoding") or "")
        headers = [("ETag", etag)]
        if "_tiles" in actual.replace(os.sep, "/").split("/"):
            # tiles only change when a rebuild changes their content key's
            # inputs; short max-age absorbs zoom-jitter refetches and the
            # ETag revalidates after it
            headers.append(("Cache-Control", "max-age=60, must-revalidate"))
        else:
            headers.append(("Cache-Control", "no-cache"))
        if precompressed:
            headers.append(("Vary", "Accept-Encoding"))
            ctype = "application/json"
            if accepts_gzip:
                f = open(actual, "rb")
                headers.append(("Content-Encoding", "gzip"))
                length = st.st_size
            else:
                try:
                    with open(actual, "rb") as raw:
                        body = gzip.decompress(raw.read())
                except (OSError, gzip.BadGzipFile, EOFError):
                    return self._unavailable()  # torn tile: retry later
                f = io.BytesIO(body)
                length = len(body)
        else:
            ctype = self.guess_type(path)
            f = open(actual, "rb")
            length = st.st_size
        self._count("200_served")
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(length))
        for key, value in headers:
            self.send_header(key, value)
        self.end_headers()
        return f


def sofa_viz(cfg, serve_forever: bool = True):
    if not os.path.isdir(cfg.logdir):
        print_error(f"logdir {cfg.logdir} does not exist")
        return None
    # A verb that died holding the write guard must not 503 every data
    # request from now on: reap its sentinel before serving (live torn
    # sentinels also expire by mtime — trace.derived_writing).
    from sofa_tpu.trace import reap_stale_sentinel

    reap_stale_sentinel(cfg.logdir)
    from sofa_tpu.archive import is_archive_root, resolve_root

    archive_root = resolve_root(cfg)
    if not is_archive_root(archive_root):
        archive_root = None  # no store: /archive/ 404s like any miss
    handler = functools.partial(_BoardHandler, directory=cfg.logdir,
                                archive_root=archive_root)
    httpd = None
    last_err = None
    for port_try in range(cfg.viz_port, cfg.viz_port + 20):
        try:
            httpd = _BoardServer((cfg.viz_bind, port_try), handler)
            break
        except OSError as e:
            last_err = e
            if getattr(e, "errno", None) != errno.EADDRINUSE:
                # A bad bind address fails identically on every port —
                # retrying the range would only bury the real error.
                break
    if httpd is None:
        print_error(
            f"cannot bind a port in {cfg.viz_port}..{cfg.viz_port + 19}: {last_err}"
        )
        return None
    port = httpd.server_address[1]
    host = _display_host(cfg.viz_bind)
    print_progress(
        f"serving {cfg.logdir} at http://{host}:{port}/ (Ctrl-C stops; "
        f"bound to {cfg.viz_bind or 'all interfaces'})"
    )
    from sofa_tpu.telemetry import MANIFEST_NAME, SELF_TRACE_NAME
    from sofa_tpu.tiles import TILES_DIR_NAME

    if os.path.isdir(os.path.join(cfg.logdir, TILES_DIR_NAME)):
        print_progress(
            f"LOD tiles: /{TILES_DIR_NAME}/ (pre-gzipped; served with "
            "Accept-Encoding negotiation — deep zoom on the timeline "
            "fetches these viewport-driven)")
    if archive_root:
        print_progress(
            f"trace archive: /archive/ (root {archive_root}; the board's "
            "Archive page diffs any two catalog runs tile-by-tile — "
            "identical tiles compare by hash, no payload fetched). "
            "This route is read-only; `sofa serve` runs the write-capable "
            "fleet ingest service over an archive root (docs/FLEET.md)")
        from sofa_tpu.archive import index as aindex

        if aindex.is_current(archive_root):
            print_progress(
                "fleet board: /fleet.html ranks the archive's worst "
                "speed-of-light-distance offenders — index-fed from the "
                "columnar catalog index (archive ls / regress --rolling "
                "read the same index; docs/ARCHIVE.md). Point it at a "
                "`sofa serve` /v1/query endpoint for the live fleet view")
        print_progress(
            "tier board: /tier.html watches a `sofa serve` worker's "
            "observability plane — push-latency sparklines, WAL depth, "
            "replica lag, and the declared-SLO verdict, polled from the "
            "authenticated /v1/metrics endpoint with ETag-aware refresh "
            "(docs/FLEET.md \"Observing the tier\")")
    from sofa_tpu.live import OFFSETS_NAME

    if os.path.isfile(os.path.join(cfg.logdir, OFFSETS_NAME)):
        print_progress(
            "live stream: this logdir is (or was) fed by `sofa live` — "
            "every live write is atomic, so data requests serve the last "
            "committed epoch mid-write (no 503), and the board polls "
            "meta.live to grow the timeline while the job runs "
            "(docs/LIVE.md)")
    if os.path.isfile(os.path.join(cfg.logdir, SELF_TRACE_NAME)):
        print_progress(
            f"self-telemetry: /{SELF_TRACE_NAME} (Chrome-trace of sofa's "
            f"own run — load in ui.perfetto.dev) and /{MANIFEST_NAME} "
            "(`sofa status` renders it)")
    if serve_forever:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
            served = httpd.stats_line()
            if served:
                print_progress(f"viz served: {served}")
        return None
    return httpd
