#!/bin/bash
# SLURM wrapper: profile a job step under sofa_tpu (reference
# tools/slurmsofa.sh).  Usage inside a batch script:
#   srun tools/slurmsofa.sh python train.py --flags
# Per-task logdirs keyed by node + proc id so a multi-task step never
# collides; merge afterwards with `sofa report --cluster_hosts ...`.
set -euo pipefail
LOGDIR="${SOFA_LOGDIR:-sofalog}-${SLURMD_NODENAME:-$(hostname)}-${SLURM_PROCID:-0}/"
exec sofa record "$*" --logdir "$LOGDIR"
