"""`sofa preprocess` — raw collector files -> unified CSVs + report.js.

The files-on-disk contract (SURVEY §1): every parser reads logdir raw files
and writes `<source>.csv` in the unified schema, then all timeline series are
serialized to report.js for the board.  Each source is optional and failures
degrade per-source (the reference wraps every pass in try/except,
sofa_analyze.py:873-977; we do the same here at ingest).
"""

from __future__ import annotations

import os
from typing import Dict, List

import pandas as pd

from sofa_tpu.config import SofaConfig
from sofa_tpu.ingest import procfs
from sofa_tpu.ingest.pcap import ingest_pcap
from sofa_tpu.ingest.perf_script import ingest_perf
from sofa_tpu.ingest.strace_parse import parse_pystacks, parse_strace
from sofa_tpu.ingest.timebase_align import converter
from sofa_tpu.ingest.xplane import ingest_xprof_dir
from sofa_tpu.printing import print_progress, print_warning
from sofa_tpu.trace import (SofaSeries, downsample, empty_frame, write_csv,
                            write_frame)

# Distinct default colors for the master timeline (CSS color names the board
# understands; reference picks similar fixed palette per series).
_SERIES_STYLE = {
    "cputrace": ("CPU samples", "dodgerblue"),
    "hosttrace": ("Host runtime", "slategray"),
    "pystacks": ("Python stacks", "goldenrod"),
    "strace": ("Syscalls", "brown"),
    "mpstat": ("CPU util %", "steelblue"),
    "vmstat": ("vmstat", "darkkhaki"),
    "diskstat": ("Disk", "sienna"),
    "netbandwidth": ("NIC B/s", "seagreen"),
    "nettrace": ("Packets", "olive"),
    "tputrace": ("TPU HLO ops", "darkorchid"),
    "tpumodules": ("TPU modules", "mediumvioletred"),
    "tpuutil": ("TPU util", "crimson"),
    "tpumon": ("TPU HBM", "firebrick"),
    "tpusteps": ("TPU steps", "black"),
    "customtrace": ("Runtime (megascale/DCN)", "teal"),
    "blktrace": ("Block IO latency (ms)", "peru"),
}


def read_time_base(cfg: SofaConfig) -> float:
    try:
        with open(cfg.path("sofa_time.txt")) as f:
            return float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        print_warning("sofa_time.txt missing; timestamps stay absolute")
        return 0.0


def read_misc(cfg: SofaConfig) -> Dict[str, str]:
    out: Dict[str, str] = {}
    try:
        with open(cfg.path("misc.txt")) as f:
            for line in f:
                p = line.split()
                if len(p) == 2:
                    out[p[0]] = p[1]
    except OSError:
        pass
    return out


def sofa_preprocess(cfg: SofaConfig) -> Dict[str, pd.DataFrame]:
    if not os.path.isdir(cfg.logdir):
        from sofa_tpu.printing import SofaUserError

        raise SofaUserError(
            f"logdir {cfg.logdir} does not exist — run `sofa record` first"
        )
    time_base = read_time_base(cfg)
    cfg.time_base = time_base
    offset = cfg.cpu_time_offset_ms / 1e3
    frames: Dict[str, pd.DataFrame] = {}

    def ingest(name: str, fn, *args, **kwargs):
        try:
            df = fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — per-source degradation
            print_warning(f"preprocess {name}: {e}")
            df = empty_frame()
        frames[name] = df
        if not df.empty and offset:
            df["timestamp"] = df["timestamp"] + offset

    # --- host samplers ----------------------------------------------------
    ingest("mpstat", procfs.load, cfg.path("mpstat.txt"), procfs.parse_mpstat, time_base)
    ingest("diskstat", procfs.load, cfg.path("diskstat.txt"), procfs.parse_diskstat, time_base)
    ingest("netbandwidth", procfs.load, cfg.path("netstat.txt"), procfs.parse_netstat, time_base)
    ingest("cpuinfo", procfs.load, cfg.path("cpuinfo.txt"), procfs.parse_cpuinfo, time_base)
    ingest("vmstat", procfs.load, cfg.path("vmstat.txt"), procfs.parse_vmstat, time_base,
           record_start=time_base)

    # --- perf CPU samples (needs the MHz interpolator + clock bridge) -----
    mono_to_unix = converter(cfg.path("timebase.txt"), "monotonic")
    mhz_at = procfs.cpu_mhz_interpolator(frames.get("cpuinfo", empty_frame()))
    ingest("cputrace", ingest_perf, cfg.logdir, time_base, mono_to_unix, mhz_at)

    # --- syscalls / python stacks / packets -------------------------------
    def _load_text(path, parser, **kw):
        if not os.path.isfile(path):
            return empty_frame()
        with open(path) as f:
            return parser(f.read(), time_base=time_base, **kw)

    ingest("strace", _load_text, cfg.path("strace.txt"), parse_strace,
           min_time=cfg.strace_min_time)
    ingest("pystacks", _load_text, cfg.path("pystacks.txt"), parse_pystacks)
    ingest("nettrace", ingest_pcap, cfg.path("sofa.pcap"), time_base)

    # --- live TPU runtime metrics (works even with --disable_xprof) -------
    from sofa_tpu.ingest.tpumon_parse import ingest_tpumon

    ingest("tpumon", ingest_tpumon, cfg.logdir, time_base)

    # --- block IO latency (blkparse times are already trace-relative) -----
    from sofa_tpu.ingest.blktrace_parse import ingest_blktrace

    ingest("blktrace", ingest_blktrace, cfg.logdir, 0.0)

    # --- TPU XPlane -------------------------------------------------------
    tpu_meta: Dict[str, Dict[str, float]] = {}
    try:
        xframes = ingest_xprof_dir(cfg.xprof_dir, time_base)
        tpu_meta = xframes.pop("_meta", {})  # type: ignore[assignment]
        # Manual escape hatch mirroring cpu_time_offset_ms for the device
        # side: when the marker/timebase alignment is wrong (bad marker, NTP
        # step mid-run), the trace can be salvaged without re-recording.
        tpu_off = cfg.tpu_time_offset_ms / 1e3
        if tpu_off:
            for df in xframes.values():
                if not df.empty:
                    df["timestamp"] = df["timestamp"] + tpu_off
        frames.update(xframes)
    except Exception as e:  # noqa: BLE001
        print_warning(f"preprocess xplane: {e}")
    for key in ("tputrace", "tpumodules", "hosttrace", "tpuutil",
                "tpusteps", "customtrace"):
        frames.setdefault(key, empty_frame())

    # --- write frames -----------------------------------------------------
    trace_format = cfg.trace_format
    if trace_format == "parquet":
        try:
            import pyarrow  # noqa: F401 — pandas' default parquet engine
        except ImportError:
            print_warning("trace_format=parquet needs pyarrow (pip install "
                          "'sofa-tpu[parquet]'); falling back to csv")
            trace_format = "csv"
    def _write_one(item):
        name, df = item
        write_frame(df, cfg.path(name), trace_format)
        if trace_format == "parquet":
            # The board's detail pages fetch <name>.csv; keep a downsampled
            # viz copy beside the full-fidelity parquet (analyze prefers
            # the parquet — trace.read_frame).  write_csv directly: the
            # csv mode of write_frame would unlink the parquet just written.
            write_csv(downsample(df, cfg.viz_downsample_to),
                      cfg.path(f"{name}.csv"))

    to_write = [(n, df) for n, df in frames.items() if n != "cpuinfo"]
    n_csv = len(to_write)
    # Frames are independent files and the pyarrow CSV/parquet writers
    # release the GIL, so a small thread pool overlaps the pod-scale
    # tputrace write with the fifteen small ones.
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=4) as pool:
        list(pool.map(_write_one, to_write))

    # --- assemble the timeline series -> report.js ------------------------
    series = build_series(cfg, frames)
    misc = read_misc(cfg)
    meta = {
        "elapsed_time": float(misc.get("elapsed_time", 0) or 0),
        "time_base": time_base,
        "tpu_meta": tpu_meta,
        "logdir": cfg.logdir,
    }
    from sofa_tpu.trace import series_to_report_js

    series_to_report_js(series, cfg.path("report.js"), cfg.viz_downsample_to, meta)
    if tpu_meta:
        # Device peak rates for the analyze-side roofline pass (analysis
        # reads CSVs, not report.js, so the peaks get their own file).
        import json

        with open(cfg.path("tpu_meta.json"), "w") as f:
            json.dump(tpu_meta, f, indent=1)
    print_progress(
        f"preprocess wrote {n_csv} {trace_format} frames and report.js "
        f"({len(series)} series)"
    )
    return frames


def build_series(cfg: SofaConfig, frames: Dict[str, pd.DataFrame]) -> List[SofaSeries]:
    series: List[SofaSeries] = []
    for key, (title, color) in _SERIES_STYLE.items():
        df = frames.get(key)
        if df is None or df.empty:
            continue
        y_axis = "event"
        kind = "scatter"
        if key in ("mpstat", "vmstat", "diskstat", "netbandwidth", "tpuutil",
                   "tpumon"):
            kind = "line"
        base = df
        if key == "mpstat":
            # Timeline shows aggregate non-idle % (per-metric detail lives in
            # the CSV for cpu-report).
            base = df[(df["deviceId"] == -1) & (df["name"].isin(["usr", "sys"]))]
        series.append(SofaSeries(key, title, color, base, y_axis=y_axis, kind=kind))

    # Keyword filter groups pulled into their own colored series
    # (reference behavior for cpu/gpu filters, bin/sofa:258-291).
    def _contains(col, keyword):
        # case-insensitive substring match via the column's UNIQUE values:
        # HLO-op/symbol names repeat heavily (~400 uniques in a 1.6M-row pod
        # trace), so matching uniques + isin beats str.contains row-by-row
        # by orders of magnitude
        kw = keyword.lower()
        hits = [u for u in col.unique() if kw in str(u).lower()]
        return col.isin(hits)

    cputrace = frames.get("cputrace", empty_frame())
    for filt in cfg.cpu_filters:
        if cputrace.empty:
            break
        sel = cputrace[_contains(cputrace["name"], filt.keyword)]
        if not sel.empty:
            series.append(
                SofaSeries(f"cpu_{filt.keyword}", f"CPU: {filt.keyword}", filt.color, sel)
            )
    # fw/bw phase series — the board filter for training-phase attribution
    # (reference default GPU filters _fw_/_bw_, bin/sofa:284-285).
    tputrace = frames.get("tputrace", empty_frame())
    if not tputrace.empty and "phase" in tputrace.columns:
        for phase, title, color in (("fw", "TPU forward", "mediumseagreen"),
                                    ("bw", "TPU backward", "crimson")):
            sel = tputrace[tputrace["phase"] == phase]
            if not sel.empty:
                series.append(
                    SofaSeries(f"tpu_phase_{phase}", title, color, sel))
    for filt in cfg.tpu_filters:
        if tputrace.empty:
            break
        mask = _contains(tputrace["name"], filt.keyword) | \
            _contains(tputrace["hlo_category"], filt.keyword)
        sel = tputrace[mask]
        if not sel.empty:
            series.append(
                SofaSeries(f"tpu_{filt.keyword}", f"TPU: {filt.keyword}", filt.color, sel)
            )
    return series
