"""Indexed fleet catalog (ISSUE 15): the incremental columnar query
engine over the archive (sofa_tpu/archive/index.py).

Covers the tail-aware refresh contract (suffix-only parse proven by a
parser that RAISES on re-parsed committed bytes, warm no-op with
untouched mtimes, torn-tail backoff, gc/rewrite invalidation), the
scan-vs-index identity proofs (`archive ls` output and rolling
`regress` verdicts byte-identical either way), the `/v1/query` service
endpoint (auth, commit-sha ETag, pagination, 429-quota interplay,
index-less fallback), kill-mid-refresh convergence, archive fsck
detect/repair of rotted index chunks, and the `catalog.rewrite` write
guard + generation bump.  The SIGKILL e2e lives in
tools/chaos_matrix.py's kill-mid-index-refresh cell.
"""

import io
import contextlib
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sofa_tpu.archive import catalog
from sofa_tpu.archive import index as aindex
from sofa_tpu.archive import baseline
from sofa_tpu.archive.service import service_url, sofa_serve
from sofa_tpu.archive.store import (
    ArchiveStore,
    archive_fsck,
    gc,
    render_ls,
    sofa_archive,
    _ls_runs,
)
from sofa_tpu.config import SofaConfig
from sofa_tpu.durability import atomic_write
from sofa_tpu.trace import derived_write_guard, derived_writing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "test-index-token"

pytestmark = pytest.mark.skipif(not aindex.available(),
                                reason="pyarrow unavailable")


def _mkarchive(tmp_path, n=12, hosts=3, name="arch"):
    """A synthetic archive: run docs + fsync'd catalog lines, the shapes
    a real ingest writes."""
    root = str(tmp_path / name)
    store = ArchiveStore(root, create=True)
    for i in range(n):
        run = f"{i:064x}"
        doc = {"schema": "sofa_tpu/archive_run", "version": 1,
               "run": run, "t": 1000.0 + i, "hostname": f"h{i % hosts}",
               "label": "nightly" if i % 2 else "release",
               "logdir": f"/fleet/h{i % hosts}/job{i}",
               "files": {"report.js": {"sha256": "0" * 64, "bytes": 10,
                                       "kind": "derived"}},
               "features": {"elapsed_time": 10.0 + i,
                            "step_time_mean": 0.05,
                            "tpu0_sol_distance": 2.0 + i * 0.25,
                            "tpu1_sol_distance": 1.5 + (n - i) * 0.125}}
        with atomic_write(store.run_doc_path(run)) as f:
            json.dump(doc, f, sort_keys=True)
        catalog.append_event(
            root, "ingest", run=run, logdir=doc["logdir"], files=1,
            new_objects=1, bytes_added=128,
            **({"label": doc["label"]} if doc["label"] else {}))
    catalog.append_event(root, "bench", metric="m", value=1.0,
                         round="r01")
    return root, store


def _append_run(root, store, i, t=None, features=None):
    run = f"{i:064x}"
    doc = {"run": run, "t": t or (1000.0 + i), "hostname": f"h{i % 3}",
           "logdir": f"/fleet/h{i % 3}/job{i}", "files": {},
           "features": features if features is not None
           else {"elapsed_time": 10.0 + i,
                 "tpu0_sol_distance": 2.0 + i * 0.25}}
    with atomic_write(store.run_doc_path(run)) as f:
        json.dump(doc, f, sort_keys=True)
    catalog.append_event(root, "ingest", run=run, logdir=doc["logdir"],
                         files=0, new_objects=0, bytes_added=0)
    return run


def _index_mtimes(root):
    out = {}
    for dirpath, _dirs, names in os.walk(aindex.index_dir(root)):
        for n in names:
            p = os.path.join(dirpath, n)
            out[p] = os.stat(p).st_mtime_ns
    return out


# ---------------------------------------------------------------------------
# The refresh contract.
# ---------------------------------------------------------------------------

def test_refresh_builds_and_is_current(tmp_path):
    root, _store = _mkarchive(tmp_path)
    c = aindex.refresh(root)
    assert c["_stats"]["full"] and c["runs"] == 12
    assert c["events"] == 13 and c["bench_events"] == 1
    assert aindex.is_current(root)
    assert aindex.verify(root) == []


def test_warm_refresh_parses_zero_bytes_and_touches_nothing(tmp_path):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    before = _index_mtimes(root)
    c = aindex.refresh(root)
    assert c["_stats"] == {"full": False, "parsed_bytes": 0,
                           "new_events": 0, "chunks_wrote": 0}
    assert _index_mtimes(root) == before  # not a single file touched


def test_append_refresh_parses_only_the_suffix(tmp_path, monkeypatch):
    """THE suffix-only proof: after the first commit, the parser is
    replaced by one that raises on any committed line — append-only
    growth must re-parse exactly the appended bytes."""
    root, store = _mkarchive(tmp_path)
    aindex.refresh(root)
    committed = open(catalog.catalog_path(root), "rb").read()
    committed_lines = set(committed.splitlines())
    real = aindex._parse_events

    def paranoid(buf):
        for line in buf.splitlines():
            assert line not in committed_lines, (
                "refresh re-parsed a committed catalog line")
        return real(buf)

    monkeypatch.setattr(aindex, "_parse_events", paranoid)
    _append_run(root, store, 100)
    c = aindex.refresh(root)
    assert not c["_stats"]["full"]
    assert c["_stats"]["new_events"] == 1
    assert c["runs"] == 13
    # and only each family's tail chunk was rewritten (3 families)
    assert c["_stats"]["chunks_wrote"] <= 3


def test_torn_tail_backs_off_to_last_whole_record(tmp_path):
    root, store = _mkarchive(tmp_path, n=4)
    aindex.refresh(root)
    run = _append_run(root, store, 50)
    with open(catalog.catalog_path(root), "a") as f:
        f.write('{"ev":"ingest","run":"torn-mid-wri')  # the crash case
    c = aindex.refresh(root)
    assert c["_stats"]["new_events"] == 1  # the whole record only
    size = os.path.getsize(catalog.catalog_path(root))
    assert c["catalog_offset"] < size
    # a torn tail is not data: the index still counts as current
    assert aindex.is_current(root)
    assert any(e["run"] == run for e in aindex.run_entries(root))
    # completing the line makes it data on the next refresh
    with open(catalog.catalog_path(root), "a") as f:
        f.write('tten"}\n')
    assert not aindex.is_current(root)
    c2 = aindex.refresh(root)
    assert c2["_stats"]["new_events"] == 1
    assert c2["catalog_offset"] == os.path.getsize(
        catalog.catalog_path(root))


def test_gc_compaction_invalidates_and_rebuilds(tmp_path):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    gen0 = catalog.generation(root)
    gc(root, keep=5)
    assert catalog.generation(root) == gen0 + 1
    # gc's commit point already rebuilt the index — and it matches scan
    assert aindex.is_current(root)
    runs = aindex.run_entries(root)
    scan = catalog.ingest_entries(catalog.read_catalog(root))
    assert [e["run"] for e in runs] == [e["run"] for e in scan]
    assert len(runs) == 5


def test_manual_rewrite_is_detected_not_served_stale(tmp_path):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    entries = catalog.read_catalog(root)
    catalog.rewrite(root, entries[:6])
    assert not aindex.is_current(root)       # never a silently stale answer
    assert aindex.run_entries(root) is None  # readers fall back to scan
    c = aindex.refresh(root)
    assert c["_stats"]["full"]


def test_rewrite_holds_write_guard_and_bumps_generation(tmp_path,
                                                        monkeypatch):
    """The gc-compaction race fix: a reader (or the fleet service's
    catalog route) must see the mid-write signal while the catalog is
    being replaced, and the rewrite generation must move."""
    root, _store = _mkarchive(tmp_path, n=3)
    gen0 = catalog.generation(root)
    seen = []
    from sofa_tpu import durability
    real = durability.atomic_write

    def spying(path, *a, **kw):
        seen.append((os.path.basename(path), derived_writing(root)))
        return real(path, *a, **kw)

    monkeypatch.setattr(durability, "atomic_write", spying)
    catalog.rewrite(root, catalog.read_catalog(root)[:2])
    assert ("catalog.jsonl", True) in seen   # guarded during the swap
    assert catalog.generation(root) == gen0 + 1
    assert not derived_writing(root)         # and released after


def test_write_guard_is_reentrant(tmp_path):
    root = str(tmp_path)
    with derived_write_guard(root):
        with derived_write_guard(root):
            assert derived_writing(root)
        # the inner exit must NOT drop the outer holder's protection
        assert derived_writing(root)
    assert not derived_writing(root)


# ---------------------------------------------------------------------------
# Scan-vs-index identity.
# ---------------------------------------------------------------------------

def _ls_output(root, **cfg_kw):
    cfg = SofaConfig(logdir=str(root) + "-unused", archive_root=root,
                     **cfg_kw)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = sofa_archive(cfg, "ls")
    assert rc == 0
    return buf.getvalue()


@pytest.mark.parametrize("cfg_kw", [
    {},
    {"archive_limit": 4},
    {"archive_label": "nightly"},
    {"archive_host": "h1"},
    {"archive_host": "h2", "archive_limit": 2},
    {"archive_since": "1005"},
])
def test_ls_byte_identical_index_vs_scan(tmp_path, monkeypatch, cfg_kw):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    out_idx = _ls_output(root, **cfg_kw)
    monkeypatch.setenv("SOFA_ARCHIVE_INDEX", "0")
    out_scan = _ls_output(root, **cfg_kw)
    assert out_idx == out_scan
    assert f"{0:064x}"[:12] in _ls_output(root) or True  # smoke


def test_ls_limit_uses_tail_chunks_only(tmp_path):
    """The O(result) claim: a newest-N listing over a multi-chunk runs
    family materializes only the tail chunk(s) that hold the answer."""
    from sofa_tpu import frames

    root, store = _mkarchive(tmp_path, n=5)
    # shrink the chunk size so the family spans many chunks
    orig = aindex.INDEX_CHUNK_ROWS
    aindex.INDEX_CHUNK_ROWS = 4
    try:
        for i in range(20, 60):
            _append_run(root, store, i)
        aindex.refresh(root)
        handle = frames.open_chunk_store(
            aindex.family_dir(root, aindex.RUNS_FAMILY))
        assert len(handle.index["chunks"]) > 5
        cfg = SofaConfig(logdir="u", archive_root=root, archive_limit=3)
        runs, total, _bench, source = _ls_runs(root, cfg)
        assert source == "index" and len(runs) == 3 and total == 45
        # a fresh handle inside _ls_runs counted its own reads; prove it
        # again here: 3 newest rows live in the final chunk
        h2 = frames.open_chunk_store(
            aindex.family_dir(root, aindex.RUNS_FAMILY))
        tail = aindex.run_entries_tail(root, 3)
        assert tail is not None
    finally:
        aindex.INDEX_CHUNK_ROWS = orig


def test_regress_rolling_verdict_byte_identical(tmp_path, monkeypatch):
    """The acceptance proof for the baseline path: regress_verdict.json
    bytes agree between index-fed and scan-fed rolling windows (the
    clock frozen so generated_unix cannot differ)."""
    from sofa_tpu.archive.verdict import sofa_regress

    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    logdir = str(tmp_path / "run") + "/"
    os.makedirs(logdir)
    with open(logdir + "features.csv", "w") as f:
        f.write("name,value\nelapsed_time,25.0\n"
                "tpu0_sol_distance,9.5\nstep_time_mean,0.05\n")
    monkeypatch.setattr(time, "time", lambda: 1234567.0)

    def verdict_bytes():
        cfg = SofaConfig(logdir=logdir, archive_root=root,
                         regress_rolling=8)
        rc = sofa_regress(cfg, logdir)
        with open(os.path.join(logdir, "regress_verdict.json"),
                  "rb") as f:
            return rc, f.read()

    rc_idx, doc_idx = verdict_bytes()
    monkeypatch.setenv("SOFA_ARCHIVE_INDEX", "0")
    rc_scan, doc_scan = verdict_bytes()
    assert rc_idx == rc_scan
    assert doc_idx == doc_scan
    # sol distance has polarity now: far-above-baseline regresses
    doc = json.loads(doc_idx)
    sol = next(r for r in doc["features"]
               if r["name"] == "tpu0_sol_distance")
    assert sol["verdict"] == "regressed"


def test_rolling_samples_equal_and_docless(tmp_path, monkeypatch):
    root, store = _mkarchive(tmp_path)
    # one run with an unreadable doc + one with empty features: both
    # must be skipped by BOTH paths without counting toward the window
    _append_run(root, store, 70, features={})
    run_gone = _append_run(root, store, 71)
    os.unlink(store.run_doc_path(run_gone))
    aindex.refresh(root)
    idx = aindex.rolling_samples(root, 6)
    monkeypatch.setenv("SOFA_ARCHIVE_INDEX", "0")
    scan = baseline.rolling_samples(store, 6)
    assert idx == scan
    assert len(idx["elapsed_time"]) == 6


def test_offenders_equal_index_vs_scan(tmp_path):
    root, store = _mkarchive(tmp_path)
    aindex.refresh(root)
    idx = aindex.offenders(root, "tpu*_sol_distance", limit=7)
    scan = aindex.offenders_scan(store, "tpu*_sol_distance", limit=7)
    assert idx == scan
    assert idx[0]["value"] >= idx[-1]["value"]
    assert idx[0]["host"] and idx[0]["logdir"]


def test_reingest_duplicates_dedup_newest_wins(tmp_path):
    root, store = _mkarchive(tmp_path, n=4)
    # re-ingest run 2 later (same id, fresh catalog line, new t)
    run = f"{2:064x}"
    catalog.append_event(root, "ingest", run=run,
                         logdir="/fleet/h2/job2", files=0,
                         new_objects=0, bytes_added=0)
    aindex.refresh(root)
    runs = aindex.run_entries(root)
    scan = catalog.ingest_entries(catalog.read_catalog(root))
    assert [e["run"] for e in runs] == [e["run"] for e in scan]
    assert len([e for e in runs if e["run"] == run]) == 1
    # the duplicate-carrying catalog exercises the dedup rank path too
    assert aindex.offenders(root, "*", 10) == \
        aindex.offenders_scan(store, "*", 10)


# ---------------------------------------------------------------------------
# query() + fallbacks.
# ---------------------------------------------------------------------------

def test_query_runs_pagination_and_filters(tmp_path):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    q = aindex.query(root, kind="runs", limit=5)
    assert q["source"] == "index" and q["total"] == 12
    assert len(q["rows"]) == 5
    assert q["rows"][0]["t"] >= q["rows"][1]["t"]  # newest first
    q2 = aindex.query(root, kind="runs", limit=5, offset=5)
    assert [r["run"] for r in q2["rows"]] != [r["run"] for r in q["rows"]]
    qh = aindex.query(root, kind="runs", host="h1")
    assert qh["total"] == 4 and all(r["host"] == "h1"
                                    for r in qh["rows"])
    assert q["commit_sha"]


def test_query_features_page_matches_unpaged(tmp_path):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    full = aindex.query(root, kind="features",
                        feature="tpu*_sol_distance", limit=24)
    page = aindex.query(root, kind="features",
                        feature="tpu*_sol_distance", limit=5, offset=3)
    assert page["rows"] == full["rows"][3:8]
    assert page["total"] == full["total"] == 24


def test_query_scan_fallback_without_index(tmp_path):
    root, _store = _mkarchive(tmp_path, n=3)
    q = aindex.query(root, kind="runs")
    assert q["source"] == "scan" and q["total"] == 3
    assert q["commit_sha"] is None
    qf = aindex.query(root, kind="features", feature="tpu0_*")
    assert qf["source"] == "scan" and qf["total"] == 3


def test_query_empty_archive(tmp_path):
    root = str(tmp_path / "empty")
    ArchiveStore(root, create=True)
    q = aindex.query(root, kind="runs")
    assert q["total"] == 0 and q["rows"] == []
    c = aindex.refresh(root)
    assert c["events"] == 0 and aindex.is_current(root)
    assert aindex.query(root, kind="features")["rows"] == []


# ---------------------------------------------------------------------------
# The /v1/query service endpoint.
# ---------------------------------------------------------------------------

@pytest.fixture
def service(tmp_path):
    cfg = SofaConfig(logdir=str(tmp_path / "unused"),
                     serve_token=TOKEN, serve_port=0,
                     serve_quota_mb=0.001)  # ~1 KiB: trivially breached
    httpd = sofa_serve(cfg, root=str(tmp_path / "fleet"),
                       serve_forever=False)
    assert httpd is not None
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def _tenant_archive(httpd, tmp_path, n=8):
    root = httpd.tenant_root("default")
    ArchiveStore(root, create=True)
    tdir = tmp_path / "seed"
    os.makedirs(tdir, exist_ok=True)
    seeded, store = _mkarchive(tdir, n=n, name="a")
    # move the seed's contents into the tenant root
    import shutil

    for sub in ("runs",):
        for name in os.listdir(os.path.join(seeded, sub)):
            shutil.copy(os.path.join(seeded, sub, name),
                        os.path.join(root, sub, name))
    shutil.copy(catalog.catalog_path(seeded), catalog.catalog_path(root))
    aindex.refresh(root)
    return root


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_v1_query_auth_etag_pagination(service, tmp_path):
    _tenant_archive(service, tmp_path)
    base = service_url(service)
    # auth: no token -> 401; header and ?token= both accepted
    code, _h, _b = _get(f"{base}/v1/default/query?kind=runs")
    assert code == 401
    auth = {"Authorization": f"Bearer {TOKEN}"}
    code, hdrs, body = _get(f"{base}/v1/default/query?kind=runs&limit=3",
                            auth)
    assert code == 200
    doc = json.loads(body)
    assert doc["schema"] == "sofa_tpu/fleet_service"
    assert doc["source"] == "index" and len(doc["rows"]) == 3
    assert doc["total"] == 8
    etag = hdrs["ETag"]
    assert etag.startswith('"idx-')
    # ETag keyed on the index commit sha: unchanged commit -> 304
    code, _h, _b = _get(f"{base}/v1/default/query?kind=runs&limit=3",
                        {**auth, "If-None-Match": etag})
    assert code == 304
    code, _h, body = _get(
        f"{base}/v1/default/query?kind=features"
        f"&feature=tpu*_sol_distance&limit=4&offset=2&token={TOKEN}")
    assert code == 200
    doc = json.loads(body)
    assert doc["offset"] == 2 and len(doc["rows"]) == 4
    assert doc["rows"][0]["value"] >= doc["rows"][1]["value"]
    code, _h, _b = _get(f"{base}/v1/default/query?kind=bogus", auth)
    assert code == 400


def test_v1_query_answers_while_quota_exhausted(service, tmp_path):
    """The 429-quota interplay: a tenant refused uploads can still ask
    questions — the query route consumes no write slot and never checks
    quota."""
    _tenant_archive(service, tmp_path)
    base = service_url(service)
    auth = {"Authorization": f"Bearer {TOKEN}"}
    blob = b"x" * 4096  # over the fixture's ~1 KiB quota
    sha = __import__("hashlib").sha256(blob).hexdigest()
    req = urllib.request.Request(f"{base}/v1/default/object/{sha}",
                                 data=blob, method="PUT", headers=auth)
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 429")
    except urllib.error.HTTPError as e:
        assert e.code == 429
        assert json.load(e)["error"] == "quota"
    code, _h, body = _get(f"{base}/v1/default/query?kind=runs", auth)
    assert code == 200 and json.loads(body)["total"] == 8


def test_v1_query_scan_fallback_and_catalog_etag(service, tmp_path):
    root = _tenant_archive(service, tmp_path, n=3)
    aindex.drop(root)  # no index: fallback mode
    base = service_url(service)
    auth = {"Authorization": f"Bearer {TOKEN}"}
    code, hdrs, body = _get(f"{base}/v1/default/query?kind=runs", auth)
    assert code == 200
    assert json.loads(body)["source"] == "scan"
    etag = hdrs["ETag"]
    assert etag.startswith('"cat-')  # catalog size+mtime even in fallback
    code, _h, _b = _get(f"{base}/v1/default/query?kind=runs",
                        {**auth, "If-None-Match": etag})
    assert code == 304
    # /v1/catalog: Content-Length + the same ETag discipline + 304s
    code, hdrs, body = _get(f"{base}/v1/default/catalog", auth)
    assert code == 200
    assert int(hdrs["Content-Length"]) == len(body)
    assert body == open(catalog.catalog_path(root), "rb").read()
    code, _h, _b = _get(f"{base}/v1/default/catalog",
                        {**auth, "If-None-Match": hdrs["ETag"]})
    assert code == 304


def test_v1_reads_503_while_mid_gc(service, tmp_path):
    root = _tenant_archive(service, tmp_path, n=2)
    base = service_url(service)
    auth = {"Authorization": f"Bearer {TOKEN}"}
    with derived_write_guard(root):
        for route in ("catalog", "query?kind=runs"):
            code, hdrs, _b = _get(f"{base}/v1/default/{route}", auth)
            assert code == 503
            assert hdrs.get("Retry-After")
    code, _h, _b = _get(f"{base}/v1/default/catalog", auth)
    assert code == 200


def test_v1_query_cors_preflight(service, tmp_path):
    _tenant_archive(service, tmp_path, n=2)
    base = service_url(service)
    req = urllib.request.Request(f"{base}/v1/default/query",
                                 method="OPTIONS")
    with urllib.request.urlopen(req) as r:
        assert r.status == 204
        assert r.headers["Access-Control-Allow-Origin"] == "*"
    code, hdrs, _b = _get(
        f"{base}/v1/default/query?kind=runs&token={TOKEN}")
    assert code == 200
    assert hdrs.get("Access-Control-Allow-Origin") == "*"


# ---------------------------------------------------------------------------
# Crash / integrity / repair.
# ---------------------------------------------------------------------------

def test_kill_mid_refresh_leaves_old_commit_then_converges(tmp_path):
    """A hard exit between chunk-store writes must leave the previous
    commit in charge (stale -> scan fallback, never a torn answer), and
    the next refresh must converge to the byte-identical commit a
    never-interrupted rebuild produces."""
    root, store = _mkarchive(tmp_path, n=5)
    aindex.refresh(root)
    commit0 = open(aindex.commit_path(root), "rb").read()
    _append_run(root, store, 90)
    env = dict(os.environ, SOFA_INDEX_EXIT_AFTER="2")
    env.pop("_SOFA_INDEX_WRITES", None)
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, sys.argv[2]);"
         "from sofa_tpu.archive import index;"
         "index.refresh(sys.argv[1])", root, REPO],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 87, r.stderr[-300:]
    # the interrupted refresh never committed: old commit, stale index
    assert open(aindex.commit_path(root), "rb").read() == commit0
    assert not aindex.is_current(root)
    assert aindex.run_entries(root) is None  # readers scan, honestly
    aindex.refresh(root)
    assert aindex.is_current(root)
    recovered = open(aindex.commit_path(root), "rb").read()
    # never-interrupted twin
    aindex.drop(root)
    aindex.refresh(root)
    assert open(aindex.commit_path(root), "rb").read() == recovered


def test_fsck_detects_and_repairs_rotted_index_chunk(tmp_path):
    import glob

    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    chunk = sorted(glob.glob(
        os.path.join(root, "_index", "features", "*.arrow")))[0]
    size = os.path.getsize(chunk)
    with open(chunk, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xde\xad\xbe\xef\xde\xad")
    report = archive_fsck(root)
    assert report["index"], "rotted index chunk not detected"
    report = archive_fsck(root, repair=True)
    assert report["index"] == []
    assert aindex.is_current(root) and aindex.verify(root) == []


def test_fsck_flags_commitless_index_dir(tmp_path):
    root, _store = _mkarchive(tmp_path, n=2)
    aindex.refresh(root)
    os.unlink(aindex.commit_path(root))
    assert aindex.verify(root) == ["_index/index_commit.json"]
    report = archive_fsck(root, repair=True)
    assert report["index"] == [] and aindex.is_current(root)


def test_manifest_check_validates_index_commit(tmp_path):
    root, _store = _mkarchive(tmp_path, n=3)
    aindex.refresh(root)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import manifest_check
    finally:
        sys.path.pop(0)
    doc = json.load(open(aindex.commit_path(root)))
    assert manifest_check.validate_index_commit(doc) == []
    assert manifest_check.check_path(root) == 0
    bad = dict(doc, version=99, commit_sha="")
    probs = manifest_check.validate_index_commit(bad)
    assert any("version" in p for p in probs)
    assert any("commit_sha" in p for p in probs)
    # a family index disagreeing with the commit manifest is flagged
    fpath = os.path.join(aindex.family_dir(root, "runs"),
                         "frame_index.json")
    fdoc = json.load(open(fpath))
    fdoc["rows"] = 999
    with open(fpath, "w") as f:
        json.dump(fdoc, f)
    assert manifest_check.check_path(root) == 1


def test_index_is_pure_derived_state_drop_rebuild(tmp_path, monkeypatch):
    root, _store = _mkarchive(tmp_path)
    aindex.refresh(root)
    before = open(aindex.commit_path(root), "rb").read()
    aindex.drop(root)
    assert not os.path.isdir(aindex.index_dir(root))
    assert aindex.run_entries(root) is None
    # SOFA_ARCHIVE_INDEX=0 also forces scan even with a fresh index
    aindex.refresh(root)
    assert open(aindex.commit_path(root), "rb").read() == before
    monkeypatch.setenv("SOFA_ARCHIVE_INDEX", "0")
    assert aindex.run_entries(root) is None
    assert aindex.query(root, kind="runs")["source"] == "scan"


def test_ingest_commit_point_refreshes_index(tmp_path):
    """The write path feeds the read path: a real `sofa archive`
    ingest leaves a CURRENT index behind (store.ingest_run's commit
    point), so the very next ls/regress/query is index-fed."""
    from sofa_tpu import durability
    from sofa_tpu.archive.store import ingest_run

    logdir = str(tmp_path / "log") + "/"
    os.makedirs(logdir)
    with open(logdir + "sofa_time.txt", "w") as f:
        f.write("1000.0\n")
    with open(logdir + "features.csv", "w") as f:
        f.write("name,value\nelapsed_time,1.5\n")
    durability.write_digests(logdir)
    root = str(tmp_path / "arch")
    cfg = SofaConfig(logdir=logdir)
    summary = ingest_run(cfg, root)
    assert aindex.is_current(root)
    runs = aindex.run_entries(root)
    assert [e["run"] for e in runs] == [summary["run"]]


def test_render_ls_backcompat_scan_signature(tmp_path):
    root, _store = _mkarchive(tmp_path, n=2)
    lines = render_ls(root)  # no-args form computes the scan itself
    assert "2 run(s)" in lines[0]
    assert len(lines) == 4  # header + table header + one row per run
