"""Shared plumbing for the built-in workloads.

Mesh construction, timing loops, and the ``sofa``-aware step-marker
annotation.  Marker names follow the ``sofa_step`` convention the AISI
iteration detector keys on, mirroring how the reference located iterations
from repeated kernel-name subsequences (/root/reference/bin/sofa_aisi.py:110-136)
— with explicit markers the detection is exact instead of fuzzy, and the
suffix-tree path remains as the fallback for unannotated programs.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def balanced_factorization(n: int, num_axes: int) -> Tuple[int, ...]:
    """Factor ``n`` into ``num_axes`` factors, largest first, as balanced as
    a greedy prime split allows (8, 3 axes -> (2, 2, 2); 12, 2 -> (4, 3))."""
    factors = [1] * num_axes
    # Prime-factorize n, then pack primes (largest first) onto the smallest bin.
    primes = []
    m, p = n, 2
    while p * p <= m:
        while m % p == 0:
            primes.append(p)
            m //= p
        p += 1
    if m > 1:
        primes.append(m)
    for prime in sorted(primes, reverse=True):
        i = int(np.argmin(factors))
        factors[i] *= prime
    return tuple(sorted(factors, reverse=True))


def fence(tree):
    """Synchronize on `tree`: block_until_ready PLUS a scalar read.

    jax.block_until_ready is the documented barrier and is what fences
    every device of a sharded tree — but on the tunneled single-chip
    backend (axon) it can return before execution finishes (measured
    2026-07-31: twenty ~112 ms kernels "completed" in 0.4 ms of wall time,
    then materializing the result took 1.6 s).  A device->host transfer of
    a computed element cannot resolve early, and the chip executes
    in order, so pulling one scalar afterwards closes that gap.  The pull
    only covers the device holding the first leaf's element 0 — exactly
    the single-device case where the axon bug lives; multi-device meshes
    rely on the block_until_ready barrier as before.  Every timing harness
    (bench.py, tools/overhead_budget.py, tools/tune_flash.py, validate_tpu
    timing checks) must use this, not bare block_until_ready.
    """
    leaves = [x for x in jax.tree.leaves(tree) if hasattr(x, "ndim")]
    jax.block_until_ready(leaves)
    if not leaves:
        return None
    leaf = leaves[0]
    if not getattr(leaf, "is_fully_addressable", True):
        # multi-host shardings can't be indexed from one process; the
        # block_until_ready barrier above is the whole fence there
        return None
    return np.asarray(leaf[(0,) * leaf.ndim])


def make_mesh(
    axis_names: Sequence[str],
    axis_sizes: Optional[Sequence[int]] = None,
    devices=None,
    platform: Optional[str] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    With ``axis_sizes=None`` the device count is balanced across the axes;
    an explicit size of -1 means "whatever is left".  ``platform="cpu"``
    selects the (virtual-device) CPU backend even when a TPU backend is the
    default — how tests and multi-chip dry runs get an 8-device mesh on a
    single-chip host.
    """
    if devices is None:
        devices = jax.devices(platform) if platform else jax.devices()
    devices = list(devices)
    n = len(devices)
    if axis_sizes is None:
        sizes = balanced_factorization(n, len(axis_names))
    else:
        sizes = list(axis_sizes)
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            if n % known:
                raise ValueError(f"{n} devices not divisible by {known}")
            sizes[sizes.index(-1)] = n // known
        if int(np.prod(sizes)) != n:
            raise ValueError(f"mesh {dict(zip(axis_names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(tuple(sizes))
    return Mesh(dev_array, tuple(axis_names))


def step_annotation(step: int):
    """TraceAnnotation wrapping one training/inference step.

    This is the TPU-era replacement for deriving iteration boundaries from
    kernel-name repetition: the annotation lands in the XPlane host plane and
    preprocess turns it into explicit iteration markers.
    """
    try:
        return jax.profiler.TraceAnnotation(f"sofa_step_{step}")
    except Exception:
        return nullcontext()


def steps_per_sec(step_fn, state, n_steps: int, warmup: int = 2,
                  annotate: bool = True) -> Tuple[float, object]:
    """Run ``state = step_fn(state)`` n_steps times and report steady-state
    steps/second (after ``warmup`` compile/autotune steps)."""
    for _ in range(warmup):
        state = step_fn(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for i in range(n_steps):
        with (step_annotation(i) if annotate else nullcontext()):
            state = step_fn(state)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    return n_steps / dt, state


def parse_workload_args(argv, defaults: Dict[str, object]):
    """Tiny ``--key value`` parser so workloads stay dependency-free.

    Also applies the env-over-config platform rule before any backend
    init: the image's sitecustomize may force-register a TPU platform
    whose init *hangs* when the device tunnel is down, and a user who set
    JAX_PLATFORMS=cpu (e.g. `sofa record` smoke runs) must win over it.
    """
    import argparse
    import os

    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    p = argparse.ArgumentParser()
    for k, v in defaults.items():
        if isinstance(v, bool):
            p.add_argument(f"--{k}", action=argparse.BooleanOptionalAction,
                           default=v)
        else:
            p.add_argument(f"--{k}", type=type(v), default=v)
    return p.parse_args(argv)
