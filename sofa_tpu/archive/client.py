"""Fleet transport client: the agent's half of the upload protocol.

Everything the service (archive/service.py) promises is only real if the
client USES it, so this module is where the resilience contract lives:

* **bounded**: every request carries a connect+read deadline
  (``--push_timeout_s``) — a stalled link degrades to a retry, never a
  wedged agent;
* **retrying with jitter**: transient failures (refused connections,
  timeouts, 5xx, 503-loaded/mid-gc, a hash-mismatch reject of a torn
  upload) retry up to ``--push_retries`` times with capped exponential
  backoff and jitter (concurrency.jittered_backoff); a server-sent
  ``Retry-After`` is honored as the floor of the wait;
* **typed refusals**: auth failures (401/403) and quota breaches
  (429 ``{"error": "quota"}``) raise :class:`ServiceRejected` — they
  will not clear on retry, so the agent keeps the run in its durable
  spool instead of hammering the service;
* **resumable**: :func:`push_run` always starts from the server's
  have-list, so a push interrupted anywhere — client SIGKILL, service
  death mid-upload, a dropped link — re-sends ZERO objects the server
  already committed.

Network fault injection (faults.py NET_KINDS, target ``service``) is
threaded through :meth:`ServiceClient._attempt`: ``conn_refused``/
``stall``/``http_500`` surface as the same exception the real failure
would raise, and ``partial@<f>`` truncates the upload body so the
SERVER's hash check — not a client shortcut — rejects it.  Every
retry/resume path is thereby testable without a flaky network.
"""

from __future__ import annotations

import json
import os
import socket
import time
import urllib.error
import urllib.request
from typing import Dict, Optional

from sofa_tpu import faults
from sofa_tpu.archive.protocol import (
    CLIENT_FATAL_STATUSES, CLIENT_RETRY_FLOOR, CLIENT_RETRY_STATUSES,
    ERR_QUOTA)
from sofa_tpu.concurrency import jittered_backoff
from sofa_tpu.printing import print_warning

#: The ``meta.health`` manifest section (docs/OBSERVABILITY.md): the
#: agent's view of its endpoint set at push time — active endpoint,
#: failover count, open breakers.  Bumps on BREAKING shape changes.
HEALTH_SCHEMA = "sofa_tpu/fleet_health"
HEALTH_VERSION = 1


class ServiceUnavailable(Exception):
    """A transient transport failure — retry with backoff."""

    def __init__(self, msg: str, status: Optional[int] = None,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


class ServiceRejected(Exception):
    """A typed refusal that retrying cannot clear (auth, quota, bad
    request) — the agent's cue to fall back to the durable spool."""

    def __init__(self, msg: str, status: Optional[int] = None,
                 quota: bool = False):
        super().__init__(msg)
        self.status = status
        self.quota = quota


class ServiceIncomplete(Exception):
    """Commit refused: objects are missing server-side (409) — resume
    from the attached have-list."""

    def __init__(self, msg: str, missing):
        super().__init__(msg)
        self.missing = list(missing or [])


class ServiceClient:
    """One service endpoint SET + tenant + token, with the retry policy.

    ``url`` may be a comma-separated failover list (``--service
    url1,url2,...``): requests prefer the first endpoint whose circuit
    breaker is closed.  A connection-level failure (refused, reset,
    timeout — the endpoint itself is suspect) opens that endpoint's
    breaker for a jittered-backoff window and the next attempt moves to
    a sibling, health-probed first (``GET /v1/health``) so a dead
    sibling costs one cheap GET, not a full request cycle.  An HTTP
    error (the endpoint answered — it is alive, just loaded or
    refusing) never trips the breaker.  Failing over is never silent:
    it is printed, counted (``failovers``), and stamped into
    ``meta.health``."""

    def __init__(self, url: str, token: str, tenant: str = "default",
                 timeout_s: float = 10.0, retries: int = 4,
                 backoff_s: float = 0.5, backoff_cap_s: float = 30.0,
                 rng=None):
        self.endpoints = [u.strip().rstrip("/") for u in url.split(",")
                          if u.strip()]
        self.base = self.endpoints[0] if self.endpoints \
            else url.rstrip("/")
        self.token = token
        self.tenant = tenant
        self.timeout_s = max(float(timeout_s), 0.1)
        self.retries = max(int(retries), 0)
        self.backoff_s = max(float(backoff_s), 0.0)
        self.backoff_cap_s = max(float(backoff_cap_s), self.backoff_s)
        import random

        self.rng = rng if rng is not None else random
        # transparency counters the agent folds into meta.agent
        self.attempts = 0
        self.retried = 0
        self.failovers = 0
        #: url -> (consecutive fails, monotonic open-until) — the
        #: per-endpoint circuit breaker ledger
        self._breaker: Dict[str, tuple] = {}
        # cross-process push trace id (docs/FLEET.md "Observing the
        # tier"): when set, every request carries it as X-Sofa-Trace so
        # the service's spans join the agent's under ONE id
        self.trace_id = ""

    # -- circuit breaker ---------------------------------------------------
    def _note_endpoint_down(self, url: str) -> None:
        fails, _until = self._breaker.get(url, (0, 0.0))
        fails += 1
        hold = jittered_backoff(fails - 1, self.backoff_s,
                                self.backoff_cap_s, self.rng)
        self._breaker[url] = (fails, time.monotonic() + hold)

    def _note_endpoint_up(self, url: str) -> None:
        self._breaker.pop(url, None)

    def breaker_open(self, url: str) -> bool:
        """True while the endpoint is untrusted — its ledger entry
        stands until a request on it succeeds (``_note_endpoint_up``).
        The jittered hold only delays when ``_select_endpoint`` starts
        health-probing it again; a probe pass still routes ONE request
        there before the ledger clears, so expiry alone never re-opens
        this answer."""
        fails, _until = self._breaker.get(url, (0, 0.0))
        return fails > 0

    def check_health(self, url: str) -> bool:
        """``GET /v1/health`` (unauthenticated, like the server's ping):
        True only for an endpoint that is up AND accepting — a draining
        worker answers 503 here, so the breaker routes around a rolling
        restart without burning a real push on it."""
        req = urllib.request.Request(f"{url}/v1/health")
        try:
            with urllib.request.urlopen(
                    req, timeout=min(self.timeout_s, 3.0)) as resp:
                doc = json.loads(resp.read() or b"{}")
        except (OSError, ValueError, urllib.error.URLError):
            return False
        return bool(isinstance(doc, dict) and doc.get("ok", True))

    def _select_endpoint(self) -> str:
        """The endpoint this attempt should use: first closed-breaker
        endpoint in preference order (a previously-failed one must pass
        a health probe before being trusted again).  With EVERY breaker
        open, the one that re-closes soonest — the client never refuses
        to try at all; the service may be back."""
        now = time.monotonic()
        best, best_until = None, None
        for url in self.endpoints:
            fails, until = self._breaker.get(url, (0, 0.0))
            if until <= now:
                if fails == 0 or self.check_health(url):
                    return url
                # the probe said no: re-open and keep looking
                self._note_endpoint_down(url)
                _f, until = self._breaker.get(url, (0, 0.0))
            if best_until is None or until < best_until:
                best, best_until = url, until
        return best or self.base

    # -- single attempt ----------------------------------------------------
    def _attempt(self, method: str, path: str, body: "bytes | None",
                 op: str, key: str) -> dict:
        url = f"{self.base}{path}"
        self.attempts += 1
        try:
            spec = faults.maybe_service_fault(op, key)
            if spec is not None:
                if spec.kind == "conn_refused":
                    raise urllib.error.URLError(
                        ConnectionRefusedError("injected conn_refused"))
                if spec.kind == "conn_reset":
                    # the connection died mid-request: the ack (if any)
                    # is lost in flight and the request may or may not
                    # have landed server-side — exactly why every verb
                    # is idempotent (the retry is a committed no-op)
                    raise ConnectionResetError("injected conn_reset")
                if spec.kind == "stall":
                    # models the read deadline having expired — the
                    # exception the bounded timeout would raise, without
                    # actually burning the wall-clock
                    raise socket.timeout("injected stall")
                if spec.kind == "http_500":
                    raise urllib.error.HTTPError(
                        url, 500, "injected http_500", None, None)
                if spec.kind == "partial" and body and op == "put":
                    # truncated-upload fault: only object bodies — the
                    # SERVER's hash check is the rejection under test
                    # (a cut JSON control request would just be a 400)
                    body = body[:max(int(len(body) * spec.fraction), 1)]
            req = urllib.request.Request(url, data=body, method=method)
            req.add_header("Authorization", f"Bearer {self.token}")
            # the push deadline (absolute unix seconds): when THIS
            # request's read timeout expires the client is gone — a
            # worker that sees the deadline already passed abandons the
            # work instead of answering nobody (docs/FLEET.md)
            req.add_header(
                "X-Sofa-Deadline",
                f"{time.time() + self.timeout_s:.3f}")  # sofa-lint: disable=SL003 — the deadline crosses process+machine boundaries; monotonic has no common epoch, wall clock is the only shared one (skew is capped server-side)
            if self.trace_id:
                req.add_header("X-Sofa-Trace", self.trace_id)
            if body is not None:
                req.add_header("Content-Type", "application/octet-stream")
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            doc = _error_doc(e)
            if e.code in CLIENT_FATAL_STATUSES:
                raise ServiceRejected(
                    f"{op}: service rejected the token ({e.code})",
                    status=e.code) from None
            if e.code == 429 and doc.get("error") == ERR_QUOTA:
                raise ServiceRejected(
                    f"{op}: tenant {self.tenant!r} is over quota "
                    f"({doc.get('used_mb')}/{doc.get('quota_mb')} MB)",
                    status=429, quota=True) from None
            if e.code == 409:
                raise ServiceIncomplete(
                    f"{op}: commit refused, "
                    f"{len(doc.get('missing') or [])} object(s) missing "
                    "server-side", doc.get("missing")) from None
            if e.code in CLIENT_RETRY_STATUSES or \
                    e.code >= CLIENT_RETRY_FLOOR:
                raise ServiceUnavailable(
                    f"{op}: HTTP {e.code} ({doc.get('error') or e.reason})",
                    status=e.code,
                    retry_after=_retry_after(e)) from None
            raise ServiceRejected(f"{op}: HTTP {e.code} "
                                  f"({doc.get('error') or e.reason})",
                                  status=e.code) from None
        except (socket.timeout, TimeoutError) as e:
            raise ServiceUnavailable(f"{op}: timed out after "
                                     f"{self.timeout_s}s: {e}") from None
        except urllib.error.URLError as e:
            raise ServiceUnavailable(f"{op}: {e.reason}") from None
        except (ConnectionError, OSError, ValueError) as e:
            raise ServiceUnavailable(f"{op}: {e}") from None

    # -- retry loop --------------------------------------------------------
    def _call(self, method: str, path: str, body: "bytes | None",
              op: str, key: str = "") -> dict:
        attempt = 0
        while True:
            if len(self.endpoints) > 1:
                url = self._select_endpoint()
                if url != self.base:
                    self.failovers += 1
                    print_warning(
                        f"service: failing over {self.base} -> {url} "
                        "(circuit breaker)")
                    self.base = url
            try:
                result = self._attempt(method, path, body, op, key)
                self._note_endpoint_up(self.base)
                return result
            except ServiceUnavailable as e:
                if e.status is None:
                    # connection-level (refused/reset/timeout): the
                    # ENDPOINT is suspect — open its breaker so the
                    # retry prefers a sibling.  An HTTP status means
                    # the endpoint answered; it stays trusted.
                    self._note_endpoint_down(self.base)
                if attempt >= self.retries:
                    raise
                delay = jittered_backoff(attempt, self.backoff_s,
                                         self.backoff_cap_s, self.rng)
                if e.retry_after is not None:
                    delay = min(max(delay, float(e.retry_after)),
                                self.backoff_cap_s)
                self.retried += 1
                attempt += 1
                time.sleep(delay)

    # -- protocol ----------------------------------------------------------
    def ping(self) -> dict:
        return self._call("GET", "/v1/ping", None, "ping")

    def have(self, files: Dict[str, dict]) -> dict:
        body = json.dumps({"files": files}).encode()
        return self._call("POST", f"/v1/{self.tenant}/have", body, "have")

    def put_object(self, sha: str, data: bytes) -> dict:
        return self._call("PUT", f"/v1/{self.tenant}/object/{sha}", data,
                          "put", key=sha)

    def commit(self, doc: dict) -> dict:
        body = json.dumps(doc).encode()
        return self._call("POST", f"/v1/{self.tenant}/commit", body,
                          "commit")


def _error_doc(e: urllib.error.HTTPError) -> dict:
    try:
        doc = json.loads(e.read() or b"{}")
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError, AttributeError):
        return {}


def _retry_after(e: urllib.error.HTTPError) -> Optional[float]:
    try:
        v = (e.headers or {}).get("Retry-After")
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def client_from_cfg(cfg, rng=None) -> "ServiceClient | None":
    """A client for the configured service, or None in spool-only mode
    (no ``--service`` / SOFA_AGENT_SERVICE)."""
    from sofa_tpu.archive.service import resolve_token

    url = (getattr(cfg, "agent_service", "")
           or os.environ.get("SOFA_AGENT_SERVICE", "") or "").strip()
    if not url:
        return None
    return ServiceClient(
        url, resolve_token(cfg),
        tenant=getattr(cfg, "fleet_tenant", "default") or "default",
        timeout_s=getattr(cfg, "agent_timeout_s", 10.0),
        retries=getattr(cfg, "agent_retries", 4),
        backoff_s=getattr(cfg, "agent_backoff_s", 0.5),
        backoff_cap_s=getattr(cfg, "agent_backoff_cap_s", 30.0),
        rng=rng)


def push_run(store, run_id: str, client: ServiceClient) -> dict:
    """Push one spooled run to the service; idempotent and resumable.

    Always begins from the server's have-list, so only objects the
    server lacks travel; returns ``{"run", "status", "objects_sent",
    "bytes_sent", "new", "server": <commit ack>}``.  Raises the client's
    typed exceptions on failure — the caller (sofa_tpu/agent.py) owns
    the spool-and-retry-later decision."""
    doc = store.load_run(run_id)
    if doc is None:
        raise ServiceRejected(
            f"spooled run {run_id[:12]} has no readable run doc — run "
            "`sofa archive fsck` on the spool", status=None)
    files = doc.get("files") or {}
    sent = 0
    sent_bytes = 0
    for round_no in (1, 2):
        have = client.have(files)
        if have.get("committed"):
            return {"run": run_id, "status": "committed", "new": False,
                    "objects_sent": sent, "bytes_sent": sent_bytes,
                    "server": have}
        for sha in have.get("missing") or []:
            data = store.read_object(sha)
            if data is None:
                raise ServiceRejected(
                    f"spool object {sha[:12]} is unreadable — run "
                    "`sofa archive fsck` on the spool", status=None)
            client.put_object(sha, data)
            sent += 1
            sent_bytes += len(data)
        try:
            ack = client.commit(doc)
            return {"run": run_id, "status": "pushed",
                    "new": bool(ack.get("new")), "objects_sent": sent,
                    "bytes_sent": sent_bytes, "server": ack}
        except ServiceIncomplete as e:
            # an object vanished between have and commit (gc racing a
            # slow push, or a competing agent's store sweep): one more
            # have->put->commit round resolves it, a second miss is real
            if round_no == 2:
                raise ServiceUnavailable(
                    f"commit still missing {len(e.missing)} object(s) "
                    "after a resume round") from None
            print_warning(
                f"push {run_id[:12]}: server reports "
                f"{len(e.missing)} object(s) missing at commit — "
                "resuming from a fresh have-list")
    raise ServiceUnavailable("unreachable")  # pragma: no cover
