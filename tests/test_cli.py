import pytest

from sofa_tpu.cli import build_parser, config_from_args


def parse(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_record_flags():
    cfg = parse(["record", "sleep 1", "--logdir", "x", "--sys_mon_rate", "33",
                 "--enable_strace", "--disable_xprof"])
    assert cfg.command == "sleep 1"
    assert cfg.logdir == "x/"
    assert cfg.sys_mon_rate == 33
    assert cfg.enable_strace
    assert not cfg.enable_xprof


def test_filter_flags():
    cfg = parse(["preprocess", "--cpu_filters", "idle:black,mem:red",
                 "--tpu_filters", "all-reduce:indigo"])
    assert [f.keyword for f in cfg.cpu_filters] == ["idle", "mem"]
    assert cfg.tpu_filters[0].color == "indigo"


def test_cluster_hosts():
    cfg = parse(["report", "--cluster_hosts", "a,b,c"])
    assert cfg.cluster_hosts == ["a", "b", "c"]


def test_toml_with_cli_override(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text('sys_mon_rate = 5\nviz_port = 9999\n')
    cfg = parse(["analyze", "--config", str(p), "--viz_port", "7777"])
    assert cfg.sys_mon_rate == 5       # from file
    assert cfg.viz_port == 7777        # CLI wins


def test_record_without_command_errors(capsys):
    from sofa_tpu.cli import main
    assert main(["record"]) == 2


def test_unknown_subcommand_rejected():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["explode"])


def test_setup_check_mode(capsys):
    from sofa_tpu.cli import main

    rc = main(["setup"])
    out = capsys.readouterr()
    text = out.out + out.err
    assert rc in (0, 1)
    assert "perf_event_paranoid" in text


def test_setup_apply_uses_runner(monkeypatch):
    from sofa_tpu import setup_env

    ran = []
    monkeypatch.setattr(setup_env, "check",
                        lambda utilities=None, probe_device=True: (["sysctl -w a=b"], 1))
    rc = setup_env.sofa_setup(apply=True, runner=lambda c: ran.append(c) or 0)
    assert rc == 0
    assert ran == ["sysctl -w a=b"]


def test_setup_reports_fixes_without_apply(monkeypatch, capsys):
    from sofa_tpu import setup_env

    monkeypatch.setattr(setup_env, "check",
                        lambda utilities=None, probe_device=True: (["setcap x /bin/tcpdump"], 1))
    rc = setup_env.sofa_setup(apply=False)
    assert rc == 1
    assert "setcap x /bin/tcpdump" in capsys.readouterr().out


def test_viz_bind_default_is_loopback(tmp_path):
    import urllib.request

    from sofa_tpu.config import SofaConfig
    from sofa_tpu.viz import sofa_viz

    d = tmp_path / "log"
    d.mkdir()
    (d / "index.html").write_text("<html>ok</html>")
    cfg = SofaConfig(logdir=str(d) + "/", viz_port=8991)
    httpd = sofa_viz(cfg, serve_forever=False)
    assert httpd is not None
    try:
        assert httpd.server_address[0] == "127.0.0.1"
        import threading
        t = threading.Thread(target=httpd.handle_request, daemon=True)
        t.start()
        port = httpd.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/index.html", timeout=5).read()
        assert b"ok" in body
    finally:
        httpd.server_close()


def test_viz_bind_flag():
    cfg = parse(["viz", "--viz_bind", "0.0.0.0"])
    assert cfg.viz_bind == "0.0.0.0"


def test_board_parallel_coords_surface():
    """The cpu/tpu report pages expose the reference's per-dimension
    brushing (d3 parallel-coordinates in sofaboard/cpu-report.html:86-162)
    via the board's own canvas renderer — no JS runtime in CI, so assert
    the structural contract: the renderer class + its page wiring, the
    brush handlers, and that both pages request real schema columns."""
    import os
    import re

    board = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "sofa_tpu", "board")
    js = open(os.path.join(board, "sofa_board.js")).read()
    assert "class ParallelCoords" in js
    assert "async function mountParallelCoords" in js
    for handler in ("mousedown", "mousemove", "mouseup", "dblclick"):
        assert handler in js, handler
    assert js.count("{") == js.count("}")  # crude parse sanity

    from sofa_tpu.trace import COLUMNS

    for page, source in (("cpu-report.html", "cputrace.csv"),
                         ("tpu-report.html", "tputrace.csv")):
        html = open(os.path.join(board, page)).read()
        assert "mountParallelCoords" in html, page
        assert source in html, page
        dims = re.findall(r'key:\s*"(\w+)"', html)
        assert len(dims) >= 5, (page, dims)
        for d in dims:
            assert d in COLUMNS, (page, d)


def test_report_missing_logdir_clean_error(tmp_path):
    """report/preprocess on a never-recorded logdir: one [ERROR] line and
    rc 1, not a FileNotFoundError traceback (found in adversarial drives)."""
    import os
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "report",
         "--logdir", str(tmp_path / "never") + "/"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 1
    assert "Traceback" not in r.stderr
    assert "does not exist" in r.stderr + r.stdout


def test_setup_backend_probe_is_bounded(monkeypatch, capsys):
    """`sofa setup` diagnoses a dead device tunnel (subprocess-bounded
    probe) instead of hanging like in-process jax.devices() would."""
    import subprocess as sp

    from sofa_tpu import setup_env

    def hang(*a, **k):
        raise sp.TimeoutExpired(cmd="probe", timeout=30)

    monkeypatch.setattr(setup_env.subprocess, "run", hang)
    setup_env._probe_backend()
    out = capsys.readouterr()
    text = out.out + out.err
    assert "hung" in text and "tunnel" in text

    def healthy(*a, **k):
        class R:
            returncode = 0
            stdout = "tpu 1 TPU v5e\n"
            stderr = ""
        return R()

    monkeypatch.setattr(setup_env.subprocess, "run", healthy)
    setup_env._probe_backend()
    text = capsys.readouterr().out
    assert "healthy: tpu" in text
