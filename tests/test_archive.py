"""Fleet trace archive + regression service (ISSUE 7).

Covers: byte-level dedup across ingests, gc retention, catalog torn-tail
tolerance, archive fsck detection/repair, rolling-percentile baseline
math, typed-verdict exit codes via real subprocess, the tile-diff
"unchanged" fast path, the `sofa clean` archive guard, `sofa resume`
replay of a killed ingest, and ml/diff.py's degradation contract.  The
end-to-end SIGKILL proof lives in tools/chaos_matrix.py's
kill-mid-archive cell.
"""

import json
import os
import subprocess
import sys

import pandas as pd
import pytest

from sofa_tpu import durability
from sofa_tpu.archive import catalog, is_archive_root, resolve_root
from sofa_tpu.archive import baseline as bl
from sofa_tpu.archive.store import (
    ArchiveStore,
    archive_fsck,
    gc,
    ingest_run,
    run_content_id,
    tile_diff,
)
from sofa_tpu.config import SofaConfig
from sofa_tpu.preprocess import sofa_preprocess
from sofa_tpu.record import sofa_clean

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_logdir(tmp_path, name="log", elapsed=1.5,
                 step_time=0.05) -> SofaConfig:
    """Smallest archivable logdir: preprocess output + a feature vector."""
    ld = str(tmp_path / name) + "/"
    os.makedirs(ld, exist_ok=True)
    with open(ld + "sofa_time.txt", "w") as f:
        f.write("1000.0\n")
    with open(ld + "misc.txt", "w") as f:
        f.write(f"elapsed_time {elapsed}\ncores 2\npid 1\nrc 0\n")
    cfg = SofaConfig(logdir=ld)
    sofa_preprocess(cfg)
    with open(ld + "features.csv", "w") as f:
        f.write("name,value\n"
                f"elapsed_time,{elapsed}\n"
                f"step_time_mean,{step_time}\n"
                "tpu_ops,100\n")
    durability.write_digests(ld)
    return cfg


def _store_bytes(root: str) -> int:
    total = 0
    for dirpath, _dirs, names in os.walk(os.path.join(root, "objects")):
        for n in names:
            total += os.path.getsize(os.path.join(dirpath, n))
    return total


# --- dedup ------------------------------------------------------------------

def test_double_ingest_grows_store_by_catalog_entry_only(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    s1 = ingest_run(cfg, root)
    bytes_after_first = _store_bytes(root)
    cat_lines = len(catalog.read_catalog(root))
    s2 = ingest_run(cfg, root)
    assert s2["run"] == s1["run"]          # content-addressed run id
    assert s2["new_objects"] == 0 and s2["bytes_added"] == 0
    assert _store_bytes(root) == bytes_after_first
    assert len(catalog.read_catalog(root)) == cat_lines + 1
    # readers dedup by run id: still ONE run
    assert len(catalog.ingest_entries(catalog.read_catalog(root))) == 1


def test_shared_objects_dedup_across_different_runs(tmp_path):
    cfg_a = _mini_logdir(tmp_path, "a", elapsed=1.5)
    cfg_b = _mini_logdir(tmp_path, "b", elapsed=2.5)
    root = str(tmp_path / "arch")
    s1 = ingest_run(cfg_a, root)
    s2 = ingest_run(cfg_b, root)
    assert s2["run"] != s1["run"]
    # the unchanged artifacts (sofa_time.txt, identical empty frames)
    # landed once: the second ingest added fewer objects than it has files
    assert s2["new_objects"] < s2["files"]


def test_run_content_id_is_order_independent():
    files = {"a.csv": {"sha256": "aa"}, "b.csv": {"sha256": "bb"}}
    flipped = dict(reversed(list(files.items())))
    assert run_content_id(files) == run_content_id(flipped)
    assert run_content_id(files) != run_content_id(
        {"a.csv": {"sha256": "aa"}})


# --- catalog ----------------------------------------------------------------

def test_catalog_torn_tail_tolerated(tmp_path):
    root = str(tmp_path / "arch")
    ArchiveStore(root, create=True)
    catalog.append_event(root, "ingest", run="x" * 64, files=1)
    catalog.append_event(root, "bench", metric="m", value=1.0)
    with open(catalog.catalog_path(root), "a") as f:
        f.write('{"ev":"ingest","run":"torn-mid-wri')   # the crash case
    entries = catalog.read_catalog(root)
    assert len(entries) == 2
    assert catalog.bench_entries(entries)[0]["value"] == 1.0


# --- gc ---------------------------------------------------------------------

def test_gc_keep_retention_sweeps_unreferenced_objects(tmp_path):
    root = str(tmp_path / "arch")
    cfgs = [_mini_logdir(tmp_path, f"r{i}", elapsed=1.0 + i)
            for i in range(3)]
    for c in cfgs:
        ingest_run(c, root)
    store = ArchiveStore(root)
    assert len(store.run_ids()) == 3
    bytes_before = _store_bytes(root)
    summary = gc(root, keep=2)
    assert summary["dropped_runs"] == 1
    assert summary["swept_objects"] > 0
    assert len(store.run_ids()) == 2
    assert _store_bytes(root) < bytes_before
    # shared objects survive: remaining runs still extract completely
    report = archive_fsck(root)
    assert not report["missing"] and not report["corrupt"]
    # gc'd state is still catalog-consistent
    assert len(catalog.ingest_entries(catalog.read_catalog(root))) == 2


def test_gc_requires_policy_via_cli(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "archive", "gc",
         "--archive_root", str(tmp_path / "arch")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT))
    assert r.returncode == 2    # refuses to guess a retention policy


# --- fsck -------------------------------------------------------------------

def test_fsck_detects_and_repairs_corrupted_frame(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    ingest_run(cfg, root)
    store = ArchiveStore(root)
    run_id = store.run_ids()[0]
    doc = store.load_run(run_id)
    sha = doc["files"]["tputrace.csv"]["sha256"]
    with open(store.object_path(sha), "ab") as f:
        f.write(b"rot")                       # silent bit-rot
    report = archive_fsck(root)
    assert any(sha in c for c in report["corrupt"])
    # repair: the source logdir still holds matching bytes -> restored
    report = archive_fsck(root, repair=True)
    assert not report["corrupt"]
    report = archive_fsck(root)
    assert not report["corrupt"] and not report["missing"]


def test_fsck_quarantines_when_source_gone(tmp_path):
    import shutil

    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    ingest_run(cfg, root)
    store = ArchiveStore(root)
    doc = store.load_run(store.run_ids()[0])
    sha = doc["files"]["tputrace.csv"]["sha256"]
    with open(store.object_path(sha), "ab") as f:
        f.write(b"rot")
    shutil.rmtree(cfg.logdir)                 # source gone: unrepairable
    report = archive_fsck(root, repair=True)
    assert not report["corrupt"]              # quarantined, not left rotted
    assert any("quarantined" in m for m in report["missing"])
    assert os.path.isfile(os.path.join(root, "_quarantine", sha))


def test_fsck_adopts_uncataloged_run(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    ingest_run(cfg, root)
    # simulate a crash between run-doc write and catalog append
    os.unlink(catalog.catalog_path(root))
    report = archive_fsck(root)
    assert len(report["uncataloged"]) == 1
    report = archive_fsck(root, repair=True)
    assert not report["uncataloged"]
    entries = catalog.ingest_entries(catalog.read_catalog(root))
    assert len(entries) == 1 and entries[0]["run"] == \
        ArchiveStore(root).run_ids()[0]


def test_fsck_verb_dispatches_on_archive_root(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    ingest_run(cfg, root)
    assert durability.sofa_fsck(SofaConfig(logdir=root)) == 0
    # orphaned tmp is damage until repaired
    stage = os.path.join(root, "objects", "zz")
    os.makedirs(stage, exist_ok=True)
    with open(os.path.join(stage, "dead.tmp"), "w") as f:
        f.write("x")
    assert durability.sofa_fsck(SofaConfig(logdir=root)) == 1
    assert durability.sofa_fsck(SofaConfig(logdir=root), repair=True) == 0


# --- resume replay ----------------------------------------------------------

def test_resume_replays_uncommitted_archive_stage(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    ingest_run(cfg, root)
    run_id = ArchiveStore(root).run_ids()[0]
    # drop the archive commit marker: a crash one instruction short
    jpath = cfg.path(durability.JOURNAL_NAME)
    with open(jpath) as f:
        lines = [ln for ln in f.read().splitlines()
                 if not ('"commit"' in ln and '"archive"' in ln)]
    with open(jpath, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert durability.sofa_resume(cfg) == 0
    # replay re-ingested into the SAME root (from the begin entry), deduped
    entries = catalog.ingest_entries(catalog.read_catalog(root))
    assert len(entries) == 1 and entries[0]["run"] == run_id
    report = archive_fsck(root)
    assert not any(report[v] for v in ("corrupt", "missing", "orphaned",
                                       "uncataloged"))


# --- sofa clean guard -------------------------------------------------------

def test_clean_never_sweeps_nested_archive_root(tmp_path):
    cfg = _mini_logdir(tmp_path)
    nested = cfg.path("board")     # a DERIVED_DIRS name, worst case
    ingest_run(cfg, nested)
    assert is_archive_root(nested)
    marker_mtime = os.path.getmtime(os.path.join(nested,
                                                 "sofa_archive.json"))
    sofa_clean(cfg)
    assert is_archive_root(nested)                 # survived the sweep
    assert os.path.isfile(catalog.catalog_path(nested))
    assert len(ArchiveStore(nested).run_ids()) == 1
    assert os.path.getmtime(os.path.join(
        nested, "sofa_archive.json")) == marker_mtime
    assert not os.path.isfile(cfg.path("report.js"))  # clean still cleaned


def test_digests_skip_nested_archive(tmp_path):
    cfg = _mini_logdir(tmp_path)
    nested = cfg.path("my_archive")
    ingest_run(cfg, nested)
    doc = durability.compute_digests(cfg.logdir)
    assert not any(rel.startswith("my_archive/") for rel in doc["files"])


# --- rolling baseline math --------------------------------------------------

def test_median_ci_floor_and_coverage():
    assert bl.median_ci([1.0] * 5) is None          # below the floor
    lo, hi = bl.median_ci([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0])
    assert lo <= 4.0 <= hi
    assert lo >= 1.0 and hi <= 7.0


def test_percentile_interpolation():
    xs = [1.0, 2.0, 3.0, 4.0]
    assert bl.percentile(xs, 0) == 1.0
    assert bl.percentile(xs, 100) == 4.0
    assert bl.percentile(xs, 50) == pytest.approx(2.5)


def test_polarity_classes():
    assert bl.polarity("elapsed_time") == 1
    assert bl.polarity("step_time_mean") == 1
    assert bl.polarity("resnet50_profiling_overhead") == 1
    assert bl.polarity("comm_ici_bandwidth") == -1
    assert bl.polarity("tpu_ops") == 0
    # the self-healing tier's benchmark pair: slower recovery and a
    # higher refusal rate under the same load are both regressions
    assert bl.polarity("tier_recovery_wall_time_s") == 1
    assert bl.polarity("tier_refusal_rate_pct") == 1
    assert bl.polarity("fleet_saturation_rps") == -1


def test_rolling_verdict_discipline():
    samples = [1.0, 1.01, 0.99, 1.02, 0.98, 1.0, 1.01]
    # far outside the CI and the threshold: regressed
    v = bl.rolling_verdict(2.0, samples, 50.0, 10.0, 1)
    assert v["verdict"] == "regressed" and "CI" in v["reason"]
    # improvement in the good direction
    v = bl.rolling_verdict(0.5, samples, 50.0, 10.0, 1)
    assert v["verdict"] == "improved"
    # inside the threshold: noise even when outside the (tight) CI
    v = bl.rolling_verdict(1.05, samples, 50.0, 10.0, 1)
    assert v["verdict"] == "noise"
    # too few samples: noise BY CONTRACT, with the count in the reason
    v = bl.rolling_verdict(9.9, samples[:4], 50.0, 10.0, 1)
    assert v["verdict"] == "noise" and "4" in v["reason"]
    # no polarity: noise no matter the move
    v = bl.rolling_verdict(9.9, samples, 50.0, 10.0, 0)
    assert v["verdict"] == "noise" and "polarity" in v["reason"]


def test_pairwise_ratio_inf_convention():
    v = bl.pairwise_verdict(3.0, 0.0, 10.0, 1)
    assert v["ratio"] == float("inf") and v["verdict"] == "regressed"
    v = bl.pairwise_verdict(0.0, 0.0, 10.0, 1)
    assert v["ratio"] == 1.0 and v["verdict"] == "noise"
    v = bl.pairwise_verdict(3.0, 0.0, 10.0, -1)
    assert v["verdict"] == "improved"       # new in run, good polarity


# --- typed-verdict exit codes (real subprocess) -----------------------------

def _run_cli(*args, **env_extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT,
               **env_extra)
    return subprocess.run([sys.executable, "-m", "sofa_tpu", *args],
                          capture_output=True, text=True, timeout=180,
                          env=env, cwd=_ROOT)


def test_regress_exit_codes_via_subprocess(tmp_path):
    cfg = _mini_logdir(tmp_path, "base", elapsed=1.5, step_time=0.05)
    slow = _mini_logdir(tmp_path, "slow", elapsed=2.9, step_time=0.09)
    # run vs itself: all noise, exit 0
    r = _run_cli("regress", cfg.logdir, cfg.logdir)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(cfg.path("regress_verdict.json")))
    assert doc["verdict"] == "noise"
    assert doc["counts"]["regressed"] == 0
    assert all(row["verdict"] == "noise" for row in doc["features"])
    # slowed run vs base: regressed, exit 1
    r = _run_cli("regress", slow.logdir, cfg.logdir)
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.load(open(slow.path("regress_verdict.json")))
    assert doc["verdict"] == "regressed"
    assert doc["schema"] == "sofa_tpu/regress_verdict"
    names = {row["name"] for row in doc["features"]
             if row["verdict"] == "regressed"}
    assert "elapsed_time" in names
    # usage error: no baseline and no --rolling
    r = _run_cli("regress", cfg.logdir)
    assert r.returncode == 2


def test_archive_and_regress_rolling_via_subprocess(tmp_path):
    root = str(tmp_path / "arch")
    for i in range(6):
        c = _mini_logdir(tmp_path, f"r{i}", elapsed=1.5 + i * 0.001)
        r = _run_cli("archive", c.logdir, "--archive_root", root)
        assert r.returncode == 0, r.stderr
    slow = _mini_logdir(tmp_path, "slow", elapsed=3.0)
    r = _run_cli("regress", slow.logdir, "--rolling", "6",
                 "--archive_root", root)
    assert r.returncode == 1, r.stdout + r.stderr
    r = _run_cli("archive", "ls", "--archive_root", root)
    assert r.returncode == 0 and "6 run(s)" in r.stdout


def test_verdict_schema_validates(tmp_path):
    cfg = _mini_logdir(tmp_path, "base")
    r = _run_cli("regress", cfg.logdir, cfg.logdir)
    assert r.returncode == 0
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "manifest_check", os.path.join(_ROOT, "tools",
                                           "manifest_check.py"))
        mc = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mc)
    finally:
        sys.path.pop(0)
    doc = json.load(open(cfg.path("regress_verdict.json")))
    assert mc.validate_verdict(doc) == []
    bad = dict(doc, verdict="maybe")
    assert mc.validate_verdict(bad)
    # CLI path: a verdict file validates through check_path
    assert mc.check_path(cfg.path("regress_verdict.json")) == 0
    # the manifest gained archive/regress-aware sections and stays valid
    manifest = json.load(open(cfg.path("run_manifest.json")))
    assert "regress" in manifest["runs"]
    assert mc.validate_manifest(manifest) == []


# --- tile diff fast path ----------------------------------------------------

def test_tile_diff_unchanged_fast_path():
    files_a = {
        "_tiles/s1/0/0.json.gz": {"sha256": "aaa"},
        "_tiles/s1/1/0.json.gz": {"sha256": "bbb"},
        "_tiles/s2/0/0.json.gz": {"sha256": "ccc"},
        "report.js": {"sha256": "zzz"},          # non-tile: ignored
    }
    files_b = {
        "_tiles/s1/0/0.json.gz": {"sha256": "aaa"},   # unchanged
        "_tiles/s1/1/0.json.gz": {"sha256": "BBB"},   # changed
        "_tiles/s3/0/0.json.gz": {"sha256": "ddd"},   # new series
    }
    d = tile_diff({"files": files_a}, {"files": files_b})
    assert d["series"]["s1"] == {"unchanged": 1, "changed": 1,
                                 "only_a": 0, "only_b": 0}
    assert d["series"]["s2"]["only_a"] == 1
    assert d["series"]["s3"]["only_b"] == 1
    assert d["totals"]["unchanged"] == 1


def test_tile_diff_never_reads_payloads(monkeypatch):
    """The fast path is hash-only: comparing two runs must not open a
    single object."""
    import builtins

    files = {f"_tiles/s/0/{i}.json.gz": {"sha256": f"s{i}"}
             for i in range(32)}

    def boom(*a, **kw):
        raise AssertionError("tile_diff read a payload")

    monkeypatch.setattr(builtins, "open", boom)
    d = tile_diff({"files": files}, {"files": dict(files)})
    assert d["totals"]["unchanged"] == 32 and d["totals"]["changed"] == 0


# --- ml/diff robustness (satellite) -----------------------------------------

def test_swarm_diff_degrades_without_cluster_columns(tmp_path, capsys):
    from sofa_tpu.ml.diff import sofa_swarm_diff

    base = tmp_path / "b"
    match = tmp_path / "m"
    for d in (base, match):
        d.mkdir()
    pd.DataFrame({"cluster_ID": [0, 0], "name": ["f", "g"],
                  "duration": [1.0, 2.0]}).to_csv(
        base / "auto_caption.csv", index=False)
    # match side LACKS cluster_ID — a foreign/older auto_caption.csv
    pd.DataFrame({"name": ["f"], "duration": [1.0]}).to_csv(
        match / "auto_caption.csv", index=False)
    cfg = SofaConfig(logdir=str(tmp_path / "out"),
                     base_logdir=str(base), match_logdir=str(match))
    out = sofa_swarm_diff(cfg)       # must warn, not raise
    assert out is None
    assert "cluster_ID" in capsys.readouterr().err


def test_delta_table_ratio_inf_convention(tmp_path):
    from sofa_tpu.ml.diff import _delta_table

    base = pd.DataFrame({"time": [1.0, 0.0]}, index=["stays", "zeros"])
    match = pd.DataFrame({"time": [2.0, 0.0, 3.0]},
                         index=["stays", "zeros", "appears"])
    out = str(tmp_path / "d.csv")
    table = _delta_table(base, match, "time", out).set_index("index")
    assert table.loc["appears", "ratio"] == float("inf")   # new key
    assert table.loc["zeros", "ratio"] == 1.0              # 0/0 unchanged
    assert table.loc["stays", "ratio"] == 2.0
    assert os.path.isfile(out)


# --- bench catalog (satellite) ----------------------------------------------

def test_bench_import_idempotent(tmp_path):
    root = str(tmp_path / "repo")
    os.makedirs(root)
    with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
        json.dump({"metric": "resnet50_profiling_overhead", "value": 1.25,
                   "preprocess_wall_time_s": 2.5,
                   "captured_unix": 1700000000}, f)
    aroot = str(tmp_path / "arch")
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_import.py"),
         root, "--archive_root", aroot],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    entries = catalog.bench_entries(catalog.read_catalog(aroot))
    assert {e["metric"] for e in entries} == {
        "resnet50_profiling_overhead", "preprocess_wall_time_s"}
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_import.py"),
         root, "--archive_root", aroot],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0
    assert len(catalog.bench_entries(catalog.read_catalog(aroot))) == 2


def test_bench_archive_evidence_rides_extras(tmp_path, monkeypatch):
    sys.path.insert(0, _ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    aroot = str(tmp_path / "arch")
    monkeypatch.setenv("SOFA_ARCHIVE_ROOT", aroot)
    out = bench._archive_evidence(
        0.5, {"preprocess_wall_time_s": 2.0, "report_js_bytes": 1000})
    assert out["regress_verdict"]["verdict"] == "noise"   # 1 round: no CI
    assert out["regress_verdict"]["metrics"][
        "resnet50_profiling_overhead"] == "noise"
    entries = catalog.bench_entries(catalog.read_catalog(aroot))
    assert len(entries) == 3
    # opt-out leaves no trace
    monkeypatch.setenv("SOFA_BENCH_ARCHIVE", "0")
    assert bench._archive_evidence(0.5, {}) == {}


# --- archive verb surface ---------------------------------------------------

def test_archive_show_and_resolve_prefix(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    s = ingest_run(cfg, root)
    store = ArchiveStore(root)
    assert store.resolve_run_id(s["run"][:8]) == s["run"]
    assert store.resolve_run_id("abc") is None      # too short
    r = _run_cli("archive", "show", s["run"][:12], "--archive_root", root)
    assert r.returncode == 0 and "features" in r.stdout


def test_extract_roundtrip(tmp_path):
    cfg = _mini_logdir(tmp_path)
    root = str(tmp_path / "arch")
    s = ingest_run(cfg, root)
    dest = str(tmp_path / "restored")
    n = ArchiveStore(root).extract(s["run"], dest)
    assert n == s["files"]
    with open(cfg.path("features.csv")) as f_orig, \
            open(os.path.join(dest, "features.csv")) as f_rest:
        assert f_orig.read() == f_rest.read()


def test_resolve_root_precedence(monkeypatch):
    cfg = SofaConfig(archive_root="/x/y")
    assert resolve_root(cfg) == "/x/y"
    monkeypatch.setenv("SOFA_ARCHIVE_ROOT", "/env/root")
    assert resolve_root(SofaConfig()) == "/env/root"
    monkeypatch.delenv("SOFA_ARCHIVE_ROOT")
    assert resolve_root(None) == "sofa_archive"


# --- backup / restore (disaster recovery) -----------------------------------

def _tree_bytes(root):
    out = {}
    for dirpath, _dirs, names in os.walk(root):
        for n in sorted(names):
            if n.endswith(".tmp"):
                continue
            p = os.path.join(dirpath, n)
            with open(p, "rb") as f:
                out[os.path.relpath(p, root)] = f.read()
    return out


def test_backup_restore_is_byte_identical(tmp_path):
    """`sofa archive backup` + `restore`: the restored root is
    byte-identical to the source at snapshot time, fsck answers 0
    problems, and the restored index commit sha equals the one recorded
    in the snapshot — restore without proof is hope."""
    from sofa_tpu.archive import index as aindex
    from sofa_tpu.archive.store import backup_archive, restore_archive

    root = str(tmp_path / "arch")
    ingest_run(_mini_logdir(tmp_path, "a", elapsed=1.5), root)
    ingest_run(_mini_logdir(tmp_path, "b", elapsed=2.5), root)
    if aindex.available():
        aindex.refresh(root, jobs=0)  # the sha the restore must match
    dest = str(tmp_path / "backup")
    stats = backup_archive(root, dest)
    assert stats["snapshot"] == 1 and stats["files"] > 0
    assert stats["new_objects"] > 0

    target = str(tmp_path / "restored")
    verdict = restore_archive(dest, target)
    assert verdict["ok"], verdict
    assert verdict["missing"] == [] and verdict["fsck_problems"] == 0
    assert verdict["commit_sha"] == verdict["commit_sha_expected"]
    assert _tree_bytes(target) == _tree_bytes(root)
    # the restored root serves reads: every run doc loads
    restored = ArchiveStore(target)
    for ent in catalog.ingest_entries(catalog.read_catalog(target)):
        assert restored.load_run(ent["run"]) is not None


def test_backup_is_incremental(tmp_path):
    """A second snapshot after one new run re-uses every unchanged
    object (content-addressed increments) and restores independently."""
    from sofa_tpu.archive.store import backup_archive, restore_archive

    root = str(tmp_path / "arch")
    ingest_run(_mini_logdir(tmp_path, "a", elapsed=1.5), root)
    dest = str(tmp_path / "backup")
    s1 = backup_archive(root, dest)
    ingest_run(_mini_logdir(tmp_path, "b", elapsed=2.5), root)
    s2 = backup_archive(root, dest)
    assert s2["snapshot"] == 2
    assert s2["reused_objects"] > 0          # only new bytes traveled
    # each snapshot is a FULL restore point: the older one still lands
    old = restore_archive(dest, str(tmp_path / "r1"), snapshot=1)
    assert old["missing"] == [] and old["fsck_problems"] == 0
    new = restore_archive(dest, str(tmp_path / "r2"))
    assert new["missing"] == [] and new["fsck_problems"] == 0
    assert len(_tree_bytes(str(tmp_path / "r2"))) > \
        len(_tree_bytes(str(tmp_path / "r1")))


def test_backup_restore_guardrails(tmp_path):
    """The refusals that keep DR honest: no backup into the source
    root, no restore onto leftovers, no restore from a non-backup."""
    from sofa_tpu.archive.store import backup_archive, restore_archive

    root = str(tmp_path / "arch")
    ingest_run(_mini_logdir(tmp_path), root)
    with pytest.raises(OSError):
        backup_archive(root, os.path.join(root, "nested"))
    dest = str(tmp_path / "backup")
    backup_archive(root, dest)
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "leftover.txt").write_text("x")
    with pytest.raises(OSError):
        restore_archive(dest, str(dirty))
    with pytest.raises(OSError):
        restore_archive(str(tmp_path / "not_a_backup"), str(tmp_path / "t"))
