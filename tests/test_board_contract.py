"""Board data-contract tests (the GUI data contract made testable — the
reference binds sofa_analyze.py:1050-1052 CSV output to its sofaboard JS by
convention only, and this repo did the same until a renamed column could
ship a silently-blank page).

Three layers:
  1. a kitchen-sink logdir: synthetic frames through the REAL frame writer
     plus the full sofa_analyze pass list (+ aisi + diff), so the emitted
     headers are what production emits;
  2. CONTRACT: for every CSV a board page indexes by column name, the
     exact columns its JS reads — each must exist in the emitted header;
  3. a static scan of board/*.html + sofa_board.js: every fetchCSV file
     must be contracted (or declared table-only), and every literal column
     reference must appear in some contracted header — so a NEW page
     reference forces a contract (and therefore an emitter) update.
"""

import glob
import os
import re
import shutil

import pandas as pd
import pytest

from sofa_tpu.config import SofaConfig
from sofa_tpu.trace import CopyKind, make_frame, packed_ip

BOARD = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "sofa_tpu", "board")

# csv -> columns the pages' JS reads by name (indexOf / col() / dims keys /
# the run-report stack comps).  Maintained WITH the pages; the static scan
# below fails when a page references something missing here.
CONTRACT = {
    "mpstat.csv": ["timestamp", "event", "deviceId", "name"],
    "cputrace.csv": ["timestamp", "event", "duration", "deviceId",
                     "pid", "tid"],
    "tputrace.csv": ["timestamp", "duration", "flops", "bytes_accessed",
                     "copyKind", "deviceId", "category"],
    "tpuutil.csv": ["timestamp", "event", "name"],
    "roofline.csv": ["deviceId", "name", "efficiency"],
    "tpu_input_pipeline.csv": ["deviceId", "step", "busy_pct"],
    "tpu_memprof.csv": ["site", "bytes"],
    "commtrace.csv": ["timestamp", "duration", "payload", "peer", "dst",
                      "kind", "cls"],
    "netbandwidth.csv": ["timestamp", "event", "name"],
    "diskstat.csv": ["timestamp", "event", "name"],
    "pystacks.csv": ["module"],
    "tpu_op_tree.csv": ["path", "depth", "time", "time_pct", "count",
                        "flops"],
    "features.csv": ["name", "value"],
    "iterations.csv": ["iteration", "fw_compute_time", "bw_compute_time",
                       "collective_time", "transfer_time", "syscall_time",
                       "host_python_time"],
    "tpu_diff.csv": ["name", "delta"],
    "mem_diff.csv": ["site", "delta"],
}

# fetched but only renderTable'd (header-agnostic) or produced by flows the
# sink doesn't exercise (swarm diff needs two --enable_hsg runs)
TABLE_ONLY = {
    "comm.csv", "ici_matrix.csv", "netrank.csv", "cpu_top.csv",
    "pystacks_top.csv", "strace_top.csv", "disk_summary.csv",
    "performance.csv", "tpu_categories.csv", "tpu_top_ops.csv",
    "tpu_modules_summary.csv", "swarm_diff.csv",
}


def _kitchen_sink_frames():
    """Synthetic frames that light up every analysis pass at once: 4 steps
    x 2 devices of kernels (fw/bw phases, op paths, serving modules),
    collectives with payloads, async copies, host samplers, packets."""
    tpu_rows, step_rows, mod_rows = [], [], []
    for dev in (0, 1):
        for it in range(4):
            t0 = it * 0.1
            step_rows.append({"timestamp": t0, "duration": 0.1,
                              "deviceId": dev, "name": str(it),
                              "device_kind": "tpu"})
            mod_rows.append({"timestamp": t0, "duration": 0.09,
                             "deviceId": dev, "name": "jit_step",
                             "device_kind": "tpu"})
            for j, phase in enumerate(("fw", "fw", "bw")):
                tpu_rows.append({
                    "timestamp": t0 + 0.01 + 0.02 * j, "duration": 0.015,
                    "deviceId": dev, "category": 0,
                    "copyKind": int(CopyKind.KERNEL),
                    "name": f"fusion.{j}", "hlo_category": "convolution",
                    "flops": 2e9, "bytes_accessed": 4e6, "phase": phase,
                    "module": "jit_step",
                    "op_path": f"jit(step)/layer{j}/dot_general",
                    "device_kind": "tpu",
                })
            tpu_rows.append({
                "timestamp": t0 + 0.07, "duration": 0.01, "deviceId": dev,
                "category": 0, "copyKind": int(CopyKind.ALL_REDUCE),
                "name": "all-reduce.1", "hlo_category": "all-reduce",
                "payload": int(1e6), "bytes_accessed": 1e6,
                "module": "jit_step", "phase": "bw", "device_kind": "tpu",
            })
            tpu_rows.append({
                "timestamp": t0 + 0.005, "duration": 0.004, "deviceId": dev,
                "category": 2, "copyKind": int(CopyKind.H2D),
                "name": "copy-start.1", "payload": int(5e5),
                "device_kind": "tpu",
            })
    # serving phases so serving_profile emits its features
    for j in range(3):
        tpu_rows.append({"timestamp": 0.41 + 0.01 * j, "duration": 0.008,
                         "deviceId": 0, "category": 0,
                         "copyKind": int(CopyKind.KERNEL),
                         "name": f"serve.{j}", "flops": 1e10,
                         "bytes_accessed": 1e8,
                         "module": "jit_run_prefill", "device_kind": "tpu"})
        tpu_rows.append({"timestamp": 0.45 + 0.01 * j, "duration": 0.008,
                         "deviceId": 0, "category": 0,
                         "copyKind": int(CopyKind.KERNEL),
                         "name": f"serve.d{j}", "flops": 1e8,
                         "bytes_accessed": 1e8,
                         "module": "jit_run_decode", "device_kind": "tpu"})

    frames = {
        "tputrace": make_frame(tpu_rows),
        "tpusteps": make_frame(step_rows),
        "tpumodules": make_frame(mod_rows),
        "tpuutil": make_frame(
            [{"timestamp": 0.01 * i, "event": 50.0 + i % 7, "deviceId": 0,
              "name": m, "device_kind": "tpu"}
             for i in range(40) for m in ("tc_util", "hbm_gbps")]),
        "mpstat": make_frame(
            [{"timestamp": 0.05 * i, "event": 30.0 + i % 5, "deviceId": c,
              "name": "usr", "device_kind": "cpu"}
             for i in range(8) for c in range(2)]),
        "cputrace": make_frame(
            [{"timestamp": 0.01 * i, "event": 14.2, "duration": 0.01,
              "deviceId": i % 2, "pid": 100, "tid": 100 + i % 3,
              "name": "python;main;work", "device_kind": "cpu"}
             for i in range(40)]),
        "diskstat": make_frame(
            [{"timestamp": 0.1 * i, "event": 1e6, "deviceId": -1,
              "name": f"sda.{d}", "device_kind": "disk"}
             for i in range(4) for d in ("r_bw", "w_bw")]),
        "netbandwidth": make_frame(
            [{"timestamp": 0.1 * i, "event": 2e6, "payload": int(2e5),
              "deviceId": -1, "name": f"eth0.{d}", "device_kind": "net"}
             for i in range(4) for d in ("tx", "rx")]),
        "nettrace": make_frame(
            [{"timestamp": 0.02 * i, "duration": 1e-6, "payload": 1500,
              "pkt_src": packed_ip("10.0.0.1"),
              "pkt_dst": packed_ip("10.0.0.2"),
              "name": "tcp", "device_kind": "net"} for i in range(20)]),
        "pystacks": make_frame(
            [{"timestamp": 0.01 * i, "event": 1.0, "deviceId": -1,
              "name": "work", "module": "main;train;step",
              "device_kind": "cpu"} for i in range(40)]),
        "strace": make_frame(
            [{"timestamp": 0.03 * i, "duration": 0.002, "deviceId": -1,
              "name": "read", "device_kind": "cpu"} for i in range(12)]),
        "hosttrace": make_frame(
            [{"timestamp": 0.04 * i, "duration": 0.003, "deviceId": -1,
              "name": "ExecuteSharded", "device_kind": "host"}
             for i in range(10)]),
    }
    return frames


@pytest.fixture(scope="module")
def sink(tmp_path_factory):
    """Kitchen-sink logdir built through the real writers + pass list."""
    import jax
    import jax.numpy as jnp

    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.collectors.tpumon import snapshot_memprof
    from sofa_tpu.ml.diff import sofa_mem_diff, sofa_tpu_diff
    from sofa_tpu.trace import write_frame

    d = str(tmp_path_factory.mktemp("sink")) + "/"
    cfg = SofaConfig(logdir=d, enable_aisi=True)
    frames = _kitchen_sink_frames()
    for name, df in frames.items():
        write_frame(df, cfg.path(name), "csv")
    # a real memprof blob (live-arrays encoder over this process's arrays)
    held = jnp.ones((256, 256))
    assert snapshot_memprof(jax, cfg.path("memprof.pb.gz"), "peak",
                            held.nbytes)
    # roofline needs the chip peaks sidecar the XPlane ingest writes
    import json

    with open(cfg.path("tpu_meta.json"), "w") as f:
        json.dump({str(dev): {"peak_teraflops_per_second": 197.0,
                              "peak_hbm_bw_gigabytes_per_second": 819.0}
                   for dev in (0, 1)}, f)
    sofa_analyze(cfg, frames=frames)
    # diff inputs: base run = the same capture
    base = str(tmp_path_factory.mktemp("base")) + "/"
    write_frame(frames["tputrace"], base + "tputrace", "csv")
    shutil.copy(cfg.path("memprof.pb.gz"), base + "memprof.pb.gz")
    shutil.copy(cfg.path("memprof.pb.gz") + ".meta.json",
                base + "memprof.pb.gz.meta.json")
    cfg.base_logdir, cfg.match_logdir = base, d
    sofa_tpu_diff(cfg)
    sofa_mem_diff(cfg)
    del held
    return cfg


def test_report_js_columnar_contract(sink):
    """index.html's data contract: series data is columnar parallel
    arrays with an interned name table (sofa_board.js pointsFromColumnar
    decodes exactly this shape), and meta.tiles carries the LOD pyramid
    manifest the TileLoader navigates."""
    import json

    text = open(sink.path("report.js")).read()
    assert text.startswith("sofa_traces = ")
    doc = json.loads(text[len("sofa_traces = "):].rstrip(";\n"))
    assert doc["series"], "sink analyze emitted no timeline series"
    for s in doc["series"]:
        for key in ("name", "title", "color", "kind"):
            assert key in s
        data = s["data"]
        assert isinstance(data, dict), "per-point dicts are the old format"
        assert len(data["x"]) == len(data["y"]) == len(data["d"]) \
            == len(data["ni"])
        assert all(0 <= i < len(data["names"]) for i in data["ni"])
    tiles = doc["meta"]["tiles"]
    assert tiles["dir"] == "_tiles"
    assert isinstance(tiles["series"], dict)
    for name, ent in tiles["series"].items():
        # every advertised pyramid must resolve to fetchable tiles
        assert ent["levels"] >= 1 and ent["x1"] >= ent["x0"]
        assert os.path.isdir(sink.path("_tiles", ent["path"]))


def test_board_js_decodes_tiles_and_columnar():
    """Static scan: the board must route series data through the columnar
    decoder and tiles through the fixed-point decoder — a format change
    here without a decoder change ships a blank timeline."""
    js = open(os.path.join(BOARD, "sofa_board.js")).read()
    index = open(os.path.join(BOARD, "index.html")).read()
    for needed in ("function pointsFromColumnar", "function pointsFromTile",
                   "class TileLoader", "DecompressionStream"):
        assert needed in js, f"sofa_board.js lost {needed}"
    assert "TileLoader" in index and "onViewChange" in index


def test_board_csv_contract(sink):
    """Every contracted CSV exists in the sink and carries every column
    the board JS reads — a renamed emitter column fails here."""
    missing_files = [c for c in CONTRACT if not os.path.isfile(sink.path(c))]
    assert not missing_files, f"sink did not produce {missing_files}"
    for csvname, cols in CONTRACT.items():
        header = list(pd.read_csv(sink.path(csvname), nrows=0).columns)
        missing = [c for c in cols if c not in header]
        assert not missing, (csvname, missing, header)


def test_board_static_references_covered():
    """Every fetchCSV target is contracted (or declared table-only) and
    every literal column reference in the board JS appears in some
    contracted header — a new page reference forces a contract update."""
    files = glob.glob(os.path.join(BOARD, "*.html"))
    files.append(os.path.join(BOARD, "sofa_board.js"))
    fetched, cols = set(), set()
    for f in files:
        src = open(f).read()
        fetched |= set(re.findall(r'fetchCSV\("([\w.]+\.csv)"\)', src))
        cols |= set(re.findall(r'\.indexOf\("(\w+)"\)', src))
        cols |= set(re.findall(r'col\(r, "(\w+)"\)', src))
        cols |= set(re.findall(r'col\("(\w+)"\)', src))
        cols |= set(re.findall(r'key: "(\w+)"', src))
    unknown = fetched - set(CONTRACT) - TABLE_ONLY
    assert not unknown, f"pages fetch uncontracted CSVs: {sorted(unknown)}"
    contracted = set().union(*CONTRACT.values())
    missing = cols - contracted
    assert not missing, f"pages read uncontracted columns: {sorted(missing)}"
    # files indexed by column must be contracted, not just table-only
    assert not (set(CONTRACT) & TABLE_ONLY)


def test_serving_feature_names_contract(sink):
    """serving.html reads specific feature NAMES (values of the name
    column), not columns — bind those too."""
    f = pd.read_csv(sink.path("features.csv"))
    names = set(f["name"])
    for needed in ("serving_prefill_time", "serving_decode_time"):
        assert needed in names, f"features.csv lacks {needed}"


def test_iterations_stack_has_signal(sink):
    """The run-report stacked bar needs nonzero device AND host components
    from the sink — guards the aisi attribution plumbing end to end."""
    it = pd.read_csv(sink.path("iterations.csv"))
    assert len(it) >= 3
    for col in ("fw_compute_time", "bw_compute_time", "collective_time",
                "syscall_time", "host_python_time"):
        assert it[col].sum() > 0, f"{col} never attributed"
    # the stack's device slices are disjoint: the compute phases exclude
    # the collectives the sink booked with phase "bw"
    assert it["bw_compute_time"].sum() == pytest.approx(
        it["bw_time"].sum() - it["collective_time"].sum())
