#!/usr/bin/env python3
"""Scan-vs-index wall-time table for the archive's fleet queries.

Synthesizes an N-run archive (catalog.jsonl + one ``runs/<id>.json`` doc
per run, realistic file maps and feature vectors, default 50 000 runs),
builds the columnar catalog index (sofa_tpu/archive/index.py), and times
the three fleet queries both ways:

  ls          ``archive ls --limit 20`` — newest-20 run listing
  rolling     the `sofa regress --rolling 20` baseline window
  rank        the fleet board's ``tpu*_sol_distance`` worst-offender
              ranking (the O(fleet)-doc-opens query)

Each query's results are asserted IDENTICAL between the scan and index
paths before a single number prints — a fast wrong answer is not a
result.  Also reports the cold index build, the suffix-only refresh
after an append, and the warm no-op refresh (0 bytes parsed).

bench.py carries the same pair every round as
``catalog_index_refresh_wall_time_s`` / ``fleet_query_wall_time_s`` on
success AND dead-tunnel paths (archived, ``_wall`` polarity).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synthesize(root: str, n_runs: int, n_hosts: int = 200) -> None:
    """An N-run archive shaped like a real fleet: per-run docs with a
    ~20-entry file map and a ~12-feature vector (4 per-device sol
    distances), plus one catalog ingest line each.  Written with plain
    buffered IO — this is a synthetic corpus, not a durability test."""
    from sofa_tpu.archive.store import ArchiveStore

    ArchiveStore(root, create=True)
    rdir = os.path.join(root, "runs")
    lines = []
    file_map = {f"f{j:02d}.csv": {"sha256": f"{j:064x}", "bytes": 1000 + j,
                                  "kind": "derived"} for j in range(20)}
    for i in range(n_runs):
        run = f"{i:064x}"
        t = 1_700_000_000.0 + i
        host = f"host{i % n_hosts}"
        label = "nightly" if i % 3 else "release"
        feats = {
            "elapsed_time": 120.0 + (i % 613) * 0.01,
            "step_time_mean": 0.05 + (i % 101) * 1e-4,
            "preprocess_wall_time_s": 2.5 + (i % 47) * 0.01,
            "host_busy_ratio": 0.4,
            "tpu_comm_ratio": 0.2,
            "images_per_sec": 900.0 - (i % 211),
            "whatif_identity_error_pct": 0.8,
            "swarm_count": 12.0,
            "tpu0_sol_distance": 2.0 + (i % 97) * 0.1,
            "tpu1_sol_distance": 2.1 + (i % 89) * 0.1,
            "tpu2_sol_distance": 1.9 + (i % 83) * 0.1,
            "tpu3_sol_distance": 2.2 + (i % 79) * 0.1,
        }
        doc = {"schema": "sofa_tpu/archive_run", "version": 1,
               "run": run, "t": t, "hostname": host, "label": label,
               "logdir": f"/fleet/{host}/job{i}", "files": file_map,
               "features": feats}
        with open(os.path.join(rdir, run + ".json"), "w") as f:
            json.dump(doc, f, sort_keys=True)
        lines.append(json.dumps(
            {"ev": "ingest", "t": t, "run": run,
             "logdir": doc["logdir"], "files": len(file_map),
             "new_objects": 3, "bytes_added": 4096, "label": label},
            separators=(",", ":")))
    from sofa_tpu.archive import catalog

    with open(catalog.catalog_path(root), "w") as f:
        f.write("\n".join(lines) + "\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--runs", type=int, default=50_000,
                   help="synthetic catalog size (default 50000)")
    p.add_argument("--window", type=int, default=20,
                   help="rolling-baseline window (default 20)")
    p.add_argument("--limit", type=int, default=20,
                   help="ls / rank result size (default 20)")
    p.add_argument("--keep", action="store_true",
                   help="keep the synthetic archive root")
    args = p.parse_args(argv)

    os.environ.pop("SOFA_ARCHIVE_INDEX", None)
    from sofa_tpu.archive import baseline, catalog
    from sofa_tpu.archive import index as aindex
    from sofa_tpu.archive.store import (ArchiveStore, _ls_runs,
                                        render_ls)
    from sofa_tpu.config import SofaConfig

    workdir = tempfile.mkdtemp(prefix="sofa_catbench_")
    root = os.path.join(workdir, "archive")
    print(f"synthesizing {args.runs} runs under {root} ...")
    t0 = time.perf_counter()
    synthesize(root, args.runs)
    print(f"  synthesized in {time.perf_counter() - t0:.1f}s")
    store = ArchiveStore(root)

    t0 = time.perf_counter()
    commit = aindex.refresh(root)
    t_build = time.perf_counter() - t0
    assert commit is not None, "pyarrow missing — nothing to benchmark"
    print(f"  index build (full): {t_build:.2f}s "
          f"({commit['events']} events, {commit['features_rows']} "
          f"feature rows, {commit['_stats']['chunks_wrote']} chunks)")

    cfg = SofaConfig(logdir="unused", archive_root=root,
                     archive_limit=args.limit)

    def timed(fn, reps=3):
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best, out

    rows = [["query", "scan", "index", "speedup"]]

    # --- ls --limit -------------------------------------------------------
    def ls():
        runs, total, bench_n, _src = _ls_runs(root, cfg)
        return "\n".join(render_ls(root, runs, total_runs=total,
                                   bench_count=bench_n))

    t_ls_idx, out_idx = timed(ls)
    os.environ["SOFA_ARCHIVE_INDEX"] = "0"
    t_ls_scan, out_scan = timed(ls, reps=1)
    os.environ.pop("SOFA_ARCHIVE_INDEX")
    assert out_idx == out_scan, "ls output differs between index and scan"
    rows.append(["ls --limit %d" % args.limit, f"{t_ls_scan:.3f}s",
                 f"{t_ls_idx * 1000:.1f}ms",
                 f"{t_ls_scan / t_ls_idx:.0f}x"])

    # --- rolling baseline window ------------------------------------------
    t_rb_idx, s_idx = timed(
        lambda: aindex.rolling_samples(root, args.window))
    os.environ["SOFA_ARCHIVE_INDEX"] = "0"
    t_rb_scan, s_scan = timed(
        lambda: baseline.rolling_samples(store, args.window), reps=1)
    os.environ.pop("SOFA_ARCHIVE_INDEX")
    assert s_idx == s_scan, "rolling samples differ between index and scan"
    rows.append(["rolling baseline (N=%d)" % args.window,
                 f"{t_rb_scan:.3f}s", f"{t_rb_idx * 1000:.1f}ms",
                 f"{t_rb_scan / t_rb_idx:.0f}x"])

    # --- sol-distance ranking ---------------------------------------------
    t_rk_idx, o_idx = timed(
        lambda: aindex.offenders(root, limit=args.limit))
    t_rk_scan, o_scan = timed(
        lambda: aindex.offenders_scan(store, limit=args.limit), reps=1)
    assert o_idx == o_scan, "offender ranking differs between index/scan"
    rows.append(["sol-distance rank (top %d)" % args.limit,
                 f"{t_rk_scan:.3f}s", f"{t_rk_idx * 1000:.1f}ms",
                 f"{t_rk_scan / t_rk_idx:.0f}x"])

    # --- refresh costs ----------------------------------------------------
    t0 = time.perf_counter()
    warm = aindex.refresh(root)
    t_warm = time.perf_counter() - t0
    assert warm["_stats"]["parsed_bytes"] == 0, "warm refresh parsed bytes"
    assert warm["_stats"]["chunks_wrote"] == 0, "warm refresh wrote chunks"
    # one appended ingest: the suffix-only refresh
    run = "f" * 64
    with open(os.path.join(root, "runs", run + ".json"), "w") as f:
        json.dump({"run": run, "hostname": "hostX", "t": 1.8e9,
                   "features": {"elapsed_time": 1.0}}, f)
    catalog.append_event(root, "ingest", run=run, logdir="/fleet/x",
                         files=1, new_objects=1, bytes_added=10)
    t0 = time.perf_counter()
    inc = aindex.refresh(root)
    t_inc = time.perf_counter() - t0
    assert not inc["_stats"]["full"], "append triggered a full rebuild"
    assert inc["_stats"]["new_events"] == 1

    from sofa_tpu.telemetry import _table

    print()
    print("\n".join(_table(rows)))
    print()
    print(f"index build (cold, {args.runs} runs): {t_build:.2f}s")
    print(f"suffix refresh (1 appended ingest):   "
          f"{t_inc * 1000:.1f}ms ({inc['_stats']['parsed_bytes']} bytes "
          "parsed — the appended line only)")
    print(f"warm refresh (unchanged catalog):     "
          f"{t_warm * 1000:.2f}ms (0 bytes parsed, 0 chunks written)")
    if args.keep:
        print(f"kept: {root}")
    else:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
