"""Llama-style decoder-only transformer, sharded over a TPU mesh.

The flagship workload (BASELINE.json configs #4/#5: Llama-style inference and
pretrain).  Pure-functional JAX: params are a pytree of stacked per-layer
arrays scanned with `lax.scan` (one compiled layer body, L iterations), every
matmul is bfloat16-in/float32-accumulate for the MXU, and parallelism is
declared, not hand-coded:

  data  axis — batch (DP); optionally also FSDP param sharding
  seq   axis — sequence (SP) via ring attention (ppermute over ICI)
  model axis — attention heads + MLP hidden (TP); XLA inserts the
               all-reduces on the wo/w2 contractions

Architecture follows Llama-3: RMSNorm, rotary position embeddings, grouped-
query attention, SwiGLU MLP, untied LM head.  The reference profiler only
*observed* such workloads (NCCL kernel attribution,
/root/reference/bin/sofa_analyze.py:363-368); here the workload ships with the
profiler so every collective class the analyzer attributes is generated
in-repo.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sofa_tpu.workloads.flash_pallas import (
    flash_causal_attention,
    flash_causal_segmented_attention,
    supports as flash_supports,
)
from sofa_tpu.workloads.ring_attention import (
    plain_causal_attention,
    plain_segmented_causal_attention,
    ring_attention,
)
from sofa_tpu.workloads.ring_flash import (
    ring_flash_attention,
    zigzag_indices,
    zigzag_ring_flash_attention,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1408
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    rope_theta: float = 500000.0
    # None = auto: fused Pallas attention on TPU when the single-chip path
    # runs and T divides the kernel's block size; True/False force it.
    flash: Optional[bool] = None
    # Load-balanced causal sequence parallelism: shard r holds zig-zag
    # chunks (r, 2S-1-r) so every shard does equal work around the ring.
    # Requires flash; sequences are permuted at the embedding and
    # un-permuted before the LM head.
    zigzag: bool = False
    # Rematerialize each layer in the backward pass (jax.checkpoint on the
    # scanned layer body): live activation memory drops from O(L*T*D) to
    # one layer's worth + residuals, at ~1 forward replay of FLOPs — the
    # standard trade for long-context / large-batch training.  `remat`
    # turns it on; `remat_policy` names a jax.checkpoint_policies entry
    # (e.g. "dots_with_no_batch_dims_saveable" keeps matmul outputs and
    # replays only the cheap elementwise work).
    remat: bool = False
    remat_policy: Optional[str] = None

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "TransformerConfig":
        return TransformerConfig(vocab=128256, d_model=4096, n_layers=32,
                                 n_heads=32, n_kv_heads=8, d_ff=14336,
                                 max_seq=8192)

    @staticmethod
    def tiny(seq: int = 128) -> "TransformerConfig":
        return TransformerConfig(vocab=256, d_model=64, n_layers=2,
                                 n_heads=4, n_kv_heads=2, d_ff=128,
                                 max_seq=seq)


def init_params(cfg: TransformerConfig, key) -> Dict[str, Any]:
    """Stacked-layer param pytree; leaves are [n_layers, ...] where per-layer."""
    k = iter(jax.random.split(key, 10))
    d, h, kvh, dh, f, L = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                           cfg.d_head, cfg.d_ff, cfg.n_layers)

    def norm(key, *shape):
        fan_in = shape[-2] if len(shape) > 1 else shape[-1]
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        "embed": norm(next(k), cfg.vocab, d),
        "layers": {
            "attn_norm": jnp.ones((L, d), jnp.float32),
            "wq": norm(next(k), L, d, h * dh),
            "wk": norm(next(k), L, d, kvh * dh),
            "wv": norm(next(k), L, d, kvh * dh),
            "wo": norm(next(k), L, h * dh, d),
            "mlp_norm": jnp.ones((L, d), jnp.float32),
            "w1": norm(next(k), L, d, f),
            "w3": norm(next(k), L, d, f),
            "w2": norm(next(k), L, f, d),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": norm(next(k), d, cfg.vocab),
    }


def param_specs(cfg: TransformerConfig, fsdp: bool = False) -> Dict[str, Any]:
    """PartitionSpecs per param leaf: TP over "model", FSDP over "data"."""
    dp = "data" if fsdp else None
    return {
        "embed": P("model", dp),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, dp, "model"),
            "wk": P(None, dp, "model"),
            "wv": P(None, dp, "model"),
            "wo": P(None, "model", dp),
            "mlp_norm": P(None, None),
            "w1": P(None, dp, "model"),
            "w3": P(None, dp, "model"),
            "w2": P(None, "model", dp),
        },
        "final_norm": P(None),
        "lm_head": P(dp, "model"),
    }


def _rmsnorm(x, w):
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (y * w).astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding over [B, T, H, D]; pairs are (x[..., :D/2], x[..., D/2:])."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # [B,T,1,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def layer_body(x, lp, cfg: TransformerConfig, positions, attn):
    """One decoder layer, parameterized by the attention implementation.

    ``attn(q, kk, v) -> (o, aux)`` receives *unrepeated* KV heads
    ([B, T, KVH, Dh]) so cache-based attention (workloads/inference.py) can
    store them compactly; training attention repeats them for GQA itself.
    The single copy of the layer math keeps training forward() and the
    inference block numerically identical by construction.
    """
    b, t = x.shape[:2]
    h = _rmsnorm(x, lp["attn_norm"])
    q = (h @ lp["wq"]).reshape(b, t, cfg.n_heads, cfg.d_head)
    kk = (h @ lp["wk"]).reshape(b, t, cfg.n_kv_heads, cfg.d_head)
    v = (h @ lp["wv"]).reshape(b, t, cfg.n_kv_heads, cfg.d_head)
    q = _rope(q, positions, cfg.rope_theta)
    kk = _rope(kk, positions, cfg.rope_theta)
    o, aux = attn(q, kk, v)
    x = x + o.reshape(b, t, -1) @ lp["wo"]
    h = _rmsnorm(x, lp["mlp_norm"])
    gate = jax.nn.silu((h @ lp["w1"]).astype(jnp.float32)).astype(cfg.dtype)
    x = x + (gate * (h @ lp["w3"])) @ lp["w2"]
    return x, aux


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None,
            segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Logits [B, T, vocab].  With a mesh whose "seq" axis is >1, attention
    runs as ring attention; otherwise plain fused causal attention.

    ``segment_ids`` [B, T] packs multiple documents per row: attention is
    masked within segments (fused into the flash kernels; explicit mask on
    the unfused path) and rope positions restart at each segment — a
    packed batch is numerically identical to processing the documents
    separately.  Ids must be CONTIGUOUS along T (e.g. 0,0,1,1,2: each id
    appears in one run — the standard packed layout); a reused id would
    attend across its earlier run while positions restart, with no error.
    Not supported together with sequence parallelism (the ring exchanges
    would need segment blocks too)."""
    b, t = tokens.shape
    if t > cfg.max_seq:
        raise ValueError(f"sequence length {t} exceeds max_seq {cfg.max_seq}")
    use_ring = mesh is not None and mesh.shape.get("seq", 1) > 1
    if segment_ids is not None and use_ring:
        raise ValueError("segment_ids are not supported with the "
                         "sequence-parallel (ring) path yet")
    t_local = t // mesh.shape["seq"] if use_ring else t
    if cfg.zigzag and use_ring:
        # Zig-zag runs the kernel per half-chunk, so the tiling gate must
        # check that size, not the full local length.
        t_local //= 2
    if cfg.flash is None:
        # Auto: fused Pallas kernel on TPU (per-shard inside the ring when
        # sequence-parallel).  Off-TPU the kernel only runs interpreted
        # (slow), so auto stays off there.
        use_flash = flash_supports(t_local) and jax.default_backend() == "tpu"
    else:
        use_flash = cfg.flash
        if use_flash and not flash_supports(t_local):
            raise ValueError(
                f"flash=True but local seq len {t_local} is not supported by "
                f"the fused kernel (needs a 16-multiple block dividing it)")
    if segment_ids is None:
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    else:
        # rope positions restart at each packed document: position =
        # global index - running max of segment-start indices (cummax)
        idx = jnp.broadcast_to(jnp.arange(t), (b, t))
        is_start = jnp.concatenate(
            [jnp.ones((b, 1), bool),
             segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
        positions = idx - lax.cummax(jnp.where(is_start, idx, 0), axis=1)

    use_zigzag = cfg.zigzag and use_ring and use_flash
    if use_zigzag:
        # Static permutation into the balanced layout, applied to the
        # token ids (not the d_model-wide activations); rope reads the
        # permuted *global* positions so the math is order-invariant.
        perm, inv_perm = zigzag_indices(t, mesh.shape["seq"])
        positions = positions[:, perm]
        tokens = tokens[:, perm]

    emb = params["embed"].astype(cfg.dtype)
    if mesh is not None and mesh.shape.get("model", 1) > 1:
        # Iota one-hot contraction instead of a gather: the table is sharded
        # over vocab ("model" axis) and a cross-shard gather forces the SPMD
        # partitioner into involuntary full rematerialization (replicate the
        # table, then re-partition).  A dot contracting over vocab partitions
        # cleanly — each shard contracts its vocab slice and XLA inserts one
        # psum over "model" — and the one-hot fuses into the MXU matmul.
        one_hot = (tokens[..., None] == lax.broadcasted_iota(
            jnp.int32, (1, 1, cfg.vocab), 2)).astype(cfg.dtype)
        x = one_hot @ emb
    else:
        # Unsharded vocab (model axis 1, or no mesh): the gather is local.
        x = emb[tokens]
    if mesh is not None:
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("data", "seq", None)))

    def attn(q, kk, v):
        # The fused (flash) paths take GQA natively — compact KV heads go
        # straight to the kernel (and over the ring's ppermute hops, which
        # cuts ICI bytes by the group factor).  The unfused paths
        # materialize the repeat, as does any path whose shard_map splits
        # the head axis more ways than there are KV heads (tensor-parallel
        # over "model": compact heads must still divide the axis).
        tp = mesh.shape.get("model", 1) if mesh is not None else 1
        rep = cfg.n_heads // cfg.n_kv_heads

        def repeated():
            return (jnp.repeat(kk, rep, axis=2), jnp.repeat(v, rep, axis=2))

        if use_flash:
            kr, vr = (kk, v) if cfg.n_kv_heads % tp == 0 else repeated()
            if use_zigzag:
                return zigzag_ring_flash_attention(q, kr, vr, mesh), None
            if use_ring:
                return ring_flash_attention(q, kr, vr, mesh), None
            if segment_ids is not None:
                return flash_causal_segmented_attention(
                    q, kr, vr, segment_ids), None
            return flash_causal_attention(q, kr, vr), None
        kk, v = repeated()
        if use_ring:
            return ring_attention(q, kk, v, mesh), None
        if segment_ids is not None:
            return plain_segmented_causal_attention(
                q, kk, v, segment_ids), None
        return plain_causal_attention(q, kk, v), None

    def layer(x, lp):
        x, _ = layer_body(x, lp, cfg, positions, attn)
        if mesh is not None:
            x = lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("data", "seq", None)))
        return x, None

    if cfg.remat or cfg.remat_policy:
        # a named policy implies remat — a policy with remat=False would
        # silently train without checkpointing (OOM surprise at scale)
        policy = (getattr(jax.checkpoint_policies, cfg.remat_policy)
                  if cfg.remat_policy else None)
        # checkpoint the scanned body: the classic scan-over-remat-layer —
        # backward holds one layer's activations and replays the rest
        layer = jax.checkpoint(layer, policy=policy,
                               prevent_cse=False)
    x, _ = lax.scan(layer, x, params["layers"])
    if use_zigzag:
        x = x[:, inv_perm]
    x = _rmsnorm(x, params["final_norm"])
    return (x @ params["lm_head"]).astype(jnp.float32)


def loss_fn(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None,
            segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross entropy; targets are tokens shifted left.

    The forward pass sees the full sequence (so T stays divisible by the
    "seq" mesh axis) and the last position's logits are dropped instead.
    With ``segment_ids`` (packed documents), positions whose target falls
    in a DIFFERENT segment are excluded — the last token of one document
    must not be trained to predict the first token of the next — and the
    mean runs over the kept positions, so a packed batch's loss equals the
    token-weighted mean of the documents' separate losses.
    """
    logits = forward(params, tokens, cfg, mesh, segment_ids)[:, :-1]
    targets = tokens[:, 1:]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if segment_ids is None:
        return jnp.mean(nll)
    keep = (segment_ids[:, 1:] == segment_ids[:, :-1]).astype(nll.dtype)
    return jnp.sum(nll * keep) / jnp.maximum(jnp.sum(keep), 1.0)


def shard_params(params, cfg: TransformerConfig, mesh: Mesh,
                 fsdp: bool = False):
    specs = param_specs(cfg, fsdp)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh],
                    learning_rate: float = 1e-3):
    """Jitted (params, opt_state, tokens) -> (params, opt_state, loss).

    Optimizer is adamw from optax; optimizer state inherits the param
    shardings through jit's sharding propagation.
    """
    import optax

    tx = optax.adamw(learning_rate)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg, mesh))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return tx, step


def build(cfg: TransformerConfig, mesh: Optional[Mesh], batch: int,
          seq: int, seed: int = 0, fsdp: bool = False):
    """Init params + optimizer + a data batch, all placed on the mesh."""
    key = jax.random.PRNGKey(seed)
    params = init_params(cfg, key)
    if mesh is not None:
        params = shard_params(params, cfg, mesh, fsdp)
    tx, step = make_train_step(cfg, mesh)
    opt_state = tx.init(params)
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab)
    if mesh is not None:
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P("data", None)))
    return params, opt_state, step, tokens


def main(argv=None):
    from sofa_tpu.workloads.common import (make_mesh, parse_workload_args,
                                           steps_per_sec)

    args = parse_workload_args(argv, {
        "batch": 8, "seq": 512, "steps": 10, "d_model": 512, "n_layers": 4,
        "n_heads": 8, "n_kv_heads": 4, "d_ff": 1408, "vocab": 32000,
        "fsdp": False, "data": 0, "seq_par": 0, "model": 0,
    })
    cfg = TransformerConfig(vocab=args.vocab, d_model=args.d_model,
                            n_layers=args.n_layers, n_heads=args.n_heads,
                            n_kv_heads=args.n_kv_heads, d_ff=args.d_ff,
                            max_seq=args.seq)
    n = len(jax.devices())
    sizes = None
    if args.data or args.seq_par or args.model:
        sizes = [args.data or 1, args.seq_par or 1, args.model or 1]
    mesh = make_mesh(("data", "seq", "model"), sizes) if n > 1 else None
    params, opt_state, step, tokens = build(cfg, mesh, args.batch, args.seq)

    def one(state):
        p, o, _ = state
        p, o, loss = step(p, o, tokens)
        return p, o, loss

    sps, state = steps_per_sec(one, (params, opt_state, 0.0), args.steps)
    toks = sps * args.batch * args.seq
    mesh_desc = dict(mesh.shape) if mesh else {"single": 1}
    print(f"transformer: {sps:.3f} steps/s  {toks:,.0f} tokens/s  "
          f"loss={float(state[2]):.3f}  mesh={mesh_desc}")


if __name__ == "__main__":
    main()
