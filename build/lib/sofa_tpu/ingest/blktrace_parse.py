"""blktrace.txt (blkparse text) -> per-IO latency frame.

The reference pairs D (dispatch) and C (complete) events on the same start
block to compute per-IO latency (/root/reference/bin/sofa_preprocess.py:684-781).
Same algorithm here, on blkparse's default output:

    <maj>,<min> <cpu> <seq> <time> <pid> <action> <rwbs> <sector> + <nsect> [proc]

Rows: timestamp = dispatch time (relative to trace start ~= record start),
duration = D->C latency, payload = bytes (nsectors * 512), event = latency in
ms (scatter y), bandwidth = payload/latency.  Unmatched dispatches (trace cut
mid-IO) are dropped.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

import pandas as pd

from sofa_tpu.trace import empty_frame, make_frame

_LINE_RE = re.compile(
    r"^\s*(?P<maj>\d+),(?P<min>\d+)\s+(?P<cpu>\d+)\s+(?P<seq>\d+)\s+"
    r"(?P<time>[\d.]+)\s+(?P<pid>\d+)\s+(?P<action>[A-Z])\s+"
    r"(?P<rwbs>[A-Z]+)\s+(?P<sector>\d+)\s+\+\s+(?P<nsect>\d+)"
)

_SECTOR_BYTES = 512


def parse_blktrace(text: str, time_base: float = 0.0) -> pd.DataFrame:
    # (dev, sector) -> list of pending dispatches (time, pid, nsect, rwbs)
    pending: Dict[Tuple[str, int], List[Tuple[float, int, int, str]]] = {}
    rows = []
    for line in text.splitlines():
        m = _LINE_RE.match(line)
        if m is None:
            continue
        action = m.group("action")
        if action not in ("D", "C"):
            continue
        dev = f"{m.group('maj')},{m.group('min')}"
        sector = int(m.group("sector"))
        t = float(m.group("time"))
        key = (dev, sector)
        if action == "D":
            pending.setdefault(key, []).append(
                (t, int(m.group("pid")), int(m.group("nsect")), m.group("rwbs"))
            )
            continue
        # C: complete — match the earliest unmatched dispatch on this block
        queue = pending.get(key)
        if not queue:
            continue
        t_d, pid, nsect, rwbs = queue.pop(0)
        if not queue:
            del pending[key]
        latency = max(t - t_d, 0.0)
        nbytes = nsect * _SECTOR_BYTES
        rows.append(
            {
                "timestamp": t_d - time_base,
                "event": latency * 1e3,       # ms, the scatter y-value
                "duration": latency,
                "deviceId": int(m.group("min")),
                "payload": nbytes,
                "bandwidth": nbytes / latency if latency > 0 else 0.0,
                "pid": pid,
                "name": f"blk_{rwbs.lower()} {dev} sector {sector}",
                "device_kind": "disk",
            }
        )
    return make_frame(rows)


def ingest_blktrace(logdir: str, time_base: float = 0.0) -> pd.DataFrame:
    path = os.path.join(logdir, "blktrace.txt")
    if not os.path.isfile(path):
        return empty_frame()
    with open(path) as f:
        return parse_blktrace(f.read(), time_base)
