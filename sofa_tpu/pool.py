"""Shared worker-pool policy for the preprocess/analyze report path.

Every pool on the report path sizes itself HERE: the ingest fan-out and the
frame writes in preprocess, the frame reads in analyze, the per-host
cluster_analyze workers, and the xplane multi-file process pool all take
their width from one ``--jobs`` setting (SofaConfig.jobs, 0 = auto from
``os.cpu_count()``, env override ``SOFA_JOBS`` for the auto default).

Thread pools are the default — pandas/pyarrow readers and writers release
the GIL, and the pure-Python parsers still overlap their file IO.  Process
pools (CPU-heavy parsers: perf script, pcap, xplane protos) are built from
:func:`process_context` — forkserver when available, else spawn, never fork:
callers may hold collector/sampler threads and a forked child of a threaded
process can deadlock (same rule as ingest/xplane.py's pool).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

# Auto mode caps here: past ~32 workers the report path is IO- or
# join-bound, and a 256-core host should not build 256-thread pools.
MAX_AUTO_JOBS = 32


def resolve_jobs(jobs: int = 0) -> int:
    """Materialize a jobs setting: explicit positive value wins; 0/negative
    means auto — ``SOFA_JOBS`` if set, else ``os.cpu_count()`` (capped)."""
    if jobs and jobs > 0:
        return int(jobs)
    env = os.environ.get("SOFA_JOBS", "").strip()
    if env.isdigit() and int(env) > 0:
        return min(int(env), MAX_AUTO_JOBS)
    try:  # cgroup/affinity-restricted containers: usable CPUs, not present
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover — non-Linux
        n = os.cpu_count() or 1
    return max(1, min(n, MAX_AUTO_JOBS))


def cfg_jobs(cfg) -> int:
    """The resolved worker count for a SofaConfig (0/absent = auto)."""
    return resolve_jobs(getattr(cfg, "jobs", 0))


def pool_size(jobs: int, n_items: int) -> int:
    """Workers to actually start: never more than items, never less than 1."""
    return max(1, min(jobs, n_items))


def thread_map(fn: Callable[[T], R], items: "Iterable[T] | Sequence[T]",
               jobs: int) -> List[R]:
    """Ordered ``map`` over a thread pool; serial when jobs==1 or one item
    (so ``--jobs 1`` is a true no-pool path with clean tracebacks)."""
    items = list(items)
    if jobs <= 1 or len(items) <= 1:
        return [fn(x) for x in items]
    with ThreadPoolExecutor(max_workers=pool_size(jobs, len(items))) as pool:
        return list(pool.map(fn, items))


def process_context():
    """Multiprocessing context for CPU-heavy parser pools: forkserver when
    available, else spawn — never fork (see module docstring)."""
    import multiprocessing as mp

    methods = mp.get_all_start_methods()
    return mp.get_context("forkserver" if "forkserver" in methods else "spawn")
