"""strace -q -T -tt -f output -> strace frame.

Line shape: ``<pid> <HH:MM:SS.ffffff> <syscall>(<args>) = <ret> <dur>``
(duration in seconds inside angle brackets).  Mirrors the reference parser's
noise filter and minimum-duration cut
(/root/reference/bin/sofa_preprocess.py:1618-1704).
"""

from __future__ import annotations

import datetime as _dt
import re

import pandas as pd

from sofa_tpu.trace import make_frame

_LINE_RE = re.compile(
    r"^(?P<pid>\d+)\s+(?P<time>\d{2}:\d{2}:\d{2}\.\d+)\s+"
    r"(?P<call>\w+)\((?P<args>.*?)\)\s*=\s*(?P<ret>[-\w?]+).*?"
    r"<(?P<dur>[\d.]+)>\s*$"
)

# Bookkeeping syscalls that drown the signal (reference list,
# sofa_preprocess.py:1623-1635).
NOISE = {
    "nanosleep", "clock_nanosleep", "clock_gettime", "gettimeofday", "brk",
    "stat", "fstat", "lstat", "newfstatat", "statx", "access", "faccessat",
    "getpid", "gettid", "sched_yield", "rt_sigprocmask", "rt_sigaction",
}


def parse_strace(text: str, time_base: float = 0.0,
                 min_time: float = 1e-6, day_origin: float | None = None) -> pd.DataFrame:
    """day_origin: unix timestamp of local midnight for the -tt wall times;
    derived from time_base when omitted."""
    if day_origin is None:
        base_dt = _dt.datetime.fromtimestamp(time_base or 0)
        day_origin = _dt.datetime(base_dt.year, base_dt.month, base_dt.day).timestamp()
    rows = []
    for line in text.splitlines():
        m = _LINE_RE.match(line.strip())
        if not m:
            continue
        call = m.group("call")
        dur = float(m.group("dur"))
        if call in NOISE or dur < min_time:
            continue
        hh, mm, ss = m.group("time").split(":")
        t = day_origin + int(hh) * 3600 + int(mm) * 60 + float(ss)
        rows.append(
            {
                "timestamp": t - time_base,
                "event": float(dur),
                "duration": dur,
                "pid": int(m.group("pid")),
                "tid": int(m.group("pid")),
                "name": f"{call}({m.group('args')[:60]}) = {m.group('ret')}",
                "device_kind": "cpu",
            }
        )
    return make_frame(rows)


def parse_pystacks(text: str, time_base: float = 0.0) -> pd.DataFrame:
    """pystacks.txt (collectors/pystacks.py): ``<ts> <tid> <f0;f1;...;leaf>``.

    Emits one row per sample: name = leaf frame, event = stack depth, and the
    full stack in `module` for flame-style analysis."""
    rows = []
    for line in text.splitlines():
        p = line.split(None, 2)
        if len(p) != 3:
            continue
        try:
            ts = float(p[0])
            tid = int(p[1])
        except ValueError:
            continue
        stack = p[2].strip()
        if not stack:
            continue
        frames = stack.split(";")
        rows.append(
            {
                "timestamp": ts - time_base,
                "event": float(len(frames)),
                "tid": tid,
                "name": frames[-1],
                "module": stack,
                "device_kind": "cpu",
            }
        )
    return make_frame(rows)
