"""Speed-of-light (SOLAR) groundwork: the attainable-peak analysis pass.

``tools/kernel_perf.py`` already knew how to read a chip's attainable
peak — XPlane plane stats first (``tpu_meta.json``, written by the
xplane ingest), device-kind datasheet table second — but only as a
standalone MFU-tracking tool.  This module promotes that read into the
first *registered* analysis pass (``sol_roofline``): every analyze run
now records how far each device ran from its hardware limit, per HLO op
class, which is the quantitative footing the SOLAR roadmap item
(per-op-class rooflines, bound-ness board overlay) builds on.

Unlike ``roofline_profile`` (which needs the measured per-device peaks
in ``tpu_meta.json`` and goes silent without them), ``sol_roofline``
falls back to the datasheet bf16 peak for the trace's ``device_kind``
— so a capture from a machine whose runtime didn't report plane stats
still gets a speed-of-light distance, with the peak's provenance
recorded as an info feature.
"""

from __future__ import annotations

import json
import os

import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import analysis_pass
from sofa_tpu.trace import CopyKind, narrow, roi_clip

# Datasheet bf16 peaks per chip generation (TFLOP/s per chip) — the
# fallback when the profiler's plane stats don't carry the peak.  Moved
# here from tools/kernel_perf.py, which now imports it.
KIND_PEAKS = {
    "v6e": 918.0, "v6": 918.0,
    "v5p": 459.0,
    "v5e": 197.0, "v5litepod": 197.0, "v5": 197.0,
    "v4": 275.0,
    "v3": 123.0,
}


def peak_from_kind(kind: str) -> "float | None":
    """Datasheet bf16 peak for a ``device_kind`` string, longest match
    first (``"TPU v5 lite"`` -> v5)."""
    k = (kind or "").lower().replace("tpu", "").strip()
    for key, val in sorted(KIND_PEAKS.items(), key=lambda kv: -len(kv[0])):
        if key in k:
            return val
    return None


def load_attainable_peaks(cfg) -> dict:
    """device_id(str) -> {"peak_tflops", "peak_hbm_gbps", "peak_source"} from
    the plane-stats sidecar; empty when absent/unreadable."""
    path = cfg.path("tpu_meta.json")
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return {}
    out = {}
    for dev, peaks in meta.items():
        if not isinstance(peaks, dict):
            continue
        tflops = float(peaks.get("peak_teraflops_per_second", 0) or 0)
        gbps = float(peaks.get("peak_hbm_bw_gigabytes_per_second", 0) or 0)
        if tflops > 0:
            out[str(dev)] = {"peak_tflops": tflops, "peak_hbm_gbps": gbps,
                             "peak_source": "plane stats"}
    return out


@analysis_pass(
    name="sol_roofline", order=270,
    reads_frames=("tputrace",),
    reads_columns=("timestamp", "duration", "deviceId", "category",
                   "copyKind", "device_kind", "hlo_category", "flops",
                   "bytes_accessed"),
    provides_features=("tpu*_sol_peak_tflops", "tpu*_sol_distance",
                       "sol_peak_source"),
    provides_artifacts=("sol_roofline.csv",),
    after=("spotlight",),
)
def sol_roofline(frames, cfg, features: Features) -> None:
    """Distance from speed of light, per device and HLO op class.

    For every kernel op with flops metadata the attainable time is
    ``flops / peak_flops`` (plus ``bytes / peak_hbm_bw`` when the memory
    peak is known — the roofline max); the *distance* is actual time over
    attainable time, duration-weighted.  1.0 = at the hardware limit.
    Emits ``tpu<N>_sol_peak_tflops`` / ``tpu<N>_sol_distance`` features,
    the per-class table ``sol_roofline.csv``, and the provenance of each
    peak (plane stats vs datasheet) as ``sol_peak_source``."""
    df = frames.get("tputrace")
    if df is None or df.empty:
        return
    df = narrow(df, ["timestamp", "duration", "deviceId", "category",
                     "copyKind", "device_kind", "hlo_category", "flops",
                     "bytes_accessed"])
    df = roi_clip(df, cfg)
    rows = df[(df["category"] == 0)
              & (df["copyKind"] == int(CopyKind.KERNEL))
              & (df["duration"] > 0) & (df["flops"] > 0)]
    if rows.empty:
        return
    measured = load_attainable_peaks(cfg)
    out = []
    sources = set()
    for device_id, dev in rows.groupby("deviceId"):
        peaks = measured.get(str(int(device_id)))
        if peaks:
            peak_tflops = peaks["peak_tflops"]
            peak_gbps = peaks["peak_hbm_gbps"]
            source = peaks["peak_source"]
        else:
            kinds = dev["device_kind"].astype(str)
            kind = kinds.mode().iloc[0] if len(kinds) else ""
            dk_peak = peak_from_kind(kind)
            if dk_peak is None:
                continue  # unknown chip: no defensible bound
            peak_tflops, peak_gbps = dk_peak, 0.0
            source = f"datasheet bf16 for device_kind {kind!r}"
        sources.add(source)
        agg = dev.groupby("hlo_category").agg(
            time=("duration", "sum"), count=("duration", "count"),
            flops=("flops", "sum"), nbytes=("bytes_accessed", "sum"))
        sol = agg["flops"] / (peak_tflops * 1e12)
        if peak_gbps > 0:
            sol = pd.concat(
                [sol, agg["nbytes"] / (peak_gbps * 1e9)], axis=1).max(axis=1)
        agg["sol_time"] = sol
        # Distance >= 1 by clipping: overcounted cost metadata must not
        # report a class as running faster than the hardware allows.
        agg["sol_distance"] = (agg["time"] / sol.where(sol > 0)).clip(
            lower=1.0)
        agg["deviceId"] = int(device_id)
        agg["peak_tflops"] = peak_tflops
        out.append(agg)
        total = float(agg["time"].sum())
        weighted = float((agg["time"] * agg["sol_distance"]).sum())
        features.add(f"tpu{device_id}_sol_peak_tflops", peak_tflops)
        if total > 0:
            features.add(f"tpu{device_id}_sol_distance", weighted / total)
    if not out:
        return
    table = (pd.concat(out).reset_index()
             .sort_values(["deviceId", "time"], ascending=[True, False]))
    table.to_csv(cfg.path("sol_roofline.csv"), index=False)
    features.add_info("sol_peak_source", "; ".join(sorted(sources)))
