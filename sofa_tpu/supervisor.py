"""Collector supervision during `sofa record`.

Before this layer, a collector that died mid-run was silently discovered
dead at stop time: its series simply ended, and nothing recorded when or
why.  The supervisor is a watchdog thread that polls every *watchable*
started collector (one that exposes liveness — a backing process or
sampler thread, :meth:`Collector.alive`) and its output growth:

  * a collector found dead before the epilogue is recorded in the run
    manifest at detection time (``died: true``, ``deaths``, ``exit_code``)
    and **restarted** with bounded retries and exponential backoff
    (``--collector_restarts``, default 1; backoff 0.5s * 2^attempt).  A
    successful restart lands ``restarts: n`` in the manifest — the series
    has a gap, but the rest of the run is covered;
  * once the budget is exhausted the collector's status becomes ``died``
    (sticky — the epilogue's stop cannot whitewash it) and `sofa status`
    exits nonzero;
  * output files that stop growing while the process stays alive are
    flagged once (``output_stalled: true``) — a wedged-but-alive collector
    is a fidelity warning, not a kill (it may legitimately be buffering).

The poll period (default 0.5s — "detected within seconds") is tunable via
SOFA_SUPERVISOR_POLL_S for tests.  The exascale-diagnostics framing
(PAPERS: "Enhancing Performance Insight at Scale") treats exactly this —
collector fault tolerance as a first-class design axis — as what separates
a profiler you trust at scale from one you babysit.

record drives the lifecycle: start() after the prologue, stop() before the
epilogue (and before kill-all), so a restart can never race a deliberate
collector stop.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List

from sofa_tpu import telemetry
from sofa_tpu.printing import print_warning

# Polls with zero output growth (while alive) before the one-time stall
# flag: 20 polls * 0.5s default = 10s of silence.
_STALL_POLLS = 20

_BACKOFF_BASE_S = 0.5


def _poll_s() -> float:
    try:
        return max(float(os.environ.get("SOFA_SUPERVISOR_POLL_S", "0.5")),
                   0.05)
    except ValueError:
        return 0.5


class CollectorSupervisor:
    """Watchdog over the started-collector list for one recording."""

    def __init__(self, cfg, collectors: List):
        self.cfg = cfg
        self.collectors = collectors  # live reference: record appends to it
        self.poll_s = _poll_s()
        self._stop = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sofa_supervisor")
        self._state: Dict[str, dict] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Idempotent; after return no restart can fire."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    # -- watchdog loop -----------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            for col in list(self.collectors):
                if self._stop.is_set():
                    return
                try:
                    self._check(col)
                except Exception as e:  # noqa: BLE001 — watchdog never dies
                    print_warning(f"supervisor: check of {col.name} "
                                  f"failed: {e}")

    def _check(self, col) -> None:
        alive = col.alive()
        if alive is None:
            return  # not watchable (prefix-only / one-shot collectors)
        st = self._state.setdefault(col.name, {
            "deaths": 0, "restarts": 0, "retry_at": None,
            "gave_up": False, "bytes": -1, "stall_polls": 0,
            "stalled_flagged": False,
        })
        if st["gave_up"]:
            return
        if st["retry_at"] is not None:
            # Monotonic, not wall: an NTP step mid-run must not fire the
            # restart early or push it out indefinitely (SL003).
            if time.monotonic() >= st["retry_at"]:
                self._restart(col, st)
            return
        if alive:
            self._track_growth(col, st)
            return
        # -- death detected ------------------------------------------------
        st["deaths"] += 1
        proc = getattr(col, "proc", None)
        exit_code = proc.poll() if proc is not None else None
        fields = {"died": True, "deaths": st["deaths"]}
        if exit_code is not None:
            fields["exit_code"] = int(exit_code)
        budget = max(int(getattr(self.cfg, "collector_restarts", 1) or 0), 0)
        if st["restarts"] >= budget:
            # Sticky status: the epilogue's stop/flush must not whitewash a
            # collector that ended the run dead.
            telemetry.collector_event(col.name, "died", **fields)
            print_warning(
                f"{col.name}: died mid-run (exit {exit_code}) — restart "
                f"budget ({budget}) exhausted; its series end here")
            st["gave_up"] = True
            return
        telemetry.collector_event(col.name, **fields)
        backoff = _BACKOFF_BASE_S * (2 ** st["restarts"])
        print_warning(f"{col.name}: died mid-run (exit {exit_code}) — "
                      f"restarting in {backoff:.1f}s")
        st["retry_at"] = time.monotonic() + backoff

    def _restart(self, col, st: dict) -> None:
        st["retry_at"] = None
        try:
            col.start()
        except Exception as e:  # noqa: BLE001 — a failed restart = gave up
            telemetry.collector_event(col.name, "died",
                                      restart_error=str(e)[:300])
            print_warning(f"{col.name}: restart failed: {e}")
            st["gave_up"] = True
            return
        st["restarts"] += 1
        st["bytes"], st["stall_polls"] = -1, 0
        telemetry.collector_event(col.name, restarts=st["restarts"])
        print_warning(f"{col.name}: restarted "
                      f"(attempt {st['restarts']})")

    def _track_growth(self, col, st: dict) -> None:
        b = telemetry.collector_bytes(col.outputs())
        if b != st["bytes"]:
            st["bytes"], st["stall_polls"] = b, 0
            return
        st["stall_polls"] += 1
        if st["stall_polls"] == _STALL_POLLS and not st["stalled_flagged"]:
            st["stalled_flagged"] = True
            telemetry.collector_event(col.name, output_stalled=True)
            print_warning(
                f"{col.name}: alive but its output has not grown for "
                f"{_STALL_POLLS * self.poll_s:.0f}s — series may be "
                "wedged or buffering")
