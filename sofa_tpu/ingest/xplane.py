"""XSpace/XPlane trace ingest — the TPU replacement for nvprof CSV parsing.

The reference shells out to `nvprof --csv --print-gpu-trace` and reads CUPTI
sqlite tables (/root/reference/bin/sofa_preprocess.py:1339-1456); here we
parse the XSpace protobuf that jax.profiler writes
(logdir/xprof/plugins/profile/<run>/<host>.xplane.pb) with bindings generated
from the public xplane.proto schema (sofa_tpu/native/xplane.proto).

Plane semantics (observed from jax.profiler on TPU v5e):
  /device:TPU:N    — device planes; lines "XLA Modules" (jit program spans,
                     one event per executed module), "XLA Ops" (per-HLO-op
                     timeline on the TensorCore), "Async XLA Ops" (DMA /
                     async copies), "TC Overlay".
  /host:CPU        — host runtime + python tracer events, one line per thread.
  plane stats carry peak_teraflops_per_second / peak_hbm_bw_gigabytes_per_second
  (used for MXU/HBM utilization percentages).

Event time = line.timestamp_ns + event.offset_ps/1e3, in a per-session clock.
Clock alignment: the injected TraceAnnotation ``sofa_timebase_marker:<unix_ns>``
(collectors/xprof.py) appears on a host line; unix_offset = its encoded unix
time minus its session time.  This replaces the reference's cuhello
known-kernel trick (sofa_preprocess.py:1557-1616).
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np
import pandas as pd

from sofa_tpu.ingest import xplane_pb2
from sofa_tpu.printing import print_info, print_warning
from sofa_tpu.trace import CopyKind, classify_hlo_kind, empty_frame, make_frame

_MARKER_RE = re.compile(r"sofa_timebase_marker:(\d+)")
_DEVICE_RE = re.compile(r"/device:TPU:(\d+)")
_MODULE_NAME_RE = re.compile(r"^(.*?)\(\d+\)$")

# HLO textual replica_groups, two syntaxes:
#   literal: replica_groups={{0,2},{1,3}}
#   iota v2: replica_groups=[4,2]<=[8]  or  [4,2]<=[2,2,2]T(0,2,1)
_RG_LITERAL_RE = re.compile(r"replica_groups=\{(\{[\d, ]*\}(?:, ?\{[\d, ]*\})*)\}")
_RG_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_RG_STAT_KEYS = ("replica_groups", "expression", "long_name", "hlo_text")


def parse_replica_groups(text: str) -> Optional[List[List[int]]]:
    """Extract collective participant groups from HLO text, if present."""
    m = _RG_LITERAL_RE.search(text)
    if m:
        groups = []
        for block in re.findall(r"\{([\d, ]*)\}", m.group(1)):
            ids = [int(x) for x in block.replace(",", " ").split()]
            if ids:
                groups.append(ids)
        return groups or None
    m = _RG_IOTA_RE.search(text)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.transpose(perm)
        flat = ids.reshape(-1)
        if len(flat) != n_groups * group_size:
            return None
        return flat.reshape(n_groups, group_size).tolist()
    return None


# fw/bw phase attribution (the reference greps GPU kernel names for _fw_/_bw_,
# bin/sofa:284-285, sofa_aisi.py:34-36).  On TPU the signal is the op's JAX
# provenance path in the XPlane "tf_op"/op_name stat: backward-pass HLOs carry
# the transpose(jvp(...)) transform marker (or gradient scope names from
# non-JAX frontends); forward HLOs carry jvp(...) without transpose.
# NB: only the transform marker "transpose(jvp" — a bare "transpose(" would
# also match ordinary HLO transpose instructions in long_name/expression text.
_BW_PATH_RE = re.compile(
    r"transpose\(jvp|/grad(?:ients)?[/_)]|backward", re.IGNORECASE)
_FW_PATH_RE = re.compile(r"jvp\(|forward", re.IGNORECASE)
_PHASE_STAT_KEYS = ("tf_op", "op_name", "long_name", "expression")


def _phase_from_stats(stats: Dict[str, object]) -> str:
    for key in _PHASE_STAT_KEYS:
        v = stats.get(key)
        if isinstance(v, bytes):
            v = v.decode(errors="replace")
        if isinstance(v, str) and v:
            if _BW_PATH_RE.search(v):
                return "bw"
            if _FW_PATH_RE.search(v):
                return "fw"
    return ""


def _groups_from_stats(stats: Dict[str, object]) -> str:
    """JSON-encoded replica groups from whichever stat carries HLO text."""
    import json as _json

    for key in _RG_STAT_KEYS:
        v = stats.get(key)
        if isinstance(v, bytes):
            v = v.decode(errors="replace")
        if isinstance(v, str) and "replica_groups" in v:
            parsed = parse_replica_groups(v)
            if parsed:
                return _json.dumps(parsed)
    return ""


def find_xplane_files(xprof_dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(xprof_dir, "plugins", "profile", "*", "*.xplane.pb")))


def load_xspace(path: str) -> xplane_pb2.XSpace:
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _stat_value(stat, stat_meta) -> Tuple[str, object]:
    name = stat_meta.get(stat.metadata_id)
    name = name.name if name is not None else str(stat.metadata_id)
    which = stat.WhichOneof("value")
    value = getattr(stat, which) if which else None
    if which == "ref_value":
        # String stats may be interned: ref_value points at the
        # stat_metadata entry whose *name* is the string payload.
        ref = stat_meta.get(stat.ref_value)
        value = ref.name if ref is not None else str(stat.ref_value)
    return name, value


def _event_stats(ev, stat_meta) -> Dict[str, object]:
    return dict(_stat_value(s, stat_meta) for s in ev.stats)


# Real libtpu captures name XLA-Ops events with the full HLO instruction
# text ("%fusion.31 = bf16[...] fusion(...), kind=kLoop, ...").  The short
# op name is the lvalue; the full text is still mined for replica_groups.
_HLO_INSTR_RE = re.compile(r"^(?:ROOT )?%([\w.\-]+) = ")


def _short_op_name(name: str) -> str:
    m = _HLO_INSTR_RE.match(name)
    return m.group(1) if m else name


# Stat names that feed a derived op field; everything else (timing stats,
# flow ids) cannot change classification, so per-metadata caching is safe.
_DERIVED_STAT_KEYS = frozenset(
    {"hlo_category", "flops", "bytes_accessed", "source"}
    | set(_PHASE_STAT_KEYS) | set(_RG_STAT_KEYS))


def _derive_op_fields(label: str, md: Dict[str, object]) -> dict:
    """Metadata-derived op fields, computed once per event-metadata id.

    Real captures repeat a few hundred metadata ids across ~10^5 events;
    deriving classification/phase/groups per event dominated ingest time.
    """
    hlo_cat = str(md.get("hlo_category", "") or "")
    kind = int(classify_hlo_kind(label, hlo_cat))
    op_path = md.get("tf_op") or md.get("op_name") or ""
    if isinstance(op_path, bytes):
        op_path = op_path.decode(errors="replace")
    return {
        "label": label,
        "hlo_cat": hlo_cat,
        "kind": kind,
        "flops": float(md.get("flops", 0) or 0),
        "nbytes": int(md.get("bytes_accessed", 0) or 0),
        "groups": _groups_from_stats(md) if kind >= 20 else "",
        "phase": _phase_from_stats(md),
        "source": str(md.get("source", "") or ""),
        "op_path": str(op_path).rstrip(":"),
        "_md": md,
    }


def find_marker_offsets_ns(xspace) -> List[Tuple[int, int]]:
    """All timebase markers as (session_ns, unix_ns - session_ns), sorted.

    api.profile emits one marker at trace start and one at stop; their
    offsets agreeing is the within-capture consistency check (the session
    clock's *origin* legitimately differs between captures on tunneled
    backends, so cross-capture comparison proves nothing).
    """
    out: List[Tuple[int, int]] = []
    for plane in xspace.planes:
        if not plane.name.startswith("/host:"):
            continue
        marker_ids = {}
        for mid, meta in plane.event_metadata.items():
            m = _MARKER_RE.search(meta.name)
            if m:
                marker_ids[mid] = int(m.group(1))
        if not marker_ids:
            continue
        for line in plane.lines:
            for ev in line.events:
                if ev.metadata_id in marker_ids:
                    session_ns = line.timestamp_ns + ev.offset_ps // 1000
                    out.append((session_ns,
                                marker_ids[ev.metadata_id] - session_ns))
    return sorted(out)


def find_marker_offset_ns(xspace) -> Optional[int]:
    """unix_ns - session_ns from the EARLIEST marker (the start-of-trace
    anchor) — the offset ingest aligns the whole capture with."""
    offs = find_marker_offsets_ns(xspace)
    return offs[0][1] if offs else None


def _resolve_event_meta(em, sm, metadata_id: int, cache: Dict[int, tuple]):
    """(name, display_name, metadata_stats) for an event's metadata id.

    Cached per call site: real captures repeat a few hundred metadata ids
    across ~10^5 events.  Real libtpu captures carry flops /
    bytes_accessed / hlo_category / tf_op on XEventMetadata.stats — only
    synthetic traces put them on the event — which round 1's self-made
    protos masked.  XEventMetadata has the same .stats shape as XEvent.
    """
    r = cache.get(metadata_id)
    if r is None:
        meta = em.get(metadata_id)
        name = meta.name if meta is not None else ""
        disp = (meta.display_name
                if meta is not None and meta.display_name else name)
        md = _event_stats(meta, sm) if meta is not None else {}
        disp = _enrich_custom_call(name, disp, md)
        r = (name, disp, md)
        cache[metadata_id] = r
    return r


_CUSTOM_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')


def _enrich_custom_call(name: str, disp: str, md: Dict) -> str:
    """Readable display names for custom-call ops.

    Real captures (v2 fixture) show every custom call as an opaque
    "custom-call.N" / "closed_call.N": Pallas kernels — the hottest
    hand-written ops — were unattributable in top-ops and the board.  The
    HLO text carries the target, and Mosaic calls carry the launching
    Python line in their `source` stat, so:

      tpu_custom_call + source -> "pallas@transformer.py:249"
      AllocateBuffer          -> "AllocateBuffer" (grouped, not per-instr)

    Applied at the shared per-metadata cache so the native-scanner and
    pure-Python paths stay row-identical.
    """
    if "custom-call" not in name:
        return disp
    m = _CUSTOM_TARGET_RE.search(name)
    if not m:
        return disp
    target = m.group(1)
    if target == "tpu_custom_call":
        src = str(md.get("source", "") or "")
        return ("pallas@" + src.rsplit("/", 1)[-1]) if src else \
            ("pallas:" + disp)
    return target


def _iter_line_events(plane, line) -> Iterable[Tuple[str, str, int, int, Dict]]:
    """Yield (name, display_name, start_ns, dur_ns, stats) per event.

    stats merge the event-metadata stats with the per-event stats (event
    wins).
    """
    em = plane.event_metadata
    sm = plane.stat_metadata
    base_ns = line.timestamp_ns
    cache: Dict[int, tuple] = {}
    for ev in line.events:
        name, disp, md = _resolve_event_meta(em, sm, ev.metadata_id, cache)
        start_ns = base_ns + ev.offset_ps // 1000
        dur_ns = ev.duration_ps // 1000
        stats = {**md, **_event_stats(ev, sm)} if md else _event_stats(ev, sm)
        yield name, disp, start_ns, dur_ns, stats


def device_plane_meta(plane) -> Dict[str, float]:
    sm = plane.stat_metadata
    out = {}
    for stat in plane.stats:
        name, value = _stat_value(stat, sm)
        if isinstance(value, (int, float)):
            out[name] = float(value)
    return out


_OP_KEYS = (
    "timestamp", "event", "duration", "deviceId", "copyKind", "payload",
    "bandwidth", "name", "category", "hlo_category", "module", "flops",
    "bytes_accessed", "groups", "phase", "source", "op_path")


_OP_STR_KEYS = frozenset(
    {"name", "hlo_category", "module", "groups", "phase", "source",
     "op_path"})
_OP_INT_KEYS = frozenset({"deviceId", "copyKind", "category"})


def _native_op_chunk(sl, em, sm, meta_cache, device_id: int, category: int,
                     base_ns: int, offset_ns: int, time_base: float):
    """One op line from native scan arrays -> a column chunk, vectorized.

    Metadata-derived fields are computed once per metadata id (exactly the
    Python loop's cache) and gathered through np.unique's inverse index;
    per-event work is pure array arithmetic.
    """
    mids = sl.metadata_ids
    uniq, inv = np.unique(mids, return_inverse=True)
    fields = []
    for mid in uniq.tolist():
        name, disp, md = _resolve_event_meta(em, sm, mid, meta_cache)
        label = _short_op_name(disp)
        if name != label:
            # The metadata name is the full HLO instruction — the one
            # place replica_groups always appears.
            md = dict(md)
            md.setdefault("hlo_text", name)
        fields.append(_derive_op_fields(label, md))
    n = len(mids)
    dur_s = sl.durations_ps.astype(np.float64) / 1e12
    ts = ((base_ns + sl.offsets_ps // 1000 + offset_ns) / 1e9) - time_base
    kind = np.fromiter((f["kind"] for f in fields), np.int64,
                       len(fields))[inv]
    flops = np.fromiter((f["flops"] for f in fields), np.float64,
                        len(fields))[inv]
    nbytes = np.fromiter((float(f["nbytes"]) for f in fields), np.float64,
                         len(fields))[inv]

    def gather(key):
        return np.asarray([f[key] for f in fields], dtype=object)[inv]

    return {
        "timestamp": ts,
        "event": np.arange(n, dtype=np.float64),
        "duration": dur_s,
        "deviceId": np.full(n, device_id, np.int64),
        "copyKind": kind,
        "payload": np.where(kind != int(CopyKind.KERNEL), nbytes, 0.0),
        "bandwidth": np.where(dur_s > 0, nbytes / np.where(dur_s > 0,
                                                           dur_s, 1.0), 0.0),
        "name": gather("label"),
        "category": np.full(n, category, np.int64),
        "hlo_category": gather("hlo_cat"),
        "flops": flops,
        "bytes_accessed": nbytes,
        "groups": gather("groups"),
        "phase": gather("phase"),
        "source": gather("source"),
        "op_path": gather("op_path"),
    }


def _concat_chunks(chunks: List[Dict[str, object]], keys, str_keys,
                   int_keys) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k in keys:
        parts = []
        for c in chunks:
            v = c[k]
            if isinstance(v, np.ndarray):
                parts.append(v)
            elif k in str_keys:
                parts.append(np.asarray(v, dtype=object))
            elif k in int_keys:
                parts.append(np.asarray(v, dtype=np.int64))
            else:
                parts.append(np.asarray(v, dtype=np.float64))
        out[k] = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return out


_HOST_KEYS = ("timestamp", "event", "duration", "tid", "name", "module")


def _scan_lines_for(native_planes, plane_name: str):
    """The native scan's per-line arrays for one plane, indexed by the
    line's position (wire order == proto repeated-field order)."""
    if native_planes is None:
        return None
    for sp in native_planes:
        if sp.name == plane_name:
            return {i: sl for i, sl in enumerate(sp.lines)}
    return None


def _native_host_chunk(sl, em, sm, cache, lane: int, thread_name: str,
                       tid: int, base_ns: int, offset_ns: int,
                       time_base: float):
    """One host line from native scan arrays -> a column chunk (markers
    filtered per unique metadata id, like the Python loop)."""
    mids = sl.metadata_ids
    uniq, inv = np.unique(mids, return_inverse=True)
    disps, keep = [], []
    for mid in uniq.tolist():
        name, disp, _md = _resolve_event_meta(em, sm, mid, cache)
        disps.append(disp)
        keep.append(_MARKER_RE.search(name) is None)
    mask = np.asarray(keep, dtype=bool)[inv]
    n = int(mask.sum())
    if n == 0:
        return None
    ts = ((base_ns + sl.offsets_ps[mask] // 1000 + offset_ns) / 1e9) \
        - time_base
    return {
        "timestamp": ts,
        "event": np.full(n, float(lane)),
        "duration": sl.durations_ps[mask].astype(np.float64) / 1e12,
        "tid": np.full(n, tid, np.int64),
        "name": np.asarray(disps, dtype=object)[inv][mask],
        "module": [thread_name] * n,
    }


def xspace_to_frames(
    xspace,
    time_base: float,
    offset_ns: Optional[int] = None,
    host: str = "",
    device_id_base: int = 0,
    pb_path: Optional[str] = None,
) -> Dict[str, pd.DataFrame]:
    """Convert one XSpace into unified-schema frames.

    Returns keys: tputrace (HLO ops, sync category=0 / async category=2),
    tpumodules, hosttrace, and device_meta (plane peak-rate stats as a
    plain dict under key "_meta").

    When ``pb_path`` names the serialized source, the native columnar
    scanner (native/xplane_scan.cc) supplies per-line event arrays and the
    op frame assembles vectorized; its absence or any layout mismatch
    falls back to the per-event Python loop with identical output.
    """
    if offset_ns is None:
        offset_ns = find_marker_offset_ns(xspace)
    if offset_ns is None:
        # Degraded alignment: assume the session clock started at the run's
        # time base. Better than dropping the trace; flagged for the report.
        print_warning(
            "xplane: no sofa_timebase_marker found — device timeline aligned "
            "to record start only (clock skew possible)"
        )
        offset_ns = int(time_base * 1e9)

    def to_rel_s(session_ns: int) -> float:
        return (session_ns + offset_ns) / 1e9 - time_base

    native_planes = None
    if pb_path is not None:
        from sofa_tpu.ingest import native_scan

        if native_scan.enabled():
            native_planes = native_scan.scan_file(pb_path, _DERIVED_STAT_KEYS)

    # The op frame accumulates per-line CHUNKS (numpy arrays from the
    # native path, plain lists from the Python loop); columns concatenate
    # once at the end.
    op_chunks: List[Dict[str, object]] = []
    module_rows: List[dict] = []
    host_chunks: List[Dict[str, object]] = []
    step_rows: List[dict] = []
    custom_rows: List[dict] = []
    meta: Dict[str, Dict[str, float]] = {}

    for plane in xspace.planes:
        dev_match = _DEVICE_RE.match(plane.name)
        if dev_match:
            # Offset per-host ordinals so multi-host ingest never merges
            # distinct chips (host i contributes ids i*256 + local ordinal).
            device_id = device_id_base + int(dev_match.group(1))
            meta[str(device_id)] = device_plane_meta(plane)
            module_spans: List[Tuple[float, float, str]] = []
            for line in plane.lines:
                if line.name == "Steps":
                    # XLA's own device-side step demarcation (one span per
                    # profiler StepMarker) — exact iteration boundaries,
                    # preferred by aisi over host-marker matching.
                    for ev_idx, (name, disp, start_ns, dur_ns, stats) in \
                            enumerate(_iter_line_events(plane, line)):
                        try:
                            step_no = int(name)
                        except ValueError:
                            # Per-line ordinal, NOT a global counter: the
                            # same logical step must get the same event id
                            # on every device or step_skew_profile's
                            # groupby(event) finds no cross-device groups.
                            step_no = ev_idx
                        step_rows.append(
                            {
                                "timestamp": to_rel_s(start_ns),
                                "event": float(step_no),
                                "duration": dur_ns / 1e9,
                                "deviceId": device_id,
                                "name": f"step {step_no}",
                                "device_kind": "tpu",
                            }
                        )
                if line.name == "XLA Modules":
                    for name, disp, start_ns, dur_ns, stats in _iter_line_events(plane, line):
                        mod_match = _MODULE_NAME_RE.match(name)
                        mod = mod_match.group(1) if mod_match else name
                        t = to_rel_s(start_ns)
                        d = dur_ns / 1e9
                        module_spans.append((t, t + d, mod))
                        module_rows.append(
                            {
                                "timestamp": t,
                                "event": float(stats.get("run_id", 0) or 0),
                                "duration": d,
                                "deviceId": device_id,
                                "pid": int(stats.get("program_id", -1) or -1),
                                "name": mod,
                                "module": mod,
                                "device_kind": "tpu",
                            }
                        )
            module_spans.sort()
            span_starts = np.array([s[0] for s in module_spans])
            span_ends = np.array([s[1] for s in module_spans])
            span_names = [s[2] for s in module_spans]
            plane_chunk_start = len(op_chunks)
            sm = plane.stat_metadata
            em = plane.event_metadata
            # Stat ids whose value would change a metadata-derived field;
            # events carrying one (synthetic traces put everything on the
            # event) take the slow re-derive path, real captures (only
            # timing stats per event) hit the per-metadata cache.
            derived_ids = {mid for mid, m in sm.items()
                           if m.name in _DERIVED_STAT_KEYS}
            scan_lines = _scan_lines_for(native_planes, plane.name)
            for line_idx, line in enumerate(plane.lines):
                if line.name not in ("XLA Ops", "Async XLA Ops"):
                    continue
                category = 0 if line.name == "XLA Ops" else 2
                base_ns = line.timestamp_ns
                meta_cache: Dict[int, tuple] = {}
                derive_cache: Dict[int, dict] = {}

                sl = scan_lines.get(line_idx) if scan_lines else None
                if (sl is not None and sl.name == line.name
                        and len(sl.metadata_ids) == len(line.events)
                        and not (sl.flags & 1).any()):
                    # Native fast path: derive once per metadata id, gather
                    # with the inverse index, no per-event Python objects.
                    # (flag bit0 = derived per-event stats -> Python loop.)
                    chunk = _native_op_chunk(
                        sl, em, sm, meta_cache, device_id, category,
                        base_ns, offset_ns, time_base)
                    if chunk is not None:
                        op_chunks.append(chunk)
                        continue
                cols: Dict[str, list] = {k: [] for k in _OP_KEYS
                                         if k != "module"}
                for idx, ev in enumerate(line.events):
                    c = derive_cache.get(ev.metadata_id)
                    if c is None:
                        name, disp, md = _resolve_event_meta(
                            em, sm, ev.metadata_id, meta_cache)
                        label = _short_op_name(disp)
                        if name != label:
                            # The metadata name is the full HLO instruction
                            # — the one place replica_groups always appears.
                            md = dict(md)
                            md.setdefault("hlo_text", name)
                        c = _derive_op_fields(label, md)
                        derive_cache[ev.metadata_id] = c
                    if ev.stats and not derived_ids.isdisjoint(
                            s.metadata_id for s in ev.stats):
                        merged = dict(c["_md"])
                        merged.update(_event_stats(ev, sm))
                        c = _derive_op_fields(c["label"], merged)
                    dur_s = ev.duration_ps / 1e12
                    t = to_rel_s(base_ns + ev.offset_ps // 1000)
                    nbytes = c["nbytes"]
                    cols["timestamp"].append(t)
                    cols["event"].append(float(idx))
                    cols["duration"].append(dur_s)
                    cols["deviceId"].append(device_id)
                    cols["copyKind"].append(c["kind"])
                    cols["payload"].append(
                        nbytes if c["kind"] != int(CopyKind.KERNEL) else 0)
                    cols["bandwidth"].append(
                        (nbytes / dur_s) if dur_s > 0 else 0.0)
                    cols["name"].append(c["label"])
                    cols["category"].append(category)
                    cols["hlo_category"].append(c["hlo_cat"])
                    cols["flops"].append(c["flops"])
                    cols["bytes_accessed"].append(float(nbytes))
                    cols["groups"].append(c["groups"])
                    cols["phase"].append(c["phase"])
                    cols["source"].append(c["source"])
                    cols["op_path"].append(c["op_path"])
                if cols["timestamp"]:
                    op_chunks.append(cols)
            # Module attribution for this plane's ops, one vectorized
            # searchsorted per chunk instead of a binary search per event.
            for chunk in op_chunks[plane_chunk_start:]:
                ts = np.asarray(chunk["timestamp"], dtype=np.float64)
                if len(ts) and len(span_starts):
                    i = np.searchsorted(span_starts, ts, side="right") - 1
                    valid = ((i >= 0)
                             & (ts < span_ends[np.clip(i, 0, None)] + 1e-9))
                    chunk["module"] = [
                        span_names[j] if ok else ""
                        for j, ok in zip(i, valid)]
                else:
                    chunk["module"] = [""] * len(ts)
        elif plane.name.startswith("/device:CUSTOM:"):
            # Runtime-defined planes (e.g. "Megascale Trace" — the DCN
            # collective engine on multi-host pods).  Semantics are
            # runtime-version-specific, so events are preserved verbatim:
            # one lane per line, module = plane label.  They render as
            # their own timeline series and feed no derived pass.
            label = plane.name.split(":", 2)[-1]
            if host:
                label = f"{host}:{label}"
            for lane, line in enumerate(plane.lines):
                for name, disp, start_ns, dur_ns, stats in \
                        _iter_line_events(plane, line):
                    custom_rows.append(
                        {
                            "timestamp": to_rel_s(start_ns),
                            "event": float(lane),
                            "duration": dur_ns / 1e9,
                            # Host ordinal base keeps multi-host events
                            # attributable, like the device planes.
                            "deviceId": device_id_base,
                            "tid": int(line.id),
                            "name": disp,
                            "device_kind": "custom",
                            "module": label,
                        }
                    )
        elif plane.name.startswith("/host:") and "metadata" not in plane.name:
            # y-value = thread lane ordinal: events of one thread share a
            # lane, like the reference's per-metric lanes (round-1 verdict
            # flagged the old len(name)%97 hash as meaningless).
            em = plane.event_metadata
            sm = plane.stat_metadata
            scan_lines = _scan_lines_for(native_planes, plane.name)
            for lane, line in enumerate(plane.lines):
                thread_name = line.name or str(line.id)
                base_ns = line.timestamp_ns
                tid = int(line.id)
                cache: Dict[int, tuple] = {}
                sl = scan_lines.get(lane) if scan_lines else None
                if (sl is not None and sl.name == line.name
                        and len(sl.metadata_ids) == len(line.events)):
                    chunk = _native_host_chunk(
                        sl, em, sm, cache, lane, thread_name, tid, base_ns,
                        offset_ns, time_base)
                    if chunk is not None:
                        host_chunks.append(chunk)
                    continue
                cols: Dict[str, list] = {k: [] for k in _HOST_KEYS}
                for ev in line.events:
                    name, disp, _md = _resolve_event_meta(
                        em, sm, ev.metadata_id, cache)
                    if _MARKER_RE.search(name):
                        continue
                    cols["timestamp"].append(
                        to_rel_s(base_ns + ev.offset_ps // 1000))
                    cols["event"].append(float(lane))
                    cols["duration"].append(ev.duration_ps / 1e12)
                    cols["tid"].append(tid)
                    cols["name"].append(disp)
                    cols["module"].append(thread_name)
                if cols["timestamp"]:
                    host_chunks.append(cols)

    n_ops = sum(len(c["timestamp"]) for c in op_chunks)
    op_cols: Dict[str, object] = {}
    if n_ops:
        op_cols = _concat_chunks(op_chunks, _OP_KEYS, _OP_STR_KEYS,
                                 _OP_INT_KEYS)
        op_cols["device_kind"] = ["tpu"] * n_ops
    n_host = sum(len(c["timestamp"]) for c in host_chunks)
    host_cols: Dict[str, object] = {}
    if n_host:
        host_cols = _concat_chunks(host_chunks, _HOST_KEYS,
                                   {"name", "module"}, {"tid"})
        host_cols["device_kind"] = ["host"] * n_host
        host_cols["pid"] = [-1] * n_host
        # Host-plane rows carry their host's ordinal base (like CUSTOM
        # planes) so multi-host captures keep per-host timelines separable.
        host_cols["deviceId"] = [device_id_base] * n_host
    frames = {
        "tputrace": make_frame(op_cols) if n_ops else empty_frame(),
        "tpumodules": make_frame(module_rows) if module_rows else empty_frame(),
        "hosttrace": make_frame(host_cols) if n_host else empty_frame(),
        "tpusteps": make_frame(step_rows) if step_rows else empty_frame(),
        "customtrace": make_frame(custom_rows) if custom_rows
        else empty_frame(),
    }
    frames["_meta"] = meta  # type: ignore[assignment]
    return frames


def _windowed_integral(starts: np.ndarray, ends: np.ndarray,
                       rates: np.ndarray, t0: float, n_win: int,
                       window_s: float) -> np.ndarray:
    """Exact per-window integral of sum_i rates[i]*[starts_i <= t < ends_i]
    over a uniform window grid, in O(len(starts) + n_win).

    Partial overlaps at an interval's first and last window are booked
    directly; fully-covered interior windows come from a rate difference
    array whose prefix sum is the total active rate per window.
    """
    acc = np.zeros(n_win)
    delta = np.zeros(n_win + 1)
    a = (starts - t0) / window_s
    b = (ends - t0) / window_s
    ia = np.clip(np.floor(a).astype(np.int64), 0, n_win - 1)
    ib = np.clip(np.floor(b).astype(np.int64), 0, n_win - 1)
    same = ia == ib
    if same.any():
        np.add.at(acc, ia[same], rates[same] * (ends[same] - starts[same]))
    d = ~same
    if d.any():
        np.add.at(acc, ia[d], rates[d] * ((ia[d] + 1) - a[d]) * window_s)
        np.add.at(acc, ib[d], rates[d] * (b[d] - ib[d]) * window_s)
        np.add.at(delta, ia[d] + 1, rates[d])
        np.add.at(delta, ib[d], -rates[d])
    return acc + np.cumsum(delta[:-1]) * window_s


def tpu_utilization(
    tputrace: pd.DataFrame,
    window_s: float = 0.1,
    device_meta: Optional[Dict[str, Dict[str, float]]] = None,
) -> pd.DataFrame:
    """Windowed device-utilization series derived from the op timeline — the
    nvidia-smi analogue (reference nvsmi collector, sofa_record.py:300-310).

    Per device and window emits:
      tc_util   — % of window covered by TensorCore ops (interval union)
      hbm_gbps  — bytes_accessed rate, GB/s
      mxu_util  — % of plane-reported peak FLOP/s
    """
    if tputrace.empty:
        return empty_frame()
    frames = []
    for device_id, df in tputrace.groupby("deviceId"):
        sync = df[df["category"] == 0]
        if sync.empty:
            continue
        starts = sync["timestamp"].to_numpy(dtype=float)
        ends = starts + sync["duration"].to_numpy(dtype=float)
        t0 = float(starts.min())
        t1 = float(ends.max())
        edges = np.arange(t0, t1 + window_s, window_s)
        n_win = len(edges) - 1
        if n_win <= 0:
            continue
        # Merge intervals (ops can nest/overlap across fusions).
        from sofa_tpu.trace import merged_intervals

        marr = merged_intervals(starts, ends)
        durs = np.maximum(ends - starts, 1e-12)
        # Per-window integrals in O(ops + windows) — the old per-window
        # re-clip of every interval was O(windows * ops) and dominated at
        # pod scale with small window_s (VERDICT r2 weak #7).
        busy = _windowed_integral(
            marr[:, 0], marr[:, 1], np.ones(len(marr)), t0, n_win, window_s)
        wflops = _windowed_integral(
            starts, ends, sync["flops"].to_numpy(dtype=float) / durs,
            t0, n_win, window_s)
        wbytes = _windowed_integral(
            starts, ends, sync["bytes_accessed"].to_numpy(dtype=float) / durs,
            t0, n_win, window_s)
        peaks = (device_meta or {}).get(str(device_id), {})
        peak_flops = peaks.get("peak_teraflops_per_second", 0.0) * 1e12
        ts = edges[1:n_win + 1]
        series = [("tc_util", 100.0 * busy / window_s, np.zeros(n_win)),
                  ("hbm_gbps", wbytes / window_s / 1e9, wbytes / window_s)]
        if peak_flops > 0:
            series.append(
                ("mxu_util", 100.0 * (wflops / window_s) / peak_flops,
                 np.zeros(n_win)))
        frames.append(make_frame({
            "timestamp": np.concatenate([ts] * len(series)),
            "event": np.concatenate([v for _, v, _ in series]),
            "bandwidth": np.concatenate([b for _, _, b in series]),
            "duration": np.full(n_win * len(series), window_s),
            "deviceId": np.full(n_win * len(series), int(device_id)),
            "name": np.repeat([n for n, _, _ in series], n_win),
            "device_kind": ["tpu"] * (n_win * len(series)),
        }))
    if not frames:
        return empty_frame()
    out = pd.concat(frames, ignore_index=True)
    # stable sort keeps the tc/hbm/mxu emission order within a timestamp
    return out.sort_values(["deviceId", "timestamp"],
                           kind="stable").reset_index(drop=True)


def _ingest_one(args) -> Tuple[Dict[str, pd.DataFrame], Dict]:
    """(path, host_index, time_base) -> (frames, meta); module-level so a
    process pool can pickle it."""
    path, host_index, time_base = args
    host = os.path.basename(path).replace(".xplane.pb", "")
    xspace = load_xspace(path)
    frames = xspace_to_frames(
        xspace, time_base, host=host, device_id_base=host_index * 256,
        pb_path=path,
    )
    meta = frames.pop("_meta", {})
    return frames, meta


def ingest_xprof_dir(
    xprof_dir: str, time_base: float, window_s: float = 0.1,
    jobs: "int | None" = None,
) -> Dict[str, pd.DataFrame]:
    """Ingest every XSpace under an xprof dir, concatenating multi-host files.

    Multi-host logdirs (one .xplane.pb per host on a pod) parse in a
    process pool — proto decode + frame building is CPU-bound Python, so
    this is the mp.Pool.map the reference used for its per-GPU nvvp files
    (sofa_preprocess.py:1343-1456).  Single files stay in-process.
    ``jobs`` caps the pool width (None = the shared auto policy,
    sofa_tpu/pool.py; preprocess passes its --jobs setting through).
    """
    from sofa_tpu.pool import pool_size, resolve_jobs

    max_jobs = resolve_jobs(jobs or 0)
    paths = find_xplane_files(xprof_dir)
    if not paths:
        return {}
    all_frames: Dict[str, List[pd.DataFrame]] = {
        "tputrace": [], "tpumodules": [], "hosttrace": [], "tpusteps": [],
        "customtrace": [],
    }
    meta: Dict[str, Dict[str, float]] = {}
    jobs = [(p, i, time_base) for i, p in enumerate(paths)]
    results: List = []
    if jobs:
        # Build the native scanner ONCE in the parent: pool workers racing
        # g++ on the same output binary would corrupt it.
        from sofa_tpu.ingest import native_scan

        native_scan.ensure_scanner()
    # Pool policy: worker spawn costs seconds (forkserver + pandas import),
    # so the pool must EARN it.  With the native scanner a small host file
    # parses in well under a second — only many files or real pod-scale
    # bytes amortize the spawn.  SOFA_INGEST_POOL=always|never overrides
    # (tests force `always` to keep the pool path covered).
    policy = os.environ.get("SOFA_INGEST_POOL", "auto")
    total_bytes = 0
    for p, _, _ in jobs:
        try:
            total_bytes += os.path.getsize(p)
        except OSError:
            pass
    # `always` overrides even a --jobs 1 / single-CPU resolution (tests use
    # it to keep the pool path covered); auto requires real parallelism.
    use_pool = len(jobs) > 1 and policy != "never" and (
        policy == "always" or (max_jobs > 1 and (
            len(jobs) >= 12 or total_bytes >= 48 * 2 ** 20)))
    serial_from = None if use_pool else 0
    if use_pool:
        try:
            import multiprocessing as mp
            from concurrent.futures import BrokenExecutor, ProcessPoolExecutor

            # Never fork: the caller may hold sampler/collector threads and
            # a forked child of a threaded process can deadlock.
            methods = mp.get_all_start_methods()
            ctx = mp.get_context(
                "forkserver" if "forkserver" in methods else "spawn")
            print_info(f"xplane: ingesting {len(jobs)} host files in "
                       f"parallel")
            with ProcessPoolExecutor(max_workers=pool_size(max_jobs,
                                                           len(jobs)),
                                     mp_context=ctx) as ex:
                futures = [ex.submit(_ingest_one, job) for job in jobs]
                for job, fut in zip(jobs, futures):
                    try:
                        results.append(fut.result())
                        print_info(f"xplane: ingested {job[0]}")
                    except BrokenExecutor:
                        raise  # handled below — NOT a per-file decode error
                    except Exception as e:  # noqa: BLE001 — one corrupt trace must not kill the rest
                        print_warning(f"xplane: cannot parse {job[0]}: {e}")
                        results.append(None)
        except BrokenExecutor as e:
            # A crashed/OOM-killed worker poisons every pending future (and
            # can surface from submit itself) — an environment failure, not
            # a decode failure.  Keep completed results, finish the rest
            # serially; "cannot parse" stays reserved for files that
            # actually failed to decode.
            print_warning(
                f"xplane: process pool broke ({e!r}); ingesting remaining "
                f"{len(jobs) - len(results)} files serially")
            serial_from = len(results)
        except (ImportError, OSError, ValueError) as e:
            # Pool creation itself failed (sandboxed /dev/shm, no spawn).
            print_warning(f"xplane: parallel ingest unavailable ({e}); "
                          "falling back to serial")
            results = []
            serial_from = 0
    if serial_from is not None:
        for job in jobs[serial_from:]:
            print_info(f"xplane: ingesting {job[0]}")
            try:
                results.append(_ingest_one(job))
            except Exception as e:  # noqa: BLE001 — a corrupt trace must not kill the report
                print_warning(f"xplane: cannot parse {job[0]}: {e}")
                results.append(None)
    for res in results:
        if res is None:
            continue
        frames, m = res
        meta.update(m)
        for key, df in frames.items():
            if not df.empty:
                all_frames[key].append(df)
    out: Dict[str, pd.DataFrame] = {}
    for key, dfs in all_frames.items():
        out[key] = (
            pd.concat(dfs, ignore_index=True).sort_values("timestamp").reset_index(drop=True)
            if dfs
            else empty_frame()
        )
    out["tpuutil"] = tpu_utilization(out["tputrace"], window_s, meta)
    out["_meta"] = meta  # type: ignore[assignment]
    return out
