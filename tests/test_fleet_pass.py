"""The incremental fleet-pass engine (ISSUE 20): the ``@fleet_pass``
registry domain over the archive's ``_index/`` column families
(sofa_tpu/analysis/fleet.py).

Covers contract validation (unknown families/columns, cross-domain
``after`` edges, duplicate names), Kahn-wave scheduling, the
memo/delta/full mode ladder (warm byte-identical to cold, ``--jobs``
width invisible, memoized no-op with untouched mtimes), the
full-recompute fallbacks (contract fingerprint edit, ``catalog.gen``
bump), the ``fold_chunks``/``parts_in_order`` state shape, kill-between
-the-two-writes convergence (``SOFA_FLEET_EXIT_AFTER``), the
``/v1/<tenant>/fleet`` route (auth, ``idx-<sha>`` ETag, 404 before the
first analyze), the `sofa fleet` verb's exit ladder, fsck detect/repair
of a rotted ``_fleet/``, the tier's post-drain refresh gate, the
manifest_check schema validators, and the vectorized index builders'
identity against per-row reference folds.  The heavyweight SIGKILL e2e
and the 50k-run speedup proof live in tools/chaos_matrix.py and
tools/fleet_analyze_bench.py.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import urllib.error
import urllib.request

import pytest

from sofa_tpu.analysis import fleet as afleet
from sofa_tpu.analysis import registry as areg
from sofa_tpu.archive import catalog
from sofa_tpu.archive import index as aindex
from sofa_tpu.archive.service import service_url, sofa_serve
from sofa_tpu.archive.store import ArchiveStore, archive_fsck
from sofa_tpu.config import SofaConfig
from sofa_tpu.durability import atomic_write

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "test-fleet-pass-token"

pytestmark = pytest.mark.skipif(not aindex.available(),
                                reason="pyarrow unavailable")


def _mkarchive(tmp_path, n=10, hosts=3, name="arch"):
    """A synthetic archive shaped like a real ingest's output."""
    root = str(tmp_path / name)
    store = ArchiveStore(root, create=True)
    for i in range(n):
        run = f"{i:064x}"
        doc = {"schema": "sofa_tpu/archive_run", "version": 1,
               "run": run, "t": 1000.0 + i, "hostname": f"h{i % hosts}",
               "label": "nightly" if i % 2 else "release",
               "logdir": f"/fleet/h{i % hosts}/job{i}",
               "files": {"report.js": {"sha256": "0" * 64, "bytes": 10,
                                       "kind": "derived"}},
               "features": {"elapsed_time": 10.0 + i,
                            "step_time_mean": 0.05,
                            "tpu0_sol_distance": 2.0 + i * 0.25,
                            "tpu1_sol_distance": 1.5 + (n - i) * 0.125}}
        with atomic_write(store.run_doc_path(run)) as f:
            json.dump(doc, f, sort_keys=True)
        catalog.append_event(
            root, "ingest", run=run, logdir=doc["logdir"], files=1,
            new_objects=1, bytes_added=128,
            **({"label": doc["label"]} if doc["label"] else {}))
    return root, store


def _append_run(root, store, i, features=None):
    run = f"{i:064x}"
    doc = {"run": run, "t": 1000.0 + i, "hostname": f"h{i % 3}",
           "logdir": f"/fleet/h{i % 3}/job{i}", "files": {},
           "features": features if features is not None
           else {"elapsed_time": 10.0 + i,
                 "step_time_mean": 0.05,
                 "tpu0_sol_distance": 2.0 + i * 0.25}}
    with atomic_write(store.run_doc_path(run)) as f:
        json.dump(doc, f, sort_keys=True)
    catalog.append_event(root, "ingest", run=run, logdir=doc["logdir"],
                         files=0, new_objects=0, bytes_added=0)
    return run


def _report_bytes(root):
    with open(afleet.report_path(root), "rb") as f:
        return f.read()


def _modes(report):
    return {n: s["mode"]
            for n, s in (report["_stats"]["passes"] or {}).items()}


# ---------------------------------------------------------------------------
# Registration contracts.
# ---------------------------------------------------------------------------

def _noop_pass(state, tables, ctx, features):
    return {"state": None, "report": {}}


def test_register_validates_contract_literals():
    with afleet.scoped():
        with pytest.raises(afleet.FleetError, match="non-empty string"):
            afleet.register_fleet_pass(_noop_pass, name="")
        with pytest.raises(afleet.FleetError, match="not an index family"):
            afleet.register_fleet_pass(_noop_pass, name="bad_family",
                                       reads_frames=("tputrace",))
        with pytest.raises(afleet.FleetError,
                           match="not a declared-family column"):
            afleet.register_fleet_pass(
                _noop_pass, name="bad_col",
                reads_frames=("features",),
                reads_columns=("features.bogus",))
        with pytest.raises(afleet.FleetError,
                           match="not a declared-family column"):
            # right column, family absent from reads_frames
            afleet.register_fleet_pass(
                _noop_pass, name="bad_qual",
                reads_frames=("features",),
                reads_columns=("catalog.verb",))
        afleet.register_fleet_pass(_noop_pass, name="dup",
                                   reads_frames=("features",))
        with pytest.raises(afleet.FleetError, match="already registered"):
            afleet.register_fleet_pass(_noop_pass, name="dup")


def test_register_rejects_cross_domain_after():
    with areg.scoped(), afleet.scoped():
        areg.register_pass(lambda frames, cfg, features: None,
                           name="per_run_pass")
        with pytest.raises(afleet.FleetError, match="crosses into"):
            afleet.register_fleet_pass(_noop_pass, name="crosser",
                                       after=("per_run_pass",))
        # fleet->fleet edges are fine
        afleet.register_fleet_pass(_noop_pass, name="base_pass",
                                   reads_frames=("features",))
        afleet.register_fleet_pass(_noop_pass, name="downstream",
                                   after=("base_pass",))


def test_fingerprint_is_pure_function_of_declaration():
    with afleet.scoped():
        a = afleet.register_fleet_pass(
            _noop_pass, name="fp", order=5, reads_frames=("features",),
            reads_columns=("features.value",))
    with afleet.scoped():
        b = afleet.register_fleet_pass(
            _noop_pass, name="fp", order=5, reads_frames=("features",),
            reads_columns=("features.value",))
    with afleet.scoped():
        c = afleet.register_fleet_pass(
            _noop_pass, name="fp", order=5, reads_frames=("features",),
            reads_columns=("features.value", "features.name"))
    assert afleet.fingerprint(a) == afleet.fingerprint(b)
    assert afleet.fingerprint(a) != afleet.fingerprint(c)


# ---------------------------------------------------------------------------
# The mode ladder: cold -> delta -> memo no-op, all byte-identical.
# ---------------------------------------------------------------------------

def test_cold_warm_noop_ladder_byte_identical(tmp_path):
    root, store = _mkarchive(tmp_path, n=8)
    cold = afleet.analyze(root)
    assert cold["_stats"]["noop"] is False
    assert set(_modes(cold).values()) == {"full"}
    assert cold["order"] == [s.name for s in afleet.registered()]
    # schedule covers exactly the registered passes, wave edges honored
    assert sorted(n for w in cold["schedule"] for n in w) \
        == sorted(cold["order"])

    # warm: one appended run -> every pass folds only the delta window
    _append_run(root, store, 100)
    warm = afleet.analyze(root)
    assert set(_modes(warm).values()) == {"delta"}
    warm_bytes = _report_bytes(root)

    # memoized no-op: same commit, same contracts -> zero writes
    mtime = os.path.getmtime(afleet.report_path(root))
    noop = afleet.analyze(root)
    assert noop["_stats"]["noop"] is True
    assert set(_modes(noop).values()) == {"memo"}
    assert os.path.getmtime(afleet.report_path(root)) == mtime

    # the warm fold is byte-identical to a cold recompute, at any width
    afleet.drop(root)
    afleet.analyze(root, jobs=1)
    assert _report_bytes(root) == warm_bytes
    afleet.drop(root)
    afleet.analyze(root, jobs=4)
    assert _report_bytes(root) == warm_bytes


def test_report_and_state_pass_manifest_check(tmp_path):
    root, _store = _mkarchive(tmp_path, n=6)
    afleet.analyze(root)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import manifest_check
    finally:
        sys.path.pop(0)
    report = json.load(open(afleet.report_path(root)))
    state = json.load(open(afleet.state_path(root)))
    assert manifest_check.validate_fleet_report(
        report, require_healthy=True) == []
    assert manifest_check.validate_fleet_state(state) == []
    # and a mangled report is caught
    bad = dict(report, version=99, commit_sha="")
    assert manifest_check.validate_fleet_report(bad) != []


def test_fingerprint_change_forces_full_recompute(tmp_path):
    root, store = _mkarchive(tmp_path, n=6)

    def counting(state, tables, ctx, features):
        return {"state": {"n": (state or {}).get("n", 0) + 1},
                "report": {"mode_seen": ctx.mode}}

    with afleet.scoped():
        afleet.register_fleet_pass(counting, name="counting",
                                   reads_frames=("features",),
                                   reads_columns=("features.value",))
        afleet.analyze(root)
        _append_run(root, store, 50)
        warm = afleet.analyze(root)
        assert _modes(warm)["counting"] == "delta"
    # same pass, edited contract -> its memoized state is unusable
    with afleet.scoped():
        afleet.register_fleet_pass(counting, name="counting",
                                   reads_frames=("features",),
                                   reads_columns=("features.value",
                                                  "features.name"))
        _append_run(root, store, 51)
        again = afleet.analyze(root)
        modes = _modes(again)
        assert modes["counting"] == "full"
        # the untouched builtins still ride the delta path
        assert all(m == "delta" for n, m in modes.items()
                   if n != "counting")


def test_catalog_gen_bump_forces_full_recompute(tmp_path):
    root, store = _mkarchive(tmp_path, n=6)
    afleet.analyze(root)
    _append_run(root, store, 60)
    assert set(_modes(afleet.analyze(root)).values()) == {"delta"}
    # a catalog rewrite bumps catalog.gen: history changed, no delta
    # window is sound
    catalog.rewrite(root, catalog.read_catalog(root))
    full = afleet.analyze(root)
    assert set(_modes(full).values()) == {"full"}


def test_schedule_orders_after_edges_and_feature_reads(tmp_path):
    root, _store = _mkarchive(tmp_path, n=4)
    seen = []

    def producer(state, tables, ctx, features):
        features.add("fleet_custom_signal", 41.0)
        seen.append("producer")
        return {"state": None, "report": {}}

    def consumer(state, tables, ctx, features):
        seen.append("consumer")
        v = features.get("fleet_custom_signal")
        return {"state": None, "report": {"got": v}}

    with afleet.scoped():
        afleet.register_fleet_pass(
            producer, name="producer", reads_frames=("features",),
            provides_features=("fleet_custom_signal",))
        afleet.register_fleet_pass(
            consumer, name="consumer",
            reads_features=("fleet_custom_signal",), after=("producer",))
        report = afleet.analyze(root)
    waves = {n: i for i, wave in enumerate(report["schedule"])
             for n in wave}
    assert waves["producer"] < waves["consumer"]
    assert seen.index("producer") < seen.index("consumer")
    assert report["passes"]["consumer"]["report"]["got"] == 41.0
    assert report["features"]["fleet_custom_signal"] == 41.0


def test_failing_pass_is_isolated_and_report_commits(tmp_path):
    root, _store = _mkarchive(tmp_path, n=4)

    def boom(state, tables, ctx, features):
        raise RuntimeError("synthetic fleet fault")

    with afleet.scoped():
        afleet.register_fleet_pass(boom, name="boom",
                                   reads_frames=("runs",))
        report = afleet.analyze(root)
    entry = report["passes"]["boom"]
    assert entry["status"] == "failed"
    assert "synthetic fleet fault" in entry["error"]
    # the other passes ran and the artifact still committed
    assert all(report["passes"][n]["status"] == "ok"
               for n in report["order"] if n != "boom")
    assert afleet.load_report(root) is not None


# ---------------------------------------------------------------------------
# The fold substrate.
# ---------------------------------------------------------------------------

def test_fold_chunks_partials_and_order():
    import pyarrow as pa

    tbl = pa.table({"v": list(range(10))})
    parts = {}
    afleet.fold_chunks(parts, tbl, 0, 4, lambda t: t.num_rows)
    assert parts == {"0": 4, "1": 4, "2": 2}
    # a delta fold drops partials at/past base, keeps the prefix
    parts["0"] = "kept"
    suffix = tbl.slice(4)  # rows of chunks 1..2
    afleet.fold_chunks(parts, suffix, 1, 4, lambda t: t.num_rows)
    assert parts == {"0": "kept", "1": 4, "2": 2}
    # chunk-ordinal ordering is numeric, not lexicographic
    many = {str(i): i for i in (0, 2, 10, 1)}
    assert afleet.parts_in_order(many) == [0, 1, 2, 10]


def test_runs_meta_point_lookups_memoized(tmp_path):
    root, _store = _mkarchive(tmp_path, n=6)
    commit = aindex.refresh(root)
    ctx = afleet.FleetContext(root=root, commit=commit, mode="full",
                              chunk_rows=aindex.INDEX_CHUNK_ROWS)
    run0, run_missing = f"{0:064x}", "f" * 64
    meta = ctx.runs_meta({run0, run_missing})
    assert set(meta) == {run0}
    assert meta[run0]["host"] == "h0"
    assert meta[run0]["label"] == "release"
    # second call is served from the per-context cache (absent ids too)
    assert run_missing in ctx._meta_absent
    again = ctx.runs_meta({run0, run_missing})
    assert again == meta


# ---------------------------------------------------------------------------
# Crash-window convergence (the in-tree cousin of the chaos cell).
# ---------------------------------------------------------------------------

def test_kill_between_report_and_memo_converges(tmp_path):
    root, store = _mkarchive(tmp_path, n=6)
    afleet.analyze(root)
    want = _report_bytes(root)
    afleet.drop(root)
    env = dict(os.environ, SOFA_FLEET_EXIT_AFTER="1",
               JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("_SOFA_FLEET_TICKS", None)
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent("""
            import sys
            from sofa_tpu.analysis import fleet
            fleet.analyze(sys.argv[1])
            sys.exit(3)  # unreachable: the chaos knob exits first
        """), root], env=env, timeout=120, capture_output=True)
    assert proc.returncode == 86, proc.stderr.decode()
    # torn state: report committed, memo missing — healthy-pending, not
    # damage
    assert os.path.exists(afleet.report_path(root))
    assert not os.path.exists(afleet.state_path(root))
    assert afleet.verify(root) == []
    # the report that DID land is already the right bytes, and the
    # re-run converges the memo without changing them
    assert _report_bytes(root) == want
    afleet.analyze(root)
    assert _report_bytes(root) == want
    assert afleet._load_state(root) is not None


def test_fsck_detects_and_repairs_rotted_fleet_tier(tmp_path):
    root, _store = _mkarchive(tmp_path, n=4)
    afleet.analyze(root)
    assert archive_fsck(root)["fleet"] == []
    with open(afleet.report_path(root), "w") as f:
        f.write("{not json")
    assert afleet.verify(root) == ["_fleet/fleet_report.json"]
    assert archive_fsck(root)["fleet"] == ["_fleet/fleet_report.json"]
    # repair drops the derived tier; the next analyze rebuilds it
    assert archive_fsck(root, repair=True)["fleet"] == []
    assert not os.path.isdir(afleet.fleet_dir(root))
    afleet.analyze(root)
    assert archive_fsck(root)["fleet"] == []


def test_refresh_after_ingest_gate_and_degrade(tmp_path, monkeypatch):
    root, _store = _mkarchive(tmp_path, n=4)
    aindex.refresh(root)
    monkeypatch.setenv("SOFA_FLEET_REFRESH", "0")
    assert afleet.refresh_after_ingest(root) is None
    assert not os.path.isdir(afleet.fleet_dir(root))
    monkeypatch.delenv("SOFA_FLEET_REFRESH")
    report = afleet.refresh_after_ingest(root)
    assert report is not None and afleet.load_report(root) is not None
    # derived state must never fail the drain: a broken substrate
    # degrades to None instead of raising
    assert afleet.refresh_after_ingest(str(tmp_path / "nowhere")) is None


# ---------------------------------------------------------------------------
# The `sofa fleet` verb.
# ---------------------------------------------------------------------------

def test_sofa_fleet_verb_exit_ladder(tmp_path, capsys):
    cfg = SofaConfig(logdir=str(tmp_path / "unused"))
    assert afleet.sofa_fleet(cfg, "analyze", "") == 2
    assert afleet.sofa_fleet(cfg, "bogus", "x") == 2
    assert afleet.sofa_fleet(cfg, "analyze",
                             str(tmp_path / "missing")) == 2
    root, _store = _mkarchive(tmp_path, n=4)
    assert afleet.sofa_fleet(cfg, "analyze", root) == 0
    out = capsys.readouterr().out
    assert "SOFA fleet analyze" in out
    for name in [s.name for s in afleet.registered()]:
        assert name in out

    def boom(state, tables, ctx, features):
        raise RuntimeError("verb fault")

    with afleet.scoped():
        afleet.register_fleet_pass(boom, name="boom",
                                   reads_frames=("runs",))
        afleet.drop(root)
        assert afleet.sofa_fleet(cfg, "analyze", root) == 1


# ---------------------------------------------------------------------------
# GET /v1/<tenant>/fleet.
# ---------------------------------------------------------------------------

@pytest.fixture
def service(tmp_path):
    cfg = SofaConfig(logdir=str(tmp_path / "unused"),
                     serve_token=TOKEN, serve_port=0)
    httpd = sofa_serve(cfg, root=str(tmp_path / "fleet"),
                       serve_forever=False)
    assert httpd is not None
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_v1_fleet_auth_etag_304(service, tmp_path):
    root = service.tenant_root("default")
    store = ArchiveStore(root, create=True)
    for i in range(5):
        _append_run(root, store, i)
    aindex.refresh(root)
    base = service_url(service)
    auth = {"Authorization": f"Bearer {TOKEN}"}
    # auth first, artifact second: no token -> 401
    code, _h, _b = _get(f"{base}/v1/default/fleet")
    assert code == 401
    # no committed report yet -> an explicit 404, not an empty 200
    code, _h, body = _get(f"{base}/v1/default/fleet", auth)
    assert code == 404
    assert json.loads(body)["error"] == "no_fleet_report"
    report = afleet.analyze(root)
    code, hdrs, body = _get(f"{base}/v1/default/fleet", auth)
    assert code == 200
    etag = hdrs.get("ETag")
    assert etag == f'"idx-{report["commit_sha"]}"'
    doc = json.loads(body)
    assert doc["schema"] == afleet.FLEET_REPORT_SCHEMA
    assert doc["tenant"] == "default"
    assert doc["commit_sha"] == report["commit_sha"]
    assert doc["order"] == report["order"]
    # idle poll: the ETag round-trips as a 304
    code, _h, _b = _get(f"{base}/v1/default/fleet",
                        {**auth, "If-None-Match": etag})
    assert code == 304
    # a new ingest moves the commit sha -> the poll turns 200 again
    _append_run(root, store, 50)
    afleet.analyze(root)
    code, hdrs, _b = _get(f"{base}/v1/default/fleet",
                          {**auth, "If-None-Match": etag})
    assert code == 200 and hdrs.get("ETag") != etag


# ---------------------------------------------------------------------------
# sofa-lint: the fleet contract domain (SL010/SL012).
# ---------------------------------------------------------------------------

def _fleet_lint(tmp_path, files):
    from sofa_tpu.lint.core import ProjectContext, lint_paths
    from sofa_tpu.lint.rules import default_rules

    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.write_text(textwrap.dedent(body))
        paths.append(str(p))
    # detect() joins the @fleet_pass declarations to their files and
    # falls back to the package's archive/index.py for the pinned
    # family schemas
    project = ProjectContext.detect(paths, base=str(tmp_path))
    assert project.index_columns
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in ("SL010", "SL011",
                                           "SL012", "SL013")]


def test_lint_flags_undeclared_fleet_reads(tmp_path):
    fs = _fleet_lint(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.fleet import fleet_pass

        @fleet_pass(name="leaky", reads_frames=("features",),
                    reads_columns=("features.value",))
        def leaky(state, tables, ctx, features):
            tbl = tables["catalog"]              # undeclared family
            col = tables["features"]["name"]     # undeclared column
            return {"state": None, "report": {}}
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL010"]
    assert any("'catalog'" in m for m in msgs), msgs
    assert any("'name'" in m for m in msgs), msgs


def test_lint_flags_phantom_fleet_declaration(tmp_path):
    fs = _fleet_lint(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.fleet import fleet_pass

        @fleet_pass(name="phantom", reads_frames=("notafamily",),
                    reads_columns=("features.bogus",))
        def phantom(state, tables, ctx, features):
            return {"state": None, "report": {}}
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL010"]
    assert any("'notafamily'" in m for m in msgs), msgs
    assert any("'features.bogus'" in m for m in msgs), msgs


def test_lint_flags_cross_domain_after_edge(tmp_path):
    fs = _fleet_lint(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.fleet import fleet_pass
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="per_run")
        def per_run(frames, cfg, features):
            pass

        @fleet_pass(name="crosser", reads_frames=("runs",),
                    after=("per_run",))
        def crosser(state, tables, ctx, features):
            return {"state": None, "report": {}}
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL012"]
    assert any("cross-domain" in m for m in msgs), msgs


def test_lint_clean_fleet_pass(tmp_path):
    fs = _fleet_lint(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.fleet import fleet_pass

        @fleet_pass(name="tidy", reads_frames=("features",),
                    reads_columns=("features.name", "features.value"),
                    provides_features=("fleet_tidy_total",))
        def tidy(state, tables, ctx, features):
            tbl = tables["features"]
            vals = tbl["value"]
            features.add("fleet_tidy_total", 1.0)
            return {"state": None, "report": {}}
    '''})
    assert fs == []


# ---------------------------------------------------------------------------
# The vectorized index builders stay identical to per-row reference
# folds (the perf-rewrite safety net).
# ---------------------------------------------------------------------------

def test_runs_rows_vectorized_matches_reference_fold():
    import random

    import pandas as pd

    random.seed(7)

    def ref_runs_rows(ev_all, ft_all):
        ing = ev_all[(ev_all["verb"] == "ingest") & (ev_all["run"] != "")]
        latest = {}
        for rec in ing.to_dict("records"):
            latest[rec["run"]] = rec
        ordered = sorted(latest.values(),
                         key=lambda r: (r.get("timestamp") or 0))
        counts = {}
        if len(ft_all):
            dd = ft_all[~ft_all.duplicated(["run", "name"], keep="last")]
            counts = dd["run"].value_counts().to_dict()
        rows = [{"run": r["run"], "label": r["label"], "host": r["host"],
                 "logdir": r["logdir"], "timestamp": r["timestamp"],
                 "bytes": r["bytes"], "files": r["files"],
                 "n_features": float(counts.get(r["run"], 0))}
                for r in ordered]
        return aindex._conform_family(
            pd.DataFrame(rows, columns=aindex.RUNS_COLUMNS),
            aindex.RUNS_COLUMNS)

    # re-ingested runs, timestamp ties, non-ingest verbs, empty-run rows
    ev_rows, t = [], 1000.0
    runs = [f"r{i:03d}" for i in range(40)]
    for k in range(300):
        r = random.choice(runs)
        verb = random.choice(["ingest", "ingest", "ingest", "gc", "serve"])
        t += random.choice([0.0, 0.0, 1.0])
        ev_rows.append({
            "run": r if verb == "ingest"
            else (r if random.random() < .5 else ""),
            "verb": verb, "label": random.choice(["", "nightly", "rel"]),
            "host": f"h{k % 7}", "logdir": f"/ld/{r}", "timestamp": t,
            "bytes": float(k), "files": float(k % 9)})
    ev_all = aindex._conform_family(
        pd.DataFrame(ev_rows, columns=aindex.CATALOG_COLUMNS),
        aindex.CATALOG_COLUMNS)
    ft_rows = []
    for r in runs[:30]:
        for j in range(random.randrange(0, 6)):
            ft_rows.append({"run": r, "name": f"f{j}", "value": float(j),
                            "timestamp": 1.0})
    ft_rows += ft_rows[:10]  # duplicate (run, name) pairs: keep-last
    ft_all = aindex._conform_family(
        pd.DataFrame(ft_rows, columns=aindex.FEATURE_COLUMNS),
        aindex.FEATURE_COLUMNS)

    for ev, ft in [(ev_all, ft_all),
                   (ev_all.iloc[0:0], ft_all),
                   (ev_all, ft_all.iloc[0:0])]:
        got = aindex._runs_rows(ev, ft).reset_index(drop=True)
        want = ref_runs_rows(ev, ft).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, want)
