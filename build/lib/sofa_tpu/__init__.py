"""sofa_tpu — a TPU-native, cross-layer performance profiler.

A ground-up rebuild of the capabilities of cyliustack/sofa (see SURVEY.md) for
the JAX/XLA/TPU stack: wrap any command, collect host CPU / network / disk
activity plus TPU XPlane traces (HLO ops, collectives, infeed/outfeed), align
every clock domain to one time base, normalize everything into one unified
trace schema, analyze it into a performance feature vector with optimization
hints, and serve an interactive browser timeline.

Pipeline verbs (mirroring the reference CLI, /root/reference/bin/sofa:328-376):

    sofa record "cmd"   -> sofalog/ raw collector outputs
    sofa preprocess     -> sofalog/*.csv in the unified schema + report.js
    sofa analyze        -> performance features, hints, reports
    sofa viz            -> http server on sofalog/ (board GUI)
    sofa stat  = record + preprocess + analyze
    sofa report= [preprocess] + analyze [+ viz]
    sofa diff  = preprocess x2 + swarm diff
    sofa clean = remove derived files

Public programmatic API:

    from sofa_tpu import SofaConfig, record, preprocess, analyze, viz
    from sofa_tpu.api import profile        # in-process context manager
"""

__version__ = "0.1.0"

from sofa_tpu.config import SofaConfig, Filter  # noqa: F401


def record(command, cfg):
    """Run ``command`` under the collector swarm. Lazy import."""
    from sofa_tpu.record import sofa_record

    return sofa_record(command, cfg)


def preprocess(cfg):
    from sofa_tpu.preprocess import sofa_preprocess

    return sofa_preprocess(cfg)


def analyze(cfg):
    from sofa_tpu.analyze import sofa_analyze

    return sofa_analyze(cfg)


def viz(cfg):
    from sofa_tpu.viz import sofa_viz

    return sofa_viz(cfg)
