"""``sofa artifacts`` — the artifact-lifecycle inventory.

Renders the flow graph sofa-lint's SL014–SL018 rules enforce
(sofa_tpu/lint/artifact_rules.py): every artifact the tree can produce,
who writes it, who reads it, and how each lifecycle registry accounts
for it — `sofa clean` (DERIVED_FILES/DIRS/SUFFIXES), the digest ledger
`sofa fsck` verifies (skip-list vs digested), and the manifest_check
validators.  With a logdir the on-disk files are additionally audited
against the graph, so "does anything here leak past clean / blind-side
fsck?" is one command:

    sofa artifacts                  # static inventory of the shipped tree
    sofa artifacts sofalog/         # + audit that logdir's files
    sofa artifacts --json           # machine-readable (bench evidence, CI)

The ``--json`` document is schema-versioned (``sofa_tpu/artifact_inventory``
v1) and validated by ``tools/manifest_check.py`` like every other emitted
schema.  Exit codes: 0 full closure, 2 on closure violations (any
non-baselined SL014–SL018 finding, or an on-disk file no registry
accounts for) — the same "unschedulable graph" posture as `sofa passes`.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

INVENTORY_SCHEMA = "sofa_tpu/artifact_inventory"
INVENTORY_VERSION = 1

#: Dirs never audited inside a logdir: the archive keeps its own ledger
#: (marker-detected below), caches/quarantine/board are registered dirs.
_AUDIT_PRUNE_MARKER = "sofa_archive.json"


def _package_root() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def build_graph():
    """(ProjectContext, base) over the shipped package — the same
    detection path `sofa lint` runs, so the inventory and the rules can
    never disagree about the graph."""
    from sofa_tpu.lint.core import ProjectContext, iter_python_files

    pkg = _package_root()
    base = os.path.dirname(pkg)
    files = iter_python_files([pkg])
    return ProjectContext.detect(files, base=base), base


def _violations(project, base: str) -> List[dict]:
    """Non-baselined SL014–SL018 findings over the shipped tree."""
    from sofa_tpu.lint.artifact_rules import ARTIFACT_RULES
    from sofa_tpu.lint.baseline import (Baseline, fingerprint_findings,
                                        locate_baseline)
    from sofa_tpu.lint.core import iter_python_files, lint_paths

    pkg = _package_root()
    findings = lint_paths(iter_python_files([pkg]),
                          [cls() for cls in ARTIFACT_RULES],
                          project=project, base=base)

    def line_text_for(f):
        path = f.file if os.path.isabs(f.file) else os.path.join(base,
                                                                 f.file)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                lines = fh.read().splitlines()
            return lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        except OSError:
            return ""

    baseline = Baseline.load(locate_baseline(pkg))
    new, _old = baseline.split(fingerprint_findings(findings,
                                                    line_text_for))
    return [f.to_dict() for f in sorted(
        new, key=lambda f: (f.rule_id, f.file, f.line))]


def _artifact_rows(g) -> List[dict]:
    names: Dict[str, dict] = {}

    def row(name: str) -> dict:
        return names.setdefault(name, {
            "name": name, "writers": [], "readers": [], "endpoints": []})

    for n in g.derived_files | g.raw_files | g.pass_artifacts:
        row(n)
    for n in g.frame_names:
        row(f"{n}.csv")
    for w in g.writers:
        row(w.name)["writers"].append(f"{w.relpath}:{w.line}")
    readers = g.reader_names
    for bfile, line, ep in g.board_fetches:
        base = os.path.basename(ep.lstrip("./"))
        if base in names:
            names[base]["endpoints"].append(f"{bfile}:{line}")
    out = []
    for name in sorted(names):
        r = names[name]
        kind = "raw" if name in g.raw_files else "derived"
        # writer fragments carry dir components, so dir coverage applies
        frags: tuple = ()
        for w in g.writers:
            if w.name == name:
                frags = frags + tuple(w.fragments)
        clean = g.clean_coverage(name, frags)
        r.update({
            "kind": kind,
            "clean": clean or "UNREGISTERED",
            "digest": ("raw" if kind == "raw"
                       else g.digest_coverage(name, frags)),
            "read": bool(r["endpoints"]) or name in readers
            or name in g.manifest_check_refs,
            "manifest_check": name in g.manifest_check_refs,
        })
        r["writers"] = sorted(set(r["writers"]))
        r["endpoints"] = sorted(set(r["endpoints"]))
        del r["readers"]
        out.append(r)
    return out


def _audit_logdir(g, logdir: str) -> dict:
    """Every on-disk file accounted for by the registries; the ones that
    are not would leak past `sofa clean` (the violations)."""
    checked, unaccounted = 0, []
    top = os.path.normpath(logdir)
    for root, dirs, files in os.walk(logdir):
        if os.path.normpath(root) != top and \
                os.path.isfile(os.path.join(root, _AUDIT_PRUNE_MARKER)):
            dirs[:] = []  # nested archive: its own fsck owns it
            continue
        rel_root = os.path.relpath(root, logdir)
        parts = [] if rel_root == "." else rel_root.split(os.sep)
        if parts and parts[0] == "xprof":
            # raw XPlane capture dir: kept by clean, digested as raw
            continue
        for name in sorted(files):
            if name.endswith(".tmp"):
                continue  # interrupted writes are fsck's orphan verdict
            checked += 1
            if g.clean_coverage(name, tuple(parts)) is None:
                unaccounted.append(
                    "/".join(parts + [name]) if parts else name)
    return {"path": logdir, "files_checked": checked,
            "unaccounted": sorted(unaccounted)}


def build_inventory(logdir: "str | None" = None) -> dict:
    """The full inventory document (``sofa artifacts --json``)."""
    project, base = build_graph()
    g = project.artifacts
    if g is None or not g.ok:
        raise RuntimeError(
            "artifact graph unavailable: the package's trace.py carries "
            "no artifact registry")
    violations = _violations(project, base)
    doc = {
        "schema": INVENTORY_SCHEMA,
        "version": INVENTORY_VERSION,
        "generated_unix": round(time.time(), 3),
        "artifacts": _artifact_rows(g),
        "violations": violations,
        "counts": {
            "artifacts": 0,
            "writers": len(g.writers),
            "board_endpoints": len(g.board_fetches),
            "violations": len(violations),
        },
    }
    doc["counts"]["artifacts"] = len(doc["artifacts"])
    if logdir and os.path.isdir(logdir):
        doc["logdir"] = _audit_logdir(g, logdir)
    doc["ok"] = not violations and \
        not (doc.get("logdir") or {}).get("unaccounted")
    return doc


def render_inventory(doc: dict) -> List[str]:
    lines: List[str] = []
    lines.append(f"{'artifact':<28} {'kind':<8} {'clean':<16} "
                 f"{'digest':<14} {'read':<5} writers")
    for r in doc["artifacts"]:
        writers = ", ".join(r["writers"][:2]) + \
            (" …" if len(r["writers"]) > 2 else "")
        lines.append(
            f"{r['name']:<28} {r['kind']:<8} {r['clean']:<16} "
            f"{r['digest']:<14} {'yes' if r['read'] else '-':<5} "
            f"{writers}")
    c = doc["counts"]
    lines.append("")
    lines.append(f"{c['artifacts']} artifact(s), {c['writers']} extracted "
                 f"writer site(s), {c['board_endpoints']} board "
                 f"endpoint(s), {c['violations']} closure violation(s)")
    audit = doc.get("logdir")
    if audit:
        lines.append(
            f"logdir {audit['path']}: {audit['files_checked']} file(s) "
            f"audited, {len(audit['unaccounted'])} unaccounted")
        for rel in audit["unaccounted"]:
            lines.append(f"  LEAK {rel} — no registry accounts for it")
    for v in doc["violations"]:
        lines.append(f"  {v['file']}:{v['line']}: {v['rule']} "
                     f"{v['message']}")
    return lines


def sofa_artifacts(logdir: "str | None" = None,
                   as_json: bool = False) -> int:
    """``sofa artifacts [logdir] [--json]`` — exit 0 on full closure, 2
    on violations, like `sofa passes`' unschedulable-graph contract."""
    from sofa_tpu.printing import print_error, print_progress, print_title

    try:
        doc = build_inventory(logdir)
    except Exception as e:  # sofa-lint: disable=SL002 — CLI boundary: the exit contract (rc 2 + stderr line) IS the routing
        print_error(f"artifacts: {type(e).__name__}: {e}")
        return 2
    if as_json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0 if doc["ok"] else 2
    print_title("Artifact lifecycle inventory")
    for line in render_inventory(doc):
        print(line)
    if doc["ok"]:
        print_progress(
            "artifacts: full closure — every artifact is covered by "
            "clean/digest/fsck and every endpoint has a producer")
        return 0
    print_error("artifacts: closure violations — see lines above "
                "(sofa lint shows the same findings)")
    return 2
