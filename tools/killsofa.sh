#!/bin/bash
# Kill every sofa_tpu process and its collector children (reference
# tools/killsofa.sh).  Safe to run repeatedly; collector kills are scoped to
# sofa-spawned invocations (matched on sofa output filenames), so unrelated
# tcpdump/blktrace sessions on the host survive.
pkill -f "sofa record" || true
pkill -f "sofa_tpu.*record" || true
pkill -f "sofa-edr" || true
pkill -f "sofa_tpu.tools.edr" || true
pkill -f "tcpdump.*sofa\.pcap" || true
pkill -f "blktrace.*-o blktrace" || true
echo "sofa_tpu processes killed"
