import json
import os
import sys

import pytest

from sofa_tpu.config import SofaConfig
from sofa_tpu.preprocess import sofa_preprocess
from sofa_tpu.record import sofa_record


def test_preprocess_after_record(logdir):
    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=50)
    sofa_record("sleep 0.3", cfg)
    frames = sofa_preprocess(cfg)
    assert not frames["mpstat"].empty
    for csv in ("mpstat.csv", "netbandwidth.csv", "cputrace.csv", "tputrace.csv"):
        assert os.path.isfile(cfg.path(csv)), csv
    text = open(cfg.path("report.js")).read()
    assert text.startswith("sofa_traces = ")
    doc = json.loads(text[len("sofa_traces = "):].rstrip(";\n"))
    names = {s["name"] for s in doc["series"]}
    assert "mpstat" in names
    assert doc["meta"]["elapsed_time"] >= 0.3


def test_tpu_time_offset_knob(tmp_path):
    """--tpu_time_offset_ms shifts the device/XPlane-side frames (and ONLY
    those): the manual escape hatch for a wrong marker/timebase alignment
    (reference --cpu_time_offset_ms, bin/sofa:111-112, extended to the
    device clock domain)."""
    import shutil

    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "cpu_host.xplane.pb")
    base = {}
    for name, off_ms in (("a", 0.0), ("b", 250.0)):
        d = str(tmp_path / name) + "/"
        prof = os.path.join(d, "xprof", "plugins", "profile", "run1")
        os.makedirs(prof)
        shutil.copy(fixture, os.path.join(prof, "host.xplane.pb"))
        with open(os.path.join(d, "sofa_time.txt"), "w") as f:
            f.write("1700000000.0\n")
        cfg = SofaConfig(logdir=d, tpu_time_offset_ms=off_ms)
        frames = sofa_preprocess(cfg)
        assert not frames["hosttrace"].empty
        base[name] = frames
    shift = (base["b"]["hosttrace"]["timestamp"].to_numpy()
             - base["a"]["hosttrace"]["timestamp"].to_numpy())
    assert shift == __import__("pytest").approx(0.25)


def test_preprocess_missing_logdir():
    cfg = SofaConfig(logdir="/tmp/definitely-not-here-xyz/")
    import pytest

    with pytest.raises(FileNotFoundError):
        sofa_preprocess(cfg)


def test_preprocess_empty_logdir(tmp_path):
    """A logdir with no raw files at all must still produce a report.js."""
    d = str(tmp_path / "empty") + "/"
    os.makedirs(d)
    cfg = SofaConfig(logdir=d)
    frames = sofa_preprocess(cfg)
    assert all(df.empty for df in frames.values())
    assert os.path.isfile(cfg.path("report.js"))


def test_parquet_trace_format(logdir):
    """--trace_format parquet drives preprocess itself: full-fidelity
    parquet + downsampled viz CSV sibling; analyze prefers the parquet;
    a later csv-mode run unlinks the stale parquet."""
    import pandas as pd

    from sofa_tpu.analyze import load_frames
    from sofa_tpu.trace import read_frame

    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=200,
                     trace_format="parquet", viz_downsample_to=5)
    sofa_record("sleep 0.3", cfg)
    sofa_preprocess(cfg)

    assert os.path.isfile(cfg.path("mpstat.parquet"))
    assert os.path.isfile(cfg.path("mpstat.csv"))
    full = read_frame(cfg.path("mpstat"))       # parquet preferred
    viz = pd.read_csv(cfg.path("mpstat.csv"))
    assert len(viz) <= 5 < len(full)
    loaded = load_frames(cfg)["mpstat"]
    assert len(loaded) == len(full)

    # Switching back to csv mode must not leave stale parquet shadowing it.
    cfg.trace_format = "csv"
    sofa_preprocess(cfg)
    assert not os.path.isfile(cfg.path("mpstat.parquet"))
    assert len(load_frames(cfg)["mpstat"]) == len(full)


def test_analyze_frames_passthrough_matches_reread(logdir):
    """`sofa report` hands preprocess's in-memory frames straight to analyze
    (re-reading the just-written CSVs cost ~25% of pod-scale report time);
    the passthrough must produce the same features as a disk round-trip."""
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_record

    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=100)
    sofa_record("sleep 0.3", cfg)
    frames = sofa_preprocess(cfg)
    import pytest

    f_mem = sofa_analyze(cfg, frames=frames)
    f_disk = sofa_analyze(cfg)           # load_frames round-trip
    mem, disk = dict(f_mem._rows), dict(f_disk._rows)
    # elapsed-breakdown features sample wall-clock-dependent windows and
    # are identical here because both calls see the same misc.txt
    assert set(mem) == set(disk)
    for k, v in mem.items():
        assert disk[k] == pytest.approx(v, rel=1e-6), k


# --- broken conversion tool -> `failed` source status (IngestToolError) -----

def _failed_logdir(tmp_path):
    d = str(tmp_path / "flog") + "/"
    os.makedirs(d)
    with open(d + "sofa_time.txt", "w") as f:
        f.write("1700000000.0\n")
    # perf.data exists but no perf.script: ingest must invoke `perf script`,
    # which this container does not have -> IngestToolError.
    with open(d + "perf.data", "wb") as f:
        f.write(b"PERFILE2" + b"\x00" * 64)
    return d


def test_broken_tool_marks_source_failed(tmp_path, monkeypatch):
    from sofa_tpu import telemetry
    from sofa_tpu.preprocess import sofa_preprocess

    monkeypatch.setenv("PATH", "/nonexistent")  # guarantee no perf binary
    d = _failed_logdir(tmp_path)
    cfg = SofaConfig(logdir=d)
    frames = sofa_preprocess(cfg)  # must not raise: per-source degradation
    assert frames["cputrace"].empty
    ent = telemetry.load_manifest(d)["sources"]["cputrace"]
    assert ent["status"] == "failed"
    assert "perf script" in ent["error"]
    # the file is NOT quarantined — the tool broke, not the raw bytes
    assert os.path.isfile(d + "perf.data")
    # failed is re-runnable: nothing poisoned lands in the ingest cache
    assert any("failed" in w and "cputrace" in w
               for w in telemetry.manifest_warnings(
                   telemetry.load_manifest(d)))


def test_failed_source_fails_require_healthy(tmp_path, monkeypatch):
    from sofa_tpu import telemetry
    from sofa_tpu.preprocess import sofa_preprocess

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from manifest_check import validate_manifest

    monkeypatch.setenv("PATH", "/nonexistent")
    d = _failed_logdir(tmp_path)
    sofa_preprocess(SofaConfig(logdir=d))
    doc = telemetry.load_manifest(d)
    assert validate_manifest(doc) == []  # `failed` is schema-valid...
    probs = validate_manifest(doc, require_healthy=True)
    assert any("cputrace failed" in p for p in probs)  # ...but unhealthy


def test_perf_script_timeout_knob(tmp_path, monkeypatch):
    from sofa_tpu.ingest import IngestToolError
    from sofa_tpu.ingest.perf_script import run_perf_script

    perf_data = str(tmp_path / "perf.data")
    with open(perf_data, "wb") as f:
        f.write(b"PERFILE2")
    # a fake `perf` that hangs longer than the (tiny) deadline
    bindir = tmp_path / "bin"
    bindir.mkdir()
    fake = bindir / "perf"
    fake.write_text("#!/bin/sh\nexec /bin/sleep 5\n")
    fake.chmod(0o755)
    monkeypatch.setenv("PATH", str(bindir))
    monkeypatch.setenv("SOFA_PERF_SCRIPT_TIMEOUT_S", "0.2")
    with pytest.raises(IngestToolError, match="exceeded"):
        run_perf_script(perf_data)
