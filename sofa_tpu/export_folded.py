"""Folded-stack export (`sofa export --folded`) for flame tooling.

Writes Brendan-Gregg-format collapsed stacks — ``frame;frame;leaf count``
per line — the lingua franca of speedscope.app, flamegraph.pl, and
inferno, so sampled stacks from a sofa capture drop straight into the
ecosystem's flame-graph viewers:

  pystacks.folded — the in-process Python sampler's FULL stacks
                    (collectors/pystacks.py stores them in `module`)
  cputrace.folded — perf samples; the parser keeps the leaf plus up to 3
                    callers ("leaf<-c1<-c2"), exported caller-first as a
                    partial stack

The reference has no flame-graph path at all; its closest artifact is the
hsg swarm clustering over the same samples.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu.printing import print_progress, print_warning

FOLDED_FRAMES = ["pystacks", "cputrace"]


def _fold_pystacks(df: pd.DataFrame) -> Counter:
    # module carries the full semicolon stack, root-first
    return Counter(s for s in df["module"] if s)


def _fold_cputrace(df: pd.DataFrame) -> Counter:
    counts: Counter = Counter()
    for name in df["name"]:
        if not name:
            continue
        # perf_script names are "leaf<-caller1<-caller2 @ dso" where the
        # dso annotates the LEAF; split it off first or it sticks to the
        # outermost caller and fragments identical stacks.
        name, _, dso = str(name).partition(" @ ")
        frames = name.split("<-")
        if dso:
            frames[0] = f"{frames[0]} [{dso}]"
        counts[";".join(reversed(frames))] += 1
    return counts


def _write(counts: Counter, path: str) -> bool:
    if not counts:
        return False
    with open(path, "w") as f:
        for stack, n in counts.most_common():
            f.write(f"{stack} {n}\n")
    return True


def export_folded(cfg, frames: Optional[Dict[str, pd.DataFrame]] = None
                  ) -> List[str]:
    """Write *.folded files into the logdir; returns the paths written."""
    if frames is None:
        from sofa_tpu.analyze import load_frames

        frames = load_frames(cfg, only=FOLDED_FRAMES)
    written: List[str] = []
    jobs = (
        ("pystacks", _fold_pystacks),
        ("cputrace", _fold_cputrace),
    )
    for name, fold in jobs:
        df = frames.get(name)
        if df is None or df.empty:
            continue
        path = cfg.path(f"{name}.folded")
        if _write(fold(df), path):
            written.append(path)
    if written:
        print_progress(
            "folded stacks -> " + ", ".join(written)
            + "  (open in speedscope.app / flamegraph.pl)")
    else:
        print_warning("folded export: no sampled stacks in this capture "
                      "(--enable_py_stacks / perf)")
    return written
