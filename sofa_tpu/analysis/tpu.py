"""TPU-side analysis: HLO-op profile, module profile, utilization, ROI.

The gpu_profile/nvsmi_profile/spotlight retarget (reference
sofa_analyze.py:343-377,259-341,875-894): kernel/NCCL attribution becomes
HLO-category and XLA-collective attribution; SM-utilization ROI detection
becomes TensorCore-duty-cycle ROI detection.
"""

from __future__ import annotations

import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import analysis_pass
from sofa_tpu.printing import print_hint, print_title, print_warning
from sofa_tpu.trace import CopyKind, narrow, roi_bounds as _roi_bounds, roi_clip


@analysis_pass(
    name="tpu_profile", order=110,
    reads_frames=("tputrace", "tpumodules"),
    reads_columns=("timestamp", "duration", "deviceId", "category",
                   "copyKind", "name", "hlo_category", "phase", "flops",
                   "bytes_accessed", "source"),
    provides_features=("tpu_devices", "tpu_ops", "tpu*_op_time",
                       "tpu*_kernel_time", "tpu*_collective_time",
                       "tpu_total_flops", "tpu_total_bytes_accessed",
                       "tpu_fw_time", "tpu_bw_time", "tpu_bw_fw_ratio",
                       "hlo_time_*", "tpu_customcall_unattributed_time",
                       "tpu_module_launches"),
    provides_artifacts=("tpu_top_ops.csv", "tpu_categories.csv",
                        "tpu_modules_summary.csv"),
    after=("spotlight",),
)
def tpu_profile(frames, cfg, features: Features) -> None:
    df = frames.get("tputrace")
    if df is None or df.empty:
        return
    # Only the columns this pass reads: the row masks below copy every
    # kept column, and the unused big string columns dominate at pod scale.
    df = narrow(df, ["timestamp", "duration", "deviceId", "category",
                     "copyKind", "name", "hlo_category", "phase", "flops",
                     "bytes_accessed", "source"])
    # Spotlight/manual ROI clips warmup+teardown like the reference's
    # profile_region did for its GPU profile (bin/sofa:302-309).
    df = roi_clip(df, cfg)
    if df.empty:
        return
    # category != 0 rows are rare (reserved tag): skip the row-mask COPY
    # when everything qualifies — at 10^7 events the mask copy alone is
    # ~2 GB, and it is pure waste on the overwhelmingly common trace
    sel = df["category"].to_numpy() == 0
    sync = df if sel.all() else df[sel]
    features.add("tpu_devices", df["deviceId"].nunique())
    features.add("tpu_ops", len(sync))

    for device_id, rows in sync.groupby("deviceId"):
        total = float(rows["duration"].sum())
        features.add(f"tpu{device_id}_op_time", total)
        kern = rows[rows["copyKind"] == int(CopyKind.KERNEL)]
        features.add(f"tpu{device_id}_kernel_time", float(kern["duration"].sum()))
        coll = rows[rows["copyKind"] >= 20]
        features.add(f"tpu{device_id}_collective_time", float(coll["duration"].sum()))

    features.add("tpu_total_flops", float(sync["flops"].sum()))
    features.add("tpu_total_bytes_accessed", float(sync["bytes_accessed"].sum()))

    # Training-phase split (reference bin/sofa:284-285 fw/bw kernel filters).
    fw = float(sync.loc[sync["phase"] == "fw", "duration"].sum())
    bw = float(sync.loc[sync["phase"] == "bw", "duration"].sum())
    if fw > 0 or bw > 0:
        features.add("tpu_fw_time", fw)
        features.add("tpu_bw_time", bw)
        if fw > 0:
            features.add("tpu_bw_fw_ratio", bw / fw)

    # Top ops by total time (the reference's top-k GPU kernel table).
    top = (
        sync.groupby("name")
        .agg(
            total_time=("duration", "sum"),
            count=("duration", "count"),
            mean_time=("duration", "mean"),
            flops=("flops", "sum"),
            bytes_accessed=("bytes_accessed", "sum"),
            source=("source", "first"),
        )
        .sort_values("total_time", ascending=False)
    )
    top.head(50).to_csv(cfg.path("tpu_top_ops.csv"))
    if cfg.verbose and not top.empty:
        print_title("Top-10 HLO ops by total time")
        print(top.head(10).to_string())

    # Per-category breakdown (convolution / fusion / all-reduce / ...).
    # Group by a standalone key series instead of .assign(): assign
    # copies the whole frame just to add one column.
    cat_key = sync["hlo_category"].where(sync["hlo_category"] != "",
                                         "uncategorized").rename("cat")
    cat = sync.groupby(cat_key)["duration"].sum() \
        .sort_values(ascending=False)
    for name, value in cat.items():
        features.add(f"hlo_time_{_slug(name)}", float(value))
    cat.to_csv(cfg.path("tpu_categories.csv"))

    # Pallas-kernel time with no cost metadata: XLA cannot see inside
    # Mosaic kernels, so un-annotated ones report flops=0/bytes=0 and
    # vanish from the roofline/top-ops accounting exactly when they are
    # the hottest ops.  Positive match on the ingest's Mosaic naming
    # (pallas@file:line / pallas:...) so host callbacks and runtime
    # markers (AllocateBuffer) can't draw inapplicable advice; a
    # bytes-annotated memory-bound kernel (flops=0 by design) is already
    # attributed.  Feeds the pl.CostEstimate advice rule.
    unattr = sync[sync["name"].str.startswith("pallas")
                  & (sync["flops"] <= 0)
                  & (sync["bytes_accessed"] <= 0)]
    if len(unattr):
        features.add("tpu_customcall_unattributed_time",
                     float(unattr["duration"].sum()))

    # Per-module (jit function) totals.
    mods = frames.get("tpumodules")
    if mods is not None and not mods.empty:
        mods = roi_clip(mods, cfg)
    if mods is not None and not mods.empty:
        per_mod = mods.groupby("name")["duration"].agg(["sum", "count"])
        per_mod.to_csv(cfg.path("tpu_modules_summary.csv"))
        features.add("tpu_module_launches", int(per_mod["count"].sum()))


@analysis_pass(
    name="overlap_profile", order=130,
    reads_frames=("tputrace",),
    reads_columns=("timestamp", "duration", "deviceId", "category"),
    provides_features=("tpu*_async_time", "tpu*_async_hidden_pct"),
    after=("spotlight",),
)
def overlap_profile(frames, cfg, features: Features) -> None:
    """How much async data movement hides under compute, per device.

    TPU DMA (Async XLA Ops, category 2) is supposed to overlap TensorCore
    work; time where a DMA runs with no concurrent sync op is exposed
    latency.  Emits per device:

      tpu<N>_async_time         total async-op span time
      tpu<N>_async_hidden_pct   % of that time covered by sync compute

    The reference's concurrency_breakdown classifies wall-clock windows
    (sofa_analyze.py:75-243); this is the op-level complement XPlane's
    exact spans make possible.
    """
    import numpy as np

    df = frames.get("tputrace")
    if df is None or df.empty:
        return
    df = narrow(df, ["timestamp", "duration", "deviceId", "category"])
    df = roi_clip(df, cfg)
    for device_id, rows in df.groupby("deviceId"):
        sync = rows[rows["category"] == 0]
        asyn = rows[rows["category"] == 2]
        if sync.empty or asyn.empty:
            continue
        from sofa_tpu.trace import merged_intervals

        marr = merged_intervals(
            sync["timestamp"].to_numpy(float),
            (sync["timestamp"] + sync["duration"]).to_numpy(float))
        a0 = asyn["timestamp"].to_numpy(float)
        a1 = (asyn["timestamp"] + asyn["duration"]).to_numpy(float)
        total = float((a1 - a0).sum())
        if total <= 0:
            continue
        hidden = float(np.maximum(_union_coverage(marr, a0, a1), 0.0).sum())
        features.add(f"tpu{device_id}_async_time", total)
        features.add(f"tpu{device_id}_async_hidden_pct",
                     100.0 * min(hidden / total, 1.0))


@analysis_pass(
    name="step_skew_profile", order=140,
    reads_frames=("tpusteps",),
    reads_columns=("timestamp", "duration", "deviceId", "event"),
    provides_features=("step_time_mean", "step_skew_mean", "step_skew_max"),
    provides_artifacts=("tpu_step_skew.csv",),
)
def step_skew_profile(frames, cfg, features: Features) -> None:
    """Straggler detection across devices from the per-device step spans.

    With >1 device, step k should begin everywhere at once; the spread
    (max-min begin over devices, per step index) is collective wait /
    straggler skew.  Emits step_skew_mean/max features and
    tpu_step_skew.csv.  Single-device traces are a no-op.
    """
    steps = frames.get("tpusteps")
    if steps is None or steps.empty:
        return
    # Baseline for "how bad is the skew": mean device step duration.  Own
    # feature (not aisi's) so the hint works in default runs where the
    # optional aisi pass is off.
    features.add("step_time_mean", float(steps["duration"].mean()))
    if steps["deviceId"].nunique() < 2:
        return
    per = steps.groupby("event")["timestamp"].agg(["min", "max", "count"])
    per = per[per["count"] >= 2]
    if per.empty:
        return
    skew = per["max"] - per["min"]
    out = per.reset_index().rename(columns={"event": "step"})
    out["skew"] = skew.values
    out[["step", "skew", "count"]].to_csv(
        cfg.path("tpu_step_skew.csv"), index=False)
    features.add("step_skew_mean", float(skew.mean()))
    features.add("step_skew_max", float(skew.max()))


def _union_coverage(arr, t0s, t1s):
    """Covered length of each query window [t0, t1) under a DISJOINT sorted
    interval union ``arr`` — O((M+Q) log M) via prefix sums, not a per-query
    clip over every interval (same technique as overlap_profile)."""
    import numpy as np

    if not len(arr):
        return np.zeros(len(t0s))
    starts, ends = arr[:, 0], arr[:, 1]
    cum = np.concatenate([[0.0], np.cumsum(ends - starts)])

    def measure_below(ts):
        # total covered length in (-inf, t) per t
        j = np.searchsorted(starts, ts, side="right")
        below = cum[j]
        prev = np.maximum(j - 1, 0)
        # subtract the part of interval j-1 that lies beyond t
        over = np.maximum(ends[prev] - np.maximum(ts, starts[prev]), 0.0)
        return below - np.where(j > 0, over, 0.0)

    return measure_below(np.asarray(t1s)) - measure_below(np.asarray(t0s))


def _intersect_intervals(a, b):
    """Intersection of two DISJOINT sorted interval unions (Mx2 arrays)."""
    import numpy as np

    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i, 0], b[j, 0])
        hi = min(a[i, 1], b[j, 1])
        if hi > lo:
            out.append((lo, hi))
        if a[i, 1] < b[j, 1]:
            i += 1
        else:
            j += 1
    return np.asarray(out, dtype=float).reshape(-1, 2)


@analysis_pass(
    name="input_pipeline_profile", order=150,
    reads_frames=("tpusteps", "tputrace"),
    reads_columns=("timestamp", "duration", "deviceId", "category",
                   "copyKind", "event"),
    provides_features=("tpu*_step_gap_pct", "tpu*_step_h2d_pct"),
    provides_artifacts=("tpu_input_pipeline.csv",),
    after=("spotlight",),
)
def input_pipeline_profile(frames, cfg, features: Features) -> None:
    """Input-pipeline boundedness: device idle gaps INSIDE steps.

    The classic TPU failure mode: the TensorCore finishes a step's compute
    and waits for the next batch (host preprocessing / infeed / H2D).  Per
    device and step span this measures

      busy_pct  — % of the step covered by sync compute (interval union)
      gap_ms    — step time with NO sync op running
      h2d_ms    — EXPOSED host->device transfer time inside the step
                  (H2D/infeed spans minus their part hidden under sync
                  compute): well-prefetched copies overlap compute and
                  must not implicate the input pipeline

    and emits tpu<N>_step_gap_pct / tpu<N>_step_h2d_pct features plus
    tpu_input_pipeline.csv.  TensorBoard's input-pipeline analyzer is the
    tpu-world precedent; the reference has no analogue (GPU idle showed up
    only in its wall-clock concurrency_breakdown, sofa_analyze.py:75-243).
    """
    import numpy as np

    from sofa_tpu.trace import merged_intervals

    steps = frames.get("tpusteps")
    ops = frames.get("tputrace")
    if steps is None or steps.empty or ops is None or ops.empty:
        return
    ops = narrow(ops, ["timestamp", "duration", "deviceId", "category",
                       "copyKind"])
    ops = roi_clip(ops, cfg)
    # Steps get the same ROI as the ops they are measured against, or
    # every step outside the window scores as 100% gap.
    steps = roi_clip(steps, cfg)
    if ops.empty or steps.empty:
        return
    rows = []
    for device_id, dev_steps in steps.groupby("deviceId"):
        dev_ops = ops[ops["deviceId"] == device_id]
        # "Busy" means the core computes: sync H2D/D2H waits (a sync infeed
        # IS the input stall this pass exists to expose) must not count.
        if dev_ops.empty:
            continue  # no op capture for this device: gap would be artifact
        sync = dev_ops[(dev_ops["category"] == 0)
                       & ~dev_ops["copyKind"].isin(
                           (int(CopyKind.H2D), int(CopyKind.D2H)))]
        # A device whose only ops are copies is FULLY input-bound — the
        # worst case must be scored (100% gap), not skipped.
        marr = (merged_intervals(
            sync["timestamp"].to_numpy(float),
            (sync["timestamp"] + sync["duration"]).to_numpy(float))
            if not sync.empty else np.empty((0, 2)))
        # infeed ops classify as CopyKind.H2D at ingest (classify_hlo_kind)
        # whichever line they appear on, so copyKind == 1 covers them.
        h2d = dev_ops[dev_ops["copyKind"] == 1]
        harr = (merged_intervals(
            h2d["timestamp"].to_numpy(float),
            (h2d["timestamp"] + h2d["duration"]).to_numpy(float))
            if not h2d.empty else np.empty((0, 2)))
        hidden_h2d = _intersect_intervals(harr, marr)

        t0s = dev_steps["timestamp"].to_numpy(float)
        t1s = t0s + dev_steps["duration"].to_numpy(float)
        bounds = _roi_bounds(cfg)
        if bounds is not None:
            # ROI-straddling steps keep only their in-window portion, or
            # the clipped-away ops would read as phantom gap.
            t0s = np.maximum(t0s, bounds[0])
            t1s = np.minimum(t1s, bounds[1])
        busy = _union_coverage(marr, t0s, t1s)
        h2d_s = (_union_coverage(harr, t0s, t1s)
                 - _union_coverage(hidden_h2d, t0s, t1s))
        for i, srow in enumerate(dev_steps.itertuples(index=False)):
            if t1s[i] <= t0s[i]:
                continue
            dur = t1s[i] - t0s[i]
            rows.append({
                "deviceId": int(device_id), "step": float(srow.event),
                "t0": t0s[i], "dur": dur,
                "busy_pct": 100.0 * busy[i] / dur,
                "gap_ms": max(0.0, dur - busy[i]) * 1e3,
                "h2d_ms": h2d_s[i] * 1e3,
            })
    if not rows:
        return
    table = pd.DataFrame(rows)
    table.to_csv(cfg.path("tpu_input_pipeline.csv"), index=False)
    for device_id, sel in table.groupby("deviceId"):
        dur_s = sel["dur"].sum()
        if dur_s <= 0:
            continue
        gap_pct = 100.0 * (sel["gap_ms"].sum() / 1e3) / dur_s
        h2d_pct = 100.0 * (sel["h2d_ms"].sum() / 1e3) / dur_s
        features.add(f"tpu{device_id}_step_gap_pct", float(gap_pct))
        features.add(f"tpu{device_id}_step_h2d_pct", float(h2d_pct))


@analysis_pass(
    name="op_tree_profile", order=120,
    reads_frames=("tputrace",),
    reads_columns=("timestamp", "duration", "category", "op_path", "flops",
                   "bytes_accessed"),
    provides_features=("op_tree_paths",),
    provides_artifacts=("tpu_op_tree.csv",),
    after=("spotlight",),
)
def op_tree_profile(frames, cfg, features: Features) -> None:
    """Hierarchical time attribution over the JAX program structure.

    Every op carries its provenance path (op_path column, from XPlane's
    tf_op stat: "jit(train_step)/jvp(main)/dot_general"); each op's time
    is credited to every prefix of its path, yielding a tree like
    TensorBoard's op_profile — but over the unified schema, so it composes
    with phase/device filters.  The reference has no analogue (its closest
    is the flat top-k kernel table, sofa_analyze.py:343-377).  Writes
    tpu_op_tree.csv (path, depth, time, count, flops, bytes).
    """
    df = frames.get("tputrace")
    if df is None or df.empty or "op_path" not in df.columns:
        return
    df = roi_clip(df, cfg)
    sync = df[(df["category"] == 0) & (df["op_path"] != "")]
    if sync.empty:
        return
    # Program paths repeat per op instance (a pod-scale trace is millions of
    # rows over hundreds of distinct paths): aggregate per unique path
    # vectorized first, then walk prefixes over the uniques only.
    per_path = sync.groupby("op_path", sort=False).agg(
        time=("duration", "sum"), count=("duration", "count"),
        flops=("flops", "sum"), nbytes=("bytes_accessed", "sum"))
    agg: dict = {}
    for path, dur, cnt, flops, nbytes in per_path.itertuples(name=None):
        parts = path.split("/")
        for depth in range(1, len(parts) + 1):
            prefix = "/".join(parts[:depth])
            a = agg.get(prefix)
            if a is None:
                agg[prefix] = a = [depth, 0.0, 0, 0.0, 0.0]
            a[1] += dur
            a[2] += cnt
            a[3] += flops
            a[4] += nbytes
    total = float(sync["duration"].sum())
    table = pd.DataFrame(
        [(p, d, t, c, f, b) for p, (d, t, c, f, b) in agg.items()],
        columns=["path", "depth", "time", "count", "flops", "bytes_accessed"],
    ).sort_values(["depth", "time"], ascending=[True, False])
    table["time_pct"] = 100.0 * table["time"] / total if total > 0 else 0.0
    table.to_csv(cfg.path("tpu_op_tree.csv"), index=False)
    features.add("op_tree_paths", len(table))
    if cfg.verbose and not table.empty:
        print_title("Op tree (time by program path, depth <= 2)")
        shallow = table[table["depth"] <= 2].head(12)
        print(shallow[["path", "time", "time_pct", "count"]]
              .to_string(index=False))


@analysis_pass(
    name="roofline_profile", order=160,
    reads_frames=("tputrace",),
    reads_columns=("timestamp", "duration", "deviceId", "category",
                   "copyKind", "name", "flops", "bytes_accessed"),
    provides_features=("tpu*_roofline_efficiency", "tpu*_compute_bound_time",
                       "tpu*_memory_bound_time",
                       "tpu*_arithmetic_intensity"),
    provides_artifacts=("roofline.csv",),
    after=("spotlight",),
)
def roofline_profile(frames, cfg, features: Features) -> None:
    """Per-op speed-of-light analysis against the chip's peak rates.

    For every HLO kernel op with flops/bytes metadata, the attainable
    ("speed of light") time is max(flops/peak_flops, bytes/peak_hbm_bw) —
    the roofline bound under perfect overlap — and efficiency is
    sol_time/actual_time.  Ops are classed compute- vs memory-bound by
    which term dominates.  The reference has no equivalent (its closest is
    nvsmi SM%, sofa_analyze.py:259-341); on TPU the XPlane op trace carries
    exact per-op flops/bytes, so the bound is computable per op.  Writes
    roofline.csv and duration-weighted per-device features.
    """
    import json
    import os

    df = frames.get("tputrace")
    if df is None or df.empty:
        return
    meta_path = cfg.path("tpu_meta.json")
    if not os.path.isfile(meta_path):
        return
    with open(meta_path) as f:
        meta = json.load(f)

    df = roi_clip(df, cfg)
    rows = df[(df["category"] == 0)
              & (df["copyKind"] == int(CopyKind.KERNEL))
              & (df["duration"] > 0)
              & ((df["flops"] > 0) | (df["bytes_accessed"] > 0))]
    if rows.empty:
        return

    out = []
    for device_id, dev in rows.groupby("deviceId"):
        peaks = meta.get(str(device_id), {})
        peak_flops = float(peaks.get("peak_teraflops_per_second", 0)) * 1e12
        peak_bw = float(
            peaks.get("peak_hbm_bw_gigabytes_per_second", 0)) * 1e9
        if peak_flops <= 0 or peak_bw <= 0:
            continue
        agg = dev.groupby("name").agg(
            time=("duration", "sum"),
            count=("duration", "count"),
            flops=("flops", "sum"),
            bytes_accessed=("bytes_accessed", "sum"),
        )
        t_compute = agg["flops"] / peak_flops
        t_memory = agg["bytes_accessed"] / peak_bw
        agg["sol_time"] = pd.concat([t_compute, t_memory], axis=1).max(axis=1)
        agg["efficiency"] = (agg["sol_time"] / agg["time"]).clip(upper=1.0)
        agg["bound"] = "memory"
        agg.loc[t_compute >= t_memory, "bound"] = "compute"
        agg["deviceId"] = device_id
        out.append(agg)

        total = float(agg["time"].sum())
        # Aggregate from the *clipped* per-op efficiencies: an op whose
        # flops/bytes metadata is overcounted (sol_time > time) must not
        # push the device aggregate past 1.0 or mask everyone else.
        sol = float((agg["time"] * agg["efficiency"]).sum())
        features.add(f"tpu{device_id}_roofline_efficiency",
                     sol / total if total else 0.0)
        for bound in ("compute", "memory"):
            features.add(
                f"tpu{device_id}_{bound}_bound_time",
                float(agg.loc[agg["bound"] == bound, "time"].sum()))
        tf, tb = float(agg["flops"].sum()), float(agg["bytes_accessed"].sum())
        if tb > 0:
            features.add(f"tpu{device_id}_arithmetic_intensity", tf / tb)

    if not out:
        return
    table = (pd.concat(out)
             .sort_values("time", ascending=False)
             .reset_index())
    table.to_csv(cfg.path("roofline.csv"), index=False)
    if cfg.verbose:
        heavy = table.head(20).sort_values("efficiency").head(5)
        print_title("Furthest-from-roofline heavy ops")
        print(heavy[["name", "time", "efficiency", "bound"]].to_string(
            index=False))


@analysis_pass(
    name="tpuutil_profile", order=180,
    reads_frames=("tpuutil",),
    reads_columns=("name", "event"),
    provides_features=("*_mean", "*_max", "*_median"),
)
def tpuutil_profile(frames, cfg, features: Features) -> None:
    df = frames.get("tpuutil")
    if df is None or df.empty:
        return
    for metric in ("tc_util", "mxu_util", "hbm_gbps"):
        rows = df[df["name"] == metric]
        if rows.empty:
            continue
        features.add(f"{metric}_mean", float(rows["event"].mean()))
        features.add(f"{metric}_max", float(rows["event"].max()))
        q = rows["event"].quantile([0.25, 0.5, 0.75])
        features.add(f"{metric}_median", float(q.loc[0.5]))


@analysis_pass(
    name="tpumon_profile", order=190,
    reads_frames=("tpumon",),
    reads_columns=("timestamp", "name", "deviceId", "event", "payload"),
    provides_features=("tpumon_samples", "tpumon_span",
                       "tpu*_hbm_used_mean_gb", "tpu*_hbm_used_max_gb",
                       "tpu*_hbm_occupancy_mean", "tpu*_hbm_occupancy_max",
                       "tpu*_hbm_peak_gb"),
)
def tpumon_profile(frames, cfg, features: Features) -> None:
    """Live HBM occupancy/liveness features (the nvsmi_profile analogue,
    reference sofa_analyze.py:259-341) from the in-process sampler — present
    even when XPlane tracing was off."""
    df = frames.get("tpumon")
    if df is None or df.empty:
        return
    alive = df[df["name"] == "alive"]
    if not alive.empty:
        features.add("tpumon_samples", len(alive))
        span = float(alive["timestamp"].max() - alive["timestamp"].min())
        features.add("tpumon_span", span)
    used = df[df["name"] == "hbm_used_gb"]
    for device_id, rows in used.groupby("deviceId"):
        features.add(f"tpu{device_id}_hbm_used_mean_gb",
                     float(rows["event"].mean()))
        features.add(f"tpu{device_id}_hbm_used_max_gb",
                     float(rows["event"].max()))
        # peak_bytes_in_use is carried in payload of the occupancy rows
    occ = df[df["name"] == "hbm_occupancy"]
    for device_id, rows in occ.groupby("deviceId"):
        features.add(f"tpu{device_id}_hbm_occupancy_mean", float(rows["event"].mean()))
        features.add(f"tpu{device_id}_hbm_occupancy_max", float(rows["event"].max()))
        peak = float(rows["payload"].max())
        if peak > 0:
            features.add(f"tpu{device_id}_hbm_peak_gb", peak / 1e9)


@analysis_pass(
    name="memprof_profile", order=200,
    provides_features=("memprof_held_gb", "memprof_buffers",
                       "memprof_sites", "memprof_devices",
                       "memprof_trigger", "memprof_top_site"),
    provides_artifacts=("tpu_memprof.csv",),
)
def memprof_profile(frames, cfg, features: Features) -> None:
    """HBM attribution: which allocation sites held the occupancy peak.

    Consumes the pprof snapshot collectors/tpumon.py captured when the
    summed bytes-in-use set its high-water mark (ingest/memprof.py), writes
    the top-site table to tpu_memprof.csv for the board, and promotes the
    totals to features.  The reference's memory story ends at one used-MB
    number per GPU from nvsmi (sofa_record.py:300-310); an allocation-site
    breakdown is the TPU-native answer to "what do I evict to stop OOMing".
    """
    from sofa_tpu.ingest.memprof import aggregate_sites, load_memprof

    df, meta = load_memprof(cfg.logdir)
    if df is None or df.empty:
        return
    buffers = df[df["kind"] == "buffer"]
    features.add("memprof_held_gb", float(buffers["bytes"].sum()) / 1e9)
    features.add("memprof_buffers", float(buffers["count"].sum()))
    features.add("memprof_sites", float(buffers["site"].nunique()))
    n_dev = buffers.loc[buffers["device"] != "", "device"].nunique()
    if n_dev:
        features.add("memprof_devices", float(n_dev))
    sites = aggregate_sites(df)
    sites.to_csv(cfg.path("tpu_memprof.csv"), index=False)
    if meta.get("trigger"):
        features.add_info("memprof_trigger", meta["trigger"])
    if not sites.empty:
        top = sites.iloc[0]
        features.add_info(
            "memprof_top_site",
            f"{top['site']} ({top['bytes'] / 1e9:.2f} GB, "
            f"{top['share']:.0%})")
    if cfg.verbose:
        print_title("Top HBM allocation sites")
        print(sites.head(10).to_string(index=False))


def _hysteresis_roi(ev, ts, dur, high: float, low: float, up_count: int,
                    t_first: float):
    """(begin, end) of the utilization ROI — the reference's per-row
    hysteresis state machine, vectorized (the iterrows loop was the last
    per-row pass on the spotlight path; on a pod-scale tpuutil frame the
    row-Series construction alone dominated the pass).

    Semantics are bit-identical to the loop: a "high" sample increments a
    counter that resets at each "low" (mid-band samples leave it alone);
    the ROI begins at the first high whose run-since-last-low reaches
    ``up_count``, and ends at the first low after that.
    """
    import numpy as np

    hi = ev >= high
    lo = ev < low
    cs = np.cumsum(hi)
    # highs since the most recent low: cs minus cs at the last low <= i
    # (cs is nondecreasing, so "value at last low" == running max over
    # low positions)
    count = cs - np.maximum.accumulate(np.where(lo, cs, 0))
    armed = np.flatnonzero(hi & (count >= up_count))
    if armed.size == 0:
        return None, None
    i = int(armed[0])
    begin = max(float(ts[i] - dur[i] * up_count), t_first)
    after = np.flatnonzero(lo[i:])
    if after.size == 0:
        return begin, None
    j = i + int(after[0])
    return begin, float(ts[j] - dur[j])


@analysis_pass(
    name="spotlight", order=10,
    reads_frames=("tpuutil",),
    reads_columns=("timestamp", "duration", "name", "event"),
    provides_features=("roi_begin", "roi_end"),
)
def spotlight_roi(frames, cfg, features: Features) -> None:
    """Set cfg.roi_begin/roi_end from TensorCore utilization.

    Hysteresis detector ported from the reference's nvsmi SM-util state
    machine (sofa_analyze.py:875-894): utilization >= high for `up` windows
    begins the ROI; < low back to 0 ends it.  Manual --profile_region wins.
    """
    if cfg.profile_region:
        try:
            begin_s, _, end_s = cfg.profile_region.partition(":")
            cfg.roi_begin = float(begin_s or 0)
            cfg.roi_end = float(end_s or 0)
            features.add("roi_begin", cfg.roi_begin)
            features.add("roi_end", cfg.roi_end)
            return
        except ValueError:
            print_warning(f"bad --profile_region {cfg.profile_region!r}; ignoring")
    if not cfg.spotlight:
        return
    df = frames.get("tpuutil")
    if df is None or df.empty:
        return
    util = df[df["name"] == "tc_util"].sort_values("timestamp")
    if util.empty:
        return
    high, low, up_count = 50.0, 10.0, 3
    t_first = float(util["timestamp"].min() - util["duration"].iloc[0])
    begin, end = _hysteresis_roi(
        util["event"].to_numpy(float), util["timestamp"].to_numpy(float),
        util["duration"].to_numpy(float), high, low, up_count, t_first)
    if begin is not None:
        if end is None or end <= begin:
            end = float(util["timestamp"].max())
        cfg.roi_begin, cfg.roi_end = begin, end
        features.add("roi_begin", begin)
        features.add("roi_end", end)
        print_hint(f"spotlight ROI: {begin:.3f}s .. {end:.3f}s")


@analysis_pass(
    name="serving_profile", order=170,
    reads_frames=("tputrace", "tpumodules"),
    reads_columns=("timestamp", "duration", "category", "module", "name",
                   "flops", "bytes_accessed"),
    provides_features=("serving_prefill_time", "serving_decode_time",
                       "serving_prefill_intensity",
                       "serving_decode_intensity",
                       "serving_decode_hbm_gbps", "serving_decode_calls",
                       "serving_ttft"),
    after=("spotlight",),
)
def serving_profile(frames, cfg, features: Features) -> None:
    """Prefill/decode phase split for serving (inference) captures.

    No reference analogue — the reference profiles training only.  On TPU
    the two serving regimes are architecturally different (prefill is
    MXU/compute-bound, decode re-reads the whole KV cache per token and is
    HBM-bound), and BASELINE config #4 asks exactly for "inference HLO-op +
    HBM-bandwidth attribution".  Phases are recognized from XLA module
    names (jit_run_prefill / jit_run_decode / *generate* — whatever the
    program jitted, matched case-insensitively), so any serving stack that
    jits its prefill and decode separately gets the split for free:

      serving_prefill_time / serving_decode_time     device time per phase
      serving_prefill_intensity / ..._decode_...     flops per HBM byte
      serving_ttft                                   first prefill span wall
      serving_decode_calls                           decode dispatches

    plus a memory-bound hint when decode's arithmetic intensity collapses
    relative to prefill's (the KV-cache-bound signature).
    """
    df = frames.get("tputrace")
    if df is None or df.empty or "module" not in df.columns:
        return
    df = roi_clip(df, cfg)  # spotlight ROI excludes warmup/compile ops
    sync = df[df["category"] == 0]
    if sync.empty:
        return
    mods = sync["module"].astype(str)
    uniq = [m for m in mods.unique() if m]
    pre_names = [m for m in uniq if "prefill" in m.lower()]
    dec_names = [m for m in uniq
                 if "decode" in m.lower() or "generate" in m.lower()]
    if not pre_names or not dec_names:
        return

    def phase(names):
        sel = sync[mods.isin(names)]
        dur = float(sel["duration"].sum())
        flops = float(sel["flops"].sum())
        nbytes = float(sel["bytes_accessed"].sum())
        return sel, dur, flops, nbytes

    pre, pre_t, pre_f, pre_b = phase(pre_names)
    dec, dec_t, dec_f, dec_b = phase(dec_names)
    if pre_t <= 0 or dec_t <= 0:
        return
    features.add("serving_prefill_time", pre_t)
    features.add("serving_decode_time", dec_t)
    pre_i = pre_f / pre_b if pre_b > 0 else 0.0
    dec_i = dec_f / dec_b if dec_b > 0 else 0.0
    features.add("serving_prefill_intensity", pre_i)
    features.add("serving_decode_intensity", dec_i)
    if dec_b > 0:
        features.add("serving_decode_hbm_gbps", dec_b / dec_t / 1e9)
    # TTFT proxy: wall span of the FIRST prefill dispatch only — a steady
    # serving capture has prefills recurring throughout, so spanning all of
    # them would approximate the whole capture.  The module-launch line
    # delimits dispatches exactly; without it, fall back to the prefill ops
    # that precede the first decode op.
    launches = frames.get("tpumodules")
    ttft = None
    if launches is not None and not launches.empty:
        launches = roi_clip(launches, cfg)
        lnames = launches["name"].astype(str)
        pre_launch = launches[lnames.isin(pre_names)] \
            .sort_values("timestamp")
        if not pre_launch.empty:
            ttft = float(pre_launch.iloc[0]["duration"])
        features.add("serving_decode_calls", int(lnames.isin(
            dec_names).sum()))
    if ttft is None:
        first_dec = float(dec["timestamp"].min())
        head = pre[pre["timestamp"] < first_dec]
        if not head.empty:
            ttft = float((head["timestamp"] + head["duration"]).max()
                         - head["timestamp"].min())
    if ttft is not None:
        features.add("serving_ttft", ttft)
    if dec_i > 0 and pre_i / max(dec_i, 1e-12) >= 4.0:
        print_hint(
            f"serving: decode is HBM-bound ({dec_i:.1f} flops/byte vs "
            f"prefill {pre_i:.1f}) — KV-cache reads dominate; consider "
            "larger decode batches, GQA/MQA, or a quantized cache")


def _slug(name: str) -> str:
    return name.strip().lower().replace(" ", "_").replace("-", "_")
