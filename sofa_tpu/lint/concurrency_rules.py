"""SL019–SL023 — concurrency & commit-ordering analysis.

Every frontier on the ROADMAP (`sofa live` tail-ingest, the `sofa agent`
fleet daemon, the out-of-core columnar engine) turns the one-shot batch
verbs into concurrent, always-on code — and the tree already carries real
concurrency: the supervisor watchdog, collector sampler threads, pool
workers, ThreadingHTTPServer handlers, and the injected sitecustomize's
watcher threads.  Until this module, none of that had a machine-checked
discipline: locks were anonymous, their protected state implicit, and the
commit-ordering the crash journal depends on was enforced only by review.

The analyzer extracts, statically and cross-file, an **execution-context
graph**: which functions run on the main verb flow, which are
thread targets (``threading.Thread(target=...)`` / ``Timer``), which are
pool workers (``pool.thread_map`` / ``executor.submit`` / ``pool.map``),
and which are request handlers (methods of ``*RequestHandler`` /
``*HTTPServer`` / ``*Servicer`` classes).  Contexts propagate along the
intra-file call graph and one hop across files (a function another
module calls from a thread context is itself thread-context).  On top of
that graph, five rules:

SL019  **declared-guard contracts.**  State a :class:`sofa_tpu.concurrency.
       Guard` declares in ``protects=`` must have every write under a
       ``with <that guard>:`` block; state written from ≥2 execution
       contexts with no declared guard at all is flagged (the cross-file
       generalization of the SL006 worker-global heuristic); and writes
       to another module's *class* attributes (process-global behavior
       changes, the old viz.py ThreadingHTTPServer mutation) are flagged.
SL020  **no blocking under a guard, no lock-order cycles.**  subprocess
       calls, ``time.sleep``, file ``open`` and ``.result()/.join()/
       .wait()`` inside a held lock/guard block serialize every other
       context on IO; nested acquisitions (lexical, plus one call hop)
       must form an acyclic lock order.
SL021  **commit-ordering.**  Inside a journaled verb function (one that
       calls ``Journal(...).begin``/``.commit``), derived-artifact writes
       must sit inside the begin→commit window, the digest refresh must
       precede the commit, and nothing may write after the commit — the
       class of bug PR 10 found dynamically in `sofa diff`, caught
       statically.  Lexical, same-function granularity: the begin/commit
       bracket and the direct writer calls between them.
SL022  **thread-context safety.**  ``signal.signal``/``os.chdir``/
       ``os.fork`` from a non-main execution context; daemon threads
       spawned at module import time (including inside the **embedded
       injection templates** — module-level string constants that parse
       as Python modules are linted as virtual modules, which is how the
       old import-time ``_g``/``_t`` watchers in collectors/xprof.py were
       caught); and check-then-act on the ``_derived.writing`` sentinel
       outside trace.py's own API (``derived_writing``/
       ``reap_stale_sentinel`` exist precisely so nobody races the raw
       file).
SL023  **shutdown liveness.**  Every ``threading.Thread`` spawned in the
       package must be reachable from a stop path: a ``.join()`` on its
       binding in the same class/function, or an ownership transfer
       (``return``) to a caller.  The invariant the fleet daemon will
       live or die by.  Scope: real modules only — the injection
       templates run inside the *profiled* process, whose watcher threads
       are daemon-by-contract and die with the host program.

Extraction is purely syntactic like the rest of sofa-lint; closure-variable
mutations and per-element dict aliasing (``st = self._state[...]``) are
out of reach by design — the guard declarations cover the containers, and
the race-marked runtime tests (tests/test_concurrency_lint.py) cover what
the AST cannot see.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from sofa_tpu.lint.core import (
    FileContext,
    Finding,
    Rule,
    SEV_ERROR,
    SEV_WARN,
    _scan_suppressions,
)

CTX_MAIN = "main"
CTX_THREAD = "thread"
CTX_WORKER = "worker"
CTX_HANDLER = "handler"

_THREAD_FACTORIES = frozenset({"threading.Thread", "threading.Timer"})
_LOCK_FACTORIES = frozenset({"threading.Lock", "threading.RLock",
                             "threading.Condition", "threading.Semaphore",
                             "threading.BoundedSemaphore"})
#: Guard construction, by dotted-origin tail (sofa_tpu.concurrency.Guard,
#: a from-imported Guard, concurrency.Guard — all end the same way).
_GUARD_TAIL = "Guard"

_HANDLER_BASE_SUFFIXES = ("RequestHandler", "HTTPServer", "Servicer",
                          "BaseRequestHandler")

#: Blocking operations that must not run while holding a guard: every
#: other context that needs the guard stalls on this one's IO.
_BLOCKING_CALLS = frozenset({
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call", "time.sleep", "open", "io.open", "gzip.open",
})
_BLOCKING_METHODS = frozenset({"result", "join", "wait"})

#: Main-thread-only / fork-unsafe operations for SL022.
_THREAD_UNSAFE = frozenset({"signal.signal", "signal.setitimer",
                            "os.chdir", "os.fork", "os.forkpty"})

#: The mid-write sentinel and its owning module (trace.py's API is the
#: only sanctioned accessor).
_SENTINEL_LITERAL = "_derived.writing"
_SENTINEL_CHECKS = frozenset({"os.path.exists", "os.path.isfile",
                              "os.stat", "os.unlink", "os.remove",
                              "open", "io.open"})

#: Derived-artifact writer helpers (mirror of artifact_rules._WRITER_FNS
#: plus the DataFrame writer methods) for the SL021 window check.
_WRITER_TAILS = frozenset({"atomic_write", "atomic_replace",
                           "fsync_append", "write_csv", "write_frame",
                           "write_report_js_doc", "to_csv", "to_parquet"})

#: Container mutations that count as writes to the named object.
_MUTATORS = frozenset({"append", "add", "update", "setdefault", "pop",
                       "extend", "insert", "remove", "discard", "clear",
                       "popitem", "appendleft", "popleft"})

_PSEUDO_MODULE = "<module>"


# ---------------------------------------------------------------------------
# Per-file extraction.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class GuardDecl:
    """One Guard(...) declaration: module-level or an instance attribute."""

    name: str                  # binding name ("_registry_lock" / "_lock")
    cls: str                   # owning class, "" for module guards
    protects: tuple
    line: int
    declared_in: str           # qualname of the declaring function ("" = module)


@dataclass(frozen=True)
class SpawnSite:
    line: int
    binding_kind: str          # "attr" | "local" | "loose"
    binding: str               # attr/var name ("" when loose)
    cls: str                   # enclosing class ("" outside classes)
    func: str                  # enclosing function qualname ("" = module level)
    factory: str               # "threading.Thread" / "threading.Timer"


@dataclass(frozen=True)
class _Write:
    name: str                  # attribute or module-global name
    cls: str                   # owning class for attr writes, "" for globals
    func: str                  # qualname of the writing function
    line: int
    held: tuple                # lock/guard keys lexically held at the write


class _FileModel:
    """Everything one parse of one (real or virtual) module contributes.

    ``line_offset`` shifts findings for virtual modules (embedded
    templates) back onto the real file's lines; ``suppressions`` for a
    virtual module are scanned from the template's own source, since the
    engine's comment scan cannot see inside a string literal.
    """

    def __init__(self, relpath: str, src: str, line_offset: int = 0,
                 virtual: bool = False):
        self.relpath = relpath
        self.line_offset = line_offset
        self.virtual = virtual
        self.ok = False
        try:
            self.tree = ast.parse(src)
        except (SyntaxError, ValueError):
            self.tree = None
            return
        self.ok = True
        self.suppressions = _scan_suppressions(src) if virtual else None

        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self.import_alias: Dict[str, str] = {}
        self.from_import: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_import[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

        # function table: qualname -> node, plus per-node ownership
        self.functions: Dict[str, ast.AST] = {}
        self.func_of: Dict[int, str] = {}
        self.class_of: Dict[str, str] = {}      # qualname -> class name
        self.methods_by_name: Dict[str, List[str]] = {}
        self.module_globals: Set[str] = set()
        self.handler_classes: Set[str] = set()
        self._index_scopes()

        self.guards: List[GuardDecl] = []
        self.plain_locks: Set[Tuple[str, str]] = set()   # (cls, name)
        self.spawns: List[SpawnSite] = []
        self.seeds: Dict[str, Set[str]] = {}
        self.call_edges: Set[Tuple[str, str]] = set()
        self.external_calls: List[Tuple[str, str]] = []  # (func, origin)
        self.writes: List[_Write] = []
        self.imported_attr_writes: List[Tuple[int, str]] = []
        self.lock_block_calls: List[Tuple[tuple, str, str, int]] = []
        self.lock_nestings: List[Tuple[tuple, tuple, int]] = []
        self.locks_in_func: Dict[str, Set[tuple]] = {}
        self.calls_under_lock: List[Tuple[tuple, str, str, int]] = []
        self.journal_funcs: Dict[str, dict] = {}
        self.unsafe_calls: List[Tuple[str, str, int]] = []
        self.sentinel_races: List[Tuple[str, int]] = []
        self.templates: List[Tuple[str, int, str]] = []  # (name, line, src)
        self._harvest()
        self.contexts: Dict[str, Set[str]] = {}
        self._infer_contexts()

    # -- scope indexing ----------------------------------------------------
    def _index_scopes(self) -> None:
        def walk(node, func, cls):
            for child in ast.iter_child_nodes(node):
                nf, nc = func, cls
                if isinstance(child, ast.ClassDef):
                    nc = child.name
                    if any(_base_tail(b).endswith(_HANDLER_BASE_SUFFIXES)
                           for b in child.bases):
                        self.handler_classes.add(child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    # Methods qualify by CLASS as well as enclosing
                    # function: two classes with a same-named method
                    # (every pair of __init__s) must not alias in
                    # functions/class_of, or spawn sites in one class
                    # get attributed to the other and SL023's join
                    # matching breaks (found when supervisor.py grew a
                    # second class).
                    if func:
                        nf = f"{func}.{child.name}"
                    elif cls:
                        nf = f"{cls}.{child.name}"
                    else:
                        nf = child.name
                    self.functions[nf] = child
                    self.class_of[nf] = cls
                    if cls and not func:
                        self.methods_by_name.setdefault(
                            child.name, []).append(nf)
                elif not func and not cls and \
                        isinstance(child, (ast.Assign, ast.AnnAssign)):
                    tgts = (child.targets if isinstance(child, ast.Assign)
                            else [child.target])
                    for tgt in tgts:
                        if isinstance(tgt, ast.Name):
                            self.module_globals.add(tgt.id)
                self.func_of[id(child)] = nf
                walk(child, nf, nc)

        self.func_of[id(self.tree)] = ""
        walk(self.tree, "", "")

    # -- shared resolution helpers ----------------------------------------
    def resolve(self, expr) -> str:
        if isinstance(expr, ast.Name):
            return self.from_import.get(expr.id,
                                        self.import_alias.get(expr.id,
                                                              expr.id))
        if isinstance(expr, ast.Attribute):
            parts = [expr.attr]
            cur = expr.value
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                parts.append(self.import_alias.get(
                    cur.id, self.from_import.get(cur.id, cur.id)))
                return ".".join(reversed(parts))
        return ""

    def ancestors(self, node) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def _local_func(self, name: str, scope: str) -> "str | None":
        """Resolve a bare function name seen in ``scope`` to a qualname:
        nested definitions shadow module-level ones."""
        while True:
            cand = f"{scope}.{name}" if scope else name
            if cand in self.functions:
                return cand
            if not scope:
                return None
            scope = scope.rpartition(".")[0]

    def _callable_ref(self, expr, scope: str) -> "str | None":
        """The function a callable expression names, if it is local:
        a bare name, or ``self.method`` within a class."""
        if isinstance(expr, ast.Name):
            return self._local_func(expr.id, scope)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            cls = self.class_of.get(scope) or self.class_of.get(
                scope.partition(".")[0], "")
            if cls:
                cand = expr.attr
                for qn in self.methods_by_name.get(cand, ()):
                    if self.class_of.get(qn) == cls:
                        return qn
        return None

    def _lock_key(self, expr, scope: str) -> "tuple | None":
        """(cls, name) key of a lock/guard a ``with`` item names, or None
        when the expression is not a known lock."""
        if isinstance(expr, ast.Name):
            key = ("", expr.id)
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            cls = self.class_of.get(scope, "")
            key = (cls, expr.attr)
        else:
            return None
        if key in self.plain_locks:
            return key
        for g in self.guards:
            if (g.cls, g.name) == key:
                return key
        return None

    def _held_at(self, node, scope: str) -> tuple:
        held = []
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    key = self._lock_key(item.context_expr, scope)
                    if key is not None:
                        held.append(key)
        return tuple(held)

    # -- the harvest -------------------------------------------------------
    def _harvest(self) -> None:
        # Pass 1: lock/guard declarations (needed before _held_at works).
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt, val = node.targets[0], node.value
            if not isinstance(val, ast.Call):
                continue
            resolved = self.resolve(val.func)
            tail = resolved.rsplit(".", 1)[-1]
            func = self.func_of.get(id(node), "")
            if isinstance(tgt, ast.Name) and not func:
                cls, name = "", tgt.id
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                cls, name = self.class_of.get(func, ""), tgt.attr
            else:
                continue
            if tail == _GUARD_TAIL:
                protects: tuple = ()
                for kw in val.keywords:
                    if kw.arg == "protects" and \
                            isinstance(kw.value, (ast.Tuple, ast.List)):
                        protects = tuple(
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
                self.guards.append(GuardDecl(name, cls, protects,
                                             node.lineno, func))
            elif resolved in _LOCK_FACTORIES:
                self.plain_locks.add((cls, name))

        # Pass 2: everything else.
        for node in ast.walk(self.tree):
            func = self.func_of.get(id(node), "")
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and not self.virtual:
                self._maybe_template(node)
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                self._harvest_write(node, func)
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = self.resolve(node.func)
            tail = resolved.rsplit(".", 1)[-1]

            # call edges + external calls for context propagation
            ref = self._callable_ref(node.func, func)
            caller = func or _PSEUDO_MODULE
            if ref is not None:
                self.call_edges.add((caller, ref))
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name):
                # X.m() binding to the unique class defining m in this file
                cands = self.methods_by_name.get(node.func.attr, ())
                if len(cands) == 1 and node.func.value.id != "self":
                    self.call_edges.add((caller, cands[0]))
                elif "." in resolved:
                    self.external_calls.append((caller, resolved))
            elif "." in resolved:
                self.external_calls.append((caller, resolved))

            # thread spawns
            if resolved in _THREAD_FACTORIES:
                self._harvest_spawn(node, resolved, func)
            # worker dispatch
            self._maybe_worker_seed(node, tail, func)
            # blocking-under-lock + lock-order facts
            held = self._held_at(node, func)
            if held:
                is_blocking = (resolved in _BLOCKING_CALLS
                               or (isinstance(node.func, ast.Attribute)
                                   and node.func.attr in _BLOCKING_METHODS
                                   and self._lock_key(node.func.value, func)
                                   is None))
                if is_blocking:
                    self.lock_block_calls.append(
                        (held, resolved or node.func.attr, func,
                         node.lineno))
                if ref is not None:
                    self.calls_under_lock.append((held, ref, func,
                                                  node.lineno))
            # SL022 facts
            if resolved in _THREAD_UNSAFE:
                self.unsafe_calls.append((func, resolved, node.lineno))
            if resolved in _SENTINEL_CHECKS and \
                    self._names_sentinel(node):
                self.sentinel_races.append((resolved, node.lineno))
            # SL021 facts
            self._harvest_journal(node, resolved, tail, func)

        # lock nesting (lexical): every with-lock inside another with-lock
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            func = self.func_of.get(id(node), "")
            inner = [self._lock_key(i.context_expr, func)
                     for i in node.items]
            inner = [k for k in inner if k is not None]
            if not inner:
                continue
            self.locks_in_func.setdefault(func, set()).update(inner)
            outer = self._held_at(node, func)
            for o in outer:
                for i in inner:
                    if o != i:
                        self.lock_nestings.append((o, i, node.lineno))

    def _maybe_template(self, node: ast.Constant) -> None:
        """Module-level string constants that parse as Python modules with
        imports are embedded templates (the sitecustomize/sampler
        injection sources) — lint them as virtual modules."""
        parent = self.parents.get(node)
        if not (isinstance(parent, ast.Assign)
                and self.func_of.get(id(parent), "") == ""
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            return
        src = node.value
        if len(src) < 200 or "import " not in src:
            return
        try:
            sub = ast.parse(src)
        except (SyntaxError, ValueError):
            return
        if not any(isinstance(s, (ast.Import, ast.ImportFrom))
                   for s in sub.body):
            return
        self.templates.append((parent.targets[0].id, node.lineno, src))

    def _harvest_spawn(self, node: ast.Call, factory: str,
                       func: str) -> None:
        # seed the target's context
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and factory.endswith("Timer") and \
                len(node.args) > 1:
            target = node.args[1]
        if target is not None:
            ref = self._callable_ref(target, func)
            if ref is not None:
                self.seeds.setdefault(ref, set()).add(CTX_THREAD)
        # record the spawn site + its binding for SL022/SL023
        parent = self.parents.get(node)
        kind, binding = "loose", ""
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                kind, binding = "local", tgt.id
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                kind, binding = "attr", tgt.attr
        self.spawns.append(SpawnSite(
            node.lineno, kind, binding, self.class_of.get(func, ""),
            func, factory))

    def _maybe_worker_seed(self, node: ast.Call, tail: str,
                           func: str) -> None:
        arg = None
        if tail == "thread_map" and node.args:
            arg = node.args[0]
        elif isinstance(node.func, ast.Attribute) and node.args:
            recv = node.func.value
            recv_name = recv.id if isinstance(recv, ast.Name) else ""
            if node.func.attr == "submit":
                arg = node.args[0]
            elif node.func.attr == "map" and any(
                    s in recv_name.lower()
                    for s in ("pool", "executor", "ex")):
                arg = node.args[0]
        if arg is None:
            return
        ref = self._callable_ref(arg, func)
        if ref is not None:
            self.seeds.setdefault(ref, set()).add(CTX_WORKER)

    def _names_sentinel(self, node: ast.Call) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and sub.value == \
                    _SENTINEL_LITERAL:
                return True
            if isinstance(sub, ast.Name) and self.from_import.get(
                    sub.id, "").endswith(".WRITING_SENTINEL"):
                return True
        return False

    def _harvest_journal(self, node: ast.Call, resolved: str, tail: str,
                         func: str) -> None:
        if not func:
            return
        ent = self.journal_funcs.setdefault(func, {
            "journal_names": set(), "begin": [], "commit": [],
            "digest": [], "writes": []})
        if tail == "Journal":
            parent = self.parents.get(node)
            if isinstance(parent, ast.Assign) and \
                    isinstance(parent.targets[0], ast.Name):
                ent["journal_names"].add(parent.targets[0].id)
        if isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in ent["journal_names"]:
            if node.func.attr == "begin":
                ent["begin"].append(node.lineno)
            elif node.func.attr == "commit":
                ent["commit"].append(node.lineno)
        if tail == "write_digests":
            ent["digest"].append(node.lineno)
        if tail in _WRITER_TAILS:
            names = [os.path.basename(s.value)
                     for s in ast.walk(node)
                     if isinstance(s, ast.Constant)
                     and isinstance(s.value, str)]
            ent["writes"].append((node.lineno,
                                  names[-1] if names else ""))

    def _harvest_write(self, node, func: str) -> None:
        if isinstance(node, ast.Assign):
            targets, line = node.targets, node.lineno
        else:
            targets, line = [node.target], node.lineno
        for tgt in targets:
            base = tgt
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute):
                root = base.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if not isinstance(root, ast.Name):
                    continue
                if root.id == "self" and \
                        isinstance(base.value, ast.Name) and func:
                    cls = self.class_of.get(func, "")
                    if cls:
                        self.writes.append(_Write(
                            base.attr, cls, func, line,
                            self._held_at(node, func)))
                elif root.id != "self" and \
                        not isinstance(tgt, ast.Subscript):
                    # X[...].attr = ... where X is imported: mutating
                    # another module's namespace.  Flag only CLASS-
                    # attribute writes (the attr's owner resolves to an
                    # uppercase-named component) — module-level config
                    # vars like ``printing.verbose`` are the startup
                    # idiom.
                    owner = self.resolve(base.value)
                    is_import = (root.id in self.import_alias
                                 or root.id in self.from_import)
                    if is_import and owner and \
                            owner.rsplit(".", 1)[-1][:1].isupper():
                        self.imported_attr_writes.append(
                            (line, f"{owner}.{base.attr}"))
            elif isinstance(base, ast.Name) and func and \
                    base.id in self.module_globals and \
                    isinstance(tgt, ast.Subscript):
                self.writes.append(_Write(base.id, "", func, line,
                                          self._held_at(node, func)))

    # mutation calls count as writes too — second walk keyed off _harvest
    def harvest_mutations(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                continue
            func = self.func_of.get(id(node), "")
            recv = node.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and func:
                cls = self.class_of.get(func, "")
                if cls:
                    self.writes.append(_Write(
                        recv.attr, cls, func, node.lineno,
                        self._held_at(node, func)))
            elif isinstance(recv, ast.Name) and func and \
                    recv.id in self.module_globals:
                self.writes.append(_Write(recv.id, "", func, node.lineno,
                                          self._held_at(node, func)))

    # -- contexts ----------------------------------------------------------
    def _infer_contexts(self) -> None:
        if not self.ok:
            return
        self.harvest_mutations()
        ctx: Dict[str, Set[str]] = {qn: set(self.seeds.get(qn, ()))
                                    for qn in self.functions}
        for cls in self.handler_classes:
            for qn, c in self.class_of.items():
                if c == cls:
                    ctx[qn].add(CTX_HANDLER)
        ctx[_PSEUDO_MODULE] = {CTX_MAIN}
        self._propagate(ctx)
        # Functions neither seeded nor called intra-file are entry points
        # (verbs, public API): main context.
        for qn, c in ctx.items():
            if not c:
                c.add(CTX_MAIN)
        self._propagate(ctx)
        self.contexts = ctx

    def _propagate(self, ctx: Dict[str, Set[str]]) -> None:
        changed = True
        while changed:
            changed = False
            for caller, callee in self.call_edges:
                src = ctx.get(caller)
                dst = ctx.get(callee)
                if src and dst is not None and not src <= dst:
                    dst |= src
                    changed = True

    def add_context(self, qualname: str, contexts: Set[str]) -> bool:
        """Cross-file propagation entry: returns True when it changed."""
        dst = self.contexts.get(qualname)
        if dst is None or contexts <= dst:
            return False
        dst |= contexts
        self._propagate(self.contexts)
        return True


def _base_tail(expr) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


# ---------------------------------------------------------------------------
# Graph assembly.
# ---------------------------------------------------------------------------

@dataclass
class ConcurrencyGraph:
    """The cross-file concurrency facts SL019–SL023 consult.  ``ok`` is
    False when extraction was skipped (explicit ProjectContext without
    detection — fixture isolation), leaving every rule inert."""

    ok: bool = False
    models: Dict[str, _FileModel] = field(default_factory=dict)
    virtuals: Dict[str, List[Tuple[str, int, _FileModel]]] = \
        field(default_factory=dict)
    lock_cycles: List[Tuple[tuple, ...]] = field(default_factory=list)
    cycle_sites: Dict[tuple, Tuple[str, int]] = field(default_factory=dict)


def build_concurrency_graph(files, base: str) -> ConcurrencyGraph:
    base = os.path.abspath(base)
    models: Dict[str, _FileModel] = {}
    for f in files:
        if not f.endswith(".py"):
            continue
        ab = os.path.abspath(f)
        rel = (os.path.relpath(ab, base).replace(os.sep, "/")
               if ab.startswith(base + os.sep) else ab)
        try:
            with open(f, encoding="utf-8", errors="replace") as fh:
                src = fh.read()
        except OSError:
            continue
        m = _FileModel(rel, src)
        if m.ok:
            models[rel] = m
    graph = ConcurrencyGraph(ok=True, models=models)

    # virtual modules from embedded templates
    for rel, m in models.items():
        for name, line, src in m.templates:
            vm = _FileModel(rel, src, line_offset=line - 1, virtual=True)
            if vm.ok:
                graph.virtuals.setdefault(rel, []).append((name, line, vm))

    # one-hop cross-file context propagation: a function another module
    # calls from a thread/worker/handler context inherits that context.
    by_stem: Dict[str, List[_FileModel]] = {}
    for rel, m in models.items():
        by_stem.setdefault(
            os.path.splitext(os.path.basename(rel))[0], []).append(m)
    for _round in range(3):
        changed = False
        for m in models.values():
            for caller, origin in m.external_calls:
                src_ctx = m.contexts.get(caller) or set()
                extra = src_ctx - {CTX_MAIN}
                if not extra:
                    continue
                parts = origin.split(".")
                if len(parts) < 2:
                    continue
                stem, fname = parts[-2], parts[-1]
                for other in by_stem.get(stem, ()):
                    if other is m:
                        continue
                    changed |= other.add_context(fname, extra)
                    # ...and into the unique method of that name (the
                    # module-fn -> ledger-method forwarding idiom).
                    cands = other.methods_by_name.get(fname, ())
                    if len(cands) == 1:
                        changed |= other.add_context(cands[0], extra)
        if not changed:
            break

    _find_lock_cycles(graph)
    return graph


def _find_lock_cycles(graph: ConcurrencyGraph) -> None:
    """Build the acquisition-order graph (lexical nesting + one call hop,
    cross-file through from-imports) and record its cycles."""
    edges: Dict[tuple, Set[tuple]] = {}
    sites: Dict[Tuple[tuple, tuple], Tuple[str, int]] = {}

    def _add(outer, inner, rel, line):
        if outer == inner:
            return
        edges.setdefault(outer, set()).add(inner)
        sites.setdefault((outer, inner), (rel, line))

    def _qualify(rel, key):
        return (rel,) + key

    for rel, m in graph.models.items():
        for outer, inner, line in m.lock_nestings:
            _add(_qualify(rel, outer), _qualify(rel, inner), rel, line)
        for held, callee, _func, line in m.calls_under_lock:
            for inner in m.locks_in_func.get(callee, ()):
                for outer in held:
                    _add(_qualify(rel, outer), _qualify(rel, inner),
                         rel, line)

    # simple DFS cycle detection
    color: Dict[tuple, int] = {}
    stack: List[tuple] = []
    cycles: List[Tuple[tuple, ...]] = []

    def dfs(node):
        color[node] = 1
        stack.append(node)
        for nxt in sorted(edges.get(node, ())):
            if color.get(nxt, 0) == 1:
                i = stack.index(nxt)
                cyc = tuple(stack[i:])
                if cyc not in cycles:
                    cycles.append(cyc)
            elif color.get(nxt, 0) == 0:
                dfs(nxt)
        stack.pop()
        color[node] = 2

    for node in sorted(edges):
        if color.get(node, 0) == 0:
            dfs(node)
    graph.lock_cycles = cycles
    for cyc in cycles:
        pairs = list(zip(cyc, cyc[1:] + (cyc[0],)))
        for pair in pairs:
            if pair in sites:
                graph.cycle_sites[cyc] = sites[pair]
                break


# ---------------------------------------------------------------------------
# The rules.
# ---------------------------------------------------------------------------

def _graph(ctx: FileContext) -> Optional[ConcurrencyGraph]:
    g = getattr(ctx.project, "concurrency", None)
    return g if isinstance(g, ConcurrencyGraph) and g.ok else None


class _ConcRule(Rule):
    node_types: tuple = ()

    def _model(self, ctx: FileContext) -> "Optional[_FileModel]":
        g = _graph(ctx)
        if g is None:
            return None
        return g.models.get(ctx.relpath)


def _ctx_of(model: _FileModel, func: str) -> Set[str]:
    return model.contexts.get(func) or {CTX_MAIN}


class UndeclaredSharedState(_ConcRule):
    """SL019 — declared-guard contracts, three arms: (1) every write to a
    name some Guard's ``protects`` declares must happen inside a ``with
    <that guard>:`` block (initialization in the declaring function and
    ``__init__``/module level is exempt); (2) state written from two or
    more execution contexts with no declared guard at all is flagged once
    per name; (3) assignments to another module's class attributes are
    process-global mutations every context observes — subclass or config
    object instead."""

    rule_id = "SL019"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        model = self._model(ctx)
        if model is None:
            return
        guards_by_state: Dict[Tuple[str, str], GuardDecl] = {}
        for g in model.guards:
            for name in g.protects:
                guards_by_state[(g.cls, name)] = g
        # arm 1: guarded state written outside its guard
        for w in model.writes:
            g = guards_by_state.get((w.cls, w.name))
            if g is None:
                continue
            if w.func == g.declared_in or \
                    w.func.rpartition(".")[-1] == "__init__":
                continue
            if (g.cls, g.name) in w.held:
                continue
            where = f"{g.cls}.{g.name}" if g.cls else g.name
            yield Finding(
                ctx.relpath, w.line, self.rule_id,
                f"write to {w.name!r} outside its declared guard {where} "
                f"(which declares protects={list(g.protects)}) — every "
                "access to declared shared state must hold the guard",
                self.severity)
        # arm 2: multi-context writes with no declared guard
        by_name: Dict[Tuple[str, str], List[_Write]] = {}
        for w in model.writes:
            if (w.cls, w.name) in guards_by_state:
                continue
            by_name.setdefault((w.cls, w.name), []).append(w)
        for (cls, name), writes in sorted(by_name.items()):
            contexts = set()
            for w in writes:
                if w.func.rpartition(".")[-1] == "__init__":
                    continue
                contexts |= _ctx_of(model, w.func)
            if len(contexts) < 2:
                continue
            anchor = min((w for w in writes
                          if w.func.rpartition(".")[-1] != "__init__"),
                         key=lambda w: w.line)
            state = f"{cls}.{name}" if cls else name
            hint = ("held under an anonymous lock — name it: " if any(
                w.held for w in writes) else "")
            yield Finding(
                ctx.relpath, anchor.line, self.rule_id,
                f"{state!r} is written from multiple execution contexts "
                f"({'/'.join(sorted(contexts))}) with no declared guard — "
                f"{hint}declare a concurrency.Guard(protects=({name!r},)) "
                "and hold it at every write", self.severity)
        # arm 3: mutating an imported class's attributes
        for line, origin in model.imported_attr_writes:
            yield Finding(
                ctx.relpath, line, self.rule_id,
                f"assignment to imported class attribute {origin!r} "
                "mutates process-global state every execution context "
                "(and every other user of the class) observes — subclass "
                "it or pass configuration explicitly", self.severity)


class BlockingUnderGuard(_ConcRule):
    """SL020 — (a) blocking operations (subprocess, file IO, sleep,
    ``.result()/.join()/.wait()``) inside a held lock/guard block stall
    every context that needs the guard behind one call's IO — warn tier;
    (b) the lock acquisition-order graph (lexical nesting plus one intra-
    file call hop) must be acyclic — error tier."""

    rule_id = "SL020"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        model = self._model(ctx)
        if model is None:
            return
        for held, what, _func, line in model.lock_block_calls:
            locks = ", ".join(
                (f"{c}.{n}" if c else n) for c, n in held)
            yield Finding(
                ctx.relpath, line, self.rule_id,
                f"blocking call {what!r} while holding guard(s) {locks} — "
                "every other execution context needing the guard stalls "
                "behind this IO; move the call outside the with block",
                SEV_WARN)
        g = _graph(ctx)
        for cyc in g.lock_cycles:
            site = g.cycle_sites.get(cyc)
            if site is None or site[0] != ctx.relpath:
                continue
            names = " -> ".join(
                f"{rel}:{(cls + '.' if cls else '') + name}"
                for rel, cls, name in cyc)
            yield Finding(
                ctx.relpath, site[1], self.rule_id,
                f"lock acquisition-order cycle: {names} -> (back) — two "
                "contexts acquiring in opposite order deadlock; impose "
                "one global order", SEV_ERROR)


class CommitOrdering(_ConcRule):
    """SL021 — inside a journaled verb function (Journal().begin/.commit),
    derived writes must sit in the begin→commit window: no commit before
    begin, no writer call after the commit, no writer call between the
    digest refresh and the commit (fsck would read the rewrite as
    corruption) unless the artifact is digest-skip-listed, and a begin
    must be matched by a commit somewhere in the function."""

    rule_id = "SL021"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        model = self._model(ctx)
        if model is None:
            return
        artifacts = getattr(ctx.project, "artifacts", None)
        for func, ent in sorted(model.journal_funcs.items()):
            begins, commits = ent["begin"], ent["commit"]
            if not begins:
                continue
            begin = min(begins)
            if not commits:
                yield Finding(
                    ctx.relpath, begin, self.rule_id,
                    f"{func} journals begin() but never commit()s — every "
                    "run of this verb replays on `sofa resume` forever; "
                    "commit after the last artifact (and digests) land",
                    self.severity)
                continue
            commit = max(commits)
            for c in commits:
                if c < begin:
                    yield Finding(
                        ctx.relpath, c, self.rule_id,
                        f"{func} commit()s at line {c} before its begin() "
                        f"at line {begin} — the journal window is "
                        "inverted; a crash between them is unrecoverable",
                        self.severity)
            digest = max((d for d in ent["digest"] if d <= commit),
                         default=None)
            for line, name in ent["writes"]:
                if line < begin or line > commit:
                    where = "before begin()" if line < begin \
                        else "after commit()"
                    yield Finding(
                        ctx.relpath, line, self.rule_id,
                        f"derived write{f' of {name!r}' if name else ''} "
                        f"{where} in journaled verb {func} — it is "
                        "outside the begin/commit window, so a crash "
                        "here leaves committed state that does not match "
                        "disk (the `sofa diff` bug class)", self.severity)
                elif digest is not None and digest < line <= commit and \
                        not _skip_listed(artifacts, name):
                    yield Finding(
                        ctx.relpath, line, self.rule_id,
                        f"derived write{f' of {name!r}' if name else ''} "
                        "after the digest refresh but before commit() — "
                        "the committed digests do not cover it; move the "
                        "write before write_digests or skip-list the "
                        "artifact", self.severity)


def _skip_listed(artifacts, name: str) -> bool:
    if not name or artifacts is None or not getattr(artifacts, "ok", False):
        return False
    return name in artifacts.skip_files


class ThreadContextSafety(_ConcRule):
    """SL022 — (a) ``signal.signal``/``os.chdir``/``os.fork`` from a
    function that runs in a thread/worker/handler context (signal
    handlers can only be installed on the main thread; chdir/fork mutate
    or snapshot process state under every other context's feet); (b)
    threads spawned at module import time — in real modules AND in the
    embedded injection templates, linted as virtual modules; (c) check-
    then-act on the ``_derived.writing`` sentinel outside trace.py
    (``derived_writing``/``reap_stale_sentinel`` own the liveness and
    staleness logic a raw exists()/unlink() race skips)."""

    rule_id = "SL022"
    severity = SEV_ERROR
    # trace.py IS the sentinel API; durability's fsck repairs it.
    _SENTINEL_OWNERS = ("trace.py",)

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        model = self._model(ctx)
        if model is None:
            return
        yield from self._check(ctx, model, offset=0, sup=None)
        g = _graph(ctx)
        for name, _line, vm in g.virtuals.get(ctx.relpath, ()):
            yield from self._check(ctx, vm, offset=vm.line_offset,
                                   sup=vm.suppressions, template=name)

    def _check(self, ctx: FileContext, model: _FileModel, offset: int,
               sup, template: str = "") -> Iterable[Finding]:
        tag = f" (in embedded template {template})" if template else ""

        def emit(vline: int, msg: str) -> Iterable[Finding]:
            f = Finding(ctx.relpath, vline + offset, self.rule_id,
                        msg + tag, self.severity)
            if sup is not None:
                shifted = Finding(ctx.relpath, vline, self.rule_id, "")
                if sup.hides(shifted):
                    return
            yield f

        for func, resolved, line in model.unsafe_calls:
            contexts = _ctx_of(model, func) if func else {CTX_MAIN}
            off_main = contexts - {CTX_MAIN}
            if not off_main:
                continue
            yield from emit(
                line,
                f"{resolved}() can run on a non-main execution context "
                f"({'/'.join(sorted(off_main))}) — signal handlers "
                "install only on the main thread, and chdir/fork mutate "
                "process state under every other context")
        for s in model.spawns:
            if s.func == "":
                yield from emit(
                    s.line,
                    f"{s.factory} spawned at module import time — "
                    "importing a module must not start threads (SL022); "
                    "arm it lazily from first use")
        for resolved, line in model.sentinel_races:
            if any(ctx.relpath.endswith(own)
                   for own in self._SENTINEL_OWNERS):
                continue
            yield from emit(
                line,
                f"check-then-act on the {_SENTINEL_LITERAL!r} sentinel "
                f"via {resolved}() — use trace.derived_writing / "
                "reap_stale_sentinel, which own the pid-liveness and "
                "staleness logic a raw file check races")


class ShutdownLiveness(_ConcRule):
    """SL023 — every spawned thread must be reachable from a stop path:
    a ``.join()`` on its binding (attribute join anywhere in the class,
    local join in the spawning function), or ownership transfer by
    returning the thread to the caller.  A daemon flag is NOT a stop
    path — the fleet daemon's threads must be stoppable, not merely
    abandonable.  Real modules only: the injection templates run inside
    the profiled process, whose watchers are daemon-by-contract."""

    rule_id = "SL023"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        model = self._model(ctx)
        if model is None:
            return
        joined_attrs = self._joined_attrs(model)
        for s in model.spawns:
            if s.func == "":
                continue  # module-level spawns are SL022's finding
            if s.binding_kind == "attr":
                if (s.cls, s.binding) in joined_attrs:
                    continue
            elif s.binding_kind == "local":
                if self._local_has_stop(model, s):
                    continue
            where = (f"self.{s.binding}" if s.binding_kind == "attr"
                     else s.binding or "the spawned thread")
            yield Finding(
                ctx.relpath, s.line, self.rule_id,
                f"{s.factory} bound to {where} has no reachable stop "
                "path — no .join() on the binding and no ownership "
                "transfer; a shutdown leaves it running (the fleet-"
                "daemon liveness invariant)", self.severity)

    def _joined_attrs(self, model: _FileModel) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        for node in ast.walk(model.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                recv = node.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self":
                    func = model.func_of.get(id(node), "")
                    out.add((model.class_of.get(func, ""), recv.attr))
        return out

    def _local_has_stop(self, model: _FileModel, s: SpawnSite) -> bool:
        funcdef = model.functions.get(s.func)
        if funcdef is None:
            return False
        for node in ast.walk(funcdef):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                if node.func.attr in ("join", "cancel") and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == s.binding:
                    return True
                # registered into a module-level registry that some code
                # in this module cancels/joins (the faults._TIMERS idiom)
                if node.func.attr == "append" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in model.module_globals and \
                        node.args and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id == s.binding and \
                        self._module_cancels(model):
                    return True
            if isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == s.binding:
                return True  # ownership transferred to the caller
        return False

    @staticmethod
    def _module_cancels(model: _FileModel) -> bool:
        for node in ast.walk(model.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("cancel", "join"):
                return True
        return False


CONCURRENCY_RULES = (
    UndeclaredSharedState,
    BlockingUnderGuard,
    CommitOrdering,
    ThreadContextSafety,
    ShutdownLiveness,
)
