"""Event-driven recording: trigger windowed captures on log keywords.

The reference's sofa-edr polls an application log for hard-coded phase
keywords and runs a timed `sofa record` per phase
(/root/reference/tools/sofa-edr.py:15-45).  Generalized here: any number of
``keyword[=phase_name]`` triggers, each firing one windowed system capture
into ``<logdir>-<phase>/`` while the watched application keeps running.

    python -m sofa_tpu.tools.edr --log train.log \
        --trigger "starting epoch=epoch" --trigger "evaluating=eval" \
        --record_seconds 30 --logdir sofalog/

Each phase fires at most once (re-arm with --rearm).  Pairs naturally with
--xprof_delay_s/--xprof_duration_s for windowed in-process traces.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def parse_trigger(spec: str):
    keyword, _, name = spec.partition("=")
    return keyword, (name or keyword.strip().replace(" ", "_"))


def tail_lines(path: str, pos: int):
    """Read new complete lines past byte offset pos; returns (lines, newpos).

    The file is read in binary and the offset tracked in raw bytes — decoding
    first would mis-count whenever the log contains non-UTF-8 bytes (each
    becomes a 3-byte U+FFFD) and skip real content.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return [], pos
    if size < pos:  # rotated/truncated
        pos = 0
    if size == pos:
        return [], pos
    with open(path, "rb") as f:
        f.seek(pos)
        chunk = f.read()
    last_nl = chunk.rfind(b"\n")
    if last_nl < 0:
        return [], pos
    chunk = chunk[: last_nl + 1]
    return chunk.decode(errors="replace").splitlines(), pos + len(chunk)


def run_edr(argv=None) -> int:
    p = argparse.ArgumentParser(prog="sofa-edr", description=__doc__)
    p.add_argument("--log", required=True, help="application log file to watch")
    p.add_argument("--trigger", action="append", required=True,
                   help='"keyword[=phase_name]", repeatable')
    p.add_argument("--record_seconds", type=float, default=30.0)
    p.add_argument("--logdir", default="sofalog/")
    p.add_argument("--poll_s", type=float, default=1.0)
    p.add_argument("--rearm", action="store_true",
                   help="phases may fire more than once (suffix -2, -3, ...)")
    p.add_argument("--timeout_s", type=float, default=0.0,
                   help="stop watching after this many seconds (0 = forever)")
    args = p.parse_args(argv)

    triggers = [parse_trigger(s) for s in args.trigger]
    fired: dict = {}
    pos = 0
    t0 = time.monotonic()  # watch deadline: immune to wall-clock steps
    print(f"sofa-edr: watching {args.log} for "
          f"{[k for k, _ in triggers]}", flush=True)
    while True:
        if args.timeout_s and time.monotonic() - t0 > args.timeout_s:
            print("sofa-edr: timeout reached", flush=True)
            return 0
        lines, pos = tail_lines(args.log, pos)
        for line in lines:
            for keyword, phase in triggers:
                if keyword not in line:
                    continue
                count = fired.get(phase, 0)
                if count and not args.rearm:
                    continue
                fired[phase] = count + 1
                suffix = phase if count == 0 else f"{phase}-{count + 1}"
                logdir = args.logdir.rstrip("/") + f"-{suffix}/"
                print(f"sofa-edr: trigger {keyword!r} -> recording "
                      f"{args.record_seconds:.0f}s into {logdir}", flush=True)
                # Timed system-wide capture while the app keeps running,
                # like the reference's per-phase timed record.  Bounded:
                # the capture is record_seconds long by construction, so a
                # generous grace past that means record wedged (dead
                # tunnel, stuck epilogue) and EDR must keep watching.
                try:
                    subprocess.run(
                        [sys.executable, "-m", "sofa_tpu", "record",
                         f"sleep {args.record_seconds}", "--logdir", logdir],
                        timeout=args.record_seconds + 300,
                    )
                except subprocess.TimeoutExpired:
                    print(f"sofa-edr: record of {logdir} exceeded "
                          f"{args.record_seconds + 300:.0f}s — killed; "
                          "resuming watch", flush=True)
        if all(phase in fired for _, phase in triggers) and not args.rearm:
            print("sofa-edr: all phases captured", flush=True)
            return 0
        time.sleep(args.poll_s)


if __name__ == "__main__":
    sys.exit(run_edr())
