"""Fleet tier observability plane tests (sofa_tpu/metrics.py,
docs/FLEET.md "Observing the tier").

The contracts under test: fixed-bucket histogram percentile math
against exact values, the flat snapshot vocabulary the SLO grammar
names, cross-process push tracing (one X-Sofa-Trace id spans the
committing service process AND a separate WAL-drain process, merged
Perfetto-valid by export_fleet_trace), scrape-history persistence as a
deterministic chunk store, the authenticated /v1/metrics endpoint
(401 / ETag-304 on idle / pagination / bad params), SLO parsing and
typed breach verdicts, breach events in the archive catalog,
`sofa status --fleet` exiting nonzero while breaching, the
slo_breach/scrape_stall fault kinds, and the tier board contract.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sofa_tpu import durability, faults, telemetry
from sofa_tpu import metrics
from sofa_tpu.agent import sofa_agent
from sofa_tpu.archive import catalog as acat
from sofa_tpu.archive import tier
from sofa_tpu.archive.service import sofa_serve
from sofa_tpu.config import SofaConfig
from sofa_tpu.metrics import (
    BUCKETS_MS,
    Histogram,
    MetricsRegistry,
    Scraper,
    evaluate_slo,
    metrics_doc,
    metrics_summary,
    parse_slo,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "tier-metrics-token"


def _load_manifest_check():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(REPO, "tools",
                                       "manifest_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)
    return mc


def _mklog(root, name="run1"):
    logdir = os.path.join(str(root), name) + "/"
    os.makedirs(logdir, exist_ok=True)
    with open(logdir + "sofa_time.txt", "w") as f:
        f.write("123.0\n")
    with open(logdir + "features.csv", "w") as f:
        f.write("name,value\nelapsed_time,1.5\n")
    tel = telemetry.begin("analyze")
    tel.write(logdir, rc=0)
    telemetry.end(tel)
    durability.write_digests(logdir)
    return logdir


def _agent_cfg(tmp_path, url, **kw):
    kw.setdefault("serve_token", TOKEN)
    kw.setdefault("agent_service", url)
    kw.setdefault("agent_spool", str(tmp_path / "spool"))
    kw.setdefault("agent_settle_s", 0.0)
    kw.setdefault("agent_retries", 4)
    kw.setdefault("agent_backoff_s", 0.01)
    kw.setdefault("agent_backoff_cap_s", 0.05)
    return SofaConfig(logdir=str(tmp_path / "unused"), **kw)


def _wait_for(pred, timeout_s=15.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(0.02)
    pytest.fail(f"timed out after {timeout_s}s waiting for {what}")


@pytest.fixture
def primary(tmp_path, monkeypatch):
    """In-process single-worker primary with the background scrape
    thread STOPPED: tests drive `httpd.scraper.tick()` themselves so
    every window is deterministic."""
    monkeypatch.setattr(tier, "REFRESH_MIN_INTERVAL_S", 0.05)
    cfg = SofaConfig(logdir=str(tmp_path / "unused_srv"),
                     serve_token=TOKEN, serve_port=0)
    httpd = sofa_serve(cfg, root=str(tmp_path / "store"),
                       serve_forever=False)
    assert httpd is not None
    httpd.scraper.close()
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


def _url(httpd):
    return f"http://127.0.0.1:{httpd.server_address[1]}"


def _get(url, headers=None, token=TOKEN):
    hdr = {}
    if token is not None:
        hdr["Authorization"] = f"Bearer {token}"
    hdr.update(headers or {})
    req = urllib.request.Request(url, headers=hdr)
    try:
        with urllib.request.urlopen(req, timeout=10.0) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ---------------------------------------------------------------------------
# Histogram math.
# ---------------------------------------------------------------------------

def _bucket_bounds(value):
    lo = 0.0
    for hi in BUCKETS_MS:
        if value <= hi:
            return lo, hi
        lo = hi
    return lo, BUCKETS_MS[-1]


def test_histogram_percentiles_bracket_exact():
    """Fixed buckets cannot beat their own resolution, but the estimate
    must land inside the bucket that holds the exact percentile."""
    import random

    rng = random.Random(7)
    values = [rng.uniform(0.5, 400.0) for _ in range(2000)]
    h = Histogram()
    for v in values:
        h.observe(v)
    assert h.count == len(values)
    s = sorted(values)
    for p in (50.0, 90.0, 99.0):
        exact = s[min(int(p / 100.0 * len(s)), len(s) - 1)]
        lo, hi = _bucket_bounds(exact)
        got = h.percentile(p)
        assert lo <= got <= hi, (p, exact, got)


def test_histogram_empty_and_open_bucket():
    h = Histogram()
    assert h.percentile(99.0) == 0.0
    h.observe(10 ** 9)  # lands in the open-ended last bucket
    # honest saturation: the open bucket answers its lower bound
    assert h.percentile(99.0) == BUCKETS_MS[-2]


def test_snapshot_vocabulary():
    """Counters -> _total/_rps, histograms -> _p50_ms/_p99_ms/_count,
    gauges verbatim — the names the SLO grammar targets."""
    reg = MetricsRegistry("/nonexistent-metrics-root", worker=3)
    reg.inc("pushes", 2)
    reg.observe("push", 7.0)
    reg.set_gauge("wal_depth", 4)
    flat, hists = reg.snapshot()
    assert flat["pushes_total"] == 2.0
    assert flat["wal_depth"] == 4
    assert flat["push_count"] == 1.0
    lo, hi = _bucket_bounds(7.0)
    assert lo <= flat["push_p99_ms"] <= hi
    assert hists["push"]["count"] == 1


# ---------------------------------------------------------------------------
# SLO parsing and evaluation.
# ---------------------------------------------------------------------------

def test_parse_slo_grammar():
    targets = parse_slo("push_p99_ms<50,wal_depth<=1000,replica_behind<3")
    assert [(t.name, t.op, t.value) for t in targets] == [
        ("push_p99_ms", "<", 50.0), ("wal_depth", "<=", 1000.0),
        ("replica_behind", "<", 3.0)]
    assert parse_slo("") == ()
    for bad in ("push_p99_ms", "push_p99_ms<", "<5", "a=5",
                "push_p99_ms<abc", "Push<5"):
        with pytest.raises(ValueError):
            parse_slo(bad)


def test_evaluate_slo_breach_and_no_data():
    targets = parse_slo("push_p99_ms<50,wal_depth<10")
    v = evaluate_slo(targets, {"push_p99_ms": 80.0, "wal_depth": 3.0}, 1)
    assert v["schema"] == metrics.SLO_SCHEMA
    assert v["ok"] is False
    assert v["breaching"] == ["push_p99_ms"]
    by = {t["name"]: t for t in v["targets"]}
    assert by["push_p99_ms"]["status"] == "breach"
    assert by["wal_depth"]["status"] == "ok"
    # a metric with no samples yet is no_data, which does NOT breach
    v2 = evaluate_slo(targets, {"wal_depth": 3.0}, 2)
    assert v2["ok"] is True
    assert {t["name"]: t["status"] for t in v2["targets"]} == {
        "push_p99_ms": "no_data", "wal_depth": "ok"}


def test_slo_verdict_roundtrip_and_validators(tmp_path):
    mc = _load_manifest_check()
    root = str(tmp_path)
    targets = parse_slo("wal_depth<10")
    ok = evaluate_slo(targets, {"wal_depth": 3.0}, 1)
    metrics.write_slo_verdict(root, ok)
    loaded = metrics.load_slo_verdict(root)
    assert loaded is not None and loaded["ok"] is True
    assert mc.validate_slo_verdict(loaded) == []
    breach = evaluate_slo(targets, {"wal_depth": 99.0}, 2)
    assert mc.validate_slo_verdict(breach) == []
    # gate mode: a breaching verdict fails --require-healthy
    assert any("breach" in p.lower() for p in
               mc.validate_slo_verdict(breach, require_passing=True))
    # inconsistent ok-vs-breached-names is flagged
    assert mc.validate_slo_verdict(dict(breach, ok=True))


def test_scraper_evaluates_slo_and_appends_breach_event(tmp_path):
    root = str(tmp_path / "fleetroot")
    os.makedirs(os.path.join(root, "tenants", "default"))
    reg = metrics.for_root(root, worker=0)
    reg.observe("push", 80.0)
    scraper = Scraper(reg, slo_targets=parse_slo("push_p99_ms<50"),
                      role="primary")
    verdict = scraper.tick()
    assert verdict is not None and verdict["ok"] is False
    assert metrics.load_slo_verdict(root)["breaching"] == ["push_p99_ms"]
    events = [e for e in acat.read_catalog(
        os.path.join(root, "tenants", "default"))
        if e.get("ev") == "slo_breach"]
    assert len(events) == 1
    assert events[0]["metric"] == "push_p99_ms"
    assert events[0]["op"] == "<" and events[0]["threshold"] == 50.0
    # a PERSISTING breach is one fact, not one event per window
    scraper.tick()
    events2 = [e for e in acat.read_catalog(
        os.path.join(root, "tenants", "default"))
        if e.get("ev") == "slo_breach"]
    assert len(events2) == 1
    # the regress feed still parses the catalog cleanly around events
    assert acat.ingest_entries(acat.read_catalog(
        os.path.join(root, "tenants", "default"))) == []


# ---------------------------------------------------------------------------
# Fault kinds.
# ---------------------------------------------------------------------------

def test_slo_breach_fault_fires_once(tmp_path):
    reg = MetricsRegistry(str(tmp_path / "faultroot"), worker=0)
    scraper = Scraper(reg)
    old = faults._PLAN
    faults._PLAN = faults.parse("service:slo_breach@1")
    try:
        v = scraper.tick()
        assert v is not None and v["ok"] is False
        assert "injected_fault" in v["breaching"]
        assert scraper.tick() is None  # fires once, not per window
    finally:
        faults._PLAN = old


def test_scrape_stall_fault_freezes_window(tmp_path):
    reg = MetricsRegistry(str(tmp_path / "stallroot"), worker=0)
    scraper = Scraper(reg)
    old = faults._PLAN
    faults._PLAN = faults.parse("service:scrape_stall")
    try:
        assert scraper.tick() is None
        assert reg.scrape_seq == 0  # the window never committed
    finally:
        faults._PLAN = old
    scraper.tick()
    assert reg.scrape_seq == 1


# ---------------------------------------------------------------------------
# History persistence.
# ---------------------------------------------------------------------------

def test_history_persist_deterministic(tmp_path):
    """The persisted history store is a pure function of the rows —
    byte-identical across independent scrapes of the same windows (the
    same discipline that makes preprocess --jobs 1 == --jobs 4)."""
    from sofa_tpu import frames

    if not frames.columnar_available():
        pytest.skip("pyarrow not available")
    trees = []
    for sub in ("a", "b"):
        root = str(tmp_path / sub)
        reg = MetricsRegistry(root, worker=1)
        for i in range(5):
            reg.record_window(1700000000.0 + i, {"wal_depth": float(i)})
        assert reg.persist_history() is not None
        sdir = os.path.join(root, "_metrics", "worker001")
        tree = {}
        for dirpath, _d, names in os.walk(sdir):
            for n in sorted(names):
                with open(os.path.join(dirpath, n), "rb") as f:
                    tree[os.path.relpath(os.path.join(dirpath, n),
                                         sdir)] = f.read()
        assert frames.verify_chunk_store(sdir, "m") == []
        trees.append(tree)
    assert trees[0] == trees[1]


def test_record_window_idle_appends_nothing():
    reg = MetricsRegistry("/nonexistent-idle-root", worker=0)
    reg.record_window(1700000000.0, {"wal_depth": 1.0})
    rows, total = reg.history_rows()
    assert total == 1
    reg.record_window(1700000002.0, {"wal_depth": 1.0})  # unchanged
    rows, total = reg.history_rows()
    assert total == 1
    reg.record_window(1700000004.0, {"wal_depth": 2.0})
    rows, total = reg.history_rows()
    assert total == 2
    assert rows[-1] == [1700000004.0, "wal_depth", 2.0]


# ---------------------------------------------------------------------------
# Cross-process push tracing.
# ---------------------------------------------------------------------------

_DRAIN_SNIPPET = """
import sys
sys.path.insert(0, {repo!r})
from sofa_tpu.archive import tier
from sofa_tpu import metrics
stats = tier.drain_tenant({troot!r}, refresh=True)
assert stats["applied"] == 1, stats
metrics.for_tenant_root({troot!r}).flush_trace()
"""


def test_trace_id_spans_wal_replay_across_processes(tmp_path):
    """One trace id: the committing process's spans and a SEPARATE
    drain process's replay spans merge into one Perfetto-valid fleet
    trace under the same id — the WAL record is the carrier."""
    root = str(tmp_path / "fleetroot")
    troot = os.path.join(root, "tenants", "default")
    os.makedirs(troot)
    trace = "feedc0de12345678"
    reg = metrics.for_root(root, worker=0)
    # the service leg: commit span + the WAL record carrying the id
    t0 = time.time()
    reg.span("commit", "service", t0, 0.002, trace=trace, run="ab" * 32)
    app = tier.WalAppender(troot, worker=0)
    app.append({"run": "ab" * 32, "t": round(t0, 3), "logdir": "/j/",
                "hostname": "h", "label": "", "tenant": "default",
                "files": {}, "features": {"elapsed_time": 1.0},
                "trace": trace})
    assert reg.flush_trace() is not None
    # the drain leg runs in ANOTHER process — the trace id must cross
    subprocess.run(
        [sys.executable, "-c",
         _DRAIN_SNIPPET.format(repo=REPO, troot=troot)],
        check=True, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=120)
    doc = metrics.export_fleet_trace(root)
    assert doc is not None
    path = os.path.join(root, "_metrics", "fleet_trace",
                        metrics.FLEET_TRACE_NAME)
    on_disk = json.load(open(path))
    assert on_disk["traceEvents"] == doc["traceEvents"]
    # Perfetto validity: X events carry int ts/dur >= 0, pid/tid, name
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    for e in spans:
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        assert isinstance(e["dur"], int) and e["dur"] >= 1
        assert e["name"] and "pid" in e and "tid" in e
    mine = [e for e in spans
            if (e.get("args") or {}).get("trace") == trace]
    names = {e["name"] for e in mine}
    assert "commit" in names, names
    assert "wal_apply" in names, names
    # genuinely cross-process: the joined spans come from >= 2 pids
    assert len({e["pid"] for e in mine}) >= 2


def test_fleet_load_push_traceable_end_to_end(primary, tmp_path):
    """The acceptance walk: one fleet_load-style push with a known
    X-Sofa-Trace id is followable in the exported fleet trace."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import fleet_load
    finally:
        sys.path.pop(0)
    url = _url(primary)
    trace = "abad1dea00000001"
    conn = fleet_load._Conn(url, TOKEN)
    committed, _ms = fleet_load._push_run(
        conn, "default", {"features.csv": b"name,value\nx,1\n"},
        trace=trace)
    assert committed
    troot = os.path.join(primary.root, "tenants", "default")
    _wait_for(lambda: tier.wal_depth(troot) == 0, what="WAL drain")
    reg = metrics.for_root(primary.root)
    reg.flush_trace()
    doc = metrics.export_fleet_trace(primary.root)
    assert doc is not None
    mine = [e for e in doc["traceEvents"] if e.get("ph") == "X"
            and (e.get("args") or {}).get("trace") == trace]
    names = {e["name"] for e in mine}
    assert {"have", "commit", "wal_apply"} <= names, names


# ---------------------------------------------------------------------------
# GET /v1/metrics.
# ---------------------------------------------------------------------------

def test_metrics_endpoint_auth_and_etag(primary):
    url = _url(primary) + "/v1/metrics"
    code, _h, _b = _get(url, token="wrong")
    assert code == 401
    status, hdr, body = _get(url)
    assert status == 200
    doc = json.loads(body)
    assert doc["schema"] == metrics.METRICS_SCHEMA
    assert doc["version"] == metrics.METRICS_VERSION
    etag = hdr["ETag"]
    assert etag.startswith('"met-')
    # idle tier: the poll costs a 304, not a payload
    code, hdr304, body304 = _get(url, {"If-None-Match": etag})
    assert code == 304
    assert hdr304["ETag"] == etag
    assert not body304
    # activity moves the tag
    reg = metrics.for_root(primary.root)
    reg.inc("pushes")
    _status, hdr2, _body = _get(url)
    assert hdr2["ETag"] != etag


def test_metrics_endpoint_pagination_and_params(primary):
    reg = metrics.for_root(primary.root)
    for i in range(6):
        reg.record_window(time.time() - 5 + i, {"wal_depth": float(i)})
    base = _url(primary) + "/v1/metrics"
    _s, _h, body = _get(base + "?offset=2&limit=2")
    doc = json.loads(body)
    assert doc["history"]["total"] == 6
    assert doc["history"]["offset"] == 2
    assert [r["value"] for r in doc["history"]["rows"]] == [2.0, 3.0]
    assert [r["name"] for r in doc["history"]["rows"]] == \
        ["wal_depth", "wal_depth"]
    # the window filter bounds by age
    _s, _h, body = _get(base + "?window=1000000")
    assert json.loads(body)["history"]["total"] == 6
    for bad in ("?offset=-1", "?limit=x", "?window=0"):
        code, _h, _b = _get(base + bad)
        assert code == 400, bad
    mc = _load_manifest_check()
    assert mc.validate_fleet_metrics(doc) == []


def test_commit_ack_and_tier_carry_metrics_summary(primary, tmp_path):
    logdir = _mklog(tmp_path)
    rc = sofa_agent(_agent_cfg(tmp_path, _url(primary)),
                    watch=str(tmp_path), once=True)
    assert rc == 0
    doc = telemetry.load_manifest(logdir)
    mm = (doc.get("meta") or {}).get("metrics")
    assert isinstance(mm, dict)
    assert mm["trace"] == doc["meta"]["agent"]["push"]["trace"]
    assert len(mm["trace"]) == 16
    mc = _load_manifest_check()
    assert mc.validate_manifest(doc) == []
    _s, _h, body = _get(_url(primary) + "/v1/tier")
    tdoc = json.loads(body)
    assert isinstance(tdoc.get("metrics"), dict)
    summary = metrics_summary(metrics.for_root(primary.root))
    assert summary.get("push_p99_ms") is not None


def test_stale_scrape_and_breach_manifest_warnings():
    doc = {"meta": {"metrics": {"scrape_age_s": 120.0}}}
    assert any("scrape" in w for w in telemetry.manifest_warnings(doc))
    doc = {"meta": {"metrics": {"scrape_age_s": 1.0}}}
    assert not any("scrape" in w
                   for w in telemetry.manifest_warnings(doc))
    doc = {"meta": {"slo": {"ok": False,
                            "breaching": ["push_p99_ms"]}}}
    assert any("push_p99_ms" in w
               for w in telemetry.manifest_warnings(doc))


# ---------------------------------------------------------------------------
# --slo wiring and sofa status --fleet.
# ---------------------------------------------------------------------------

def test_serve_rejects_bad_slo_spec(tmp_path):
    cfg = SofaConfig(logdir=str(tmp_path / "u"), serve_token=TOKEN,
                     serve_port=0, serve_slo="push_p99_ms<<50")
    assert sofa_serve(cfg, root=str(tmp_path / "store"),
                      serve_forever=True) == 2


def test_status_fleet_exit_codes_on_breach(primary, tmp_path, capsys):
    cfg = SofaConfig(logdir=str(tmp_path / "u"), serve_token=TOKEN,
                     status_fleet=_url(primary))
    assert tier.sofa_fleet_status(cfg) == 0
    reg = metrics.for_root(primary.root)
    verdict = evaluate_slo(parse_slo("wal_depth<0"),
                           {"wal_depth": 5.0}, 1)
    assert verdict["ok"] is False
    reg.update_slo(verdict)
    assert tier.sofa_fleet_status(cfg) == 1
    out = capsys.readouterr()
    assert "wal_depth" in out.out + out.err
    # recovery: a passing verdict clears the exit code
    reg.update_slo(evaluate_slo(parse_slo("wal_depth<10"),
                                {"wal_depth": 5.0}, 2))
    assert tier.sofa_fleet_status(cfg) == 0


# ---------------------------------------------------------------------------
# Kill switch.
# ---------------------------------------------------------------------------

def test_metrics_kill_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("SOFA_TIER_METRICS", "0")
    root = str(tmp_path / "off")
    reg = MetricsRegistry(root, worker=0)
    reg.span("commit", "service", time.time(), 0.01, trace="aa" * 8)
    assert reg.flush_trace() is None
    assert Scraper(reg).tick() is None
    assert reg.scrape_seq == 0
    assert not os.path.isdir(os.path.join(root, "_metrics"))


# ---------------------------------------------------------------------------
# The tier board contract.
# ---------------------------------------------------------------------------

def test_tier_board_contract():
    board = os.path.join(REPO, "sofa_tpu", "board")
    with open(os.path.join(board, "tier.html")) as f:
        page = f.read()
    # the page speaks the endpoint's actual protocol
    assert "/v1/metrics" in page
    assert "If-None-Match" in page and "304" in page
    assert "Authorization" in page and "Bearer" in page
    assert "breaching" in page  # the breach banner names metrics
    # nav closure: every board page links Tier, and Tier links back
    pages = sorted(n for n in os.listdir(board) if n.endswith(".html"))
    for name in pages:
        with open(os.path.join(board, name)) as f:
            src = f.read()
        assert 'href="tier.html"' in src, f"{name} misses the Tier link"
        if name != "tier.html":
            assert f'href="{name}"' in page, f"Tier misses {name}"
