"""Parsers turning raw collector output into unified-schema DataFrames.

One module per source (the reference concentrates all of this in the 2106-line
sofa_preprocess.py; see SURVEY §2.4 for the per-parser map).  Every parser is
a pure function ``text/path -> DataFrame`` so fixtures can test it without
running collectors.

Corruption contract: a parser that can positively identify a truncated or
corrupt raw file raises :class:`CorruptRawError` (never for a merely-empty
or absent file — those are normal degradations).  Preprocess reacts by
quarantining the file to ``<logdir>/_quarantine/`` and recording the source
as ``quarantined`` in the run manifest; see docs/ROBUSTNESS.md.
"""

from __future__ import annotations


class CorruptRawError(ValueError):
    """A raw collector file is positively corrupt (not merely absent/empty).

    Carries the on-disk ``path`` so preprocess can quarantine the file.
    args stay ``(path, reason)`` so the exception survives a process-pool
    pickle round-trip with its attributes intact.
    """

    def __init__(self, path: str, reason: str):
        super().__init__(path, reason)
        self.path = path
        self.reason = reason

    def __str__(self) -> str:
        return f"{self.path}: {self.reason}"
