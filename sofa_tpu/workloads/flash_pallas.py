"""Pallas TPU flash attention — the fused local-attention kernel.

The transformer workload's per-chip attention (plain_causal_attention and
each ring-attention hop) materializes the [B,H,Tq,Tk] score matrix in HBM;
this kernel keeps the online-softmax recurrence in VMEM so scores never
leave the chip.  Grid = (batch*head, q-block, k-block) with the k dimension
innermost ("arbitrary" semantics): K/V stream through VMEM one block at a
time while the running (acc, m, l) state lives in VMEM scratch, so per-chip
sequence length is bounded by HBM, not the ~16 MB VMEM — f32 accumulation,
MXU matmuls via jnp.dot(preferred_element_type=f32).

Layout notes (see /opt/skills/guides/pallas_guide.md): last dim = head_dim
rides the 128-lane axis; q/k blocks default to 128 rows (MXU tile); the m/l
softmax state is kept lane-broadcast at [block_q, 128] so every scratch
buffer respects the (8, 128) f32 tile.

Falls back to the interpreter off-TPU so numerics are testable anywhere
(tests/test_workloads.py compares against the reference lax implementation).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sofa_tpu.workloads.compat import tpu_compiler_params
from sofa_tpu.workloads.ring_attention import NEG_INF


def _flash_kernel(shift_ref, *refs, block_q: int, block_k: int, num_k: int,
                  scale: float, segmented: bool = False):
    # shift_ref: [1] int32 in SMEM — the causal offset: key j is visible to
    #   query i iff j <= i + shift.  shift=0 is aligned causal attention,
    #   shift>=T sees everything (non-causal), shift<=-block sees nothing
    #   (the kernel still runs and emits out=0, lse~NEG_INF).  A *dynamic*
    #   shift lets one compiled kernel serve every hop of ring attention,
    #   where the visiting K/V block's global offset is a traced value.
    # q_ref: [1, block_q, D]; k_ref, v_ref: [1, block_k, D] (streamed per ik)
    # segmented adds sq/sk refs ([1, block] int32 rows of the per-BATCH
    #   segment ids): keys in a different segment are masked like
    #   out-of-causal keys — packed-sequence training.
    # o_ref: [1, block_q, D]; lse_ref: [1, 8, block_q] (sublane-broadcast so
    # the block satisfies TPU (8, 128) tiling)
    # scratch: acc [block_q, D] f32; m, l [block_q, 128] f32 lane-broadcast
    if segmented:
        (q_ref, k_ref, v_ref, sq_ref, sk_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    shift = shift_ref[0]

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Blocks past the frontier (every key strictly after the last visible
    # position for this q-block) contribute nothing — skip their compute.
    contributes = ik * block_k <= iq * block_q + block_q - 1 + shift

    @pl.when(contributes)
    def _step():
        # Inputs stay in their storage dtype through the matmuls: casting
        # to f32 first forced the MXU into f32 mode (~4x slower than native
        # bf16 with f32 accumulation).  The scale moves after the dot —
        # same math, f32 from there on.
        q = q_ref[0]                                     # [bq, D]
        k = k_ref[0]                                     # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        masked = k_pos > q_pos + shift
        if segmented:
            masked = masked | (sq_ref[0][:, None] != sk_ref[0][None, :])
        s = jnp.where(masked, NEG_INF, s)
        m_prev = m_ref[:, :1]                            # [bq, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        # Clamp the softmax reference: a row with every key masked so far
        # keeps m ~ NEG_INF, and exp(s - m) would be exp(0)=1 garbage
        # instead of 0.  Clamped, exp(NEG_INF - (-1e29)) underflows to 0, so
        # fully-masked rows accumulate nothing and emit lse ~ -1e29.
        m_new = jnp.maximum(jnp.maximum(m_prev, m_blk), -1e29)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                           # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p rounds to the storage dtype for the second MXU matmul (the
        # standard flash-attention trade: ~1e-3 relative error on bf16
        # inputs, full f32 path preserved for f32 inputs).
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse = (m_ref[:, 0] + jnp.log(l[:, 0]))           # [bq]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, block_q))


def pick_block(n: int, cap: int = 512, head_dim: int = 128) -> Optional[int]:
    """Largest power-of-two block size <= cap (>= 16) that divides n.

    512 is the measured sweet spot on v5e AT d=128 — the on-chip sweep
    (tools/tune_flash.py, 2026-07-31) put 512x512 blocks at 16.8 ms for a
    16k-token forward vs 51.8 ms at the old 128x128 default — while
    smaller powers of two keep every 16-multiple sequence length (the
    sublane constraint) supported.  The sweep only measured d=128; larger
    head dims grow the q/k/v tiles (and the backward's accumulators)
    linearly in d, so the cap halves per doubling of head_dim past 128 to
    stay inside VMEM instead of failing Mosaic compilation loudly with no
    fallback.
    """
    while head_dim > 128 and cap > 128:
        head_dim //= 2
        cap //= 2
    b = cap
    while b >= 16:
        if n % b == 0:
            return b
        b //= 2
    return None


def _to_planes(x):
    """[B, T, H', D] -> [B*H', T, D]: one contiguous (T, D) plane per head —
    the layout every kernel grid row indexes (forward and backward must
    agree on it, so it lives here once)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _kv_plane(i, h: int, kvh: int):
    """K/V plane serving query plane-row ``i``: grid row i = batch * H +
    query head; its compact KV head is shared by the whole query group."""
    return (i // h) * kvh + (i % h) // (h // kvh)


def _normalize_segments(segment_ids, kv_segment_ids, b, t, tk):
    """(seg_q [B,T] i32, seg_kv [B,Tk] i32) or (None, None); the one
    shape-validation point for forward AND backward — a [B,T] default
    silently indexing past a longer kv side would corrupt results."""
    if segment_ids is None:
        if kv_segment_ids is not None:
            raise ValueError("kv_segment_ids given without segment_ids")
        return None, None
    kv = segment_ids if kv_segment_ids is None else kv_segment_ids
    if segment_ids.shape != (b, t) or kv.shape != (b, tk):
        raise ValueError(f"segment ids must be [B, T]/[B, Tk] = "
                         f"({b}, {t})/({b}, {tk}); got "
                         f"{segment_ids.shape}/{kv.shape}")
    return segment_ids.astype(jnp.int32), kv.astype(jnp.int32)


def _check_static_shift(static_causal: bool, shift) -> None:
    """static_causal index-map clamps assume shift <= 0 at trace time; a
    traced or positive shift under them silently fetches the wrong blocks
    (the in-kernel masks honor shift, the clamps don't) — make that a
    trace-time error instead of wrong numbers."""
    if not static_causal:
        return
    if isinstance(shift, jax.core.Tracer):
        raise ValueError("static_causal=True needs a compile-time shift; "
                         "pass static_causal=False for traced (ring-hop) "
                         "shifts")
    if int(shift) > 0:
        raise ValueError(f"static_causal=True promises shift <= 0, got "
                         f"{int(shift)}; pass static_causal=False")


def _flash_forward(
    q, k, v,
    shift,
    block_q: Optional[int],
    block_k: Optional[int],
    interpret: Optional[bool],
    static_causal: bool = False,
    segment_ids=None,
    kv_segment_ids=None,
):
    """Runs the kernel; returns (out [B,T,H,D], lse [B,H,T]).

    ``shift`` is the (possibly traced) causal offset: key j visible to query
    i iff j <= i + shift.  0 = aligned causal, >= T = full attention,
    <= -T = fully masked (out 0, lse ~ NEG_INF).

    ``block_q``/``block_k`` = None picks the measured-best size that fits
    the sequence (pick_block).

    ``static_causal`` promises shift <= 0 at trace time.  Then no k-block
    past the q-block's diagonal can ever contribute, so the K/V index maps
    clamp to the diagonal: skipped iterations re-request the previous
    block, and the Pallas pipeline elides the copy — the upper-triangle
    half of K/V HBM traffic disappears.  Must stay False for ring hops,
    whose traced shift can be positive.

    GQA is native: k/v may carry KVH < H heads (H % KVH == 0) and each K/V
    plane serves its whole query-head group straight from HBM — the
    [B,T,H,D] repeat the unfused path materializes never exists here.
    """
    b, t, h, d = q.shape
    tk, kvh = k.shape[1], k.shape[2]
    if h % kvh:
        raise ValueError(f"query heads {h} not a multiple of kv heads {kvh}")
    group = h // kvh
    block_q = pick_block(t, head_dim=d) if block_q is None \
        else min(block_q, t)
    block_k = pick_block(tk, head_dim=d) if block_k is None \
        else min(block_k, tk)
    if not block_q or not block_k or t % block_q or tk % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq lens ({t}, {tk})")
    _check_static_shift(static_causal, shift)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = d ** -0.5
    num_k = tk // block_k
    shift = jnp.asarray(shift, jnp.int32).reshape(1)

    qp, kp, vp = _to_planes(q), _to_planes(k), _to_planes(v)
    segment_ids, kv_segment_ids = _normalize_segments(
        segment_ids, kv_segment_ids, b, t, tk)
    segmented = segment_ids is not None
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, num_k=num_k,
        scale=scale, segmented=segmented)
    # XLA's cost model cannot see inside a Mosaic kernel: without this the
    # trace reports flops=0/bytes=0 for exactly the hottest op and the
    # roofline/top-ops passes undercount it (observed on the real v2
    # fixture).  Causal halves the work when promised at trace time; a
    # dynamic ring-hop shift can be anything, so it reports the full-block
    # upper bound.  bytes = operand + result HBM traffic (causal elision
    # makes it an upper bound too).
    frac = 0.5 if static_causal else 1.0
    cost = pl.CostEstimate(
        flops=int(4 * b * h * t * tk * d * frac),
        transcendentals=int(b * h * t * tk * frac),
        bytes_accessed=int(qp.size * qp.dtype.itemsize * 2
                           + (kp.size + vp.size) * kp.dtype.itemsize
                           + b * h * t * 4))

    if static_causal:
        def kv_index(bh, iq, ik):
            last = (iq * block_q + block_q - 1) // block_k
            return (_kv_plane(bh, h, kvh), jnp.minimum(ik, last), 0)
    else:
        def kv_index(bh, iq, ik):
            return (_kv_plane(bh, h, kvh), ik, 0)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    inputs = [shift, qp, kp, vp]
    if segmented:
        # per-BATCH rows (no per-head copy): index maps divide the plane
        # row back down to its batch; the k-side map reuses kv_index's
        # block clamp so segment rows stream with their K/V blocks
        in_specs += [
            pl.BlockSpec((1, block_q),
                         lambda bh, iq, ik: (bh // h, iq)),
            pl.BlockSpec((1, block_k),
                         lambda bh, iq, ik: (bh // h, kv_index(bh, iq, ik)[1])),
        ]
        inputs += [segment_ids, kv_segment_ids]
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q, num_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh, iq, ik: (bh, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=cost,
        name="sofa_flash_fwd",
        interpret=interpret,
    )(*inputs)
    return (out.reshape(b, h, t, d).transpose(0, 2, 1, 3),
            lse[:, 0, :].reshape(b, h, t))


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(
    q, k, v,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids=None,
    kv_segment_ids=None,
):
    """Fused attention: q [B, T, H, D]; k/v may carry KVH <= H heads
    (GQA runs natively in the kernel — no repeat materialized).

    ``segment_ids`` [B, T] int masks cross-segment pairs on top of the
    causal rule — packed-sequence training; ``kv_segment_ids`` defaults to
    the same array (self-attention)."""
    shift = 0 if causal else k.shape[1]
    return _flash_forward(q, k, v, shift, block_q, block_k, interpret,
                          static_causal=causal, segment_ids=segment_ids,
                          kv_segment_ids=kv_segment_ids)[0]


def supports(t: int, block: int = 512) -> bool:
    """True when a [.., T, ..] attention can run through the fused kernel.

    Some power-of-two block >= 16 (the sublane multiple for bf16/f32) and
    <= ``block`` must divide T — i.e. any 16-multiple sequence length.
    """
    return pick_block(t, block) is not None


@jax.custom_vjp
def flash_causal_attention(q, k, v):
    """Differentiable fused causal attention, [B, T, H, D] in and out.

    Forward runs the Pallas kernel and keeps only O(B·H·T) residuals (the
    output and per-row logsumexp) — the FlashAttention recipe.  Backward is
    an explicit blockwise gradient (one scan over k-blocks, probabilities
    recomputed per block from the saved lse) in stock lax ops, so the
    [T, T] score matrix never materializes in either direction and XLA
    still fuses everything onto the MXU.
    """
    out, _ = _flash_forward(q, k, v, 0, None, None, None, static_causal=True)
    return out


def _fwd(q, k, v):
    out, lse = _flash_forward(q, k, v, 0, None, None, None,
                              static_causal=True)
    return out, (q, k, v, out, lse)


@jax.custom_vjp
def flash_causal_segmented_attention(q, k, v, segment_ids):
    """Differentiable fused causal attention over PACKED sequences:
    [B, T, H, D] with segment_ids [B, T] — tokens attend causally within
    their own segment only.  Same kernels, fwd and bwd, with the segment
    mask fused in; GQA-native like the unsegmented wrapper.  Masking is
    pure id equality: ids should be contiguous runs (the standard packed
    layout) — a reused id attends across both of its runs."""
    out, _ = _flash_forward(q, k, v, 0, None, None, None,
                            static_causal=True, segment_ids=segment_ids)
    return out


def _seg_fwd(q, k, v, segment_ids):
    out, lse = _flash_forward(q, k, v, 0, None, None, None,
                              static_causal=True, segment_ids=segment_ids)
    return out, (q, k, v, segment_ids, out, lse)


def _seg_bwd(res, g):
    import numpy as np

    q, k, v, seg, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, g, out, lse, segment_ids=seg)
    # integer primal -> float0 cotangent (jax's "no gradient" sentinel)
    dseg = np.zeros(seg.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg


flash_causal_segmented_attention.defvjp(_seg_fwd, _seg_bwd)


def _bwd_kv_kernel(shift_ref, *refs,
                   block_q: int, block_k: int, num_q: int,
                   num_inner: int, scale: float, segmented: bool = False):
    # dK/dV for one K/V block, accumulated over every (group head, q-block)
    # that attends to it.  Everything is computed in the TRANSPOSED [bk, bq]
    # layout so lse/delta enter as the [1, bq] rows the forward already
    # emits and no in-kernel transposes (Mosaic relayouts) are needed:
    #   s^T = K Q^T;  p^T = exp(s^T - lse);  dV += p^T dO
    #   dp^T = V dO^T;  ds^T = p^T (dp^T - delta);  dK += ds^T Q
    # shift_ref is the forward's dynamic causal offset (SMEM scalar): one
    # compiled kernel serves aligned-causal (0) and every ring-hop shift.
    # segmented adds sk/sq id rows masking cross-segment pairs.
    if segmented:
        (k_ref, v_ref, q_ref, do_ref, lse_ref, dta_ref, sq_ref, sk_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, lse_ref, dta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    jk = pl.program_id(1)
    inner = pl.program_id(2)
    iq = inner % num_q
    shift = shift_ref[0]

    @pl.when(inner == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # q-blocks whose every row sits before this k-block's frontier see none
    @pl.when(iq * block_q + block_q - 1 + shift >= jk * block_k)
    def _step():
        k = k_ref[0]                                   # [bk, D]
        v = v_ref[0]
        q = q_ref[0]                                   # [bq, D]
        do = do_ref[0]
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bk, bq]
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_q), 1)
        masked = k_pos > q_pos + shift
        if segmented:
            masked = masked | (sk_ref[0][:, None] != sq_ref[0][None, :])
        st = jnp.where(masked, NEG_INF, st)
        # Clamp like the forward's m: a fully-masked row carries
        # lse ~ NEG_INF, and exp(NEG_INF - NEG_INF) = 1 would inject
        # garbage into dK/dV; clamped, exp(NEG_INF + 1e29) underflows to 0.
        lse_row = jnp.maximum(lse_ref[0, :1, :], -1e29)  # [1, bq] f32
        pt = jnp.exp(st - lse_row)
        dv_acc[...] = dv_acc[...] + jnp.dot(
            pt.astype(do.dtype), do, preferred_element_type=jnp.float32)
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, bq]
        dst = (pt * (dpt - dta_ref[0, :1, :])).astype(q.dtype)
        dk_acc[...] = dk_acc[...] + jnp.dot(
            dst, q, preferred_element_type=jnp.float32) * scale

    @pl.when(inner == num_inner - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_q_kernel(shift_ref, *refs,
                  block_q: int, block_k: int, num_k: int, scale: float,
                  segmented: bool = False):
    # dQ for one q-block, accumulated over its visible K/V blocks — in the
    # same transposed layout; the accumulator holds dQ^T [D, bq]
    # (dQ^T = K^T ds^T), un-transposed by XLA outside the kernel.
    if segmented:
        (k_ref, v_ref, q_ref, do_ref, lse_ref, dta_ref, sq_ref, sk_ref,
         dqt_ref, dqt_acc) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, lse_ref, dta_ref,
         dqt_ref, dqt_acc) = refs
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    shift = shift_ref[0]

    @pl.when(jk == 0)
    def _init():
        dqt_acc[...] = jnp.zeros_like(dqt_acc)

    @pl.when(jk * block_k <= iq * block_q + block_q - 1 + shift)
    def _step():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bk, bq]
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_q), 1)
        masked = k_pos > q_pos + shift
        if segmented:
            masked = masked | (sk_ref[0][:, None] != sq_ref[0][None, :])
        st = jnp.where(masked, NEG_INF, st)
        # same fully-masked-row clamp as the dK/dV kernel
        pt = jnp.exp(st - jnp.maximum(lse_ref[0, :1, :], -1e29))
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dst = (pt * (dpt - dta_ref[0, :1, :])).astype(q.dtype)
        dqt_acc[...] = dqt_acc[...] + jax.lax.dot_general(
            k, dst, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [D, bq]

    @pl.when(jk == num_k - 1)
    def _emit():
        dqt_ref[0] = dqt_acc[...]


def _flash_backward(q, k, v, g, out, lse,
                    shift=0,
                    static_causal: bool = True,
                    delta=None,
                    grad_dtype=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    segment_ids=None,
                    kv_segment_ids=None):
    """Fused causal-attention backward: two Pallas kernels (dK/dV and dQ),
    probabilities recomputed per block from the forward's lse so the [T,T]
    matrix never leaves VMEM in either direction.  GQA-native like the
    forward: compact K/V heads, each dK/dV block accumulating over its
    whole query-head group.

    ``shift``/``static_causal`` follow _flash_forward: a traced shift (ring
    hops) needs static_causal=False, which drops the pre-diagonal
    index-map clamps (the copies stream; compute is still skipped).
    ``delta`` (rowsum(dO*O), [B,H,T]) may be passed precomputed — ring
    reuses one delta across hops — otherwise it is derived from ``out``.
    Returns (dq, dk, dv) in ``grad_dtype`` (default: the input dtypes).
    """
    b, t, h, d = q.shape
    tk, kvh = k.shape[1], k.shape[2]
    if h % kvh:
        raise ValueError(f"query heads {h} not a multiple of kv heads {kvh}")
    grp = h // kvh
    block_q = pick_block(t, head_dim=d) if block_q is None \
        else min(block_q, t)
    block_k = pick_block(tk, head_dim=d) if block_k is None \
        else min(block_k, tk)
    if not block_q or not block_k or t % block_q or tk % block_k:
        # same contract as _flash_forward — a non-dividing block here would
        # silently leave gradient rows uncovered, not just misperform
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq lens ({t}, {tk})")
    _check_static_shift(static_causal, shift)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = d ** -0.5
    num_q, num_k = t // block_q, tk // block_k
    bh, bkv = b * h, b * kvh
    shift_arr = jnp.asarray(shift, jnp.int32).reshape(1)
    dq_dt = grad_dtype or q.dtype
    dk_dt = grad_dtype or k.dtype
    dv_dt = grad_dtype or v.dtype
    segment_ids, kv_segment_ids = _normalize_segments(
        segment_ids, kv_segment_ids, b, t, tk)
    segmented = segment_ids is not None

    qp, kp, vp, gp = (_to_planes(x) for x in (q, k, v, g))
    # delta_i = sum_d(dO_i * O_i); both it and lse ride the same [8, T]
    # sublane-broadcast tile layout the forward emits lse in, so the
    # kernels read them as [1, bq] rows with no relayout.
    if delta is None:
        delta = jnp.einsum("bqhd,bqhd->bhq", g.astype(jnp.float32),
                           out.astype(jnp.float32))
    lse_t = jnp.broadcast_to(lse.reshape(bh, 1, t), (bh, 8, t))
    delta_t = jnp.broadcast_to(
        delta.astype(jnp.float32).reshape(bh, 1, t), (bh, 8, t))

    # --- dK/dV: grid over compact K/V planes; inner walks (group, q) ---
    num_inner = grp * num_q

    def qplane(bkvi, jk, inner):
        return ((bkvi // kvh) * h + (bkvi % kvh) * grp + inner // num_q)

    if static_causal:
        # clamp skipped pre-diagonal q-blocks onto the first contributor so
        # the pipeline elides their copies (mirrors the forward's trick);
        # only valid when shift <= 0 is promised at trace time
        def q_block(jk, inner):
            return jnp.maximum(inner % num_q, (jk * block_k) // block_q)
    else:
        def q_block(jk, inner):
            return inner % num_q

    def q_index(bkvi, jk, inner):
        return (qplane(bkvi, jk, inner), q_block(jk, inner), 0)

    def row_index(bkvi, jk, inner):
        return (qplane(bkvi, jk, inner), 0, q_block(jk, inner))

    # cost estimates mirror the forward's rationale (flops=0 otherwise):
    # the dK/dV kernel runs 4 MXU matmuls per visible block pair, dQ 3.
    frac = 0.5 if static_causal else 1.0
    kv_bytes = int((kp.size + vp.size) * kp.dtype.itemsize * 2
                   + (qp.size + gp.size) * qp.dtype.itemsize
                   + 2 * bh * t * 4)
    kv_in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_k, d), lambda i, jk, n: (i, jk, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, jk, n: (i, jk, 0)),
        pl.BlockSpec((1, block_q, d), q_index),
        pl.BlockSpec((1, block_q, d), q_index),
        pl.BlockSpec((1, 8, block_q), row_index),
        pl.BlockSpec((1, 8, block_q), row_index),
    ]
    kv_inputs = [shift_arr, kp, vp, qp, gp, lse_t, delta_t]
    if segmented:
        kv_in_specs += [
            pl.BlockSpec((1, block_q),
                         lambda i, jk, n: (i // kvh, q_block(jk, n))),
            pl.BlockSpec((1, block_k), lambda i, jk, n: (i // kvh, jk)),
        ]
        kv_inputs += [segment_ids, kv_segment_ids]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_kv_kernel, block_q=block_q, block_k=block_k,
                          num_q=num_q, num_inner=num_inner, scale=scale,
                          segmented=segmented),
        grid=(bkv, num_k, num_inner),
        in_specs=kv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, jk, n: (i, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, jk, n: (i, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, tk, d), dk_dt),
            jax.ShapeDtypeStruct((bkv, tk, d), dv_dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(8 * b * h * t * tk * d * frac),
            transcendentals=int(b * h * t * tk * frac),
            bytes_accessed=kv_bytes),
        name="sofa_flash_bwd_kv",
        interpret=interpret,
    )(*kv_inputs)

    # --- dQ: grid over query planes; inner walks visible K/V blocks ---
    if static_causal:
        def kv_index(i, iq, jk):
            last = (iq * block_q + block_q - 1) // block_k
            return (_kv_plane(i, h, kvh), jnp.minimum(jk, last), 0)
    else:
        def kv_index(i, iq, jk):
            return (_kv_plane(i, h, kvh), jk, 0)

    q_in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_q, d), lambda i, iq, jk: (i, iq, 0)),
        pl.BlockSpec((1, block_q, d), lambda i, iq, jk: (i, iq, 0)),
        pl.BlockSpec((1, 8, block_q), lambda i, iq, jk: (i, 0, iq)),
        pl.BlockSpec((1, 8, block_q), lambda i, iq, jk: (i, 0, iq)),
    ]
    q_inputs = [shift_arr, kp, vp, qp, gp, lse_t, delta_t]
    if segmented:
        q_in_specs += [
            pl.BlockSpec((1, block_q), lambda i, iq, jk: (i // h, iq)),
            pl.BlockSpec((1, block_k),
                         lambda i, iq, jk: (i // h, kv_index(i, iq, jk)[1])),
        ]
        q_inputs += [segment_ids, kv_segment_ids]
    dqt = pl.pallas_call(
        functools.partial(_bwd_q_kernel, block_q=block_q, block_k=block_k,
                          num_k=num_k, scale=scale, segmented=segmented),
        grid=(bh, num_q, num_k),
        in_specs=q_in_specs,
        out_specs=[
            pl.BlockSpec((1, d, block_q), lambda i, iq, jk: (i, 0, iq)),
        ],
        out_shape=[jax.ShapeDtypeStruct((bh, d, t), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((d, block_q), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(6 * b * h * t * tk * d * frac),
            transcendentals=int(b * h * t * tk * frac),
            # reads K/V/Q/dO + lse/delta; writes the f32 dQ^T output
            bytes_accessed=int(
                (kp.size + vp.size) * kp.dtype.itemsize
                + (qp.size + gp.size) * qp.dtype.itemsize
                + 2 * bh * t * 4 + bh * t * d * 4)),
        name="sofa_flash_bwd_dq",
        interpret=interpret,
    )(*q_inputs)[0]

    dq = dqt.reshape(b, h, d, t).transpose(0, 3, 1, 2).astype(dq_dt)
    dk = dk.reshape(b, kvh, tk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, kvh, tk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _grad_block(q, k, v, g, delta, lse, shift,
                block: Optional[int] = None):
    """Blockwise attention gradients against one visiting K/V block.

    All stock lax ops (one scan over k-chunks, probabilities recomputed from
    the saved per-row lse) — the [Tq, Tk] matrix never fully materializes.
    ``shift`` is the same causal offset the forward kernel uses; q rows are
    local positions, k positions are offset by it.  Returns (dq, dk, dv) in
    f32 — dq for the local q shard, dk/dv for the *visiting* block.

    GQA: k/v may carry KVH < H heads; q/g fold into [B,T,KVH,G,D] so every
    einsum contracts the shared kv head across its query group, and dk/dv
    come back in the compact KVH layout (the group axis sums away — the
    repeated-KV gradient identity).
    """
    b, t, h, d = q.shape
    tk, kvh = k.shape[1], k.shape[2]
    grp = h // kvh
    bk = pick_block(tk) if block is None else min(block, tk)
    if not bk or tk % bk:
        raise ValueError(f"k-chunk {bk} must divide key length {tk}")
    scale = d ** -0.5
    # Operands keep their storage dtype into every einsum with f32
    # accumulation (preferred_element_type): bf16 inputs run the MXU in
    # native bf16 mode instead of 4x-slower f32 (same fix as the forward
    # kernel).  p/ds round to the storage dtype before their matmuls —
    # the standard flash-attention backward trade.
    cdt = q.dtype
    f32 = jnp.float32
    q_pos = jnp.arange(t)[:, None]                     # [T, 1]
    q5 = q.reshape(b, t, kvh, grp, d)
    g5 = g.reshape(b, t, kvh, grp, d)
    lse5 = lse.reshape(b, kvh, grp, t)
    delta5 = delta.reshape(b, kvh, grp, t)
    kb = k.reshape(b, tk // bk, bk, kvh, d)
    vb = v.reshape(b, tk // bk, bk, kvh, d)

    def body(dq, blk):
        kj, vj, j = blk                               # [B,bk,KVH,D]
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, kj,
                       preferred_element_type=f32) * scale
        k_pos = j * bk + jnp.arange(bk)[None, :]
        s = jnp.where((k_pos > q_pos + shift)[None, None, None],
                      NEG_INF, s)
        p = jnp.exp(s - lse5[..., None])              # [B,KVH,G,T,bk] f32
        dv_j = jnp.einsum("bhgqk,bqhgd->bkhd", p.astype(cdt), g5,
                          preferred_element_type=f32)
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", g5, vj,
                        preferred_element_type=f32)
        ds = (p * (dp - delta5[..., None])).astype(cdt)
        dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", ds, kj,
                             preferred_element_type=f32) * scale
        dk_j = jnp.einsum("bhgqk,bqhgd->bkhd", ds, q5,
                          preferred_element_type=f32) * scale
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((b, t, kvh, grp, d), jnp.float32)
    dq, (dk, dv) = jax.lax.scan(
        body, dq0,
        (kb.transpose(1, 0, 2, 3, 4), vb.transpose(1, 0, 2, 3, 4),
         jnp.arange(tk // bk)))
    dq = dq.reshape(b, t, h, d)
    dk = dk.transpose(1, 0, 2, 3, 4).reshape(b, tk, kvh, d)
    dv = dv.transpose(1, 0, 2, 3, 4).reshape(b, tk, kvh, d)
    return dq, dk, dv


def _bwd(res, g):
    # Fused Pallas backward (dK/dV kernel + dQ kernel); the lax fallback
    # _grad_block remains for ring hops, whose causal shift is traced.
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, g, out, lse)


flash_causal_attention.defvjp(_fwd, _bwd)
