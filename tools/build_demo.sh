#!/bin/bash
# Build a self-contained demo logdir: profile the disk-churn example and
# snapshot the fully-analyzed result (board + report.js + CSVs) into demo/.
# Analogue of the reference's tools/build_demo.sh (dd-based).
set -e
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-demo}"
"$ROOT/bin/sofa" stat "python $ROOT/examples/io_churn.py" --logdir "$OUT/sofalog/"
echo "demo ready: open with  $ROOT/bin/sofa viz --logdir $OUT/sofalog/"
