#!/bin/bash
# Build a self-contained demo logdir: profile the disk-churn example and
# snapshot the fully-analyzed result (board + report.js + CSVs) into demo/.
# Analogue of the reference's tools/build_demo.sh (dd-based).
set -e
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-demo}"
# The demo workload is host-only (disk churn, no JAX) — pin the CPU backend
# so an ambient accelerator platform (JAX_PLATFORMS=axon/tpu with its
# tunnel down) can't stall the chained site hooks.  Override with
# SOFA_DEMO_PLATFORM if you want the demo to ride the real backend.
export JAX_PLATFORMS="${SOFA_DEMO_PLATFORM:-cpu}"
"$ROOT/bin/sofa" stat "python $ROOT/examples/io_churn.py" --logdir "$OUT/sofalog/"
echo "demo ready: open with  $ROOT/bin/sofa viz --logdir $OUT/sofalog/"
