"""`sofa regress` — the typed regression engine over the archive.

Promotes ml/diff.py's run-to-run swarm diff into a first-class verdict
service: compare a run (logdir path or archived run id) against another
run, or against a rolling percentile baseline computed over the catalog,
and emit a typed verdict per feature and per swarm cluster —
``regressed`` / ``improved`` / ``noise`` — with the interval discipline
of tools/overhead_budget.py (archive/baseline.py: no verdict without a
defensible interval; short histories and polarity-less features say
``noise`` and say why).

Artifacts: a machine-readable ``regress_verdict.json`` (schema below,
validated by tools/manifest_check.py) beside the run (its logdir, or the
archive root for archived ids) plus a human table.  Exit contract:
0 noise/improved, 1 regressed — so CI can gate on it exactly the way
bench.py gates evidence.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Dict, List, Optional

from sofa_tpu.archive import VERDICT_NAME, baseline, resolve_root
from sofa_tpu.archive.store import ArchiveStore, _read_features_csv
from sofa_tpu.printing import (
    print_error,
    print_progress,
    print_title,
    print_warning,
)

VERDICT_SCHEMA = "sofa_tpu/regress_verdict"
VERDICT_VERSION = 1

VERDICTS = ("regressed", "improved", "noise")

# A new swarm cluster only earns a verdict when it carries at least this
# fraction of the base run's total clustered duration — tiny new clusters
# are churn, not regressions.
_NEW_CLUSTER_MIN_SHARE = 0.05


class _Side:
    """One comparison side: a logdir path or an archived run."""

    def __init__(self, label: str, features: Dict[str, float],
                 clusters, run_id: "str | None" = None):
        self.label = label
        self.features = features
        self.clusters = clusters            # DataFrame or None
        self.run_id = run_id


def _clusters_ok(df) -> bool:
    return df is not None and not df.empty and \
        {"cluster_ID", "name", "duration"}.issubset(df.columns)


def _load_clusters_csv(path_or_buf) -> "object | None":
    import pandas as pd

    try:
        df = pd.read_csv(path_or_buf)
    except Exception as e:  # noqa: BLE001 — absent/corrupt: degrade to features-only
        print_warning(f"regress: cannot read auto_caption table ({e}) — "
                      "cluster verdicts skipped")
        return None
    return df


def resolve_side(store: "ArchiveStore | None", arg: str) -> "_Side | None":
    """A logdir path, or a (>= 6 char) archived run-id prefix."""
    if os.path.isdir(arg):
        feats = _read_features_csv(os.path.join(arg, "features.csv"))
        cpath = os.path.join(arg, "auto_caption.csv")
        clusters = _load_clusters_csv(cpath) if os.path.isfile(cpath) \
            else None
        return _Side(arg, feats, clusters)
    if store is not None and store.exists:
        run_id = store.resolve_run_id(arg)
        if run_id is not None:
            doc = store.load_run(run_id) or {}
            clusters = None
            ent = (doc.get("files") or {}).get("auto_caption.csv")
            if ent:
                blob = store.read_object(ent.get("sha256", ""))
                if blob is not None:
                    clusters = _load_clusters_csv(io.BytesIO(blob))
            return _Side(run_id[:12], doc.get("features") or {}, clusters,
                         run_id=run_id)
    return None


# ---------------------------------------------------------------------------
# The comparison.
# ---------------------------------------------------------------------------

def compare_features(run: _Side, base: "_Side | None", store,
                     rolling: int, pct: float,
                     threshold_pct: float) -> List[dict]:
    rows: List[dict] = []
    if base is not None:
        names = sorted(set(run.features) | set(base.features))
        for name in names:
            v = float(run.features.get(name, 0.0))
            b = float(base.features.get(name, 0.0))
            row = baseline.pairwise_verdict(v, b, threshold_pct,
                                            baseline.polarity(name))
            rows.append({"name": name, "value": v, **row})
        return rows
    samples = baseline.rolling_samples(store, rolling,
                                       exclude_run=run.run_id)
    for name in sorted(run.features):
        v = float(run.features[name])
        row = baseline.rolling_verdict(v, samples.get(name, []), pct,
                                       threshold_pct,
                                       baseline.polarity(name))
        rows.append({"name": name, "value": v, **row})
    return rows


def compare_clusters(run: _Side, base: _Side,
                     threshold_pct: float) -> List[dict]:
    """Per-swarm-cluster verdicts (pairwise only): fuzzy-match clusters
    with ml/diff.py's greedy matcher, verdict each matched pair's
    duration ratio, and surface new clusters that carry real weight."""
    from sofa_tpu.ml.diff import _cluster_signatures, match_swarms

    if not (_clusters_ok(run.clusters) and _clusters_ok(base.clusters)):
        return []
    base_sig = _cluster_signatures(base.clusters)
    run_sig = _cluster_signatures(run.clusters)
    mapping = match_swarms(base_sig, run_sig)
    rows: List[dict] = []
    total_base = sum(s["duration"] for s in base_sig.values()) or 1.0
    matched_run = {m for m in mapping.values() if m is not None}
    for b, m in sorted(mapping.items()):
        bs = base_sig[b]
        name = f"cluster {b} ({bs['names'][:48]})"
        if m is None:
            rows.append({"name": name, "value": 0.0,
                         "baseline": bs["duration"], "ratio": 0.0,
                         "verdict": "noise",
                         "reason": "no matching cluster in the run "
                                   "(vanished or renamed beyond the "
                                   "fuzzy matcher)"})
            continue
        row = baseline.pairwise_verdict(
            run_sig[m]["duration"], bs["duration"], threshold_pct, 1)
        rows.append({"name": name, "value": run_sig[m]["duration"],
                     "matched_cluster": m, **row})
    for m, ms in sorted(run_sig.items()):
        if m in matched_run:
            continue
        share = ms["duration"] / total_base
        if share >= _NEW_CLUSTER_MIN_SHARE:
            rows.append({"name": f"cluster new:{m} ({ms['names'][:48]})",
                         "value": ms["duration"], "baseline": 0.0,
                         "ratio": float("inf"), "verdict": "regressed",
                         "reason": f"new cluster carrying "
                                   f"{share * 100:.1f}% of the base run's "
                                   "clustered time (ratio inf)"})
        else:
            rows.append({"name": f"cluster new:{m}", "value": ms["duration"],
                         "baseline": 0.0, "ratio": float("inf"),
                         "verdict": "noise",
                         "reason": f"new cluster below the "
                                   f"{_NEW_CLUSTER_MIN_SHARE * 100:.0f}% "
                                   "weight floor"})
    return rows


def overall_verdict(rows: List[dict]) -> str:
    verdicts = {r.get("verdict") for r in rows}
    if "regressed" in verdicts:
        return "regressed"
    if "improved" in verdicts:
        return "improved"
    return "noise"


def build_verdict_doc(run: _Side, base: "_Side | None", mode: dict,
                      features: List[dict], clusters: List[dict]) -> dict:
    counts = {v: 0 for v in VERDICTS}
    for r in features + clusters:
        counts[r.get("verdict", "noise")] += 1
    return {
        "schema": VERDICT_SCHEMA,
        "version": VERDICT_VERSION,
        "generated_unix": round(time.time(), 3),
        "run": {"label": run.label, "run_id": run.run_id},
        "baseline": mode if base is None else {
            "mode": "pairwise", "label": base.label,
            "run_id": base.run_id, **mode},
        "features": features,
        "clusters": clusters,
        "counts": counts,
        "verdict": overall_verdict(features + clusters),
    }


def write_verdict(doc: dict, out_path: str) -> None:
    from sofa_tpu.durability import atomic_write

    # json.dumps(inf) emits the non-standard Infinity token; the board's
    # JSON.parse (and any strict consumer) rejects it, so encode inf as
    # the string "inf" — the one sentinel the diff tables already use.
    def _clean(v):
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            return "inf" if v > 0 else ("-inf" if v < 0 else "nan")
        if isinstance(v, dict):
            return {k: _clean(x) for k, x in v.items()}
        if isinstance(v, list):
            return [_clean(x) for x in v]
        return v

    with atomic_write(out_path, fsync=True) as f:
        json.dump(_clean(doc), f, indent=1, sort_keys=True)


def render_verdict(doc: dict) -> List[str]:
    lines: List[str] = []
    rows = [["FEATURE", "VALUE", "BASELINE", "RATIO", "VERDICT", "WHY"]]

    def fmt(v):
        if isinstance(v, str):
            return v
        if not isinstance(v, (int, float)):
            return "-"
        return f"{v:.6g}"

    for r in (doc.get("features") or []) + (doc.get("clusters") or []):
        if r.get("verdict") == "noise" and len(rows) > 40:
            continue  # the table leads with signal; noise past 40 rows is summarized by counts
        rows.append([str(r.get("name", "?"))[:48], fmt(r.get("value")),
                     fmt(r.get("baseline")), fmt(r.get("ratio")),
                     str(r.get("verdict", "?")),
                     str(r.get("reason", ""))[:60]])
    rows[1:] = sorted(
        rows[1:],
        key=lambda r: ("regressed", "improved", "noise").index(r[4])
        if r[4] in VERDICTS else 3)
    from sofa_tpu.telemetry import _table

    lines += _table(rows)
    counts = doc.get("counts") or {}
    lines.append("")
    lines.append(
        f"verdict: {doc.get('verdict', '?').upper()} — "
        + ", ".join(f"{counts.get(v, 0)} {v}" for v in VERDICTS))
    return lines


# ---------------------------------------------------------------------------
# The verb.
# ---------------------------------------------------------------------------

def sofa_regress(cfg, run_arg: str, base_arg: str = "") -> int:
    """``sofa regress <run> [<baseline>] [--rolling N --pct P]`` — exit 0
    noise/improved, 1 regressed, 2 usage errors."""
    from sofa_tpu import telemetry

    root = resolve_root(cfg)
    store = ArchiveStore(root)
    if not run_arg:
        print_error("regress needs a run: `sofa regress <logdir-or-run-id> "
                    "[<baseline>]` (or --rolling N for a catalog baseline)")
        return 2
    run = resolve_side(store, run_arg)
    if run is None:
        print_error(f"regress: {run_arg!r} is neither a logdir nor a "
                    f"unique archived run id (archive: {root})")
        return 2
    rolling = int(getattr(cfg, "regress_rolling", 0) or 0)
    base: "Optional[_Side]" = None
    if base_arg:
        base = resolve_side(store, base_arg)
        if base is None:
            print_error(f"regress: baseline {base_arg!r} is neither a "
                        "logdir nor a unique archived run id")
            return 2
    elif rolling <= 0:
        print_error("regress needs a baseline: a second run argument, or "
                    "--rolling N to compare against the last N archived "
                    "runs")
        return 2
    elif not store.exists:
        print_error(f"regress --rolling: no archive at {root} — "
                    "`sofa archive <logdir>` some runs first")
        return 2
    if not run.features:
        print_warning(f"regress: {run.label} has no features "
                      "(features.csv missing — run `sofa analyze` / "
                      "`sofa report` before archiving); every verdict "
                      "will be noise")

    pct = float(getattr(cfg, "regress_pct", 50.0) or 50.0)
    threshold = float(getattr(cfg, "regress_threshold", 10.0) or 10.0)
    mode = ({"mode": "rolling", "rolling": rolling, "pct": pct,
             "threshold_pct": threshold} if base is None
            else {"threshold_pct": threshold})

    tel = None
    out_dir = run_arg if os.path.isdir(run_arg) else root
    if os.path.isdir(run_arg):
        tel = telemetry.begin("regress")
    try:
        with telemetry.maybe_span("regress_verdict", cat="stage"):
            features = compare_features(run, base, store, rolling, pct,
                                        threshold)
            clusters = compare_clusters(run, base, threshold) \
                if base is not None else []
            doc = build_verdict_doc(run, base, mode, features, clusters)
            out_path = os.path.join(out_dir, VERDICT_NAME)
            write_verdict(doc, out_path)
        if tel is not None:
            tel.set_meta(regress={"verdict": doc["verdict"],
                                  "counts": doc["counts"],
                                  "out": out_path})
            tel.write(run_arg, rc=0 if doc["verdict"] != "regressed"
                      else 1, cfg=cfg)
    finally:
        if tel is not None:
            telemetry.end(tel)
    print_title(
        f"regression verdict — {run.label} vs "
        + (base.label if base is not None
           else f"rolling p{pct:g} of last {rolling}"))
    print("\n".join(render_verdict(doc)))
    print_progress(f"regress: wrote {out_path}")
    return 1 if doc["verdict"] == "regressed" else 0
