#!/usr/bin/env python3
"""Headline benchmark: profiling overhead on a ResNet-50 training loop.

Mirrors the reference's only quantitative quality gate — paired runs of a
resnet50 workload with and without the profiler, overhead = time delta
(/root/reference/validation/framework_eval.py:195-215) — retargeted to the
TPU: the "with profiling" leg runs under sofa_tpu.api.profile (XPlane trace +
clock marker + 10 Hz host samplers), and the run only counts if the captured
trace actually contains HLO ops (coverage guard, per BASELINE.json's
"overhead % + HLO-op trace coverage" metric).

Output contract: the result is the LAST parseable JSON line on stdout.
Normally that is the only line, but a run that had to wait on a dead device
tunnel first prints a provisional line (`"provisional": true, value null`)
so an uncatchable SIGKILL still leaves something parseable; a completed run
always prints the real result after it.  Fields:
  value       = profiling overhead in percent (lower is better)
  vs_baseline = value / 5.0, the fraction of the reference's <5 % overhead
                budget consumed (<1.0 beats the target)
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from typing import Optional


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# Where the benchmark currently is, for the signal-handler error line; the
# final _emit flips `done` so a late signal can't print a second JSON line.
_state = {"phase": "starting", "done": False, "provisional": False}


_LAST_GOOD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_last_good.json")


def _read_last_good() -> "dict | None":
    """The last committed on-chip result, tagged `cached` for re-emission
    inside a dead-tunnel error line (VERDICT r4 missing #1: four rounds of
    driver windows, zero numbers — the evidence chain must survive an
    outage window).  Matches the spirit of the reference's persisted eval
    table (/root/reference/validation/framework_eval.py:206-215)."""
    try:
        with open(_LAST_GOOD_PATH) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("value") is None:
        return None
    doc["cached"] = True
    return doc


def _write_last_good(result: dict) -> None:
    """Persist a successful ON-CHIP result (full JSON + capture timestamp +
    git SHA) so the next dead-tunnel driver window still carries it."""
    sha = ""
    try:
        import subprocess

        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(_LAST_GOOD_PATH), timeout=10,
        ).stdout.strip()
    except Exception:  # noqa: BLE001 — provenance is best-effort
        pass
    doc = dict(result)
    doc["captured_unix"] = int(time.time())
    doc["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    doc["git_sha"] = sha
    try:
        tmp = _LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, _LAST_GOOD_PATH)
        _log(f"bench: persisted on-chip result to {_LAST_GOOD_PATH} "
             "(commit it!)")
    except OSError as e:
        _log(f"bench: could not persist last-good result: {e!r}")


def _emit(value, error: str | None = None,
          p_value: "float | None" = None,
          extra: "dict | None" = None,
          provisional: bool = False) -> dict:
    """The one JSON line the driver parses — emitted on success AND failure.

    A non-provisional emit is final: it marks the process as having spoken,
    so the SIGTERM/SIGALRM handler stays silent afterwards.  A provisional
    emit (written when the retry loop starts waiting on a dead tunnel) exists
    so even SIGKILL — which no handler can catch — leaves a parseable line on
    stdout; the driver reads the LAST parseable line, so a later real result
    supersedes it.  (Round 3 regressed to `parsed: null` because the driver's
    timeout beat the retry budget and _emit only ran at the end of main.)
    """
    if not provisional:
        _state["done"] = True
    out = {
        "metric": "resnet50_profiling_overhead",
        "value": value,
        "unit": "percent",
        "vs_baseline": None if value is None else round(value / 5.0, 4),
    }
    if p_value is not None:
        # paired-run significance, mirroring the reference's t-test
        # (validation/framework_eval.py:144-145,208-215)
        out["p_value"] = round(p_value, 4)
    if extra:
        out.update(extra)  # secondary evidence keys; drivers ignore extras
    if error:
        out["error"] = error
    if provisional:
        out["provisional"] = True
    print(json.dumps(out), flush=True)
    return out


def _emit_provisional_once() -> None:
    """First time the retry loop decides to wait, leave a parseable line so
    an uncatchable kill (driver SIGKILL) still yields a non-null parse."""
    if _state["provisional"] or _state["done"]:
        return
    _state["provisional"] = True
    _emit(None, error="provisional: benchmark still running "
                      "(waiting for a healthy device tunnel); if this is the "
                      "last line, the process was killed before finishing",
          provisional=True)


def _install_signal_handlers() -> None:
    """SIGTERM/SIGALRM → emit the error JSON line NOW, then exit.

    `timeout(1)` and most drivers send SIGTERM first; without a handler the
    process dies mid-retry with nothing on stdout (BENCH_r03.json: rc=124,
    parsed null).  SIGKILL can't be caught — that's what the provisional
    line is for.
    """
    import signal

    def die(signum, frame):  # noqa: ARG001 — signal handler signature
        child = _state.get("smoke_child")
        if child is not None:   # don't orphan a running evidence smoke
            try:
                child.kill()
            except Exception:  # noqa: BLE001
                pass
        if not _state["done"]:
            name = signal.Signals(signum).name
            _emit(None, error=f"killed by {name} while {_state['phase']} "
                              "(driver timeout beat the retry budget?)")
        os._exit(1)

    for sig in (signal.SIGTERM, signal.SIGALRM):
        try:
            signal.signal(sig, die)
        except (ValueError, OSError):  # non-main thread / exotic platform
            pass


def _log_chip_holders() -> None:
    """Best-effort: name the processes holding a TPU/accel device node."""
    import glob
    import os

    holders = []
    for fd in glob.glob("/proc/[0-9]*/fd/*"):
        try:
            tgt = os.readlink(fd)
        except OSError:
            continue
        if "/dev/accel" in tgt or "/dev/vfio" in tgt or "libtpu" in tgt:
            pid = fd.split("/")[2]
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode()[:160]
            except OSError:
                cmd = "?"
            holders.append(f"pid {pid}: {cmd}")
    if holders:
        _log("bench: device held by: " + "; ".join(sorted(set(holders))))
    else:
        _log("bench: no local process holds an accel device node "
             "(chip may be held remotely / tunnel busy)")


# The probe re-applies main()'s env-over-config rule: the image's
# sitecustomize force-prepends the TPU platform, and a JAX_PLATFORMS=cpu
# smoke run must probe the CPU backend, not the tunnel.
_PROBE_SNIPPET = """
import os
import jax
p = os.environ.get("JAX_PLATFORMS", "")
if p and jax.config.jax_platforms != p:
    jax.config.update("jax_platforms", p)
jax.devices()
print(jax.default_backend())
"""

_probed_backend: Optional[str] = None


def _preflight(timeout_s: float = 60.0) -> Optional[str]:
    """Probe backend init in a subprocess so a *hanging* tunnel (dead axon
    service: jax.devices() blocks forever rather than raising) cannot hang
    the benchmark itself.  Returns None when healthy, else a short reason.
    On success records the probed backend name in _probed_backend.
    """
    import subprocess

    global _probed_backend
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SNIPPET],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return f"backend init hung > {timeout_s:.0f}s (device tunnel down?)"
    if r.returncode != 0:
        tail = (r.stderr.strip().splitlines() or ["?"])[-1]
        return f"backend init failed: {tail[:200]}"
    _probed_backend = (r.stdout.strip().splitlines() or ["?"])[-1]
    return None


def _next_round_tag(root: str) -> str:
    """rNN of the round being benchmarked: one past the newest BENCH_r*.json
    artifact (the driver writes BENCH_r{N}.json *after* running bench)."""
    import glob
    import re

    ns = [int(m.group(1))
          for f in glob.glob(os.path.join(root, "BENCH_r*.json"))
          for m in [re.search(r"BENCH_r(\d+)\.json$", f)] if m]
    return f"r{max(ns, default=0) + 1:02d}"


def _run_validate_checklist(root: Optional[str] = None) -> bool:
    """Run tools/validate_tpu.py in the SAME healthy tunnel window the bench
    just found, so one window yields both the on-chip checklist (and a fresh
    real-capture fixture) and the overhead number.  Best-effort: a failing or
    slow checklist must never sink the benchmark itself.  Opt out with
    SOFA_BENCH_VALIDATE=0.  Returns whether the checklist actually ran (and
    so may be holding the chip briefly).
    """
    import subprocess

    if os.environ.get("SOFA_BENCH_VALIDATE", "1") != "1":
        return False
    if _probed_backend != "tpu":
        return False  # CPU smoke run: the checklist requires the real chip
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    script = os.path.join(root, "tools", "validate_tpu.py")
    if not os.path.isfile(script):
        return False
    out_path = os.path.join(root, f"VALIDATE_{_next_round_tag(root)}.txt")
    # 900 s + SOFA_VALIDATE_FAST: the checklist carries the overhead-budget
    # pairs and the kernel-perf sweep, but it runs INSIDE the driver's own
    # ~20-min bench window — fast mode halves those sweeps so a slow
    # tunnel can't spend the whole window on the checklist and leave the
    # headline metric unmeasured (r3 died exactly that way).
    timeout_s = float(os.environ.get("SOFA_BENCH_VALIDATE_TIMEOUT_S", "900"))
    _log(f"bench: running validate_tpu checklist -> {out_path} "
         f"(timeout {timeout_s:.0f}s)")
    _state["phase"] = "running validate_tpu checklist"
    t0 = time.time()
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        r = subprocess.run([sys.executable, script, "--capture-fixture"],
                           capture_output=True, text=True, timeout=timeout_s,
                           cwd=root,
                           env=dict(os.environ, SOFA_VALIDATE_FAST="1"))
        body = r.stdout
        if r.stderr.strip():
            body += "\n--- stderr ---\n" + r.stderr
        head = (f"# tools/validate_tpu.py --capture-fixture  {stamp}  "
                f"rc={r.returncode}  ({time.time() - t0:.0f}s)\n")
        with open(out_path, "w") as f:
            f.write(head + body)
        _log(f"bench: validate_tpu rc={r.returncode} "
             f"({time.time() - t0:.0f}s)")
        return True
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        body = out.decode(errors="replace") if isinstance(out, bytes) else out
        with open(out_path, "w") as f:
            f.write(f"# tools/validate_tpu.py  {stamp}  TIMEOUT after "
                    f"{timeout_s:.0f}s — partial output below\n" + body)
        _log(f"bench: validate_tpu timed out after {timeout_s:.0f}s; "
             "the killed run may hold the chip for a few minutes")
        return True
    except Exception as e:  # noqa: BLE001 — checklist is best-effort
        _log(f"bench: validate_tpu launch failed: {e!r}")
        return False


def _preprocess_wall_evidence() -> dict:
    """CPU-only report-path metric: time ``sofa_preprocess`` over the
    pod_synth ``--raw`` logdir, cold (parallel ingest) and warm (content-
    keyed ingest cache).  Needs no device at all, so the bench trajectory
    keeps a real number even when the tunnel is down for the whole window
    (BENCH_r05 ran with a dead tunnel and a null headline).  Rides the
    extras of BOTH the success and the error emit; opt out with
    SOFA_BENCH_PREPROCESS=0.
    """
    import subprocess
    import tempfile

    if os.environ.get("SOFA_BENCH_PREPROCESS", "1") != "1":
        return {}
    _state["phase"] = "preprocess wall-time evidence"
    root = os.path.dirname(os.path.abspath(__file__))
    logdir = os.path.join(tempfile.mkdtemp(prefix="sofa_prewall_"), "")
    snippet = """
import json, os, sys, time
sys.path.insert(0, {root!r})
from sofa_tpu.config import SofaConfig
from sofa_tpu.preprocess import sofa_preprocess
cfg = SofaConfig(logdir={logdir!r})
# what-if evidence (sofa_tpu/whatif/): zero-scenario identity replay —
# |replayed mean - measured mean| as a percentage.  The first bench
# metric that needs NO hardware at all: it gauges the replay model's
# fidelity, so a model regression shows in the trajectory even when the
# tunnel is dead for the whole round.  It runs on a pristine SIDE COPY
# of the synthetic device frames, staged before the preprocess below:
# preprocess regenerates frame CSVs from RAW collector files, and this
# harness has no raw xplane, so preprocessing (and the later resume
# replay) would clobber the very step spans the replay calibrates
# against.
import shutil as _sh, tempfile as _tf
wout = {{}}
try:
    from sofa_tpu.whatif import REPORT_NAME, sofa_whatif
    wdir = os.path.join(_tf.mkdtemp(prefix="sofa_whatif_"), "")
    try:
        for fname in ("tpusteps.csv", "tputrace.csv", "sofa_time.txt",
                      "misc.txt", "tpu_meta.json"):
            if os.path.isfile(cfg.path(fname)):
                _sh.copy(cfg.path(fname), os.path.join(wdir, fname))
        wcfg = SofaConfig(logdir=wdir)
        rc = sofa_whatif(wcfg)
        with open(wcfg.path(REPORT_NAME)) as f:
            wdoc = json.load(f)
        err = (wdoc.get("calibration") or {{}}).get("identity_error_pct")
        if err is not None:
            wout["whatif_identity_error_pct"] = err
        if rc != 0:
            wout["whatif_evidence_error"] = (
                f"whatif rc={{rc}}: "
                + str((wdoc.get("calibration") or {{}}).get("reason")))[:160]
    finally:
        _sh.rmtree(wdir, ignore_errors=True)
except Exception as e:
    wout["whatif_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
t0 = time.perf_counter(); sofa_preprocess(cfg)
cold = time.perf_counter() - t0
t0 = time.perf_counter(); sofa_preprocess(cfg)
warm = time.perf_counter() - t0
out = {{"cold": round(cold, 3), "warm": round(warm, 3)}}
out.update(wout)
# viz-path evidence (sofa_tpu/tiles.py): the columnar report.js payload
# and the LOD tile-pyramid build time from the manifest's tiles stage.
try:
    out["report_js_bytes"] = os.path.getsize(cfg.path("report.js"))
    from sofa_tpu.telemetry import load_manifest
    doc = load_manifest(cfg.logdir) or {{}}
    stage = next((s for s in doc.get("stages", [])
                  if s.get("verb") == "preprocess"
                  and s.get("name") == "tiles"), None)
    if stage is not None:
        out["tile_build_wall_time_s"] = stage.get("dur_s")
except Exception as e:
    out["viz_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# analyze-path evidence (sofa_tpu/analysis/registry.py): wall time of the
# full registry-scheduled pass run over the preprocessed logdir, plus the
# meta.passes ledger's health counts — a failed pass is visible in the
# bench trajectory even when the timing looks fine.  analyze_peak_rss_mb
# rides the same run: this subprocess's high-water RSS right after the
# projection-pushdown analyze (sofa_tpu/frames.py) — the out-of-core
# memory bound's trajectory number.
try:
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.telemetry import load_manifest
    t0 = time.perf_counter()
    sofa_analyze(cfg)
    out["analyze_wall_time_s"] = round(time.perf_counter() - t0, 3)
    import resource
    out["analyze_peak_rss_mb"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    doc = load_manifest(cfg.logdir) or {{}}
    ledger = ((doc.get("meta") or {{}}).get("passes") or {{}}).get(
        "passes") or {{}}
    out["analyze_pass_count"] = len(ledger)
    out["analyze_failed_passes"] = sum(
        1 for e in ledger.values() if e.get("status") == "failed")
except Exception as e:
    out["analyze_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# frame-store evidence (sofa_tpu/frames.py): full deserialization wall
# time of every frame through the interchange format this build defaults
# to (the chunked columnar store), the number tools/frame_bench.py
# breaks down against the CSV path and a projected load.
try:
    from sofa_tpu.analyze import load_frames
    t0 = time.perf_counter()
    load_frames(cfg)
    out["frame_load_wall_time_s"] = round(time.perf_counter() - t0, 3)
except Exception as e:
    out["frame_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# live-streaming evidence (sofa_tpu/live.py): an INCREMENTAL epoch over
# a tail-append — epoch 1 ingests half the tpumon tail on a side copy of
# the raw collector files, the rest is appended, and epoch 2 (the timed
# one) must fold in only the new records: committed chunks load from the
# chunk cache, only dirty tiles rebuild, only touched passes re-run.
# live_lag_events is the backlog that epoch drained.  Needs no hardware,
# so the streaming path's cost stays in the trajectory on dead-tunnel
# rounds.
try:
    from sofa_tpu.live import sofa_live
    from sofa_tpu.telemetry import load_manifest as _live_lm
    ldir = os.path.join(_tf.mkdtemp(prefix="sofa_live_"), "")
    for fname in ("sofa_time.txt", "misc.txt", "tpumon.txt",
                  "pystacks.txt", "strace.txt", "cpuinfo.txt",
                  "mpstat.txt", "netstat.txt", "vmstat.txt"):
        if os.path.isfile(cfg.path(fname)):
            _sh.copy(cfg.path(fname), os.path.join(ldir, fname))
    with open(os.path.join(ldir, "tpumon.txt"), "rb") as f:
        _tl = f.read().splitlines(keepends=True)
    with open(os.path.join(ldir, "tpumon.txt"), "wb") as f:
        f.write(b"".join(_tl[:len(_tl) // 2]))
    lcfg = SofaConfig(logdir=ldir, live_interval_s=0.0)
    sofa_live(lcfg, epochs=1)
    _lm1 = ((_live_lm(ldir) or {{}}).get("meta") or {{}}).get("live") or {{}}
    _ev1 = sum(s.get("events", 0)
               for s in (_lm1.get("sources") or {{}}).values())
    with open(os.path.join(ldir, "tpumon.txt"), "ab") as f:
        f.write(b"".join(_tl[len(_tl) // 2:]))
    t0 = time.perf_counter()
    rc = sofa_live(lcfg, epochs=1)
    if rc == 0:
        out["live_epoch_wall_time_s"] = round(time.perf_counter() - t0, 3)
        _lm2 = ((_live_lm(ldir) or {{}}).get("meta") or {{}}).get("live") or {{}}
        _ev2 = sum(s.get("events", 0)
                   for s in (_lm2.get("sources") or {{}}).values())
        out["live_lag_events"] = max(_ev2 - _ev1, 0)
        # the no-reparse contract: the incremental epoch parsed exactly
        # the one appended chunk, everything committed loaded
        if _lm2.get("chunks_parsed", 0) > 1:
            out["live_evidence_error"] = (
                f"incremental epoch reparsed "
                f"{{_lm2.get('chunks_parsed')}} chunk(s), expected 1")
    else:
        out["live_evidence_error"] = f"live rc={{rc}}"
    _sh.rmtree(ldir, ignore_errors=True)
except Exception as e:
    out["live_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# fleet evidence (sofa_tpu/archive/service.py + sofa_tpu/agent.py):
# loopback `sofa serve` + `sofa agent --once` push of this pod_synth
# logdir — spool ingest, have-list, object uploads, commit, all over a
# real HTTP round trip on an ephemeral port.  Needs no hardware and no
# network, so the fleet transport's wall time stays in the bench
# trajectory even on dead-tunnel rounds.
try:
    import threading as _th
    from sofa_tpu.agent import sofa_agent
    from sofa_tpu.archive.service import service_url, sofa_serve
    _fw = _tf.mkdtemp(prefix="sofa_fleet_")
    fcfg = SofaConfig(logdir=cfg.logdir, serve_token="bench",
                      serve_port=0)
    httpd = sofa_serve(fcfg, root=os.path.join(_fw, "store"),
                       serve_forever=False)
    if httpd is None:
        raise RuntimeError("serve failed to bind")
    _sthread = _th.Thread(target=httpd.serve_forever, daemon=True)
    _sthread.start()
    try:
        acfg = SofaConfig(logdir=cfg.logdir, serve_token="bench",
                          agent_service=service_url(httpd),
                          agent_spool=os.path.join(_fw, "spool"),
                          agent_settle_s=0.0)
        t0 = time.perf_counter()
        rc = sofa_agent(acfg, watch=cfg.logdir, once=True)
        if rc == 0:
            out["fleet_push_wall_time_s"] = round(
                time.perf_counter() - t0, 3)
        else:
            out["fleet_evidence_error"] = f"agent rc={{rc}}"
    finally:
        httpd.shutdown()
        _sthread.join(timeout=10)
        httpd.server_close()
        _sh.rmtree(_fw, ignore_errors=True)
except Exception as e:
    out["fleet_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# fleet tier evidence (sofa_tpu/archive/tier.py + tools/fleet_load.py):
# a seconds-scale smoke fleet — a forked 2-worker pool on loopback under
# concurrent synthetic agents + query pollers — lands the tier's p50/p99
# push/query latency and saturation throughput.  Needs no hardware, so
# the scaling tier's numbers ride dead-tunnel rounds too.
try:
    import subprocess as _sp
    _r = _sp.run(
        [sys.executable, os.path.join({root!r}, "tools", "fleet_load.py"),
         "--smoke", "--workers", "2"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if _r.returncode != 0:
        _tail = (_r.stderr.strip().splitlines() or ["?"])[-1]
        out["fleet_load_evidence_error"] = f"rc={{_r.returncode}}: " \
            f"{{_tail}}"[:160]
    else:
        _fl = json.loads(_r.stdout.strip().splitlines()[-1])
        for _k in ("fleet_push_p50_ms", "fleet_push_p99_ms",
                   "fleet_query_p50_ms", "fleet_query_p99_ms",
                   "fleet_saturation_rps"):
            if _k in _fl.get("metrics", {{}}):
                out[_k] = _fl["metrics"][_k]
        _tm = _fl.get("tier_metrics") or {{}}
        if _tm.get("scrape_wall_ms") is not None:
            out["tier_scrape_wall_time_s"] = round(
                _tm["scrape_wall_ms"] / 1000.0, 4)
        # metrics-overhead evidence (sofa_tpu/metrics.py): the SAME
        # smoke workload with the observability plane OFF
        # (SOFA_TIER_METRICS=0) — the saturation delta is what the
        # per-request counters/spans cost the push path (the ISSUE's
        # < 5% bar rides tier_metrics_overhead_pct)
        _r2 = _sp.run(
            [sys.executable,
             os.path.join({root!r}, "tools", "fleet_load.py"),
             "--smoke", "--workers", "2", "--no_metrics"],
            capture_output=True, text=True, timeout=240,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if _r2.returncode == 0:
            _fl2 = json.loads(_r2.stdout.strip().splitlines()[-1])
            _on = _fl.get("metrics", {{}}).get("fleet_saturation_rps")
            _off = _fl2.get("metrics", {{}}).get("fleet_saturation_rps")
            if _on and _off:
                out["tier_metrics_overhead_pct"] = round(
                    (_off - _on) / _off * 100.0, 2)
except Exception as e:
    out["fleet_load_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# self-healing tier evidence (tools/chaos_tier.py): the chaos-under-load
# smoke — a SIGKILLed worker, a full rolling restart, and a fires-once
# disk_full ENOSPC under sustained fleet_load traffic — lands the tier's
# recovery wall time (last push acked -> drained + healthy) and its
# typed refusal rate.  Needs no hardware, so both ride dead-tunnel
# rounds too.
try:
    import subprocess as _sp
    _r = _sp.run(
        [sys.executable, os.path.join({root!r}, "tools", "chaos_tier.py"),
         "--smoke", "--no_replica"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if _r.returncode != 0:
        _tail = (_r.stderr.strip().splitlines() or ["?"])[-1]
        out["chaos_tier_evidence_error"] = f"rc={{_r.returncode}}: " \
            f"{{_tail}}"[:160]
    else:
        _ct = json.loads(_r.stdout.strip().splitlines()[-1])
        for _k in ("tier_recovery_wall_time_s", "tier_refusal_rate_pct"):
            if _k in _ct.get("metrics", {{}}):
                out[_k] = _ct["metrics"][_k]
except Exception as e:
    out["chaos_tier_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# catalog-index evidence (sofa_tpu/archive/index.py): the fleet query
# path's steady-state numbers on a synthetic fleet archive —
# catalog_index_refresh_wall_time_s is the SUFFIX-ONLY refresh after one
# appended ingest (the per-ingest commit-point cost) and
# fleet_query_wall_time_s is the indexed sol-distance worst-offender
# ranking (the board's /v1/query).  The index answer is asserted equal
# to the linear scan before either number is emitted — a fast wrong
# answer is not evidence.  tools/catalog_bench.py prints the full
# 50k-run scan-vs-index table; needs no hardware, so both ride
# dead-tunnel rounds.
try:
    sys.path.insert(0, os.path.join({root!r}, "tools"))
    from catalog_bench import synthesize as _cat_synth
    from sofa_tpu.archive import catalog as _acat
    from sofa_tpu.archive import index as _aindex
    from sofa_tpu.archive.store import ArchiveStore as _AStore
    _cw = _tf.mkdtemp(prefix="sofa_catidx_")
    _croot = os.path.join(_cw, "archive")
    _cat_synth(_croot, 400)
    _aindex.refresh(_croot)
    _run = "e" * 64
    with open(os.path.join(_croot, "runs", _run + ".json"), "w") as f:
        json.dump({{"run": _run, "hostname": "hostX", "t": 1.8e9,
                   "features": {{"elapsed_time": 1.0,
                                "tpu0_sol_distance": 3.3}}}}, f)
    _acat.append_event(_croot, "ingest", run=_run, logdir="/x",
                       files=1, new_objects=1, bytes_added=10)
    t0 = time.perf_counter()
    _inc = _aindex.refresh(_croot)
    out["catalog_index_refresh_wall_time_s"] = round(
        time.perf_counter() - t0, 4)
    if _inc is None or _inc["_stats"]["full"]:
        out["catalog_evidence_error"] = "suffix refresh fell to full"
    t0 = time.perf_counter()
    _oi = _aindex.offenders(_croot, limit=20)
    out["fleet_query_wall_time_s"] = round(time.perf_counter() - t0, 4)
    _os2 = _aindex.offenders_scan(_AStore(_croot), limit=20)
    if _oi != _os2:
        out["catalog_evidence_error"] = "index != scan ranking"
    _sh.rmtree(_cw, ignore_errors=True)
except Exception as e:
    out["catalog_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
# fleet-pass evidence (sofa_tpu/analysis/fleet.py): the incremental
# cross-run engine's cold-vs-warm wall on a synthetic archive —
# fleet_analyze_wall_time_s is the full cold fan-out over the index and
# fleet_analyze_warm_wall_time_s the delta refresh after ONE appended
# ingest (the drainer's post-commit steady-state cost).  The warm
# report is asserted byte-identical to a drop-and-recompute before
# either number is emitted — a fast stale answer is not evidence.
# tools/fleet_analyze_bench.py prints the 50k-run cold/warm/per-pass
# table; needs no hardware, so both ride dead-tunnel rounds.
try:
    from catalog_bench import synthesize as _fcat_synth
    from sofa_tpu.analysis import fleet as _afleet
    from sofa_tpu.archive import catalog as _facat
    _fw = _tf.mkdtemp(prefix="sofa_fleet_pass_")
    _froot = os.path.join(_fw, "archive")
    _fcat_synth(_froot, 400)
    t0 = time.perf_counter()
    _afleet.analyze(_froot)
    out["fleet_analyze_wall_time_s"] = round(time.perf_counter() - t0, 4)
    _run = "f" * 64
    with open(os.path.join(_froot, "runs", _run + ".json"), "w") as f:
        json.dump({{"run": _run, "hostname": "hostX", "t": 1.8e9,
                   "features": {{"elapsed_time": 1.0,
                                "tpu0_sol_distance": 3.3}}}}, f)
    _facat.append_event(_froot, "ingest", run=_run, logdir="/x",
                        files=1, new_objects=1, bytes_added=10)
    t0 = time.perf_counter()
    _afleet.analyze(_froot)
    out["fleet_analyze_warm_wall_time_s"] = round(
        time.perf_counter() - t0, 4)
    _fwarm = open(_afleet.report_path(_froot), "rb").read()
    _afleet.drop(_froot)
    _afleet.analyze(_froot)
    if _fwarm != open(_afleet.report_path(_froot), "rb").read():
        out["fleet_analyze_evidence_error"] = "warm != cold recompute"
    _sh.rmtree(_fw, ignore_errors=True)
except Exception as e:
    out["fleet_analyze_evidence_error"] = \
        f"{{type(e).__name__}}: {{e}}"[:160]
# durability evidence (sofa_tpu/durability.py): fsck over the healthy
# logdir, then drop the preprocess commit marker (a crash one instruction
# before the commit) and time `sofa resume` — the number proves committed
# work is served warm from the content-keyed caches on replay.
try:
    from sofa_tpu import durability
    out["fsck_ok"] = durability.sofa_fsck(cfg) == 0
    jpath = cfg.path(durability.JOURNAL_NAME)
    with open(jpath) as f:
        lines = [ln for ln in f.read().splitlines()
                 if '"commit"' not in ln or '"preprocess"' not in ln]
    with open(jpath, "w") as f:
        f.write("\\n".join(lines) + "\\n")
    t0 = time.perf_counter()
    rc = durability.sofa_resume(cfg)
    if rc == 0:
        out["resume_wall_time_s"] = round(time.perf_counter() - t0, 3)
    else:
        out["durability_evidence_error"] = f"resume rc={{rc}}"
except Exception as e:
    out["durability_evidence_error"] = f"{{type(e).__name__}}: {{e}}"[:160]
print(json.dumps(out))
""".format(root=root, logdir=logdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        subprocess.run(
            [sys.executable, os.path.join(root, "tools", "pod_synth.py"),
             logdir, "--raw"],
            capture_output=True, timeout=300, check=True, env=env)
        r = subprocess.run([sys.executable, "-c", snippet],
                           capture_output=True, text=True, timeout=300,
                           env=env)
        if r.returncode != 0:
            tail = (r.stderr.strip().splitlines() or ["?"])[-1]
            return {"preprocess_wall_error": tail[:160]}
        doc = json.loads(r.stdout.strip().splitlines()[-1])
        _log(f"bench: preprocess wall time cold {doc['cold']}s / "
             f"warm-cache {doc['warm']}s (pod_synth --raw)")
        out = {"preprocess_wall_time_s": doc["cold"],
               "preprocess_warm_wall_time_s": doc["warm"]}
        # Viz-path secondary evidence (tools/viz_bench.py measures the
        # full picture; these ride every bench round): report.js payload
        # bytes + LOD tile-pyramid build wall time, plus the durability
        # pair — fsck over the healthy logdir and the crash-replay
        # `sofa resume` wall time (sofa_tpu/durability.py).
        for key in ("report_js_bytes", "tile_build_wall_time_s",
                    "viz_evidence_error", "fsck_ok", "resume_wall_time_s",
                    "durability_evidence_error", "analyze_wall_time_s",
                    "analyze_pass_count", "analyze_failed_passes",
                    "analyze_peak_rss_mb", "frame_load_wall_time_s",
                    "frame_evidence_error",
                    "analyze_evidence_error", "whatif_identity_error_pct",
                    "whatif_evidence_error", "fleet_push_wall_time_s",
                    "fleet_evidence_error", "fleet_push_p50_ms",
                    "fleet_push_p99_ms", "fleet_query_p50_ms",
                    "fleet_query_p99_ms", "fleet_saturation_rps",
                    "fleet_load_evidence_error",
                    "tier_metrics_overhead_pct", "tier_scrape_wall_time_s",
                    "tier_recovery_wall_time_s", "tier_refusal_rate_pct",
                    "chaos_tier_evidence_error",
                    "live_epoch_wall_time_s",
                    "live_lag_events", "live_evidence_error",
                    "catalog_index_refresh_wall_time_s",
                    "fleet_query_wall_time_s", "catalog_evidence_error",
                    "fleet_analyze_wall_time_s",
                    "fleet_analyze_warm_wall_time_s",
                    "fleet_analyze_evidence_error"):
            if key in doc:
                out[key] = doc[key]
        if "report_js_bytes" in out:
            _log(f"bench: report.js {out['report_js_bytes']} B, "
                 f"tile build {out.get('tile_build_wall_time_s')}s")
        if "fsck_ok" in out:
            _log(f"bench: fsck_ok={out['fsck_ok']}, resume wall "
                 f"{out.get('resume_wall_time_s')}s (crash-replay)")
        if "analyze_wall_time_s" in out:
            _log(f"bench: analyze wall {out['analyze_wall_time_s']}s, "
                 f"{out.get('analyze_pass_count')} pass(es), "
                 f"{out.get('analyze_failed_passes')} failed")
        if "whatif_identity_error_pct" in out:
            _log(f"bench: whatif identity error "
                 f"{out['whatif_identity_error_pct']}% (zero-scenario "
                 "replay vs measured — no hardware needed)")
        if "fleet_push_wall_time_s" in out:
            _log(f"bench: fleet push wall "
                 f"{out['fleet_push_wall_time_s']}s (loopback serve + "
                 "agent spool-and-push of the pod_synth logdir)")
        if "fleet_saturation_rps" in out:
            _log(f"bench: fleet tier smoke "
                 f"{out['fleet_saturation_rps']} pushes/s, push p99 "
                 f"{out.get('fleet_push_p99_ms')} ms, query p99 "
                 f"{out.get('fleet_query_p99_ms')} ms (2-worker pool, "
                 "tools/fleet_load.py --smoke)")
        if "tier_metrics_overhead_pct" in out:
            _log(f"bench: tier metrics overhead "
                 f"{out['tier_metrics_overhead_pct']}% of push "
                 f"saturation, scrape wall "
                 f"{out.get('tier_scrape_wall_time_s')}s (metrics on "
                 "vs SOFA_TIER_METRICS=0)")
        if "tier_recovery_wall_time_s" in out:
            _log(f"bench: chaos tier recovery "
                 f"{out['tier_recovery_wall_time_s']}s, refusal rate "
                 f"{out.get('tier_refusal_rate_pct')}% (worker kill + "
                 "rolling restart + disk_full under load, "
                 "tools/chaos_tier.py --smoke)")
        if "live_epoch_wall_time_s" in out:
            _log(f"bench: live incremental epoch "
                 f"{out['live_epoch_wall_time_s']}s, drained "
                 f"{out.get('live_lag_events')} lagged event(s) "
                 "(tail-append, zero committed chunks reparsed)")
        if "fleet_query_wall_time_s" in out:
            _log(f"bench: catalog index suffix refresh "
                 f"{out.get('catalog_index_refresh_wall_time_s')}s, "
                 f"indexed sol-rank query "
                 f"{out['fleet_query_wall_time_s']}s "
                 "(scan-identical, tools/catalog_bench.py has the "
                 "50k table)")
        if "fleet_analyze_wall_time_s" in out:
            _log(f"bench: fleet analyze cold "
                 f"{out['fleet_analyze_wall_time_s']}s, warm delta "
                 f"{out.get('fleet_analyze_warm_wall_time_s')}s "
                 "(byte-identical to recompute, "
                 "tools/fleet_analyze_bench.py has the 50k table)")
        # Every bench run also asserts the self-telemetry ledger the
        # preprocess above must have written (tools/manifest_check.py):
        # a healthy number from an unhealthy pipeline is not evidence.
        mc = subprocess.run(
            [sys.executable,
             os.path.join(root, "tools", "manifest_check.py"),
             logdir, "--require-healthy"],
            capture_output=True, text=True, timeout=60, env=env)
        out["manifest_ok"] = mc.returncode == 0
        if mc.returncode != 0:
            tail = (mc.stderr.strip().splitlines() or ["?"])[-1]
            out["manifest_error"] = tail[:160]
            _log(f"bench: manifest_check FAILED: {tail[:160]}")
        else:
            _log("bench: manifest_check OK (run_manifest.json healthy)")
        return out
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"preprocess_wall_error": f"{type(e).__name__}: {e}"[:160]}
    finally:
        shutil.rmtree(os.path.dirname(logdir), ignore_errors=True)


#: Rule-id prefix -> evidence family for the per-family finding counts
#: (docs/STATIC_ANALYSIS.md's catalog sections).
_LINT_FAMILIES = (
    ("core", range(0, 10)),          # SL000–SL009: runtime contracts
    ("passes", range(10, 14)),       # SL010–SL013: pass registry
    ("artifacts", range(14, 19)),    # SL014–SL018: artifact lifecycle
    ("concurrency", range(19, 24)),  # SL019–SL023: guards & ordering
)


def _lint_families(by_rule: dict) -> dict:
    counts = {name: 0 for name, _r in _LINT_FAMILIES}
    for rule, n in (by_rule or {}).items():
        try:
            num = int(rule[2:])
        except (ValueError, IndexError):
            continue
        for name, rng in _LINT_FAMILIES:
            if num in rng:
                counts[name] += int(n)
    return counts


def _lint_evidence() -> dict:
    """Static-analysis gate riding the evidence extras: run sofa-lint over
    the package and report ``lint_ok`` + the new-finding count, the wall
    time of the lint itself (the engine must stay cheap enough to run on
    every commit), and per-rule-family finding counts — so a bench round
    whose code silently broke a runtime contract (unbounded subprocess,
    swallowed except, an unguarded shared write) is visibly unhealthy
    even when its numbers look fine.  Needs no device; opt out with
    SOFA_BENCH_LINT=0.  Emitted on success AND dead-tunnel paths.
    """
    import subprocess

    if os.environ.get("SOFA_BENCH_LINT", "1") != "1":
        return {}
    _state["phase"] = "sofa-lint evidence"
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "sofa_lint.py"),
             os.path.join(root, "sofa_tpu"), "--json"],
            capture_output=True, text=True, timeout=120)
        wall = round(time.monotonic() - t0, 3)
        if r.returncode == 2:
            return {"lint_error": (r.stderr.strip().splitlines()
                                   or ["internal error"])[-1][:160],
                    "lint_wall_time_s": wall}
        doc = json.loads(r.stdout)
        n_new = len(doc.get("new", []))
        _log(f"bench: sofa-lint {'OK' if not n_new else 'FAILED'} "
             f"({n_new} new, {doc.get('baselined', 0)} baselined, "
             f"{wall:.2f}s)")
        return {"lint_ok": n_new == 0, "lint_new_findings": n_new,
                "lint_wall_time_s": wall,
                "lint_findings_by_family": _lint_families(
                    doc.get("by_rule"))}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"lint_error": f"{type(e).__name__}: {e}"[:160]}


def _artifact_evidence() -> dict:
    """Artifact-lifecycle closure riding the evidence extras: build the
    `sofa artifacts` inventory (sofa_tpu/artifacts.py) and report
    ``artifact_inventory_ok`` + ``artifact_count``, so a bench round
    whose code leaked an unregistered artifact past `sofa clean` or
    blind-sided fsck is visibly unhealthy.  Needs no device; shares the
    SOFA_BENCH_LINT=0 opt-out with the lint gate (same static-analysis
    family)."""
    if os.environ.get("SOFA_BENCH_LINT", "1") != "1":
        return {}
    _state["phase"] = "artifact-inventory evidence"
    try:
        from sofa_tpu.artifacts import build_inventory

        doc = build_inventory()
        ok = bool(doc.get("ok"))
        _log(f"bench: artifact inventory {'OK' if ok else 'FAILED'} "
             f"({doc['counts']['artifacts']} artifacts, "
             f"{doc['counts']['violations']} violations)")
        return {"artifact_inventory_ok": ok,
                "artifact_count": int(doc["counts"]["artifacts"])}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"artifact_error": f"{type(e).__name__}: {e}"[:160]}


def _protocol_evidence() -> dict:
    """Protocol-contract closure riding the evidence extras: build the
    `sofa protocol` inventory (sofa_tpu/protocol.py) and report
    ``protocol_inventory_ok`` + ``protocol_route_count``, so a bench
    round whose code drifted the client<->server contract (an
    undeclared status, a refusal without Retry-After, an undocumented
    SOFA_* knob) is visibly unhealthy.  Needs no device; shares the
    SOFA_BENCH_LINT=0 opt-out with the lint gate (same static-analysis
    family)."""
    if os.environ.get("SOFA_BENCH_LINT", "1") != "1":
        return {}
    _state["phase"] = "protocol-inventory evidence"
    try:
        from sofa_tpu.protocol import build_inventory

        doc = build_inventory()
        ok = bool(doc.get("ok"))
        _log(f"bench: protocol inventory {'OK' if ok else 'FAILED'} "
             f"({doc['counts']['routes']} routes, "
             f"{doc['counts']['violations']} violations)")
        return {"protocol_inventory_ok": ok,
                "protocol_route_count": int(doc["counts"]["routes"])}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"protocol_error": f"{type(e).__name__}: {e}"[:160]}


# Metrics whose trajectory the archive catalog tracks round over round
# (the headline plus the device-free report-path numbers, so dead-tunnel
# rounds still extend the trajectory).
_ARCHIVED_METRICS = ("resnet50_profiling_overhead", "preprocess_wall_time_s",
                     "preprocess_warm_wall_time_s", "tile_build_wall_time_s",
                     "resume_wall_time_s", "report_js_bytes",
                     "analyze_wall_time_s", "whatif_identity_error_pct",
                     "fleet_push_wall_time_s", "live_epoch_wall_time_s",
                     "live_lag_events", "frame_load_wall_time_s",
                     "analyze_peak_rss_mb",
                     "catalog_index_refresh_wall_time_s",
                     "fleet_query_wall_time_s",
                     "fleet_analyze_wall_time_s",
                     "fleet_analyze_warm_wall_time_s",
                     "fleet_push_p50_ms",
                     "fleet_push_p99_ms", "fleet_query_p50_ms",
                     "fleet_query_p99_ms", "fleet_saturation_rps",
                     "tier_metrics_overhead_pct", "tier_scrape_wall_time_s",
                     "tier_recovery_wall_time_s", "tier_refusal_rate_pct")


def _archive_evidence(value, extra: dict) -> dict:
    """Append this round's evidence into the fleet trace-archive catalog
    (sofa_tpu/archive/) and regress it against the archived trajectory.

    This is what retires the hand-rolled BENCH_r0*.json flat files: the
    catalog is the bench trajectory, append-only and fsync'd, and the
    returned ``regress_verdict`` (rolling median-CI per metric — noise
    until >= 6 rounds exist, by design) rides the evidence extras on
    success AND dead-tunnel paths.  Opt out with SOFA_BENCH_ARCHIVE=0.
    """
    if os.environ.get("SOFA_BENCH_ARCHIVE", "1") != "1":
        return {}
    _state["phase"] = "archiving bench evidence"
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        from sofa_tpu.archive import catalog as acat
        from sofa_tpu.archive.baseline import polarity, rolling_verdict
        from sofa_tpu.archive.store import ArchiveStore

        aroot = os.environ.get("SOFA_ARCHIVE_ROOT") \
            or os.path.join(root, "sofa_archive")
        ArchiveStore(aroot, create=True)  # marker: clean/fsck recognize it
        tracked = {"resnet50_profiling_overhead": value}
        for key in _ARCHIVED_METRICS[1:]:
            v = extra.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                tracked[key] = float(v)
        entries = acat.bench_entries(acat.read_catalog(aroot))
        tag = _next_round_tag(root)
        verdicts = {}
        for metric, v in tracked.items():
            if v is None:
                continue
            samples = [float(e["value"]) for e in entries
                       if e.get("metric") == metric
                       and isinstance(e.get("value"), (int, float))]
            verdicts[metric] = rolling_verdict(
                float(v), samples, 50.0, 10.0, polarity(metric))
            acat.append_event(aroot, "bench", metric=metric,
                              value=float(v), round=tag)
        overall = "noise"
        if any(d["verdict"] == "regressed" for d in verdicts.values()):
            overall = "regressed"
        elif any(d["verdict"] == "improved" for d in verdicts.values()):
            overall = "improved"
        _log(f"bench: archived {len(tracked)} metric(s) as round {tag} "
             f"-> {aroot} (rolling verdict: {overall})")
        return {"regress_verdict": {
            "verdict": overall,
            "metrics": {m: d["verdict"] for m, d in verdicts.items()},
            "rounds_archived": len({e.get('round') for e in entries}
                                   | {tag}),
            "archive_root": aroot,
        }}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"archive_error": f"{type(e).__name__}: {e}"[:160]}


class _Hung(Exception):
    pass


def _with_timeout(fn, timeout_s: float):
    """Run fn on a watchdog thread; raise _Hung if it outlives timeout_s.

    The hung thread cannot be killed — callers must treat _Hung as fatal
    for in-process backend work (the backend lock may be wedged).
    """
    import threading

    box = {}

    def run():
        try:
            box["value"] = fn()
        except Exception as e:  # noqa: BLE001 — re-raised on the caller side
            box["error"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        raise _Hung(f"call outlived {timeout_s:.0f}s")
    if "error" in box:
        raise box["error"]
    return box["value"]


def _init_backend(budget_s: Optional[float] = None,
                  timeout_s: float = 90.0):
    """Initialize the JAX backend, outlasting a transiently-dead chip tunnel.

    Every attempt probes backend init in a *subprocess* first: a dead or
    busy device tunnel makes jax.devices() hang rather than raise, and a
    probe hang/failure costs us nothing in-process, so waiting is free and
    safe.  The observed failure mode is a tunnel that dies for HOURS (rounds
    1 and 2 both lost the race with a ~2.5 min retry window), so retries run
    against a total time budget — SOFA_BENCH_RETRY_BUDGET_S, default 15 min:
    round 3 proved the driver's own timeout is ~20 min, and a budget that
    outlives the driver means the driver kills us mid-retry — with capped
    exponential backoff rather than a fixed attempt count.

    On the first healthy probe the validate_tpu checklist runs in the same
    window (subprocess — see _run_validate_checklist), then the real
    in-process init runs under a watchdog; if THAT hangs despite a healthy
    probe, the backend lock is wedged and retrying in this process is
    pointless.
    """
    import jax

    if budget_s is None:
        budget_s = float(os.environ.get("SOFA_BENCH_RETRY_BUDGET_S", "900"))
    deadline = time.monotonic() + budget_s
    backoff, attempt, last, validated = 15.0, 0, None, False
    while True:
        if attempt:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise last or RuntimeError(
                    f"no healthy tunnel window within {budget_s:.0f}s budget")
            _emit_provisional_once()
            _state["phase"] = (f"retrying backend init "
                               f"({remaining:.0f}s budget left)")
            sleep = min(backoff, max(remaining, 1.0))
            _log(f"bench: retry {attempt} in {sleep:.0f}s "
                 f"(budget {remaining:.0f}s left)")
            time.sleep(sleep)
            backoff = min(backoff * 1.7, 150.0)
            try:
                import jax.extend.backend as jeb

                _with_timeout(jeb.clear_backends, 30.0)
            except Exception:
                pass
        attempt += 1
        reason = _preflight()
        if reason is not None:
            last = RuntimeError(reason)
            _log(f"bench: {reason}")
            _log_chip_holders()
            continue
        if not validated:
            validated = True
            if _run_validate_checklist() and _preflight() is not None:
                # the (killed?) checklist may hold the chip briefly; the
                # budget loop absorbs the wait
                _log("bench: chip busy after checklist; re-entering retries")
                last = RuntimeError("chip busy after validate checklist")
                continue
        try:
            devs = _with_timeout(jax.devices, timeout_s)
            _log(f"bench: backend={jax.default_backend()} devices={devs}")
            return devs
        except _Hung:
            err = RuntimeError(
                f"in-process backend init hung > {timeout_s:.0f}s despite "
                "a healthy subprocess probe; backend lock wedged")
            _log(f"bench: {err}")
            _log_chip_holders()
            raise err from None
        except Exception as e:  # RuntimeError / JaxRuntimeError
            last = e
            _log(f"bench: backend init failed: {type(e).__name__}: "
                 f"{str(e).splitlines()[0] if str(e) else e!r}")
            _log_chip_holders()


def _cpu_fallback_evidence() -> dict:
    """Tunnel dead for the whole budget: measure the SAME paired-run
    overhead on the CPU backend in a fresh subprocess and ride it on the
    error line's extras.  The headline metric stays null — a CPU number is
    not the TPU number — but the round still records that the harness
    measures end to end (collector injection, trace capture, coverage
    guard) rather than only that the relay was down.  Opt out with
    SOFA_BENCH_CPU_FALLBACK=0.
    """
    import subprocess

    if os.environ.get("SOFA_BENCH_CPU_FALLBACK", "1") != "1":
        return {}
    _state["phase"] = "cpu-backend evidence smoke"
    _log("bench: tunnel never came up — measuring CPU-backend overhead "
         "evidence (headline value stays null)")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SOFA_BENCH_RETRY_BUDGET_S="60",
        SOFA_BENCH_CPU_FALLBACK="0",   # no recursion
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--batch", "8", "--image_size", "64", "--steps", "5",
             "--repeats", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        _state["smoke_child"] = proc   # the signal handler kills it with us
        try:
            stdout, _stderr = proc.communicate(timeout=240)
        finally:
            _state["smoke_child"] = None
            if proc.poll() is None:
                proc.kill()
        r = type("R", (), {"stdout": stdout, "returncode": proc.returncode})
        for line in reversed(r.stdout.splitlines()):
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if not isinstance(doc, dict):
                continue  # a bare JSON scalar on stdout is not the result
            if doc.get("value") is None:
                return {"cpu_smoke_error": str(doc.get("error"))[:160]}
            return {
                "cpu_smoke_overhead_pct": doc["value"],
                # host runtime rows ARE the capture proof on CPU (no
                # device planes exist by construction)
                "cpu_smoke_host_rows": doc.get("host_rows"),
                "cpu_smoke_backend": doc.get("backend"),
            }
        return {"cpu_smoke_error": f"no JSON line (rc={r.returncode})"}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        return {"cpu_smoke_error": f"{type(e).__name__}: {e}"[:160]}


def _time_steps(step, state_maker, n_steps: int, annotate: bool):
    from sofa_tpu.workloads.common import fence, step_annotation

    state = state_maker()
    state = step(state)                      # compile
    fence(state)   # NOT block_until_ready: see workloads/common.py:fence
    t0 = time.perf_counter()
    for i in range(n_steps):
        if annotate:
            with step_annotation(i):
                state = step(state)
        else:
            state = step(state)
    fence(state)
    return time.perf_counter() - t0


def main() -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--repeats", type=int, default=3,
                   help="paired bare/profiled passes; medians are compared "
                        "(pass 0 sometimes runs anomalously fast right after "
                        "compile; the median of 3 discards it)")
    args = p.parse_args()

    _install_signal_handlers()

    import os

    import jax

    # The image's sitecustomize may force-prepend a TPU platform; if the user
    # explicitly asked for something else (JAX_PLATFORMS=cpu smoke runs),
    # honor the env var over the injected override.
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    import jax.numpy as jnp

    from sofa_tpu.workloads.resnet import create, make_train_step

    _state["phase"] = "initializing backend"
    try:
        _init_backend()
    except Exception as e:
        msg = str(e).splitlines()[0] if str(e) else repr(e)
        err = f"backend init failed after retries: {type(e).__name__}: {msg}"
        # The committed last-good on-chip result rides the error line so a
        # dead-tunnel driver window still carries the evidence chain.
        lg = _read_last_good()
        base = {"last_good": lg} if lg else {}
        # Error line FIRST — the smoke below can take minutes and a driver
        # kill in that window must still find a parseable line (round 3
        # regressed to parsed:null exactly by deferring the final emit).
        _emit(None, error=err, extra=base or None)
        extra = _cpu_fallback_evidence()
        # Report-path perf needs no chip: the preprocess wall-time metric
        # keeps this round's trajectory non-null even with a dead tunnel.
        extra.update(_preprocess_wall_evidence())
        extra.update(_lint_evidence())
        extra.update(_artifact_evidence())
        extra.update(_protocol_evidence())
        # Dead-tunnel rounds still extend the archived trajectory: the
        # report-path metrics need no device, and the rolling verdict
        # proves the round against the catalog's history.
        extra.update(_archive_evidence(None, extra))
        if extra:
            # The driver reads the LAST parseable line: re-emit the same
            # error enriched with the CPU-backend evidence.
            merged = dict(base)
            merged.update(extra)
            _emit(None, error=err, extra=merged)
        return 1

    model, variables, x = create(args.batch, args.image_size)
    labels = jnp.zeros((args.batch,), jnp.int32)
    tx, train = make_train_step(model)
    opt_state = tx.init(variables["params"])

    def state_maker():
        return (variables["params"], variables["batch_stats"], opt_state, 0.0)

    def step(state):
        params, stats, opt, _ = state
        return train(params, stats, opt, x, labels)

    import sofa_tpu.api as sofa
    from sofa_tpu.ingest.xplane import ingest_xprof_dir

    bare, prof = [], []
    hlo_rows = 0
    logdir = tempfile.mkdtemp(prefix="sofa_bench_") + "/"
    try:
        for r in range(args.repeats):
            _state["phase"] = f"measuring pass {r + 1}/{args.repeats}"
            tb = _time_steps(step, state_maker, args.steps, annotate=False)
            bare.append(tb)
            run_dir = f"{logdir}r{r}/"
            with sofa.profile(run_dir):
                tp = _time_steps(step, state_maker, args.steps, annotate=True)
            prof.append(tp)
            _log(f"bench: pass {r}: bare {tb:.3f}s profiled {tp:.3f}s")
        frames = ingest_xprof_dir(f"{logdir}r{args.repeats - 1}/xprof/",
                                  time.time())
        hlo_rows = len(frames.get("tputrace", []))
        host_rows = len(frames.get("hosttrace", []))
    except Exception as e:
        _emit(None, error=f"benchmark run failed: {type(e).__name__}: "
                          f"{str(e).splitlines()[0] if str(e) else e!r}")
        return 1
    finally:
        shutil.rmtree(logdir, ignore_errors=True)

    p_value = None
    if len(bare) >= 2:
        try:
            from scipy import stats

            p_value = float(stats.ttest_rel(prof, bare).pvalue)
        except Exception:  # noqa: BLE001 — significance is optional
            pass
    bare.sort()
    prof.sort()
    t_bare = bare[len(bare) // 2]
    t_prof = prof[len(prof) // 2]
    overhead = max(0.0, (t_prof - t_bare) / t_bare * 100.0)
    # Coverage guard: an overhead number with an empty capture is a lie.
    # On TPU the evidence is HLO device ops; a CPU(-smoke) backend has no
    # device planes by construction, so its capture proof is the host
    # runtime trace.
    if hlo_rows == 0 and (jax.default_backend() == "tpu" or host_rows == 0):
        _log("bench: FAILED coverage guard — empty captured trace")
        overhead = 100.0
    _log(f"bench: images/s bare {args.steps * args.batch / t_bare:.1f}, "
         f"profiled {args.steps * args.batch / t_prof:.1f}; "
         f"trace rows {hlo_rows}")
    extra = {
        "images_per_sec_bare": round(args.steps * args.batch / t_bare, 1),
        "images_per_sec_profiled": round(args.steps * args.batch / t_prof, 1),
        "hlo_rows": int(hlo_rows),
        "host_rows": int(host_rows),
        "backend": jax.default_backend(),
    }
    out = _emit(round(overhead, 3), p_value=p_value, extra=extra)
    # Only a real-chip result with a non-empty device capture becomes the
    # cached evidence — a CPU smoke number must never masquerade as one.
    if jax.default_backend() == "tpu" and hlo_rows > 0:
        _write_last_good(out)
    # Secondary report-path metric AFTER the headline emit (the driver
    # reads the LAST parseable line; a kill during this minute-scale
    # evidence run must still find the real result above).
    pre = _preprocess_wall_evidence()
    pre.update(_lint_evidence())
    pre.update(_artifact_evidence())
    pre.update(_protocol_evidence())
    pre.update(_archive_evidence(round(overhead, 3), {**extra, **pre}))
    if pre:
        _emit(round(overhead, 3), p_value=p_value, extra={**extra, **pre})
    return 0


if __name__ == "__main__":
    sys.exit(main())
