"""Environment doctor & enabler: ``sofa setup``.

The reference splits host enablement across three root-needing helpers —
sysctl tweaks (/root/reference/tools/enable_strace_perf_pcm.py), capability
grants for tcpdump-style utilities via a "sofa" group
(/root/reference/tools/empower.py:46-60), and a distro-probing dependency
installer (/root/reference/tools/prepare.sh).  Here all of it is one
subcommand with a safe default: ``sofa setup`` *reports* what each collector
needs and prints the exact commands; ``sofa setup --apply`` runs them
(through sudo when available).  Nothing is installed — the TPU image is
expected to ship its own toolchain, so missing binaries only degrade the
matching collector.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Callable, List, Optional, Tuple

from sofa_tpu.printing import print_hint, print_info, print_progress, print_warning

# (sysctl key, value wanted for full-fidelity perf/strace recording)
SYSCTLS = [
    ("kernel.perf_event_paranoid", "-1"),
    ("kernel.kptr_restrict", "0"),
]

# Collector binaries and the subsystem each one unlocks.
TOOLS = [
    ("perf", "CPU sampling (collectors/perf.py)"),
    ("tcpdump", "DCN packet capture (collectors/hostproc.py)"),
    ("blktrace", "block-IO tracing (collectors/hostproc.py)"),
    ("blkparse", "block-IO trace decoding"),
    ("strace", "syscall tracing (collectors/hostproc.py)"),
    ("vmstat", "memory/context-switch sampling"),
]

# Capabilities a non-root profiling user needs per utility (empower.py's
# setcap line, generalized).
CAPS = {
    "tcpdump": "cap_net_raw,cap_net_admin=eip",
    "blktrace": "cap_sys_admin=eip",
    "perf": "cap_perfmon,cap_sys_ptrace=eip",
}


def _read_sysctl(key: str) -> Optional[str]:
    path = "/proc/sys/" + key.replace(".", "/")
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def _sudo_prefix() -> str:
    return "sudo " if shutil.which("sudo") and os.geteuid() != 0 else ""


def check(utilities: Optional[List[str]] = None) -> Tuple[List[str], int]:
    """Returns (fix commands, number of problems found) and prints a report."""
    fixes: List[str] = []
    problems = 0
    sudo = _sudo_prefix()

    for key, want in SYSCTLS:
        have = _read_sysctl(key)
        if have is None:
            print_warning(f"setup: {key} unreadable (sandboxed /proc?)")
            problems += 1
        elif have != want:
            print_warning(f"setup: {key} = {have}, want {want}")
            fixes.append(f"{sudo}sysctl -w {key}={want}")
            problems += 1
        else:
            print_info(f"setup: {key} = {have} ok")

    for tool, why in TOOLS:
        path = shutil.which(tool)
        if path:
            print_info(f"setup: {tool} found at {path}")
        else:
            print_warning(f"setup: {tool} missing — degrades {why}")
            problems += 1

    for util in utilities or []:
        path = shutil.which(util) or util
        cap = CAPS.get(os.path.basename(path))
        if cap is None:
            print_warning(
                f"setup: no capability profile for {util!r} (known: "
                f"{', '.join(sorted(CAPS))}) — refusing to guess a grant")
            problems += 1
            continue
        if not os.path.isfile(path):
            print_warning(f"setup: {util}: not a file, cannot grant caps")
            problems += 1
            continue
        got = ""
        if shutil.which("getcap"):
            out = subprocess.run(["getcap", path], capture_output=True,
                                 text=True)
            got = out.stdout.strip()
        # getcap prints caps sorted by capability number, so compare the
        # individual names, not the whole comma-joined string.
        if all(c in got for c in cap.split("=")[0].split(",")):
            print_info(f"setup: {path} already has {cap}")
        else:
            print_warning(f"setup: {path} lacks {cap}")
            fixes.append(f"{sudo}setcap {cap} {path}")
            problems += 1

    # TPU side: purely file-level checks; never touch the JAX backend here
    # (its init can hang when the chip is busy, and `setup` must always work).
    accel = [d for d in ("/dev/accel0", "/dev/vfio/0") if os.path.exists(d)]
    if accel:
        print_info(f"setup: TPU device node present: {', '.join(accel)}")
    else:
        print_info("setup: no local TPU device node (remote/tunneled chips "
                   "are still usable via JAX)")
    return fixes, problems


def sofa_setup(utilities: Optional[List[str]] = None, apply: bool = False,
               runner: Callable[[str], int] = None) -> int:
    """Report (and with apply=True, fix) host prerequisites.

    runner is injectable for tests; defaults to shell execution.
    """
    fixes, problems = check(utilities)
    if not fixes:
        if problems:
            print_hint(f"setup: {problems} issue(s), none auto-fixable "
                       "(install missing tools via your image/package manager)")
        else:
            print_progress("setup: environment fully enabled")
        return 0 if not problems else 1
    if not apply:
        print_hint("setup: run these (or re-run with --apply):")
        for cmd in fixes:
            print(f"  {cmd}")
        return 1
    run = runner or (lambda c: subprocess.run(c, shell=True).returncode)
    rc = 0
    for cmd in fixes:
        print_progress(f"setup: {cmd}")
        rc = max(rc, run(cmd))
    return rc
