"""Perfetto / Chrome-trace export of the unified timeline.

``sofa export --perfetto`` writes ``trace.json.gz`` in the Trace Event
Format, openable in ui.perfetto.dev or chrome://tracing — so a sofa
capture can ride the ecosystem's standard trace viewer in addition to the
built-in board.  The reference has no equivalent (its only interchange
formats are CSVs); this is TPU-first interop: every frame of the unified
schema maps onto Perfetto's process/thread/track model:

  process = device (tpu<N> / host / custom plane), named via metadata
  thread  = lane within the device (sync ops, async DMA, Steps, modules,
            host threads by tid)
  X events = spans (ops, steps, host events) with args carrying the
            schema's analysis columns (flops, bytes, phase, op_path, ...)
  C events = counter tracks from tpuutil (tc/mxu util %, HBM GB/s),
    tpumon (live HBM used/occupancy per device) and
            host net/cpu series

Timestamps are emitted in microseconds relative to the capture so traces
stay compact.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu.printing import print_progress, print_warning

# Stable synthetic pids per source "process" — Perfetto groups tracks by pid.
_HOST_PID = 1_000_000
_CUSTOM_PID = 1_100_000
_SELF_PID = 1_200_000  # sofa's own pipeline spans (sofa_self_trace.json)

PERFETTO_FRAMES = ["tputrace", "tpusteps", "tpumodules", "hosttrace",
                   "customtrace", "tpuutil", "tpumon", "mpstat",
                   "netbandwidth"]


# Row iteration uses itertuples for the SMALL frames; the pod-scale op
# frame gets a columnar path below (itertuples walks arrow-backed string
# cells one by one — ~12M __iter__ calls on a 1.6M-row trace — and
# per-event json.dumps dominated the export; column-wise bulk conversion +
# cached per-unique-args serialization cut the 1.6M-event export ~4x).

def _op_args(row) -> Dict[str, object]:
    args: Dict[str, object] = {}
    for key in ("hlo_category", "module", "phase", "op_path", "source"):
        v = getattr(row, key, "")
        if v:
            args[key] = str(v)
    for key in ("flops", "bytes_accessed", "payload"):
        v = getattr(row, key, 0)
        if v:
            args[key] = float(v)
    g = getattr(row, "groups", "")
    if g:
        args["replica_groups"] = str(g)
    return args


class _DeviceColumns:
    """The pod-scale op frame, reduced to per-signature JSON prefixes plus
    flat ts/dur/pid/lane/sig arrays — the exact input of the native writer
    (native/perfetto_write.cc) and of the Python fallback loop."""

    def __init__(self, ops: pd.DataFrame) -> None:
        import numpy as np

        self.n = len(ops)
        # Clamp AFTER the µs scale: nan->0 before *1e6 would let an inf (or
        # ~1.8e302 s) re-overflow and both writers would emit the invalid
        # JSON token `inf`.  ±1e15 µs (~31 years) is beyond any real trace,
        # and %.3f of it stays well inside the native writer's buffer.
        self.ts = np.clip(np.nan_to_num(
            ops["timestamp"].to_numpy(dtype=float) * 1e6,
            posinf=1e15, neginf=-1e15), -1e15, 1e15)
        self.dur = np.clip(np.nan_to_num(
            ops["duration"].to_numpy(dtype=float) * 1e6,
            posinf=1e15), 0.0, 1e15)
        self.pid = ops["deviceId"].to_numpy(dtype=np.int32)
        cat = ops["category"].to_numpy(dtype=int)
        self.lane = np.where(
            cat == 0, 0, np.where(cat == 2, 1, 2)).astype(np.uint8)

        # Args are metadata-derived, so the (name, args) pair takes only a
        # few hundred distinct values in a pod-scale trace.  An EXACT
        # vectorized signature (groupby.ngroup over the arg columns, C
        # speed, no hash collisions) means only the FIRST row of each
        # signature is ever converted to Python objects.
        sig_cols = [k for k in ("name", "hlo_category", "module", "phase",
                                "op_path", "source", "flops",
                                "bytes_accessed", "payload", "groups")
                    if k in ops.columns]
        sig_arr = ops.groupby(sig_cols, sort=False, dropna=False).ngroup() \
            .to_numpy()
        self.sig = sig_arr.astype(np.uint32)
        uniq, firsts = np.unique(sig_arr, return_index=True)
        dumps = json.dumps
        self.prefixes: List[str] = [""] * len(uniq)
        for s, row in zip(uniq.tolist(),
                          ops.iloc[firsts].itertuples(index=False)):
            self.prefixes[s] = (
                f'{{"name":{dumps(str(row.name))},"ph":"X","cat":"tpu_op",'
                f'"args":{dumps(_op_args(row), separators=(",", ":"))},')

    def event_strings(self) -> "List[str]":
        """Python fallback: pre-serialized Trace-Event lines (floats via
        repr — valid JSON for the finite floats nan_to_num guarantees)."""
        prefix = self.prefixes
        sig = self.sig.tolist()  # .tolist() yields PYTHON scalars;
        ts = self.ts.tolist()    # np.float64's repr is not valid JSON
        dur = self.dur.tolist()
        pid = self.pid.tolist()
        lane = self.lane.tolist()
        return [
            f'{prefix[sig[i]]}"ts":{ts[i]!r},"dur":{dur[i]!r},'
            f'"pid":{pid[i]},"tid":{lane[i]}}}'
            for i in range(self.n)
        ]


def _steps_events(steps: pd.DataFrame, events: List[dict]) -> None:
    for row in steps.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "step",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": int(row.deviceId), "tid": 3,
        })


def _module_events(mods: pd.DataFrame, events: List[dict]) -> None:
    for row in mods.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "xla_module",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": int(row.deviceId), "tid": 4,
        })


def _host_events(host: pd.DataFrame, events: List[dict]) -> None:
    # deviceId on host rows is the host's ordinal base (host_index*256), so
    # each host of a pod gets its own Perfetto process — thread ids from
    # different machines must never interleave on one track.
    for row in host.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "X", "cat": "host",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": _HOST_PID + max(int(row.deviceId), 0),
            "tid": int(row.tid) & 0x7FFFFFFF,
            "args": ({"thread": row.module}
                     if getattr(row, "module", "") else {}),
        })


def _custom_events(custom: pd.DataFrame, events: List[dict],
                   plane_pids: Dict[tuple, int]) -> None:
    # One pid per (host, plane label): a runtime can emit several CUSTOM
    # planes per host and they share deviceId (the host's ordinal base).
    for row in custom.itertuples(index=False):
        key = (int(row.deviceId), getattr(row, "module", ""))
        pid = plane_pids.setdefault(key, _CUSTOM_PID + len(plane_pids))
        events.append({
            "name": row.name, "ph": "X", "cat": "custom_plane",
            "ts": row.timestamp * 1e6,
            "dur": max(row.duration, 0.0) * 1e6,
            "pid": pid,
            "tid": int(row.tid) & 0x7FFFFFFF,
            "args": {"plane": key[1]},
        })


def _counter_events(util: pd.DataFrame, events: List[dict]) -> None:
    for row in util.itertuples(index=False):
        events.append({
            "name": row.name, "ph": "C", "cat": "util",
            "ts": row.timestamp * 1e6,
            "pid": int(row.deviceId),
            "args": {row.name: float(row.event)},
        })


def _host_counter_events(df: pd.DataFrame, names: List[str],
                         label: str, events: List[dict]) -> None:
    """Per-timestamp mean of a host sampler series as a Perfetto counter —
    per HOST, so a cluster export never averages one saturated machine
    against its idle neighbors.  Host identity is the `pid` column
    (stamped by load_cluster_frames; -1 = single-host capture); deviceId
    in sampler frames is the CPU-core/lane index and is deliberately
    averaged over."""
    if df.empty:
        return
    for hpid, host_rows in df.groupby("pid"):
        pid = _HOST_PID + max(int(hpid), 0) * 256
        for name in names:
            rows = host_rows[host_rows["name"] == name]
            if rows.empty:
                continue
            agg = rows.groupby("timestamp")["event"].mean()
            for ts, v in agg.items():
                events.append({
                    "name": f"{label}{name}", "ph": "C", "cat": "host_util",
                    "ts": ts * 1e6, "pid": pid,
                    "args": {f"{label}{name}": float(v)},
                })


def _self_trace_events(cfg) -> List[dict]:
    """The profiler's own spans (telemetry self-trace), remapped onto a
    dedicated Perfetto process so a sofa capture and the pipeline that
    produced it open side by side in one viewer.  The self-trace shares
    the capture's time zero (telemetry anchors it to sofa_time.txt), so
    no timestamp surgery is needed — only the pid."""
    from sofa_tpu.telemetry import load_self_trace

    doc = load_self_trace(cfg.logdir)
    if doc is None:
        return []
    out = []
    for e in doc["traceEvents"]:
        if not isinstance(e, dict) or "ph" not in e:
            continue
        e = dict(e)
        e["pid"] = _SELF_PID
        out.append(e)
    return out


def _host_threads(sel: pd.DataFrame) -> Dict[int, str]:
    """tid -> thread-name map for one host lane — columnar (the
    ``drop_duplicates().iterrows()`` loop this replaces built a pandas
    Series per row; on a pod-scale hosttrace that was the whole cost of
    the metadata pass).  Output is byte-identical to the row loop:
    first-seen row per tid, module name when non-empty, else "tid <n>"."""
    dd = sel.drop_duplicates("tid")
    tids = dd["tid"].to_numpy()
    if "module" in dd.columns:
        mods = dd["module"].to_numpy()
    else:
        mods = [""] * len(dd)
    threads: Dict[int, str] = {}
    for tid, mod in zip(tids.tolist(), list(mods)):
        threads[int(tid) & 0x7FFFFFFF] = str(mod) or f"tid {tid}"
    return threads


def _meta(events: List[dict], pid: int, name: str,
          threads: Optional[Dict[int, str]] = None) -> None:
    events.append({"name": "process_name", "ph": "M", "pid": pid,
                   "args": {"name": name}})
    for tid, tname in (threads or {}).items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})


def export_perfetto(cfg, frames: Optional[Dict[str, pd.DataFrame]] = None,
                    out_name: str = "trace.json.gz") -> Optional[str]:
    """Write the Trace-Event-Format export; returns the path or None."""
    if frames is None:
        from sofa_tpu.analyze import load_frames

        frames = load_frames(cfg, only=PERFETTO_FRAMES)

    def get(name: str) -> pd.DataFrame:
        df = frames.get(name)
        return df if df is not None else pd.DataFrame()

    # The pod-scale op frame stays COLUMNAR end to end (native writer gets
    # arrays, Python fallback materializes strings late); everything else
    # stays a dict until the writer.
    events: "List[dict]" = []
    ops = get("tputrace")
    dev = _DeviceColumns(ops) if not ops.empty else None
    steps = get("tpusteps")
    if not steps.empty:
        _steps_events(steps, events)
    mods = get("tpumodules")
    if not mods.empty:
        _module_events(mods, events)
    host = get("hosttrace")
    if not host.empty:
        _host_events(host, events)
    custom = get("customtrace")
    plane_pids: Dict[tuple, int] = {}
    if not custom.empty:
        _custom_events(custom, events, plane_pids)
    util = get("tpuutil")
    if not util.empty:
        _counter_events(util, events)
    # Live HBM occupancy rides the same per-device counter convention as
    # the trace-derived rates; heartbeat rows (deviceId -1) are liveness
    # bookkeeping, not a device counter.
    mon = get("tpumon")
    if not mon.empty:
        mon = mon[(mon["name"] != "alive") & (mon["deviceId"] >= 0)]
    if not mon.empty:
        _counter_events(mon, events)
    _host_counter_events(get("mpstat"), ["usr", "sys", "iow"],
                         "cpu_", events)
    net = get("netbandwidth")
    if not net.empty:
        _host_counter_events(net, sorted(set(net["name"])), "", events)
    if dev is None and not events:
        print_warning("perfetto export: no trace frames — run "
                      "`sofa report` first")
        return None
    # The pipeline's own spans ride along as one more process: the user's
    # workload and the profiler that captured it, on the same timeline.
    events.extend(_self_trace_events(cfg))

    device_ids = set()
    for df in (ops, steps, mods, util, mon):
        if not df.empty:
            device_ids.update(int(d) for d in df["deviceId"].unique())
    for pid in sorted(device_ids):
        _meta(events, pid, f"tpu{pid}",
              {0: "XLA Ops (sync)", 1: "Async DMA", 3: "Steps",
               4: "XLA Modules"})
    if not host.empty:
        for base, sel in host.groupby("deviceId"):
            threads = _host_threads(sel)
            base = max(int(base), 0)
            name = "host" if host["deviceId"].nunique() == 1 \
                else f"host{base // 256}"
            _meta(events, _HOST_PID + base, name, threads)
    for (_dev, label), pid in plane_pids.items():
        _meta(events, pid, str(label or "custom plane"))

    os.makedirs(cfg.logdir, exist_ok=True)  # cluster export may precede it
    path = cfg.path(out_name)
    dumps = json.dumps
    tail = ('],"displayTimeUnit":"ms","otherData":'
            + dumps({"producer": "sofa_tpu", "logdir": cfg.logdir}) + "}")
    n_total = (dev.n if dev is not None else 0) + len(events)

    # Native single-pass writer (sprintf + zlib in C, ~4x on pod-scale
    # traces); only worth a subprocess when the device frame is large.
    # The non-device blob is joined only on this path — the fallback
    # streams dicts in batches instead of materializing one giant string.
    if dev is not None and dev.n >= 100_000 \
            and os.environ.get("SOFA_NATIVE_PERFETTO", "1") != "0":
        other_json = ",".join(
            dumps(e, separators=(",", ":")) for e in events)
        if _native_write(dev, other_json, tail, path):
            print_progress(f"perfetto export: {n_total} events -> {path} "
                           "(native writer; open in ui.perfetto.dev)")
            return path

    # Pure-Python fallback: streamed write, gzip level 5, batched ~64k
    # strings per f.write (per-event writes were ~15% of the export).
    # The stream targets a tmp name; atomic_replace renames on success.
    from sofa_tpu.durability import atomic_replace

    with atomic_replace(path) as tmp_path, \
            gzip.open(tmp_path, "wt", encoding="utf-8",  # sofa-lint: disable=SL009 — streamed gzip body inside atomic_replace; the rename IS the atomic step
                      compresslevel=5) as f:
        f.write('{"traceEvents":[')
        batch: List[str] = []
        wrote_any = False

        def flush():
            nonlocal wrote_any
            if not batch:
                return
            if wrote_any:
                f.write(",")
            f.write(",".join(batch))
            wrote_any = True
            batch.clear()

        for e in (dev.event_strings() if dev is not None else []):
            batch.append(e)
            if len(batch) >= 65536:
                flush()
        for e in events:
            batch.append(dumps(e, separators=(",", ":")))
            if len(batch) >= 65536:
                flush()
        flush()
        f.write(tail)
    print_progress(f"perfetto export: {n_total} events -> {path} "
                   "(open in ui.perfetto.dev)")
    return path


def _native_write(dev: _DeviceColumns, other_json: str, tail: str,
                  path: str) -> bool:
    """Hand the columnar device events to native/perfetto_write.cc.

    Returns False on any failure (no compiler, bad exit, missing output) —
    the caller keeps the pure-Python path, mirroring ingest/native_scan.py's
    degradation contract.  Gzip level 4 ≈ the Python path's level 5 within
    a few % of size at roughly twice the deflate speed.
    """
    import struct
    import subprocess
    import tempfile

    from sofa_tpu.collectors.native_build import ensure_built

    tool = ensure_built("perfetto_write")
    if tool is None:
        return False
    tmp = None
    out_tmp = path + f".native.{os.getpid()}"
    try:
        with tempfile.NamedTemporaryFile(
                prefix="sofa_perfetto_", suffix=".bin", delete=False) as f:
            tmp = f.name
            f.write(struct.pack("<IIII", 0x31504653, 1, 4,
                                len(dev.prefixes)))
            for p in dev.prefixes:
                b = p.encode("utf-8")
                f.write(struct.pack("<I", len(b)))
                f.write(b)
            f.write(struct.pack("<Q", dev.n))
            f.write(dev.ts.tobytes())
            f.write(dev.dur.tobytes())
            f.write(dev.sig.tobytes())
            f.write(dev.pid.tobytes())
            f.write(dev.lane.tobytes())
            other = other_json.encode("utf-8")
            f.write(struct.pack("<Q", len(other)))
            f.write(other)
            tail_b = tail.encode("utf-8")
            f.write(struct.pack("<Q", len(tail_b)))
            f.write(tail_b)
        r = subprocess.run([tool, tmp, out_tmp],
                           capture_output=True, timeout=600)
        if r.returncode != 0 or not os.path.isfile(out_tmp):
            print_warning("native perfetto_write failed "
                          f"(rc={r.returncode}): "
                          f"{r.stderr.decode(errors='replace')[:200]} — "
                          "using the Python writer")
            return False
        os.replace(out_tmp, path)
        return True
    except Exception as e:  # noqa: BLE001 — any failure degrades
        print_warning(f"native perfetto_write failed ({e}) — "
                      "using the Python writer")
        return False
    finally:
        # out_tmp survives only via the os.replace above; a timeout or
        # tool crash must not leave a multi-hundred-MB partial in the
        # logdir.
        for leftover in (tmp, out_tmp):
            if leftover:
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
