"""HSG — hierarchical swarm grouping of timeline samples.

Reference hsg_v2 (sofa_ml.py:243-287): AgglomerativeClustering of CPU
samples on event=log10(IP) with average linkage into num_swarms clusters;
each swarm is captioned by the most common demangled function name, reported
as a "Function Swarm Report" and written to auto_caption.csv (the input to
`sofa diff`).

Same algorithm here, running over cputrace (perf samples) when present and
falling back to the XPlane host-runtime trace otherwise — TPU hosts often
lack perf but always have the host tracer.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.printing import print_progress, print_title, print_warning


def pick_samples(frames) -> Tuple[Optional[pd.DataFrame], str]:
    cputrace = frames.get("cputrace")
    if cputrace is not None and not cputrace.empty:
        return cputrace, "cputrace"
    hosttrace = frames.get("hosttrace")
    if hosttrace is not None and not hosttrace.empty:
        return hosttrace, "hosttrace"
    return None, ""


def hsg_cluster(
    df: pd.DataFrame, num_swarms: int, max_samples: int = 200_000
) -> pd.DataFrame:
    """Return df with an added cluster_ID column.

    The clustering feature is one scalar (event = log10 IP / lane value), so
    full AgglomerativeClustering — O(n^2) memory in sklearn — is overkill;
    splitting the sorted values at the k-1 largest gaps produces the same
    partition single-linkage would, in O(n log n), and survives million-row
    perf captures.
    """
    if len(df) > max_samples:
        df = df.iloc[:: int(np.ceil(len(df) / max_samples))]
    df = df.reset_index(drop=True)
    k = min(num_swarms, len(df))
    if k < 2:
        return df.assign(cluster_ID=0)
    values = df["event"].to_numpy(dtype=float)
    order = np.argsort(values)
    sorted_vals = values[order]
    gaps = np.diff(sorted_vals)
    if len(gaps) == 0 or not np.any(gaps > 0):
        return df.assign(cluster_ID=0)
    k = min(k, int(np.count_nonzero(gaps > 0)) + 1)
    cut_positions = np.sort(np.argsort(gaps)[-(k - 1):])  # indices into sorted_vals
    boundaries = sorted_vals[cut_positions]  # last value of each lower cluster
    # side="left": a value equal to a boundary belongs to the lower cluster.
    labels = np.searchsorted(boundaries, values, side="left")
    return df.assign(cluster_ID=labels)


def sofa_hsg(frames, cfg, features: Features) -> Optional[pd.DataFrame]:
    df, source = pick_samples(frames)
    if df is None:
        print_warning("hsg: no cputrace or hosttrace samples to cluster")
        return None
    clustered = hsg_cluster(df, cfg.num_swarms)
    report_rows = []
    for cid, rows in clustered.groupby("cluster_ID"):
        names = rows["name"].astype(str)
        caption = names.mode().iloc[0] if not names.empty else ""
        report_rows.append(
            {
                "cluster_ID": int(cid),
                "caption": caption,
                "samples": len(rows),
                "total_duration": float(rows["duration"].sum()),
                "function_names": "|".join(names.unique()[:50]),
            }
        )
    report = pd.DataFrame(report_rows).sort_values(
        "total_duration", ascending=False
    ).reset_index(drop=True)
    # auto_caption.csv is the diff input (reference sofa_ml.py:289-309).
    clustered.to_csv(cfg.path("auto_caption.csv"), index=False)
    report.to_csv(cfg.path("swarms_report.csv"), index=False)
    from sofa_tpu.durability import atomic_write

    with atomic_write(cfg.path("swarms_report.txt")) as f:
        f.write(report.drop(columns=["function_names"]).to_string(index=False) + "\n")
    features.add("hsg_swarms", len(report))
    print_progress(f"hsg: {len(report)} swarms over {len(clustered)} {source} samples")
    if cfg.verbose:
        print_title("Function Swarm Report")
        print(report.drop(columns=["function_names"]).head(20).to_string(index=False))
    return clustered


def swarm_series(clustered: Optional[pd.DataFrame], max_swarms: int = 10):
    """Per-swarm timeline series for the board."""
    if clustered is None or clustered.empty or "cluster_ID" not in clustered:
        return []
    from sofa_tpu.trace import SofaSeries

    palette = [
        "tomato", "gold", "mediumseagreen", "deepskyblue", "orchid",
        "darkkhaki", "salmon", "turquoise", "plum", "lightslategray",
    ]
    out = []
    top = (
        clustered.groupby("cluster_ID")["duration"].sum()
        .sort_values(ascending=False).head(max_swarms)
    )
    for i, cid in enumerate(top.index):
        rows = clustered[clustered["cluster_ID"] == cid]
        caption = rows["name"].astype(str).mode()
        title = f"swarm {cid}: {caption.iloc[0][:40] if not caption.empty else ''}"
        out.append(
            SofaSeries(
                f"swarm_{cid}", title, palette[i % len(palette)],
                rows.drop(columns=["cluster_ID"]),
            )
        )
    return out
