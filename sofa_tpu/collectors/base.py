"""Collector lifecycle.

A collector moves through: probe() -> start() -> [child runs] -> stop() ->
harvest().  All steps are best-effort: a probe failure downgrades the
collector to a no-op with a console warning, never an error — profiling must
work on machines missing any subset of tools (the reference probes with
`command -v` for the same reason, sofa_record.py:217-223,249,264,300).

Every lifecycle transition also lands in the run manifest's collector
health ledger (sofa_tpu/telemetry.py).  The hook lives HERE, once: record
drives the ``run_start``/``run_stop``/``run_harvest``/``run_kill`` wrappers
below, subclasses keep overriding the bare hooks, and all collectors
inherit the instrumentation — status, start/stop ordering, wall times,
exit codes, and bytes captured (via :meth:`Collector.outputs`).
"""

from __future__ import annotations

import enum
import os
import shutil
import signal
import subprocess
import threading
import time
from typing import Dict, List, Optional

from sofa_tpu import faults, telemetry
from sofa_tpu.printing import print_info, print_warning


def _next_seq() -> int:
    """Monotone start/stop ordinal within the active telemetry run (0 when
    none) — the manifest's proof that stop order reversed start order."""
    tel = telemetry.current()
    return tel.next_seq() if tel is not None else 0


def _run_bounded(fn, timeout: "float | None", name: str, phase: str) -> bool:
    """Run a collector epilogue step with a wall-clock deadline.

    True iff ``fn`` finished (its exception, if any, propagates to the
    caller exactly as an unbounded call would).  False once the deadline
    passes: ``fn`` keeps running on an abandoned daemon thread that dies
    with the process — a C call wedged without the GIL cannot be cancelled
    from Python, so abandonment is the only escalation that always works
    (same reasoning as the injected atexit guard, collectors/xprof.py).
    timeout None/<=0 disables the bound (direct call).
    """
    if not timeout or timeout <= 0:
        fn()
        return True
    box: dict = {}

    def _run():
        try:
            fn()
        except BaseException as e:  # sofa-lint: disable=SL002 — re-raised in the caller via box["err"]
            box["err"] = e

    t = threading.Thread(target=_run, daemon=True,
                         name=f"sofa_{name}_{phase}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        return False
    if "err" in box:
        raise box["err"]
    return True


class CollectorState(enum.Enum):
    IDLE = "idle"
    RUNNING = "running"
    STOPPED = "stopped"
    UNAVAILABLE = "unavailable"


class Collector:
    """Base collector; subclasses override the hooks they need."""

    name = "collector"

    def __init__(self, cfg):
        self.cfg = cfg
        self.state = CollectorState.IDLE

    # -- lifecycle ---------------------------------------------------------
    def probe(self) -> Optional[str]:
        """Return None if usable, else a human-readable reason it is not."""
        return None

    def start(self) -> None:
        """Begin collection (background process / thread / file setup)."""

    def stop(self) -> None:
        """End collection and flush output files."""

    def harvest(self) -> None:
        """Post-run transformation of raw output (e.g. blkparse)."""

    # -- composition hooks -------------------------------------------------
    def command_prefix(self) -> List[str]:
        """Tokens to prepend to the profiled command (e.g. strace ...)."""
        return []

    def child_env(self) -> Dict[str, str]:
        """Environment variables to inject into the profiled command."""
        return {}

    def outputs(self) -> List[str]:
        """Paths this collector writes — the manifest's bytes-captured
        ledger sums their on-disk sizes after harvest."""
        return []

    # -- supervision hooks (sofa_tpu/supervisor.py) ------------------------
    def alive(self) -> Optional[bool]:
        """Liveness for the watchdog: True/False when this collector has a
        watchable backing worker, None when there is nothing to watch
        (prefix-only or one-shot collectors)."""
        return None

    def fault_kill(self) -> None:
        """Fault-injection kill point (faults.py ``die``): make the backing
        worker vanish the way a crash would."""
        if hasattr(self, "kill"):
            self.kill()

    def _deadline(self, field: str, default: float) -> "float | None":
        return getattr(self.cfg, field, default)

    def _escalate_kill(self) -> None:
        """TERM -> KILL -> abandon on the backing process after a stop
        deadline — the `_signal_tree` discipline from record.py applied to
        one collector (killpg falls back to a direct signal for processes
        that are not group leaders, i.e. every collector proc)."""
        proc = getattr(self, "proc", None)
        if proc is None or proc.poll() is not None:
            return
        from sofa_tpu.record import _signal_tree  # lazy: record imports us

        _signal_tree(proc, signal.SIGTERM)
        try:
            proc.wait(timeout=2)
            return
        except subprocess.TimeoutExpired:
            pass
        _signal_tree(proc, signal.SIGKILL)
        try:
            proc.wait(timeout=2)
        except subprocess.TimeoutExpired:
            pass  # abandoned; the manifest carries timed_out

    # -- instrumented lifecycle (driven by record; do not override) --------
    def run_start(self) -> None:
        t0 = time.perf_counter()
        try:
            with telemetry.maybe_span(f"{self.name}.start", cat="collector"):
                faults.maybe_inject(self.name, "start")
                self.start()
        except Exception as e:  # noqa: BLE001 — ledger first, caller decides
            telemetry.collector_event(
                self.name, "failed", phase="start", error=str(e)[:300])
            raise
        telemetry.collector_event(
            self.name, "started", start_seq=_next_seq(),
            start_wall_s=round(time.perf_counter() - t0, 6))
        faults.arm_die(self)

    def run_stop(self) -> None:
        t0 = time.perf_counter()

        def _do_stop():
            faults.maybe_inject(self.name, "stop")
            self.stop()

        timeout = self._deadline("collector_stop_timeout_s", 15.0)
        try:
            with telemetry.maybe_span(f"{self.name}.stop", cat="collector"):
                finished = _run_bounded(_do_stop, timeout, self.name, "stop")
        except Exception as e:  # noqa: BLE001
            telemetry.collector_event(
                self.name, "failed", phase="stop", error=str(e)[:300])
            raise
        if not finished:
            # A wedged flush degrades THIS series, never the whole record.
            self._escalate_kill()
            telemetry.collector_event(
                self.name, "timed_out", phase="stop", timed_out=True,
                stop_seq=_next_seq(),
                stop_wall_s=round(time.perf_counter() - t0, 6))
            print_warning(
                f"{self.name}: stop exceeded {timeout:g}s — killed and "
                "abandoned; its series may be partial "
                "(--collector_stop_timeout_s)")
            return
        fields = {"stop_seq": _next_seq(),
                  "stop_wall_s": round(time.perf_counter() - t0, 6)}
        proc = getattr(self, "proc", None)
        if proc is not None and proc.returncode is not None:
            fields["exit_code"] = int(proc.returncode)
        telemetry.collector_event(self.name, "stopped", **fields)

    def run_harvest(self) -> None:
        t0 = time.perf_counter()

        def _do_harvest():
            faults.maybe_inject(self.name, "harvest")
            self.harvest()
            faults.maybe_truncate(self)

        timeout = self._deadline("collector_harvest_timeout_s", 120.0)
        try:
            with telemetry.maybe_span(f"{self.name}.harvest",
                                      cat="collector"):
                finished = _run_bounded(_do_harvest, timeout, self.name,
                                        "harvest")
        except Exception as e:  # noqa: BLE001
            telemetry.collector_event(
                self.name, "failed", phase="harvest", error=str(e)[:300])
            raise
        finally:
            telemetry.collector_event(
                self.name,
                bytes_captured=telemetry.collector_bytes(self.outputs()))
        if not finished:
            telemetry.collector_event(
                self.name, "timed_out", phase="harvest", timed_out=True)
            print_warning(
                f"{self.name}: harvest exceeded {timeout:g}s — abandoned; "
                "its derived series may be missing "
                "(--collector_harvest_timeout_s)")
            return
        telemetry.collector_event(
            self.name, harvest_wall_s=round(time.perf_counter() - t0, 6))

    def run_kill(self) -> None:
        if hasattr(self, "kill"):
            self.kill()
        telemetry.collector_event(self.name, "killed")

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def which(tool: str) -> Optional[str]:
        return shutil.which(tool)

    def unavailable(self, reason: str) -> None:
        self.state = CollectorState.UNAVAILABLE
        telemetry.collector_event(self.name, "skipped", reason=reason)
        print_warning(f"{self.name}: {reason} — skipping this collector")


class ProcessCollector(Collector):
    """A collector backed by one background process."""

    def __init__(self, cfg):
        super().__init__(cfg)
        self.proc: Optional[subprocess.Popen] = None

    def launch(self, argv, **popen_kwargs) -> None:
        print_info(f"{self.name}: {' '.join(argv)}")
        self.proc = subprocess.Popen(argv, **popen_kwargs)
        self.state = CollectorState.RUNNING

    def alive(self) -> Optional[bool]:
        if self.proc is None:
            return None
        return self.proc.poll() is None

    def stop(self, sig=signal.SIGTERM, timeout: float = 5.0) -> None:
        if self.proc is None:
            return
        try:
            if self.proc.poll() is None:
                self.proc.send_signal(sig)
                try:
                    self.proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    print_warning(f"{self.name}: did not exit on signal; killing")
                    self.proc.kill()
                    try:
                        self.proc.wait(timeout=timeout)
                    except subprocess.TimeoutExpired:
                        # Already SIGKILLed: an unreapable zombie (wedged in
                        # an uninterruptible syscall) must degrade to a
                        # recorded state, not turn the epilogue into a
                        # failure for a collector that is already dead.
                        print_warning(
                            f"{self.name}: not reaped after SIGKILL; "
                            "abandoning the wait")
                        telemetry.collector_event(self.name, unreaped=True)
        except ProcessLookupError:
            pass
        self.state = CollectorState.STOPPED

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass


def ensure_logdir(path: str) -> None:
    try:
        os.makedirs(path, exist_ok=True)
    except (FileExistsError, NotADirectoryError):
        from sofa_tpu.printing import SofaUserError

        raise SofaUserError(
            f"cannot create logdir {path}: a path component exists and is "
            "not a directory — pick another --logdir") from None
