"""Calibration: what makes a what-if prediction honest.

Two disciplines, both borrowed from the parts of the repo that already
refuse to manufacture confidence:

* **Error bars from the run's own variance.**  The measured per-step
  times give a nonparametric 95 % CI of the median via binomial order
  statistics (``archive/baseline.py`` — the regression engine's
  interval).  Its half-widths are the run's own step-to-step jitter
  scale; a predicted mean is reported as ``predicted ± those
  half-widths``.  Below ``MIN_CI_SAMPLES`` steps no such interval exists
  — a sample range is NOT a 95 % CI — so the verdict degrades to
  ``uncalibrated`` with the reason stated.

* **The identity gate.**  Replaying the model with *zero* scenarios must
  reproduce the measured mean step time within that interval (translated
  to the measured mean).  The model's decomposition makes the identity
  replay exact by construction, so a gate failure means the model is
  damaged (spans lost, clipping bugs, a tampered model file) — and every
  scenario prediction built on it would inherit the damage.  An
  ``uncalibrated`` verdict poisons the report loudly: ``manifest_check
  --require-healthy`` treats it as unhealthy and ``sofa whatif`` exits 1.
"""

from __future__ import annotations

from typing import List, Optional

from sofa_tpu.archive.baseline import MIN_CI_SAMPLES, median, median_ci

#: Calibration verdict vocabulary (report + meta.whatif).
CALIBRATION_VERDICTS = ("calibrated", "uncalibrated")


def calibration(measured: List[float], identity_mean: float) -> dict:
    """The report's ``calibration`` section from the measured per-step
    times and the zero-scenario replayed mean."""
    n = len(measured)
    out: dict = {"n_steps": n}
    if n == 0:
        out.update(verdict="uncalibrated",
                   reason="no step spans in the trace — nothing to "
                          "calibrate against (is tpusteps captured?)")
        return out
    mean = sum(measured) / n
    med = median(measured)
    out.update(measured_mean_s=round(mean, 9),
               measured_median_s=round(med, 9),
               identity_mean_s=round(identity_mean, 9),
               identity_error_pct=round(
                   100.0 * abs(identity_mean - mean) / mean, 6)
                   if mean > 0 else 0.0)
    ci = median_ci(measured)
    if ci is None:
        out.update(ci=None, verdict="uncalibrated",
                   reason=f"only {n} step sample(s) — no defensible 95% "
                          f"CI (need >= {MIN_CI_SAMPLES})")
        return out
    lo, hi = ci
    out["ci"] = [round(lo, 9), round(hi, 9)]
    # The gate interval is the median CI translated to the measured mean:
    # same variance scale, centered on the quantity the replay reproduces.
    gate_lo = mean - (med - lo)
    gate_hi = mean + (hi - med)
    if gate_lo <= identity_mean <= gate_hi:
        out.update(verdict="calibrated",
                   reason=f"zero-scenario replay reproduces the measured "
                          f"mean within [{gate_lo:g}, {gate_hi:g}]")
    else:
        out.update(verdict="uncalibrated",
                   reason=f"zero-scenario replay ({identity_mean:g}s) "
                          f"falls outside [{gate_lo:g}, {gate_hi:g}] — "
                          "the timeline model does not reproduce this "
                          "run; scenario predictions would inherit the "
                          "error")
    return out


def error_bars(calib: dict, predicted_mean: float) -> "Optional[list]":
    """``[lo, hi]`` around a predicted mean: the measured median CI's
    half-widths translated to the prediction; None when the run was too
    short for a defensible interval."""
    ci = calib.get("ci")
    med = calib.get("measured_median_s")
    if not ci or med is None:
        return None
    lo = predicted_mean - (med - ci[0])
    hi = predicted_mean + (ci[1] - med)
    return [round(max(lo, 0.0), 9), round(max(hi, 0.0), 9)]
