"""sofa-lint: project-native static analysis for sofa_tpu's own contracts.

PRs 1-3 established hard runtime invariants — every pipeline pool sized by
--jobs, every collector epilogue bounded by a deadline, every parser raising
typed errors into the quarantine path, every warning routed through the
telemetry counters.  Nothing in pytest stops the next patch from silently
violating them: a new ``subprocess.run`` without a timeout or a new
``except Exception: pass`` is invisible until it wedges or swallows a
production run.  This package turns those contracts into machine-checked
rules, following the modular program-analysis-framework design (PASTA,
PAPERS.md) and SOFA's own philosophy of replacing ad-hoc observation with a
checked schema (PAPER.md §1).

Layout:

  core.py      single-pass AST engine: per-file visitor dispatch, import
               alias resolution, ``# sofa-lint: disable=RULE`` suppressions,
               project context (the trace schema, extracted statically)
  rules.py     the project-specific rules SL001..SL008
  baseline.py  fingerprint baseline: grandfather existing findings so only
               NEW violations fail (``lint_baseline.json`` — shrinks over
               PRs, never grows)
  cli.py       exit-code contract (0 clean / 1 new findings / 2 internal
               error), --json, --update-baseline; backs both
               ``tools/sofa_lint.py`` and the ``sofa lint`` verb

See docs/STATIC_ANALYSIS.md for each rule's rationale and the baseline
workflow.
"""

from sofa_tpu.lint.core import (  # noqa: F401
    Finding,
    LintEngine,
    ProjectContext,
    lint_paths,
)
from sofa_tpu.lint.baseline import Baseline  # noqa: F401
from sofa_tpu.lint.cli import run_lint  # noqa: F401
