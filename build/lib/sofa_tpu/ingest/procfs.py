"""Parsers for the procmon/sysmon sampler files and vmstat output.

Input formats are defined in sofa_tpu/native/sysmon.cc (shared by the Python
fallback sampler).  Counter files are cumulative; parsing differentiates
consecutive samples into rates, the same math the reference does inline
(/root/reference/bin/sofa_preprocess.py:482-673,1235-1337) but emitting typed
rows instead of stringly-encoded names.

Output row conventions (unified schema):
  mpstat:   one row per core per interval per metric; event = percent,
            deviceId = core index (-1 = all cores), name = metric
  diskstat: one row per device per interval per metric; event = value,
            name = "<dev>.<metric>", payload = bytes moved that interval
  netstat:  name = "<iface>.tx"/"<iface>.rx", event = bytes/s,
            payload = interval bytes
  cpu_mhz:  name = "cpu_mhz", event = mean MHz across cores
  vmstat:   name = vmstat column, event = value
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from sofa_tpu.trace import empty_frame, make_frame

MPSTAT_METRICS = ["usr", "nice", "sys", "idl", "iow", "irq", "sirq", "steal"]


def parse_mpstat(text: str, time_base: float = 0.0) -> pd.DataFrame:
    """mpstat.txt lines: ``<ts> cpu<id|all> u n s i io irq sirq st`` (jiffies)."""
    samples: Dict[str, List] = {}
    for line in text.splitlines():
        p = line.split()
        if len(p) != 10:
            continue
        try:
            ts = float(p[0])
            vals = np.array([int(v) for v in p[2:10]], dtype=np.int64)
        except ValueError:
            continue
        samples.setdefault(p[1], []).append((ts, vals))

    rows = []
    for cpu, series in samples.items():
        if cpu == "cpuall":
            device = -1
        else:
            try:
                device = int(cpu[3:])
            except ValueError:
                continue
        for (t0, v0), (t1, v1) in zip(series, series[1:]):
            delta = v1 - v0
            total = delta.sum()
            if t1 <= t0 or total < 0:
                continue
            for metric, d in zip(MPSTAT_METRICS, delta):
                if total > 0:
                    pct = 100.0 * float(d) / float(total)
                else:
                    # Jiffy counters did not advance this interval (sub-tick
                    # interval, or a sandboxed /proc/stat that reads all
                    # zeros): report the core as fully idle rather than
                    # dropping it, so the core inventory survives.
                    pct = 100.0 if metric == "idl" else 0.0
                rows.append(
                    {
                        "timestamp": t1 - time_base,
                        "event": pct,
                        "duration": t1 - t0,
                        "deviceId": device,
                        "payload": int(d),
                        "name": metric,
                        "device_kind": "cpu",
                    }
                )
    return make_frame(rows)


def parse_diskstat(text: str, time_base: float = 0.0,
                   sector_bytes: int = 512) -> pd.DataFrame:
    """diskstat.txt: ``<ts> <dev> rd_ios rd_sec rd_ms wr_ios wr_sec wr_ms inflight``."""
    samples: Dict[str, List] = {}
    for line in text.splitlines():
        p = line.split()
        if len(p) != 9:
            continue
        try:
            ts = float(p[0])
            vals = np.array([int(v) for v in p[2:9]], dtype=np.int64)
        except ValueError:
            continue
        samples.setdefault(p[1], []).append((ts, vals))

    rows = []
    for dev_idx, (dev, series) in enumerate(sorted(samples.items())):
        # Drop devices with no activity at all, like the reference's all-zero
        # filter (sofa_preprocess.py:661-665).
        if len(series) < 2 or not (series[-1][1][:6] - series[0][1][:6]).any():
            continue
        for (t0, v0), (t1, v1) in zip(series, series[1:]):
            if t1 <= t0:
                continue
            d = v1 - v0
            rd_ios, rd_sec, rd_ms, wr_ios, wr_sec, wr_ms, _ = d
            dt = t1 - t0
            metrics = {
                "r_iops": rd_ios / dt,
                "w_iops": wr_ios / dt,
                "r_bw": rd_sec * sector_bytes / dt,
                "w_bw": wr_sec * sector_bytes / dt,
                "r_await_ms": (rd_ms / rd_ios) if rd_ios > 0 else 0.0,
                "w_await_ms": (wr_ms / wr_ios) if wr_ios > 0 else 0.0,
            }
            payload = int((rd_sec + wr_sec) * sector_bytes)
            for metric, value in metrics.items():
                rows.append(
                    {
                        "timestamp": t1 - time_base,
                        "event": float(value),
                        "duration": dt,
                        "deviceId": dev_idx,
                        "payload": payload,
                        "bandwidth": metrics["r_bw"] + metrics["w_bw"],
                        "name": f"{dev}.{metric}",
                        "device_kind": "disk",
                    }
                )
    return make_frame(rows)


def parse_netstat(text: str, time_base: float = 0.0) -> pd.DataFrame:
    """netstat.txt: ``<ts> <iface> rx_bytes tx_bytes rx_pkts tx_pkts``."""
    samples: Dict[str, List] = {}
    for line in text.splitlines():
        p = line.split()
        if len(p) != 6:
            continue
        try:
            ts = float(p[0])
            vals = np.array([int(v) for v in p[2:6]], dtype=np.int64)
        except ValueError:
            continue
        samples.setdefault(p[1], []).append((ts, vals))

    rows = []
    for iface, series in sorted(samples.items()):
        if len(series) < 2:
            continue
        if not (series[-1][1] - series[0][1]).any():
            continue  # idle interface
        for (t0, v0), (t1, v1) in zip(series, series[1:]):
            if t1 <= t0:
                continue
            d = v1 - v0
            dt = t1 - t0
            for name, nbytes, npkts in (
                ("rx", d[0], d[2]),
                ("tx", d[1], d[3]),
            ):
                rows.append(
                    {
                        "timestamp": t1 - time_base,
                        "event": float(nbytes) / dt,
                        "duration": dt,
                        "payload": int(nbytes),
                        "bandwidth": float(nbytes) / dt,
                        "name": f"{iface}.{name}",
                        "device_kind": "net",
                    }
                )
    return make_frame(rows)


def parse_cpuinfo(text: str, time_base: float = 0.0) -> pd.DataFrame:
    """cpuinfo.txt: ``<ts> <mhz0> <mhz1> ...`` -> mean-MHz series."""
    rows = []
    for line in text.splitlines():
        p = line.split()
        if len(p) < 2:
            continue
        try:
            ts = float(p[0])
            mhz = [float(v) for v in p[1:]]
        except ValueError:
            continue
        rows.append(
            {
                "timestamp": ts - time_base,
                "event": float(np.mean(mhz)),
                "name": "cpu_mhz",
                "device_kind": "cpu",
            }
        )
    return make_frame(rows)


def cpu_mhz_interpolator(df: pd.DataFrame):
    """Return f(t)->MHz for converting perf cycle counts to seconds
    (the reference's np.interp over cpuinfo samples, sofa_preprocess.py:131-134)."""
    if df.empty:
        return lambda t: 2000.0
    ts = df["timestamp"].to_numpy(dtype=float)
    mhz = df["event"].to_numpy(dtype=float)

    def f(t):
        return float(np.interp(t, ts, mhz))

    return f


# `vmstat -w -t 1` column layout (procps-ng): r b | swpd free buff cache |
# si so | bi bo | in cs | us sy id wa st [gu] | date time
_VMSTAT_KEEP = ["bi", "bo", "in", "cs", "us", "sy", "wa", "st"]


def parse_vmstat(text: str, time_base: float = 0.0,
                 record_start: Optional[float] = None) -> pd.DataFrame:
    lines = text.splitlines()
    header: List[str] = []
    rows = []
    tick = 0
    for line in lines:
        p = line.split()
        if not p:
            continue
        if p[0] == "r":  # header row
            header = p
            continue
        if not header or not p[0].lstrip("-").isdigit():
            continue
        vals = p
        # -t appends "date time"; prefer it for absolute timestamps.
        ts: Optional[float] = None
        if len(vals) >= len(header) + 2:
            try:
                # datetime treats the naive string as LOCAL time, matching
                # what `vmstat -t` prints (pd.Timestamp would assume UTC).
                import datetime as _dt

                ts = _dt.datetime.strptime(
                    f"{vals[-2]} {vals[-1]}", "%Y-%m-%d %H:%M:%S"
                ).timestamp()
                vals = vals[:-2]
            except ValueError:
                ts = None
        if ts is None:
            ts = (record_start or time_base) + tick
        tick += 1
        named = dict(zip(header, vals))
        for key in _VMSTAT_KEEP:
            if key not in named:
                continue
            try:
                value = float(named[key])
            except ValueError:
                continue
            rows.append(
                {
                    "timestamp": ts - time_base,
                    "event": value,
                    "duration": 1.0,
                    "name": f"vmstat.{key}",
                    "device_kind": "cpu",
                }
            )
    return make_frame(rows)


def load(path: str, parser, time_base: float = 0.0, **kwargs) -> pd.DataFrame:
    if not os.path.isfile(path):
        return empty_frame()
    with open(path) as f:
        return parser(f.read(), time_base=time_base, **kwargs)
