"""Environment doctor & enabler: ``sofa setup``.

The reference splits host enablement across three root-needing helpers —
sysctl tweaks (/root/reference/tools/enable_strace_perf_pcm.py), capability
grants for tcpdump-style utilities via a "sofa" group
(/root/reference/tools/empower.py:46-60), and a distro-probing dependency
installer (/root/reference/tools/prepare.sh).  Here all of it is one
subcommand with a safe default: ``sofa setup`` *reports* what each collector
needs and prints the exact commands; ``sofa setup --apply`` runs them
(through sudo when available).  Nothing is installed — the TPU image is
expected to ship its own toolchain, so missing binaries only degrade the
matching collector.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Callable, List, Optional, Tuple

from sofa_tpu.printing import print_hint, print_info, print_progress, print_warning

# (sysctl key, value wanted for full-fidelity perf/strace recording)
SYSCTLS = [
    ("kernel.perf_event_paranoid", "-1"),
    ("kernel.kptr_restrict", "0"),
]

# Collector binaries and the subsystem each one unlocks.
TOOLS = [
    ("perf", "CPU sampling (collectors/perf.py)"),
    ("tcpdump", "DCN packet capture (collectors/hostproc.py)"),
    ("blktrace", "block-IO tracing (collectors/hostproc.py)"),
    ("blkparse", "block-IO trace decoding"),
    ("strace", "syscall tracing (collectors/hostproc.py)"),
    ("vmstat", "memory/context-switch sampling"),
]

# Capabilities a non-root profiling user needs per utility (empower.py's
# setcap line, generalized).
CAPS = {
    "tcpdump": "cap_net_raw,cap_net_admin=eip",
    "blktrace": "cap_sys_admin=eip",
    "perf": "cap_perfmon,cap_sys_ptrace=eip",
}


def _read_sysctl(key: str) -> Optional[str]:
    path = "/proc/sys/" + key.replace(".", "/")
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def _sudo_prefix() -> str:
    return "sudo " if shutil.which("sudo") and os.geteuid() != 0 else ""


def check(utilities: Optional[List[str]] = None,
          probe_device: bool = True) -> Tuple[List[str], int]:
    """Returns (fix commands, number of problems found) and prints a report."""
    fixes: List[str] = []
    problems = 0
    sudo = _sudo_prefix()

    for key, want in SYSCTLS:
        have = _read_sysctl(key)
        if have is None:
            print_warning(f"setup: {key} unreadable (sandboxed /proc?)")
            problems += 1
        elif have != want:
            print_warning(f"setup: {key} = {have}, want {want}")
            fixes.append(f"{sudo}sysctl -w {key}={want}")
            problems += 1
        else:
            print_info(f"setup: {key} = {have} ok")

    for tool, why in TOOLS:
        path = shutil.which(tool)
        if path:
            print_info(f"setup: {tool} found at {path}")
        else:
            print_warning(f"setup: {tool} missing — degrades {why}")
            problems += 1

    for util in utilities or []:
        path = shutil.which(util) or util
        cap = CAPS.get(os.path.basename(path))
        if cap is None:
            print_warning(
                f"setup: no capability profile for {util!r} (known: "
                f"{', '.join(sorted(CAPS))}) — refusing to guess a grant")
            problems += 1
            continue
        if not os.path.isfile(path):
            print_warning(f"setup: {util}: not a file, cannot grant caps")
            problems += 1
            continue
        got = ""
        if shutil.which("getcap"):
            try:
                out = subprocess.run(["getcap", path], capture_output=True,
                                     text=True, timeout=10)
                got = out.stdout.strip()
            except (subprocess.SubprocessError, OSError) as e:
                print_warning(f"setup: getcap {path} failed ({e}); "
                              "assuming no capabilities")
        # getcap prints caps sorted by capability number, so compare the
        # individual names, not the whole comma-joined string.
        if all(c in got for c in cap.split("=")[0].split(",")):
            print_info(f"setup: {path} already has {cap}")
        else:
            print_warning(f"setup: {path} lacks {cap}")
            fixes.append(f"{sudo}setcap {cap} {path}")
            problems += 1

    # TPU side: file-level checks plus a SUBPROCESS-bounded backend probe —
    # in-process init can hang forever on a dead/busy device tunnel, and
    # `setup` must always return.  The probe is how users diagnose "every
    # JAX program hangs" before sinking a training run into it.
    accel = [d for d in ("/dev/accel0", "/dev/vfio/0") if os.path.exists(d)]
    if accel:
        print_info(f"setup: TPU device node present: {', '.join(accel)}")
    else:
        print_info("setup: no local TPU device node (remote/tunneled chips "
                   "are still usable via JAX)")
    if probe_device and not _probe_backend():
        problems += 1   # an unusable device backend IS a setup problem:
        # scripts gating on the exit code must not read 'fully enabled'
    return fixes, problems


def _probe_backend(timeout_s: float = 30.0) -> bool:
    """Bounded device-backend health report (never raises, never hangs);
    True iff the backend initialized."""
    import sys

    # The env-over-config re-apply is NOT redundant: this image's site
    # hook force-prepends its platform after jax reads JAX_PLATFORMS, so
    # a JAX_PLATFORMS=cpu probe would otherwise probe the tunnel (same
    # rule as bench.py's _PROBE_SNIPPET).
    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS', '')\n"
            "if p and jax.config.jax_platforms != p:\n"
            "    jax.config.update('jax_platforms', p)\n"
            "d = jax.devices()\n"
            "print(jax.default_backend(), len(d),\n"
            "      getattr(d[0], 'device_kind', ''))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print_warning(
            f"setup: device backend init hung > {timeout_s:.0f}s — the "
            "device tunnel/runtime is down; JAX programs (and `sofa "
            "record` of them) will hang at jax.devices().  Host-side "
            "collectors still work; pin JAX_PLATFORMS=cpu for CPU runs")
        return False
    except OSError as e:
        print_warning(f"setup: backend probe could not launch: {e}")
        return False
    if r.returncode == 0 and r.stdout.strip():
        parts = r.stdout.strip().split(None, 2)
        backend = parts[0]
        n = parts[1] if len(parts) > 1 else "?"
        kind = parts[2] if len(parts) > 2 else ""
        # print_progress, not print_info: the health verdict is the answer
        # the user ran `sofa setup` for — it must show without --verbose
        print_progress(f"setup: device backend healthy: {backend} "
                       f"({n} device(s){', ' + kind if kind else ''})")
        return True
    tail = (r.stderr.strip().splitlines() or ["?"])[-1]
    print_warning(f"setup: device backend init failed: {tail[:160]}")
    return False


def sofa_setup(utilities: Optional[List[str]] = None, apply: bool = False,
               runner: Callable[[str], int] = None,
               probe_device: bool = True) -> int:
    """Report (and with apply=True, fix) host prerequisites.

    runner is injectable for tests; defaults to shell execution.
    """
    fixes, problems = check(utilities, probe_device)
    if not fixes:
        if problems:
            print_hint(f"setup: {problems} issue(s), none auto-fixable "
                       "(install missing tools via your image/package manager)")
        else:
            print_progress("setup: environment fully enabled")
        return 0 if not problems else 1
    if not apply:
        print_hint("setup: run these (or re-run with --apply):")
        for cmd in fixes:
            print(f"  {cmd}")
        return 1
    run = runner or _run_fix
    rc = 0
    for cmd in fixes:
        print_progress(f"setup: {cmd}")
        rc = max(rc, run(cmd))
    return rc


def _run_fix(cmd: str, timeout_s: float = 120.0) -> int:
    """Default --apply runner.  Bounded: the fix commands are setcap/sysctl
    one-liners — a sudo prompt or wedged PAM stack must not hang
    `setup --apply` forever."""
    try:
        return subprocess.run(cmd, shell=True, timeout=timeout_s).returncode
    except subprocess.TimeoutExpired:
        print_warning(f"setup: fix command exceeded {timeout_s:.0f}s and "
                      f"was killed: {cmd}")
        return 124
