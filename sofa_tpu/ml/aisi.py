"""AISI — automatic iteration detection and per-step profiling.

Reference pipeline (sofa_aisi.py:110-136,218-286,413-453): GPU kernel names
-> symbol string -> suffix-tree repeat mining at num_iterations -> fuzzy
boundary scan -> KMeans on boundary timestamps -> per-iteration fw/bw/gemm/
copy/allreduce profile -> compute- vs communication-bound verdict.

TPU retarget: the symbol sequence comes from HLO-op names (or XLA module
launches, which are already step-granular under jit), repeats are mined with
the suffix automaton, boundaries are the exact (or fuzzy) occurrence
positions — no KMeans needed — and the per-step profile attributes time to
HLO categories and collective kinds.

Explicit markers beat mining: if the profiled program annotated its steps
with ``jax.profiler.TraceAnnotation("sofa_step_<i>")`` (what the built-in
workloads' steps_per_sec loop does, sofa_tpu/workloads/common.py), those
host-plane spans are used as exact iteration boundaries and the fuzzy
detection never runs.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np
import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.ml.suffix import SuffixAutomaton, find_occurrences, fuzzy_occurrences
from sofa_tpu.printing import print_hint, print_progress, print_warning
from sofa_tpu.trace import CopyKind

COMM_BOUND_RATIO = 0.15  # the reference's verdict threshold (sofa_aisi.py:503-507)

_STEP_MARKER_RE = re.compile(r"^sofa_step_(\d+)$")


def _busiest_device(df):
    """The device carrying the most total span time — every boundary and
    sequence source anchors to the same chip."""
    return df.groupby("deviceId")["duration"].sum().idxmax()


def _iterations_from_steps(frames) -> Optional[Tuple[List[float], List[float]]]:
    """Exact (begins, ends) from the device plane's "Steps" line, if traced.

    XLA demarcates profiler steps on the device itself (one span per
    StepMarker); these are device-anchored and exact, so they beat both
    host-marker matching and sequence mining whenever present.
    """
    steps = frames.get("tpusteps")
    if steps is None or steps.empty:
        return None
    dev = _busiest_device(steps)
    rows = steps[steps["deviceId"] == dev].sort_values("timestamp")
    if len(rows) < 2:
        return None
    begins = rows["timestamp"].astype(float).tolist()
    ends = (rows["timestamp"] + rows["duration"]).astype(float).tolist()
    return begins, ends


def _iterations_from_markers(frames) -> Optional[Tuple[List[float], List[float]]]:
    """Exact (begins, ends) from sofa_step_<i> TraceAnnotations, if present.

    The annotation spans live on the host plane and wrap the host-side step
    *dispatch*; under JAX async dispatch the device executes each step later
    than its enqueue.  So markers contribute the step count and order, and the
    boundaries are re-anchored to the device plane when possible: marker k is
    matched (greedy, in time order) to the first unclaimed device module
    launch starting at or after its host begin.  Without a usable device
    module trace the raw host spans are used, with the documented skew.
    """
    host = frames.get("hosttrace")
    if host is None or host.empty:
        return None
    marks = host[host["name"].str.match(_STEP_MARKER_RE)].copy()
    marks["step"] = marks["name"].str.extract(_STEP_MARKER_RE).astype(int)
    marks = marks.sort_values(["step", "timestamp"]).drop_duplicates("step")
    if len(marks) < 2:
        return None
    begins = marks["timestamp"].astype(float).tolist()
    span_ends = (marks["timestamp"] + marks["duration"]).astype(float).tolist()

    anchored = _anchor_to_device(frames, begins)
    if anchored is not None:
        return anchored
    # Host-span fallback: the span end is the *enqueue* end, which under
    # async dispatch undershoots the device completion — pad the final
    # boundary to at least one median step period.
    last_end = span_ends[-1]
    if len(begins) >= 2:
        period = float(np.median(np.diff(np.asarray(begins))))
        last_end = max(last_end, begins[-1] + period)
    return begins, begins[1:] + [last_end]


def _anchor_to_device(frames, host_begins: List[float]):
    """Map host-side marker begins to device-side module-launch windows."""
    modules = frames.get("tpumodules")
    if modules is None or modules.empty:
        return None
    dev = _busiest_device(modules)
    mods = modules[modules["deviceId"] == dev]
    # The step program is the module with the largest total device time; a
    # small per-step helper (scalar readback/convert) can out-COUNT the real
    # step module, but cannot out-weigh it.  If the heaviest module launches
    # fewer times than there are markers (e.g. it compiled once), fall back
    # to the most-launched one.
    per_name = mods.groupby("name")["duration"].agg(["sum", "count"])
    top = per_name["sum"].idxmax()
    if per_name.loc[top, "count"] < len(host_begins):
        top = per_name["count"].idxmax()
    launches = mods[mods["name"] == top].sort_values("timestamp")
    lts = launches["timestamp"].to_numpy(dtype=float)
    lend = lts + launches["duration"].to_numpy(dtype=float)

    # 100 us of slack: clock-alignment jitter between host and device planes
    # can place a step's launch marginally before its marker begin.
    eps = 1e-4
    begins: List[float] = []
    last_end = 0.0
    j = 0
    for hb in host_begins:
        while j < len(lts) and lts[j] < max(hb, 0.0) - eps:
            j += 1
        if j >= len(lts):
            return None                    # fewer launches than markers
        begins.append(float(lts[j]))
        last_end = float(lend[j])
        j += 1
    return begins, begins[1:] + [last_end]


def detect_iterations(
    names: List[str],
    num_iterations: int,
    tolerance: int = 2,
    fuzzy: bool = True,
) -> Tuple[List[int], int]:
    """Return (start indices of each detected iteration, pattern length).

    Candidate patterns come from the suffix automaton's overlapping counts,
    then each is re-verified with a non-overlapping scan: periodic sequences
    make a k-period pattern "occur" nearly as often as the true period, so
    the candidate whose non-overlapping count lands closest to the target
    (best coverage on ties) wins.
    """
    if len(names) < num_iterations:
        return [], 0
    symbols = {}
    seq = [symbols.setdefault(n, len(symbols)) for n in names]
    sa = SuffixAutomaton(seq)
    candidates = sa.repeat_candidates(
        num_iterations, tolerance=tolerance,
        # the expected period anchors the candidate ordering; without it a
        # long periodic trace yields thousands of multi-period candidates
        # and the truncated list never contains the true step pattern
        prefer_len=len(seq) / max(num_iterations, 1))
    best_occ: List[int] = []
    best_len = 0
    best_key = None
    for start, length, _count in candidates:
        pattern = seq[start:start + length]
        occ = find_occurrences(seq, pattern)
        if abs(len(occ) - num_iterations) > tolerance:
            continue
        key = (-abs(len(occ) - num_iterations), length * len(occ), length)
        if best_key is None or key > best_key:
            best_key = key
            best_occ = occ
            best_len = length
    if not best_occ and candidates and fuzzy:
        start, length, _count = candidates[0]
        best_occ = fuzzy_occurrences(seq, seq[start:start + length], min_ratio=0.9)
        best_len = length
    return best_occ, best_len


def _window_time(df: pd.DataFrame, t0: float, t1: float) -> Tuple[float, int]:
    """(total span time clipped to [t0, t1), number of overlapping spans)."""
    ts = df["timestamp"].to_numpy(dtype=float)
    dur = df["duration"].to_numpy(dtype=float)
    s = np.clip(ts, t0, t1)
    e = np.clip(ts + dur, t0, t1)
    ov = np.maximum(e - s, 0.0)
    # zero-duration spans (strace -T can report <0.000000>) still count as
    # occurrences when they START inside the window
    inside = (ts >= t0) & (ts < t1)
    return float(ov.sum()), int(((ov > 0) | inside).sum())


def _sample_period(pystacks: Optional[pd.DataFrame]) -> float:
    """The py-stack sampler's tick interval, inferred from the capture
    itself (median gap between distinct sample timestamps) — the frame
    doesn't carry the configured rate."""
    if pystacks is None or pystacks.empty:
        return 0.0
    ts = np.sort(pystacks["timestamp"].unique())
    if len(ts) < 2:
        return 0.0
    return float(np.median(np.diff(ts)))


def sofa_aisi(frames, cfg, features: Features) -> Optional[pd.DataFrame]:
    """Detect iterations on the busiest TPU device and profile each one.

    Writes iterations.csv; appends per-step features and the
    compute- vs communication-bound verdict.
    """
    source = cfg.iterations_from  # auto | steps | marker | module | op
    tputrace = frames.get("tputrace")
    modules = frames.get("tpumodules")

    marked = None
    label = ""
    if source in ("auto", "steps"):
        marked = _iterations_from_steps(frames)
        label = "device-plane step spans"
        if marked is None and source == "steps":
            print_warning("aisi: iterations_from=steps but the device trace "
                          "has fewer than two step spans")
            return None
    if marked is None and source in ("auto", "marker"):
        marked = _iterations_from_markers(frames)
        label = "explicit sofa_step markers"
        if marked is None and source == "marker":
            print_warning("aisi: iterations_from=marker but no usable "
                          "sofa_step annotations in the host trace")
            return None
    if marked is not None:
        bounds, ends = marked
        print_progress(f"aisi: {len(bounds)} iterations from {label}")
    else:
        if source in ("auto", "module") and modules is not None \
                and not modules.empty:
            seq_df, label = _module_sequence(modules), "module launches"
        elif tputrace is not None and not tputrace.empty:
            seq_df, label = _op_sequence(tputrace), "HLO ops"
        else:
            return None
        if seq_df.empty:
            return None

        names = list(seq_df["name"])
        starts, pattern_len = detect_iterations(names, cfg.num_iterations)
        if len(starts) < 2:
            print_warning(
                f"aisi: no pattern repeating ~{cfg.num_iterations}x in {label} "
                f"({len(names)} events)"
            )
            return None
        print_progress(f"aisi: detected {len(starts)} iterations over {label}")

        ts = seq_df["timestamp"].to_numpy(dtype=float)
        dur = seq_df["duration"].to_numpy(dtype=float)
        bounds = [float(ts[i]) for i in starts]
        # Each iteration ends where the next begins; the last ends after its
        # own pattern_len events (NOT len/num_iterations, which would absorb
        # warmup or teardown ops into the final step).
        last_end_idx = min(starts[-1] + pattern_len, len(ts))
        ends = bounds[1:] + [float((ts + dur)[last_end_idx - 1])]

    strace = frames.get("strace")
    pystacks = frames.get("pystacks")
    hosttrace = frames.get("hosttrace")
    py_period = _sample_period(pystacks)
    rows = []
    for it, (t0, t1) in enumerate(zip(bounds, ends)):
        row = {"iteration": it, "begin": t0, "end": t1, "step_time": t1 - t0}
        # Host-side attribution per step (the reference's iter_profile
        # credits syscalls and per-iteration payload to each iteration,
        # sofa_aisi.py:21-59): syscall wall time + count from strace spans
        # clipped to the step window, Python wall time from pystacks sample
        # counts x the sampler's own period, runtime-API time from the
        # host plane.
        if strace is not None and not strace.empty:
            t, c = _window_time(strace, t0, t1)
            row["syscall_time"], row["syscall_count"] = t, c
        if pystacks is not None and not pystacks.empty and py_period > 0:
            in_win = pystacks[(pystacks["timestamp"] >= t0)
                              & (pystacks["timestamp"] < t1)]
            # samples, not spans: wall time ~= samples x period (per thread
            # samples double-count the wall clock, so count distinct ticks)
            row["host_python_time"] = (
                float(in_win["timestamp"].nunique()) * py_period)
        if hosttrace is not None and not hosttrace.empty:
            t, _ = _window_time(hosttrace, t0, t1)
            row["host_runtime_time"] = t
        if tputrace is not None and not tputrace.empty:
            ops = tputrace[
                (tputrace["timestamp"] >= t0)
                & (tputrace["timestamp"] < t1)
                & (tputrace["category"] == 0)
            ]
            row["op_time"] = float(ops["duration"].sum())
            row["kernel_time"] = float(
                ops.loc[ops["copyKind"] == int(CopyKind.KERNEL), "duration"].sum()
            )
            coll = ops[ops["copyKind"] >= 20]
            row["collective_time"] = float(coll["duration"].sum())
            row["collective_bytes"] = float(coll["payload"].sum())
            row["flops"] = float(ops["flops"].sum())
            row["bytes_accessed"] = float(ops["bytes_accessed"].sum())
            # fw/bw split from the provenance-derived phase column (the
            # reference's _fw_/_bw_ kernel-name split, sofa_aisi.py:34-36).
            row["fw_time"] = float(
                ops.loc[ops["phase"] == "fw", "duration"].sum())
            row["bw_time"] = float(
                ops.loc[ops["phase"] == "bw", "duration"].sum())
            # compute-only variants for stacked views: collectives carry a
            # phase too (a gradient all-reduce is "bw"), so fw/bw_time
            # overlap collective_time — these exclude it, making
            # fw_compute + bw_compute + collective disjoint slices.
            comp = ops[ops["copyKind"] < 20]
            row["fw_compute_time"] = float(
                comp.loc[comp["phase"] == "fw", "duration"].sum())
            row["bw_compute_time"] = float(
                comp.loc[comp["phase"] == "bw", "duration"].sum())
            copies = tputrace[
                (tputrace["timestamp"] >= t0) & (tputrace["timestamp"] < t1)
                & (tputrace["copyKind"].isin([int(CopyKind.H2D), int(CopyKind.D2H)]))
            ]
            row["transfer_time"] = float(copies["duration"].sum())
        rows.append(row)
    table = pd.DataFrame(rows)
    table.to_csv(cfg.path("iterations.csv"), index=False)

    steps = table["step_time"].to_numpy(dtype=float)
    steps = steps[steps > 0]
    if len(steps):
        features.add("aisi_iterations", len(table))
        features.add("aisi_step_time_mean", float(np.mean(steps)))
        features.add("aisi_step_time_gmean", float(np.exp(np.mean(np.log(steps)))))
        features.add("aisi_step_time_std", float(np.std(steps)))
    if "op_time" in table.columns and table["op_time"].sum() > 0:
        comm_ratio = float(table["collective_time"].sum() / table["op_time"].sum())
        features.add("aisi_comm_ratio", comm_ratio)
        if comm_ratio >= COMM_BOUND_RATIO:
            print_hint(
                f"aisi verdict: COMMUNICATION-bound (collectives {comm_ratio:.0%} "
                "of per-step device time)"
            )
        else:
            print_hint(
                f"aisi verdict: COMPUTE-bound (collectives {comm_ratio:.0%} "
                "of per-step device time)"
            )
    return table


def _module_sequence(modules: pd.DataFrame) -> pd.DataFrame:
    dev = _busiest_device(modules)
    return modules[modules["deviceId"] == dev].sort_values("timestamp")


def _op_sequence(tputrace: pd.DataFrame) -> pd.DataFrame:
    sync = tputrace[tputrace["category"] == 0]
    if sync.empty:
        return sync
    dev = _busiest_device(sync)
    return sync[sync["deviceId"] == dev].sort_values("timestamp")


def iteration_series(table: Optional[pd.DataFrame]):
    """Timeline marker series for the board (reference injects iteration
    begin/end markers into report.js, sofa_aisi.py:318-345)."""
    if table is None or table.empty:
        return None
    from sofa_tpu.trace import SofaSeries, make_frame

    rows = []
    for _, r in table.iterrows():
        rows.append(
            {
                "timestamp": r["begin"],
                "event": 0.0,
                "duration": r["step_time"],
                "name": f"iter {int(r['iteration'])}",
                "device_kind": "tpu",
            }
        )
    return SofaSeries("iterations", "Iterations", "black", make_frame(rows), kind="scatter")
