"""Full report pipeline over a synthetic MULTI-DEVICE capture.

Unit tests feed hand-made frames to single passes; this builds a 4-chip
XSpace proto (Steps lines, XLA Ops with an all-reduce carrying
replica_groups in its HLO text, per-device skewed step begins), writes a
raw logdir, and drives `sofa report` end-to-end — so the ICI matrix, step
skew, comm attribution, and device-step iteration detection are exercised
through the real ingest path, not frame fixtures.  (Real multi-chip
hardware is unavailable; this is the closest CPU-only integration.)
"""

import json
import os
import subprocess
import sys

import pandas as pd
import pytest

from sofa_tpu.ingest import xplane_pb2

N_DEV = 4
STEP_NS = 1_000_000          # 1 ms steps
SKEW_NS = 50_000             # chip d starts each step d*50 us late


from conftest import MARKER_UNIX_NS, add_event, add_stat


def build_multichip_xspace() -> xplane_pb2.XSpace:
    xs = xplane_pb2.XSpace()
    host = xs.planes.add()
    host.name = "/host:CPU"
    hline = host.lines.add()
    hline.id = 1
    hline.name = "python"
    hline.timestamp_ns = 0
    add_event(host, hline, f"sofa_timebase_marker:{MARKER_UNIX_NS}", 1_000_000,
           1000)

    mega = xs.planes.add()
    mega.name = "/device:CUSTOM:Megascale Trace"
    gline = mega.lines.add()
    gline.id = 3
    gline.name = "dcn"
    add_event(mega, gline, "send_reduce.4", 2_500_000, 400_000)

    ar_text = ("%all-reduce.7 = bf16[1024]{0} all-reduce(%x), "
               "replica_groups={{0,1,2,3}}, to_apply=%add")
    for d in range(N_DEV):
        dev = xs.planes.add()
        dev.name = f"/device:TPU:{d}"
        add_stat(dev, dev, "peak_teraflops_per_second", 100.0)
        add_stat(dev, dev, "peak_hbm_bw_gigabytes_per_second", 800.0)
        sline = dev.lines.add()
        sline.name = "Steps"
        mline = dev.lines.add()
        mline.name = "XLA Modules"
        oline = dev.lines.add()
        oline.name = "XLA Ops"
        for step in range(4):
            t0 = 2_000_000 + step * STEP_NS + d * SKEW_NS
            add_event(dev, sline, str(step), t0, STEP_NS - 100_000)
            add_event(dev, mline, "jit_train(42)", t0, STEP_NS - 100_000)
            add_event(dev, oline, "%fusion.1 = ...", t0 + 10_000, 600_000,
                   mstats=[("hlo_category", "convolution fusion"),
                           ("flops", 4_000_000_000),
                           ("bytes_accessed", 2_000_000),
                           ("tf_op", "jit(train)/jvp(net)/conv")])
            add_event(dev, oline, ar_text, t0 + 620_000, 200_000,
                   mstats=[("hlo_category", "all-reduce"),
                           ("bytes_accessed", 8_000_000)])
            add_event(dev, oline, "%fusion.9 = ...", t0 + 830_000, 60_000,
                   mstats=[("hlo_category", "loop fusion"),
                           ("flops", 1_000_000),
                           ("bytes_accessed", 500_000),
                           ("tf_op",
                            "jit(train)/transpose(jvp(net))/conv_bwd")])
    return xs


@pytest.fixture(scope="module")
def report_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("multichip")
    logdir = str(d) + "/"
    prof = os.path.join(logdir, "xprof", "plugins", "profile", "run1")
    os.makedirs(prof)
    with open(os.path.join(prof, "host.xplane.pb"), "wb") as f:
        f.write(build_multichip_xspace().SerializeToString())
    with open(os.path.join(logdir, "sofa_time.txt"), "w") as f:
        f.write(f"{MARKER_UNIX_NS / 1e9 - 1.0}\n")
    with open(os.path.join(logdir, "tpu_topo.json"), "w") as f:
        json.dump({"devices": [
            {"id": i, "process_index": 0, "coords": [i, 0, 0]}
            for i in range(N_DEV)]}, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "report", "--logdir", logdir,
         "--enable_aisi", "--num_iterations", "4"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Complete!!" in r.stdout
    return logdir, r.stdout


def test_multichip_ici_matrix(report_dir):
    logdir, _ = report_dir
    mat = pd.read_csv(os.path.join(logdir, "ici_matrix.csv"), index_col=0)
    arr = mat.to_numpy()
    assert arr.shape == (N_DEV, N_DEV)
    # Ring all-reduce estimate: per instance each chip sends
    # 2*P*(n-1)/n = 12 MB to its ring successor; 4 steps -> 48 MB on each
    # of exactly 4 successor edges, nothing anywhere else.
    per_edge = 2 * 8e6 * (N_DEV - 1) / N_DEV * 4
    nonzero = arr[arr > 0]
    assert len(nonzero) == N_DEV
    assert nonzero == pytest.approx([per_edge] * N_DEV)
    assert (arr.diagonal() == 0).all()


def test_multichip_step_skew(report_dir):
    logdir, _ = report_dir
    skew = pd.read_csv(os.path.join(logdir, "tpu_step_skew.csv"))
    assert len(skew) == 4
    # chips 0..3 start (d * 50 us) apart -> skew 150 us per step
    # abs tolerance: the timestamp pipeline divides epoch-scale ns by 1e9,
    # whose float64 ulp (~0.24 us) dwarfs any relative tolerance here.
    assert skew["skew"].max() == pytest.approx(3 * SKEW_NS / 1e9, abs=1e-6)


def test_multichip_features_and_iterations(report_dir):
    logdir, out = report_dir
    feats = pd.read_csv(os.path.join(logdir, "features.csv"))
    get = dict(zip(feats["name"], feats["value"]))
    assert get["tpu_devices"] == N_DEV
    assert get["tpu_fw_time"] > 0 and get["tpu_bw_time"] > 0
    assert get["step_skew_mean"] > 0
    # collective attribution reaches the comm profile
    assert get["comm_all_reduce_bytes"] == pytest.approx(8e6 * 4 * 4)
    # device-plane steps drive aisi
    assert "device-plane step spans" in out
    iters = pd.read_csv(os.path.join(logdir, "iterations.csv"))
    assert len(iters) == 4
    # op tree got both fw and bw paths
    tree = pd.read_csv(os.path.join(logdir, "tpu_op_tree.csv"))
    assert any("transpose" in p for p in tree["path"])


def test_multichip_custom_plane_preserved(report_dir):
    logdir, _ = report_dir
    custom = pd.read_csv(os.path.join(logdir, "customtrace.csv"))
    assert len(custom) == 1
    row = custom.iloc[0]
    assert row["name"] == "send_reduce.4"
    assert row["module"] == "host:Megascale Trace"
    assert row["device_kind"] == "custom"
    assert row["deviceId"] == 0          # host 0's ordinal base
