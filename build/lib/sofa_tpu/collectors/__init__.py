"""Collector classes for `sofa record`.

The reference implements collection as one 370-line function full of Popen
handles and daemon threads (/root/reference/bin/sofa_record.py:150-524).
Here every source is a Collector with a uniform probe/start/stop/harvest
lifecycle plus two composition hooks — a command prefix (strace-style) and
child-environment injection (the JAX profiler hook) — so record.py is a thin
orchestrator and each collector degrades independently when its tool or
hardware is absent (SURVEY §1 "graceful degradation everywhere").
"""

from sofa_tpu.collectors.base import Collector, CollectorState  # noqa: F401
