"""sofa_tpu/whatif/ — the hardware-free what-if replay engine.

Covers the ISSUE 9 acceptance surface: scenario parsing (incl.
unknown-scenario degradation), model decomposition exactness, replay
determinism across ``--jobs``, the identity calibration gate (pass AND
fail), ``sol``-scaling fed from a synthetic ``sol_roofline.csv``, CLI
exit codes, report schema validation via tools/manifest_check.py,
``meta.whatif`` manifest plumbing, ``sofa clean`` / ``sofa resume``
integration, the registered ``whatif_model`` pass, and a pod_synth
end-to-end (slow-marked).
"""

import importlib.util
import json
import os

import numpy as np
import pandas as pd
import pytest

from sofa_tpu.config import SofaConfig
from sofa_tpu.trace import make_frame, write_csv
from sofa_tpu.whatif import (REPORT_NAME, WHATIF_SCHEMA, run_whatif,
                             sofa_whatif, whatif_hints)
from sofa_tpu.whatif.calibrate import calibration, error_bars
from sofa_tpu.whatif.model import build_model
from sofa_tpu.whatif.replay import (load_sol_table, measured_step_times,
                                    replay)
from sofa_tpu.whatif.scenarios import parse_scenario, parse_scenarios

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_DEV, N_STEPS = 2, 8
STEP_S, COMPUTE_S, COLL_S, GAP_S = 0.05, 0.03, 0.01, 0.01


def _mc():
    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(ROOT, "tools", "manifest_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def synth_frames(n_dev=N_DEV, n_steps=N_STEPS):
    """Per step: 30 ms fusion compute, 10 ms fully-exposed all-reduce,
    10 ms gap — a decomposition every test can predict by hand."""
    ops, steps = [], []
    for dev in range(n_dev):
        t = 0.0
        for s in range(n_steps):
            steps.append({"timestamp": t, "duration": STEP_S,
                          "deviceId": dev, "event": float(s),
                          "name": f"step {s}", "device_kind": "tpu"})
            ops.append({"timestamp": t, "duration": COMPUTE_S,
                        "deviceId": dev, "category": 0, "copyKind": 0,
                        "name": "fusion.1", "hlo_category": "fusion",
                        "flops": 3e12, "bytes_accessed": 1e6,
                        "device_kind": "tpu"})
            ops.append({"timestamp": t + COMPUTE_S, "duration": COLL_S,
                        "deviceId": dev, "category": 0, "copyKind": 21,
                        "name": "all-reduce.1",
                        "hlo_category": "all-reduce",
                        "device_kind": "tpu"})
            t += STEP_S
    return {"tputrace": make_frame(ops), "tpusteps": make_frame(steps)}


def write_logdir(logdir, frames):
    os.makedirs(logdir, exist_ok=True)
    write_csv(frames["tputrace"], os.path.join(logdir, "tputrace.csv"))
    write_csv(frames["tpusteps"], os.path.join(logdir, "tpusteps.csv"))
    with open(os.path.join(logdir, "misc.txt"), "w") as f:
        f.write("elapsed_time 0.4\ncores 2\npid 1\nrc 0\n")


@pytest.fixture
def frames():
    return synth_frames()


@pytest.fixture
def cfg(logdir, frames):
    write_logdir(logdir, frames)
    return SofaConfig(logdir=logdir)


# --------------------------------------------------------------------------
# scenarios.py — parsing + degradation
# --------------------------------------------------------------------------

def test_parse_each_kind():
    s = parse_scenario("overlap:all-reduce")
    assert (s.kind, s.pattern) == ("overlap", "all-reduce")
    s = parse_scenario("scale:fusion*=0.5")
    assert (s.kind, s.pattern, s.factor) == ("scale", "fusion*", 0.5)
    s = parse_scenario("scale:*=sol")
    assert s.factor == "sol"
    assert parse_scenario("link:2").factor == 2.0
    assert parse_scenario("batch:1.5").factor == 1.5


def test_parse_unknown_degrades_not_aborts():
    scenarios, problems = parse_scenarios(
        "frobnicate:9,scale:fusion=0.5,overlap")
    assert len(scenarios) == 3
    assert [s.known for s in scenarios] == [False, True, False]
    assert len(problems) == 2
    assert "unknown scenario kind" in problems[0]


@pytest.mark.parametrize("bad", ["link:abc", "link:0", "link:-2",
                                 "scale:fusion=", "scale:=0.5",
                                 "scale:fusion", "batch:"])
def test_parse_malformed_is_unknown(bad):
    s = parse_scenario(bad)
    assert not s.known and s.problem


def test_parse_empty_spec():
    assert parse_scenarios("") == ([], [])
    assert parse_scenarios(" , ,") == ([], [])


# --------------------------------------------------------------------------
# model.py — decomposition exactness
# --------------------------------------------------------------------------

def test_model_components_sum_to_step_duration(frames, cfg):
    model = build_model(frames, cfg)
    per = model.groupby(["deviceId", "step"]).agg(
        dur=("dur", "first"), total=("seconds", "sum"))
    assert len(per) == N_DEV * N_STEPS
    assert np.allclose(per["dur"], per["total"])
    by_kind = model.groupby("kind")["seconds"].sum()
    n = N_DEV * N_STEPS
    assert by_kind["compute"] == pytest.approx(COMPUTE_S * n)
    assert by_kind["collective"] == pytest.approx(COLL_S * n)
    assert by_kind["gap"] == pytest.approx(GAP_S * n)


def test_model_empty_without_steps(cfg):
    assert build_model({}, cfg).empty
    assert build_model({"tpusteps": make_frame([])}, cfg).empty


def test_model_ops_missing_is_all_gap(frames, cfg):
    model = build_model({"tpusteps": frames["tpusteps"]}, cfg)
    assert set(model["kind"]) == {"gap"}
    assert model["seconds"].sum() == pytest.approx(
        STEP_S * N_DEV * N_STEPS)


# --------------------------------------------------------------------------
# replay.py — scenario semantics + attribution
# --------------------------------------------------------------------------

def test_identity_replay_reproduces_measured(frames, cfg):
    model = build_model(frames, cfg)
    r = replay(model, [])
    assert r["mean_predicted_s"] == pytest.approx(r["mean_measured_s"])
    for s in r["steps"]:
        assert s["predicted_s"] == pytest.approx(s["measured_s"])


def test_overlap_hides_exposed_collective(frames, cfg):
    model = build_model(frames, cfg)
    scenarios, _ = parse_scenarios("overlap:all-reduce")
    r = replay(model, scenarios)
    # the 10 ms exposure hides entirely (30 ms compute available)
    assert r["mean_predicted_s"] == pytest.approx(STEP_S - COLL_S)
    att = r["attribution"][0]
    assert att["status"] == "applied"
    assert att["delta_s"] == pytest.approx(COLL_S)


def test_overlap_bounded_by_available_compute(cfg):
    # collective twice the compute: only the compute-sized part can hide
    ops, steps = [], []
    for s in range(6):
        t = s * 0.1
        steps.append({"timestamp": t, "duration": 0.1, "deviceId": 0,
                      "event": float(s), "device_kind": "tpu"})
        ops.append({"timestamp": t, "duration": 0.02, "deviceId": 0,
                    "category": 0, "copyKind": 0, "name": "fusion.1",
                    "hlo_category": "fusion"})
        ops.append({"timestamp": t + 0.02, "duration": 0.06, "deviceId": 0,
                    "category": 0, "copyKind": 21, "name": "all-reduce.1",
                    "hlo_category": "all-reduce"})
    model = build_model({"tputrace": make_frame(ops),
                         "tpusteps": make_frame(steps)}, cfg)
    scenarios, _ = parse_scenarios("overlap:*")
    r = replay(model, scenarios)
    assert r["attribution"][0]["delta_s"] == pytest.approx(0.02)


def test_scale_and_link_and_batch(frames, cfg):
    model = build_model(frames, cfg)
    r = replay(model, parse_scenarios("scale:fusion=0.5")[0])
    assert r["mean_predicted_s"] == pytest.approx(STEP_S - COMPUTE_S / 2)
    r = replay(model, parse_scenarios("link:2")[0])
    assert r["mean_predicted_s"] == pytest.approx(STEP_S - COLL_S / 2)
    r = replay(model, parse_scenarios("batch:2")[0])
    assert r["mean_predicted_s"] == pytest.approx(STEP_S + COMPUTE_S)


def test_attribution_is_marginal_and_sums(frames, cfg):
    model = build_model(frames, cfg)
    scenarios, _ = parse_scenarios(
        "overlap:all-reduce,scale:fusion=0.5,frobnicate:9")
    r = replay(model, scenarios)
    deltas = [a["delta_s"] for a in r["attribution"]]
    assert sum(deltas) == pytest.approx(
        r["mean_measured_s"] - r["mean_predicted_s"])
    assert r["attribution"][2]["status"] == "unknown"
    assert r["attribution"][2]["delta_s"] == 0.0


def test_scale_sol_from_synthetic_roofline_csv(frames, cfg):
    # sol_time/time = 0.5 for fusion on both devices -> compute halves
    pd.DataFrame([
        {"deviceId": d, "hlo_category": "fusion", "time": 0.24,
         "sol_time": 0.12} for d in range(N_DEV)
    ]).to_csv(cfg.path("sol_roofline.csv"), index=False)
    sol = load_sol_table(cfg)
    assert sol[(0, "fusion")] == pytest.approx(0.5)
    model = build_model(frames, cfg)
    r = replay(model, parse_scenarios("scale:*=sol")[0], sol)
    assert r["mean_predicted_s"] == pytest.approx(STEP_S - COMPUTE_S / 2)


def test_scale_sol_without_roofline_degrades(frames, cfg):
    model = build_model(frames, cfg)
    r = replay(model, parse_scenarios("scale:*=sol")[0], {})
    att = r["attribution"][0]
    assert att["status"] == "no_match"
    assert "sol_roofline.csv" in att["note"]
    assert r["mean_predicted_s"] == pytest.approx(STEP_S)


# --------------------------------------------------------------------------
# calibrate.py — the identity gate
# --------------------------------------------------------------------------

def test_calibration_gate_passes_on_exact_identity():
    measured = [0.05, 0.051, 0.049, 0.05, 0.052, 0.048, 0.05]
    c = calibration(measured, sum(measured) / len(measured))
    assert c["verdict"] == "calibrated"
    assert c["identity_error_pct"] == pytest.approx(0.0)
    assert c["ci"] is not None


def test_calibration_gate_fails_on_model_damage():
    measured = [0.05, 0.051, 0.049, 0.05, 0.052, 0.048, 0.05]
    c = calibration(measured, 0.08)   # replay 60% off: broken model
    assert c["verdict"] == "uncalibrated"
    assert "outside" in c["reason"]


def test_calibration_needs_a_defensible_ci():
    c = calibration([0.05, 0.05, 0.05], 0.05)
    assert c["verdict"] == "uncalibrated"
    assert "no defensible 95% CI" in c["reason"]
    assert error_bars(c, 0.04) is None
    assert calibration([], 0.0)["verdict"] == "uncalibrated"


def test_error_bars_translate_measured_variance():
    measured = [0.04, 0.05, 0.05, 0.05, 0.05, 0.06, 0.05]
    c = calibration(measured, sum(measured) / len(measured))
    bars = error_bars(c, 0.03)
    lo, hi = c["ci"]
    med = c["measured_median_s"]
    assert bars == [pytest.approx(0.03 - (med - lo)),
                    pytest.approx(0.03 + (hi - med))]


# --------------------------------------------------------------------------
# the verb: report, schema, manifest, CLI, clean, resume, determinism
# --------------------------------------------------------------------------

def test_run_whatif_writes_schema_valid_report(cfg):
    cfg.whatif_apply = "overlap:*,scale:fusion=0.5,frobnicate:9"
    doc = run_whatif(cfg)
    assert doc["schema"] == WHATIF_SCHEMA
    assert os.path.isfile(cfg.path(REPORT_NAME))
    mc = _mc()
    assert mc.validate_whatif(doc) == []
    assert doc["calibration"]["verdict"] == "calibrated"
    assert [s["status"] for s in doc["scenarios"]] == \
        ["parsed", "parsed", "unknown"]
    assert doc["problems"]
    assert len(doc["steps"]) == N_DEV * N_STEPS
    assert doc["predicted"]["error_bars"] is not None


def test_report_jobs_determinism(tmp_path, frames):
    docs = []
    for jobs in (1, 4):
        d = str(tmp_path / f"j{jobs}") + "/"
        write_logdir(d, frames)
        cfg = SofaConfig(logdir=d, jobs=jobs,
                         whatif_apply="overlap:*,scale:fusion=0.5")
        docs.append(run_whatif(cfg))
    for doc in docs:
        doc.pop("generated_unix")
    assert docs[0] == docs[1]


def test_cli_exit_codes(cfg, tmp_path):
    from sofa_tpu.cli import main

    assert main(["whatif", cfg.logdir, "--apply", "overlap:*"]) == 0
    # too few steps for a defensible CI -> uncalibrated -> exit 1
    short = str(tmp_path / "short") + "/"
    write_logdir(short, synth_frames(n_dev=1, n_steps=3))
    assert main(["whatif", short]) == 1
    # nothing to replay -> exit 2
    assert main(["whatif", str(tmp_path / "nope") + "/"]) == 2


def test_cli_apply_flag_shared_with_setup():
    from sofa_tpu.cli import build_parser, config_from_args

    cfg = config_from_args(build_parser().parse_args(
        ["whatif", "x/", "--apply", "overlap:*,link:2"]))
    assert cfg.whatif_apply == "overlap:*,link:2"
    # setup's bare --apply stays a boolean, not a scenario spec
    args = build_parser().parse_args(["setup", "--apply"])
    assert args.apply is True
    assert config_from_args(args).whatif_apply == ""


def test_meta_whatif_in_manifest(cfg):
    cfg.whatif_apply = "overlap:*"
    assert sofa_whatif(cfg) == 0
    from sofa_tpu.telemetry import load_manifest

    doc = load_manifest(cfg.logdir)
    mc = _mc()
    assert mc.validate_manifest(doc) == []
    meta = doc["meta"]["whatif"]
    assert meta["verdict"] == "calibrated"
    assert meta["n_steps"] == N_DEV * N_STEPS
    assert "whatif" in doc["runs"]


def test_require_healthy_flags_uncalibrated(tmp_path):
    short = str(tmp_path / "short") + "/"
    write_logdir(short, synth_frames(n_dev=1, n_steps=3))
    cfg = SofaConfig(logdir=short)
    assert sofa_whatif(cfg) == 1
    mc = _mc()
    from sofa_tpu.telemetry import load_manifest

    doc = load_manifest(short)
    assert mc.validate_manifest(doc) == []
    probs = mc.validate_manifest(doc, require_healthy=True)
    assert any("uncalibrated" in p for p in probs)
    # the report itself is auto-detected and gate-checked the same way
    with open(os.path.join(short, REPORT_NAME)) as f:
        report = json.load(f)
    assert mc.validate_whatif(report) == []
    assert any("uncalibrated" in p for p in
               mc.validate_whatif(report, require_healthy=True))
    assert mc.check_path(os.path.join(short, REPORT_NAME)) == 0


def test_whatif_hints_rank_top_payoffs(cfg):
    cfg.whatif_apply = "overlap:*,scale:fusion=0.5"
    doc = run_whatif(cfg)
    hints = whatif_hints(doc)
    assert hints and all(h.startswith("[whatif]") for h in hints)
    # largest predicted saving first (scale saves 15 ms, overlap 10 ms)
    assert "scale:fusion=0.5" in hints[0]


def test_advice_pipeline_ranks_whatif_features(cfg):
    from sofa_tpu.analysis.advice import generate_hints
    from sofa_tpu.analysis.features import Features

    f = Features()
    f.add("whatif_overlap_payoff_pct", 8.0)
    f.add("whatif_sol_payoff_pct", 21.0)
    hints = [h for h in generate_hints(f, cfg) if h.startswith("[whatif]")]
    assert len(hints) == 2
    assert "speed-of-light" in hints[0]      # bigger payoff ranks first
    assert "sofa whatif" in hints[0]


def test_clean_removes_report_and_model(cfg):
    cfg.whatif_apply = ""
    assert sofa_whatif(cfg) == 0
    with open(cfg.path("whatif_model.csv"), "w") as f:
        f.write("deviceId\n")  # the pass artifact, present after analyze
    from sofa_tpu.record import sofa_clean

    sofa_clean(cfg)
    assert not os.path.exists(cfg.path(REPORT_NAME))
    assert not os.path.exists(cfg.path("whatif_model.csv"))
    assert os.path.exists(cfg.path("misc.txt"))  # raw inputs survive


def test_resume_replays_uncommitted_whatif(cfg):
    from sofa_tpu.durability import JOURNAL_NAME, sofa_resume

    cfg.whatif_apply = "overlap:*"
    assert sofa_whatif(cfg) == 0
    jpath = cfg.path(JOURNAL_NAME)
    with open(jpath) as f:
        lines = [ln for ln in f.read().splitlines()
                 if not ('"commit"' in ln and '"whatif"' in ln)]
    with open(jpath, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.unlink(cfg.path(REPORT_NAME))
    cfg.whatif_apply = ""  # the replay must recover the spec from begin
    assert sofa_resume(cfg) == 0
    with open(cfg.path(REPORT_NAME)) as f:
        doc = json.load(f)
    assert [s["spec"] for s in doc["scenarios"]] == ["overlap:*"]


# --------------------------------------------------------------------------
# the registered pass
# --------------------------------------------------------------------------

def test_whatif_model_pass_registered_and_scheduled():
    from sofa_tpu.analysis import registry

    registry.load_builtin_passes()
    spec = registry.get("whatif_model")
    assert spec is not None
    assert "tpusteps" in spec.reads_frames
    assert "tpu*_sol_distance" in spec.reads_features
    # scheduled strictly after its sol_roofline feature producer
    enabled = [s for s in registry.registered()
               if s.enabled(SofaConfig())]
    waves = registry.resolve_schedule(enabled, strict=True)
    wave_of = {s.name: i for i, w in enumerate(waves) for s in w}
    assert wave_of["whatif_model"] > wave_of["sol_roofline"]


def test_whatif_model_pass_emits_features_and_artifact(cfg, frames):
    from sofa_tpu.analysis import registry
    from sofa_tpu.analysis.features import Features

    with registry.scoped():
        registry.load_builtin_passes()
        features = Features()
        ledger, _series = registry.run_passes(frames, cfg, features)
        assert ledger["passes"]["whatif_model"]["status"] == "ok"
    assert features.get("whatif_steps") == N_DEV * N_STEPS
    assert features.get("whatif_step_time_mean") == pytest.approx(STEP_S)
    assert features.get("whatif_identity_error_pct") == pytest.approx(0.0)
    assert features.get("whatif_overlap_payoff_pct") == pytest.approx(
        100.0 * COLL_S / STEP_S)
    model = pd.read_csv(cfg.path("whatif_model.csv"))
    assert set(model["kind"]) == {"compute", "collective", "gap"}


# --------------------------------------------------------------------------
# end to end
# --------------------------------------------------------------------------

def test_e2e_analyze_then_whatif_with_sol(cfg):
    """The acceptance flow on a hand-sized trace: analyze builds
    sol_roofline.csv (plane-stats peak chosen so fusion headroom is 2x),
    then `scale:*=sol` + `overlap:*` each predict finite step times with
    attribution and stated error bars, and the identity gate passes."""
    with open(cfg.path("tpu_meta.json"), "w") as f:
        json.dump({str(d): {"peak_teraflops_per_second": 200.0,
                            "peak_hbm_bw_gigabytes_per_second": 1000.0}
                   for d in range(N_DEV)}, f)
    from sofa_tpu.analyze import sofa_analyze

    sofa_analyze(cfg)
    assert os.path.isfile(cfg.path("sol_roofline.csv"))
    with open(cfg.path("hints.txt")) as f:
        assert "[whatif]" in f.read()

    cfg.whatif_apply = "overlap:*,scale:*=sol"
    assert sofa_whatif(cfg) == 0
    with open(cfg.path(REPORT_NAME)) as f:
        doc = json.load(f)
    assert _mc().validate_whatif(doc, require_healthy=True) == []
    pred = doc["predicted"]
    assert np.isfinite(pred["step_time_mean_s"])
    assert pred["error_bars"] is not None
    att = {a["scenario"]: a for a in pred["attribution"]}
    assert att["overlap:*"]["status"] == "applied"
    assert att["overlap:*"]["delta_s"] == pytest.approx(COLL_S)
    assert att["scale:*=sol"]["status"] == "applied"
    # sol headroom 2x on 3e12*8-flop fusion vs the 200 TF peak:
    # 24e12/200e12 = 0.12 s attainable vs 0.24 s measured per device
    assert att["scale:*=sol"]["delta_s"] == pytest.approx(
        COMPUTE_S / 2, rel=0.01)
    assert pred["step_time_mean_s"] == pytest.approx(
        STEP_S - COLL_S - COMPUTE_S / 2, rel=0.01)


@pytest.mark.slow
def test_pod_synth_e2e(tmp_path):
    """ISSUE 9 acceptance on the real harness: pod_synth, analyze, then
    the zero-scenario identity gate passes and both canonical scenarios
    produce finite calibrated predictions."""
    import subprocess
    import sys

    logdir = str(tmp_path / "pod") + "/"
    subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "pod_synth.py"),
         logdir], check=True, capture_output=True, timeout=600)
    cfg = SofaConfig(logdir=logdir)
    from sofa_tpu.analyze import sofa_analyze

    sofa_analyze(cfg)
    cfg.whatif_apply = "overlap:*,scale:*=sol"
    assert sofa_whatif(cfg) == 0
    with open(cfg.path(REPORT_NAME)) as f:
        doc = json.load(f)
    assert _mc().validate_whatif(doc, require_healthy=True) == []
    assert doc["calibration"]["verdict"] == "calibrated"
    assert np.isfinite(doc["predicted"]["step_time_mean_s"])
    assert doc["predicted"]["error_bars"] is not None
    att = doc["predicted"]["attribution"]
    assert [a["scenario"] for a in att] == ["overlap:*", "scale:*=sol"]
