"""Rolling-baseline math for the regression engine.

The statistical discipline is tools/overhead_budget.py's: a verdict needs
a *defensible interval*, and the only distribution-free one available
from a catalog of run samples is the nonparametric 95 % CI of the median
via binomial order statistics.  Below 6 samples no such CI exists — a
sample range is NOT a 95 % CI — so rolling comparisons against a short
history degrade to ``noise`` with an explicit reason instead of
manufacturing confidence.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

# Minimum rolling samples for an order-statistic 95 % CI (the same floor
# overhead_budget._median_ci enforces).
MIN_CI_SAMPLES = 6


def median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def median_ci(xs: List[float],
              conf: float = 0.95) -> "Optional[Tuple[float, float]]":
    """Nonparametric CI for the median via binomial order statistics
    (normal approximation to the rank) — distribution-free, so fat-tailed
    run-to-run jitter can't fake a tight bound.  None below
    MIN_CI_SAMPLES."""
    n = len(xs)
    if n < MIN_CI_SAMPLES:
        return None
    s = sorted(xs)
    z = 1.959964 if conf >= 0.95 else 1.644854
    delta = z * math.sqrt(n) / 2.0
    lo = max(0, int(math.floor(n / 2.0 - delta)))
    hi = min(n - 1, int(math.ceil(n / 2.0 + delta)) - 1)
    return s[lo], s[hi]


def percentile(xs: List[float], pct: float) -> float:
    """Linear-interpolated percentile (pct in [0, 100])."""
    s = sorted(xs)
    if len(s) == 1:
        return s[0]
    pos = max(0.0, min(100.0, pct)) / 100.0 * (len(s) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(s) - 1)
    frac = pos - lo
    return s[lo] * (1 - frac) + s[hi] * frac


# ---------------------------------------------------------------------------
# Feature polarity: which direction is a regression?
# ---------------------------------------------------------------------------

# Higher is worse: durations, latencies, skew, overhead, model error,
# peak memory (the out-of-core frame store's analyze_peak_rss_mb),
# speed-of-light distance (sol_roofline: how far measured kernels sit
# from the hardware's attainable peak — the fleet board's ranking key),
# millisecond latencies (the fleet tier's push/query p50/p99), and the
# observability plane's own cost pair (tier_metrics_overhead_pct /
# tier_scrape_wall_time_s — `_overhead_pct$`/`_wall_time_s$` are pinned
# explicitly; a blanket `_pct$` would flip the higher-is-better payoff
# percentages like whatif_overlap_payoff_pct).  The self-healing tier's
# admission-control pair follows the same rule: tier_recovery_wall_time_s
# rides `_wall_time_s$`, and tier_refusal_rate_pct gets its own
# `_refusal_rate_pct$` pin — more typed refusals under the same load is
# a regression, even though refusing *correctly* is the feature.
_WORSE_HIGH = re.compile(
    r"(^elapsed_time$|_time$|_time_|_wall|latency|overhead|_skew_|ttft"
    r"|_idle|_error_pct$|_rss_mb$|_sol_distance$|_ms$|_overhead_pct$"
    r"|_wall_time_s$|_refusal_rate_pct$)")
# Lower is worse: rates and utilization (including the fleet tier's
# saturation throughput, fleet_saturation_rps).
_WORSE_LOW = re.compile(
    r"(bandwidth|_gbps|per_sec|throughput|flops|images_per_sec|_util$"
    r"|_rps$)")


def polarity(name: str) -> int:
    """+1 = higher is worse (time-like), -1 = lower is worse (rate-like),
    0 = no defensible polarity (counts, ids, coordinates) — a feature
    with no polarity can never earn a regressed/improved verdict."""
    n = name.lower()
    if _WORSE_HIGH.search(n):
        return 1
    if _WORSE_LOW.search(n):
        return -1
    return 0


# ---------------------------------------------------------------------------
# Rolling baselines over the catalog.
# ---------------------------------------------------------------------------

def rolling_samples(store, rolling: int,
                    exclude_run: "str | None" = None
                    ) -> Dict[str, List[float]]:
    """Per-feature sample lists from the newest ``rolling`` archived runs
    (catalog order, the run under test excluded so it cannot vouch for
    itself).

    Index-fed when the archive carries a CURRENT columnar index
    (archive/index.py — same selection rules, zero run-doc opens and no
    catalog re-parse, proven verdict-identical by
    tests/test_archive_index.py); falls back to the linear catalog scan
    otherwise.  ``SOFA_ARCHIVE_INDEX=0`` forces the scan."""
    import os

    from sofa_tpu.archive import catalog

    if os.environ.get("SOFA_ARCHIVE_INDEX", "1") != "0":
        from sofa_tpu.archive import index as aindex

        hit = aindex.rolling_samples(store.root, rolling,
                                     exclude_run=exclude_run)
        if hit is not None:
            return hit
    entries = catalog.ingest_entries(catalog.read_catalog(store.root))
    out: Dict[str, List[float]] = {}
    taken = 0
    for e in reversed(entries):          # newest first
        if taken >= rolling:
            break
        run_id = e.get("run")
        if run_id == exclude_run:
            continue
        doc = store.load_run(run_id)
        if doc is None:
            continue
        feats = doc.get("features") or {}
        if not feats:
            continue
        taken += 1
        for name, value in feats.items():
            if isinstance(value, (int, float)):
                out.setdefault(name, []).append(float(value))
    for name in out:
        out[name].reverse()              # oldest first, for readers
    return out


def rolling_verdict(value: float, samples: List[float], pct: float,
                    threshold_pct: float, pol: int) -> dict:
    """Verdict of one value against a rolling sample history.

    The reported baseline is the ``pct``-th percentile of the samples;
    the *verdict* requires the value to fall outside the nonparametric
    95 % median CI in the polarity's bad (or good) direction AND to move
    more than ``threshold_pct`` percent relative to that baseline —
    no CI (too few samples) or no polarity means ``noise``, stated."""
    base = percentile(samples, pct) if samples else 0.0
    out = {"baseline": base, "n_samples": len(samples),
           "ratio": _ratio(value, base)}
    if pol == 0:
        out.update(verdict="noise", reason="no polarity for this feature")
        return out
    ci = median_ci(samples)
    if ci is None:
        out.update(verdict="noise",
                   reason=f"only {len(samples)} baseline sample(s) — no "
                          f"defensible 95% CI (need >= {MIN_CI_SAMPLES})")
        return out
    lo, hi = ci
    out["ci"] = [lo, hi]
    moved_pct = abs(value - base) / base * 100.0 if base else (
        0.0 if value == 0 else float("inf"))
    if moved_pct <= threshold_pct:
        out.update(verdict="noise",
                   reason=f"moved {moved_pct:.2f}% <= threshold "
                          f"{threshold_pct:g}%")
        return out
    worse = value > hi if pol > 0 else value < lo
    better = value < lo if pol > 0 else value > hi
    if worse:
        out.update(verdict="regressed",
                   reason=f"outside the 95% median CI [{lo:g}, {hi:g}] "
                          f"in the bad direction ({moved_pct:.1f}% vs the "
                          f"p{pct:g} baseline)")
    elif better:
        out.update(verdict="improved",
                   reason=f"outside the 95% median CI [{lo:g}, {hi:g}] "
                          f"in the good direction ({moved_pct:.1f}%)")
    else:
        out.update(verdict="noise",
                   reason=f"inside the 95% median CI [{lo:g}, {hi:g}]")
    return out


def pairwise_verdict(value: float, base: float, threshold_pct: float,
                     pol: int) -> dict:
    """Verdict of one value against a single explicit baseline value.

    With one sample a CI is impossible, so the defensible interval here
    is the user-supplied relative threshold (``--regress_threshold``,
    default 10 %): inside it everything is ``noise``; polarity-less
    features are always ``noise``.  ``ratio`` keeps ml/diff.py's inf
    convention: a key with zero baseline and nonzero value is
    ratio=inf — visible, never silently dropped."""
    ratio = _ratio(value, base)
    out = {"baseline": base, "ratio": ratio}
    if pol == 0:
        out.update(verdict="noise", reason="no polarity for this feature")
        return out
    if base == 0 and value == 0:
        out.update(verdict="noise", reason="zero in both runs")
        return out
    moved_pct = (abs(value - base) / base * 100.0 if base
                 else float("inf"))
    if moved_pct <= threshold_pct:
        out.update(verdict="noise",
                   reason=f"moved {moved_pct:.2f}% <= threshold "
                          f"{threshold_pct:g}%")
        return out
    worse = (value > base) if pol > 0 else (value < base)
    out.update(
        verdict="regressed" if worse else "improved",
        reason=(f"moved {'+' if value >= base else '-'}"
                f"{moved_pct if moved_pct != float('inf') else 0:.1f}% "
                f"(ratio {ratio:g}) beyond the {threshold_pct:g}% "
                "threshold" if moved_pct != float("inf") else
                "new in this run (ratio inf) with a bad polarity"
                if worse else
                "new in this run (ratio inf) with a good polarity"))
    return out


def _ratio(value: float, base: float) -> float:
    """ml/diff.py's convention: base 0 & value > 0 -> inf (a mover that
    only exists in the new run must be visible); 0/0 -> 1 (unchanged)."""
    if base > 0:
        return value / base
    return float("inf") if value > 0 else 1.0
